"""Engine-side weight streaming: stage between decode steps, flip
atomically.

A :class:`Subscriber` sits between one engine (``ServeEngine`` or
``DisaggEngine``) and a :class:`~tpu_ddp.publish.publisher.Publisher`.
Delivered updates queue in an inbox; the engine calls
:meth:`on_engine_step` at the top of every ``step()``, and the
subscriber decodes AT MOST ONE bucket per call into a host-side
staging copy — streaming work spread across decode gaps, never a long
pause. When the last bucket lands, the staged tree's per-leaf sha256
digests are checked against the update's (the publisher digested its
own reconstruction — agreement means bitwise-identical params on both
ends), and only then does the version flip.

The flip is atomic with respect to the token stream: it happens
BETWEEN engine steps, so an in-flight request samples token ``t`` on
version N and token ``t+1`` on version N+1 — never a mixed forward.
Engines stamp every emitted token with the serving version
(``Request.token_versions``), which is what the atomic-cutover
assertions in tests and loadgen check.

The staging→live swap does not copy: delta flips run the jitted
``apply_delta`` program with the old live tree DONATED, so XLA writes
the new version into the old version's buffers (pinned by
``donation_report``/``runtime_donation_check`` in tests and the graph
audit). Last-good retention is therefore HOST-side, in the
:class:`VersionedParams` store — the donated device buffers are gone
by design, and rollback re-places the retained host tree.

Rejection paths (all warn + count, never crash serving): a digest
mismatch, a delta that skips a version (a late joiner or a
post-rollback subscriber needs a full push — ``Publisher.force_full``)
and a bucket-layout mismatch all drop the update and keep serving the
current version.
"""

from __future__ import annotations

import warnings
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from tpu_ddp.parallel.compress import EdgeCodec
from tpu_ddp.parallel.overlap import BucketPlan
from tpu_ddp.publish.store import VersionedParams, tree_digests


def apply_delta(live, delta):
    """live tree + f32 delta tree -> next version, per leaf in f32 then
    cast back — the same arithmetic the publisher's reconstruction and
    the subscriber's host mirror run in numpy, so device and host stay
    bitwise equal. ``live`` is donated at the jit boundary."""
    return jax.tree.map(
        lambda p, d: (p.astype(jnp.float32) + d).astype(p.dtype),
        live, delta)


# One jitted program for every subscriber (jax.jit caches per input
# avals/treedef): the staging->live swap. Donating the live tree is
# what makes the flip zero-copy — the old version's buffers become the
# new version's.
_APPLY = jax.jit(apply_delta, donate_argnums=(0,))


class Subscriber:
    """One engine's end of the weight-streaming edge."""

    def __init__(self, engine, name: str = "sub"):
        self.engine = engine
        self.name = name
        self.store = VersionedParams(
            engine.params, version=getattr(engine, "param_version", 0))
        self._inbox: deque = deque()
        self._staging = None      # (update, [decoded|None]*B, next_idx)
        self._plan = None
        self.applied_version = self.store.version
        self.applied_step = -1
        self.applied = 0
        self.full_applied = 0
        self.rejected = 0
        self.publisher_lost_n = 0
        self.needs_full = False

    # ---- publisher-facing ----------------------------------------------

    def deliver(self, update) -> None:
        """The wire hop: enqueue; application happens between the
        engine's decode steps, never here."""
        self._inbox.append(update)

    def publisher_lost(self) -> None:
        """The publisher died (chaos or real): keep serving the
        current (last-good) version, loudly."""
        self.publisher_lost_n += 1
        warnings.warn(
            f"publish[{self.name}]: publisher lost; continuing to "
            f"serve version {self.applied_version}", stacklevel=3)

    @property
    def lag(self) -> int:
        """Updates delivered but not yet fully applied."""
        return len(self._inbox) + (1 if self._staging else 0)

    # ---- engine-facing -------------------------------------------------

    def on_engine_step(self) -> None:
        """Called by the engine at the top of ``step()``: decode at
        most one bucket into staging; flip when the update completes.
        Bounded work per call — streaming never stalls the bank."""
        if self._staging is None:
            if not self._inbox:
                return
            update = self._inbox.popleft()
            if not self._admit(update):
                return
            self._staging = (update, [None] * len(update.wires), 0)
        update, decoded, b = self._staging
        decoded[b] = np.asarray(
            EdgeCodec.decode(update.wires[b]), np.float32)
        if b + 1 < len(decoded):
            self._staging = (update, decoded, b + 1)
            return
        self._staging = None
        self._flip(update, decoded)

    def _admit(self, update) -> bool:
        """Order + layout checks before any decode work."""
        if update.kind == "delta" \
                and (self.needs_full
                     or update.version != self.applied_version + 1):
            self.rejected += 1
            self.needs_full = True
            warnings.warn(
                f"publish[{self.name}]: delta for version "
                f"{update.version} does not extend applied version "
                f"{self.applied_version}; dropped (a full push "
                "resyncs)", stacklevel=3)
            return False
        if self._plan is None \
                or self._plan.fingerprint() != update.layout:
            plan = BucketPlan(self.store.host, update.bucket_mb)
            if plan.fingerprint() != update.layout:
                self.rejected += 1
                warnings.warn(
                    f"publish[{self.name}]: update layout does not "
                    "match this engine's parameters; dropped",
                    stacklevel=3)
                return False
            self._plan = plan
        return True

    # ---- the flip ------------------------------------------------------

    def _flip(self, update, decoded) -> None:
        plan = self._plan
        old_host = jax.tree.leaves(self.store.host)
        new_host = [None] * len(plan.metas)
        delta = [None] * len(plan.metas)
        for b, idxs in enumerate(plan.buckets):
            off = 0
            for i in idxs:
                m = plan.metas[i]
                d = decoded[b][off:off + m.size].reshape(m.shape)
                off += m.size
                if update.kind == "full":
                    new_host[i] = d.astype(m.dtype)
                else:
                    delta[i] = d
                    new_host[i] = (np.asarray(old_host[i], np.float32)
                                   + d).astype(m.dtype)
        host_tree = jax.tree.unflatten(plan.treedef, new_host)
        if tree_digests(host_tree) != update.digests:
            self.rejected += 1
            self.needs_full = True
            warnings.warn(
                f"publish[{self.name}]: digest mismatch on version "
                f"{update.version}; keeping last-good version "
                f"{self.applied_version}", stacklevel=3)
            return
        live = self.engine.params
        shardings = jax.tree.map(lambda x: x.sharding, live)
        if update.kind == "full":
            new_live = jax.tree.map(
                jax.device_put, host_tree, shardings)
        else:
            delta_tree = jax.tree.unflatten(plan.treedef, delta)
            delta_dev = jax.tree.map(
                jax.device_put, delta_tree, shardings)
            # Drop every live reference before the donating call so
            # the staging->live swap aliases instead of copying.
            self.engine.params = None
            self.store.live = None
            new_live = _APPLY(live, delta_dev)
            del live
        self.store.commit(new_live, update.version, host_tree,
                          update.digests)
        self.engine.swap_params(new_live, update.version)
        self.applied_version = update.version
        self.applied_step = update.step
        self.applied += 1
        if update.kind == "full":
            self.full_applied += 1
            self.needs_full = False

    def rollback(self) -> int:
        """Re-place the retained last-good version and serve it. The
        next delta is rejected until a full push resyncs."""
        version, host = self.store.rollback()
        shardings = jax.tree.map(lambda x: x.sharding,
                                 self.engine.params)
        live = jax.tree.map(jax.device_put, host, shardings)
        self.store.live = live
        self.engine.swap_params(live, version)
        self.applied_version = version
        self.needs_full = True
        return version

    def lower_apply_step(self):
        """``jit.lower`` the donating apply program at this engine's
        param shapes — the apply-side graph-audit surface."""
        sds = lambda x: jax.ShapeDtypeStruct(  # noqa: E731
            jnp.shape(x), jnp.result_type(x))
        live = jax.tree.map(sds, self.engine.params)
        delta = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(jnp.shape(x), jnp.float32),
            self.engine.params)
        return _APPLY.lower(live, delta)

    def stats(self) -> dict:
        return {"name": self.name, "version": self.applied_version,
                "step": self.applied_step, "applied": self.applied,
                "full_applied": self.full_applied,
                "rejected": self.rejected, "lag": self.lag,
                "publisher_lost": self.publisher_lost_n,
                "last_good": self.store.last_good_version}


def attach(publisher, target, name: str = "sub") -> list:
    """Wire ``target`` onto ``publisher``'s edge. ``target`` is one
    engine, or a fleet Router — then every replica gets its own
    subscriber (fleet-wide version fan-out: one publish reaches all
    replicas; ``Router.stats()`` reports the per-replica versions).
    Also points the publisher's in-process catch-up hook at the
    subscribed engines so the staleness gate can pump them."""
    engines = getattr(target, "replicas", None) or [target]
    subs = []
    for i, eng in enumerate(engines):
        sub = Subscriber(
            eng, name=f"{name}{i}" if len(engines) > 1 else name)
        eng.subscriber = sub
        publisher.connect(sub)
        subs.append(sub)
    if publisher.drive is None:
        def drive(engines=tuple(engines)):
            for eng in engines:
                eng.step()
        publisher.drive = drive
    return subs


__all__ = ["Subscriber", "apply_delta", "attach"]
