"""The closed online-RL scenario: generate → score → train → publish.

The loop the whole subsystem exists for (ROADMAP: online post-training
colocates a trainer and a generation fleet): a ``ServeEngine`` (or
fleet) generates rollouts from the CURRENT served version, a scorer
ranks them, the trainer consumes the best ones as a training batch
(rejection-sampling fine-tuning — the simplest honest member of the
online-RL family: no advantage estimator, just best-of-n selection +
LM loss on the winners), and the publisher streams the updated weights
back into the engine live. No restart, no drain: generation for round
``r+1`` runs on the weights round ``r`` trained, while any still-open
requests finish their current token on the old version.

Geometry contract: all prompts share one length and every rollout runs
to exactly ``max_new_tokens`` (no EOS), so the selected rollouts stack
into uniform ``(B, P + max_new)`` rows for ``make_lm_batch`` — no
padding, no loss masking. Sampling temperature must be > 0 (best-of-n
over identical greedy rollouts selects nothing).

``scripts/publish_sweep.py`` benchmarks this loop; the scenario test
(tests/test_publish.py) pins that the engine provably serves
trainer-updated weights — digests equal on both ends, versions
advanced, generations changed.

Speculation composes (DESIGN.md §26): a speculative engine
(``spec_k > 0``) multiplies rollout generation throughput, and with
an int8 draft or target the engine's ``swap_params`` re-derives the
quantized tree on every publisher flip — the draft-distill-and-push
loop: each round's draft is re-quantized FROM the weights that round
trained, so the draft never serves a stale version (the per-round
report pins ``speculative.draft_version == engine_version``). With
the default "chain" family the rollout streams stay bitwise what the
non-speculative engine would have sampled, so speculation changes
the loop's wall-clock, never its trajectory.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from tpu_ddp.train.lm import make_lm_batch


@dataclasses.dataclass
class Rollout:
    """One scored generation."""

    prompt: np.ndarray
    tokens: list
    logprobs: list
    reward: float = 0.0
    versions: tuple = ()      # param versions the tokens sampled under

    def row(self) -> np.ndarray:
        """prompt + generation as one packed LM training row."""
        return np.concatenate([np.asarray(self.prompt, np.int32),
                               np.asarray(self.tokens, np.int32)])


def make_prompts(n: int, vocab_size: int, prompt_len: int,
                 seed: int = 0) -> list:
    """Deterministic fixed-length prompts (the loadgen analogue for
    the rollout loop)."""
    rng = np.random.default_rng(seed)
    return [rng.integers(1, vocab_size, size=prompt_len).astype(np.int32)
            for _ in range(n)]


def mean_logprob_scorer(rollout: Rollout) -> float:
    """Default scorer: mean sampled logprob — deterministic, needs no
    external reward model, and selecting on it (best-of-n) pushes the
    policy toward its own high-likelihood continuations (the
    self-distillation degenerate case of RFT; swap in a real reward
    model via the ``scorer`` argument)."""
    return float(np.mean(rollout.logprobs)) if rollout.logprobs else 0.0


def generate_rollouts(engine, prompts, *, max_new_tokens: int,
                      temperature: float, round_idx: int,
                      samples_per_prompt: int = 2,
                      scorer=mean_logprob_scorer) -> list:
    """Submit ``samples_per_prompt`` stochastic samples per prompt,
    drain the engine, score. Seeds fold (round, prompt, sample) so
    every rollout is distinct and the whole loop is replayable."""
    if temperature <= 0:
        raise ValueError("online rollouts need temperature > 0 "
                         "(best-of-n over greedy duplicates is vacuous)")
    handles = []
    for i, p in enumerate(prompts):
        for k in range(samples_per_prompt):
            seed = 100003 * round_idx + 1009 * i + k
            handles.append((i, engine.submit(
                p, max_new_tokens, temperature=temperature, seed=seed)))
    engine.run()
    rollouts = []
    for i, req in enumerate(handles):
        pi, r = req
        if not r.done or r.cancelled or r.shed or r.quarantined:
            continue
        ro = Rollout(prompt=prompts[pi], tokens=list(r.tokens),
                     logprobs=list(r.logprobs),
                     versions=tuple(sorted(set(r.token_versions))))
        ro.reward = scorer(ro)
        rollouts.append((pi, ro))
    return rollouts


def select_best(rollouts, n_prompts: int) -> list:
    """Best-of-n per prompt: the highest-reward rollout of each
    prompt, in prompt order — the training batch."""
    best: dict = {}
    for pi, ro in rollouts:
        if pi not in best or ro.reward > best[pi].reward:
            best[pi] = ro
    return [best[pi] for pi in range(n_prompts) if pi in best]


def run_online_loop(trainer, engine, publisher, state, *, rounds: int,
                    prompts, max_new_tokens: int,
                    temperature: float = 0.7,
                    samples_per_prompt: int = 2,
                    scorer=mean_logprob_scorer,
                    settle_steps: int = 8):
    """The closed loop. Returns ``(state, report)`` where ``report``
    carries per-round loss/reward/version plus the publisher's final
    stats. ``settle_steps`` idle engine steps after the last round
    land any still-staged buckets, so the caller observes the final
    version served (each engine step stages at most one bucket)."""
    report = {"rounds": []}
    for r in range(rounds):
        rollouts = generate_rollouts(
            engine, prompts, max_new_tokens=max_new_tokens,
            temperature=temperature, round_idx=r,
            samples_per_prompt=samples_per_prompt, scorer=scorer)
        batch = select_best(rollouts, len(prompts))
        if not batch:
            raise RuntimeError(f"round {r}: no rollout survived "
                               "(all shed/cancelled/quarantined?)")
        rows = np.stack([ro.row() for ro in batch])
        inputs, targets = make_lm_batch(rows)
        x, y = trainer.put_batch(inputs, targets)
        state, loss = trainer.train_step(state, x, y)
        publisher.after_step(state, int(state.step))
        rep = {
            "round": r, "loss": float(np.mean(np.asarray(loss))),
            "reward_mean": float(np.mean([ro.reward for ro in batch])),
            "published_version": publisher.version,
            "engine_version": getattr(engine, "param_version", 0),
        }
        if getattr(engine, "spec_k", 0) > 0 \
                and hasattr(engine, "spec_stats"):
            # Draft provenance: swap_params re-derived the draft from
            # the engine's current weights, so the draft's version IS
            # the engine's — pinned per round by the scenario test.
            rep["speculative"] = dict(
                engine.spec_stats(),
                draft_version=getattr(engine, "param_version", 0))
        report["rounds"].append(rep)
    for _ in range(settle_steps):
        engine.step()
    report["publisher"] = publisher.stats()
    report["subscribers"] = [s.stats() for s in publisher.subscribers]
    if getattr(engine, "spec_k", 0) > 0 and hasattr(engine, "spec_stats"):
        report["speculative"] = engine.spec_stats()
    return state, report


__all__ = [
    "Rollout",
    "generate_rollouts",
    "make_prompts",
    "mean_logprob_scorer",
    "run_online_loop",
    "select_best",
]
