"""Versioned parameter store: params as a swappable resource.

The round-12 engines took params as a constructor argument and never
touched them again; weight streaming (veScale, arxiv 2509.07003) needs
the opposite — parameters as a *versioned resource* that an engine can
atomically flip while requests are in flight. This module is the
resource half of the contract:

- **monotonic version ids** — every committed tree carries the version
  the publisher stamped it with; commits must strictly increase, so a
  reordered or replayed push can never roll a subscriber backwards.
- **last-good retention** — the previously committed version survives
  each commit as a HOST-side copy (device buffers of the old version
  are donated into the swap, see publish/subscriber.py, so retention
  on-device would force a copy). A corrupt push rolls back to it.
- **per-leaf sha256 digests** — the same ``leaf_digest`` the verified
  checkpoints ride (resilience/integrity.py): the publisher digests
  its post-push reconstruction, the subscriber digests its staged
  tree, and a flip only commits when they agree bitwise.

The store itself is engine-agnostic: it holds trees and versions. The
engine coupling (flip between decode steps, never mid-forward) lives
in :class:`tpu_ddp.publish.subscriber.Subscriber`.
"""

from __future__ import annotations

import jax
import numpy as np

from tpu_ddp.resilience.integrity import leaf_digest


def tree_digests(tree) -> tuple:
    """Per-leaf sha256 digests in ``jax.tree.flatten`` order — the
    checkpoint-integrity primitive applied leaf-by-leaf to a live
    tree. Publisher and subscriber both digest their own copy; equal
    tuples mean bitwise-identical parameters."""
    return tuple(leaf_digest(x) for x in jax.tree.leaves(tree))


class StaleVersionError(ValueError):
    """A commit tried to move the store backwards (or sideways) in
    version order — the replayed/reordered-push failure mode."""


class VersionedParams:
    """One engine's parameters as a versioned resource.

    ``live`` is whatever the engine serves from (a device tree);
    ``host`` is the canonical host-numpy mirror the digests and the
    delta arithmetic run over. ``commit`` swaps both and retains the
    previous (version, host) pair as last-good.
    """

    def __init__(self, live, version: int = 0, host=None):
        self.live = live
        self.version = int(version)
        self.host = (jax.tree.map(np.asarray, live)
                     if host is None else host)
        self.digests = tree_digests(self.host)
        self._last_good = None    # (version, host tree, digests)

    @property
    def last_good_version(self) -> int | None:
        return self._last_good[0] if self._last_good else None

    def commit(self, live, version: int, host, digests=None) -> None:
        """Atomically advance to ``version``. The outgoing version is
        retained host-side for :meth:`rollback`; versions must be
        strictly monotonic (a stale push must never be committed)."""
        version = int(version)
        if version <= self.version:
            raise StaleVersionError(
                f"commit of version {version} onto version "
                f"{self.version}: versions must strictly increase")
        self._last_good = (self.version, self.host, self.digests)
        self.live = live
        self.host = host
        self.digests = (tree_digests(host) if digests is None
                        else tuple(digests))
        self.version = version

    def rollback(self):
        """Restore the retained last-good version: returns its
        ``(version, host_tree)`` for the caller to re-place on device
        (placement is the subscriber's job — it knows the engine's
        shardings). Raises when nothing is retained."""
        if self._last_good is None:
            raise ValueError("no last-good version retained")
        version, host, digests = self._last_good
        self._last_good = None
        self.live = None
        self.host = host
        self.digests = digests
        self.version = version
        return version, host

    def verify(self) -> bool:
        """Recompute the host mirror's digests against the stored
        ones — the integrity self-check (bit rot / bad apply)."""
        return tree_digests(self.host) == self.digests


__all__ = ["StaleVersionError", "VersionedParams", "tree_digests"]
