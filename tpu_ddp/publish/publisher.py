"""Trainer-side weight streaming: snapshot, delta, bucket, compress,
ship.

Every ``publish_every`` trainer steps the publisher snapshots the
params in CANONICAL host form (``params_to_host`` — the same portable
seam checkpoints and live resharding use, so any training strategy
feeds any serving layout), diffs them against the last *published*
reconstruction, chunks the delta along :class:`BucketPlan` bucket
boundaries (parallel/overlap.py — the same size-targeted partition the
in-backward gradient sync uses), and compresses each bucket with the
:class:`EdgeCodec` wire formats (parallel/compress.py, ``none`` /
``bf16`` / ``int8`` / lossless ``sparse``).

Two invariants make lossy wires safe along a trajectory:

- **Reconstruction tracking** — the publisher's baseline for the next
  delta is what the SUBSCRIBERS decoded (``last + decode(encode(new -
  last))``), never the raw trainer params. Publisher and subscriber
  therefore hold bitwise-identical trees at every version (pinned by
  the per-leaf sha256 digests each update carries), and quantization
  error never compounds silently.
- **Error feedback** — the ``int8`` wire rides the EF variant: each
  push's quantization error is carried into the next delta (per
  bucket, like the per-edge MPMD residuals), so the served weights
  converge to the trained ones instead of random-walking away.

Full-tensor fallback: the first push, or any bucket-layout change
(leaf shapes/dtypes — a resumed trainer with a different model), ships
full values instead of deltas and resets the per-bucket codecs.

Staleness: ``max_staleness_steps`` bounds how far training may run
ahead of the slowest subscriber (measured in trainer steps since the
oldest unapplied publish). ``after_step`` — the train-loop hook —
blocks at the gate, pumping local subscribers when attached in-process
(a stalled push is a *delay*: it flushes when the gate drains).

Chaos (resilience/chaos.py, ``TPU_DDP_CHAOS_FAULTS``):
``publisher-death@N`` kills the publisher at its N-th push (nothing
further is delivered; subscribers are notified and keep serving their
last-good version); ``push-stall@N`` holds the N-th push undelivered
until the staleness gate flushes it.
"""

from __future__ import annotations

import dataclasses
import time
import warnings

import jax
import jax.numpy as jnp
import numpy as np

from tpu_ddp.parallel.compress import EdgeCodec
from tpu_ddp.parallel.overlap import BucketPlan
from tpu_ddp.publish.store import tree_digests

# "sparse" is the lossless zero-chunk-elision wire (EDGE_SPECS in
# parallel/compress.py) — the natural fit for MoE expert deltas, where
# a step touches only the routed-to experts and untouched expert rows
# diff to all-zero chunks (experiments/moe_sweep.json measures it).
PUBLISH_WIRES = ("none", "bf16", "int8", "sparse")


@dataclasses.dataclass(frozen=True)
class WeightUpdate:
    """One push on the weight-streaming edge: per-bucket wire payloads
    plus the metadata a subscriber needs to verify and flip."""

    version: int           # monotonic publish id (1-based)
    step: int              # trainer step the snapshot was taken at
    kind: str              # "full" (first push / layout change) | "delta"
    wires: tuple           # one EdgeCodec wire dict per bucket
    nbytes: int            # payload bytes actually shipped
    digests: tuple         # per-leaf sha256 of the POST-apply params
    layout: tuple          # BucketPlan fingerprint (shapes/dtypes/cuts)
    bucket_mb: float       # plan parameter (subscriber rebuilds plan)
    strategy: str          # source ShardingPlan strategy (provenance)


def _build_pack(plan: BucketPlan):
    """The push-side jitted program: per-bucket f32 deltas, flattened
    and concatenated at the plan's boundaries. Module-path function
    (named ``push_pack``) so ``no_retrace`` can watch its compiles and
    graph_audit can register its lowering."""

    def push_pack(new_leaves, last_leaves):
        out = []
        for idxs in plan.buckets:
            parts = [(new_leaves[i].astype(jnp.float32)
                      - last_leaves[i].astype(jnp.float32)).reshape(-1)
                     for i in idxs]
            out.append(parts[0] if len(parts) == 1
                       else jnp.concatenate(parts))
        return tuple(out)

    return jax.jit(push_pack)


class Publisher:
    """The trainer side of the weight-streaming edge.

    Knob defaults come from ``TrainConfig`` (``TPU_DDP_PUBLISH_EVERY``
    / ``TPU_DDP_PUBLISH_WIRE`` / ``TPU_DDP_PUBLISH_MAX_STALENESS``,
    registered in tune/space.py); explicit arguments win.
    ``publish_every == 0`` leaves the publisher inert (``maybe_publish``
    is a no-op) — the live-streaming analogue of ``ckpt_every_iters=0``.
    """

    def __init__(self, trainer=None, *, publish_every: int | None = None,
                 wire: str | None = None,
                 max_staleness_steps: int | None = None,
                 bucket_mb: float = 4, config=None):
        if config is None:
            from tpu_ddp.utils.config import TrainConfig
            config = TrainConfig()
        self.trainer = trainer
        self.publish_every = int(publish_every if publish_every is not None
                                 else config.publish_every)
        self.wire = str(wire if wire is not None else config.publish_wire)
        self.max_staleness_steps = int(
            max_staleness_steps if max_staleness_steps is not None
            else config.max_staleness_steps)
        if self.publish_every < 0:
            raise ValueError("publish_every must be >= 0")
        if self.wire not in PUBLISH_WIRES:
            raise ValueError(f"publish_wire={self.wire!r}: expected "
                             "none|bf16|int8|sparse")
        if self.max_staleness_steps < 0:
            raise ValueError("max_staleness_steps must be >= 0")
        self.bucket_mb = bucket_mb
        self.subscribers: list = []
        self.version = 0
        self.dead = False
        # In-process catch-up hook: attach() points this at the
        # subscribed engines' step() so the staleness gate can pump
        # them instead of sleeping (a real deployment leaves it None).
        self.drive = None
        self._plan = None
        self._pack = None
        self._codecs = None
        self._treedef = None
        self._last = None            # reconstruction leaves (host np)
        self._push_n = 0
        self._version_steps: dict = {}   # version -> trainer step
        self._stalled: list = []
        self.full_pushes = 0
        self.delta_pushes = 0
        self.bootstraps = 0   # §25 scale-up boots served from _last
        self.stalls = 0
        self.deaths = 0
        self.gate_blocks = 0
        self.stall_events = 0
        self.chaos = None
        from tpu_ddp.fleet.resilience import (ServeFaultInjector,
                                              serve_chaos_active)
        if serve_chaos_active():
            self.chaos = ServeFaultInjector.from_env()

    # ---- wiring --------------------------------------------------------

    def connect(self, subscriber) -> None:
        self.subscribers.append(subscriber)

    # ---- snapshot / plan -----------------------------------------------

    def _snapshot(self, state):
        """Canonical host-numpy params for ``state`` — the portable
        form any training strategy can produce (fused/ZeRO/FSDP/
        pipeline all land here via their trainer's params_to_host)."""
        if self.trainer is not None \
                and hasattr(self.trainer, "params_to_host"):
            return self.trainer.params_to_host(state)
        return jax.tree.map(np.asarray, state.params)

    def ensure_plan(self, host_params) -> BucketPlan:
        """(Re)build the bucket plan + pack program + per-bucket codecs
        for ``host_params``'s layout. Idempotent while the layout holds;
        a layout change resets everything (next push goes full)."""
        plan = BucketPlan(host_params, self.bucket_mb)
        if self._plan is not None \
                and plan.fingerprint() == self._plan.fingerprint():
            return self._plan
        self._plan = plan
        self._pack = _build_pack(plan)
        # int8 rides ERROR FEEDBACK here (unlike the one-shot KV edge):
        # deltas form a trajectory, and the residual is what keeps the
        # served weights converging to the trained ones. One codec per
        # bucket — each carries its own residual, sized to its payload.
        self._codecs = tuple(
            EdgeCodec(self.wire, seed=b) for b in range(plan.n_buckets))
        self._treedef = plan.treedef
        self._last = None
        return plan

    def lower_push_step(self):
        """``jit.lower`` the pack program at the plan's leaf shapes —
        the push-side graph-audit surface. Requires a plan (publish
        once, or call :meth:`ensure_plan` with a params template)."""
        if self._plan is None:
            raise ValueError("no bucket plan yet: publish once or call "
                             "ensure_plan(params) first")
        sds = tuple(jax.ShapeDtypeStruct(m.shape, m.dtype)
                    for m in self._plan.metas)
        return self._pack.lower(sds, sds)

    # ---- publishing ----------------------------------------------------

    def maybe_publish(self, state, step: int | None = None):
        """The ``publish_every`` cadence: publish when due, else None."""
        if not self.publish_every or self.dead:
            return None
        step = int(state.step if step is None else step)
        if step % self.publish_every:
            return None
        return self.publish(state=state, step=step)

    def publish(self, state=None, step: int | None = None, *,
                params=None):
        """Snapshot → delta → bucket → compress → deliver. Returns the
        :class:`WeightUpdate` (None when chaos killed the publisher).
        ``params`` (a host tree) bypasses the trainer snapshot — the
        drills and sweeps push synthetic trees through the real path."""
        self._push_n += 1
        if self.dead:
            return None
        if self.chaos is not None \
                and self.chaos.publisher_death_fires(self._push_n):
            self.dead = True
            self.deaths += 1
            warnings.warn(
                f"chaos: publisher died at push {self._push_n}; "
                "subscribers keep serving their last-good version",
                stacklevel=2)
            for s in self.subscribers:
                s.publisher_lost()
            return None
        if params is None:
            params = self._snapshot(state)
        step = int(state.step if step is None and state is not None
                   else (step or 0))
        host = jax.tree.map(np.asarray, params)
        plan = self.ensure_plan(host)
        new_leaves = jax.tree.leaves(host)
        if self._last is None:
            update = self._publish_full(plan, new_leaves, step)
            self.full_pushes += 1
        else:
            update = self._publish_delta(plan, new_leaves, step)
            self.delta_pushes += 1
        self._version_steps[update.version] = step
        if self.chaos is not None \
                and self.chaos.push_stall_fires(self._push_n):
            warnings.warn(
                f"chaos: push of version {update.version} stalled in "
                "flight; delivery is delayed, not lost",
                stacklevel=2)
            self.stalls += 1
            self._stalled.append(update)
            return update
        if self._stalled:
            # Deliveries are ordered: a push behind a stalled one must
            # not overtake it (the subscriber would reject the gap).
            # The next successful push is also when the stalled one
            # clears — a stall is a transport delay, and the transport
            # just demonstrated recovery.
            self._flush_stalled()
        self._deliver(update)
        return update

    def _publish_full(self, plan, new_leaves, step) -> WeightUpdate:
        """Full-tensor push: first contact and layout changes. Resets
        the per-bucket codecs (a fresh baseline owes no residual)."""
        for c in self._codecs:
            c.reset()
        wires, nbytes, recon = [], 0, [None] * len(plan.metas)
        for b, idxs in enumerate(plan.buckets):
            payload = np.concatenate(
                [np.asarray(new_leaves[i], np.float32).ravel()
                 for i in idxs])
            wire, n = self._codecs[b].encode(payload)
            wires.append(wire)
            nbytes += n
            dec = np.asarray(EdgeCodec.decode(wire), np.float32)
            off = 0
            for i in idxs:
                m = plan.metas[i]
                recon[i] = dec[off:off + m.size].reshape(
                    m.shape).astype(m.dtype)
                off += m.size
        return self._finish(plan, recon, "full", wires, nbytes, step)

    def _publish_delta(self, plan, new_leaves, step) -> WeightUpdate:
        """Delta push along the trajectory: pack on device (the jitted
        ``push_pack`` program), encode per bucket, and advance the
        reconstruction by the DECODED delta — exactly what every
        subscriber computes, so both ends stay bitwise equal."""
        payloads = self._pack(tuple(new_leaves), tuple(self._last))
        wires, nbytes, recon = [], 0, [None] * len(plan.metas)
        for b, idxs in enumerate(plan.buckets):
            wire, n = self._codecs[b].encode(np.asarray(payloads[b]))
            wires.append(wire)
            nbytes += n
            dec = np.asarray(EdgeCodec.decode(wire), np.float32)
            off = 0
            for i in idxs:
                m = plan.metas[i]
                d = dec[off:off + m.size].reshape(m.shape)
                recon[i] = (np.asarray(self._last[i], np.float32)
                            + d).astype(m.dtype)
                off += m.size
        return self._finish(plan, recon, "delta", wires, nbytes, step)

    def _finish(self, plan, recon, kind, wires, nbytes,
                step) -> WeightUpdate:
        self._last = recon
        self.version += 1
        tree = jax.tree.unflatten(self._treedef, recon)
        strategy = "none"
        if self.trainer is not None \
                and hasattr(self.trainer, "sharding_plan"):
            strategy = self.trainer.sharding_plan().strategy
        return WeightUpdate(
            version=self.version, step=step, kind=kind,
            wires=tuple(wires), nbytes=int(nbytes),
            digests=tree_digests(tree), layout=plan.fingerprint(),
            bucket_mb=self.bucket_mb, strategy=strategy)

    def bootstrap(self, subscriber, params=None):
        """Seed ONE late-joining subscriber — the §25 autoscaler's
        scale-up boot path — with the publisher's CURRENT
        reconstruction as a full update at the CURRENT version: no
        version bump, no trainer involvement, nothing delivered to the
        fleet. Ships ``_last`` (bitwise what every other subscriber
        serves) over the exact ``none`` wire regardless of the
        publish wire: a boot is one full-size transfer, and bitwise
        fleet parity matters more than its bytes. Full updates pass
        the subscriber's ordering check by design, so the booted
        replica lands at ``applied_version == version`` and every
        later delta extends it normally. Before any publish has
        happened, ``params`` seeds the whole edge via a regular full
        push (every connected subscriber needs version 1 anyway).
        Returns the :class:`WeightUpdate` (None if the publisher is
        dead)."""
        if self.dead:
            return None
        if self._last is None:
            if params is None:
                raise ValueError(
                    "bootstrap before the first publish needs params")
            return self.publish(params=params, step=0)
        plan = self._plan
        wires, nbytes = [], 0
        for idxs in plan.buckets:
            payload = np.concatenate(
                [np.asarray(self._last[i], np.float32).ravel()
                 for i in idxs])
            # One-shot exact codec per bucket: the publisher's own
            # codecs carry delta residuals a boot must not disturb.
            wire, n = EdgeCodec("none").encode(payload)
            wires.append(wire)
            nbytes += n
        tree = jax.tree.unflatten(self._treedef, self._last)
        strategy = "none"
        if self.trainer is not None \
                and hasattr(self.trainer, "sharding_plan"):
            strategy = self.trainer.sharding_plan().strategy
        update = WeightUpdate(
            version=self.version,
            step=self._version_steps.get(self.version, 0),
            kind="full", wires=tuple(wires), nbytes=int(nbytes),
            digests=tree_digests(tree), layout=plan.fingerprint(),
            bucket_mb=self.bucket_mb, strategy=strategy)
        self.bootstraps += 1
        subscriber.deliver(update)
        return update

    def force_full(self) -> None:
        """Make the NEXT publish ship full tensors: drop the delta
        baseline (codecs reset at the full push, as always). The resync
        lever the subscriber rejection paths point at, and the per-round
        mode of the DiLoCo ``none`` outer wire — on a lossless dense
        wire a full costs the same bytes as a delta and decodes
        bitwise."""
        self._last = None

    def rebase(self, params) -> None:
        """Re-anchor the delta baseline at ``params`` WITHOUT shipping
        anything. Only valid when both ends of the edge already hold
        ``params`` (the DiLoCo outer edge: every group holds the
        digest-pinned post-round global tree, so moving the baseline
        there is free) — the next delta is then exactly ``new - params``,
        i.e. the round's pseudo-gradient. Codec state is NOT touched:
        int8 error-feedback residuals carry across rounds by design."""
        host = jax.tree.map(np.asarray, params)
        self.ensure_plan(host)
        self._last = list(jax.tree.leaves(host))

    def reconstruction(self):
        """The current published reconstruction as a host tree — bitwise
        what every in-sync subscriber holds (None before any push)."""
        if self._last is None or self._treedef is None:
            return None
        return jax.tree.unflatten(self._treedef, list(self._last))

    def reset_codecs(self) -> None:
        """Drop per-bucket codec state (error-feedback residuals + byte
        counters) without touching the delta baseline — the membership-
        change semantics of the DiLoCo outer edge (mirrors the round-7
        dp-change reset in parallel/compress.py)."""
        for c in self._codecs or ():
            c.reset()

    def _deliver(self, update) -> None:
        for s in self.subscribers:
            s.deliver(update)

    def _flush_stalled(self) -> None:
        stalled, self._stalled = self._stalled, []
        self.stall_events += len(stalled)
        for update in stalled:
            warnings.warn(
                f"publish: stalled push of version {update.version} "
                "cleared; delivering", stacklevel=3)
            self._deliver(update)

    # ---- staleness gate ------------------------------------------------

    def staleness(self, step: int) -> int:
        """Trainer steps since the oldest publish the SLOWEST
        subscriber has not applied yet (0 when everyone is current)."""
        if not self.subscribers or not self._version_steps:
            return 0
        slowest = min(s.applied_version for s in self.subscribers)
        pending = [s for v, s in self._version_steps.items()
                   if v > slowest]
        if not pending:
            # Everyone is current; drop the applied-version history.
            self._version_steps = {self.version:
                                   self._version_steps[self.version]}
            return 0
        return max(0, int(step) - min(pending))

    def gate(self, step: int) -> bool:
        """False when training must pause for subscribers to catch up
        (``max_staleness_steps == 0`` disables the gate)."""
        if not self.max_staleness_steps:
            return True
        return self.staleness(step) <= self.max_staleness_steps

    def wait_until_fresh(self, step: int, drive=None,
                         timeout_s: float = 5.0) -> int:
        """Block until the gate opens: flush stalled pushes (a stall
        is a delay, not a loss), pump ``drive`` (attached in-process
        engines) or sleep, and bail with a warning after ``timeout_s``
        — a dead fleet must degrade training, never deadlock it."""
        drive = drive if drive is not None else self.drive
        if self.gate(step):
            return 0
        self.gate_blocks += 1
        spins = 0
        t0 = time.perf_counter()
        while not self.gate(step):
            if self._stalled:
                self._flush_stalled()
            if drive is not None:
                drive()
            else:
                time.sleep(1e-3)
            spins += 1
            if time.perf_counter() - t0 > timeout_s:
                warnings.warn(
                    f"publish: subscribers still "
                    f"{self.staleness(step)} steps stale after "
                    f"{timeout_s:.1f}s; proceeding", stacklevel=2)
                break
        return spins

    def after_step(self, state, step: int) -> None:
        """The train-loop hook (train/engine.py train_epoch, the
        rollout loop): publish on cadence, then respect the gate."""
        self.maybe_publish(state, step)
        if self.max_staleness_steps:
            self.wait_until_fresh(step)

    # ---- stats ---------------------------------------------------------

    def stats(self) -> dict:
        sent = sum(c.bytes_sent for c in self._codecs or ())
        dense = sum(c.bytes_dense for c in self._codecs or ())
        return {"wire": self.wire, "version": self.version,
                "full_pushes": self.full_pushes,
                "delta_pushes": self.delta_pushes,
                "bytes_sent": sent, "bytes_dense": dense,
                "ratio": dense / sent if sent else 1.0,
                "stalls": self.stalls, "stall_events": self.stall_events,
                "gate_blocks": self.gate_blocks, "deaths": self.deaths,
                "subscribers": len(self.subscribers)}


__all__ = ["PUBLISH_WIRES", "Publisher", "WeightUpdate"]
