"""Live train→serve weight streaming (docs/DESIGN.md §24).

``store``      — params as a versioned, atomically-swappable resource
``publisher``  — trainer-side snapshot → delta → bucket → compress → ship
``subscriber`` — engine-side staged apply + atomic version flip
``rollout``    — the closed generate → score → train → publish loop
"""

from tpu_ddp.publish.publisher import PUBLISH_WIRES, Publisher, WeightUpdate
from tpu_ddp.publish.rollout import (
    Rollout,
    make_prompts,
    run_online_loop,
)
from tpu_ddp.publish.store import (
    StaleVersionError,
    VersionedParams,
    tree_digests,
)
from tpu_ddp.publish.subscriber import Subscriber, apply_delta, attach

__all__ = [
    "PUBLISH_WIRES",
    "Publisher",
    "Rollout",
    "StaleVersionError",
    "Subscriber",
    "VersionedParams",
    "WeightUpdate",
    "apply_delta",
    "attach",
    "make_prompts",
    "run_online_loop",
    "tree_digests",
]
