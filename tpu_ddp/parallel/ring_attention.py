"""Ring attention: exact attention over sequences sharded across devices.

No reference counterpart — the reference's workload is 32x32 image
classification and it implements no sequence/context parallelism
(SURVEY.md §5 "Long-context / sequence parallelism: Absent") — but
long-context training is first-class in this framework, so the primitive
lives here in the parallel layer next to the DP sync strategies.

Scheme (Liu et al., "Ring Attention with Blockwise Transformers",
arXiv:2310.01889 — reimplemented from the paper's algorithm, not from any
code): the sequence axis is sharded over the ``sp`` mesh axis; each device
keeps its Q chunk resident and the K/V chunks travel around the ring via
``lax.ppermute`` (XLA lowers this to ICI neighbor exchange), one hop per
step, overlapping each hop with the local blockwise-attention compute.
Softmax is computed online (flash-attention style running max / sum /
accumulator in float32), so the result is EXACT full attention — verified
against a single-device reference in tests/test_ring_attention.py —
with per-device memory O(L/sp · L/sp) instead of O(L²).

Causal masking uses global positions (chunk offset = ring distance), so
chunks strictly above the diagonal contribute nothing (their scores are
masked; the compute is still issued — a skip would unbalance ring steps).

Differentiable: pure jnp + ``ppermute`` (whose transpose is the inverse
rotation), so ``jax.grad`` through a ``shard_map``'d call just works.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from tpu_ddp.parallel.mesh import SEQ_AXIS

_NEG_INF = -1e30  # mask value; avoids NaN from (-inf) - (-inf)


def _block_attn(q, k, v, m_prev, l_prev, acc_prev, q_pos, k_pos, causal,
                scale, k_valid=None):
    """One blockwise-attention update of the online softmax state.

    q: (B, Lq, H, D); k/v: (B, Lk, H, D); positions: (Lq,), (Lk,).
    State: m (B, H, Lq) running max, l (B, H, Lq) running sum,
    acc (B, Lq, H, D) unnormalized output. All state float32.
    ``k_valid`` (bool (Lk,), optional) masks out padded key positions.

    Grouped-query attention: ``k``/``v`` may carry KV < H heads (H % KV
    == 0) — each contiguous group of H/KV query heads contracts against
    its shared KV head directly, the expansion never materialized. Head
    order matches ``jnp.repeat(k, H // KV, axis=2)`` (group-contiguous).
    """
    b, lq, h, d = q.shape
    kvh = k.shape[2]
    # scores: (B, H, Lq, Lk) in f32 (MXU accumulates f32 from bf16 inputs).
    if kvh == h:
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32) * scale
    else:
        qg = q.reshape(b, lq, kvh, h // kvh, d)
        scores = jnp.einsum("bqkgd,bskd->bkgqs", qg, k,
                            preferred_element_type=jnp.float32
                            ).reshape(b, h, lq, -1) * scale
    if causal:
        mask = k_pos[None, None, None, :] > q_pos[None, None, :, None]
        scores = jnp.where(mask, _NEG_INF, scores)
    if k_valid is not None:
        scores = jnp.where(k_valid[None, None, None, :], scores, _NEG_INF)
    m_new = jnp.maximum(m_prev, jnp.max(scores, axis=-1))      # (B,H,Lq)
    p = jnp.exp(scores - m_new[..., None])                     # (B,H,Lq,Lk)
    correction = jnp.exp(m_prev - m_new)                       # (B,H,Lq)
    l_new = correction * l_prev + jnp.sum(p, axis=-1)
    v32 = v.astype(jnp.float32)
    if kvh == h:
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, v32,
                        preferred_element_type=jnp.float32)
    else:
        pg = p.reshape(b, kvh, h // kvh, lq, p.shape[-1])
        pv = jnp.einsum("bkgqs,bskd->bqkgd", pg, v32,
                        preferred_element_type=jnp.float32
                        ).reshape(b, lq, h, d)
    acc_new = acc_prev * correction.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, acc_new


def ring_attention(q, k, v, axis_name: str = SEQ_AXIS,
                   axis_size: int | None = None, causal: bool = False,
                   *, q_offset=0, cache_k=None, cache_v=None,
                   cache_valid=None):
    """Exact multi-head attention with sequence sharded over ``axis_name``.

    Must be called inside a ``shard_map`` over a mesh with that axis.
    ``q``/``k``/``v``: local chunks (B, L/sp, H, D). Returns the local
    output chunk (B, L/sp, H, D) in ``q``'s dtype.

    Cache seeding (context-parallel chunked prefill, DESIGN.md §27):
    ``cache_k``/``cache_v`` (B, S, KV, D), REPLICATED across the ring,
    hold already-committed KV for absolute positions ``0 .. S-1`` — a
    paged-pool view of the chunks prefilled so far. They seed the
    online-softmax state with one extra ``_block_attn`` before the ring
    spins, and ``q_offset`` (static or traced scalar) shifts every
    position so chunk-local indices become absolute: the result is
    exact attention over ``cache ++ current chunk``, chunk by chunk.
    ``cache_valid`` (bool (S,)) masks cache tail garbage; cache entries
    never need the causal mask (every cache position precedes
    ``q_offset``, hence every query).
    """
    if axis_size is None:
        raise ValueError("axis_size (the sp mesh extent) is required — "
                         "loop bounds must be static under jit")
    b, lc, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    my = lax.axis_index(axis_name)
    q_pos = q_offset + my * lc + jnp.arange(lc)

    m = jnp.full((b, h, lc), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, lc), jnp.float32)
    acc = jnp.zeros((b, lc, h, d), jnp.float32)
    if cache_k is not None:
        lk = cache_k.shape[1]
        m, l, acc = _block_attn(q, cache_k, cache_v, m, l, acc,
                                q_pos, jnp.arange(lk), False, scale,
                                k_valid=cache_valid)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    k_cur, v_cur = k, v
    for step in range(axis_size):
        # After `step` forward rotations each device holds the chunk that
        # originated `step` positions behind it on the ring.
        kv_owner = (my - step) % axis_size
        k_pos = q_offset + kv_owner * lc + jnp.arange(lc)
        m, l, acc = _block_attn(q, k_cur, v_cur, m, l, acc,
                                q_pos, k_pos, causal, scale)
        if step != axis_size - 1:
            # Rotate K/V one hop; XLA overlaps this ICI exchange with the
            # next iteration's einsums (independent dataflow).
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
    out = acc / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def blockwise_attention(q, k, v, causal: bool = False,
                        block_size: int = 512, *, q_pos=None, k_pos=None,
                        k_valid=None):
    """Exact attention with K/V streamed in blocks (online softmax).

    Same math as :func:`full_attention` but the score buffer is
    (B, H, L, block) instead of (B, H, L, L) — the memory-bounded jnp
    path for long local sequences (the Ulysses local attention uses this
    when the Pallas flash kernel is off, tpu_ddp/parallel/ulysses.py).

    Explicit positions (§27 chunked prefill): ``q_pos`` (Lq,) and
    ``k_pos`` (Lk,) override the default 0-based index alignment, and
    ``k_valid`` (bool (Lk,)) masks invalid keys — what lets a caller
    prepend cache KV (absolute positions 0..S-1) to a chunk whose
    queries start at an offset. Defaults reproduce the original
    program exactly — existing callers' compiled steps are unchanged.
    """
    b, L, h, d = q.shape
    Lk = k.shape[1]
    kvh = k.shape[2]  # may be < h under grouped-query attention
    explicit = (q_pos is not None or k_pos is not None
                or k_valid is not None)
    bs = min(block_size, Lk)
    n = -(-Lk // bs)
    pad = n * bs - Lk
    scale = 1.0 / (d ** 0.5)
    if explicit:
        # General path: carry positions/validity through the padding
        # and the scan explicitly. Pad positions get a huge sentinel
        # (causally masked for any query) AND k_valid False.
        if q_pos is None:
            q_pos = jnp.arange(L)
        if k_pos is None:
            k_pos = jnp.arange(Lk)
        if k_valid is None:
            k_valid = jnp.ones((Lk,), bool)
        if pad:
            k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
            v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
            k_pos = jnp.pad(k_pos, (0, pad),
                            constant_values=jnp.iinfo(jnp.int32).max)
            k_valid = jnp.pad(k_valid, (0, pad), constant_values=False)
        kb = jnp.moveaxis(k.reshape(b, n, bs, kvh, d), 1, 0)
        vb = jnp.moveaxis(v.reshape(b, n, bs, kvh, d), 1, 0)

        @jax.checkpoint
        def xbody(carry, inp):
            kc, vc, kp, kw = inp
            state = _block_attn(q, kc, vc, *carry, q_pos, kp, causal,
                                scale, k_valid=kw)
            return state, None

        init = (jnp.full((b, h, L), _NEG_INF, jnp.float32),
                jnp.zeros((b, h, L), jnp.float32),
                jnp.zeros((b, L, h, d), jnp.float32))
        (m, l, acc), _ = lax.scan(
            init=init, xs=(kb, vb, k_pos.reshape(n, bs),
                           k_valid.reshape(n, bs)), f=xbody)
        out = acc / l.transpose(0, 2, 1)[..., None]
        return out.astype(q.dtype)
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    q_pos = jnp.arange(L)
    # (n, B, bs, KV, D) so lax.scan carries the online-softmax state over
    # key blocks; XLA keeps only one block's scores live at a time.
    kb = jnp.moveaxis(k.reshape(b, n, bs, kvh, d), 1, 0)
    vb = jnp.moveaxis(v.reshape(b, n, bs, kvh, d), 1, 0)

    # Remat the block update: without it, scan's VJP stacks every block's
    # (B, H, L, bs) probabilities — O(L^2) residuals, the exact buffer
    # this function exists to avoid. Checkpointing recomputes them in the
    # backward sweep (the standard blockwise-transformer trade).
    @jax.checkpoint
    def body(carry, inp):
        m_prev, l_prev, acc_prev = carry
        kc, vc, idx = inp
        k_pos = idx * bs + jnp.arange(bs)
        state = _block_attn(q, kc, vc, m_prev, l_prev, acc_prev,
                            q_pos, k_pos, causal, scale,
                            k_valid=(k_pos < L) if pad else None)
        return state, None

    init = (jnp.full((b, h, L), _NEG_INF, jnp.float32),
            jnp.zeros((b, h, L), jnp.float32),
            jnp.zeros((b, L, h, d), jnp.float32))
    (m, l, acc), _ = lax.scan(body, init, (kb, vb, jnp.arange(n)))
    out = acc / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def full_attention(q, k, v, causal: bool = False):
    """Single-device reference: same math, whole sequence resident.
    Accepts grouped-query k/v (KV < H heads) without expansion."""
    b, L, h, d = q.shape
    kvh = k.shape[2]
    scale = 1.0 / (d ** 0.5)
    if kvh == h:
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32) * scale
    else:
        qg = q.reshape(b, L, kvh, h // kvh, d)
        scores = jnp.einsum("bqkgd,bskd->bkgqs", qg, k,
                            preferred_element_type=jnp.float32
                            ).reshape(b, h, L, L) * scale
    if causal:
        pos = jnp.arange(L)
        scores = jnp.where(pos[None, None, None, :] > pos[None, None, :, None],
                           _NEG_INF, scores)
    p = jax.nn.softmax(scores, axis=-1)
    v32 = v.astype(jnp.float32)
    if kvh == h:
        out = jnp.einsum("bhqk,bkhd->bqhd", p, v32,
                         preferred_element_type=jnp.float32)
    else:
        pg = p.reshape(b, kvh, h // kvh, L, L)
        out = jnp.einsum("bkgqs,bskd->bqkgd", pg, v32,
                         preferred_element_type=jnp.float32
                         ).reshape(b, L, h, d)
    return out.astype(q.dtype)


def repeat_kv_heads(k, v, rep: int):
    """Materialize the GQA expansion (group-contiguous, matching
    ``_block_attn``'s grouped contraction order) — only for consumers
    with no grouped path (the Pallas flash kernel)."""
    if rep == 1:
        return k, v
    return jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2)


def attend(q, k, v, *, causal: bool = False, axis_name: str | None = None,
           axis_size: int | None = None, flash: bool = False,
           mode: str = "ring"):
    """Dispatch: sequence-parallel attention when a sequence axis is given
    (``mode`` picks the scheme: ``"ring"`` K/V rotation or ``"ulysses"``
    all-to-all head re-sharding, tpu_ddp/parallel/ulysses.py), else the
    flash Pallas kernel (``flash=True``) or the jnp reference.

    Grouped-query attention: ``k``/``v`` may carry fewer heads than
    ``q`` (H % KV == 0). Every path contracts grouped — KV-width bytes
    on the wire and in memory; the flash kernel indexes K/V blocks by
    q-head group natively (tpu_ddp/ops/pallas/flash_attention.py)."""
    if axis_name is not None:
        if axis_size is None:
            # Falling back to full_attention here would silently compute
            # block-LOCAL attention on each shard — wrong logits, no error.
            raise ValueError(
                "attend: axis_name given without axis_size; pass the sp "
                "mesh extent (loop bounds must be static under jit)")
        if axis_size > 1:
            if mode == "ulysses":
                from tpu_ddp.parallel.ulysses import ulysses_attention
                return ulysses_attention(q, k, v, axis_name, axis_size,
                                         causal=causal, flash=flash)
            if mode != "ring":
                raise ValueError(f"attend: unknown sequence-parallel mode "
                                 f"{mode!r}; expected 'ring' or 'ulysses'")
            return ring_attention(q, k, v, axis_name, axis_size,
                                  causal=causal)
    if flash:
        from tpu_ddp.ops.pallas import flash_attention
        return flash_attention(q, k, v, causal)
    return full_attention(q, k, v, causal=causal)
