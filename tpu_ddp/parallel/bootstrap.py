"""Distributed process bootstrap — the L5 layer.

Replaces the reference's ``init_distributed_setup`` (reference
part2/part2a/main.py:52-58: MASTER_ADDR/MASTER_PORT env vars + gloo TCP
rendezvous) with ``jax.distributed.initialize``: the coordinator address is
``master_ip:master_port``, ``num_processes`` is the ``--num-nodes`` flag and
``process_id`` is the rank — a 1:1 mapping of the reference CLI contract.

Also preserves:
- hostname rank inference (``node3`` -> 3, reference part2/part2a/main.py:35-39),
- the ``test_distributed_setup`` sanity probe printing
  initialized/backend/world_size/rank (reference part2/part2a/main.py:42-49),
- teardown (``dist.destroy_process_group()``, reference part2/part2a/main.py:207).
"""

from __future__ import annotations

import dataclasses
import os
import re

import jax


@dataclasses.dataclass
class DistributedContext:
    """What L6 hands to the rest of the stack after bootstrap."""

    rank: int                 # process id (one process per host/node)
    world_size: int           # number of processes
    num_devices: int          # total devices across all processes
    local_devices: tuple      # this process's devices
    coordinator: str | None   # "ip:port" when multi-process, else None
    backend: str              # jax platform name ("tpu" / "cpu" / ...)

    @property
    def is_initialized(self) -> bool:
        return True


def get_rank_from_hostname(hostname: str | None = None) -> int:
    """Default rank = the digit in a ``nodeN`` hostname.

    The reference reads exactly ``os.uname().nodename[4]`` (reference
    part2/part2a/main.py:35-39), which breaks for any other hostname
    (SURVEY.md §3.5); we keep the semantic but parse defensively and fall
    back to 0 so single-host runs work anywhere.
    """
    if hostname is None:
        hostname = os.uname().nodename
    m = re.match(r"node(\d+)", hostname)
    return int(m.group(1)) if m else 0


def init_distributed_setup(
    master_ip: str = "10.10.1.1",
    master_port: str = "4000",
    rank: int = 0,
    world_size: int = 1,
) -> DistributedContext:
    """Join the process group and return a :class:`DistributedContext`.

    Defaults mirror the reference CLI defaults (reference
    part2/part2a/main.py:22-25). With ``world_size == 1`` (or when JAX is
    already multi-process-initialized by the environment) no rendezvous is
    performed — the local devices are the whole world, which is also how a
    single TPU host with N chips runs the distributed parts.
    """
    coordinator = None
    if world_size is None:
        raise ValueError(
            "--num-nodes is required (the reference CLI has no default; "
            "SURVEY.md §3.5)")
    if not (0 <= rank < world_size):
        raise ValueError(
            f"rank {rank} out of range for world size {world_size}")
    # NOTE: nothing before this point may touch the backend (jax.devices,
    # jax.process_count, ...) — jax.distributed.initialize must run first.
    if world_size > 1 and not jax.distributed.is_initialized():
        coordinator = f"{master_ip}:{master_port}"
        if "cpu" in os.environ.get("JAX_PLATFORMS", "").lower().split(","):
            # The CPU backend's default collectives implementation
            # ("none") rejects multi-process computations at the first
            # collective; gloo-over-TCP is the working one — and the
            # literal analogue of the reference's gloo process group.
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo")
            except (AttributeError, ValueError):
                pass  # flag renamed/absent: that jax works by default
        from tpu_ddp.resilience.elastic import (bootstrap as
                                                elastic_bootstrap,
                                                elastic_env_active)
        if elastic_env_active():
            # Elastic worlds must survive peer death: the stock
            # initialize installs a missed-heartbeat callback that
            # LOG(FATAL)s the survivors and a shutdown barrier a dead
            # peer fails fatally (resilience/elastic.py). Same
            # rendezvous semantics, non-fatal failure modes.
            elastic_bootstrap(coordinator, world_size, rank)
        else:
            # Blocks until all `world_size` processes join, like the
            # gloo TCP rendezvous at reference part2/part2a/main.py:56-58.
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=world_size,
                process_id=rank,
            )
    devices = jax.devices()
    return DistributedContext(
        rank=jax.process_index() if world_size > 1 else rank,
        world_size=max(world_size, jax.process_count()),
        num_devices=len(devices),
        local_devices=tuple(jax.local_devices()),
        coordinator=coordinator,
        backend=devices[0].platform,
    )


def test_distributed_setup(ctx: DistributedContext) -> dict:
    """Print the same fields as the reference's sanity probe
    (reference part2/part2a/main.py:42-49) and return them for tests."""
    info = {
        "is_initialized": ctx.is_initialized,
        "backend": ctx.backend,
        "world_size": ctx.world_size,
        "rank": ctx.rank,
        "num_devices": ctx.num_devices,
    }
    print(f"Distributed setup initialized: {info['is_initialized']}")
    print(f"Backend: {info['backend']}")
    print(f"World size: {info['world_size']}")
    print(f"Rank: {info['rank']} | devices: {info['num_devices']}")
    return info


def shutdown(ctx: DistributedContext) -> None:
    """Teardown, mirroring ``dist.destroy_process_group()``
    (reference part2/part2a/main.py:207)."""
    if ctx.coordinator is not None:
        from tpu_ddp.resilience.elastic import elastic_env_active
        if elastic_env_active():
            # The elastic client never enters the shutdown barrier (a
            # departed peer fails it fatally, and our non-fatal client
            # hangs in it); processes just exit — the coordination
            # stubs are leaked by design (resilience/elastic.py).
            return
        jax.distributed.shutdown()
