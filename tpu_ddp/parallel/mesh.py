"""Device-mesh construction and sharding specs.

The TPU-native replacement for the reference's notion of "world" (N gloo
processes): a ``jax.sharding.Mesh`` over all devices with a ``dp`` axis.
Data-parallel replicas are mesh slots; the batch is sharded over ``dp`` and
parameters are replicated — XLA then lowers the gradient ``psum`` onto ICI
(intra-slice) / DCN (cross-slice) automatically (SURVEY.md §2 row N1).

A second, size-1-by-default ``mp`` axis is kept in the mesh shape so tensor/
pipeline extensions can widen the mesh without touching callers.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "dp"
MODEL_AXIS = "mp"


def make_mesh(devices=None, dp: int | None = None, mp: int = 1) -> Mesh:
    """Build a (dp, mp) mesh over ``devices`` (default: all devices).

    ``dp`` defaults to ``len(devices) // mp``. For pure data parallelism
    (the reference's only mode) this is a 1-D dp mesh with a trivial mp
    axis.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if dp is None:
        if n % mp:
            raise ValueError(f"{n} devices not divisible by mp={mp}")
        dp = n // mp
    if dp * mp != n:
        raise ValueError(f"dp*mp = {dp}*{mp} != {n} devices")
    arr = np.asarray(devices).reshape(dp, mp)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def data_parallel_specs():
    """(batch_spec, replicated_spec) for classic DP: batch split over dp,
    params/opt-state replicated."""
    return P(DATA_AXIS), P()


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
