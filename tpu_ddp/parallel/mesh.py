"""Device-mesh construction and sharding specs.

The TPU-native replacement for the reference's notion of "world" (N gloo
processes): a ``jax.sharding.Mesh`` over all devices with a ``dp`` axis.
Data-parallel replicas are mesh slots; the batch is sharded over ``dp`` and
parameters are replicated — XLA then lowers the gradient ``psum`` onto ICI
(intra-slice) / DCN (cross-slice) automatically (SURVEY.md §2 row N1).

The mesh is always (``dp``, ``sp``, ``mp``, ``pp``, ``ep``): ``sp``
shards the sequence axis for ring attention, ``mp`` shards tensors
(Megatron column/row, tpu_ddp/parallel/tensor_parallel.py), ``pp`` shards
the layer stack into pipeline stages (tpu_ddp/parallel/pipeline.py),
``ep`` shards mixture-of-experts layers (tpu_ddp/parallel/moe.py) — all
size 1 by default so the DP-only ladder sees a plain 1-D dp mesh.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "dp"
SEQ_AXIS = "sp"
MODEL_AXIS = "mp"
PIPE_AXIS = "pp"
EXPERT_AXIS = "ep"


def make_mesh(devices=None, dp: int | None = None, sp: int = 1,
              mp: int = 1, pp: int = 1, ep: int = 1) -> Mesh:
    """Build a (dp, sp, mp, pp, ep) mesh over ``devices`` (default: all).

    ``dp`` defaults to ``len(devices) // (sp * mp * pp * ep)``. For pure
    data parallelism (the reference's only mode) this is a 1-D dp mesh
    with trivial sp/mp/pp/ep axes; ``sp`` > 1 shards the sequence axis
    for ring attention (tpu_ddp/parallel/ring_attention.py).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    denom = sp * mp * pp * ep
    if dp is None:
        if n % denom:
            raise ValueError(
                f"{n} devices not divisible by sp*mp*pp*ep={denom}")
        dp = n // denom
    if dp * denom != n:
        raise ValueError(
            f"dp*sp*mp*pp*ep = {dp}*{sp}*{mp}*{pp}*{ep} != {n} devices")
    arr = np.asarray(devices).reshape(dp, sp, mp, pp, ep)
    return Mesh(arr, (DATA_AXIS, SEQ_AXIS, MODEL_AXIS, PIPE_AXIS,
                      EXPERT_AXIS))


def data_parallel_specs():
    """(batch_spec, replicated_spec) for classic DP: batch split over dp,
    params/opt-state replicated."""
    return P(DATA_AXIS), P()


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def put_sharded(array, sharding: NamedSharding):
    """Place a host array into ``sharding``: a single process puts the
    global array; in a multi-process launch each process contributes its
    LOCAL shard and the pieces assemble into one global array. The one
    placement rule both train engines share."""
    if jax.process_count() == 1:
        return jax.device_put(array, sharding)
    return jax.make_array_from_process_local_data(sharding, array)
