"""Live TrainState redistribution — shardings as first-class objects.

The strategies bake their shardings into jit closures (engine.py
``_build_train_step``, lm.py ``_compile_step``); nothing about "how is
this state laid out" survives outside a live Trainer. That is fine
until the mesh *changes under you*: a preempted host shrinks the world,
a recovered one grows it, and every closure — and every NamedSharding
aimed at the dead mesh — is garbage.

This module extracts the layout into a :class:`ShardingPlan`, a small
serializable value (strategy name, mesh axis sizes, per-tree
PartitionSpec trees) that can be written next to a checkpoint, shipped
across a membership epoch, and *re-resolved* against a mesh of a
different size. The redistribution itself follows the shape of
*Memory-efficient array redistribution through portable collective
communication* (arxiv 2112.01075): rather than materializing the whole
state replicated (the all-gather-everything baseline), state moves
through a sequence of per-leaf transfers — each leaf is gathered to its
canonical host form, re-partitioned for the destination layout, and
placed, so the device-memory peak is ONE replicated leaf and the host
is the portable transport. On the CPU/gloo backend the same code path
runs unchanged, which is what makes the whole elastic loop testable in
tier-1 (conftest's 8 virtual devices stand in for 8 hosts).

Layout resolution is strategy-aware but *world-size free*: the flat
dp-padded layouts of ZeRO-1/FSDP (parallel/zero.py ``_FlatLayout``) are
pure functions of (template, axis sizes), so the same canonical bytes
reshard onto any dp — the property the cross-world-size checkpoint
restore and the live reshard both lean on.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_ddp.parallel.mesh import DATA_AXIS

PLAN_FILENAME = "sharding_plan.json"

# ---------------------------------------------------------------------------
# JSON codec for pytrees of PartitionSpecs.
#
# The trees we serialize are built from dicts (model params, optimizer
# slots), lists/tuples (pipeline stages), and leaves that are
# PartitionSpec / None / plain scalars. JSON has no tuples and no
# PartitionSpecs, so both get explicit markers; inside a spec, an entry
# is None, an axis name, or a tuple of axis names (encoded as a list —
# unambiguous there, since bare lists never appear inside a spec).
# ---------------------------------------------------------------------------


def encode_spec_tree(tree: Any) -> Any:
    """Pytree of P/None/scalar leaves -> JSON-serializable structure."""
    if isinstance(tree, P):
        return {"__pspec__": [list(e) if isinstance(e, tuple) else e
                              for e in tree]}
    if isinstance(tree, tuple):
        return {"__tuple__": [encode_spec_tree(x) for x in tree]}
    if isinstance(tree, list):
        return [encode_spec_tree(x) for x in tree]
    if isinstance(tree, dict):
        return {str(k): encode_spec_tree(v) for k, v in tree.items()}
    if tree is None or isinstance(tree, (bool, int, float, str)):
        return tree
    raise TypeError(
        f"cannot serialize {type(tree).__name__} in a spec tree")


def decode_spec_tree(obj: Any) -> Any:
    """Inverse of :func:`encode_spec_tree`."""
    if isinstance(obj, dict):
        if "__pspec__" in obj:
            return P(*[tuple(e) if isinstance(e, list) else e
                       for e in obj["__pspec__"]])
        if "__tuple__" in obj:
            return tuple(decode_spec_tree(x) for x in obj["__tuple__"])
        return {k: decode_spec_tree(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [decode_spec_tree(x) for x in obj]
    return obj


def _is_spec(x: Any) -> bool:
    return isinstance(x, P)


def broadcast_shardings(mesh, specs: Any, tree: Any) -> Any:
    """Broadcast a (possibly prefix) spec tree over a concrete state tree.

    Every P leaf in ``specs`` covers the whole subtree at the matching
    position in ``tree`` — the same contract engine.py's shard_map specs
    already follow, so a plan resolved here places state exactly where
    the train step expects it.
    """
    return jax.tree.map(
        lambda spec, sub: jax.tree.map(
            lambda _: NamedSharding(mesh, spec), sub),
        specs, tree, is_leaf=_is_spec)


@dataclasses.dataclass
class ShardingPlan:
    """The serializable layout contract of one trainer configuration.

    ``mesh_axes`` records the axis sizes the plan was *built* against;
    :meth:`resolve_axes` recomputes them for a different device count
    (only the data axis absorbs world-size changes — model axes are
    part of the program, not the fleet).
    """

    strategy: str
    mesh_axes: tuple  # ((axis_name, size), ...) in mesh order
    param_specs: Any  # pytree with P leaves (prefix or per-leaf)
    opt_specs: Any
    comp_specs: Any = None
    batch_spec: Any = dataclasses.field(default_factory=lambda: P(DATA_AXIS))
    # Per-stage layout metadata (round 10): specs alone cannot tell an
    # interleaved-virtual-stage row order from the linear one — both are
    # P(pp, ...) over identical shapes — so a plan carries the stage
    # layout explicitly and compatibility REFUSES across different row
    # orders instead of silently mixing layers. None = linear stages
    # (every pre-round-10 plan decodes to None and stays compatible).
    stage_layout: Any = None

    # -- serialization ----------------------------------------------------

    def to_json(self) -> str:
        obj = {
            "version": 1,
            "strategy": self.strategy,
            "mesh_axes": [[n, s] for n, s in self.mesh_axes],
            "param_specs": encode_spec_tree(self.param_specs),
            "opt_specs": encode_spec_tree(self.opt_specs),
            "comp_specs": encode_spec_tree(self.comp_specs),
            "batch_spec": encode_spec_tree(self.batch_spec),
        }
        if self.stage_layout is not None:
            # Written only when set: version stays 1 and plans from
            # linear-stage trainers are byte-identical to pre-round-10.
            obj["stage_layout"] = encode_spec_tree(self.stage_layout)
        return json.dumps(obj, indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "ShardingPlan":
        obj = json.loads(text)
        if obj.get("version") != 1:
            raise ValueError(
                f"unknown ShardingPlan version {obj.get('version')!r}")
        return cls(
            strategy=obj["strategy"],
            mesh_axes=tuple((n, int(s)) for n, s in obj["mesh_axes"]),
            param_specs=decode_spec_tree(obj["param_specs"]),
            opt_specs=decode_spec_tree(obj["opt_specs"]),
            comp_specs=decode_spec_tree(obj["comp_specs"]),
            batch_spec=decode_spec_tree(obj["batch_spec"]),
            stage_layout=decode_spec_tree(obj.get("stage_layout")),
        )

    def save(self, directory: str) -> str:
        path = os.path.join(directory, PLAN_FILENAME)
        tmp = path + ".tmp"
        os.makedirs(directory, exist_ok=True)
        with open(tmp, "w") as f:
            f.write(self.to_json())
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, directory: str) -> "ShardingPlan | None":
        path = os.path.join(directory, PLAN_FILENAME)
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return cls.from_json(f.read())

    # -- re-resolution ----------------------------------------------------

    @property
    def axis_sizes(self) -> dict:
        return dict(self.mesh_axes)

    def resolve_axes(self, n_devices: int) -> dict:
        """Axis sizes for a NEW world of ``n_devices``.

        Model axes (sp/mp/pp/ep) keep their sizes — they partition the
        program. The data axis is the elastic one: it absorbs whatever
        devices remain. A world the model axes no longer divide cannot
        be resharded onto (that membership change forces a restart;
        DESIGN.md §17).
        """
        sizes = dict(self.mesh_axes)
        model = 1
        for name, size in sizes.items():
            if name != DATA_AXIS:
                model *= size
        if n_devices % model != 0:
            raise ValueError(
                f"cannot resolve plan onto {n_devices} devices: model "
                f"axes need a multiple of {model}")
        sizes[DATA_AXIS] = n_devices // model
        return sizes

    def shardings_for(self, mesh, tree: Any, which: str) -> Any:
        """NamedShardings for ``tree`` on ``mesh`` per this plan.

        ``which`` selects the spec tree: 'params' | 'opt' | 'comp'.
        """
        specs = {"params": self.param_specs, "opt": self.opt_specs,
                 "comp": self.comp_specs}[which]
        return broadcast_shardings(mesh, specs, tree)

    def compatible_with(self, other: "ShardingPlan") -> bool:
        """Same layout contract (strategy + specs + stage row order),
        ANY world size."""
        return (self.strategy == other.strategy
                and self.param_specs == other.param_specs
                and self.opt_specs == other.opt_specs
                and self.comp_specs == other.comp_specs
                and self.stage_layout == other.stage_layout)

    def __eq__(self, other) -> bool:
        if not isinstance(other, ShardingPlan):
            return NotImplemented
        return (self.compatible_with(other)
                and self.mesh_axes == other.mesh_axes
                and self.batch_spec == other.batch_spec)


def redistribute_state(state, src_trainer, dst_trainer):
    """Move a live TrainState from one trainer's layout to another's.

    Fast path: identical plan AND identical mesh — the state is already
    where it needs to be; hand it back untouched (the degenerate
    same-mesh case of 2112.01075's decomposition, zero collectives).

    Otherwise: per-leaf gather to canonical host form on the source
    layout, re-partition + place on the destination. Both halves live
    on the Trainer (``state_to_host`` / ``state_from_host``) because
    they are strategy-aware; this function is the portable seam between
    them.
    """
    src_plan = src_trainer.sharding_plan()
    dst_plan = dst_trainer.sharding_plan()
    if src_plan == dst_plan and src_trainer.mesh is dst_trainer.mesh:
        return state
    return dst_trainer.state_from_host(src_trainer.state_to_host(state))
