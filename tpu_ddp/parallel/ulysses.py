"""Ulysses sequence parallelism: all-to-all head/sequence re-sharding.

No reference counterpart — the reference's workload is 32x32 image
classification with no sequence dimension (SURVEY.md §5 "Long-context /
sequence parallelism: Absent") — but long-context training is first-class
in this framework, and ring attention (tpu_ddp/parallel/ring_attention.py)
is only one of the two standard schemes. This module implements the other:
DeepSpeed-Ulysses (Jacobs et al., arXiv:2309.14509 — reimplemented from
the paper's description, not from any code).

Scheme: activations arrive sequence-sharded over the ``sp`` mesh axis,
shape (B, L/sp, H, D) per device. One ``lax.all_to_all`` re-shards from
sequence to heads — every device ends up with the FULL sequence for H/sp
of the heads, (B, L, H/sp, D) — then attention runs entirely locally
(dense, or the Pallas flash kernel: no inter-device traffic during the
softmax), and a second all-to-all restores the sequence sharding.

Trade-off vs ring attention (why both exist):

- Ulysses moves 2 all-to-alls of the QKV/O activations per attention
  call; total bytes on the wire are O(B.L.H.D / sp) per device, CONSTANT
  in sp — it scales better than ring's ppermute chain when sp is large
  and heads are plentiful, and the local attention can use the flash
  Pallas kernel unchanged.
- Ring keeps heads intact (works for H < sp, e.g. MQA/GQA with few KV
  heads) and overlaps its K/V hops with compute; Ulysses requires
  ``H % sp == 0`` and its all-to-alls sit on the critical path, but XLA
  lowers them to a single ICI all-to-all, the cheapest collective per
  byte on a torus.

Both compute EXACT attention — tests/test_ulysses.py checks this one
against the same single-device reference as ring.

Differentiable: ``lax.all_to_all`` is its own transpose (with split/concat
axes swapped), so ``jax.grad`` through a ``shard_map``'d call just works.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from tpu_ddp.parallel.mesh import SEQ_AXIS
from tpu_ddp.parallel.ring_attention import (blockwise_attention,
                                             repeat_kv_heads)


def _heads_to_seq(x, axis_name, stacked: bool = False):
    """(B, L/sp, H, D) -> (B, L, H/sp, D): scatter heads, gather sequence.

    With ``tiled=True`` the split axis is cut into sp blocks (block i ->
    device i) and received blocks concatenate along the concat axis in
    source-device order — so the gathered sequence axis comes out in
    global order because device j held chunk j. ``stacked`` shifts both
    axes by one for a (3, B, ...) QKV stack.
    """
    off = 1 if stacked else 0
    return lax.all_to_all(x, axis_name, split_axis=2 + off,
                          concat_axis=1 + off, tiled=True)


def _seq_to_heads(x, axis_name):
    """(B, L, H/sp, D) -> (B, L/sp, H, D): the inverse re-shard."""
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention(q, k, v, axis_name: str = SEQ_AXIS,
                      axis_size: int | None = None, causal: bool = False,
                      flash: bool = False, *, q_offset=0, cache_k=None,
                      cache_v=None, cache_valid=None):
    """Exact multi-head attention with sequence sharded over ``axis_name``.

    Must be called inside a ``shard_map`` over a mesh with that axis.
    ``q``/``k``/``v``: local chunks (B, L/sp, H, D) with RoPE (or any
    position encoding) already applied at the chunks' GLOBAL positions.
    Returns the local output chunk (B, L/sp, H, D) in ``q``'s dtype.

    Cache prepending (context-parallel chunked prefill, DESIGN.md §27):
    ``cache_k``/``cache_v`` (B, S, KV, D), replicated across ranks, hold
    committed KV for absolute positions ``0 .. S-1``; ``q_offset``
    shifts the gathered chunk's positions to absolute. After the
    all-to-all each rank holds the full chunk for H/sp heads — it
    slices ITS head group out of the replicated cache, concatenates
    cache-then-chunk along keys, and runs the blockwise path with
    explicit positions (``cache_valid`` masks the cache tail). This
    path requires the jnp blockwise attention (the flash kernel has no
    explicit-position interface), so ``flash`` must be off when a
    cache is given.
    """
    if axis_size is None:
        raise ValueError("axis_size (the sp mesh extent) is required — "
                         "loop bounds must be static under jit")
    if cache_k is not None and flash:
        raise ValueError("ulysses_attention: cache prepending requires "
                         "the blockwise path (flash=False)")
    h, kvh = q.shape[2], k.shape[2]
    if h % axis_size:
        raise ValueError(
            f"ulysses_attention needs num_heads % sp == 0 (got heads={h}, "
            f"sp={axis_size}); use ring attention for head-poor models")
    if kvh != h and kvh % axis_size:
        # Grouped K/V can only scatter when KV % sp == 0; otherwise the
        # expansion happens pre-collective (the wire saving is lost, the
        # result unchanged). Head-contiguous groups survive the a2a: q's
        # i-th head block maps exactly onto kv's i-th head block.
        k, v = repeat_kv_heads(k, v, h // kvh)
        if cache_k is not None:
            cache_k, cache_v = repeat_kv_heads(cache_k, cache_v, h // kvh)
        kvh = h
    if kvh == h:
        # One collective for all three tensors: same bytes as three
        # separate all_to_alls but a single launch on the critical path.
        qkv = _heads_to_seq(jnp.stack([q, k, v]), axis_name, stacked=True)
        q, k, v = qkv[0], qkv[1], qkv[2]
    else:
        q = _heads_to_seq(q, axis_name)
        kv = _heads_to_seq(jnp.stack([k, v]), axis_name, stacked=True)
        k, v = kv[0], kv[1]
    # Full sequence is now resident: local positions ARE global positions,
    # so the plain causal mask is exact. Local attention must stay
    # memory-bounded — the gathered L here is sp x the resident chunk, and
    # materializing (L, L) scores would forfeit what sp is for — so it's
    # the Pallas flash kernel or the blockwise jnp path, never
    # full_attention.
    if cache_k is not None:
        # Each rank now owns head group `idx`: slice the SAME group out
        # of the replicated cache (group-contiguous head order survives
        # the tiled a2a) and prepend it on the key axis. Explicit
        # positions make the causal mask exact: the chunk's queries sit
        # at q_offset.., the cache's keys at 0..S-1 (always visible,
        # modulo cache_valid).
        L = q.shape[1]
        S = cache_k.shape[1]
        idx = lax.axis_index(axis_name)
        ckvh = cache_k.shape[2]
        per = ckvh // axis_size
        ck = lax.dynamic_slice_in_dim(cache_k, idx * per, per, axis=2)
        cv = lax.dynamic_slice_in_dim(cache_v, idx * per, per, axis=2)
        pos = q_offset + jnp.arange(L)
        out = blockwise_attention(
            q,
            jnp.concatenate([ck.astype(k.dtype), k], axis=1),
            jnp.concatenate([cv.astype(v.dtype), v], axis=1),
            causal=causal, q_pos=pos,
            k_pos=jnp.concatenate([jnp.arange(S), pos]),
            k_valid=jnp.concatenate(
                [jnp.ones((S,), bool) if cache_valid is None
                 else cache_valid, jnp.ones((L,), bool)]))
    elif flash:
        from tpu_ddp.ops.pallas import flash_attention
        # Grouped K/V go straight in: the kernel indexes K/V blocks by
        # q-head group natively, and the a2a's contiguous head blocks
        # keep groups contiguous locally (q block i's heads map exactly
        # onto kv block i's heads).
        out = flash_attention(q, k, v, causal)
    else:
        out = blockwise_attention(q, k, v, causal=causal)
    return _seq_to_heads(out, axis_name)
