"""Tensor (model) parallelism primitives — Megatron-style sharded matmuls.

No reference counterpart (the reference implements data parallelism only —
SURVEY.md §2 "Absent parallelism strategies"); this module exists because
multi-axis model sharding is first-class in this framework. The scheme is
the classic column/row-parallel pair (Shoeybi et al., "Megatron-LM",
arXiv:1909.08053 — reimplemented from the paper's algebra, not from any
code), expressed the shard_map way:

- **column-parallel** matmul ``y @ W_col``: ``W`` is sharded on its OUTPUT
  axis over the ``mp`` mesh axis; each device computes its slice of the
  output with no communication. Its input must carry the ``f`` operator
  (:func:`tp_input`): identity in the forward pass, gradient ``psum`` in
  the backward pass — because each shard back-propagates only its slice's
  contribution to ``dy``, the true ``dy`` is the sum over shards.
- **row-parallel** matmul ``h @ W_row``: ``W`` is sharded on its INPUT
  axis; each device computes a partial sum of the full output, combined
  with an explicit ``lax.psum`` (:func:`tp_output`) — the ``g`` operator.
  Its backward is the free part: the psum's transpose is a broadcast.

One transformer block therefore costs exactly two ``psum``s (after the
attention output projection and after the MLP down-projection), which XLA
lowers onto ICI and overlaps with neighbouring compute. Everything outside
the column→row sandwiches (LayerNorm, residual stream, embeddings, LM
head) stays replicated over ``mp``, and because ``tp_input`` sits between
the LayerNorm and the column matmul, gradients of those replicated
parameters come out identical on every ``mp`` shard — the replication
invariant the optimizer relies on (tested in tests/test_tensor_parallel.py
by numerically comparing a TP step against a dense step; psum reduction
order makes bitwise equality unattainable).
"""

from __future__ import annotations

import functools

import jax
from jax import custom_vjp, lax
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_ddp.parallel.mesh import MODEL_AXIS, make_mesh


@functools.partial(custom_vjp, nondiff_argnums=(1,))
def tp_input(x, axis_name: str = MODEL_AXIS):
    """Megatron's ``f``: identity forward, gradient all-reduce backward.

    Place immediately before a column-parallel matmul. The forward input is
    replicated over ``axis_name``; each shard's backward contributes only
    its output-slice's term of the input gradient, so the transpose sums
    them — making every gradient upstream of this point (LayerNorm scales,
    embeddings, the residual stream) exact and replicated.
    """
    return x


def _tp_input_fwd(x, axis_name):
    return x, None


def _tp_input_bwd(axis_name, _, g):
    return (lax.psum(g, axis_name),)


tp_input.defvjp(_tp_input_fwd, _tp_input_bwd)


@functools.partial(custom_vjp, nondiff_argnums=(1,))
def tp_output(x, axis_name: str = MODEL_AXIS):
    """Megatron's ``g``: all-reduce the row-parallel partial sums.

    Place immediately after a row-parallel matmul. The backward is the
    identity — the output (and hence its cotangent) is replicated over
    ``axis_name``, and each shard's partial-sum input receives exactly
    that cotangent. Spelled as a custom_vjp because under
    ``check_vma=False`` shard_map cannot see the replication and would
    transpose a bare ``lax.psum`` into another ``psum``, inflating every
    gradient that flows through the block branch by the axis size.
    """
    return lax.psum(x, axis_name)


def _tp_output_fwd(x, axis_name):
    return lax.psum(x, axis_name), None


def _tp_output_bwd(axis_name, _, g):
    return (g,)


tp_output.defvjp(_tp_output_fwd, _tp_output_bwd)


# ---- tensor-parallel SERVING ------------------------------------------


def serve_param_specs(model) -> dict:
    """Megatron placement for a dense decode checkpoint, independent of
    the model's training-time ``tp_size`` (a DP-trained checkpoint is
    dense; serving re-shards it): attention head axes and the MLP
    hidden axis split over ``mp``, LayerNorms / embeddings / LM head
    replicated. Mirrors :meth:`TransformerLM.param_specs` but hardwires
    the ``mp`` mesh axis — the training specs go replicated whenever
    the model itself was not built tensor-parallel."""
    if getattr(model, "moe_experts", 0):
        raise ValueError("tensor-parallel serving supports dense "
                         "models only (MoE routing is not decodable "
                         "through the paged engine)")
    mp = MODEL_AXIS
    ln = {"scale": P(), "bias": P()}
    blk = {
        "ln1": dict(ln),
        "wo": P(mp, None, None),
        "ln2": dict(ln),
        "w1": P(None, mp),
        "w2": P(mp, None),
    }
    if model.is_gqa:
        blk["wq"] = P(None, mp, None)
        blk["wkv"] = P(None, None, mp, None)
    else:
        blk["wqkv"] = P(None, None, mp, None)
    return {
        "embed": P(),
        "ln_f": dict(ln),
        "head": P(),
        "blocks": tuple(dict(blk) for _ in range(model.num_layers)),
    }


def shard_decode_params(model, params, devices=None):
    """Place dense decode params onto an ``mp``-only mesh over
    ``devices`` per :func:`serve_param_specs`; returns ``(params,
    mesh)``. The serve engine's jitted steps then run under GSPMD:
    QKV/MLP up-projections are column-parallel (no communication), the
    attention output and MLP down-projections row-parallel (one
    all-reduce each) — the same two-psum-per-block cost as TP training,
    with the KV pool and all host-built step inputs replicated."""
    devices = list(devices) if devices is not None else jax.devices()
    tp = len(devices)
    kv = model.kv_heads
    if model.num_heads % tp or kv % tp or model.d_ff % tp:
        raise ValueError(
            f"cannot shard decode params over {tp} devices: "
            f"num_heads={model.num_heads}, kv_heads={kv}, "
            f"d_ff={model.d_ff} must all be divisible by the "
            "tensor-parallel degree")
    mesh = make_mesh(devices, dp=1, mp=tp)
    specs = serve_param_specs(model)
    sharded = jax.tree.map(
        lambda s, x: jax.device_put(x, NamedSharding(mesh, s)),
        specs, params, is_leaf=lambda x: isinstance(x, P))
    return sharded, mesh
