"""Compressed gradient collectives — bf16/int8 wire formats for the ladder.

Every rung of the sync ladder ships gradients at fp32; this layer wraps
any rung with a reduced wire format while keeping fp32 accumulation:

- ``none``  — no-op (the fp32 baseline).
- ``bf16``  — gradients cast to bfloat16 before the collective, mean
  accumulated in fp32 after. Stateless; 2x the wire bytes back.
- ``int8``  — blockwise int8 quantization (per-block fp32 scales over
  ``block_size``-element blocks, stochastic rounding) with an
  error-feedback residual: each device re-injects the quantization
  error it introduced into its NEXT step's gradient, so the bias of
  the lossy wire telescopes away (Seide et al.'s 1-bit-SGD trick,
  generalized to 8 bits). ~4x the wire bytes back.
- ``int8-noef`` — int8 without the residual (ablation: shows the drift
  error feedback removes; tests/test_compress.py pins it).
- ``sparse`` — LOSSLESS zero-chunk elision (EdgeCodec only): the flat
  payload is cut into ``block_size``-element chunks, a packed bitmap
  marks the nonzero ones, and only those travel at fp32. Exact (no
  error feedback to carry), and the natural wire for MoE expert
  deltas, where one optimizer step touches only the routed-to experts
  and every untouched expert row is an all-zero delta chunk
  (tpu_ddp/publish/, experiments/moe_sweep.json).

Wire scheme. A compressed all-reduce is built from dtype-PRESERVING
movement collectives instead of an arithmetic ``psum``:

    phase 1 (reduce):    all_to_all of quantized rows — each device
                         receives every peer's row of ITS 1/N chunk and
                         accumulates the mean in fp32;
    phase 2 (broadcast): the owner re-quantizes its chunk's mean and
                         all_gathers it (replicated rungs only — the
                         ZeRO/FSDP scattered path stops after phase 1,
                         exactly the folded reduce_scatter半 they need).

Two reasons this shape, both load-bearing:

1. Wire volume. At N devices an fp32 all-reduce moves 8S(N-1)/N bytes
   for S gradient elements. The two-phase scheme moves 2 * wS(N-1)/N
   (w = wire bytes/element), i.e. exactly 8/(2w): 2.0x for bf16, ~3.9x
   for int8 (+1/64 scale overhead). A naive "all_gather the quantized
   gradients" moves (N-1)wS — at w=1, N=8 that is NO reduction.
2. HLO verifiability. Arithmetic collectives are subject to backend
   float-legalization: XLA:CPU's FloatNormalization rewrites a bf16
   ``all-reduce`` to convert→f32-all-reduce→convert, silently widening
   the wire back to fp32 (measured; the numerics keep the bf16
   rounding, the bytes don't shrink). Movement collectives at INTEGER
   dtypes are untouched by that pass on every backend, so bf16 payloads
   travel bitcast as ``u16`` and int8 as ``s8`` — the compiled-HLO
   invariant (tests/test_compress.py, scripts/comm_volume.py) can then
   assert the reduced dtype is really on the wire, not constant-folded
   away (utils/hlo_comm.py scans for it).

Error-feedback algebra (int8). With per-device residual r_i and
acc_i = g_i + r_i, phase 1 introduces e1_i = acc_i - deq(q(acc_i)) on
device i and phase 2 introduces e2 = m - deq(q(m)) on the chunk's
owner, where m is the fp32 mean of the dequantized rows. The applied
gradient is mean_i(acc_i) - mean_i(e1_i) - e2, so setting

    r_i' = e1_i  +  N * e2   (the owner's chunk only)

makes mean_i(r_i') equal the full error — the residual carried into the
next step compensates exactly (owner-attributed: only the device that
quantized the mean charges itself the broadcast error, scaled by N so
the mean over devices recovers it once).

The residual pytree lives in ``TrainState.comp_state`` (engine.py):
threaded through the jitted step's carry, donated with params/opt
state, checkpointed, selected OLD on a StepGuard skip (a skipped step
must not consume residuals), and reset to zeros on restore-mismatch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

SPECS = ("none", "bf16", "int8", "int8-noef")

# Point-to-point-only wires (EdgeCodec / the publish delta push). The
# collective compressor cannot ship "sparse": its all_to_all phases
# need static per-device payload shapes, while the sparse wire's whole
# point is a data-dependent payload size — fine on a host-loop edge.
EDGE_SPECS = SPECS + ("sparse",)

# Replicated rungs the compressor can wrap (kind -> collective shape);
# the ZeRO/FSDP rungs use scatter_mean instead.
REPLICATED_KINDS = ("gather_scatter", "all_reduce", "fused")


def get_compressor(spec: str | None, block_size: int = 256
                   ) -> "GradCompressor":
    """Resolve a compressor spec string (None == 'none')."""
    return GradCompressor(spec or "none", block_size=block_size)


class GradCompressor:
    """Gradient wire compression for one sync rung.

    Jit-side entry points (call INSIDE the shard_map'd step):

    - :meth:`sync_replicated` — full compressed mean for the replicated
      rungs (gather_scatter / all_reduce / fused); replaces ``sync_fn``.
    - :meth:`scatter_mean` — phase-1-only compressed reduce_scatter for
      ZeRO-1/FSDP: per-leaf 1/N fp32 mean slices in the flat-padded
      layout ``parallel/zero.py`` uses (chunk = ceil(size/N), so the
      slices feed ``ZeRO1.apply_scattered``/``ZeRO3.apply`` directly).

    Host-side: :meth:`init_state` builds the carried state (int8 only —
    a replicated uint32 seed counter for stochastic rounding, plus the
    per-device error-feedback residual, global shape (dp, *leaf_shape)
    sharded over dp); :meth:`state_specs` its shard_map specs.
    """

    def __init__(self, spec: str = "none", block_size: int = 256):
        if spec not in SPECS:
            raise ValueError(
                f"unknown grad_compress spec {spec!r}; available: "
                f"{list(SPECS)}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.spec = spec
        self.block_size = int(block_size)
        self.is_int8 = spec.startswith("int8")
        self.error_feedback = spec == "int8"
        # Only int8 carries state (seed counter + residual); bf16 is a
        # pure cast and 'none' a no-op.
        self.stateful = self.is_int8
        self.wire_dtype = ("s8" if self.is_int8
                           else "u16" if spec == "bf16" else None)

    def describe(self) -> dict:
        """JSON-serializable summary (bench.py's extra.grad_compress)."""
        return {"spec": self.spec, "wire_dtype": self.wire_dtype,
                "block_size": self.block_size if self.is_int8 else None,
                "error_feedback": self.error_feedback}

    # ---- carried state (host side) -------------------------------------

    def init_state(self, params_template, dp: int, seed: int = 0,
                   abstract: bool = False):
        """Fresh comp state for a dp-way mesh, or None when stateless.

        ``params_template`` supplies CANONICAL leaf shapes (under FSDP
        the compressed path differentiates w.r.t. the gathered full
        params, so residuals are canonical-shaped there too). Residual
        leaves are host numpy — the engine device_puts them P(dp).
        ``abstract=True`` returns ShapeDtypeStructs (for spec/template
        derivation without allocating dp full param copies)."""
        if not self.stateful:
            return None
        state = {"seed": (jax.ShapeDtypeStruct((), np.uint32) if abstract
                          else np.uint32(seed))}
        if self.error_feedback:
            if abstract:
                mk = lambda t: jax.ShapeDtypeStruct(  # noqa: E731
                    (dp,) + tuple(t.shape), np.float32)
            else:
                mk = lambda t: np.zeros(  # noqa: E731
                    (dp,) + tuple(t.shape), np.float32)
            state["residual"] = jax.tree.map(mk, params_template)
        return state

    def state_specs(self, comp_state):
        """shard_map spec tree for :meth:`init_state`'s output: the seed
        counter replicated, residual leaves sharded over dp's leading
        axis (each device carries ITS OWN error)."""
        from jax.sharding import PartitionSpec as P

        from tpu_ddp.parallel.mesh import DATA_AXIS
        if comp_state is None:
            return None
        specs = {"seed": P()}
        if "residual" in comp_state:
            specs["residual"] = jax.tree.map(lambda _: P(DATA_AXIS),
                                             comp_state["residual"])
        return specs

    # ---- quantization kernel -------------------------------------------

    def _quant(self, x, key):
        """Blockwise int8 over the LAST axis (must be % block_size):
        per-block scale = max|x|/127, stochastic rounding via
        floor(x/scale + u), u ~ U[0,1) — unbiased per element."""
        b = self.block_size
        blk = x.reshape(x.shape[:-1] + (-1, b))
        amax = jnp.max(jnp.abs(blk), axis=-1)
        scale = jnp.maximum(amax / 127.0, jnp.float32(1e-30))
        u = jax.random.uniform(key, blk.shape, jnp.float32)
        q = jnp.clip(jnp.floor(blk / scale[..., None] + u), -127, 127)
        return q.astype(jnp.int8).reshape(x.shape), scale

    def _dequant(self, q, scale):
        b = self.block_size
        blk = q.astype(jnp.float32).reshape(q.shape[:-1] + (-1, b))
        return (blk * scale[..., None]).reshape(q.shape)

    # ---- bf16 wire (stateless) -----------------------------------------

    @staticmethod
    def _to_wire_bf16(x):
        """f32 -> bf16, bitcast u16 so backend float-normalization can
        never widen the collective back to f32 (module docstring)."""
        return lax.bitcast_convert_type(x.astype(jnp.bfloat16), jnp.uint16)

    @staticmethod
    def _from_wire_bf16(w):
        return lax.bitcast_convert_type(w, jnp.bfloat16).astype(jnp.float32)

    # ---- layout helpers ------------------------------------------------

    def _pad_to(self, flat, total):
        return jnp.pad(flat, (0, total - flat.shape[0]))

    def _qchunk(self, chunk: int) -> int:
        """Chunk rounded up to a whole number of quant blocks (the extra
        tail is quantization-internal padding, sliced off after)."""
        b = self.block_size
        return -(-chunk // b) * b

    # ---- the two-phase compressed mean ---------------------------------

    def _bf16_two_phase(self, flat, chunk, axis_name, n):
        """(n*chunk,) f32 -> exact-dp-mean-of-bf16-payloads, re-broadcast
        at bf16. Movement collectives only; fp32 accumulation."""
        rows = self._to_wire_bf16(flat.reshape(n, chunk))
        rows = lax.all_to_all(rows, axis_name, split_axis=0,
                              concat_axis=0, tiled=True)
        m = jnp.mean(self._from_wire_bf16(rows), axis=0)      # (chunk,)
        full = lax.all_gather(self._to_wire_bf16(m), axis_name,
                              tiled=True)                     # (n*chunk,)
        return self._from_wire_bf16(full)

    def _int8_phase1(self, flat, chunk, axis_name, n, key):
        """Quantized all_to_all reduce: (n*chunk,) f32 ->
        (my chunk's fp32 mean (chunk,), my phase-1 error (n*chunk,))."""
        qchunk = self._qchunk(chunk)
        rows = flat.reshape(n, chunk)
        rows_q = jnp.pad(rows, ((0, 0), (0, qchunk - chunk)))
        q1, s1 = self._quant(rows_q, key)
        deq_own = self._dequant(q1, s1)[:, :chunk]
        err = (rows - deq_own).reshape(-1)
        q1t = lax.all_to_all(q1, axis_name, split_axis=0,
                             concat_axis=0, tiled=True)
        s1t = lax.all_to_all(s1, axis_name, split_axis=0,
                             concat_axis=0, tiled=True)
        m = jnp.mean(self._dequant(q1t, s1t)[:, :chunk], axis=0)
        return m, err

    def _int8_two_phase(self, flat, chunk, axis_name, n, key):
        """Full compressed all-reduce: phase-1 reduce + re-quantized
        all_gather broadcast. Returns (mean (n*chunk,), err (n*chunk,))
        with the phase-2 error owner-attributed at N x into this
        device's chunk (module docstring algebra)."""
        k1, k2 = jax.random.split(key)
        m, err = self._int8_phase1(flat, chunk, axis_name, n, k1)
        qchunk = self._qchunk(chunk)
        q2, s2 = self._quant(self._pad_to(m, qchunk), k2)
        full_q = lax.all_gather(q2, axis_name, tiled=False)   # (n, qchunk)
        full_s = lax.all_gather(s2, axis_name, tiled=False)
        out = self._dequant(full_q, full_s)[:, :chunk].reshape(-1)
        e2 = m - self._dequant(q2, s2)[:chunk]
        idx = lax.axis_index(axis_name)
        own = lax.dynamic_slice(err, (idx * chunk,), (chunk,))
        err = lax.dynamic_update_slice(err, own + n * e2, (idx * chunk,))
        return out, err

    def _int8_gather_all(self, flat, axis_name, n, key):
        """gather_scatter wire shape: every device quantizes its FULL
        payload and all_gathers it; each replica dequantizes and means
        locally (identical values everywhere, so the reference's
        root-selects-the-mean step is a no-op and elided). Returns
        (mean (L,), err (L,))."""
        total = flat.shape[0]
        qtotal = self._qchunk(total)
        q, s = self._quant(self._pad_to(flat, qtotal), key)
        err = flat - self._dequant(q, s)[:total]
        qg = lax.all_gather(q, axis_name, tiled=False)        # (n, qtotal)
        sg = lax.all_gather(s, axis_name, tiled=False)
        m = jnp.mean(self._dequant(qg, sg)[:, :total], axis=0)
        return m, err

    # ---- per-step PRNG -------------------------------------------------

    def _device_key(self, comp, axis_name):
        """Per-(step, device) base key; per-leaf keys fold the leaf
        index in. Each device quantizes only its OWN payloads, so keys
        need not agree across devices — determinism of the applied
        gradient comes from the all_gathered phase-2 bytes."""
        base = jax.random.key(comp["seed"])
        return jax.random.fold_in(base, lax.axis_index(axis_name))

    @staticmethod
    def _bump_seed(comp):
        return comp["seed"] + jnp.uint32(1)

    # ---- residual plumbing ---------------------------------------------

    @staticmethod
    def _res_leaf(comp, i, g):
        """Residual for leaf i as a g-shaped array (the shard_map block
        of the (dp, *shape) leaf is (1, *shape))."""
        return jax.tree.leaves(comp["residual"])[i].reshape(g.shape)

    # ---- public jit-side API -------------------------------------------

    def sync_replicated(self, kind, grads, comp, axis_name, n):
        """Compressed replacement for the replicated rungs' ``sync_fn``:
        (grads, comp) -> (synced fp32 grads, new comp). Call inside the
        shard_map'd step; ``kind`` picks the rung's collective shape
        (one pair per leaf for all_reduce, ONE pair for the whole
        concatenated tree for fused, a full-payload all_gather for
        gather_scatter)."""
        if kind not in REPLICATED_KINDS:
            raise ValueError(f"sync_replicated got kind {kind!r}; "
                             f"expected one of {REPLICATED_KINDS}")
        if self.spec == "none":
            raise ValueError("sync_replicated on a 'none' compressor; "
                             "use the rung's sync_fn")
        leaves, treedef = jax.tree.flatten(grads)
        if self.spec == "bf16":
            out = [self._bf16_leaf(kind, g, axis_name, n) for g in leaves]
            return treedef.unflatten(out), None
        return self._int8_replicated(kind, leaves, treedef, comp,
                                     axis_name, n)

    def _bf16_leaf(self, kind, g, axis_name, n):
        size = g.size
        flat = g.astype(jnp.float32).reshape(-1)
        if kind == "gather_scatter":
            stacked = lax.all_gather(self._to_wire_bf16(flat), axis_name,
                                     tiled=False)             # (n, size)
            return jnp.mean(self._from_wire_bf16(stacked),
                            axis=0).reshape(g.shape)
        chunk = -(-size // n)
        out = self._bf16_two_phase(self._pad_to(flat, n * chunk), chunk,
                                   axis_name, n)
        return out[:size].reshape(g.shape)

    def _int8_replicated(self, kind, leaves, treedef, comp, axis_name, n):
        key = self._device_key(comp, axis_name)
        new_comp = dict(comp)
        new_comp["seed"] = self._bump_seed(comp)

        def acc_for(i, g):
            flat = g.astype(jnp.float32).reshape(-1)
            if self.error_feedback:
                flat = flat + self._res_leaf(comp, i, g).reshape(-1)
            return flat

        if kind == "fused":
            # ONE collective pair for the whole tree: concatenate the
            # accumulated leaves, run the two-phase mean once, split.
            sizes = [g.size for g in leaves]
            flat = jnp.concatenate([acc_for(i, g)
                                    for i, g in enumerate(leaves)])
            total = int(sum(sizes))
            chunk = -(-total // n)
            m, err = self._int8_two_phase(
                self._pad_to(flat, n * chunk), chunk, axis_name, n,
                jax.random.fold_in(key, 0))
            outs, errs, off = [], [], 0
            for g, size in zip(leaves, sizes):
                outs.append(m[off:off + size].reshape(g.shape))
                errs.append(err[off:off + size])
                off += size
        else:
            outs, errs = [], []
            for i, g in enumerate(leaves):
                size = g.size
                flat = acc_for(i, g)
                leaf_key = jax.random.fold_in(key, i)
                if kind == "gather_scatter":
                    m, err = self._int8_gather_all(flat, axis_name, n,
                                                   leaf_key)
                else:  # all_reduce: one pair per leaf
                    chunk = -(-size // n)
                    m, err = self._int8_two_phase(
                        self._pad_to(flat, n * chunk), chunk, axis_name,
                        n, leaf_key)
                outs.append(m[:size].reshape(g.shape))
                errs.append(err[:size])
        if self.error_feedback:
            res_leaves = jax.tree.leaves(comp["residual"])
            new_comp["residual"] = jax.tree.unflatten(
                jax.tree.structure(comp["residual"]),
                [e[:r.size].reshape(r.shape)
                 for e, r in zip(errs, res_leaves)])
        return treedef.unflatten(outs), new_comp

    def scatter_mean(self, grads, comp, axis_name, n):
        """Compressed reduce_scatter for the ZeRO-1/FSDP rungs: (grads,
        comp) -> (per-leaf (chunk,) fp32 MEAN slices, new comp) with
        chunk = ceil(size/N) — the exact flat-padded layout
        ``ZeRO1.apply_scattered`` and ``ZeRO3.apply`` consume. Phase 1
        only: the result stays scattered (the rung's parameter
        all_gather is its own second half and stays fp32 — parameters,
        not gradients, are out of this layer's scope)."""
        leaves, treedef = jax.tree.flatten(grads)
        if self.spec == "bf16":
            def leaf(g):
                size = g.size
                chunk = -(-size // n)
                flat = self._pad_to(g.astype(jnp.float32).reshape(-1),
                                    n * chunk)
                rows = lax.all_to_all(
                    self._to_wire_bf16(flat.reshape(n, chunk)), axis_name,
                    split_axis=0, concat_axis=0, tiled=True)
                return jnp.mean(self._from_wire_bf16(rows), axis=0)
            return treedef.unflatten([leaf(g) for g in leaves]), None
        key = self._device_key(comp, axis_name)
        new_comp = dict(comp)
        new_comp["seed"] = self._bump_seed(comp)
        outs, errs = [], []
        for i, g in enumerate(leaves):
            size = g.size
            chunk = -(-size // n)
            flat = g.astype(jnp.float32).reshape(-1)
            if self.error_feedback:
                flat = flat + self._res_leaf(comp, i, g).reshape(-1)
            m, err = self._int8_phase1(
                self._pad_to(flat, n * chunk), chunk, axis_name, n,
                jax.random.fold_in(key, i))
            outs.append(m)
            errs.append(err[:size])
        if self.error_feedback:
            res_leaves = jax.tree.leaves(comp["residual"])
            new_comp["residual"] = jax.tree.unflatten(
                jax.tree.structure(comp["residual"]),
                [e.reshape(r.shape) for e, r in zip(errs, res_leaves)])
        return treedef.unflatten(outs), new_comp


# ---------------------------------------------------------------------------
# Point-to-point edge codec (round 10, MPMD pipeline).
#
# The collectives above compress an ALL-REDUCE; an MPMD pipeline edge is
# a point-to-point handoff of one activation (down) or cotangent (up)
# tensor per tick. Same wire formats, different shape: no phases, no
# all_to_all — just encode on the sending stage, ship the reduced
# payload over DCN, decode on the receiver. Error feedback carries PER
# EDGE on the sender: each tick's quantization error is added to the
# next payload on the same edge, so the bias telescopes along the
# training trajectory exactly as it does for gradients (the edge sees
# the same microbatch slot every M ticks, and the loss is what
# accumulates the bias — tests/test_mpmd.py pins the trajectory).
# ---------------------------------------------------------------------------


class EdgeCodec:
    """Wire codec for ONE directed MPMD edge.

    Stateful on the sender side (int8 stochastic-rounding seed counter
    + optional error-feedback residual); the receiver only needs
    :meth:`decode`, which is stateless. The MPMD scheduler is a host
    loop, so host-held mutable state is the natural form here — unlike
    the jit-carried ``comp_state`` of the collective compressor.

    ``encode`` returns ``(wire, nbytes)`` where ``wire`` is a dict of
    arrays that actually travel and ``nbytes`` counts their payload
    bytes (the honest numerator for the compression-ratio acceptance
    numbers; fp32 would be ``4 * x.size``).
    """

    def __init__(self, spec: str = "none", block_size: int = 256,
                 seed: int = 0):
        if spec not in EDGE_SPECS:
            raise ValueError(
                f"unknown edge codec spec {spec!r}; available: "
                f"{list(EDGE_SPECS)}")
        self.spec = spec
        self.is_int8 = spec.startswith("int8")
        self.error_feedback = spec == "int8"
        # Kernel host: borrows _quant/_dequant (and block_size
        # validation) from the collective compressor.
        self._k = GradCompressor("int8" if self.is_int8 else "none",
                                 block_size=block_size)
        self.block_size = self._k.block_size
        self._seed = np.uint32(seed)
        self._residual = None   # lazily sized to the edge payload
        self.bytes_sent = 0     # cumulative wire bytes (stats surface)
        self.bytes_dense = 0    # what fp32 would have cost

    def describe(self) -> dict:
        return {"spec": self.spec,
                "block_size": self.block_size if self.is_int8 else None,
                "error_feedback": self.error_feedback}

    @property
    def ratio(self) -> float:
        """Achieved dense/wire byte ratio so far (1.0 before traffic)."""
        return (self.bytes_dense / self.bytes_sent
                if self.bytes_sent else 1.0)

    def reset(self) -> None:
        """Drop carried state (elastic restart: a new edge peer must
        not inherit a residual accumulated against the old one)."""
        self._residual = None
        self.bytes_sent = 0
        self.bytes_dense = 0

    # ---- sender --------------------------------------------------------

    def encode(self, x) -> tuple[dict, int]:
        x = jnp.asarray(x, jnp.float32)
        self.bytes_dense += 4 * x.size
        if self.spec == "none":
            wire = {"kind": "none", "payload": x}
            nbytes = 4 * x.size
        elif self.spec == "bf16":
            wire = {"kind": "bf16",
                    "payload": GradCompressor._to_wire_bf16(x)}
            nbytes = 2 * x.size
        elif self.spec == "sparse":
            wire, nbytes = self._encode_sparse(x)
        else:
            wire, nbytes = self._encode_int8(x)
        self.bytes_sent += nbytes
        return wire, nbytes

    def _encode_sparse(self, x) -> tuple[dict, int]:
        """Lossless zero-chunk elision: chunk the flat fp32 payload at
        ``block_size``, packbits which chunks hold any nonzero, ship
        only those. A host-side codec (the sparsity pattern sizes the
        payload — exactly what a compiled collective cannot do), which
        is where EdgeCodec already lives. Worst case (nothing zero)
        costs the dense bytes + the ~size/8B bitmap; best case (an MoE
        delta touching few experts) drops whole untouched expert rows.
        """
        b = self.block_size
        flat = np.asarray(x, np.float32).reshape(-1)
        n = max(1, -(-flat.size // b))
        padded = np.zeros((n * b,), np.float32)
        padded[:flat.size] = flat
        rows = padded.reshape(n, b)
        nz = np.any(rows != 0.0, axis=1)                    # (n,) bool
        wire = {"kind": "sparse", "payload": jnp.asarray(rows[nz]),
                "mask": np.packbits(nz), "chunks": n, "chunk": b,
                "shape": tuple(np.shape(x))}
        return wire, 4 * int(nz.sum()) * b + int(np.packbits(nz).size)

    def _encode_int8(self, x) -> tuple[dict, int]:
        flat = x.reshape(-1)
        if self.error_feedback:
            if (self._residual is None
                    or self._residual.shape != flat.shape):
                self._residual = jnp.zeros_like(flat)
            flat = flat + self._residual
        qtotal = self._k._qchunk(flat.shape[0])
        key = jax.random.key(self._seed)
        self._seed = np.uint32(self._seed + np.uint32(1))
        q, scale = self._k._quant(self._k._pad_to(flat, qtotal), key)
        if self.error_feedback:
            deq = self._k._dequant(q, scale)[:flat.shape[0]]
            self._residual = flat - deq
        wire = {"kind": "int8", "q": q, "scale": scale,
                "shape": tuple(x.shape)}
        return wire, q.size + 4 * scale.size

    # ---- receiver (stateless) ------------------------------------------

    @staticmethod
    def decode(wire: dict):
        kind = wire["kind"]
        if kind == "none":
            return wire["payload"]
        if kind == "bf16":
            return GradCompressor._from_wire_bf16(wire["payload"])
        if kind == "int8":
            shape = wire["shape"]
            size = int(np.prod(shape)) if shape else 1
            k = GradCompressor("int8",
                               block_size=wire["q"].size
                               // wire["scale"].size)
            flat = k._dequant(wire["q"], wire["scale"])[:size]
            return flat.reshape(shape)
        if kind == "sparse":
            n, b = int(wire["chunks"]), int(wire["chunk"])
            nz = np.unpackbits(np.asarray(wire["mask"]),
                               count=n).astype(bool)
            rows = np.zeros((n, b), np.float32)
            if nz.any():
                rows[nz] = np.asarray(wire["payload"],
                                      np.float32).reshape(-1, b)
            shape = wire["shape"]
            size = int(np.prod(shape)) if shape else 1
            return jnp.asarray(rows.reshape(-1)[:size].reshape(shape))
        raise ValueError(f"unknown edge wire kind {kind!r}")


# ---------------------------------------------------------------------------
# Cold-page codec (tpu_ddp/serve/kv_pool.py tiered KV, DESIGN.md §27).
# The SAME per-block int8 scheme as GradCompressor._quant — scale =
# max|x|/127 clamped away from zero — but with DETERMINISTIC
# round-to-nearest instead of stochastic rounding: a KV page demoted
# and promoted twice must dequantize identically both times (replay /
# migration parity is position-keyed, never RNG-keyed), and there is
# no error-feedback loop to absorb rounding bias here. The scale is
# per (layer, page, token-row) — one row's outlier cannot flatten its
# neighbours' resolution — matching the disagg KV wire's granularity
# choice (fleet/disagg.py zero-masks garbage tails for the same
# reason).
# ---------------------------------------------------------------------------


def page_quantize(x, cold_dtype):
    """Quantize KV pages ``x`` (..., bs, KV, hd) for cold storage.

    Returns ``(q, scale)`` with scale shaped like ``x`` minus the two
    trailing (KV, hd) axes. ``cold_dtype`` jnp.int8 -> per-row symmetric
    int8; jnp.bfloat16 -> a plain downcast with unit scales (lossless
    when the hot dtype is already bf16 — the parity-testing tier)."""
    if cold_dtype == jnp.bfloat16:
        return (x.astype(jnp.bfloat16),
                jnp.ones(x.shape[:-2], jnp.float32))
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=(-2, -1))
    scale = jnp.maximum(amax / 127.0, jnp.float32(1e-30))
    q = jnp.clip(jnp.round(xf / scale[..., None, None]), -127, 127)
    return q.astype(jnp.int8), scale


def page_dequantize(q, scale, out_dtype):
    """Inverse of :func:`page_quantize`: (..., bs, KV, hd) pages back
    in ``out_dtype`` (the pool's hot dtype)."""
    return (q.astype(jnp.float32)
            * scale[..., None, None]).astype(out_dtype)
