"""Distributed runtime: bootstrap, device mesh, gradient-sync strategies.

Replaces the reference's L5/L1/L0 stack (SURVEY.md §1): gloo process group +
manual collectives + torch DDP become ``jax.distributed`` rendezvous + XLA
collectives (``psum``/``all_gather``) over the device mesh (ICI/DCN).
"""

from tpu_ddp.parallel.bootstrap import (  # noqa: F401
    DistributedContext,
    get_rank_from_hostname,
    init_distributed_setup,
    shutdown,
    test_distributed_setup,
)
from tpu_ddp.parallel.mesh import make_mesh, data_parallel_specs  # noqa: F401
from tpu_ddp.parallel.ring_attention import attend, ring_attention  # noqa: F401
from tpu_ddp.parallel.ulysses import ulysses_attention  # noqa: F401
from tpu_ddp.parallel.sync import (  # noqa: F401
    SYNC_STRATEGIES,
    get_sync_strategy,
    sync_none,
    sync_gather_scatter,
    sync_all_reduce,
    sync_fused,
)
