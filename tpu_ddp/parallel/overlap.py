"""Overlapped bucketized gradient collectives + cross-replica sharded update.

The reference's part-3 rung is torch DDP's C++ reducer: parameters are
partitioned into ~25 MB buckets in REVERSE registration order, and each
bucket's all-reduce is launched by an autograd hook the moment the last
gradient of the bucket is produced — so communication rides under the
remaining backward compute instead of after it (reference
part3/main.py:174, ``DDP(model, bucket_cap_mb=25)``). The fused rung's
tree-level ``pmean`` (parallel/sync.py) leaves that scheduling freedom
implicit in XLA's dataflow; THIS module reproduces the trick explicitly:

- :class:`BucketPlan` partitions the parameter/gradient pytree into
  size-targeted buckets over the REVERSED flatten order (the JAX
  analogue of reversed ``model.parameters()`` — output-side leaves get
  their cotangents first, so their bucket's collective can launch while
  earlier layers are still differentiating).
- :class:`OverlapSync` plants one ``jax.custom_vjp`` identity "tap" per
  bucket on the parameter leaves before ``model.apply`` — the JAX
  analogue of DDP's autograd hooks. AD invokes each tap's backward rule
  exactly when that bucket's cotangents are ready, and the rule ISSUES
  the bucket's collective right there, inside the backward dataflow. A
  scalar carrier threads tap-to-tap through ``optimization_barrier``
  ties, so bucket k+1's payload depends on bucket k's collective result:
  buckets issue in reverse-autodiff order and XLA's collective combiner
  cannot re-merge them (the barrier is honored through scheduling on
  backends with a latency-hiding scheduler; backends that strip it —
  XLA:CPU — still see the deterministic jaxpr issue order via channel
  ids). ``utils/hlo_comm.overlap_report`` checks the resulting dataflow:
  every non-final bucket's collective has backward compute OUTSIDE its
  ancestor cone, i.e. work available to overlap with.
- On the plain (all_reduce) and fused rungs the bucket collective is a
  ``psum_scatter``, and :class:`ShardedUpdate` finishes the job in the
  style of arxiv 2004.13336: each replica applies the optimizer to only
  its 1/N payload shard and ``all_gather``\\ s fresh parameters — the
  optimizer's FLOPs and the gradient wire bytes stop being replicated
  work even on the data-parallel rungs (state memory stays ZeRO-1-shaped:
  the momentum payload is dp-sharded). The gather_scatter rung keeps its
  root-mean semantics (all_gather + root-selected psum per bucket) and a
  replicated update — there is no scattered reduction to build on.

Compression composes (parallel/compress.py): the bucket payload travels
the same bf16/u16 or int8/s8 wire formats, per bucket instead of per
leaf or per tree. The int8 error-feedback residual poses the one
structural puzzle: a ``custom_vjp`` backward rule can only return
cotangents for its primal inputs — there is no side channel for carried
state. The residual therefore rides the EXTENDED-DIFFERENTIATION trick:
each tap takes an ``aux`` primal (this bucket's residual slices, the
f32-encoded stochastic-rounding seed, a zero "flag" scalar), and its
backward returns the NEW residual — and a nonfinite count of the raw
gradients, for the step guard, since a NaN can vanish through the int8
cast — AS THE COTANGENT OF ``aux``. ``jax.vjp`` w.r.t. (params, carrier,
aux) then delivers gradients and the updated compression carry in one
pass, with the carry layout identical to the unbucketed compressor's
(``TrainState.comp_state`` checkpoints, restores and rolls back on a
guard skip unchanged).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

# Rungs the overlapped backward can serve; all_reduce and fused take the
# scattered-reduction + sharded-update path, gather_scatter keeps its
# root-mean semantics (parallel/sync.py parity table).
OVERLAP_KINDS = ("gather_scatter", "all_reduce", "fused")
SCATTER_KINDS = ("all_reduce", "fused")


@dataclasses.dataclass(frozen=True)
class _LeafMeta:
    shape: tuple
    size: int
    dtype: Any


class BucketPlan:
    """Size-targeted partition of a pytree in reverse-autodiff order.

    Buckets are consecutive runs of the REVERSED ``jax.tree.flatten``
    leaf order (torch DDP buckets reversed ``model.parameters()`` the
    same way), greedily filled to ``bucket_mb`` MiB of fp32 payload; a
    single leaf larger than the target gets its own bucket. Bucket 0
    therefore holds the output-side leaves whose cotangents the
    backward produces FIRST.
    """

    def __init__(self, template, bucket_mb: int | float):
        if bucket_mb <= 0:
            raise ValueError(f"bucket_mb must be > 0, got {bucket_mb}")
        leaves, self.treedef = jax.tree.flatten(template)
        if not leaves:
            raise ValueError("cannot bucket an empty pytree")
        self.bucket_mb = bucket_mb
        self.metas = tuple(
            _LeafMeta(tuple(x.shape), int(np.prod(x.shape, dtype=np.int64))
                      if x.shape else 1, x.dtype)
            for x in leaves)
        target = int(bucket_mb * (1 << 20))
        buckets: list[tuple[int, ...]] = []
        cur: list[int] = []
        cur_bytes = 0
        for i in reversed(range(len(leaves))):
            nbytes = self.metas[i].size * 4       # fp32 wire bytes
            if cur and cur_bytes + nbytes > target:
                buckets.append(tuple(cur))
                cur, cur_bytes = [], 0
            cur.append(i)
            cur_bytes += nbytes
        if cur:
            buckets.append(tuple(cur))
        self.buckets: tuple[tuple[int, ...], ...] = tuple(buckets)

    @property
    def n_buckets(self) -> int:
        return len(self.buckets)

    def bucket_sizes(self) -> list[int]:
        """Payload element count per bucket."""
        return [sum(self.metas[i].size for i in b) for b in self.buckets]

    def partition(self, tree) -> list[tuple]:
        """Leaves grouped per bucket (reverse-autodiff order within and
        across buckets). Together with :meth:`combine` a round trip:
        every leaf lands in exactly one bucket."""
        leaves = jax.tree.leaves(tree)
        if len(leaves) != len(self.metas):
            raise ValueError(
                f"tree has {len(leaves)} leaves; plan was built over "
                f"{len(self.metas)}")
        return [tuple(leaves[i] for i in b) for b in self.buckets]

    def combine(self, bucket_leaves) -> Any:
        """Inverse of :meth:`partition`: bucket groups -> original tree."""
        out: list = [None] * len(self.metas)
        for b_idx, idxs in enumerate(self.buckets):
            group = bucket_leaves[b_idx]
            if len(group) != len(idxs):
                raise ValueError(
                    f"bucket {b_idx} expects {len(idxs)} leaves, got "
                    f"{len(group)}")
            for j, i in enumerate(idxs):
                out[i] = group[j]
        return jax.tree.unflatten(self.treedef, out)

    def describe(self) -> dict:
        """JSON-serializable summary (bench.py's extra.overlap)."""
        sizes = self.bucket_sizes()
        return {"bucket_mb": self.bucket_mb,
                "n_buckets": self.n_buckets,
                "n_leaves": len(self.metas),
                "bucket_bytes": [s * 4 for s in sizes],
                "bucket_leaf_counts": [len(b) for b in self.buckets]}

    def fingerprint(self) -> tuple:
        """Hashable layout identity: leaf shapes/dtypes + the bucket
        cuts. Two plans with equal fingerprints partition equal-layout
        trees identically — the weight-streaming publisher compares
        fingerprints to detect a layout change (→ full-tensor push)
        and subscribers reject updates built against a foreign layout
        (tpu_ddp/publish/)."""
        return (tuple((m.shape, str(np.dtype(m.dtype)))
                      for m in self.metas),
                self.buckets)


class OverlapSync:
    """Bucketed in-backward gradient sync for one replicated rung.

    Jit-side entry point (call INSIDE the shard_map'd step):
    :meth:`value_and_grad` — replaces the engine's
    ``value_and_grad(loss_fn) + sync_fn`` pair. Collectives are issued
    from the taps' backward rules, per bucket, in reverse-autodiff
    order; the returned gradients are

    - full root-mean leaves on the ``gather_scatter`` rung;
    - SCATTER-EMBEDDED leaves on the ``all_reduce``/``fused`` rungs:
      each device's 1/N payload chunk of the mean, placed at its offset
      in otherwise-zero full-shape leaves — exactly what
      :meth:`ShardedUpdate.apply_scattered` re-slices (the embed/slice
      pair folds away in XLA; across devices the chunks tile the full
      mean exactly once, so a psum of the squared leaves is the global
      norm and a NaN anywhere is caught by the guard's psum).
    """

    def __init__(self, plan: BucketPlan, kind: str, axis_name: str,
                 axis_size: int, compressor=None):
        if kind not in OVERLAP_KINDS:
            raise ValueError(
                f"overlap got kind {kind!r}; expected one of "
                f"{OVERLAP_KINDS}")
        self.plan = plan
        self.kind = kind
        self.axis_name = axis_name
        self.axis_size = int(axis_size)
        self.scatter = kind in SCATTER_KINDS
        if compressor is not None and compressor.spec == "none":
            compressor = None
        self.compressor = compressor
        self._spec = compressor.spec if compressor is not None else "none"
        self._stateful = (compressor is not None and compressor.stateful)
        self._ef = (compressor is not None and compressor.error_feedback)
        self._taps = [self._make_tap(k) for k in range(plan.n_buckets)]

    def describe(self) -> dict:
        return {**self.plan.describe(), "kind": self.kind,
                "sharded_update": self.scatter, "wire": self._spec}

    # ---- taps ----------------------------------------------------------

    def _make_tap(self, k: int):
        metas = [self.plan.metas[i] for i in self.plan.buckets[k]]

        @jax.custom_vjp
        def tap(leaves, carrier, aux):
            return tuple(leaves), carrier

        def fwd(leaves, carrier, aux):
            return (tuple(leaves), carrier), aux

        def bwd(aux, cot):
            g_leaves, c_bar = cot
            # Chain tie (i): this bucket's payload depends on the
            # incoming carrier cotangent — i.e. on the PREVIOUS bucket's
            # collective result — so buckets issue strictly in reverse-
            # autodiff order and cannot be combined back into one op.
            g0, c_in = lax.optimization_barrier((g_leaves[0], c_bar))
            g_leaves = (g0,) + tuple(g_leaves[1:])
            outs, aux_cot, marker = self._bucket_sync(k, g_leaves, metas,
                                                      aux)
            # Chain tie (ii): the outgoing carrier cotangent depends on
            # THIS bucket's collective result.
            c_out, _ = lax.optimization_barrier((c_in, marker))
            return tuple(outs), c_out, aux_cot

        tap.defvjp(fwd, bwd)
        return tap

    def _bucket_sync(self, k: int, g_leaves, metas, aux):
        """One bucket's collective: concatenated payload -> synced
        full-shape leaves (+ the aux cotangent: new EF residual slices,
        seed placeholder, raw-gradient nonfinite count)."""
        n, ax = self.axis_size, self.axis_name
        sizes = [m.size for m in metas]
        total = sum(sizes)
        chunk = -(-total // n)
        flat = jnp.concatenate(
            [g.astype(jnp.float32).reshape(-1) for g in g_leaves])
        aux_cot: dict = {}
        err = None
        comp = self.compressor
        if self._stateful:
            # The guard flag must come from the RAW local grads — a NaN
            # can vanish through the int8 cast (engine.py's unbucketed
            # path guards pre-compression grads for the same reason).
            aux_cot["flag"] = jnp.sum(
                ~jnp.isfinite(flat)).astype(jnp.float32)
            aux_cot["seed"] = jnp.zeros((), jnp.float32)
            if self._ef:
                flat = flat + jnp.concatenate(
                    [r.reshape(-1) for r in aux["res"]])
            key = jax.random.key(aux["seed"].astype(jnp.uint32))
            key = jax.random.fold_in(
                jax.random.fold_in(key, lax.axis_index(ax)), k)
        if self.scatter:
            pad = jnp.pad(flat, (0, n * chunk - total))
            if self._spec == "none":
                sh = lax.psum_scatter(pad.reshape(n, chunk), ax,
                                      scatter_dimension=0) / n
            elif self._spec == "bf16":
                rows = lax.all_to_all(
                    comp._to_wire_bf16(pad.reshape(n, chunk)), ax,
                    split_axis=0, concat_axis=0, tiled=True)
                sh = jnp.mean(comp._from_wire_bf16(rows), axis=0)
            else:  # int8 phase 1: the scattered mean IS the result
                sh, err = comp._int8_phase1(pad, chunk, ax, n, key)
            full = lax.dynamic_update_slice(
                jnp.zeros((n * chunk,), jnp.float32), sh,
                (lax.axis_index(ax) * chunk,))[:total]
            marker = sh[0]
        else:  # gather_scatter: the rung's root-mean, per bucket payload
            if self._spec == "none":
                stacked = lax.all_gather(flat, ax, tiled=False)
                mean = jnp.mean(stacked, axis=0)
                root = jnp.where(lax.axis_index(ax) == 0, mean,
                                 jnp.zeros_like(mean))
                full = lax.psum(root, ax)
            elif self._spec == "bf16":
                stacked = lax.all_gather(comp._to_wire_bf16(flat), ax,
                                         tiled=False)
                # Replicas mean identical bf16 stacks — the root-select
                # is a no-op and elided (compress.py `_bf16_leaf`).
                full = jnp.mean(comp._from_wire_bf16(stacked), axis=0)
            else:
                full, err = comp._int8_gather_all(flat, ax, n, key)
            marker = full[0]
        if self._ef:
            errt = err[:total]
            outs_err, off = [], 0
            for m in metas:
                outs_err.append(errt[off:off + m.size].reshape(m.shape))
                off += m.size
            aux_cot["res"] = tuple(outs_err)
        outs, off = [], 0
        for g, m in zip(g_leaves, metas):
            outs.append(full[off:off + m.size].reshape(m.shape)
                        .astype(g.dtype))
            off += m.size
        return outs, aux_cot, marker

    # ---- aux (compression carry) plumbing ------------------------------

    def _aux_in(self, comp_state):
        """Per-bucket aux primals from the carried comp state's LOCAL
        shard_map views (residual leaves (1, *shape) -> leaf-shaped)."""
        if not self._stateful:
            return tuple({} for _ in self.plan.buckets)
        seed_f = comp_state["seed"].astype(jnp.float32)
        res = (jax.tree.leaves(comp_state["residual"]) if self._ef
               else None)
        aux = []
        for idxs in self.plan.buckets:
            a = {"seed": seed_f, "flag": jnp.zeros((), jnp.float32)}
            if res is not None:
                a["res"] = tuple(
                    res[i].reshape(self.plan.metas[i].shape)
                    for i in idxs)
            aux.append(a)
        return tuple(aux)

    def _collect_aux(self, comp_state, g_aux):
        """(new comp state, extra guard flag) from the aux cotangents."""
        if not self._stateful:
            return None, None
        new_comp = {"seed": comp_state["seed"] + jnp.uint32(1)}
        if self._ef:
            old = jax.tree.leaves(comp_state["residual"])
            new_leaves: list = [None] * len(self.plan.metas)
            for k, idxs in enumerate(self.plan.buckets):
                for j, i in enumerate(idxs):
                    new_leaves[i] = g_aux[k]["res"][j].reshape(
                        old[i].shape)
            new_comp["residual"] = jax.tree.unflatten(
                jax.tree.structure(comp_state["residual"]), new_leaves)
        extra_bad = sum(g_aux[k]["flag"]
                        for k in range(self.plan.n_buckets))
        return new_comp, extra_bad

    # ---- public jit-side API -------------------------------------------

    def _apply_taps(self, params, carrier, aux):
        leaves, structure = jax.tree.flatten(params)
        out = list(leaves)
        # Forward chain order tap_{B-1} -> ... -> tap_0 makes tap_0's
        # backward rule run FIRST — bucket 0 (output-side leaves) issues
        # its collective while earlier layers still differentiate.
        for k in reversed(range(self.plan.n_buckets)):
            group = tuple(out[i] for i in self.plan.buckets[k])
            new_group, carrier = self._taps[k](group, carrier, aux[k])
            for j, i in enumerate(self.plan.buckets[k]):
                out[i] = new_group[j]
        return jax.tree.unflatten(structure, out), carrier

    def value_and_grad(self, loss_fn, params, comp_state=None):
        """Differentiate ``loss_fn(params) -> (loss_for_grad,
        local_mean)`` with the bucketed in-backward sync. Returns
        ``(local_mean, grads, new_comp, extra_bad)`` where ``grads`` are
        synced (root-mean full leaves, or scatter-embedded leaves on the
        scattered rungs), ``new_comp`` mirrors the compressor's carry
        layout (None when stateless) and ``extra_bad`` is the summed
        raw-gradient nonfinite count for the step guard (None unless
        int8 — fp32/bf16 NaNs survive the wire and are caught by the
        guard's norm check on the synced grads)."""
        aux = self._aux_in(comp_state)

        def wrapped(p, carrier, aux):
            p_tapped, carrier = self._apply_taps(p, carrier, aux)
            loss_for_grad, local_mean = loss_fn(p_tapped)
            # The final carrier output is deliberately unused: the taps'
            # LEAF outputs feed the loss, so AD invokes every tap's
            # backward rule regardless, and the carrier chain is wired
            # through the cotangents alone.
            del carrier
            return loss_for_grad, local_mean

        _, vjp_fn, local_mean = jax.vjp(
            wrapped, params, jnp.zeros((), jnp.float32), aux,
            has_aux=True)
        grads, _, g_aux = vjp_fn(jnp.ones((), jnp.float32))
        new_comp, extra_bad = self._collect_aux(comp_state, g_aux)
        return local_mean, grads, new_comp, extra_bad


class ShardedUpdate:
    """Cross-replica sharded weight update over bucket payloads
    (arxiv 2004.13336 §3, "optimizer state sharding" specialised to the
    plain-DDP rungs).

    Wraps an elementwise optimizer: state leaves live as dp-sharded
    flat payloads ``{"b<k>": (N * chunk_k,)}`` (one per bucket,
    ``chunk_k = ceil(bucket_size / N)``), so the optimizer FLOPs and
    state memory per device shrink by 1/N. :meth:`apply_scattered`
    consumes :class:`OverlapSync`'s scatter-embedded gradients: slice
    the parameter payload at this device's offset (the slice of the
    embed folds away in XLA), update the shard, ``all_gather`` fresh
    parameters, split back to canonical leaves.

    The inner optimizer must decay uniformly (``decay_mask() is None``
    — SGD): a rank-dependent mask cannot survive payload flattening.
    Elementwise updates commute with slicing, so the sharded update is
    BITWISE the replicated one (tests/test_overlap.py pins this on
    dp=2); the zero-padded payload tail stays zero under SGD (zero
    param, zero grad, zero momentum).

    Host-side layout converters (:meth:`canonicalize_opt_host` /
    :meth:`flatten_opt`) mirror ZeRO-1's: checkpoints always hold
    CANONICAL shapes, so they move freely across dp sizes and
    strategies.
    """

    def __init__(self, inner, plan: BucketPlan, axis_name: str,
                 axis_size: int):
        self.inner = inner
        self.plan = plan
        self.axis_name = axis_name
        self.n = int(axis_size)
        self._chunks = [-(-s // self.n) for s in plan.bucket_sizes()]
        tmpl = jax.tree.unflatten(
            plan.treedef,
            [jax.ShapeDtypeStruct(m.shape, m.dtype) for m in plan.metas])
        if inner.decay_mask(tmpl) is not None:
            raise NotImplementedError(
                "the sharded update supports uniformly-decaying "
                "optimizers only (SGD): a per-leaf decay mask cannot "
                "survive payload flattening")

    def _payload_template(self):
        return {f"b{k}": jnp.zeros((self.n * c,), jnp.float32)
                for k, c in enumerate(self._chunks)}

    def init(self, params):
        del params  # payload shapes come from the plan
        return self.inner.init(self._payload_template())

    def state_specs(self, param_specs=None):
        """Payload leaves dp-sharded; schedule scalars replicated (the
        inner optimizer's own state_specs does the mapping)."""
        del param_specs  # the payload layout fixes the spec
        return self.inner.state_specs(P(self.axis_name))

    def decay_mask(self, params):
        return None

    # ---- jit-side update (inside shard_map) ----------------------------

    def _payloads(self, leaves, k: int):
        idxs = self.plan.buckets[k]
        chunk = self._chunks[k]
        flat = jnp.concatenate(
            [leaves[i].astype(jnp.float32).reshape(-1) for i in idxs])
        return jnp.pad(flat, (0, self.n * chunk - flat.shape[0]))

    def apply_scattered(self, params, grads, opt_state, clip_norm=None):
        """One sharded update step: ``params`` full canonical leaves,
        ``grads`` scatter-embedded (OverlapSync), ``opt_state`` the
        LOCAL (chunk,) payload views. Returns (new_params, new_state).
        """
        ax, n = self.axis_name, self.n
        idx = lax.axis_index(ax)
        p_leaves = jax.tree.leaves(params)
        g_leaves = jax.tree.leaves(grads)
        p_sh, g_sh = {}, {}
        for k, chunk in enumerate(self._chunks):
            p_sh[f"b{k}"] = lax.dynamic_slice_in_dim(
                self._payloads(p_leaves, k), idx * chunk, chunk)
            g_sh[f"b{k}"] = lax.dynamic_slice_in_dim(
                self._payloads(g_leaves, k), idx * chunk, chunk)
        if clip_norm is not None:
            # The chunks tile the mean exactly once across devices:
            # psum of the slice squares IS the global norm (the same
            # argument as ZeRO1.apply_scattered's clip).
            from tpu_ddp.ops.optim import clip_scale_from_sq
            sq = lax.psum(sum(jnp.sum(jnp.square(g))
                              for g in g_sh.values()), ax)
            scale = clip_scale_from_sq(sq, clip_norm)
            g_sh = {key: g * scale for key, g in g_sh.items()}
        new_sh, new_state = self.inner.apply(p_sh, g_sh, opt_state)
        new_leaves = list(p_leaves)
        for k, idxs in enumerate(self.plan.buckets):
            fullp = lax.all_gather(new_sh[f"b{k}"], ax, tiled=True)
            off = 0
            for i in idxs:
                m = self.plan.metas[i]
                new_leaves[i] = (fullp[off:off + m.size]
                                 .reshape(m.shape)
                                 .astype(p_leaves[i].dtype))
                off += m.size
        return (jax.tree.unflatten(jax.tree.structure(params),
                                   new_leaves), new_state)

    # ---- host-side layout converters (checkpoint / reshard) ------------

    def canonicalize_opt_host(self, state):
        """Flat dp-padded payload state -> canonical (params-shaped)
        host numpy — what checkpoints hold."""
        def to_canon(payload_tree):
            leaves: list = [None] * len(self.plan.metas)
            for k, idxs in enumerate(self.plan.buckets):
                flat = np.asarray(payload_tree[f"b{k}"])
                off = 0
                for i in idxs:
                    m = self.plan.metas[i]
                    leaves[i] = (flat[off:off + m.size]
                                 .reshape(m.shape)
                                 .astype(np.dtype(m.dtype)))
                    off += m.size
            return jax.tree.unflatten(self.plan.treedef, leaves)
        return self.inner.map_param_like(state, to_canon)

    def flatten_opt(self, state):
        """Canonical host state -> this trainer's payload layout."""
        def to_flat(canon_tree):
            leaves = jax.tree.leaves(canon_tree)
            out = {}
            for k, idxs in enumerate(self.plan.buckets):
                chunk = self._chunks[k]
                flat = np.concatenate(
                    [np.asarray(leaves[i], np.float32).reshape(-1)
                     for i in idxs])
                out[f"b{k}"] = np.pad(
                    flat, (0, self.n * chunk - flat.size))
            return out
        return self.inner.map_param_like(state, to_flat)
