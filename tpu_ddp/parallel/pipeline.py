"""Pipeline parallelism: the layer stack sharded into stages over ``pp``.

No reference counterpart (the reference implements data parallelism only —
SURVEY.md §2 "Absent parallelism strategies"); included because multi-axis
model sharding is first-class in this framework. The schedule is GPipe-
style microbatching (Huang et al., arXiv:1811.06965 — reimplemented from
the paper's schedule, not from any code) expressed the SPMD way, as a
collective-permute ring pipeline:

- block parameters are STACKED on a leading layer axis and sharded over
  the ``pp`` mesh axis — each stage holds ``num_layers / pp`` layers and
  scans over them locally (``lax.scan`` keeps one compiled block body);
- the local batch is split into M microbatches; the pipeline runs
  ``T = M + pp - 1`` ticks. Every tick each stage applies its layer slice
  to its resident activation, then ``lax.ppermute`` rotates activations
  one hop along the ring (stage i -> i+1) — XLA overlaps the ICI hop with
  the next tick's compute, exactly like ring attention's K/V rotation;
- stage 0 injects embedded microbatch t at tick t; the LAST stage's
  output at tick t is microbatch ``t - (pp-1)``'s final activation. The
  first ``pp - 1`` ticks per direction are the pipeline bubble — its
  relative cost shrinks as M grows (bubble fraction = (pp-1)/(M+pp-1));
- embeddings and the LM head run OUTSIDE the tick loop, once per device
  over the full local batch (their per-device cost equals the dense
  model's; only the result computed on stage 0 / the last stage is real,
  selected by masks that zero the garbage — and, in the backward pass,
  zero the garbage's gradients).

Gradient flow needs no custom rules: ``ppermute`` transposes to the
inverse rotation (the backward pipeline runs the ring in reverse), and
the ``where``-masks confine embed/head gradients to the stages that
actually used them — the trainer then ``psum``s those replicated leaves
over ``pp`` (each stage contributes its share, zeros elsewhere) while
stacked block leaves stay stage-local. Composes with tensor parallelism
(block weights additionally sharded over ``mp`` inside each stage) and
data parallelism; exactness vs the dense model is tested in
tests/test_pipeline.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from tpu_ddp.parallel.mesh import PIPE_AXIS


def _is_spec(x):
    return isinstance(x, P)


def stack_block_params(params: dict) -> dict:
    """Per-layer blocks tuple -> one tree with a leading layer axis.

    ``stacked[k][j] == params["blocks"][j][k]`` — layer order is the
    stacking order, so specs/values round-trip with
    :func:`unstack_block_params`.
    """
    blocks = params["blocks"]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)
    out = dict(params)
    out["blocks"] = stacked
    return out


def unstack_block_params(params: dict, num_layers: int) -> dict:
    """Inverse of :func:`stack_block_params` (host-side, for tests/ckpt)."""
    stacked = params["blocks"]
    blocks = tuple(
        jax.tree.map(lambda x: x[j], stacked) for j in range(num_layers))
    out = dict(params)
    out["blocks"] = blocks
    return out


def pipeline_param_specs(model) -> dict:
    """Specs for the STACKED tree: block leaves gain a leading ``pp``
    axis on top of the model's own (tp) layout; embed/head/ln_f stay
    replicated (their grads are pp-psum'd by the trainer)."""
    base = model.param_specs()
    blk = jax.tree.map(lambda s: P(PIPE_AXIS, *tuple(s)),
                       base["blocks"][0], is_leaf=_is_spec)
    return {"embed": base["embed"], "ln_f": base["ln_f"],
            "head": base["head"], "blocks": blk}


def _embed_micro(model, params, micro, rng, num_micro: int):
    """(M, mb, L) token microbatches -> (M, mb, L, dm) embedded, with the
    dense model's embedding dropout applied PER MICROBATCH: key =
    fold(fold(rng, mb_index), num_layers) — the same derivation the dense
    trunk uses (models/transformer.py:trunk_with_aux), so a given
    microbatch's mask is independent of the pipeline geometry."""
    x = params["embed"][micro].astype(model.compute_dtype)
    if rng is not None and model.dropout_rate > 0.0:
        keys = jax.vmap(
            lambda i: jax.random.fold_in(jax.random.fold_in(rng, i),
                                         model.num_layers)
        )(jnp.arange(num_micro))
        x = jax.vmap(model._dropout)(x, keys)
    return x


def _make_run_stage(model, blocks, pos, rng, pp_axis: str):
    """This stage's layer slice as one function ``(x, mb_idx) -> y``,
    scanned layer by layer. Dropout keys derive from (microbatch index,
    GLOBAL layer index) — global = stage * layers_per_stage + local — so
    every microbatch sees exactly the dense model's per-layer key
    sequence regardless of how layers shard over stages (tested:
    pp=1 == pp=2 gradients with dropout on). Under the model's remat
    policy (``remat``/deprecated ``remat_blocks``, tpu_ddp/memory/)
    each layer recomputes in the backward pass — essential under GPipe, whose
    T = M + pp - 1 ticks would otherwise stash every tick's activations.
    """
    layers_per_stage = jax.tree.leaves(blocks)[0].shape[0]
    stage_base = lax.axis_index(pp_axis) * layers_per_stage

    def run_stage(x, mb_idx):
        def body(h, sl):
            layer, local_i = sl
            r = None
            if rng is not None and model.dropout_rate > 0.0:
                r = jax.random.fold_in(jax.random.fold_in(rng, mb_idx),
                                       stage_base + local_i)
            h, _ = model.block_apply_aux(layer, h, pos, r)
            return h, None
        from tpu_ddp.memory import effective_remat, wrap_stage
        remat = effective_remat(model.remat_policy, "attn")
        if remat != "none":
            # prevent_cse=False: scan's loop structure already prevents
            # the problematic CSE, so keep XLA free to fuse.
            body = wrap_stage(body, remat, prevent_cse=False)
        h, _ = lax.scan(body, x, (blocks, jnp.arange(layers_per_stage)))
        return h

    return run_stage


def pipeline_loss(model, params, inputs, targets, *, pp_size: int,
                  num_micro: int, pp_axis: str = PIPE_AXIS, rng=None):
    """(masked_loss_sum, local_n) for this shard's (B, L) batch.

    Must run inside a shard_map over ``pp_axis`` with ``params["blocks"]``
    holding this stage's stacked layer slice. ``masked_loss_sum`` is the
    summed token NLL on the LAST stage and exactly 0.0 elsewhere (so its
    gradient is confined to real compute); psum it over ``pp_axis`` to
    read the value. ``local_n`` is the token count (same on all stages).
    ``rng`` activates dropout, keyed per (microbatch, global layer) so
    masks are pipeline-geometry-independent.
    """
    B, L = inputs.shape
    model.check_seq_len(L)
    if B % num_micro:
        raise ValueError(f"local batch {B} not divisible by "
                         f"num_micro={num_micro}")
    mb = B // num_micro
    S, M = pp_size, num_micro
    cd = model.compute_dtype
    stage = lax.axis_index(pp_axis)
    # Global positions of this shard's chunk: under sequence parallelism
    # (sp > 1) L is the LOCAL chunk length and the model offsets by the
    # sp coordinate (models/transformer.py:_positions).
    pos = model._positions(L)

    micro = inputs.reshape(M, mb, L)
    x_embed = _embed_micro(model, params, micro, rng, M)  # (M, mb, L, dm)
    run_stage = _make_run_stage(model, params["blocks"], pos, rng, pp_axis)

    perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(carry, t):
        x_prev = carry
        f = jnp.minimum(t, M - 1)
        inj = lax.dynamic_index_in_dim(x_embed, f, 0, keepdims=False)
        # Stage 0's input comes from injection, later stages' from the
        # ring; the where-mask also zeroes embed grads on stages > 0.
        x_in = jnp.where(stage == 0, inj, x_prev)
        # Microbatch resident on this stage at tick t is t - stage
        # (clipped: out-of-range ticks compute masked garbage anyway).
        x_out = run_stage(x_in, jnp.clip(t - stage, 0, M - 1))
        x_send = lax.ppermute(x_out, pp_axis, perm)
        return x_send, x_out

    x0 = jnp.zeros((mb, L, model.d_model), cd)
    _, ys = lax.scan(tick, x0, jnp.arange(M + S - 1))
    # On the last stage, tick t emitted microbatch t-(S-1): ys[S-1+m] = m.
    outs = ys[S - 1:]                                 # (M, mb, L, dm)
    x = outs.reshape(B, L, model.d_model)

    from tpu_ddp.ops.loss import softmax_cross_entropy
    logits = model.head_apply(params, x)              # (B, L, V) f32
    nll = softmax_cross_entropy(
        logits.reshape(-1, logits.shape[-1]), targets.reshape(-1))
    # Only the last stage's activations are real; the mask zeroes the
    # other stages' loss AND, transposed, their head/ln_f gradients.
    is_last = (stage == S - 1).astype(nll.dtype)
    return jnp.sum(nll) * is_last, jnp.float32(nll.size)


def pipeline_1f1b_grads(model, params, inputs, targets, *, pp_size: int,
                        num_micro: int, pp_axis: str = PIPE_AXIS,
                        rng=None, scatter_blocks=None,
                        blocks_grad_init=None):
    """One-forward-one-backward schedule (PipeDream-flush / Megatron
    1F1B; Narayanan et al., arXiv:2104.04473 — reimplemented from the
    schedule description, not from any code), hand-scheduled because AD
    of the GPipe scan pins the order to all-forwards-then-all-backwards.

    Returns ``(masked_loss_sum, local_n, grads)`` for this shard's
    (B, L) batch — same semantics as differentiating
    :func:`pipeline_loss` (block grads stage-local; embed grads real on
    stage 0, head/ln_f on the last stage, zeros elsewhere; caller scales
    by its loss normalization and psums over ``pp_axis``).

    Schedule, expressed SPMD: every tick every stage runs one forward
    micro-step AND one backward micro-step (masked outside their valid
    ranges). At tick t, stage s forwards microbatch ``f = t - s`` and
    backwards ``b = t - 2(pp-1) + s``; activations ppermute down the
    ring, cotangents ppermute up, and the last stage feeds each
    microbatch's loss cotangent into the backward stream the same tick
    its forward completes. T = M + 2(pp-1) ticks total.

    ``scatter_blocks`` (ZeRO-2 under pp, round-4 verdict item 5): a
    callable mapping a stacked-block gradient tree to its dp-scattered
    f32 slices (ZeRO1.scatter_grads). When given, each tick's block
    gradient contribution is reduce-scattered over dp IMMEDIATELY and
    the scan carry accumulates 1/dp slices — the dominant accumulator
    (the stacked block leaves) shrinks dp x, at the cost of one
    psum_scatter per tick instead of one per step (the ZeRO-2 trade,
    arXiv:1910.02054 §5). ``blocks_grad_init`` must then supply the
    slice-shaped f32 zero tree (ZeRO1.shard_zeros on the local stacked
    leaves). Embed/head/ln_f accumulate full-size either way: the embed
    gradient is built by per-tick scatter-adds into the table (a
    per-tick dp-scatter would materialize a dense (V, dm) exchange
    every tick), and head/ln_f are O(dm*V + dm) — the caller scatters
    them once, after the scan.

    Why it exists: the GPipe path's forward scan materializes one
    boundary activation per tick plus the full embedded batch — O(M)
    microbatches resident. Here a stage keeps at most ``2*pp - 1`` saved
    inputs (the ring buffer below), the backward recomputes the stage
    forward under ``jax.vjp`` from the saved input (same trade as
    ``remat_blocks``), and embeddings are computed per tick — so
    activation residency is O(pp), independent of M. Gradients are
    bit-comparable to the GPipe path (tested: tests/test_pipeline.py).
    """
    B, L = inputs.shape
    model.check_seq_len(L)
    if B % num_micro:
        raise ValueError(f"local batch {B} not divisible by "
                         f"num_micro={num_micro}")
    mb = B // num_micro
    S, M = pp_size, num_micro
    cd = model.compute_dtype
    stage = lax.axis_index(pp_axis)
    pos = model._positions(L)  # sp-aware global chunk positions
    K = 2 * S - 1  # ring-buffer slots: max fwd->bwd gap is 2(S-1) ticks

    micro = inputs.reshape(M, mb, L)
    tmicro = targets.reshape(M, mb, L)
    run_stage = _make_run_stage(model, params["blocks"], pos, rng, pp_axis)

    def embed_mb(table, mb_idx):
        """Embedding (+ the dense model's embedding dropout) for ONE
        microbatch — computed per tick, never materialized for all M."""
        toks = lax.dynamic_index_in_dim(micro, mb_idx, 0, keepdims=False)
        x = table[toks].astype(cd)
        if rng is not None and model.dropout_rate > 0.0:
            k = jax.random.fold_in(jax.random.fold_in(rng, mb_idx),
                                   model.num_layers)
            x = model._dropout(x, k)
        return x

    def head_loss(hp, y, tgt):
        """Summed token NLL of one microbatch through ln_f + head."""
        from tpu_ddp.ops.loss import softmax_cross_entropy
        logits = model.head_apply(hp, y)
        nll = softmax_cross_entropy(
            logits.reshape(-1, logits.shape[-1]), tgt.reshape(-1))
        return jnp.sum(nll)

    head_params = {"ln_f": params["ln_f"], "head": params["head"]}
    perm_down = [(i, (i + 1) % S) for i in range(S)]
    perm_up = [(i, (i - 1) % S) for i in range(S)]

    def run_stage_with(blocks, x, mb_idx):
        """run_stage over EXPLICIT blocks — the vjp target (gradients
        w.r.t. the stage's layer slice flow through this)."""
        return _make_run_stage(model, blocks, pos, rng, pp_axis)(x, mb_idx)

    def masked_add(acc, g, valid):
        return jax.tree.map(
            lambda a, gg: a + jnp.where(valid, gg, 0).astype(a.dtype),
            acc, g)

    def tick(carry, t):
        fwd_in, bwd_in, buf, g_blk, g_emb, g_head, loss_sum = carry
        f = t - stage
        b = t - 2 * (S - 1) + stage
        f_valid = (0 <= f) & (f < M)
        b_valid = (0 <= b) & (b < M)
        f_safe = jnp.clip(f, 0, M - 1)
        b_safe = jnp.clip(b, 0, M - 1)

        # ---- forward micro-step: embed-inject at stage 0, ring above.
        x_in = jnp.where(stage == 0, embed_mb(params["embed"], f_safe),
                         fwd_in)
        y = run_stage(x_in, f_safe)
        buf = jnp.where(f_valid,
                        lax.dynamic_update_index_in_dim(
                            buf, x_in, f_safe % K, 0),
                        buf)

        # ---- loss + its cotangent at the last stage (same tick: the
        # last stage's backward microbatch b equals its forward f).
        # lax.cond, not masking: under shard_map the predicate is
        # device-varying, so non-last stages (and the last stage's
        # ramp-up/drain ticks) genuinely SKIP the ln_f+head forward and
        # vjp — at real vocab sizes that B/M*L*dm*V matmul pair per tick
        # would otherwise run S*T/M times more than the GPipe path's
        # once-per-microbatch head cost (round-2 advisor finding). Safe
        # because head_loss contains no collectives.
        tgt = lax.dynamic_index_in_dim(tmicro, f_safe, 0, keepdims=False)
        at_last = stage == S - 1

        def head_fwd_bwd(y, tgt):
            nll_sum, head_vjp = jax.vjp(
                lambda hp, yy: head_loss(hp, yy, tgt), head_params, y)
            d_hp, dy_head = head_vjp(jnp.float32(1.0))
            return nll_sum, d_hp, dy_head

        def head_skip(y, tgt):
            return (jnp.float32(0.0),
                    jax.tree.map(lambda p: jnp.zeros(p.shape, p.dtype),
                                 head_params),
                    jnp.zeros_like(y))

        nll_sum, d_hp, dy_head = lax.cond(at_last & f_valid,
                                          head_fwd_bwd, head_skip, y, tgt)
        loss_sum = loss_sum + nll_sum
        g_head = jax.tree.map(lambda a, gg: a + gg.astype(a.dtype),
                              g_head, d_hp)

        # ---- backward micro-step: recompute-vjp from the saved input.
        x_saved = lax.dynamic_index_in_dim(buf, b_safe % K, 0,
                                           keepdims=False)
        d_in = jnp.where(at_last, dy_head.astype(cd), bwd_in)
        _, stage_vjp = jax.vjp(
            lambda blk, xx: run_stage_with(blk, xx, b_safe),
            params["blocks"], x_saved)
        d_blk, dx = stage_vjp(d_in)
        if scatter_blocks is None:
            g_blk = masked_add(g_blk, d_blk, b_valid)
        else:
            # ZeRO-2: mask the invalid-tick garbage BEFORE the collective
            # (every dp rank runs the psum_scatter every tick — uniform
            # participation — so masking the value, not the call, keeps
            # the schedule collective-safe), then accumulate slices.
            d_blk = jax.tree.map(
                lambda gg: jnp.where(b_valid, gg, 0), d_blk)
            g_blk = jax.tree.map(lambda a, s: a + s, g_blk,
                                 scatter_blocks(d_blk))

        # Embed grad at stage 0 (dx there is d(embed output) of mb b):
        # scatter-add straight into the carried accumulator — touches
        # only the mb*L indexed rows per tick. A jax.vjp of the gather
        # would materialize a dense (V, dm) cotangent and a full-table
        # add EVERY tick on EVERY stage, dominating the step at real
        # vocab sizes. Dropout's backward is recomputed from its key
        # (where(mask, dx/keep, 0) — the transpose of _dropout).
        toks_b = lax.dynamic_index_in_dim(micro, b_safe, 0,
                                          keepdims=False)
        dxe = dx.astype(jnp.float32)
        if rng is not None and model.dropout_rate > 0.0:
            k = jax.random.fold_in(jax.random.fold_in(rng, b_safe),
                                   model.num_layers)
            keep = 1.0 - model.dropout_rate
            mask = jax.random.bernoulli(k, keep, dx.shape)
            dxe = jnp.where(mask, dxe / keep, 0.0)
        contrib = jnp.where(b_valid & (stage == 0), dxe, 0.0)
        g_emb = g_emb.at[toks_b.reshape(-1)].add(
            contrib.reshape(-1, contrib.shape[-1]))

        return ((lax.ppermute(y, pp_axis, perm_down),
                 lax.ppermute(dx, pp_axis, perm_up),
                 buf, g_blk, g_emb, g_head, loss_sum), None)

    zeros_f32 = lambda tree: jax.tree.map(  # noqa: E731
        lambda p: jnp.zeros(p.shape, jnp.float32), tree)
    if scatter_blocks is not None and blocks_grad_init is None:
        raise ValueError("scatter_blocks needs blocks_grad_init (the "
                         "slice-shaped f32 zero tree)")
    carry0 = (
        jnp.zeros((mb, L, model.d_model), cd),       # fwd ring
        jnp.zeros((mb, L, model.d_model), cd),       # bwd ring
        jnp.zeros((K, mb, L, model.d_model), cd),    # saved inputs
        (blocks_grad_init if scatter_blocks is not None
         else zeros_f32(params["blocks"])),
        zeros_f32(params["embed"]),
        zeros_f32(head_params),
        jnp.float32(0.0),
    )
    (_, _, _, g_blk, g_emb, g_head, loss_sum), _ = lax.scan(
        tick, carry0, jnp.arange(M + 2 * (S - 1)))

    grads = {"embed": g_emb, "ln_f": g_head["ln_f"],
             "head": g_head["head"], "blocks": g_blk}
    return loss_sum, jnp.float32(B * L), grads


# ---------------------------------------------------------------------------
# Interleaved 1F1B (virtual stages) and zero-bubble (split backward)
# ---------------------------------------------------------------------------

def interleave_permutation(num_layers: int, pp_size: int,
                           pp_virtual: int) -> np.ndarray:
    """Dense -> interleaved row permutation for the STACKED block tree.

    Interleaved 1F1B (Megatron virtual stages, arXiv:2104.04473 §2.2 —
    reimplemented from the schedule description) splits the layer stack
    into ``pp * pp_virtual`` chunks of ``Lc = L / (pp * pp_virtual)``
    layers and assigns stage ``s`` the chunks ``{c*pp + s : c < V}``.
    The stacked tree shards CONTIGUOUSLY over ``pp``, so stage ``s``'s
    rows must hold its V chunks back to back: stacked row
    ``p = s*(L/pp) + c*Lc + j`` holds dense layer ``(c*pp + s)*Lc + j``.
    Returns ``perm`` with ``stacked_interleaved = dense_stacked[perm]``;
    ``pp_virtual == 1`` is the identity. Invert with ``np.argsort``.
    """
    L, S, V = num_layers, pp_size, pp_virtual
    if V < 1:
        raise ValueError(f"pp_virtual must be >= 1, got {V}")
    if L % (S * V):
        raise ValueError(f"num_layers={L} not divisible by "
                         f"pp*pp_virtual={S * V}")
    Lc = L // (S * V)
    perm = np.empty(L, np.int64)
    p = 0
    for s in range(S):
        for c in range(V):
            for j in range(Lc):
                perm[p] = (c * S + s) * Lc + j
                p += 1
    return perm


def permute_stacked_blocks(params: dict, perm) -> dict:
    """Reorder the stacked block rows by ``perm`` (host or device tree).
    Leaves every other entry untouched; apply ``np.argsort(perm)`` to
    undo (checkpoints always store the DENSE order)."""
    idx = np.asarray(perm)
    out = dict(params)
    out["blocks"] = jax.tree.map(lambda x: x[idx], params["blocks"])
    return out


def _make_run_chunk(model, blocks, pos, rng, pp_axis: str, pp_size: int,
                    pp_virtual: int):
    """One VIRTUAL chunk of this stage's layer rows as
    ``(x, mb_idx, c) -> y``. The stage's stacked slice holds its V
    chunks contiguously (:func:`interleave_permutation`): chunk ``c``
    occupies rows ``[c*Lc, (c+1)*Lc)`` and its global dense layers are
    ``(c*pp + stage)*Lc + local`` — dropout keys fold the DENSE layer
    index so masks agree with every other schedule and the dense model.
    """
    layers_per_stage = jax.tree.leaves(blocks)[0].shape[0]
    Lc = layers_per_stage // pp_virtual
    stage = lax.axis_index(pp_axis)

    def run_chunk(x, mb_idx, c):
        blocks_c = jax.tree.map(
            lambda a: lax.dynamic_slice_in_dim(a, c * Lc, Lc, 0), blocks)
        base = (c * pp_size + stage) * Lc

        def body(h, sl):
            layer, local_i = sl
            r = None
            if rng is not None and model.dropout_rate > 0.0:
                r = jax.random.fold_in(jax.random.fold_in(rng, mb_idx),
                                       base + local_i)
            h, _ = model.block_apply_aux(layer, h, pos, r)
            return h, None
        from tpu_ddp.memory import effective_remat, wrap_stage
        remat = effective_remat(model.remat_policy, "attn")
        if remat != "none":
            body = wrap_stage(body, remat, prevent_cse=False)
        h, _ = lax.scan(body, x, (blocks_c, jnp.arange(Lc)))
        return h

    return run_chunk


def pipeline_interleaved_grads(model, params, inputs, targets, *,
                               pp_size: int, num_micro: int,
                               pp_virtual: int,
                               pp_axis: str = PIPE_AXIS, rng=None,
                               skip_invalid: bool = True):
    """Interleaved 1F1B with ``pp_virtual`` chunks per stage (Megatron
    virtual stages, arXiv:2104.04473 — reimplemented from the schedule
    description, not from any code). Same contract as
    :func:`pipeline_1f1b_grads`; ``params["blocks"]`` must hold this
    stage's rows in the :func:`interleave_permutation` order.

    Schedule, expressed SPMD: the forward stream is a single sequence of
    work items ``k`` — item ``k`` is chunk ``(k % (S*V)) // S`` of
    microbatch ``(k // (S*V)) * S + k % S`` (microbatches travel in
    groups of S, hence ``num_micro % pp == 0``). Stage ``s`` forwards
    item ``t - s`` at tick ``t``, so the item arriving from the ring
    (stage S-1's output S ticks ago, item ``k - S``) is exactly the
    previous chunk of the same microbatch — chunk continuity by
    construction. The backward stream walks chunks in reverse with lag
    ``D + S - 2`` (``D = S*V``): stage ``s`` backwards item
    ``t - (D+S-2) + s`` whose effective chunk is ``V-1 - slot``. At
    ``V == 1`` every index degenerates to plain 1F1B. T = M*V + D + S - 2
    ticks; per-item compute is 1/V of a 1F1B item, so the bubble
    fraction drops to ``(pp-1)/(M*V + pp-1)`` — V x smaller for V x more
    in-flight activations (ring buffer 2*S*V - 1 chunk slots vs 2*pp-1).

    ``skip_invalid``: wrap the chunk forward/backward in ``lax.cond`` so
    out-of-range ticks genuinely SKIP compute instead of masking garbage
    (safe only when stage bodies contain no collectives — pure dp x pp;
    the trainer disables it under sp/tp/ep).
    """
    B, L = inputs.shape
    model.check_seq_len(L)
    if B % num_micro:
        raise ValueError(f"local batch {B} not divisible by "
                         f"num_micro={num_micro}")
    S, M, V = pp_size, num_micro, pp_virtual
    if M % S:
        raise ValueError(f"interleaved schedule needs num_micro "
                         f"divisible by pp: {M} % {S} != 0")
    mb = B // num_micro
    cd = model.compute_dtype
    stage = lax.axis_index(pp_axis)
    pos = model._positions(L)
    D = S * V          # work items per microbatch group
    MV = M * V         # total forward (= backward) items per stage
    K = 2 * D - 1      # saved-input slots: max fwd->bwd gap is 2D-2 ticks
    lag = D + S - 2    # backward stream offset (V=1: the 1F1B 2(S-1))

    micro = inputs.reshape(M, mb, L)
    tmicro = targets.reshape(M, mb, L)
    run_chunk = _make_run_chunk(model, params["blocks"], pos, rng,
                                pp_axis, S, V)

    def embed_mb(table, mb_idx):
        toks = lax.dynamic_index_in_dim(micro, mb_idx, 0, keepdims=False)
        x = table[toks].astype(cd)
        if rng is not None and model.dropout_rate > 0.0:
            k = jax.random.fold_in(jax.random.fold_in(rng, mb_idx),
                                   model.num_layers)
            x = model._dropout(x, k)
        return x

    def head_loss(hp, y, tgt):
        from tpu_ddp.ops.loss import softmax_cross_entropy
        logits = model.head_apply(hp, y)
        nll = softmax_cross_entropy(
            logits.reshape(-1, logits.shape[-1]), tgt.reshape(-1))
        return jnp.sum(nll)

    head_params = {"ln_f": params["ln_f"], "head": params["head"]}
    perm_down = [(i, (i + 1) % S) for i in range(S)]
    perm_up = [(i, (i - 1) % S) for i in range(S)]

    def run_chunk_with(blocks, x, mb_idx, c):
        return _make_run_chunk(model, blocks, pos, rng, pp_axis, S,
                               V)(x, mb_idx, c)

    def masked_add(acc, g, valid):
        return jax.tree.map(
            lambda a, gg: a + jnp.where(valid, gg, 0).astype(a.dtype),
            acc, g)

    def decomp(k):
        """Work item -> (microbatch, chunk slot): k = g*D + c*S + i with
        microbatch g*S + i."""
        g, r = k // D, k % D
        return g * S + r % S, r // S

    def tick(carry, t):
        fwd_in, bwd_in, buf, g_blk, g_emb, g_head, loss_sum = carry
        kf = t - stage
        kb = t - lag + stage
        f_valid = (0 <= kf) & (kf < MV)
        b_valid = (0 <= kb) & (kb < MV)
        kf_safe = jnp.clip(kf, 0, MV - 1)
        kb_safe = jnp.clip(kb, 0, MV - 1)
        m_f, c_f = decomp(kf_safe)
        m_b, cs_b = decomp(kb_safe)
        c_b = (V - 1) - cs_b  # the backward walks chunks in reverse
        # The backward item's own forward item (same microbatch, chunk
        # c_b) — locates its saved input in the ring buffer.
        kf_of_b = kb_safe + (c_b - cs_b) * S

        # ---- forward micro-step: embed-inject at (stage 0, chunk 0);
        # everywhere else the ring delivers the previous chunk's output.
        x_in = jnp.where((stage == 0) & (c_f == 0),
                         embed_mb(params["embed"], m_f), fwd_in)
        if skip_invalid:
            y = lax.cond(f_valid,
                         lambda xx: run_chunk(xx, m_f, c_f),
                         lambda xx: jnp.zeros_like(xx), x_in)
        else:
            y = run_chunk(x_in, m_f, c_f)
        buf = jnp.where(f_valid,
                        lax.dynamic_update_index_in_dim(
                            buf, x_in, kf_safe % K, 0),
                        buf)

        # ---- head at the last stage when the forward item is the FINAL
        # chunk; the same tick's backward item is that microbatch's
        # chunk V-1 (kf - kb = (V-1)*S by construction), so dy_head
        # feeds the backward stream directly, as in plain 1F1B.
        tgt = lax.dynamic_index_in_dim(tmicro, m_f, 0, keepdims=False)
        at_last = stage == S - 1

        def head_fwd_bwd(y, tgt):
            nll_sum, head_vjp = jax.vjp(
                lambda hp, yy: head_loss(hp, yy, tgt), head_params, y)
            d_hp, dy_head = head_vjp(jnp.float32(1.0))
            return nll_sum, d_hp, dy_head

        def head_skip(y, tgt):
            return (jnp.float32(0.0),
                    jax.tree.map(lambda p: jnp.zeros(p.shape, p.dtype),
                                 head_params),
                    jnp.zeros_like(y))

        nll_sum, d_hp, dy_head = lax.cond(
            at_last & f_valid & (c_f == V - 1),
            head_fwd_bwd, head_skip, y, tgt)
        loss_sum = loss_sum + nll_sum
        g_head = jax.tree.map(lambda a, gg: a + gg.astype(a.dtype),
                              g_head, d_hp)

        # ---- backward micro-step: recompute-vjp of chunk c_b from its
        # saved input (stored kf_of_b's tick; the stage-S-1/chunk-V-1
        # case reads the slot written THIS tick — write precedes read).
        x_saved = lax.dynamic_index_in_dim(buf, kf_of_b % K, 0,
                                           keepdims=False)
        d_in = jnp.where(at_last & (cs_b == 0), dy_head.astype(cd),
                         bwd_in)

        def bwd_real(xx, dd):
            _, stage_vjp = jax.vjp(
                lambda blk, x2: run_chunk_with(blk, x2, m_b, c_b),
                params["blocks"], xx)
            return stage_vjp(dd)

        def bwd_skip(xx, dd):
            return (jax.tree.map(
                lambda p: jnp.zeros(p.shape, p.dtype),
                params["blocks"]), jnp.zeros_like(xx))

        if skip_invalid:
            d_blk, dx = lax.cond(b_valid, bwd_real, bwd_skip,
                                 x_saved, d_in)
        else:
            d_blk, dx = bwd_real(x_saved, d_in)
        g_blk = masked_add(g_blk, d_blk, b_valid)

        # Embed grad at (stage 0, backward chunk 0): dx there is
        # d(embed output) of microbatch m_b — scatter-add per tick,
        # dropout transposed from its key (the 1F1B pattern).
        toks_b = lax.dynamic_index_in_dim(micro, m_b, 0, keepdims=False)
        dxe = dx.astype(jnp.float32)
        if rng is not None and model.dropout_rate > 0.0:
            k = jax.random.fold_in(jax.random.fold_in(rng, m_b),
                                   model.num_layers)
            keep = 1.0 - model.dropout_rate
            mask = jax.random.bernoulli(k, keep, dx.shape)
            dxe = jnp.where(mask, dxe / keep, 0.0)
        contrib = jnp.where(b_valid & (stage == 0) & (c_b == 0),
                            dxe, 0.0)
        g_emb = g_emb.at[toks_b.reshape(-1)].add(
            contrib.reshape(-1, contrib.shape[-1]))

        return ((lax.ppermute(y, pp_axis, perm_down),
                 lax.ppermute(dx, pp_axis, perm_up),
                 buf, g_blk, g_emb, g_head, loss_sum), None)

    zeros_f32 = lambda tree: jax.tree.map(  # noqa: E731
        lambda p: jnp.zeros(p.shape, jnp.float32), tree)
    carry0 = (
        jnp.zeros((mb, L, model.d_model), cd),       # fwd ring
        jnp.zeros((mb, L, model.d_model), cd),       # bwd ring
        jnp.zeros((K, mb, L, model.d_model), cd),    # saved chunk inputs
        zeros_f32(params["blocks"]),
        zeros_f32(params["embed"]),
        zeros_f32(head_params),
        jnp.float32(0.0),
    )
    (_, _, _, g_blk, g_emb, g_head, loss_sum), _ = lax.scan(
        tick, carry0, jnp.arange(MV + D + S - 2))

    grads = {"embed": g_emb, "ln_f": g_head["ln_f"],
             "head": g_head["head"], "blocks": g_blk}
    return loss_sum, jnp.float32(B * L), grads


def pipeline_zerobubble_grads(model, params, inputs, targets, *,
                              pp_size: int, num_micro: int,
                              pp_axis: str = PIPE_AXIS, rng=None,
                              skip_invalid: bool = True):
    """Zero-bubble 1F1B (ZB-H1 family, Qi et al., arXiv:2401.10241 —
    reimplemented from the schedule description, not from any code):
    the backward splits into B-input (cotangent propagation, on the
    1F1B backward clock ``b = t - 2(pp-1) + s`` — it sits on the
    critical path of upstream stages) and B-weight (the stage's weight
    gradient, deferred to the UNIFORM clock ``w = t - 2(pp-1)``). The
    deferral moves every stage's weight-gradient work off the warmup
    ticks — between the first backward reaching a stage and the ramp
    being full, stages run F + B-input only — so the lockstep tick cost
    there drops from 3 to 2 work units and the analytic bubble fraction
    falls from ``2(pp-1)/(M + 2(pp-1))`` to ``2(pp-1)/(3M + 2(pp-1))``.
    T = M + 2(pp-1) ticks, unchanged.

    Same contract and layout as :func:`pipeline_1f1b_grads` (linear
    stage order; no virtual stages — zero-bubble extends plain 1F1B).
    Each B-input stores its ``(saved input, output cotangent)`` pair in
    a ``pp``-slot ring for the B-weight that consumes it up to ``s``
    ticks later, costing one extra stage recompute per item (the
    recompute-vjp runs once per half). ``skip_invalid`` as in
    :func:`pipeline_interleaved_grads`.
    """
    B, L = inputs.shape
    model.check_seq_len(L)
    if B % num_micro:
        raise ValueError(f"local batch {B} not divisible by "
                         f"num_micro={num_micro}")
    mb = B // num_micro
    S, M = pp_size, num_micro
    cd = model.compute_dtype
    stage = lax.axis_index(pp_axis)
    pos = model._positions(L)
    K = 2 * S - 1   # saved-input slots (the 1F1B fwd->bwd gap)
    W = S           # (input, cotangent) slots: B-input -> B-weight gap

    micro = inputs.reshape(M, mb, L)
    tmicro = targets.reshape(M, mb, L)
    run_stage = _make_run_stage(model, params["blocks"], pos, rng, pp_axis)

    def embed_mb(table, mb_idx):
        toks = lax.dynamic_index_in_dim(micro, mb_idx, 0, keepdims=False)
        x = table[toks].astype(cd)
        if rng is not None and model.dropout_rate > 0.0:
            k = jax.random.fold_in(jax.random.fold_in(rng, mb_idx),
                                   model.num_layers)
            x = model._dropout(x, k)
        return x

    def head_loss(hp, y, tgt):
        from tpu_ddp.ops.loss import softmax_cross_entropy
        logits = model.head_apply(hp, y)
        nll = softmax_cross_entropy(
            logits.reshape(-1, logits.shape[-1]), tgt.reshape(-1))
        return jnp.sum(nll)

    head_params = {"ln_f": params["ln_f"], "head": params["head"]}
    perm_down = [(i, (i + 1) % S) for i in range(S)]
    perm_up = [(i, (i - 1) % S) for i in range(S)]

    def run_stage_with(blocks, x, mb_idx):
        return _make_run_stage(model, blocks, pos, rng, pp_axis)(x, mb_idx)

    def masked_add(acc, g, valid):
        return jax.tree.map(
            lambda a, gg: a + jnp.where(valid, gg, 0).astype(a.dtype),
            acc, g)

    def tick(carry, t):
        (fwd_in, bwd_in, buf, wbuf_x, wbuf_d, g_blk, g_emb, g_head,
         loss_sum) = carry
        f = t - stage
        b = t - 2 * (S - 1) + stage     # B-input clock (1F1B backward)
        w = t - 2 * (S - 1)             # B-weight clock, stage-uniform
        f_valid = (0 <= f) & (f < M)
        b_valid = (0 <= b) & (b < M)
        w_valid = (0 <= w) & (w < M)
        f_safe = jnp.clip(f, 0, M - 1)
        b_safe = jnp.clip(b, 0, M - 1)
        w_safe = jnp.clip(w, 0, M - 1)

        # ---- forward micro-step (identical to 1F1B).
        x_in = jnp.where(stage == 0, embed_mb(params["embed"], f_safe),
                         fwd_in)
        if skip_invalid:
            y = lax.cond(f_valid, lambda xx: run_stage(xx, f_safe),
                         lambda xx: jnp.zeros_like(xx), x_in)
        else:
            y = run_stage(x_in, f_safe)
        buf = jnp.where(f_valid,
                        lax.dynamic_update_index_in_dim(
                            buf, x_in, f_safe % K, 0),
                        buf)

        # ---- head at the last stage (f == b there, as in 1F1B).
        tgt = lax.dynamic_index_in_dim(tmicro, f_safe, 0, keepdims=False)
        at_last = stage == S - 1

        def head_fwd_bwd(y, tgt):
            nll_sum, head_vjp = jax.vjp(
                lambda hp, yy: head_loss(hp, yy, tgt), head_params, y)
            d_hp, dy_head = head_vjp(jnp.float32(1.0))
            return nll_sum, d_hp, dy_head

        def head_skip(y, tgt):
            return (jnp.float32(0.0),
                    jax.tree.map(lambda p: jnp.zeros(p.shape, p.dtype),
                                 head_params),
                    jnp.zeros_like(y))

        nll_sum, d_hp, dy_head = lax.cond(at_last & f_valid,
                                          head_fwd_bwd, head_skip, y, tgt)
        loss_sum = loss_sum + nll_sum
        g_head = jax.tree.map(lambda a, gg: a + gg.astype(a.dtype),
                              g_head, d_hp)

        # ---- B-input: cotangent only (vjp w.r.t. x, blocks closed
        # over) — the half that feeds the upstream stage's next tick.
        x_saved = lax.dynamic_index_in_dim(buf, b_safe % K, 0,
                                           keepdims=False)
        d_in = jnp.where(at_last, dy_head.astype(cd), bwd_in)

        def binput_real(xx, dd):
            _, in_vjp = jax.vjp(
                lambda x2: run_stage_with(params["blocks"], x2, b_safe),
                xx)
            (dx,) = in_vjp(dd)
            return dx

        if skip_invalid:
            dx = lax.cond(b_valid, binput_real,
                          lambda xx, dd: jnp.zeros_like(xx),
                          x_saved, d_in)
        else:
            dx = binput_real(x_saved, d_in)
        # Stash (input, output cotangent) for this item's deferred
        # B-weight, up to stage-index ticks later (slot reuse is safe:
        # item b+S's B-input lands strictly after item b's B-weight).
        wbuf_x = jnp.where(b_valid,
                           lax.dynamic_update_index_in_dim(
                               wbuf_x, x_saved, b_safe % W, 0),
                           wbuf_x)
        wbuf_d = jnp.where(b_valid,
                           lax.dynamic_update_index_in_dim(
                               wbuf_d, d_in, b_safe % W, 0),
                           wbuf_d)

        # ---- B-weight: the deferred weight-gradient half (vjp w.r.t.
        # blocks), consuming the stashed pair. At stage 0 it reads the
        # slot written THIS tick (w == b there) — write precedes read.
        x_w = lax.dynamic_index_in_dim(wbuf_x, w_safe % W, 0,
                                       keepdims=False)
        d_w = lax.dynamic_index_in_dim(wbuf_d, w_safe % W, 0,
                                       keepdims=False)

        def bweight_real(xx, dd):
            _, wt_vjp = jax.vjp(
                lambda blk: run_stage_with(blk, xx, w_safe),
                params["blocks"])
            (d_blk,) = wt_vjp(dd)
            return d_blk

        def bweight_skip(xx, dd):
            return jax.tree.map(
                lambda p: jnp.zeros(p.shape, p.dtype), params["blocks"])

        if skip_invalid:
            d_blk = lax.cond(w_valid, bweight_real, bweight_skip,
                             x_w, d_w)
        else:
            d_blk = bweight_real(x_w, d_w)
        g_blk = masked_add(g_blk, d_blk, w_valid)

        # Embed grad at stage 0 from the B-input cotangent (1F1B
        # pattern: per-tick scatter-add, dropout transposed by key).
        toks_b = lax.dynamic_index_in_dim(micro, b_safe, 0,
                                          keepdims=False)
        dxe = dx.astype(jnp.float32)
        if rng is not None and model.dropout_rate > 0.0:
            k = jax.random.fold_in(jax.random.fold_in(rng, b_safe),
                                   model.num_layers)
            keep = 1.0 - model.dropout_rate
            mask = jax.random.bernoulli(k, keep, dx.shape)
            dxe = jnp.where(mask, dxe / keep, 0.0)
        contrib = jnp.where(b_valid & (stage == 0), dxe, 0.0)
        g_emb = g_emb.at[toks_b.reshape(-1)].add(
            contrib.reshape(-1, contrib.shape[-1]))

        return ((lax.ppermute(y, pp_axis, perm_down),
                 lax.ppermute(dx, pp_axis, perm_up),
                 buf, wbuf_x, wbuf_d, g_blk, g_emb, g_head, loss_sum),
                None)

    zeros_f32 = lambda tree: jax.tree.map(  # noqa: E731
        lambda p: jnp.zeros(p.shape, jnp.float32), tree)
    carry0 = (
        jnp.zeros((mb, L, model.d_model), cd),       # fwd ring
        jnp.zeros((mb, L, model.d_model), cd),       # bwd ring
        jnp.zeros((K, mb, L, model.d_model), cd),    # saved inputs
        jnp.zeros((W, mb, L, model.d_model), cd),    # B-weight inputs
        jnp.zeros((W, mb, L, model.d_model), cd),    # B-weight cotangents
        zeros_f32(params["blocks"]),
        zeros_f32(params["embed"]),
        zeros_f32(head_params),
        jnp.float32(0.0),
    )
    (_, _, _, _, _, g_blk, g_emb, g_head, loss_sum), _ = lax.scan(
        tick, carry0, jnp.arange(M + 2 * (S - 1)))

    grads = {"embed": g_emb, "ln_f": g_head["ln_f"],
             "head": g_head["head"], "blocks": g_blk}
    return loss_sum, jnp.float32(B * L), grads
