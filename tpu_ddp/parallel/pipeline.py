"""Pipeline parallelism: the layer stack sharded into stages over ``pp``.

No reference counterpart (the reference implements data parallelism only —
SURVEY.md §2 "Absent parallelism strategies"); included because multi-axis
model sharding is first-class in this framework. The schedule is GPipe-
style microbatching (Huang et al., arXiv:1811.06965 — reimplemented from
the paper's schedule, not from any code) expressed the SPMD way, as a
collective-permute ring pipeline:

- block parameters are STACKED on a leading layer axis and sharded over
  the ``pp`` mesh axis — each stage holds ``num_layers / pp`` layers and
  scans over them locally (``lax.scan`` keeps one compiled block body);
- the local batch is split into M microbatches; the pipeline runs
  ``T = M + pp - 1`` ticks. Every tick each stage applies its layer slice
  to its resident activation, then ``lax.ppermute`` rotates activations
  one hop along the ring (stage i -> i+1) — XLA overlaps the ICI hop with
  the next tick's compute, exactly like ring attention's K/V rotation;
- stage 0 injects embedded microbatch t at tick t; the LAST stage's
  output at tick t is microbatch ``t - (pp-1)``'s final activation. The
  first ``pp - 1`` ticks per direction are the pipeline bubble — its
  relative cost shrinks as M grows (bubble fraction = (pp-1)/(M+pp-1));
- embeddings and the LM head run OUTSIDE the tick loop, once per device
  over the full local batch (their per-device cost equals the dense
  model's; only the result computed on stage 0 / the last stage is real,
  selected by masks that zero the garbage — and, in the backward pass,
  zero the garbage's gradients).

Gradient flow needs no custom rules: ``ppermute`` transposes to the
inverse rotation (the backward pipeline runs the ring in reverse), and
the ``where``-masks confine embed/head gradients to the stages that
actually used them — the trainer then ``psum``s those replicated leaves
over ``pp`` (each stage contributes its share, zeros elsewhere) while
stacked block leaves stay stage-local. Composes with tensor parallelism
(block weights additionally sharded over ``mp`` inside each stage) and
data parallelism; exactness vs the dense model is tested in
tests/test_pipeline.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from tpu_ddp.parallel.mesh import PIPE_AXIS


def _is_spec(x):
    return isinstance(x, P)


def stack_block_params(params: dict) -> dict:
    """Per-layer blocks tuple -> one tree with a leading layer axis.

    ``stacked[k][j] == params["blocks"][j][k]`` — layer order is the
    stacking order, so specs/values round-trip with
    :func:`unstack_block_params`.
    """
    blocks = params["blocks"]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)
    out = dict(params)
    out["blocks"] = stacked
    return out


def unstack_block_params(params: dict, num_layers: int) -> dict:
    """Inverse of :func:`stack_block_params` (host-side, for tests/ckpt)."""
    stacked = params["blocks"]
    blocks = tuple(
        jax.tree.map(lambda x: x[j], stacked) for j in range(num_layers))
    out = dict(params)
    out["blocks"] = blocks
    return out


def pipeline_param_specs(model) -> dict:
    """Specs for the STACKED tree: block leaves gain a leading ``pp``
    axis on top of the model's own (tp) layout; embed/head/ln_f stay
    replicated (their grads are pp-psum'd by the trainer)."""
    base = model.param_specs()
    blk = jax.tree.map(lambda s: P(PIPE_AXIS, *tuple(s)),
                       base["blocks"][0], is_leaf=_is_spec)
    return {"embed": base["embed"], "ln_f": base["ln_f"],
            "head": base["head"], "blocks": blk}


def pipeline_loss(model, params, inputs, targets, *, pp_size: int,
                  num_micro: int, pp_axis: str = PIPE_AXIS):
    """(masked_loss_sum, local_n) for this shard's (B, L) batch.

    Must run inside a shard_map over ``pp_axis`` with ``params["blocks"]``
    holding this stage's stacked layer slice. ``masked_loss_sum`` is the
    summed token NLL on the LAST stage and exactly 0.0 elsewhere (so its
    gradient is confined to real compute); psum it over ``pp_axis`` to
    read the value. ``local_n`` is the token count (same on all stages).
    """
    B, L = inputs.shape
    if L > model.max_seq_len:
        raise ValueError(f"sequence length {L} exceeds "
                         f"max_seq_len={model.max_seq_len}")
    if B % num_micro:
        raise ValueError(f"local batch {B} not divisible by "
                         f"num_micro={num_micro}")
    mb = B // num_micro
    S, M = pp_size, num_micro
    cd = model.compute_dtype
    stage = lax.axis_index(pp_axis)
    pos = jnp.arange(L)

    micro = inputs.reshape(M, mb, L)
    x_embed = params["embed"][micro].astype(cd)      # (M, mb, L, dm)

    def run_stage(x):
        """This stage's layer slice, scanned layer by layer. With
        ``remat_blocks`` each layer recomputes in the backward pass —
        essential under GPipe, whose T = M + pp - 1 ticks would otherwise
        stash every tick's activations."""
        def body(h, layer):
            return model.block_apply(layer, h, pos), None
        if model.remat_blocks:
            # prevent_cse=False: scan's loop structure already prevents
            # the problematic CSE, so keep XLA free to fuse.
            body = jax.checkpoint(body, prevent_cse=False)
        h, _ = lax.scan(body, x, params["blocks"])
        return h

    perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(carry, t):
        x_prev = carry
        inj = lax.dynamic_index_in_dim(x_embed, jnp.minimum(t, M - 1), 0,
                                       keepdims=False)
        # Stage 0's input comes from injection, later stages' from the
        # ring; the where-mask also zeroes embed grads on stages > 0.
        x_in = jnp.where(stage == 0, inj, x_prev)
        x_out = run_stage(x_in)
        x_send = lax.ppermute(x_out, pp_axis, perm)
        return x_send, x_out

    x0 = jnp.zeros((mb, L, model.d_model), cd)
    _, ys = lax.scan(tick, x0, jnp.arange(M + S - 1))
    # On the last stage, tick t emitted microbatch t-(S-1): ys[S-1+m] = m.
    outs = ys[S - 1:]                                 # (M, mb, L, dm)
    x = outs.reshape(B, L, model.d_model)

    from tpu_ddp.ops.loss import softmax_cross_entropy
    logits = model.head_apply(params, x)              # (B, L, V) f32
    nll = softmax_cross_entropy(
        logits.reshape(-1, logits.shape[-1]), targets.reshape(-1))
    # Only the last stage's activations are real; the mask zeroes the
    # other stages' loss AND, transposed, their head/ln_f gradients.
    is_last = (stage == S - 1).astype(nll.dtype)
    return jnp.sum(nll) * is_last, jnp.float32(nll.size)
