"""DiLoCo: the cross-datacenter rung of the gradient-sync ladder.

The ladder so far trades gradient-sync strategies inside one cluster
(none -> gather/scatter -> all_reduce -> bucketed/fused DDP -> ZeRO /
FSDP / overlap). Its missing rung is the one where the link between
replica GROUPS is WAN-grade and a per-step all_reduce is unaffordable.
DiLoCo-style training climbs it with a TWO-LEVEL hierarchy (the
map/reduce-over-groups structure of *DrJAX*, arXiv 2403.07128):

- **inner**: each group runs ``H`` local optimizer steps with ANY
  existing rung — fused DDP, ZeRO, FSDP, overlap all compose inside a
  group, because the only thing the outer level ever sees is the
  group's canonical ``params_to_host`` snapshot.
- **outer**: once per round the groups exchange *pseudo-gradients*
  (``params_start - params_end``) and a Nesterov-momentum outer step
  updates the shared global params. Cross-group bytes drop by ~H×
  before compression even starts.

The outer wire is NOT a new delta path: the pseudo-gradient IS a
:class:`~tpu_ddp.publish.publisher.WeightUpdate`. Each group's end-of-
round params go through a round-17 ``publish/`` Publisher whose delta
baseline was re-anchored (``Publisher.rebase``) at the agreed global
params both ends already hold — so the bucketed, compressed, digest-
verified wire delta is *exactly* ``end - start``, i.e. the negated
pseudo-gradient, with per-bucket int8 error feedback carried across
rounds. Transport rides the same DCN channel class as the MPMD
pipeline edges (:class:`UpdateEdge` below, the ``parallel/mpmd.py``
framing), so a cross-process deployment reuses ``SocketEdge``
machinery unchanged.

Bitwise policy (what the pins in tests/test_diloco.py claim): on a
COMPRESSING wire (bf16/int8/sparse) both edges ship rebased deltas —
the pseudo-gradient's small dynamic range is what makes int8 viable.
On the lossless dense wire (``none``) a delta and a full tensor cost
IDENTICAL bytes, but ``start + (end - start)`` is not ``end`` in f32 —
so the ``none`` wire ships FULL pushes (``Publisher.force_full``),
which decode bitwise. That is what makes ``H=1, outer_lr=1, zero
momentum, wire=none`` match plain synced training bit for bit.

Agreement model: the outer apply is ONE jitted program
(:func:`outer_program`) run by the coordinator; ``nonfinite_flag`` +
``select_update`` make a non-finite pseudo-gradient an exact in-graph
no-op (the psum-agreed skip of the SPMD rungs — here agreement is by
construction, since every group receives the same digest-pinned result
over the down edge). Group (re)placement on join/loss needs no
parameter reshuffle beyond one bootstrap transfer (cf. *Memory-
efficient array redistribution*, arXiv 2112.01075): the global params
are already in canonical host form, so a joiner lands with one
``Publisher.bootstrap`` full push at the current outer version.
"""

from __future__ import annotations

import dataclasses
import functools
import pickle
import struct

import jax
import jax.numpy as jnp
import numpy as np

from tpu_ddp.parallel.compress import EdgeCodec
from tpu_ddp.parallel.mpmd import InProcessEdge
from tpu_ddp.publish.store import tree_digests
from tpu_ddp.resilience.guard import nonfinite_flag, select_update

__all__ = [
    "GroupEndpoint", "UpdateEdge", "decode_update", "finite_leaves",
    "lower_outer_step", "mean_end_leaves", "outer_program",
]


# ---------------------------------------------------------------------------
# The outer-step jitted program (the graph_audit surface).
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def outer_program(outer_lr: float, outer_momentum: float):
    """The jitted outer Nesterov step for static ``(lr, mu)``.

    ``diloco_outer_apply(start, mean_end, momentum)`` over leaf tuples:

    - pseudo-gradient ``g = start - mean_end`` (f32),
    - ``m_new = mu * m + g``; Nesterov update ``start - lr*(g + mu*m_new)``,
    - in-graph guard: a non-finite pseudo-gradient selects the OLD
      params and momentum per leaf (``select_update`` — exact identity
      on a healthy round), returning the ``bad`` flag.

    ``lr == 1 and mu == 0`` is the identity outer optimizer: the
    program adopts ``mean_end`` STRUCTURALLY (no delta arithmetic), so
    the bitwise pin holds by construction instead of by float luck.
    ``start`` and ``momentum`` are donated — round t+1's buffers are
    round t's.
    """
    lr = float(outer_lr)
    mu = float(outer_momentum)
    identity = lr == 1.0 and mu == 0.0

    def diloco_outer_apply(start, mean_end, momentum):
        g = tuple(s.astype(jnp.float32) - e.astype(jnp.float32)
                  for s, e in zip(start, mean_end))
        bad = nonfinite_flag(jnp.float32(0.0), g)
        m_new = tuple(mu * m + gi for m, gi in zip(momentum, g))
        if identity:
            new = tuple(e.astype(s.dtype)
                        for s, e in zip(start, mean_end))
        else:
            new = tuple(
                (s.astype(jnp.float32) - lr * (gi + mu * mi))
                .astype(s.dtype)
                for s, gi, mi in zip(start, g, m_new))
        new = select_update(bad, tuple(start), new)
        m_out = select_update(bad, tuple(momentum), m_new)
        return new, m_out, bad

    return jax.jit(diloco_outer_apply, donate_argnums=(0, 2))


def lower_outer_step(params, *, outer_lr: float = 0.7,
                     outer_momentum: float = 0.9):
    """``jit.lower`` the outer apply at ``params``'s leaf shapes — the
    outer-step graph-audit surface (scripts/graph_audit.py): groups in
    lockstep must dispatch THIS program identically, which is exactly
    the divergent-collective-order class the auditor fingerprints."""
    leaves = jax.tree.leaves(params)
    starts = tuple(jax.ShapeDtypeStruct(np.shape(x), jnp.result_type(x))
                   for x in leaves)
    f32s = tuple(jax.ShapeDtypeStruct(np.shape(x), jnp.float32)
                 for x in leaves)
    return outer_program(outer_lr, outer_momentum).lower(
        starts, f32s, f32s)


# ---------------------------------------------------------------------------
# Host-side wire decode (the coordinator's end of the up edge).
# ---------------------------------------------------------------------------


def decode_update(update, plan, last_leaves=None):
    """Decode one :class:`WeightUpdate` against ``last_leaves`` on the
    host — the coordinator's (engine-free) mirror of the subscriber
    flip. Returns ``(leaves, tree)`` of the reconstruction; raises on a
    layout or digest mismatch (a silently-wrong outer mean is the one
    failure mode this edge must never have)."""
    if plan.fingerprint() != update.layout:
        raise ValueError(
            "diloco: update layout does not match the outer plan "
            "(group and coordinator disagree on the model)")
    if update.kind != "full" and last_leaves is None:
        raise ValueError("diloco: delta decode needs last_leaves")
    recon = [None] * len(plan.metas)
    for b, idxs in enumerate(plan.buckets):
        dec = np.asarray(EdgeCodec.decode(update.wires[b]), np.float32)
        off = 0
        for i in idxs:
            m = plan.metas[i]
            d = dec[off:off + m.size].reshape(m.shape)
            off += m.size
            if update.kind == "full":
                recon[i] = d.astype(m.dtype)
            else:
                recon[i] = (np.asarray(last_leaves[i], np.float32)
                            + d).astype(m.dtype)
    tree = jax.tree.unflatten(plan.treedef, recon)
    if tree_digests(tree) != update.digests:
        raise ValueError(
            f"diloco: digest mismatch on version {update.version} — "
            "refusing to fold a corrupt pseudo-gradient into the "
            "outer mean")
    return recon, tree


def finite_leaves(leaves) -> bool:
    """Host-side all-finite check over a leaf list (the pre-publish
    flag collection: a bad group must be known BEFORE any codec
    consumes its delta, so a skipped round leaves every error-feedback
    residual untouched)."""
    return all(bool(np.isfinite(np.asarray(x)).all()) for x in leaves)


def mean_end_leaves(ends: list) -> list:
    """Equal-weight f32 mean over groups' decoded end leaves — the
    reduce of the two-level hierarchy, and the reweighting point: a
    lost group is simply absent from ``ends`` and the divisor. For a
    single group this is ``end / 1.0``, which is exact."""
    if not ends:
        raise ValueError("diloco: outer mean over zero groups")
    inv = np.float32(1.0 / len(ends))
    out = []
    for parts in zip(*ends):
        acc = np.asarray(parts[0], np.float32)
        for p in parts[1:]:
            acc = acc + np.asarray(p, np.float32)
        out.append(acc * inv)
    return out


# ---------------------------------------------------------------------------
# The group's engine adapter (publish/subscriber protocol).
# ---------------------------------------------------------------------------


class GroupEndpoint:
    """One DiLoCo group's end of the down (broadcast) edge.

    Satisfies the ``publish/subscriber.py`` engine protocol — ``params``
    (live device tree), ``swap_params``, ``param_version``, ``step()``
    — over any trainer whose state is a dataclass with a ``params``
    field (LMTrainState, TrainState). A subscriber flip therefore lands
    in the group's REAL training state: the delta path donates the old
    live params and the group trains on from the flipped tree.

    Call :meth:`sync` before pumping the subscriber — inner steps
    donate their input state, so the live tree must be re-read from the
    group's current state, never cached across steps.
    """

    def __init__(self, group):
        self._group = group
        self.params = group.state.params
        self.param_version = 0
        self.subscriber = None

    def sync(self) -> None:
        self.params = self._group.state.params

    def swap_params(self, new_live, version: int) -> None:
        self.params = new_live
        self.param_version = version
        g = self._group
        g.state = dataclasses.replace(g.state, params=new_live)

    def step(self) -> None:
        if self.subscriber is not None:
            self.subscriber.on_engine_step()


# ---------------------------------------------------------------------------
# The DCN hop: WeightUpdates over the MPMD edge machinery.
# ---------------------------------------------------------------------------


class UpdateEdge(InProcessEdge):
    """A cross-group DCN channel carrying whole ``WeightUpdate``s.

    Same framing as :class:`~tpu_ddp.parallel.mpmd.SocketEdge` — 4-byte
    big-endian length + pickle — held in the in-process deque, so the
    single-process tests and sweeps exercise byte-for-byte the blobs a
    socket deployment would ship (``WeightUpdate`` wires are already
    host numpy). The payload is pre-compressed by the publisher's
    codecs; this edge's own codec stays ``none``.
    """

    def __init__(self):
        super().__init__(EdgeCodec("none"))
        self.wire_bytes = 0

    def send(self, update) -> None:
        blob = pickle.dumps(update, protocol=pickle.HIGHEST_PROTOCOL)
        self._q.append(struct.pack(">I", len(blob)) + blob)
        self.messages += 1
        self.wire_bytes += 4 + len(blob)

    def recv(self):
        frame = self._q.popleft()
        (n,) = struct.unpack(">I", frame[:4])
        return pickle.loads(frame[4:4 + n])

    def stats(self) -> dict:
        return {"transport": type(self).__name__,
                "messages": self.messages,
                "wire_bytes": int(self.wire_bytes)}
