"""The four gradient-synchronization strategies — the heart of the ladder.

Parity map (SURVEY.md §1 L1):

=========  =====================================================  ==========================
strategy   reference implementation                               TPU-native implementation
=========  =====================================================  ==========================
none       part1: no sync calls (part1/main.py:52)                identity
gather     part2a ``sync_gradients(model, rank, ws)``             per-leaf ``all_gather`` to
scatter    (part2/part2a/main.py:97-115): rank 0 gathers every    every replica; the *root
           param grad, means, scatters the mean back              replica's* mean is selected
                                                                  and broadcast via ``psum``
                                                                  so "who computes the mean"
                                                                  matches the reference
all_reduce part2b ``sync_gradients(model, ws)``                   per-leaf ``psum(SUM)`` then
           (part2/part2b/main.py:97-103): per-param               divide by world size
           ``all_reduce(SUM)`` then ``grad /= ws``
fused      part3 ``DDP(model)`` (part3/main.py:174): bucketed     one tree-level ``pmean``
           async all-reduce overlapped with backward by the       inside the jitted step —
           C++ reducer (25 MB buckets)                            XLA's latency-hiding
                                                                  scheduler overlaps the ICI
                                                                  collective with the rest of
                                                                  the backward pass (the
                                                                  idiomatic analogue of
                                                                  bucketing, SURVEY §2 N2).
                                                                  With ``--overlap`` the
                                                                  reducer's mechanics are
                                                                  *reproduced* explicitly:
                                                                  ``parallel/overlap.py``
                                                                  builds size-targeted
                                                                  buckets in reverse-autodiff
                                                                  order and issues one
                                                                  collective per bucket
                                                                  mid-backward (DESIGN §18)
=========  =====================================================  ==========================

All strategies are pure functions ``(grads, axis_name) -> grads`` applied
inside the (shard_map'd, jitted) train step, so every strategy produces
identical synchronized gradients — the ladder's correctness invariant
(report §2.2) — and they are numerically interchangeable (tested in
tests/test_sync.py).

Note on part2a fidelity: XLA/SPMD has no asymmetric root-centric collective;
the composition below preserves the *semantics* (the root's mean is what
every replica applies) while the latency asymmetry of a TCP master
bottleneck does not exist on ICI (SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def sync_none(grads, axis_name=None):
    """part1: single device, no synchronization (reference part1/main.py:52)."""
    return grads


def _leafwise(fn, grads):
    return jax.tree.map(fn, grads)


def sync_gather_scatter(grads, axis_name):
    """part2a: gather all replicas' grads at the root, mean there, scatter.

    Reference part2/part2a/main.py:97-115 does, per parameter: rank 0
    allocates ``world_size`` buffers, ``dist.gather(...)``, means the stack,
    ``dist.scatter(...)`` the mean back; other ranks send/receive. Here each
    leaf is ``all_gather``'d, the mean is computed, and the *root replica's*
    copy of the mean is what gets broadcast (mask + ``psum``) — so the value
    every replica applies is, as in the reference, the root's mean.
    """
    idx = lax.axis_index(axis_name)

    def leaf(g):
        stacked = lax.all_gather(g, axis_name)          # (world, ...)
        mean = jnp.mean(stacked, axis=0)
        root_only = jnp.where(idx == 0, mean, jnp.zeros_like(mean))
        return lax.psum(root_only, axis_name)           # broadcast root's mean

    return _leafwise(leaf, grads)


def sync_all_reduce(grads, axis_name):
    """part2b: per-parameter ring all-reduce(SUM), then divide by world size
    (reference part2/part2b/main.py:97-103). Kept per-leaf for ladder
    pedagogy; XLA may still fuse adjacent collectives."""
    world = lax.psum(1, axis_name)
    return _leafwise(lambda g: lax.psum(g, axis_name) / world, grads)


def sync_fused(grads, axis_name):
    """part3: the DDP equivalent — one tree-level ``pmean`` inside the jitted
    step. XLA sees the whole backward + collective dataflow and overlaps the
    ICI all-reduce with remaining backward compute, which is what torch DDP's
    25 MB bucketing + autograd hooks achieve by hand (reference
    part3/main.py:13,174; SURVEY.md §2 row N2).

    When the ``overlap`` knob is on, the engine bypasses this hook entirely:
    ``parallel/overlap.py`` reproduces the reducer literally — 25 MB (default)
    size-targeted buckets in reverse-autodiff order, one collective per bucket
    issued mid-backward via custom_vjp taps — instead of delegating the
    overlap to XLA's scheduler (DESIGN.md §18)."""
    return lax.pmean(grads, axis_name)


SYNC_STRATEGIES = {
    "none": sync_none,
    "gather_scatter": sync_gather_scatter,
    "all_reduce": sync_all_reduce,
    "fused": sync_fused,
    # ZeRO's reduce_scatter IS the sync; grads enter the optimizer
    # unsynced and the engine wraps the optimizer in ZeRO1
    # (tpu_ddp/parallel/zero.py), so the grads->grads hook is identity.
    "zero": sync_none,
    # FSDP/ZeRO-3: the gradient reduce_scatter is the TRANSPOSE of the
    # forward's parameter all_gather — autodiff performs the sync, so
    # the grads->grads hook is again identity (tpu_ddp/parallel/zero.py
    # ZeRO3).
    "fsdp": sync_none,
}

# The reference parts, by name. "part4" extends the ladder beyond the
# reference: ZeRO-1 sharded optimizer (tpu_ddp/parallel/zero.py) — the
# sync is a reduce_scatter + all_gather pair folded into the optimizer,
# so it is not a (grads -> grads) strategy and the engine special-cases it.
PART_TO_STRATEGY = {
    "part1": "none",
    "part2a": "gather_scatter",
    "part2b": "all_reduce",
    "part3": "fused",
    "part4": "zero",
    "part5": "fsdp",
}


def canonical_strategy(name: str) -> str:
    """Resolve a part alias ('part4') to its strategy name ('zero').

    An unknown ``part*`` name raises immediately: passing it through
    (the old behavior) deferred the failure to ``get_sync_strategy``'s
    dict lookup — or, worse, to a caller that only compares the
    canonical name and silently treated 'part9' as a no-sync strategy.
    """
    if name in PART_TO_STRATEGY:
        return PART_TO_STRATEGY[name]
    if name.startswith("part"):
        raise ValueError(
            f"unknown part alias {name!r}; available parts: "
            f"{sorted(PART_TO_STRATEGY)}")
    return name


def get_sync_strategy(name: str):
    key = canonical_strategy(name)
    try:
        return SYNC_STRATEGIES[key]
    except KeyError:
        raise ValueError(
            f"unknown sync strategy {name!r}; available: "
            f"{sorted(SYNC_STRATEGIES)} or parts {sorted(PART_TO_STRATEGY)}"
        ) from None
