"""Expert parallelism: Switch-style mixture-of-experts over ``ep``.

No reference counterpart (the reference implements data parallelism only —
SURVEY.md §2 "Absent parallelism strategies"); included because multi-axis
model sharding is first-class in this framework. The layer is a top-k
routed MoE MLP — top-1 per Switch Transformers (Fedus et al.,
arXiv:2101.03961), top-2 per GShard (Lepikhin et al., arXiv:2006.16668);
both reimplemented from the papers' routing algebra, not from any code —
expressed the SPMD way:

- expert weights are STACKED on a leading expert axis and sharded over
  the ``ep`` mesh axis — each device hosts ``num_experts / ep`` experts;
- tokens are data-parallel over (dp × ep): every device routes its OWN
  tokens, builds a (tokens, experts, capacity) one-hot dispatch tensor,
  and two ``lax.all_to_all``s move token activations to their expert's
  host device and back — the ep-analogue of the pipeline's ppermute ring;
- capacity is static: ``C = ceil(T * capacity_factor * top_k / E)``
  slots per expert per source device, shared by a token's k choices.
  Assignments beyond an expert's capacity are dropped (that branch
  contributes zero; the residual stream still carries the token) — the
  standard static-shape trade XLA needs;
- the router is differentiable through the combine weights (the chosen
  expert's probability scales its output), and the Switch auxiliary
  load-balancing loss ``E * Σ_e f_e·P_e`` is returned alongside so the
  trainer can regularize routing collapse.

Gradient flow needs no custom rules: dispatch/combine are einsums against
a stop-gradient one-hot, and ``all_to_all`` transposes to the reverse
``all_to_all``. Exactness of the ep-sharded layer vs its single-device
execution is tested in tests/test_moe.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from tpu_ddp.parallel.mesh import EXPERT_AXIS


def topk_route(router_logits, num_experts: int, capacity: int,
               top_k: int = 1):
    """Top-k routing: (T, E) logits -> (dispatch, combine, aux).

    ``dispatch``: (T, E, C) one-hots of each kept token's (expert, slot)
    assignments — up to ``top_k`` per token. ``combine``: dispatch scaled
    by the router gates (the differentiable path into the router).
    ``aux``: load-balance loss over the FIRST choice (the Switch form).

    ``top_k == 1`` is Switch routing with the raw probability as gate;
    ``top_k > 1`` is the GShard scheme (arXiv:2006.16668 — reimplemented
    from the paper's algebra, not from any code): iterative argmax over
    masked probabilities, gates renormalized over the chosen experts,
    and later choices queue in an expert's capacity AFTER the slots the
    earlier choices kept (so slots never collide).
    """
    if not 1 <= top_k <= num_experts:
        raise ValueError(f"top_k={top_k} must be in [1, num_experts="
                         f"{num_experts}] (beyond E the argmax of the "
                         "fully-masked probabilities would silently "
                         "re-route everything to expert 0)")
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    remaining = probs
    onehots, gates = [], []
    for _ in range(top_k):
        expert = jnp.argmax(remaining, axis=-1)             # (T,)
        oh = jax.nn.one_hot(expert, num_experts,
                            dtype=jnp.float32)              # (T, E)
        onehots.append(oh)
        gates.append(jnp.sum(probs * oh, axis=-1))          # (T,)
        remaining = remaining * (1.0 - oh)
    if top_k == 1:
        weights = gates                                     # raw (Switch)
    else:
        denom = sum(gates) + 1e-9
        weights = [g / denom for g in gates]                # normalized

    base = jnp.zeros((num_experts,), jnp.float32)  # slots already taken
    dispatch = jnp.zeros((router_logits.shape[0], num_experts, capacity),
                         jnp.float32)
    combine = dispatch
    for oh, w in zip(onehots, weights):
        # Slot of each token within its expert's queue, in token order,
        # offset past the slots earlier choices kept.
        pos = (jnp.cumsum(oh, axis=0) - 1.0 + base[None, :]) * oh
        kept = oh * (pos < capacity)                        # (T, E)
        slot = jax.nn.one_hot(
            jnp.sum(pos * kept, axis=-1).astype(jnp.int32),
            capacity, dtype=jnp.float32)                    # (T, C)
        d = kept[:, :, None] * slot[:, None, :]             # (T, E, C)
        dispatch = dispatch + d
        combine = combine + lax.stop_gradient(d) * w[:, None, None]
        base = base + jnp.sum(kept, axis=0)
    # Load balance: fraction first-routed to e times mean prob of e.
    f = jnp.mean(onehots[0], axis=0)
    p = jnp.mean(probs, axis=0)
    aux = num_experts * jnp.sum(f * p)
    return lax.stop_gradient(dispatch), combine, aux


def switch_route(router_logits, num_experts: int, capacity: int):
    """Top-1 (Switch) routing — see :func:`topk_route`."""
    return topk_route(router_logits, num_experts, capacity, top_k=1)


def routing_stats(dispatch, top_k: int = 1):
    """Routing-health counters from a (T, E, C) dispatch tensor.

    ``dropped_frac``: fraction of the T*top_k routing assignments that
    found no capacity slot (those branches contribute zero; the token
    rides the residual stream). ``expert_load``: (E,) fraction of all
    assignments each expert kept — sums to ``1 - dropped_frac``.
    ``imbalance``: the hottest expert's load relative to the uniform
    share (1.0 = perfectly balanced; ``E`` = total collapse onto one
    expert). All float32, cheap enough to ride along every step.
    """
    t, e = dispatch.shape[0], dispatch.shape[1]
    per_expert = jnp.sum(dispatch, axis=(0, 2))             # (E,) kept
    total = jnp.float32(t * max(top_k, 1))
    load = per_expert / total
    return {"dropped_frac": 1.0 - jnp.sum(load),
            "expert_load": load,
            "imbalance": jnp.max(load) * e}


def moe_mlp(y, router_w, w1, w2, *, num_experts: int,
            capacity_factor: float = 1.25, top_k: int = 1,
            ep_axis: str = EXPERT_AXIS,
            ep_size: int = 1, activation=None,
            tp_in=None, tp_out=None, stats=None):
    """Top-k routed MoE MLP: (B, L, dm) -> ((B, L, dm), aux).

    ``w1``: (E_local, dm, dff_local), ``w2``: (E_local, dff_local, dm) —
    stacked expert weights, already sharded over ``ep`` (and optionally
    ``mp`` via the ``tp_in``/``tp_out`` Megatron hooks). Must run inside
    a shard_map over ``ep_axis`` when ``ep_size > 1``.

    ``stats``: optional mutable list; when given, this call appends its
    :func:`routing_stats` dict (per-shard numbers under ep — diagnostic
    callers run the dense configuration).
    """
    b, L, dm = y.shape
    T = b * L
    E = num_experts
    e_loc = w1.shape[0]
    if e_loc * max(ep_size, 1) != E:
        raise ValueError(f"{w1.shape[0]} local experts x ep={ep_size} "
                         f"!= num_experts={E}")
    # top_k choices per token share the capacity budget.
    cap = max(1, int(-(-T * capacity_factor * max(top_k, 1) // E)))
    act = activation or (lambda h: jax.nn.gelu(h.astype(jnp.float32)))
    cd = y.dtype

    x = y.reshape(T, dm)
    logits = jnp.dot(x, router_w.astype(cd),
                     preferred_element_type=jnp.float32)    # (T, E)
    dispatch, combine, aux = topk_route(logits, E, cap, top_k=top_k)
    if stats is not None:
        stats.append(routing_stats(dispatch, top_k=top_k))

    # (T, E, C) x (T, dm) -> (E, C, dm): gather each expert's slot queue.
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(cd), x,
                           preferred_element_type=jnp.float32).astype(cd)
    if ep_size > 1:
        # Exchange: split the expert axis across ep peers, concatenate
        # the per-source queues -> (E_local, ep*C, dm) on each device.
        expert_in = lax.all_to_all(expert_in, ep_axis, split_axis=0,
                                   concat_axis=1, tiled=True)
    h_in = tp_in(expert_in) if tp_in is not None else expert_in
    h = jnp.einsum("ecd,edf->ecf", h_in, w1.astype(cd),
                   preferred_element_type=jnp.float32)
    h = act(h).astype(cd)
    out = jnp.einsum("ecf,efd->ecd", h, w2.astype(cd),
                     preferred_element_type=jnp.float32)
    out = (tp_out(out) if tp_out is not None else out).astype(cd)
    if ep_size > 1:
        # Reverse exchange: every token's output returns to its source.
        out = lax.all_to_all(out, ep_axis, split_axis=1, concat_axis=0,
                             tiled=True)
    # (T, E, C) x (E, C, dm) -> (T, dm): weight by router prob; dropped
    # tokens (no slot) get zeros and ride the residual stream unchanged.
    y_out = jnp.einsum("tec,ecd->td", combine.astype(cd), out,
                       preferred_element_type=jnp.float32).astype(cd)
    return y_out.reshape(b, L, dm), aux
