"""Expert parallelism: Switch-style mixture-of-experts over ``ep``.

No reference counterpart (the reference implements data parallelism only —
SURVEY.md §2 "Absent parallelism strategies"); included because multi-axis
model sharding is first-class in this framework. The layer is a top-1
routed MoE MLP (Fedus et al., "Switch Transformers", arXiv:2101.03961 —
reimplemented from the paper's routing algebra, not from any code),
expressed the SPMD way:

- expert weights are STACKED on a leading expert axis and sharded over
  the ``ep`` mesh axis — each device hosts ``num_experts / ep`` experts;
- tokens are data-parallel over (dp × ep): every device routes its OWN
  tokens, builds a (tokens, experts, capacity) one-hot dispatch tensor,
  and two ``lax.all_to_all``s move token activations to their expert's
  host device and back — the ep-analogue of the pipeline's ppermute ring;
- capacity is static: ``C = ceil(T/E * capacity_factor)`` slots per
  expert per source device. Tokens beyond an expert's capacity are
  dropped (their MLP branch contributes zero; the residual stream still
  carries them) — the standard static-shape trade XLA needs;
- the router is differentiable through the combine weights (the chosen
  expert's probability scales its output), and the Switch auxiliary
  load-balancing loss ``E * Σ_e f_e·P_e`` is returned alongside so the
  trainer can regularize routing collapse.

Gradient flow needs no custom rules: dispatch/combine are einsums against
a stop-gradient one-hot, and ``all_to_all`` transposes to the reverse
``all_to_all``. Exactness of the ep-sharded layer vs its single-device
execution is tested in tests/test_moe.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from tpu_ddp.parallel.mesh import EXPERT_AXIS


def switch_route(router_logits, num_experts: int, capacity: int):
    """Top-1 routing: (T, E) logits -> (dispatch, combine, aux).

    ``dispatch``: (T, E, C) one-hot of (expert, slot) per kept token.
    ``combine``: dispatch scaled by the router probability (differentiable
    path into the router weights). ``aux``: Switch load-balance loss.
    """
    T = router_logits.shape[0]
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)                     # (T,)
    onehot = jax.nn.one_hot(expert, num_experts,
                            dtype=jnp.float32)              # (T, E)
    # Slot index of each token within its expert's queue, in token order.
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0         # (T, E)
    kept = onehot * (pos < capacity)                        # (T, E)
    slot = jax.nn.one_hot(jnp.sum(pos * kept, axis=-1).astype(jnp.int32),
                          capacity, dtype=jnp.float32)      # (T, C)
    dispatch = kept[:, :, None] * slot[:, None, :]          # (T, E, C)
    gate = jnp.sum(probs * onehot, axis=-1)                 # (T,)
    combine = lax.stop_gradient(dispatch) * gate[:, None, None]
    # Load balance: fraction routed to e times mean router prob of e.
    f = jnp.mean(onehot, axis=0)
    p = jnp.mean(probs, axis=0)
    aux = num_experts * jnp.sum(f * p)
    return lax.stop_gradient(dispatch), combine, aux


def moe_mlp(y, router_w, w1, w2, *, num_experts: int,
            capacity_factor: float = 1.25, ep_axis: str = EXPERT_AXIS,
            ep_size: int = 1, activation=None,
            tp_in=None, tp_out=None):
    """Switch MoE MLP: (B, L, dm) -> ((B, L, dm), aux).

    ``w1``: (E_local, dm, dff_local), ``w2``: (E_local, dff_local, dm) —
    stacked expert weights, already sharded over ``ep`` (and optionally
    ``mp`` via the ``tp_in``/``tp_out`` Megatron hooks). Must run inside
    a shard_map over ``ep_axis`` when ``ep_size > 1``.
    """
    b, L, dm = y.shape
    T = b * L
    E = num_experts
    e_loc = w1.shape[0]
    if e_loc * max(ep_size, 1) != E:
        raise ValueError(f"{w1.shape[0]} local experts x ep={ep_size} "
                         f"!= num_experts={E}")
    cap = max(1, int(-(-T * capacity_factor // E)))
    act = activation or (lambda h: jax.nn.gelu(h.astype(jnp.float32)))
    cd = y.dtype

    x = y.reshape(T, dm)
    logits = jnp.dot(x, router_w.astype(cd),
                     preferred_element_type=jnp.float32)    # (T, E)
    dispatch, combine, aux = switch_route(logits, E, cap)

    # (T, E, C) x (T, dm) -> (E, C, dm): gather each expert's slot queue.
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(cd), x,
                           preferred_element_type=jnp.float32).astype(cd)
    if ep_size > 1:
        # Exchange: split the expert axis across ep peers, concatenate
        # the per-source queues -> (E_local, ep*C, dm) on each device.
        expert_in = lax.all_to_all(expert_in, ep_axis, split_axis=0,
                                   concat_axis=1, tiled=True)
    h_in = tp_in(expert_in) if tp_in is not None else expert_in
    h = jnp.einsum("ecd,edf->ecf", h_in, w1.astype(cd),
                   preferred_element_type=jnp.float32)
    h = act(h).astype(cd)
    out = jnp.einsum("ecf,efd->ecd", h, w2.astype(cd),
                     preferred_element_type=jnp.float32)
    out = (tp_out(out) if tp_out is not None else out).astype(cd)
    if ep_size > 1:
        # Reverse exchange: every token's output returns to its source.
        out = lax.all_to_all(out, ep_axis, split_axis=1, concat_axis=0,
                             tiled=True)
    # (T, E, C) x (E, C, dm) -> (T, dm): weight by router prob; dropped
    # tokens (no slot) get zeros and ride the residual stream unchanged.
    y_out = jnp.einsum("tec,ecd->td", combine.astype(cd), out,
                       preferred_element_type=jnp.float32).astype(cd)
    return y_out.reshape(b, L, dm), aux
