"""MPMD pipeline: per-stage compiled programs over explicit edges.

The SPMD engines in ``parallel/pipeline.py`` express the pipeline as ONE
jitted program on one mesh — every device holds every stage's code, and
stage selection happens with ``lax.axis_index`` inside shard_map. That
is the right shape inside a slice (ICI-dense, one compiler view of the
whole step) and the wrong one across slices: a multi-slice pipeline
(PAPERS.md: *Scaling Deep Learning Training with MPMD Pipeline
Parallelism*, arXiv 2412.14374) wants each slice to compile ONLY its
stages' forward/backward against only its stages' params, with
activations and cotangents crossing slice boundaries as explicit DCN
transfers, not as ring collectives of a global program.

This module is that scale-out path:

- :class:`StageProgram` — one stage's jit-compiled forward/backward
  pair. Stage 0 owns the embedding, the last stage owns ln_f + head,
  every stage owns its contiguous slice of transformer blocks. The
  backward recomputes the stage forward under ``jax.vjp`` from the
  saved input (the same recompute trade as the SPMD 1F1B), so a stage
  keeps O(pp) saved inputs, never activations.
- :class:`InProcessEdge` / :class:`SocketEdge` — directed stage-to-stage
  channels. In-process edges back the CPU/test path and the intra-slice
  hops (``jax.device_put`` is the transport, a deque the buffer);
  socket edges back the multi-process drill (examples/mpmd_train.py),
  pickled numpy wires over TCP. Every edge owns an
  :class:`~tpu_ddp.parallel.compress.EdgeCodec`: fp32 on intra-slice
  hops, the round-7 bf16/int8(+error-feedback) wire formats on
  cross-slice hops — the DCN is the slow wire, so that is where the
  bytes matter (:class:`SliceTopology` decides which is which).
- :class:`MPMDPipeline` — the host-driven 1F1B loop over per-stage
  programs. The host owns the schedule (tick -> (stage, fwd mb, bwd
  mb)); JAX's async dispatch keeps stages' compute in flight while the
  host shuffles edge payloads, and a
  :class:`~tpu_ddp.train.pipeline.StageScheduler` accounts each
  stage's warmup/steady/cooldown ticks and bounds its in-flight window.
  Guard-skip stays host-side here: a non-finite loss skips the whole
  update (params untouched), mirroring the jit-side
  ``select_update`` contract of the SPMD rungs.

Numerics contract: with fp32 edges the MPMD step computes EXACTLY the
dense model's loss and gradients (tests/test_mpmd.py pins it against
the dense trainer the same way the SPMD schedules are pinned); with
compressed cross-slice edges the per-step gradient is lossy but the
error-feedback residual keeps the trajectory within the acceptance
envelope (scripts/bench_pipeline_schedules.py measures it).

Dropout is out of scope on this path (MPMD serves the scale-out bench
and drills; the SPMD engines carry the regularization story) — a model
with ``dropout_rate > 0`` is rejected at construction.
"""

from __future__ import annotations

import dataclasses
import pickle
import socket
import struct
from collections import deque
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from tpu_ddp.parallel.compress import EdgeCodec

__all__ = [
    "SliceTopology", "StageProgram", "InProcessEdge", "SocketEdge",
    "MPMDPipeline", "split_stage_params", "merge_stage_grads",
    "spmd_pipeline_hlo", "mega_edge_hlo",
]


# ---------------------------------------------------------------------------
# Topology: which stages live on which slice.
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SliceTopology:
    """Stage -> slice assignment; decides which edges cross DCN.

    ``stage_slice[s]`` is the slice id hosting stage ``s``. The edge
    ``s -> s+1`` is *cross-slice* iff the two ids differ — those edges
    get the compressed wire format, intra-slice edges stay fp32.
    """

    stage_slice: tuple

    def __post_init__(self):
        if not self.stage_slice:
            raise ValueError("empty topology")
        ids = list(self.stage_slice)
        if ids != sorted(ids):
            raise ValueError(
                f"stages must map to slices in order, got {ids}")

    @classmethod
    def single_slice(cls, pp_size: int) -> "SliceTopology":
        return cls(tuple(0 for _ in range(pp_size)))

    @classmethod
    def even(cls, pp_size: int, num_slices: int) -> "SliceTopology":
        """Contiguous stages split evenly over ``num_slices``."""
        if pp_size % num_slices:
            raise ValueError(f"pp={pp_size} not divisible by "
                             f"num_slices={num_slices}")
        per = pp_size // num_slices
        return cls(tuple(s // per for s in range(pp_size)))

    @property
    def pp_size(self) -> int:
        return len(self.stage_slice)

    def is_cross(self, boundary: int) -> bool:
        """True when edge ``boundary -> boundary+1`` crosses slices."""
        return (self.stage_slice[boundary]
                != self.stage_slice[boundary + 1])

    def cross_boundaries(self) -> list:
        return [b for b in range(self.pp_size - 1) if self.is_cross(b)]


# ---------------------------------------------------------------------------
# Per-stage parameter partition (linear stage layout).
# ---------------------------------------------------------------------------


def split_stage_params(params: dict, pp_size: int) -> list:
    """Stacked-param tree -> per-stage param dicts.

    Stage s owns block rows ``[s*Lps, (s+1)*Lps)``; stage 0 additionally
    owns ``embed``, the last stage ``ln_f`` + ``head``. Each returned
    dict references ONLY its stage's arrays — the property per-stage
    compilation exists for.
    """
    L = jax.tree.leaves(params["blocks"])[0].shape[0]
    if L % pp_size:
        raise ValueError(f"{L} layers not divisible by pp={pp_size}")
    lps = L // pp_size
    out = []
    for s in range(pp_size):
        p = {"blocks": jax.tree.map(
            lambda x: x[s * lps:(s + 1) * lps], params["blocks"])}
        if s == 0:
            p["embed"] = params["embed"]
        if s == pp_size - 1:
            p["ln_f"] = params["ln_f"]
            p["head"] = params["head"]
        out.append(p)
    return out


def merge_stage_grads(stage_grads: list) -> dict:
    """Inverse of :func:`split_stage_params` for gradient trees."""
    blocks = jax.tree.map(
        lambda *xs: jnp.concatenate(xs, axis=0),
        *[g["blocks"] for g in stage_grads])
    return {"embed": stage_grads[0]["embed"],
            "ln_f": stage_grads[-1]["ln_f"],
            "head": stage_grads[-1]["head"],
            "blocks": blocks}


# ---------------------------------------------------------------------------
# One stage's compiled programs.
# ---------------------------------------------------------------------------


class StageProgram:
    """Forward/backward jit pair for ONE pipeline stage.

    Four distinct compiled programs exist across a pipeline (first /
    middle / last stage shapes), each closed over only its stage's
    param structure — ``jit`` here is per-stage compilation, not a
    slice of a global program. Dropout keys would need the global layer
    index; the MPMD path runs eval-mode trunks (module docstring).
    """

    def __init__(self, model, stage: int, pp_size: int, seq_len: int):
        if pp_size < 2:
            raise ValueError("MPMD needs pp_size >= 2 (one stage is "
                             "just the dense model)")
        if model.dropout_rate > 0.0:
            raise ValueError("MPMD path does not support dropout; "
                             "use the SPMD schedules for regularized "
                             "training")
        model.check_seq_len(seq_len)
        self.model = model
        self.stage = stage
        self.pp_size = pp_size
        self.is_first = stage == 0
        self.is_last = stage == pp_size - 1
        pos = model._positions(seq_len)
        cd = model.compute_dtype

        def run_blocks(blocks, x):
            def body(h, layer):
                h, _ = model.block_apply_aux(layer, h, pos, None)
                return h, None
            h, _ = jax.lax.scan(body, x, blocks)
            return h

        def fwd_first(p, toks):
            x = p["embed"][toks].astype(cd)
            return run_blocks(p["blocks"], x)

        def fwd_mid(p, x):
            return run_blocks(p["blocks"], x.astype(cd))

        def loss_last(p, x, tgt):
            from tpu_ddp.ops.loss import softmax_cross_entropy
            y = run_blocks(p["blocks"], x.astype(cd))
            logits = self.model.head_apply(
                {"ln_f": p["ln_f"], "head": p["head"]}, y)
            nll = softmax_cross_entropy(
                logits.reshape(-1, logits.shape[-1]), tgt.reshape(-1))
            return jnp.sum(nll)

        if self.is_last:
            def bwd_last(p, x, tgt):
                (loss, (gp, dx)) = jax.value_and_grad(
                    loss_last, argnums=(0, 1))(p, x, tgt)
                return loss, gp, dx.astype(jnp.float32)
            self.bwd = jax.jit(bwd_last)
            self.fwd = None
        elif self.is_first:
            def bwd_first(p, toks, dy):
                _, vjp = jax.vjp(lambda q: fwd_first(q, toks), p)
                (gp,) = vjp(dy.astype(cd))
                return gp
            self.fwd = jax.jit(fwd_first)
            self.bwd = jax.jit(bwd_first)
        else:
            def bwd_mid(p, x, dy):
                _, vjp = jax.vjp(fwd_mid, p, x)
                gp, dx = vjp(dy.astype(cd))
                return gp, dx.astype(jnp.float32)
            self.fwd = jax.jit(fwd_mid)
            self.bwd = jax.jit(bwd_mid)


# ---------------------------------------------------------------------------
# Edges.
# ---------------------------------------------------------------------------


class InProcessEdge:
    """Directed stage channel inside one process.

    ``jax.device_put`` of the decoded payload is the transfer; the wire
    format still round-trips through the codec, so the compression
    numerics and the byte accounting are identical to the socket path
    (what tier-1 tests, the drill then exercises over real sockets).
    """

    def __init__(self, codec: EdgeCodec | None = None, device=None):
        self.codec = codec or EdgeCodec("none")
        self.device = device
        self._q: deque = deque()
        self.messages = 0

    def send(self, x) -> None:
        wire, _ = self.codec.encode(x)
        self._q.append(wire)
        self.messages += 1

    def recv(self):
        out = EdgeCodec.decode(self._q.popleft())
        if self.device is not None:
            out = jax.device_put(out, self.device)
        return out

    def __len__(self) -> int:
        return len(self._q)

    def stats(self) -> dict:
        return {"transport": type(self).__name__,
                "spec": self.codec.spec,
                "messages": self.messages,
                "wire_bytes": int(self.codec.bytes_sent),
                "dense_bytes": int(self.codec.bytes_dense),
                "ratio": round(self.codec.ratio, 3)}


class SocketEdge(InProcessEdge):
    """Stage channel over a connected TCP socket (the 2-process drill).

    Wire = 4-byte big-endian length + pickled dict of numpy arrays.
    One SocketEdge end sends, the peer's receives — construct a pair
    per direction. Blocking recv IS the schedule synchronization: a
    stage that needs an activation that has not arrived simply waits,
    which is exactly the 1F1B dependence order.
    """

    def __init__(self, sock: socket.socket,
                 codec: EdgeCodec | None = None, device=None):
        super().__init__(codec, device)
        self.sock = sock

    def send(self, x) -> None:
        wire, _ = self.codec.encode(x)
        host = {k: (np.asarray(v) if hasattr(v, "shape") else v)
                for k, v in wire.items()}
        blob = pickle.dumps(host, protocol=pickle.HIGHEST_PROTOCOL)
        self.sock.sendall(struct.pack(">I", len(blob)) + blob)
        self.messages += 1

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("edge peer closed")
            buf += chunk
        return buf

    def recv(self):
        (n,) = struct.unpack(">I", self._read_exact(4))
        wire = pickle.loads(self._read_exact(n))
        out = EdgeCodec.decode(wire)
        if self.device is not None:
            out = jax.device_put(out, self.device)
        return out


def build_edges(topology: SliceTopology, compress: str = "bf16",
                block_size: int = 256, devices=None) -> tuple:
    """(down, up) edge lists for an in-process pipeline.

    ``down[b]`` carries activations over boundary ``b`` (stage b ->
    b+1), ``up[b]`` cotangents back. Cross-slice boundaries get the
    ``compress`` wire format (each DIRECTION carries its own codec —
    error-feedback residuals are per-edge sender state); intra-slice
    boundaries stay fp32.
    """
    down, up = [], []
    for b in range(topology.pp_size - 1):
        spec = compress if topology.is_cross(b) else "none"
        dev_fwd = devices[b + 1] if devices is not None else None
        dev_bwd = devices[b] if devices is not None else None
        down.append(InProcessEdge(EdgeCodec(spec, block_size, seed=2 * b),
                                  device=dev_fwd))
        up.append(InProcessEdge(EdgeCodec(spec, block_size,
                                          seed=2 * b + 1),
                                device=dev_bwd))
    return down, up


# ---------------------------------------------------------------------------
# The host-driven 1F1B engine.
# ---------------------------------------------------------------------------


class MPMDPipeline:
    """All stages of an MPMD pipeline driven by one host loop.

    The single-process form (every ``StageProgram`` in this process,
    edges in-process) is the CPU/test path AND the template for the
    per-process form: :meth:`run_stage` executes ONE stage's tick loop
    against whatever edges it is handed, so a multi-process launch
    simply runs ``run_stage`` once per process with socket edges
    (examples/mpmd_train.py).
    """

    def __init__(self, model, pp_size: int, seq_len: int, *,
                 num_micro: int | None = None,
                 topology: SliceTopology | None = None,
                 compress: str = "bf16", block_size: int = 256,
                 optimizer=None, scheduler=None, devices=None):
        from tpu_ddp.ops.optim import SGD
        self.model = model
        self.pp_size = pp_size
        self.num_micro = num_micro if num_micro is not None else pp_size
        self.seq_len = seq_len
        self.topology = topology or SliceTopology.single_slice(pp_size)
        if self.topology.pp_size != pp_size:
            raise ValueError(
                f"topology covers {self.topology.pp_size} stages, "
                f"pipeline has {pp_size}")
        self.programs = [StageProgram(model, s, pp_size, seq_len)
                         for s in range(pp_size)]
        self.down, self.up = build_edges(self.topology, compress,
                                         block_size, devices=devices)
        self.optimizer = optimizer or SGD(learning_rate=0.1)
        self.scheduler = scheduler
        self.skipped_steps = 0
        # Test seam for the chaos drills: maps the harvested loss to
        # what the guard sees (inject NaN without breaking the math).
        self._chaos_hook: Callable[[float, int], float] | None = None
        self._step = 0

    # ---- schedule ------------------------------------------------------

    def ticks(self) -> int:
        return self.num_micro + 2 * (self.pp_size - 1)

    def run_stage(self, stage: int, params_s, micro_in, micro_tgt,
                  down_in, down_out, up_in, up_out) -> tuple:
        """One stage's full 1F1B tick loop; returns
        ``(grads_s, loss_sum)`` (loss_sum is 0.0 except on the last
        stage). ``micro_in``/``micro_tgt`` are the (M, mb, L) token /
        target arrays (first / last stage only); the four edge ends are
        whichever of this stage's channels exist (None at the pipeline
        ends).

        At tick t stage s forwards microbatch ``f = t - s`` and
        backwards ``b = t - 2(pp-1) + s`` — the same clocks as the SPMD
        1F1B — except the last stage fuses its forward+backward into
        one ``value_and_grad`` program (its f and b coincide).
        """
        S, M = self.pp_size, self.num_micro
        prog = self.programs[stage]
        saved: deque = deque()
        grads = None
        loss_sum = jnp.float32(0.0)
        sched = self.scheduler
        for t in range(self.ticks()):
            f = t - stage
            b = t - 2 * (S - 1) + stage
            f_valid = 0 <= f < M
            b_valid = 0 <= b < M
            did = False
            if prog.is_last:
                # forward+backward fused; f == b at the last stage
                if f_valid:
                    x = down_in.recv()
                    loss, gp, dx = prog.bwd(params_s, x,
                                            micro_tgt[f])
                    loss_sum = loss_sum + loss
                    grads = _tree_add(grads, gp)
                    up_out.send(dx)
                    did = True
            else:
                if f_valid:
                    if prog.is_first:
                        x = micro_in[f]
                    else:
                        x = down_in.recv()
                    saved.append(x)
                    down_out.send(prog.fwd(params_s, x))
                    did = True
                if b_valid:
                    dy = up_in.recv()
                    x = saved.popleft()
                    if prog.is_first:
                        gp = prog.bwd(params_s, x, dy)
                    else:
                        gp, dx = prog.bwd(params_s, x, dy)
                        up_out.send(dx)
                    grads = _tree_add(grads, gp)
                    did = True
            if sched is not None:
                sched.tick(stage, fwd=f_valid, bwd=b_valid,
                           handle=(jax.tree.leaves(grads)[0]
                                   if did and grads is not None
                                   else None))
        return grads, loss_sum

    # ---- single-process step ------------------------------------------

    def step_grads(self, params: dict, inputs, targets) -> tuple:
        """One MPMD step's (mean_loss, merged_grads) for a (B, L) batch.

        Stages interleave through the host loop: each tick touches
        every stage once (ascending), edges are FIFO, so the dataflow
        is identical to S concurrent per-process loops — just easier
        to test. Gradients come back in the dense model's layout,
        scaled to the mean-NLL normalization the dense trainer uses.
        """
        B, L = inputs.shape
        if B % self.num_micro:
            raise ValueError(f"batch {B} not divisible by "
                             f"num_micro={self.num_micro}")
        mb = B // self.num_micro
        micro = np.asarray(inputs, np.int32).reshape(
            self.num_micro, mb, L)
        tmicro = np.asarray(targets, np.int32).reshape(
            self.num_micro, mb, L)
        stage_params = split_stage_params(params, self.pp_size)

        S, M = self.pp_size, self.num_micro
        saved = [deque() for _ in range(S)]
        grads: list = [None] * S
        loss_sum = jnp.float32(0.0)
        sched = self.scheduler
        for t in range(self.ticks()):
            for s in range(S):
                prog = self.programs[s]
                f = t - s
                b = t - 2 * (S - 1) + s
                f_valid = 0 <= f < M
                b_valid = 0 <= b < M
                if prog.is_last:
                    if f_valid:
                        x = self.down[s - 1].recv() if s else micro[f]
                        loss, gp, dx = prog.bwd(stage_params[s], x,
                                                tmicro[f])
                        loss_sum = loss_sum + loss
                        grads[s] = _tree_add(grads[s], gp)
                        if s:
                            self.up[s - 1].send(dx)
                else:
                    if f_valid:
                        x = self.down[s - 1].recv() if s else micro[f]
                        saved[s].append(x)
                        self.down[s].send(
                            prog.fwd(stage_params[s], x))
                    if b_valid:
                        dy = self.up[s].recv()
                        x = saved[s].popleft()
                        if prog.is_first:
                            gp = prog.bwd(stage_params[s], x, dy)
                        else:
                            gp, dx = prog.bwd(stage_params[s], x, dy)
                            self.up[s - 1].send(dx)
                        grads[s] = _tree_add(grads[s], gp)
                if sched is not None:
                    sched.tick(s, fwd=f_valid, bwd=b_valid)
        assert all(len(q) == 0 for q in saved)
        assert all(len(e) == 0 for e in self.down + self.up)
        denom = jnp.float32(B * L)
        merged = merge_stage_grads(grads)
        merged = jax.tree.map(lambda g: g.astype(jnp.float32) / denom,
                              merged)
        return loss_sum / denom, merged

    # ---- training ------------------------------------------------------

    def init_state(self, params: dict):
        return self.optimizer.init(params)

    def train_step(self, params: dict, opt_state, inputs, targets,
                   guard=None) -> tuple:
        """(params, opt_state, loss, skipped) — guard-skip is HOST-side:
        a non-finite harvested loss leaves params/opt_state untouched
        (the no-op update the chaos drills assert), and ``guard``
        (resilience.guard.StepGuard) accounts the streak."""
        loss, grads = self.step_grads(params, inputs, targets)
        loss_f = float(np.asarray(loss))
        if self._chaos_hook is not None:
            loss_f = float(self._chaos_hook(loss_f, self._step))
        skipped = not np.isfinite(loss_f)
        if not skipped:
            mask = self.optimizer.decay_mask(params)
            params, opt_state = self.optimizer.apply(
                params, grads, opt_state, decay_mask=mask)
        else:
            self.skipped_steps += 1
        if guard is not None:
            guard.record(self._step, skipped, loss_f)
        if self.scheduler is not None:
            self.scheduler.step_done(self._step)
        self._step += 1
        return params, opt_state, loss_f, skipped

    def edge_stats(self) -> dict:
        return {
            "down": [e.stats() for e in self.down],
            "up": [e.stats() for e in self.up],
            "cross_boundaries": self.topology.cross_boundaries(),
            "skipped_steps": self.skipped_steps,
        }


def _tree_add(acc, g):
    if acc is None:
        return jax.tree.map(lambda x: x.astype(jnp.float32), g)
    return jax.tree.map(lambda a, x: a + x.astype(jnp.float32), acc, g)


# ---------------------------------------------------------------------------
# HLO overlap controls (utils/hlo_comm verdicts; round-10 satellite).
#
# The SPMD step IS the in-slice compiled artifact of this rung: its
# per-tick ppermutes are the edge collectives, and the overlap scanner
# must find them interleavable with stage compute. The negative control
# compiles the shape MPMD must NOT have — all stage compute first, then
# one concatenated mega-edge transfer — where every FLOP is an ancestor
# of the single collective and nothing can overlap.
# ---------------------------------------------------------------------------


def spmd_pipeline_hlo(model, mesh, num_micro: int, seq_len: int,
                      batch: int) -> str:
    """Compiled HLO of the SPMD 1F1B grad step on ``mesh`` (positive
    overlap control: per-tick edge ppermutes interleave with compute)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    from tpu_ddp.parallel.mesh import PIPE_AXIS
    from tpu_ddp.parallel.pipeline import (pipeline_1f1b_grads,
                                           pipeline_param_specs,
                                           stack_block_params)
    pp = mesh.shape[PIPE_AXIS]
    params = stack_block_params(model.init(jax.random.key(0)))
    specs = pipeline_param_specs(model)

    def step(p, x, y):
        def body(p, x, y):
            ls, n, g = pipeline_1f1b_grads(
                model, p, x, y, pp_size=pp, num_micro=num_micro)
            return ls[None], g
        return shard_map(body, mesh=mesh,
                         in_specs=(specs, P(), P()),
                         out_specs=(P(PIPE_AXIS), specs),
                         check_rep=False)(p, x, y)

    x = jnp.zeros((batch, seq_len), jnp.int32)
    p = jax.device_put(params, jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda s: isinstance(s, P)))
    return jax.jit(step).lower(p, x, x).compile().as_text()


def mega_edge_hlo(model, mesh, num_micro: int, seq_len: int,
                  batch: int) -> str:
    """Negative control: every microbatch's stage forward runs first,
    the activations concatenate into ONE mega ppermute, and the result
    feeds the loss — the single heavy transfer depends on ALL compute
    and feeds ALL remaining compute, so ``assert_overlap`` must fail."""
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    from tpu_ddp.parallel.mesh import PIPE_AXIS
    from tpu_ddp.parallel.pipeline import (pipeline_param_specs,
                                           stack_block_params)
    pp = mesh.shape[PIPE_AXIS]
    params = stack_block_params(model.init(jax.random.key(0)))
    specs = pipeline_param_specs(model)
    del num_micro  # the mega edge is schedule-free by construction
    perm = [(i, (i + 1) % pp) for i in range(pp)]
    pos = model._positions(seq_len)

    def body(p, x, y):
        cd = model.compute_dtype
        h = p["embed"][x].astype(cd)          # (B, L, dm)

        def layer_body(h, layer):
            h, _ = model.block_apply_aux(layer, h, pos, None)
            return h, None
        h, _ = lax.scan(layer_body, h, p["blocks"])
        # ALL microbatches' boundary activations in one transfer: the
        # anti-pattern (a GPipe-style bulk handoff) the per-tick
        # schedules exist to avoid.
        h = lax.ppermute(h.astype(jnp.float32), PIPE_AXIS, perm)
        logits = model.head_apply(
            {"ln_f": p["ln_f"], "head": p["head"]}, h.astype(cd))
        from tpu_ddp.ops.loss import softmax_cross_entropy
        nll = softmax_cross_entropy(
            logits.reshape(-1, logits.shape[-1]), y.reshape(-1))
        return jnp.sum(nll)[None]

    def step(p, x, y):
        return shard_map(body, mesh=mesh, in_specs=(specs, P(), P()),
                         out_specs=P(PIPE_AXIS), check_rep=False)(p, x, y)

    x = jnp.zeros((batch, seq_len), jnp.int32)
    p = jax.device_put(params, jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda s: isinstance(s, P)))
    return jax.jit(step).lower(p, x, x).compile().as_text()
