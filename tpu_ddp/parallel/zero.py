"""ZeRO-1 sharded optimizer — the fifth rung of the DP ladder.

No reference counterpart: the reference ladder stops at framework DDP
(part3, reference part3/main.py:13,174), where parameters, gradients and
optimizer state are fully replicated on every worker. This rung goes one
step beyond (Rajbhandari et al., "ZeRO: Memory Optimizations Toward
Training Trillion Parameter Models", arXiv:1910.02054 — reimplemented from
the paper's stage-1 partitioning scheme, not from any code): optimizer
state is sharded 1/N per data-parallel worker, and the gradient all-reduce
is split into its two halves —

    all_reduce == reduce_scatter + all_gather

- ``reduce_scatter`` (``lax.psum_scatter`` over the ``dp`` axis) hands each
  worker the SUM of one 1/N slice of every gradient — half the comm volume
  of an all-reduce, and the only slice this worker needs;
- each worker runs the (elementwise) optimizer update on its slice only —
  1/N of the update FLOPs and 1/N of the optimizer-state memory;
- ``all_gather`` (tiled) reassembles the updated parameters on every
  worker.

Total bytes on the wire per step equal part3's all-reduce (XLA lowers both
halves onto ICI), so throughput matches the fused strategy while optimizer
memory drops from O(P) to O(P/N) per device — the property that matters
once P stops fitting in HBM. Numerical equivalence with the fused rung is
tested in tests/test_zero.py.

Leaves are flattened and zero-padded to a multiple of the axis size so
every worker owns an equal contiguous slice; the padding tail receives
zero gradients and never leaves the pad region (elementwise update of a
zero-init, zero-grad slice stays zero under SGD/AdamW's decay-free tail).
Because flattening erases leaf ranks, the wrapper computes AdamW's
weight-decay mask from the ORIGINAL leaf shapes and passes it through
(``decay_mask`` in tpu_ddp/ops/optim.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from tpu_ddp.parallel.mesh import DATA_AXIS


class _LeafMeta:
    """Shape/dtype/rank of an original leaf; deliberately NOT a pytree
    node so it travels tree.maps as a leaf."""

    def __init__(self, t):
        self.shape = tuple(t.shape)
        self.dtype = t.dtype
        self.ndim = len(self.shape)
        self.size = 1
        for d in self.shape:
            self.size *= int(d)


class _FlatLayout:
    """Shared flat-padded layout machinery: leaves flatten to
    (ceil(size/N)*N,) and pad with zeros so every worker owns an equal
    contiguous slice. ``self.meta`` (from a params template) is the
    single source of truth for the original shapes, and makes the
    checkpoint representation CANONICAL — flat layouts never reach disk,
    so a checkpoint restores at any dp size or into a replicated
    trainer."""

    def _chunk(self, size: int) -> int:
        return -(-size // self.axis_size)  # ceil div

    def _require_meta(self):
        if getattr(self, "meta", None) is None:
            raise ValueError(f"{type(self).__name__} needs a params "
                             "template for layout conversions")

    def shard_params(self, params):
        """Canonical-shape tree -> global flat padded tree (place with
        ``P(dp)``); host-side at init/restore time. Deliberately numpy:
        the full-size tree must stay HOST-resident until device_put
        shards it — a jnp pad would commit every unsharded leaf to one
        device first, the exact OOM FSDP exists to avoid."""
        self._require_meta()

        def flat(p, m):
            pad = self._chunk(m.size) * self.axis_size - m.size
            return np.pad(np.asarray(p).reshape(-1), (0, pad))
        return jax.tree.map(flat, params, self.meta)

    def unshard_host(self, host_tree):
        """Host flat padded arrays -> canonical shapes (checkpoint
        write path)."""
        self._require_meta()
        return jax.tree.map(
            lambda x, m: np.asarray(x)[:m.size].reshape(m.shape),
            host_tree, self.meta)

    def canonicalize_opt_host(self, state):
        """Flat host optimizer state -> canonical shapes per leaf."""
        return self.inner.map_param_like(state, self.unshard_host)

    def flatten_opt(self, state):
        """Canonical optimizer state -> flat padded (restore path)."""
        return self.inner.map_param_like(state, self.shard_params)


class ZeRO1(_FlatLayout):
    """Wrap an elementwise optimizer; shard its state over ``axis_name``.

    ``init``/``state_specs`` run OUTSIDE shard_map (global view: every
    state leaf is a flat (padded_size,) array, sharded over the axis);
    ``apply`` runs INSIDE the shard_map'd train step on UNSYNCED local
    gradients — the reduce-scatter it performs IS the gradient sync.
    """

    def __init__(self, inner, axis_name: str = DATA_AXIS,
                 axis_size: int | None = None, template=None):
        if axis_size is None or axis_size < 1:
            raise ValueError("ZeRO1 needs the static dp axis size")
        self.inner = inner
        self.axis_name = axis_name
        self.axis_size = axis_size
        # Optional: enables canonical checkpoint layout conversions.
        self.meta = (jax.tree.map(_LeafMeta, template)
                     if template is not None else None)

    def init(self, params):
        """Global flat state: inner state over (padded_size,) zero leaves."""
        flat = jax.tree.map(
            lambda p: jnp.zeros((self._chunk(p.size) * self.axis_size,),
                                p.dtype), params)
        return self.inner.init(flat)

    def state_specs(self, param_specs=None):
        """Every (flat) state leaf shards over the dp axis; scalars (e.g.
        AdamW's step count) stay replicated — the inner optimizer's
        state_specs decides which is which."""
        return self.inner.state_specs(P(self.axis_name))

    def apply(self, params, grads, opt_state):
        """One sharded step. Call inside shard_map over ``axis_name`` with
        ``grads`` UNSYNCED; returns (new_params, new_state) with params
        full-size and synchronized (identical on every worker)."""
        ax, n = self.axis_name, self.axis_size
        idx = lax.axis_index(ax)

        def grad_slice(g):
            chunk = self._chunk(g.size)
            flat = jnp.pad(g.reshape(-1), (0, chunk * n - g.size))
            # SUM of this slice across workers, then mean over replicas —
            # the ladder's all_reduce semantics, half delivered here, half
            # by the all_gather below.
            return lax.psum_scatter(flat.reshape(n, chunk), ax,
                                    scatter_dimension=0) / n

        def param_slice(p):
            chunk = self._chunk(p.size)
            flat = jnp.pad(p.reshape(-1), (0, chunk * n - p.size))
            return lax.dynamic_slice_in_dim(flat, idx * chunk, chunk)

        g_sh = jax.tree.map(grad_slice, grads)
        p_sh = jax.tree.map(param_slice, params)
        # The decay policy must be evaluated on the ORIGINAL leaves (the
        # flat slices are all rank-1), so query the inner optimizer for
        # its mask rather than re-implementing its rule here.
        mask = self.inner.decay_mask(params)
        new_p_sh, new_state = self.inner.apply(p_sh, g_sh, opt_state,
                                               decay_mask=mask)

        def reassemble(p, sh):
            full = lax.all_gather(sh.astype(p.dtype), ax, tiled=True)
            return full[:p.size].reshape(p.shape)

        return jax.tree.map(reassemble, params, new_p_sh), new_state


class ZeRO3(_FlatLayout):
    """Fully-sharded parameters — FSDP / ZeRO stage 3 (part5).

    One step beyond :class:`ZeRO1`: PARAMETERS (not just optimizer state)
    live as flat 1/N shards per data-parallel worker; per-device
    parameter memory is O(P/N) at rest. Inside the train step the full
    parameters exist only transiently:

    - forward: each leaf is ``all_gather``'d (tiled) and reshaped to its
      true shape — exactly the on-demand materialization FSDP does;
    - backward: autodiff's transpose of that ``all_gather`` is
      ``psum_scatter``, so the gradient arrives ALREADY reduce-scattered
      into this worker's shard — the ZeRO gradient sync falls out of the
      chain rule with no explicit collective;
    - update: the (elementwise) optimizer touches only the local shard,
      with the weight-decay policy evaluated on the ORIGINAL leaf ranks.

    The backward psum_scatter SUMS over workers, so the trainer divides
    the shard gradient by N to recover the replica mean (same algebra as
    :class:`ZeRO1.apply`'s ``/ n``).
    """

    def __init__(self, inner, axis_name: str = DATA_AXIS,
                 axis_size: int | None = None, template=None):
        if axis_size is None or axis_size < 1:
            raise ValueError("ZeRO3 needs the static dp axis size")
        if template is None:
            raise ValueError("ZeRO3 needs a params template "
                             "(shapes/dtypes of the original leaves)")
        self.inner = inner
        self.axis_name = axis_name
        self.axis_size = axis_size
        # Shape/dtype per leaf, wrapped in an unregistered type so the
        # metadata rides pytrees as LEAVES; rank drives the decay policy.
        self.meta = jax.tree.map(_LeafMeta, template)

    def init(self, flat_params):
        return self.inner.init(flat_params)

    def state_specs(self, param_specs=None):
        return self.inner.state_specs(P(self.axis_name))

    def gather_params(self, flat_local):
        """INSIDE shard_map: local (chunk,) shards -> full-shape leaves.
        Differentiable; the transpose reduce-scatters cotangents."""
        def full(sh, meta):
            g = lax.all_gather(sh, self.axis_name, tiled=True)
            return g[:meta.size].reshape(meta.shape)
        return jax.tree.map(full, flat_local, self.meta)

    def decay_mask(self):
        """Inner optimizer's policy on the ORIGINAL ranks (flat shards
        are all rank-1; _LeafMeta exposes .ndim for the policy)."""
        return self.inner.decay_mask(self.meta)

    def apply(self, flat_params, flat_grads, opt_state):
        """Shard-local update; grads must already be the psum_scatter'd
        shards divided by the axis size (the trainer's job)."""
        return self.inner.apply(flat_params, flat_grads, opt_state,
                                decay_mask=self.decay_mask())
