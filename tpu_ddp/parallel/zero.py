"""ZeRO-1 sharded optimizer — the fifth rung of the DP ladder.

No reference counterpart: the reference ladder stops at framework DDP
(part3, reference part3/main.py:13,174), where parameters, gradients and
optimizer state are fully replicated on every worker. This rung goes one
step beyond (Rajbhandari et al., "ZeRO: Memory Optimizations Toward
Training Trillion Parameter Models", arXiv:1910.02054 — reimplemented from
the paper's stage-1 partitioning scheme, not from any code): optimizer
state is sharded 1/N per data-parallel worker, and the gradient all-reduce
is split into its two halves —

    all_reduce == reduce_scatter + all_gather

- ``reduce_scatter`` (``lax.psum_scatter`` over the ``dp`` axis) hands each
  worker the SUM of one 1/N slice of every gradient — half the comm volume
  of an all-reduce, and the only slice this worker needs;
- each worker runs the (elementwise) optimizer update on its slice only —
  1/N of the update FLOPs and 1/N of the optimizer-state memory;
- ``all_gather`` (tiled) reassembles the updated parameters on every
  worker.

Total bytes on the wire per step equal part3's all-reduce (XLA lowers both
halves onto ICI), so throughput matches the fused strategy while optimizer
memory drops from O(P) to O(P/N) per device — the property that matters
once P stops fitting in HBM. Numerical equivalence with the fused rung is
tested in tests/test_zero.py.

Leaves are flattened and zero-padded to a multiple of the axis size so
every worker owns an equal contiguous slice; the padding tail receives
zero gradients and never leaves the pad region (elementwise update of a
zero-init, zero-grad slice stays zero under SGD/AdamW's decay-free tail).
Because flattening erases leaf ranks, the wrapper computes AdamW's
weight-decay mask from the ORIGINAL leaf shapes and passes it through
(``decay_mask`` in tpu_ddp/ops/optim.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from tpu_ddp.parallel.mesh import DATA_AXIS


class _LeafMeta:
    """Shape/dtype/rank of an original leaf; deliberately NOT a pytree
    node so it travels tree.maps as a leaf."""

    def __init__(self, t):
        self.shape = tuple(t.shape)
        self.dtype = t.dtype
        self.ndim = len(self.shape)
        self.size = 1
        for d in self.shape:
            self.size *= int(d)


class _LeafPart:
    """Model-parallel partition of one leaf: which dims are sharded over
    which non-dp mesh axes — in MAJOR-to-minor order (= the spec's dim
    order) — and the resulting LOCAL geometry. NOT a pytree node
    (travels tree.maps as a leaf). ``None`` in the part tree means the
    leaf is replicated over every non-dp axis.

    Round-4 generalization: a leaf may shard SEVERAL dims, each over one
    mesh axis (pipeline-stacked tp leaves are P(pp, ..., mp); MoE expert
    leaves are P(ep, ..., mp)) — the flat state lays the R1*R2*...
    model-parallel cells out row-major, spec ``P((ax1, ax2, ..., dp))``.
    """

    def __init__(self, parts: tuple, local_shape: tuple):
        self.parts = tuple(parts)   # ((mesh_axis, leaf_dim, size), ...)
        self.local_shape = local_shape
        self.local_size = 1
        for d in local_shape:
            self.local_size *= int(d)
        self.count = 1              # total model-parallel cells
        for _, _, r in self.parts:
            self.count *= int(r)

    @property
    def axes(self) -> tuple:
        """Mesh axis names, major to minor."""
        return tuple(a for a, _, _ in self.parts)


def _leaf_partition(spec, meta: _LeafMeta, mesh_axis_sizes: dict,
                    dp_axis: str):
    """Partition info for one leaf from its PartitionSpec, or None when
    the leaf is replicated (or every sharding axis has extent 1). Each
    sharded dim must map to exactly ONE mesh axis — a single dim split
    over multiple axes is refused loudly rather than silently
    mis-sliced."""
    parts = []
    for d, entry in enumerate(tuple(spec)):
        if entry is None:
            continue
        axes = tuple(entry) if isinstance(entry, (tuple, list)) else (entry,)
        for a in axes:
            if a == dp_axis:
                raise NotImplementedError(
                    f"ZeRO cannot wrap a leaf already sharded over its "
                    f"own axis {dp_axis!r} (spec {spec})")
        if len(axes) > 1:
            raise NotImplementedError(
                f"ZeRO supports one mesh axis per sharded leaf dim "
                f"(got spec {spec})")
        ax = axes[0]
        r = int(mesh_axis_sizes[ax])
        if r == 1:
            continue
        if meta.shape[d] % r:
            raise ValueError(f"leaf dim {d} of shape {meta.shape} not "
                             f"divisible by {ax}={r}")
        parts.append((ax, d, r))
    if not parts:
        return None
    local = list(meta.shape)
    for _, d, r in parts:
        local[d] //= r
    return _LeafPart(tuple(parts), tuple(local))


def _part_cells(arr, part: _LeafPart) -> list:
    """Slice one canonical host leaf into its model-parallel cells, in
    the row-major (major-axis-first) order the flat layout uses."""
    cells = [np.asarray(arr)]
    for _, dim, count in part.parts:
        cells = [piece for c in cells
                 for piece in np.split(c, count, axis=dim)]
    return cells


def _part_assemble(cells: list, part: _LeafPart):
    """Inverse of :func:`_part_cells`: row-major cell list -> canonical
    leaf."""
    for _, dim, count in reversed(part.parts):
        cells = [np.concatenate(cells[i:i + count], axis=dim)
                 for i in range(0, len(cells), count)]
    return cells[0]


class _FlatLayout:
    """Shared flat-padded layout machinery: leaves flatten to
    (ceil(size/N)*N,) and pad with zeros so every worker owns an equal
    contiguous slice. ``self.meta`` (from a params template) is the
    single source of truth for the original shapes, and makes the
    checkpoint representation CANONICAL — flat layouts never reach disk,
    so a checkpoint restores at any dp size or into a replicated
    trainer.

    With ``param_specs`` + ``mesh_axis_sizes`` the layout is PARTITION-
    AWARE: a model-parallel-sharded leaf (tp/ep/pp-stacked) splits into
    its cells FIRST (row-major over the part's axes), each cell then
    flattening and padding to dp * chunk — the ``P((mp..., dp))``
    placement order — so each model-parallel cell holds the flat
    dp-sharded layout of ITS slice only."""

    def _init_layout(self, template, param_specs=None,
                     mesh_axis_sizes: dict | None = None):
        """Set ``self.meta`` (original shapes) and ``self.part``
        (per-leaf model-parallel partitions, None = replicated)."""
        self.meta = (jax.tree.map(_LeafMeta, template)
                     if template is not None else None)
        if param_specs is not None:
            if self.meta is None:
                raise ValueError(f"{type(self).__name__} with param_specs"
                                 " needs a params template (global leaf "
                                 "shapes)")
            if mesh_axis_sizes is None:
                raise ValueError(f"{type(self).__name__} with param_specs"
                                 " needs mesh_axis_sizes")
            self.part = jax.tree.map(
                lambda s, m: _leaf_partition(s, m, mesh_axis_sizes,
                                             self.axis_name),
                param_specs, self.meta,
                is_leaf=lambda x: isinstance(x, P))
        else:
            self.part = (jax.tree.map(lambda m: None, self.meta)
                         if self.meta is not None else None)

    def _chunk(self, size: int) -> int:
        return -(-size // self.axis_size)  # ceil div

    def _require_meta(self):
        if getattr(self, "meta", None) is None:
            raise ValueError(f"{type(self).__name__} needs a params "
                             "template for layout conversions")

    def _part_leaves(self, n: int) -> list:
        """Flattened partition list aligned with the meta/params leaf
        order (None entries must survive flattening, hence the is_leaf)."""
        if getattr(self, "part", None) is None:
            return [None] * n
        return jax.tree.leaves(
            self.part,
            is_leaf=lambda x: x is None or isinstance(x, _LeafPart))

    def _flat_leaf(self, p, m: _LeafMeta):
        """One canonical leaf -> flat zero-padded (chunk * N,)."""
        pad = self._chunk(m.size) * self.axis_size - m.size
        return np.pad(np.asarray(p).reshape(-1), (0, pad))

    def _unflat_leaf(self, x, m: _LeafMeta):
        """One flat padded array -> its canonical shape."""
        return np.asarray(x)[:m.size].reshape(m.shape)

    def shard_params(self, params):
        """Canonical-shape tree -> global flat padded tree (place with
        the flat specs); host-side at init/restore time. Deliberately
        numpy: the full-size tree must stay HOST-resident until
        device_put shards it — a jnp pad would commit every unsharded
        leaf to one device first, the exact OOM FSDP exists to avoid.
        Partitioned leaves split into model-parallel cells first (the
        ``P((mp..., dp))`` placement order)."""
        self._require_meta()
        p_l, treedef = jax.tree.flatten(params)
        m_l = jax.tree.leaves(self.meta)
        out = []
        for p, m, pt in zip(p_l, m_l, self._part_leaves(len(p_l))):
            if pt is None:
                out.append(self._flat_leaf(p, m))
            else:
                chunk = self._chunk(pt.local_size)
                pad = chunk * self.axis_size - pt.local_size
                out.append(np.concatenate(
                    [np.pad(c.reshape(-1), (0, pad))
                     for c in _part_cells(p, pt)]))
        return treedef.unflatten(out)

    def unshard_host(self, host_tree):
        """Host flat padded arrays -> canonical shapes (checkpoint
        write path); inverse of :meth:`shard_params`."""
        self._require_meta()
        x_l, treedef = jax.tree.flatten(host_tree)
        m_l = jax.tree.leaves(self.meta)
        out = []
        for x, m, pt in zip(x_l, m_l, self._part_leaves(len(x_l))):
            if pt is None:
                out.append(self._unflat_leaf(x, m))
            else:
                rows = np.asarray(x).reshape(pt.count, -1)
                out.append(_part_assemble(
                    [r[:pt.local_size].reshape(pt.local_shape)
                     for r in rows], pt))
        return treedef.unflatten(out)

    def canonicalize_opt_host(self, state):
        """Flat host optimizer state -> canonical shapes per leaf."""
        return self.inner.map_param_like(state, self.unshard_host)

    def flatten_opt(self, state):
        """Canonical optimizer state -> flat padded (restore path)."""
        return self.inner.map_param_like(state, self.shard_params)

    def shard_zeros(self, params):
        """f32 zero tree shaped like :meth:`scatter_grads` output — the
        ZeRO-2 accumulation buffer (1/N of each local leaf per worker).
        ``params`` are the LOCAL leaves seen inside shard_map."""
        return jax.tree.map(
            lambda p: jnp.zeros((self._chunk(p.size),), jnp.float32),
            params)

    def scatter_grads(self, grads):
        """INSIDE shard_map: reduce-scatter each leaf over the flat
        layout's axis — this worker's 1/N slice of the axis-MEAN
        gradient, in f32. The ZeRO-2 building block (a trainer
        accumulating microbatch gradients sums THESE slices), and the
        1F1B x FSDP bridge (full stage-local grads -> the flat shards
        ZeRO3.apply consumes). Works on LOCAL leaves: each leaf's
        chunking derives from its local size, matching the flat state
        layout cell by cell."""
        ax, n = self.axis_name, self.axis_size

        def slc(g):
            chunk = self._chunk(g.size)
            flat = jnp.pad(g.astype(jnp.float32).reshape(-1),
                           (0, chunk * n - g.size))
            return lax.psum_scatter(flat.reshape(n, chunk), ax,
                                    scatter_dimension=0) / n
        return jax.tree.map(slc, grads)


class ZeRO1(_FlatLayout):
    """Wrap an elementwise optimizer; shard its state over ``axis_name``.

    ``init``/``state_specs`` run OUTSIDE shard_map (global view: every
    state leaf is a flat (padded_size,) array, sharded over the axis);
    ``apply`` runs INSIDE the shard_map'd train step on UNSYNCED local
    gradients — the reduce-scatter it performs IS the gradient sync.

    Composes with tensor/expert parallelism (round-3 verdict item 6):
    pass ``param_specs`` + ``mesh_axis_sizes`` and each mp/ep-sharded
    leaf's optimizer state is laid out as (R * dp * chunk,) sharded
    ``P((mp, dp))`` — R model-parallel cells, each holding the flat
    dp-sharded state of ITS tp slice. Inside shard_map ``apply`` only
    ever sees local leaves, so the sharded update is IDENTICAL for
    replicated and tp-sharded leaves; only the global layout, the spec
    tree, and the checkpoint conversions are partition-aware.
    """

    def __init__(self, inner, axis_name: str = DATA_AXIS,
                 axis_size: int | None = None, template=None,
                 param_specs=None, mesh_axis_sizes: dict | None = None):
        if axis_size is None or axis_size < 1:
            raise ValueError("ZeRO1 needs the static dp axis size")
        self.inner = inner
        self.axis_name = axis_name
        self.axis_size = axis_size
        # Template (optional) enables canonical checkpoint layout
        # conversions; param_specs additionally makes the layout
        # partition-aware (tp/ep/pp-stacked leaves).
        self._init_layout(template, param_specs, mesh_axis_sizes)

    def decay_mask(self, params):
        """Inner optimizer's policy, passed through so trainers that
        override the mask (pipeline stacked leaves) can query the
        wrapper like they would the bare optimizer."""
        return self.inner.decay_mask(params)

    def init(self, params):
        """Global flat state: inner state over (R * padded_local,) zero
        leaves (R = 1 for leaves with no model-parallel partition)."""
        p_l, treedef = jax.tree.flatten(params)

        def zeros(p, pt):
            r = pt.count if pt is not None else 1
            chunk = self._chunk(pt.local_size if pt is not None
                                else p.size)
            return jnp.zeros((r * chunk * self.axis_size,), p.dtype)
        flat = treedef.unflatten(
            [zeros(p, pt) for p, pt
             in zip(p_l, self._part_leaves(len(p_l)))])
        return self.inner.init(flat)

    def state_specs(self, param_specs=None):
        """Flat state leaves shard over the dp axis — model-parallel
        partitioned leaves over ``P((mp..., dp))`` (major axes first,
        matching the layout's row-major cell order); scalars (e.g.
        AdamW's step count) stay replicated — the inner optimizer's
        state_specs decides which is which."""
        if self.meta is None:
            return self.inner.state_specs(P(self.axis_name))
        m_l, treedef = jax.tree.flatten(self.meta)
        pt_l = self._part_leaves(len(m_l))
        if all(pt is None for pt in pt_l):
            return self.inner.state_specs(P(self.axis_name))
        specs = treedef.unflatten(
            [P((*pt.axes, self.axis_name)) if pt is not None
             else P(self.axis_name) for pt in pt_l])
        return self.inner.state_specs(specs)

    def apply(self, params, grads, opt_state, decay_mask=None,
              clip_norm=None):
        """One sharded step. Call inside shard_map over ``axis_name`` with
        ``grads`` UNSYNCED; returns (new_params, new_state) with params
        full-size and synchronized (identical on every worker).

        ``decay_mask`` overrides the inner optimizer's policy — needed by
        callers whose LOCAL leaves are re-laid-out (the pipeline trainer's
        stacked blocks raise every leaf's rank by one, which would
        otherwise weight-decay the (L, dm) LayerNorm scales)."""
        ax, n = self.axis_name, self.axis_size

        def grad_slice(g):
            chunk = self._chunk(g.size)
            flat = jnp.pad(g.reshape(-1), (0, chunk * n - g.size))
            # SUM of this slice across workers, then mean over replicas —
            # the ladder's all_reduce semantics, half delivered here, half
            # by the all_gather in apply_scattered.
            return lax.psum_scatter(flat.reshape(n, chunk), ax,
                                    scatter_dimension=0) / n

        return self.apply_scattered(params, jax.tree.map(grad_slice, grads),
                                    opt_state, decay_mask=decay_mask,
                                    clip_norm=clip_norm)

    def apply_scattered(self, params, g_sh, opt_state, decay_mask=None,
                        clip_norm=None):
        """The second half of :meth:`apply`: update from gradient slices
        that are ALREADY reduce-scattered over dp (``scatter_grads`` or
        a ZeRO-2 accumulation of them).

        ``clip_norm``: optional global-norm gradient clip, computed from
        the slices — each slice's squared sum is psum'd over dp AND over
        the leaf's model-parallel axes (distinct cells hold distinct
        elements; replicated-leaf slices are identical across mp, so only
        their dp psum counts them once), giving every device the exact
        global norm before any slice is scaled."""
        ax, n = self.axis_name, self.axis_size
        idx = lax.axis_index(ax)

        if clip_norm is not None:
            g_l = jax.tree.leaves(g_sh)
            parts = self._part_leaves(len(g_l))
            # One psum per distinct axis set (leaves with the same
            # partition share a reduction), not one per leaf.
            groups: dict = {}
            for g, pt in zip(g_l, parts):
                axes = (ax,) + (pt.axes if pt is not None else ())
                groups.setdefault(axes, []).append(
                    jnp.sum(jnp.square(g.astype(jnp.float32))))
            sq = 0.0
            for axes, sums in groups.items():
                sq = sq + lax.psum(sum(sums), axes)
            from tpu_ddp.ops.optim import clip_scale_from_sq, clip_tree
            g_sh = clip_tree(g_sh, clip_scale_from_sq(sq, clip_norm))

        def param_slice(p):
            chunk = self._chunk(p.size)
            flat = jnp.pad(p.reshape(-1), (0, chunk * n - p.size))
            return lax.dynamic_slice_in_dim(flat, idx * chunk, chunk)

        p_sh = jax.tree.map(param_slice, params)
        # The decay policy must be evaluated on the ORIGINAL leaves (the
        # flat slices are all rank-1), so query the inner optimizer for
        # its mask rather than re-implementing its rule here.
        mask = (decay_mask if decay_mask is not None
                else self.inner.decay_mask(params))
        new_p_sh, new_state = self.inner.apply(p_sh, g_sh, opt_state,
                                               decay_mask=mask)

        def reassemble(p, sh):
            full = lax.all_gather(sh.astype(p.dtype), ax, tiled=True)
            return full[:p.size].reshape(p.shape)

        return jax.tree.map(reassemble, params, new_p_sh), new_state


class CellAdafactor:
    """Adafactor over model-parallel-sharded leaves — PER-CELL factoring
    (round-5; the T5X semantic: each mp/ep/pp cell maintains row/column
    moments of its OWN local slice).

    The bare :class:`~tpu_ddp.ops.optim.Adafactor` refuses sharded
    parameter leaves: its factored moments have reduced shapes, and a
    cell's row/column means are means over the LOCAL slice — there is
    no global array those per-cell factors are a plain slice of (the
    "split"-plan flattening mixes sharded dims into the view, and the
    reduction that built ``vr`` erased the very axis ``mp`` shards).
    This wrapper makes the per-cell layout explicit instead:

    - UPDATE: inside shard_map every parameter leaf already IS its
      local cell, so each cell simply runs Adafactor's per-leaf update
      on its slice — factoring plan, update-RMS clip and relative step
      size all per-cell, zero collectives added. Exactly "dense
      Adafactor run on the sliced parameter tree" (tests/
      test_adafactor.py pins that ground truth, which is NOT the dense
      run's factored state sliced).
    - STATE LAYOUT: reduced-shape state (``vr``/``vc``) gains one
      leading cell axis per sharding mesh axis — global
      ``(R1, ..., *cell_state_shape)`` sharded ``P(ax1, ...)`` — so
      each cell's shard_map block is its own state (leading singletons
      squeezed in ``apply``). Param-shaped state (unfactored ``v``,
      momentum ``mu``) keeps the parameter's own spec: its local block
      already aligns with the cell. Replicated leaves take the bare
      optimizer's layout unchanged. State is replicated over dp
      (:class:`FactoredZeRO1` additionally shards it 1/dp).

    Checkpoint note: per-cell factored moments are coupled to the mesh
    partitioning (as in T5X) — the state restores exactly into the
    SAME tp/ep/pp layout; a different layout fails the restore shape
    check loudly (utils/checkpoint.py). Parameters are full-size and
    restore anywhere.
    """

    def __init__(self, inner, template, param_specs,
                 mesh_axis_sizes: dict):
        from tpu_ddp.ops.optim import Adafactor
        if not isinstance(inner, Adafactor):
            raise ValueError(
                "CellAdafactor wraps Adafactor (per-cell factored "
                "state); elementwise optimizers already shard state in "
                "their parameter's own spec")
        self.inner = inner
        self.meta = jax.tree.map(_LeafMeta, template)
        self._param_specs = param_specs
        # dp_axis="": EVERY spec axis is a model-parallel cell axis here
        # (never matches a real axis name, so nothing is refused as dp);
        # sharding the state over dp as well is FactoredZeRO1's job.
        self.part = jax.tree.map(
            lambda s, m: _leaf_partition(s, m, mesh_axis_sizes, ""),
            param_specs, self.meta,
            is_leaf=lambda x: isinstance(x, P))

    def decay_mask(self, params):
        return self.inner.decay_mask(params)

    def _rows(self, *extra_trees):
        """Flat per-leaf (meta, part, spec, *extras) rows aligned on the
        params treedef; returns (treedef, rows)."""
        m_l, treedef = jax.tree.flatten(self.meta)
        pt_l = jax.tree.leaves(
            self.part,
            is_leaf=lambda x: x is None or isinstance(x, _LeafPart))
        s_l = jax.tree.leaves(self._param_specs,
                              is_leaf=lambda x: isinstance(x, P))
        extras = [jax.tree.leaves(t) for t in extra_trees]
        return treedef, list(zip(m_l, pt_l, s_l, *extras))

    def _cell_shapes(self, local_shape):
        """(vr, vc) cell-state shapes, or None when the CELL does not
        factor (full second moment)."""
        if self.inner._plan(local_shape) is None:
            return None
        view = self.inner._view_shape(local_shape)
        return view[:-1], view[:-2] + view[-1:]

    def init(self, params) -> dict:
        one = lambda: jnp.zeros((1,), jnp.float32)  # noqa: E731
        treedef, rows = self._rows(params)
        vr_l, vc_l, v_l, mu_l = [], [], [], []
        for m, pt, _, p in rows:
            local = pt.local_shape if pt is not None else m.shape
            cells = tuple(r for _, _, r in pt.parts) if pt else ()
            cs = self._cell_shapes(local)
            if cs is None:
                vr_l.append(one())
                vc_l.append(one())
                v_l.append(jnp.zeros(m.shape, jnp.float32))
            else:
                vr_l.append(jnp.zeros(cells + cs[0], jnp.float32))
                vc_l.append(jnp.zeros(cells + cs[1], jnp.float32))
                v_l.append(one())
            mu_l.append(jnp.zeros(m.shape, m.dtype)
                        if self.inner.b1 is not None else one())
        unf = treedef.unflatten
        return {"vr": unf(vr_l), "vc": unf(vc_l), "v": unf(v_l),
                "mu": unf(mu_l), "count": jnp.zeros((), jnp.int32)}

    def state_specs(self, param_specs=None):
        treedef, rows = self._rows()
        vr_l, v_l, mu_l = [], [], []
        for m, pt, spec in rows:
            local = pt.local_shape if pt is not None else m.shape
            factored = self._cell_shapes(local) is not None
            vr_l.append(P(*pt.axes) if (factored and pt is not None)
                        else P())
            v_l.append(P() if factored else spec)
            mu_l.append(spec if self.inner.b1 is not None else P())
        unf = treedef.unflatten
        vr = unf(vr_l)
        return {"vr": vr, "vc": vr, "v": unf(v_l), "mu": unf(mu_l),
                "count": P()}

    def apply(self, params, grads, state, decay_mask=None):
        """One per-cell step; call INSIDE shard_map when any leaf is
        partitioned (each leaf must be its local cell — the factoring
        plan is derived from the shapes seen here, which init derived
        from the cells)."""
        count = state["count"] + 1
        beta2t, rho, lr = self.inner._schedule_terms(count)
        if decay_mask is None:
            decay_mask = self.inner.decay_mask(params)
        treedef, rows = self._rows(
            params, grads, state["vr"], state["vc"], state["v"],
            state["mu"], decay_mask)
        outs = []
        for m, pt, _, p, g, vr, vc, v, mu, dk in rows:
            k = len(pt.parts) if pt is not None else 0
            factored = self._cell_shapes(tuple(p.shape)) is not None
            if k and factored:
                # (1, ..., *cell_state) shard_map block -> cell state.
                vr = vr.reshape(vr.shape[k:])
                vc = vc.reshape(vc.shape[k:])
            new_p, nvr, nvc, nv, nmu = self.inner._leaf_update(
                p, g, vr, vc, v, mu, dk, beta2t, rho, lr)
            if k and factored:
                nvr = nvr.reshape((1,) * k + nvr.shape)
                nvc = nvc.reshape((1,) * k + nvc.shape)
            outs.append((new_p, nvr, nvc, nv, nmu))
        unf = lambda i: treedef.unflatten(  # noqa: E731
            [o[i] for o in outs])
        return unf(0), {"vr": unf(1), "vc": unf(2), "v": unf(3),
                        "mu": unf(4), "count": count}


class FactoredZeRO1:

    """ZeRO-1 for FACTORED optimizers (Adafactor) — exact, row-sharded.

    :class:`ZeRO1`'s flat slices destroy the row/column structure
    Adafactor's factored second moment is built from, so the generic
    wrapper cannot host it (tpu_ddp/ops/optim.py:Adafactor refuses).
    This wrapper shards BY ROWS of each leaf's factoring view instead
    (the (..., n, m) per-matrix view from ``Adafactor._view_shape``):

    - ``psum_scatter`` over the view's row axis hands each worker the
      dp-MEAN of its 1/N row block (half an all-reduce, as ZeRO-1);
    - the row factor ``vr`` (and the momentum ``mu`` when b1 is set —
      the only O(nm) state) shard with the rows: state memory O(P/N);
    - the column factor ``vc`` stays replicated (it is the O(m) part)
      and its cross-row mean, the ``vr`` normalizer, and the update-RMS
      clip each cost one tiny ``psum`` over dp;
    - ``all_gather`` reassembles the updated rows on every worker.

    The result is bit-equal (up to reduction order) to replicated
    Adafactor — tested in tests/test_adafactor.py — while sharding the
    update compute and the O(nm) momentum 1/N over dp. Leaves too small
    to factor take :class:`ZeRO1`'s flat elementwise path, with the RMS
    terms psum'd so clipping stays global per leaf.

    Round-5: composes with tensor/expert/pipeline sharding via PER-CELL
    factoring (the :class:`CellAdafactor` semantic — row/column moments
    of each cell's LOCAL slice). Pass ``param_specs`` +
    ``mesh_axis_sizes`` and every mp/ep/pp-sharded leaf's state gains
    one leading cell axis per sharding mesh axis, with the row geometry
    computed from the CELL shape and the dp row-sharding applied WITHIN
    each cell (``vr``: ``P((mp..., None..., dp))``). Inside shard_map
    ``apply`` sees local cells, squeezes the leading singleton cell
    axes, and runs the unchanged row-sharded update — so the sharded
    step is exactly "FactoredZeRO1 on the sliced parameter tree".
    Per-cell factored moments are layout-coupled (as in T5X):
    checkpoints restore into the SAME mp layout only; a different
    layout fails the restore shape check loudly. Unpartitioned layouts
    keep their canonical (any-dp, any-trainer) checkpoint form.
    """

    def __init__(self, inner, axis_name: str = DATA_AXIS,
                 axis_size: int | None = None, template=None,
                 param_specs=None, mesh_axis_sizes: dict | None = None):
        if axis_size is None or axis_size < 1:
            raise ValueError("FactoredZeRO1 needs the static dp axis size")
        if not hasattr(inner, "_plan"):
            raise ValueError("FactoredZeRO1 wraps factored optimizers "
                             "(Adafactor); use ZeRO1 for elementwise ones")
        self.inner = inner
        self.axis_name = axis_name
        self.axis_size = axis_size
        self.meta = (jax.tree.map(_LeafMeta, template)
                     if template is not None else None)
        self._has_partition_info = param_specs is not None
        if param_specs is not None:
            if self.meta is None or mesh_axis_sizes is None:
                raise ValueError("FactoredZeRO1 with param_specs needs a "
                                 "params template and mesh_axis_sizes")
            self.part = jax.tree.map(
                lambda s, m: _leaf_partition(s, m, mesh_axis_sizes,
                                             self.axis_name),
                param_specs, self.meta,
                is_leaf=lambda x: isinstance(x, P))
        else:
            self.part = (jax.tree.map(lambda m: None, self.meta)
                         if self.meta is not None else None)

    # Shared helpers (same semantics as the flat-layout wrappers; aliased,
    # not re-implemented, so the two cannot drift).
    _chunk = _FlatLayout._chunk
    _require_meta = _FlatLayout._require_meta
    _part_leaves = _FlatLayout._part_leaves

    def decay_mask(self, params):
        """Inner optimizer's policy, passed through so trainers that
        override the mask (pipeline stacked leaves) can query the
        wrapper like they would the bare optimizer."""
        return self.inner.decay_mask(params)

    # ---- per-leaf geometry ---------------------------------------------

    def _geom(self, shape):
        """(lead, n, m, n_loc) of the factoring view, or None when the
        leaf is unfactored (flat elementwise path)."""
        if self.inner._plan(shape) is None:
            return None
        view = self.inner._view_shape(shape)
        lead, n, m = view[:-2], view[-2], view[-1]
        n_loc = self._chunk(n)
        return lead, n, m, n_loc

    # ---- state layout (global view) ------------------------------------

    @staticmethod
    def _local(m_or_p, pt):
        """LOCAL cell shape of one leaf (= the full shape sans
        partition)."""
        return pt.local_shape if pt is not None else tuple(m_or_p.shape)

    @staticmethod
    def _cells(pt) -> tuple:
        """Leading cell-axis extents, major to minor (empty sans
        partition)."""
        return tuple(r for _, _, r in pt.parts) if pt is not None else ()

    def _leaf_rows(self, tree):
        """(treedef, [(leaf, part), ...]) aligned on ``tree``'s leaves."""
        l_l, treedef = jax.tree.flatten(tree)
        return treedef, list(zip(l_l, self._part_leaves(len(l_l))))

    def init(self, params) -> dict:
        N = self.axis_size
        one = lambda: jnp.zeros((1,), jnp.float32)  # noqa: E731
        treedef, rows = self._leaf_rows(params)
        vr_l, vc_l, v_l, mu_l = [], [], [], []
        for p, pt in rows:
            local = self._local(p, pt)
            cells = self._cells(pt)
            g = self._geom(local)
            if g is None:
                chunk = self._chunk(int(np.prod(local)))
                vr_l.append(one())
                vc_l.append(one())
                v_l.append(jnp.zeros(cells + (chunk * N,), jnp.float32))
                mu_l.append(jnp.zeros(cells + (chunk * N,), p.dtype)
                            if self.inner.b1 is not None else one())
            else:
                lead, n, m, n_loc = g
                vr_l.append(jnp.zeros(cells + lead + (n_loc * N,),
                                      jnp.float32))
                vc_l.append(jnp.zeros(cells + lead + (m,), jnp.float32))
                v_l.append(one())
                mu_l.append(jnp.zeros(cells + lead + (n_loc * N, m),
                                      p.dtype)
                            if self.inner.b1 is not None else one())
        unf = treedef.unflatten
        return {"vr": unf(vr_l), "vc": unf(vc_l), "v": unf(v_l),
                "mu": unf(mu_l), "count": jnp.zeros((), jnp.int32)}

    def state_specs(self, param_specs=None):
        """Per-leaf specs over the layout above. Without partition info
        (no ``param_specs`` at construction) sharded parameter leaves
        are refused loudly — the row geometry would silently be computed
        from FULL leaf shapes; construct with ``param_specs`` +
        ``mesh_axis_sizes`` for the per-cell layout."""
        self._require_meta()
        # Skip the refusal whenever partition INFO was supplied at
        # construction — even if every sharding axis has extent 1 (all
        # parts None), the caller already did the right thing and the
        # layout degenerates correctly.
        if param_specs is not None and not self._has_partition_info:
            def check(spec):
                if tuple(x for x in spec if x is not None):
                    raise NotImplementedError(
                        "FactoredZeRO1 without partition info shards "
                        "over full-leaf row geometry and cannot host "
                        f"sharded parameter leaves (got spec {spec}); "
                        "construct it with param_specs + mesh_axis_sizes "
                        "for per-cell factoring")
                return spec
            jax.tree.map(check, param_specs,
                         is_leaf=lambda x: isinstance(x, P))
        ax = self.axis_name
        treedef, rows = self._leaf_rows(self.meta)
        vr_l, vc_l, v_l, mu_l = [], [], [], []
        for m, pt in rows:
            local = self._local(m, pt)
            axes = pt.axes if pt is not None else ()
            g = self._geom(local)
            if g is None:
                vr_l.append(P())
                vc_l.append(P())
                v_l.append(P(*axes, ax))
                mu_l.append(P(*axes, ax)
                            if self.inner.b1 is not None else P())
            else:
                lead = g[0]
                vr_l.append(P(*axes, *([None] * len(lead)), ax))
                vc_l.append(P(*axes) if axes else P())
                v_l.append(P())
                mu_l.append(P(*axes, *([None] * len(lead)), ax, None)
                            if self.inner.b1 is not None else P())
        unf = treedef.unflatten
        return {"vr": unf(vr_l), "vc": unf(vc_l), "v": unf(v_l),
                "mu": unf(mu_l), "count": P()}

    # ---- checkpoint canonicalization (host-side) -----------------------

    def canonicalize_opt_host(self, state) -> dict:
        """Gathered (global-layout) host state -> canonical shapes.

        Unpartitioned leaves canonicalize to the replicated Adafactor's
        shapes (restore at any dp size or into an unsharded trainer).
        Partitioned (per-cell) leaves strip the dp row padding but KEEP
        their leading cell axes — per-cell factored moments have no
        layout-independent form (the cells' factors are distinct
        statistics), so they restore into the same mp layout only."""
        self._require_meta()
        treedef, rows = self._leaf_rows(self.meta)

        def over(leaf_fn, tree):
            return treedef.unflatten(
                [leaf_fn(x, m, pt) for x, (m, pt)
                 in zip(jax.tree.leaves(tree), rows)])

        def vr(x, m, pt):
            g = self._geom(self._local(m, pt))
            if g is None:
                return np.asarray(x)
            return np.asarray(x)[..., :g[1]]

        def v(x, m, pt):
            local = self._local(m, pt)
            if self._geom(local) is not None:
                return np.asarray(x)
            size = int(np.prod(local))
            return np.asarray(x)[..., :size].reshape(
                self._cells(pt) + tuple(local))

        def mu(x, m, pt):
            if self.inner.b1 is None:
                return np.asarray(x)
            local = self._local(m, pt)
            g = self._geom(local)
            if g is None:
                return v(x, m, pt)
            lead, n, mm, _ = g
            return np.asarray(x)[..., :n, :].reshape(
                self._cells(pt) + tuple(local))

        return {"vr": over(vr, state["vr"]),
                "vc": over(lambda x, m, pt: np.asarray(x), state["vc"]),
                "v": over(v, state["v"]),
                "mu": over(mu, state["mu"]),
                "count": state["count"]}

    def canonical_opt_template(self, params_template) -> dict:
        """ShapeDtypeStructs of the canonical (on-disk) state — what
        :meth:`canonicalize_opt_host` emits — for building a restore
        template. Reduces to the replicated Adafactor's ``init`` shapes
        when no leaf is partitioned."""
        self._require_meta()
        sds = jax.ShapeDtypeStruct
        treedef, rows = self._leaf_rows(self.meta)
        vr_l, vc_l, v_l, mu_l = [], [], [], []
        for m, pt in rows:
            local = self._local(m, pt)
            cells = self._cells(pt)
            g = self._geom(local)
            if g is None:
                vr_l.append(sds((1,), jnp.float32))
                vc_l.append(sds((1,), jnp.float32))
                v_l.append(sds(cells + tuple(local), jnp.float32))
                mu_l.append(sds(cells + tuple(local), m.dtype)
                            if self.inner.b1 is not None
                            else sds((1,), jnp.float32))
            else:
                lead, n, mm, _ = g
                vr_l.append(sds(cells + lead + (n,), jnp.float32))
                vc_l.append(sds(cells + lead + (mm,), jnp.float32))
                v_l.append(sds((1,), jnp.float32))
                mu_l.append(sds(cells + tuple(local), m.dtype)
                            if self.inner.b1 is not None
                            else sds((1,), jnp.float32))
        unf = treedef.unflatten
        return {"vr": unf(vr_l), "vc": unf(vc_l), "v": unf(v_l),
                "mu": unf(mu_l),
                "count": sds((), jnp.int32)}

    def flatten_opt(self, state) -> dict:
        """Canonical host state -> this wrapper's global layout (restore
        path; inverse of :meth:`canonicalize_opt_host`)."""
        self._require_meta()
        N = self.axis_size
        treedef, rows = self._leaf_rows(self.meta)

        def over(leaf_fn, tree):
            return treedef.unflatten(
                [leaf_fn(x, m, pt) for x, (m, pt)
                 in zip(jax.tree.leaves(tree), rows)])

        def vr(x, m, pt):
            g = self._geom(self._local(m, pt))
            if g is None:
                return np.asarray(x)
            lead, n, _, n_loc = g
            x = np.asarray(x)
            pad = [(0, 0)] * (x.ndim - 1) + [(0, n_loc * N - n)]
            return np.pad(x, pad)

        def v(x, m, pt):
            local = self._local(m, pt)
            if self._geom(local) is not None:
                return np.asarray(x)
            size = int(np.prod(local))
            cells = self._cells(pt)
            flat = np.asarray(x).reshape(cells + (size,))
            pad = [(0, 0)] * len(cells) \
                + [(0, self._chunk(size) * N - size)]
            return np.pad(flat, pad)

        def mu(x, m, pt):
            if self.inner.b1 is None:
                return np.asarray(x)
            local = self._local(m, pt)
            g = self._geom(local)
            if g is None:
                return v(x, m, pt)
            lead, n, mm, n_loc = g
            cells = self._cells(pt)
            arr = np.asarray(x).reshape(cells + lead + (n, mm))
            pad = [(0, 0)] * (len(cells) + len(lead)) \
                + [(0, n_loc * N - n), (0, 0)]
            return np.pad(arr, pad)

        return {"vr": over(vr, state["vr"]),
                "vc": over(lambda x, m, pt: np.asarray(x), state["vc"]),
                "v": over(v, state["v"]),
                "mu": over(mu, state["mu"]),
                "count": state["count"]}

    # ---- the sharded update (inside shard_map) -------------------------

    def apply(self, params, grads, opt_state, decay_mask=None,
              clip_norm=None):
        """One sharded Adafactor step; call inside shard_map over the dp
        axis with ``grads`` UNSYNCED over dp (pre-synced over any other
        data axes). Returns (new_params, new_state) with params full-size
        and identical on every worker. Under partition-aware layouts
        each leaf here is its LOCAL cell — the row geometry derives from
        ``p.shape``, so the unchanged update IS per-cell factoring.

        ``decay_mask``: optional override of the inner policy — the
        pipeline trainer passes the ORIGINAL per-layer ranks so stacked
        (L, dm) LayerNorm leaves are not decayed. ``clip_norm`` is
        refused loudly: Adafactor already clips by update RMS."""
        if clip_norm is not None:
            raise ValueError(
                "clip_norm with FactoredZeRO1 (Adafactor) is not "
                "supported — Adafactor already clips by update RMS "
                "(ops/optim.py); use AdamW/SGD or drop the clip")
        o = self.inner
        ax, N = self.axis_name, self.axis_size
        idx = lax.axis_index(ax)
        count = opt_state["count"] + 1
        c = count.astype(jnp.float32)
        beta2t = 1.0 - c ** (-o.decay_rate)
        if o.learning_rate is None:
            rho, lr = jnp.minimum(1e-2, 1.0 / jnp.sqrt(c)), None
        else:
            lr = (o.learning_rate(c) if callable(o.learning_rate)
                  else o.learning_rate)
            rho = None
        if decay_mask is None:
            decay_mask = o.decay_mask(params)

        def alpha_for(p):
            if lr is not None:
                return lr
            rms_p = jnp.sqrt(jnp.mean(jnp.square(p.astype(jnp.float32))))
            return rho * jnp.maximum(o.eps2, rms_p)

        def upd(p, g, vr, vc, v, mu, dk, pt):
            # Partitioned leaves' REAL state blocks arrive as
            # (1, ..., *cell_state) inside shard_map: squeeze the
            # leading singleton cell axes, update, restore them. The
            # (1,)-placeholder leaves (vr/vc of an unfactored cell, v of
            # a factored one, mu sans b1) carry no cell axes and pass
            # through untouched.
            k = len(pt.parts) if pt is not None else 0
            geom = self._geom(p.shape)
            has_mu = self.inner.b1 is not None
            sq = [k and geom is not None, k and geom is not None,
                  k and geom is None, k and has_mu]  # vr, vc, v, mu
            if k:
                vr, vc, v, mu = (x.reshape(x.shape[k:]) if s else x
                                 for s, x in zip(sq, (vr, vc, v, mu)))
            if geom is None:
                out = self._upd_flat(p, g, vr, vc, v, mu, dk, idx,
                                     beta2t, alpha_for(p))
            else:
                out = self._upd_factored(p, g, vr, vc, v, mu, dk, idx,
                                         beta2t, alpha_for(p), geom)
            if k:
                new_p, nvr, nvc, nv, nmu = out
                out = (new_p,) + tuple(
                    x.reshape((1,) * k + x.shape) if s else x
                    for s, x in zip(sq, (nvr, nvc, nv, nmu)))
            return out

        p_l, treedef = jax.tree.flatten(params)
        outs = [upd(*args) for args in zip(
            p_l, jax.tree.leaves(grads),
            jax.tree.leaves(opt_state["vr"]),
            jax.tree.leaves(opt_state["vc"]),
            jax.tree.leaves(opt_state["v"]),
            jax.tree.leaves(opt_state["mu"]),
            jax.tree.leaves(decay_mask),
            self._part_leaves(len(p_l)))]
        unf = lambda i: treedef.unflatten([o_[i] for o_ in outs])  # noqa: E731
        return unf(0), {"vr": unf(1), "vc": unf(2), "v": unf(3),
                        "mu": unf(4), "count": count}

    def _clip(self, u, sq_sum, n_elems):
        rms_u = jnp.sqrt(sq_sum / n_elems)
        return u / jnp.maximum(1.0, rms_u / self.inner.clip_threshold)

    def _step_and_mu(self, u, mu, p_dtype):
        if self.inner.b1 is None:
            return u, mu
        new_mu = self.inner.b1 * mu + (1 - self.inner.b1) * u.astype(p_dtype)
        return new_mu, new_mu

    def _upd_factored(self, p, g, vr, vc, v, mu, dk, idx, beta2t, alpha,
                      geom):
        o, ax, N = self.inner, self.axis_name, self.axis_size
        lead, n, m, n_loc = geom
        L = len(lead)

        def to_blocks(x):
            """(orig shape) -> (lead..., N, n_loc, m) row blocks."""
            xv = x.reshape(lead + (n, m))
            pad = [(0, 0)] * L + [(0, n_loc * N - n), (0, 0)]
            return jnp.pad(xv, pad).reshape(lead + (N, n_loc, m))

        # dp-mean of MY row block: psum_scatter = half an all-reduce.
        g_loc = lax.psum_scatter(to_blocks(g.astype(jnp.float32)), ax,
                                 scatter_dimension=L) / N
        # Rows >= n are padding on the last worker(s): masked out of every
        # cross-row reduction, and sliced off at reassembly.
        row_mask = ((idx * n_loc + jnp.arange(n_loc)) < n
                    ).astype(jnp.float32)                     # (n_loc,)
        g2 = jnp.square(g_loc) + o.eps1
        new_vr = beta2t * vr + (1 - beta2t) * jnp.mean(g2, axis=-1)
        col_sum = lax.psum(
            jnp.sum(g2 * row_mask[:, None], axis=-2), ax)     # (lead..., m)
        new_vc = beta2t * vc + (1 - beta2t) * col_sum / n
        vr_mean = lax.psum(jnp.sum(new_vr * row_mask, axis=-1), ax) / n
        r = new_vr / vr_mean[..., None]
        u = g_loc * lax.rsqrt(r)[..., None] * lax.rsqrt(new_vc)[..., None, :]
        # Update-RMS clip is ONE scalar over the whole leaf (matching the
        # replicated Adafactor), so sum over every axis before the psum.
        sq_sum = lax.psum(jnp.sum(jnp.square(u) * row_mask[:, None]), ax)
        n_elems = float(int(np.prod(lead, initial=1)) * n * m)
        u = self._clip(u, sq_sum, n_elems)
        step, new_mu = self._step_and_mu(u, mu, p.dtype)
        p_loc = lax.dynamic_index_in_dim(to_blocks(p), idx, axis=L,
                                         keepdims=False)
        new_p_loc = p_loc - (alpha * step
                             + (alpha * o.weight_decay * p_loc if dk
                                else 0.0)).astype(p.dtype)
        full = lax.all_gather(new_p_loc.astype(p.dtype), ax, axis=L)
        full = full.reshape(lead + (n_loc * N, m))
        new_p = full[..., :n, :].reshape(p.shape)
        return new_p, new_vr, new_vc, v, new_mu

    def _upd_flat(self, p, g, vr, vc, v, mu, dk, idx, beta2t, alpha):
        o, ax, N = self.inner, self.axis_name, self.axis_size
        chunk = self._chunk(p.size)
        flat_g = jnp.pad(g.astype(jnp.float32).reshape(-1),
                         (0, chunk * N - p.size))
        g_loc = lax.psum_scatter(flat_g.reshape(N, chunk), ax,
                                 scatter_dimension=0) / N
        elem_mask = ((idx * chunk + jnp.arange(chunk)) < p.size
                     ).astype(jnp.float32)
        g2 = jnp.square(g_loc) + o.eps1
        new_v = beta2t * v + (1 - beta2t) * g2
        u = g_loc * lax.rsqrt(new_v)
        sq_sum = lax.psum(jnp.sum(jnp.square(u) * elem_mask), ax)
        u = self._clip(u, sq_sum, float(p.size))
        step, new_mu = self._step_and_mu(u, mu, p.dtype)
        flat_p = jnp.pad(p.reshape(-1), (0, chunk * N - p.size))
        p_loc = lax.dynamic_slice_in_dim(flat_p, idx * chunk, chunk)
        new_p_loc = p_loc - (alpha * step
                             + (alpha * o.weight_decay * p_loc if dk
                                else 0.0)).astype(p.dtype)
        full = lax.all_gather(new_p_loc.astype(p.dtype), ax, tiled=True)
        return full[:p.size].reshape(p.shape), vr, vc, new_v, new_mu


class ZeRO3(_FlatLayout):
    """Fully-sharded parameters — FSDP / ZeRO stage 3 (part5).

    One step beyond :class:`ZeRO1`: PARAMETERS (not just optimizer state)
    live as flat 1/N shards per data-parallel worker; per-device
    parameter memory is O(P/N) at rest. Inside the train step the full
    parameters exist only transiently:

    - forward: each leaf is ``all_gather``'d (tiled) and reshaped to its
      true shape — exactly the on-demand materialization FSDP does;
    - backward: autodiff's transpose of that ``all_gather`` is
      ``psum_scatter``, so the gradient arrives ALREADY reduce-scattered
      into this worker's shard — the ZeRO gradient sync falls out of the
      chain rule with no explicit collective;
    - update: the (elementwise) optimizer touches only the local shard,
      with the weight-decay policy evaluated on the ORIGINAL leaf ranks.

    The backward psum_scatter SUMS over workers, so the trainer divides
    the shard gradient by N to recover the replica mean (same algebra as
    :class:`ZeRO1.apply`'s ``/ n``).

    Composes with tensor/expert parallelism (round-3 verdict item 3):
    pass ``param_specs`` + ``mesh_axis_sizes`` and each mp/ep-sharded
    leaf's flat layout is laid out per model-parallel cell and
    dp-sharded within it (``P((mp..., dp))``, the same scheme ZeRO-1
    uses for its state) — ``gather_params`` then reassembles each
    cell's LOCAL tp/ep slice from its dp shards, which is exactly the
    leaf the tensor-parallel model code expects inside shard_map.
    """

    def __init__(self, inner, axis_name: str = DATA_AXIS,
                 axis_size: int | None = None, template=None,
                 param_specs=None, mesh_axis_sizes: dict | None = None):
        if axis_size is None or axis_size < 1:
            raise ValueError("ZeRO3 needs the static dp axis size")
        if template is None:
            raise ValueError("ZeRO3 needs a params template "
                             "(shapes/dtypes of the original leaves)")
        self.inner = inner
        self.axis_name = axis_name
        self.axis_size = axis_size
        # Shape/dtype per leaf, wrapped in an unregistered type so the
        # metadata rides pytrees as LEAVES; rank drives the decay policy.
        # param_specs (optional) makes the flat layout partition-aware.
        self._init_layout(template, param_specs, mesh_axis_sizes)

    def init(self, flat_params):
        return self.inner.init(flat_params)

    def flat_param_specs(self):
        """Per-leaf specs of the flat layout: ``P((mp..., dp))`` for
        model-parallel partitioned leaves, ``P(dp)`` for the rest."""
        m_l, treedef = jax.tree.flatten(self.meta)
        return treedef.unflatten(
            [P((*pt.axes, self.axis_name)) if pt is not None
             else P(self.axis_name)
             for pt in self._part_leaves(len(m_l))])

    def state_specs(self, param_specs=None):
        return self.inner.state_specs(self.flat_param_specs())

    def gather_params(self, flat_local):
        """INSIDE shard_map: local (chunk,) shards -> this cell's
        full-shape leaves (the GLOBAL shape for replicated leaves, the
        LOCAL tp/ep slice for partitioned ones — exactly what the
        tensor-parallel model expects). Differentiable; the transpose
        reduce-scatters cotangents over dp."""
        p_l, treedef = jax.tree.flatten(flat_local)
        m_l = jax.tree.leaves(self.meta)
        out = []
        for sh, meta, pt in zip(p_l, m_l, self._part_leaves(len(p_l))):
            g = lax.all_gather(sh, self.axis_name, tiled=True)
            if pt is None:
                out.append(g[:meta.size].reshape(meta.shape))
            else:
                out.append(g[:pt.local_size].reshape(pt.local_shape))
        return treedef.unflatten(out)

    def decay_mask(self):
        """Inner optimizer's policy on the ORIGINAL ranks (flat shards
        are all rank-1; _LeafMeta exposes .ndim for the policy)."""
        return self.inner.decay_mask(self.meta)

    def apply(self, flat_params, flat_grads, opt_state, decay_mask=None):
        """Shard-local update; grads must already be the psum_scatter'd
        shards divided by the axis size (the trainer's job).
        ``decay_mask`` overrides the meta-rank policy — the pipeline
        trainer passes the ORIGINAL per-layer ranks so stacked (L, dm)
        LayerNorm leaves are not decayed (same hook as ZeRO1.apply)."""
        return self.inner.apply(
            flat_params, flat_grads, opt_state,
            decay_mask=(decay_mask if decay_mask is not None
                        else self.decay_mask()))
