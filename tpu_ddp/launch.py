"""Local multi-process launcher — the cluster-in-a-box analogue of the
reference's launch recipe.

The reference is launched by hand on every node of a 4-node cluster with
the same command (reference README.md:8-19)::

    python main.py --num-nodes 4 --rank R --master-ip 10.10.1.1 --master-port 4000

This module automates that loop on ONE host: it spawns ``nproc`` worker
processes, each running a part's ``main.py`` with ``--rank i`` and a shared
``127.0.0.1`` coordinator, so the real multi-process rendezvous path
(``jax.distributed.initialize`` -> cross-process collectives) is exercised
without a cluster — the TPU-native analogue of gloo's multi-process
single-host mode (SURVEY.md §4). On an actual TPU pod each host still runs
its part ``main.py`` directly, exactly like the reference.

CLI::

    python -m tpu_ddp.launch part2b --nproc 4 [--platform cpu]
        [--devices-per-proc 1] [--port auto] [part args...]
"""

from __future__ import annotations

import argparse
import os
import random
import socket
import subprocess
import sys
import tempfile
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from pathlib import Path

from tpu_ddp.resilience.watchdog import (HEARTBEAT_ENV, STALL_EXIT_CODE,
                                         HeartbeatMonitor)

PARTS_DIR = Path(__file__).resolve().parent.parent / "parts"
PARTS = ("part1", "part2a", "part2b", "part3", "part4", "part5")


def find_free_port() -> int:
    """Ask the OS for a free TCP port for the coordinator."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class WorkerResult:
    rank: int
    returncode: int
    output: str = ""
    # True when this worker's (nonzero) exit was handled by a live
    # reshard — the survivors carried on, so it does not fail the launch.
    absorbed: bool = False


@dataclass
class LaunchResult:
    workers: list = field(default_factory=list)
    # Exit code of the FIRST rank observed failing — the root cause, not
    # the -9 of bystander ranks reaped afterwards. 0 when all succeeded.
    first_failure: int = 0
    # Number of cluster restarts performed before this (final) attempt —
    # nonzero only for launch_elastic.
    restarts: int = 0
    # Number of live membership epochs (reshard-arounds) this attempt
    # performed instead of restarting — nonzero only under
    # elastic_reshard.
    reshards: int = 0
    # True when the heartbeat watchdog killed this attempt: every rank
    # was alive but none had completed a step within heartbeat_timeout
    # (the hung-collective failure mode — see resilience/watchdog.py).
    stalled: bool = False

    @property
    def returncode(self) -> int:
        if self.first_failure:
            return self.first_failure
        # Fallback (e.g. hand-built results): any nonzero rank fails the
        # launch, including negative signal-kill codes — except workers
        # whose departure a reshard absorbed.
        return next((w.returncode for w in self.workers
                     if w.returncode != 0 and not w.absorbed), 0)

    @property
    def ok(self) -> bool:
        return self.returncode == 0

    def output_of(self, rank: int) -> str:
        for w in self.workers:
            if w.rank == rank:
                return w.output
        raise KeyError(rank)


def _drain(proc, rank: int, sink: list, echo: bool) -> None:
    """Stream one worker's stdout, prefixing lines with its rank."""
    for raw in proc.stdout:
        line = raw.rstrip("\n")
        sink.append(line)
        if echo:
            print(f"[rank {rank}] {line}", flush=True)
    proc.stdout.close()


def launch(
    part: str,
    nproc: int,
    extra_args: list | None = None,
    platform: str = "cpu",
    devices_per_proc: int = 1,
    port: int | None = None,
    env: dict | None = None,
    echo: bool = True,
    timeout: float | None = None,
    heartbeat_timeout: float | None = None,
    heartbeat_dir: str | None = None,
    elastic_reshard: bool = False,
    ack_timeout: float = 120.0,
    rejoin_delay: float = 1.0,
) -> LaunchResult:
    """Run ``nproc`` rank processes of ``parts/<part>/main.py`` and wait.

    Each worker gets ``JAX_PLATFORMS=<platform>`` and (on cpu) a forced
    host-platform device count of ``devices_per_proc``, so a laptop/CI host
    emulates an ``nproc``-node cluster with ``nproc * devices_per_proc``
    total dp slots. Extra env wins over the computed defaults.

    ``heartbeat_timeout`` arms the watchdog: workers inherit
    ``TPU_DDP_HEARTBEAT_DIR`` (a fresh temp dir unless ``heartbeat_dir``
    pins it) and touch a per-rank file each step; once heartbeats exist,
    a cluster whose NEWEST beat is older than the deadline is killed and
    reported with ``stalled=True`` / exit :data:`STALL_EXIT_CODE` —
    catching hung collectives in seconds instead of waiting out
    ``timeout`` (which still bounds never-started clusters).

    ``elastic_reshard`` turns a lost rank from a cluster-wide failure
    into a membership epoch: the launcher writes a ``membership.json``
    protocol directory (resilience/elastic.py), workers join via the
    non-fatal elastic bootstrap, and when a rank dies or stalls while
    others survive, the launcher publishes a shrunken epoch and waits
    for the survivors to reshard their LIVE TrainState around the hole
    (acks within ``ack_timeout``) instead of killing everyone. A rank
    exiting ``HOST_JOIN_EXIT`` is respawned after ``rejoin_delay`` as a
    joiner of a regrown epoch. When a reshard cannot converge (acks
    time out, a survivor exits ``RESHARD_FALLBACK_EXIT``), the attempt
    fails with that code so :func:`launch_elastic` falls back to
    restart-from-checkpoint.
    """
    if nproc < 1:
        raise ValueError("nproc must be >= 1")
    if part in PARTS:
        script = PARTS_DIR / part / "main.py"
    elif part.endswith(".py"):
        # Any CLI honouring the reference launch contract
        # (--num-nodes/--rank/--master-ip/--master-port) can be
        # clustered, e.g. examples/lm_train.py. Relative paths resolve
        # against the repo root — the same cwd the workers get — so the
        # call works from any directory.
        p = Path(part)
        script = (p if p.is_absolute() else PARTS_DIR.parent / p).resolve()
    else:
        raise ValueError(f"unknown part {part!r}; available: {PARTS} "
                         "or a path to a *.py CLI")
    if not script.exists():
        raise FileNotFoundError(
            f"{script}: the launcher runs source-checkout CLIs "
            "(parts/ and examples/ are not part of the installed "
            "package)")
    port = port or find_free_port()
    monitor = None
    if heartbeat_timeout is not None:
        hb_dir = heartbeat_dir or tempfile.mkdtemp(prefix="tpu_ddp_hb_")
        monitor = HeartbeatMonitor(hb_dir, nproc, heartbeat_timeout)
    control_dir = None
    if elastic_reshard and nproc > 1:
        from tpu_ddp.resilience import elastic as _el
        # The heartbeat dir doubles as the protocol dir when armed —
        # one place to look at in a post-mortem.
        control_dir = (monitor.directory if monitor is not None
                       else tempfile.mkdtemp(prefix="tpu_ddp_elastic_"))
        _el.reset_control_dir(control_dir)
        _el.write_membership(control_dir, {
            "epoch": 0, "world": nproc, "base_world": nproc,
            "assignments": {str(i): i for i in range(nproc)},
            "coordinator": f"127.0.0.1:{port}",
            "joiners": [], "dropped": []})

    def spawn(rank: int, join_epoch: int | None = None):
        child_env = dict(os.environ)
        child_env["JAX_PLATFORMS"] = platform
        if monitor is not None:
            child_env[HEARTBEAT_ENV] = monitor.directory
        if platform == "cpu":
            # Replace (not append) any inherited forced device count.
            flags = [f for f in child_env.get("XLA_FLAGS", "").split()
                     if "xla_force_host_platform_device_count" not in f]
            flags.append("--xla_force_host_platform_device_count="
                         f"{devices_per_proc}")
            child_env["XLA_FLAGS"] = " ".join(flags)
        if control_dir is not None:
            from tpu_ddp.resilience import elastic as _el
            child_env[_el.ELASTIC_ENV] = "1"
            child_env[_el.ELASTIC_DIR_ENV] = control_dir
            child_env[_el.ELASTIC_RANK_ENV] = str(rank)
            if join_epoch is not None:
                child_env[_el.ELASTIC_JOIN_ENV] = str(join_epoch)
        if env:
            child_env.update(env)
        cmd = [sys.executable, str(script),
               "--num-nodes", str(nproc),
               "--rank", str(rank),
               "--master-ip", "127.0.0.1",
               "--master-port", str(port)] + list(extra_args or [])
        proc = subprocess.Popen(
            cmd, env=child_env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
            cwd=str(PARTS_DIR.parent))
        sink: list = []
        t = threading.Thread(target=_drain, args=(proc, rank, sink, echo),
                             daemon=True)
        t.start()
        return proc, sink, t

    if control_dir is not None:
        return _run_elastic(spawn, nproc, control_dir, monitor, timeout,
                            ack_timeout, rejoin_delay)

    procs = []
    sinks = []
    threads = []
    for rank in range(nproc):
        proc, sink, t = spawn(rank)
        procs.append(proc)
        sinks.append(sink)
        threads.append(t)

    # Poll all ranks concurrently against ONE shared deadline. Sequential
    # proc.wait() calls would hang forever (timeout=None) or for
    # nproc*timeout when one rank dies early and the survivors block in
    # the rendezvous/collective waiting for it.
    deadline = None if timeout is None else time.monotonic() + timeout
    rcs: dict = {}
    first_failure = 0
    while len(rcs) < len(procs):
        for rank, proc in enumerate(procs):
            if rank in rcs:
                continue
            rc = proc.poll()
            if rc is None:
                continue
            rcs[rank] = rc
            if rc != 0:
                first_failure = first_failure or rc
                # A dead rank leaves the others blocked in a collective;
                # reap them now instead of waiting out the timeout.
                for other in procs:
                    if other.poll() is None:
                        other.kill()
        if len(rcs) < len(procs):
            if monitor is not None and not first_failure \
                    and monitor.stalled():
                # Watchdog: every remaining rank is alive but the whole
                # cluster stopped completing steps — a hung collective.
                # Kill it now; launch_elastic will restart with backoff.
                print(f"[launch] heartbeat stall: no step completed in "
                      f"{monitor.timeout:.0f}s — killing the cluster",
                      flush=True)
                for rank, proc in enumerate(procs):
                    if rank not in rcs:
                        proc.kill()
                        rcs[rank] = proc.wait()
                first_failure = STALL_EXIT_CODE
                break
            if deadline is not None and time.monotonic() > deadline:
                # A rank may have exited with a real code (even 0, or a
                # real signal like SIGSEGV) between the last poll and
                # this sweep — record whatever wait() reports, and prefer
                # any such real code as the root cause over the -9 of
                # ranks we killed ourselves (checked only after the whole
                # sweep, so an early hung rank cannot mask a later rank's
                # real failure).
                sweep_real = 0
                for rank, proc in enumerate(procs):
                    if rank not in rcs:
                        proc.kill()
                        rc = proc.wait()
                        rcs[rank] = rc
                        if rc not in (0, -9):
                            sweep_real = sweep_real or rc
                first_failure = first_failure or sweep_real or -9
                break
            time.sleep(0.05)
    result = LaunchResult(first_failure=first_failure,
                          stalled=first_failure == STALL_EXIT_CODE)
    for rank in range(len(procs)):
        result.workers.append(WorkerResult(rank=rank, returncode=rcs[rank]))
    for t in threads:
        t.join(timeout=5)
    for w, sink in zip(result.workers, sinks):
        w.output = "\n".join(sink)
    return result


def _run_elastic(spawn, nproc: int, control_dir: str,
                 monitor: HeartbeatMonitor | None, timeout: float | None,
                 ack_timeout: float, rejoin_delay: float) -> LaunchResult:
    """The elastic poll loop: absorb rank departures into membership
    epochs instead of killing the cluster.

    State machine per event:
    - worker exits 0            -> done (success once all members do)
    - worker exits nonzero,
      survivors remain          -> departure note on its behalf, write
                                   epoch+1 (survivors keep low ranks,
                                   fresh coordinator port), wait for
                                   every survivor's ack
    - exit was HOST_JOIN_EXIT   -> additionally respawn it after
                                   ``rejoin_delay`` as the highest rank
                                   of a regrown epoch (it restores from
                                   the survivors' state beacon)
    - RESHARD_FALLBACK_EXIT, no
      survivors, or acks time
      out                       -> kill everyone, fail the attempt so
                                   launch_elastic restarts from ckpt
    - a rank's heartbeat stalls -> kill THAT rank; its -9 is absorbed
                                   like any other departure (all ranks
                                   stalled -> whole-cluster stall, the
                                   plain watchdog path)
    """
    from tpu_ddp.resilience import elastic as _el

    live = {wid: spawn(wid) for wid in range(nproc)}
    epoch = 0
    reshards = 0
    dropped: list = []
    done: list = []  # (WorkerResult, sink, thread)
    pending_join: list = []  # (due_monotonic, wid)
    deadline = None if timeout is None else time.monotonic() + timeout
    first_failure = 0
    stalled_flag = False

    def record(wid, rc, sink, thread, absorbed=False):
        done.append((WorkerResult(rank=wid, returncode=rc,
                                  absorbed=absorbed), sink, thread))

    def kill_all():
        for wid, (proc, sink, t) in list(live.items()):
            if proc.poll() is None:
                proc.kill()
            record(wid, proc.wait(), sink, t)
            del live[wid]

    def write_epoch(joiner=None):
        nonlocal epoch, reshards
        epoch += 1
        reshards += 1
        # Survivors keep the low ranks; a joiner takes the highest —
        # rank 0 (coordination service host + beacon writer) is always
        # an already-running survivor.
        order = sorted(live)
        if joiner is not None and joiner not in live:
            order.append(joiner)
        _el.write_membership(control_dir, {
            "epoch": epoch, "world": len(order), "base_world": nproc,
            "assignments": {str(w): i for i, w in enumerate(order)},
            "coordinator": f"127.0.0.1:{find_free_port()}",
            "joiners": [] if joiner is None else [joiner],
            "dropped": sorted(dropped)})
        return order

    def await_acks(members):
        stop = time.monotonic() + ack_timeout
        while time.monotonic() < stop:
            if all(os.path.exists(_el.ack_path(control_dir, epoch, w))
                   for w in members):
                if monitor is not None:
                    # Survivors paused beating to recompile; fresh grace.
                    monitor.reset_grace()
                return True
            # A member dying mid-reshard (cascade) fails the epoch.
            if any(w in live and live[w][0].poll() is not None
                   for w in members):
                return False
            time.sleep(0.05)
        return False

    while (live or pending_join) and not first_failure:
        now = time.monotonic()
        # 1. Respawn due joiners into a regrown epoch.
        for item in [x for x in pending_join if x[0] <= now]:
            pending_join.remove(item)
            wid = item[1]
            if not live:
                first_failure = _el.HOST_JOIN_EXIT
                break
            _el.clear_departure(control_dir, wid)
            if wid in dropped:
                dropped.remove(wid)
            members = write_epoch(joiner=wid)
            live[wid] = spawn(wid, join_epoch=epoch)
            print(f"[launch] epoch {epoch}: worker {wid} rejoining, "
                  f"world={len(members)}", flush=True)
            if not await_acks(members):
                print("[launch] rejoin epoch failed to converge; "
                      "falling back to restart", flush=True)
                first_failure = _el.RESHARD_FALLBACK_EXIT
                kill_all()
                break
        if first_failure:
            break
        # 2. Reap exits.
        for wid in sorted(live):
            proc, sink, t = live[wid]
            rc = proc.poll()
            if rc is None:
                continue
            del live[wid]
            if rc == 0:
                record(wid, 0, sink, t)
                continue
            if rc == _el.RESHARD_FALLBACK_EXIT or not live:
                # A survivor that cannot carry its live state, or the
                # last member dying: nothing to reshard around.
                record(wid, rc, sink, t)
                first_failure = rc
                kill_all()
                break
            reason = {_el.HOST_LOSS_EXIT: "host-loss",
                      _el.HOST_JOIN_EXIT: "host-join"}.get(
                          rc, f"rc={rc}")
            _el.announce_departure(control_dir, wid, reason)
            record(wid, rc, sink, t, absorbed=True)
            dropped.append(wid)
            members = write_epoch()
            print(f"[launch] epoch {epoch}: worker {wid} left "
                  f"({reason}); resharding onto {len(members)} "
                  f"survivor(s)", flush=True)
            if not await_acks(members):
                print("[launch] reshard failed to converge; falling "
                      "back to restart", flush=True)
                first_failure = _el.RESHARD_FALLBACK_EXIT
                kill_all()
                break
            if rc == _el.HOST_JOIN_EXIT:
                pending_join.append((time.monotonic() + rejoin_delay,
                                     wid))
        if first_failure:
            break
        # 3. Per-rank stalls: kill the wedged rank, absorb it above.
        if monitor is not None and live:
            stalled = monitor.stalled_ranks(ranks=sorted(live))
            if stalled and len(stalled) == len(live):
                print(f"[launch] heartbeat stall on every live rank "
                      f"({monitor.timeout:.0f}s) — killing the cluster",
                      flush=True)
                first_failure = STALL_EXIT_CODE
                stalled_flag = True
                kill_all()
                break
            for wid in stalled:
                print(f"[launch] rank {wid} heartbeat stalled "
                      f"({monitor.timeout:.0f}s); killing it and "
                      f"resharding around it", flush=True)
                live[wid][0].kill()
        # 4. Overall deadline still bounds the attempt.
        if deadline is not None and now > deadline:
            first_failure = -9
            kill_all()
            break
        if live or pending_join:
            time.sleep(0.05)

    result = LaunchResult(first_failure=first_failure,
                          reshards=reshards, stalled=stalled_flag)
    for w, sink, t in done:
        t.join(timeout=5)
        w.output = "\n".join(sink)
        result.workers.append(w)
    result.workers.sort(key=lambda w: w.rank)
    return result


def backoff_delay(attempt: int, floor: float = 1.0, cap: float = 60.0,
                  rng: random.Random | None = None) -> float:
    """Seconds to wait before restart ``attempt`` (1-based).

    Exponential from ``floor`` (doubling per attempt, capped at ``cap``)
    plus 0–25% multiplicative jitter: a flaky shared dependency that
    fails N clusters at once must not have them all re-stampede it in
    lockstep. ``floor <= 0`` disables the wait entirely (tests).
    ``rng`` injects a seeded generator for deterministic schedules.
    """
    if attempt < 1:
        raise ValueError(f"attempt is 1-based, got {attempt}")
    if floor <= 0:
        return 0.0
    base = min(cap, floor * (2.0 ** (attempt - 1)))
    return base * (1.0 + (rng or random).uniform(0.0, 0.25))


def launch_elastic(
    part: str,
    nproc: int,
    max_restarts: int = 0,
    extra_args: list | None = None,
    min_restart_interval: float = 1.0,
    restart_window: float | None = None,
    backoff_cap: float = 60.0,
    **kwargs,
) -> LaunchResult:
    """:func:`launch` with elastic recovery — the failure-handling layer
    the reference lacks entirely (SURVEY.md §5: a dead gloo rank just
    hangs the cluster). On failure the whole cluster is respawned (fresh
    coordinator port) up to ``max_restarts`` times; when the part was
    given a ``--ckpt-dir`` and a checkpoint exists, retries append
    ``--resume`` so training continues from the last saved step instead
    of restarting from scratch.

    Restarts back off exponentially from ``min_restart_interval``
    (doubling per attempt up to ``backoff_cap``, with jitter —
    :func:`backoff_delay`), so a persistent failure burns budget slowly
    instead of crash-looping. ``restart_window`` makes the budget a
    SLIDING window: only restarts within the last ``restart_window``
    seconds count against ``max_restarts``, so a long healthy run that
    hits one preemption a day restarts indefinitely while a crash loop
    still stops after ``max_restarts`` attempts. ``None`` keeps the
    lifetime budget. Extra ``kwargs`` reach :func:`launch` — pass
    ``heartbeat_timeout`` to also arm the stall watchdog per attempt.
    """
    if max_restarts < 0:
        raise ValueError("max_restarts must be >= 0")
    extra = list(extra_args or [])
    ckpt_dir = None
    for idx, tok in enumerate(extra):
        if tok == "--ckpt-dir":
            if idx + 1 >= len(extra):
                raise ValueError("--ckpt-dir requires a value")
            ckpt_dir = extra[idx + 1]
        elif tok.startswith("--ckpt-dir="):
            ckpt_dir = tok.split("=", 1)[1]
    restart_times: deque = deque()  # monotonic stamps of restarts done
    attempt = 0
    while True:
        args = list(extra)
        if attempt > 0 and ckpt_dir and "--resume" not in args:
            from tpu_ddp.utils.checkpoint import latest_step
            if latest_step(ckpt_dir) is not None:
                args.append("--resume")
        res = launch(part, nproc, extra_args=args, **kwargs)
        res.restarts = attempt
        if res.ok:
            break
        # Budget for one more restart? Under a sliding window, stamps
        # older than the window no longer count.
        now = time.monotonic()
        if restart_window is not None:
            while restart_times and now - restart_times[0] \
                    > restart_window:
                restart_times.popleft()
            if len(restart_times) >= max_restarts:
                break
        elif attempt >= max_restarts:
            break
        attempt += 1
        delay = backoff_delay(attempt, floor=min_restart_interval,
                              cap=backoff_cap)
        why = "stalled" if res.stalled else f"rc={res.returncode}"
        print(f"[launch] attempt failed ({why}); restart {attempt} in "
              f"{delay:.2f}s", flush=True)
        if delay > 0:
            time.sleep(delay)
        restart_times.append(time.monotonic())
        kwargs.pop("port", None)  # fresh coordinator port per attempt
    return res


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tpu_ddp.launch",
        description="spawn an N-process local cluster running one part")
    p.add_argument("part", metavar="part|script.py",
                   help=f"one of {', '.join(PARTS)}, or a path to a "
                        "*.py CLI honouring the launch contract")
    p.add_argument("--nproc", type=int, required=True,
                   help="number of rank processes (the --num-nodes value)")
    p.add_argument("--platform", default="cpu",
                   help="JAX platform for workers (default cpu; use tpu "
                        "only with per-process device isolation)")
    p.add_argument("--devices-per-proc", type=int, default=1,
                   help="forced CPU device count per worker (cpu only)")
    p.add_argument("--port", type=int, default=None,
                   help="coordinator port (default: pick a free one)")
    p.add_argument("--max-restarts", type=int, default=0,
                   help="respawn the cluster up to N times on failure, "
                        "resuming from --ckpt-dir when possible")
    p.add_argument("--min-restart-interval", type=float, default=1.0,
                   help="backoff floor in seconds before the first "
                        "restart; doubles per attempt with jitter "
                        "(<= 0 restarts immediately)")
    p.add_argument("--restart-window", type=float, default=None,
                   help="count only restarts within the last N seconds "
                        "against --max-restarts (default: lifetime)")
    p.add_argument("--heartbeat-timeout", type=float, default=None,
                   help="kill + restart a cluster whose ranks all stop "
                        "completing steps for N seconds (stall watchdog)")
    p.add_argument("--dispatch-depth", type=int, default=None,
                   help="train steps kept in flight per worker before a "
                        "forced host sync (async dispatch pipeline, "
                        "tpu_ddp/train/pipeline.py); 0 = synchronous "
                        "loop. Sets TPU_DDP_DISPATCH_DEPTH for every "
                        "rank (default: the workers' config default)")
    p.add_argument("--grad-compress", default=None,
                   choices=("none", "bf16", "int8", "int8-noef"),
                   help="gradient wire format for the sync collectives "
                        "(tpu_ddp/parallel/compress.py): bf16 halves, "
                        "int8 ~quarters the bytes on the wire (int8 "
                        "carries an error-feedback residual; int8-noef "
                        "is the ablation without it). Sets "
                        "TPU_DDP_GRAD_COMPRESS for every rank")
    p.add_argument("--pp-schedule", default=None,
                   choices=("gpipe", "1f1b", "interleaved", "zerobubble"),
                   help="pipeline tick schedule for the pp rung "
                        "(tpu_ddp/parallel/pipeline.py): gpipe (AD of "
                        "the forward scan), 1f1b (O(pp) activation "
                        "residency), interleaved (virtual stages, "
                        "bubble / pp_virtual) or zerobubble (B-weight "
                        "fills the cooldown). Sets TPU_DDP_PP_SCHEDULE "
                        "for every rank")
    p.add_argument("--pp-microbatches", type=int, default=None,
                   help="microbatches per pipeline step (0 = auto, one "
                        "per stage). Sets TPU_DDP_PP_MICROBATCHES for "
                        "every rank")
    p.add_argument("--pp-virtual", type=int, default=None,
                   help="virtual stage chunks per physical stage "
                        "(interleaved schedule only; needs num_layers "
                        "divisible by pp*pp_virtual). Sets "
                        "TPU_DDP_PP_VIRTUAL for every rank")
    p.add_argument("--remat", default=None,
                   choices=("none", "blocks", "conv_stages", "dots"),
                   help="activation rematerialization policy "
                        "(tpu_ddp/memory/): which model stages "
                        "recompute in the backward pass instead of "
                        "saving activations — 'blocks' (per residual/"
                        "transformer block), 'conv_stages' (per "
                        "resolution stage, conv families), 'dots' "
                        "(save matmul outputs only). Sets "
                        "TPU_DDP_REMAT for every rank")
    p.add_argument("--act-dtype", default=None,
                   choices=("compute", "bf16", "f32"),
                   help="saved-residual dtype at remat-stage "
                        "boundaries (tpu_ddp/memory/): what autodiff "
                        "stores between forward and backward; stage "
                        "arithmetic stays in compute_dtype. Sets "
                        "TPU_DDP_ACT_DTYPE for every rank")
    p.add_argument("--overlap", action="store_true",
                   help="bucketize gradients in reverse-autodiff order "
                        "and issue each bucket's collective from inside "
                        "the backward pass (torch DDP's reducer; "
                        "tpu_ddp/parallel/overlap.py), with the sharded "
                        "weight update on the all_reduce/fused rungs. "
                        "Sets TPU_DDP_OVERLAP for every rank")
    p.add_argument("--bucket-mb", type=int, default=None,
                   help="bucket payload target in MiB for --overlap "
                        "(torch DDP's bucket_cap_mb; default 25). Sets "
                        "TPU_DDP_BUCKET_MB for every rank")
    p.add_argument("--elastic-reshard", action="store_true",
                   help="on membership change (a rank lost, stalled, "
                        "or rejoining) reshard the survivors' LIVE "
                        "TrainState onto a rebuilt mesh instead of "
                        "killing the cluster "
                        "(tpu_ddp/resilience/elastic.py + "
                        "parallel/redistribute.py); failed reshards "
                        "still fall back to --max-restarts checkpoint "
                        "recovery. Sets TPU_DDP_ELASTIC_RESHARD for "
                        "every rank")
    p.add_argument("--fleet-health", default=None, choices=("0", "1"),
                   help="replica health tracking + deterministic "
                        "request migration in the serving Router "
                        "(tpu_ddp/fleet/router.py); '0' = fail-fast. "
                        "Sets TPU_DDP_FLEET_HEALTH for every rank")
    p.add_argument("--fleet-probe-backoff-ms", type=float, default=None,
                   help="initial probe-re-admission backoff for an "
                        "unhealthy replica, doubling per consecutive "
                        "failure (default 200). Sets "
                        "TPU_DDP_FLEET_HEALTH_BACKOFF_MS for every rank")
    p.add_argument("--fleet-step-deadline-ms", type=float, default=None,
                   help="per-replica step deadline; a step exceeding "
                        "it counts as a failure (0 disables). Sets "
                        "TPU_DDP_FLEET_HEALTH_DEADLINE_MS for every "
                        "rank")
    p.add_argument("--fleet-retry-budget", type=int, default=None,
                   help="migrations allowed per request before the "
                        "Router sheds it (default 3). Sets "
                        "TPU_DDP_FLEET_RETRY_BUDGET for every rank")
    p.add_argument("--serve-queue-limit", type=int, default=None,
                   help="bounded serving admission queue; submits "
                        "beyond this many waiting requests are shed "
                        "(0 = unbounded). Sets TPU_DDP_SERVE_QUEUE_LIMIT "
                        "for every rank")
    p.add_argument("--serve-shed-ms", type=float, default=None,
                   help="shed a queued request that has not started "
                        "prefill after this many ms (0 disables). Sets "
                        "TPU_DDP_SERVE_SHED_MS for every rank")
    p.add_argument("--fleet-autoscale", default=None, choices=("0", "1"),
                   help="autoscaling replica lifecycle control plane "
                        "(tpu_ddp/fleet/autoscale.py): scale-up boots "
                        "replicas from the publisher's full-push path, "
                        "scale-down drains via bitwise continuation "
                        "migration. Sets TPU_DDP_FLEET_AUTOSCALE for "
                        "every rank")
    p.add_argument("--scale-cooldown-ms", type=float, default=None,
                   help="minimum ms between autoscaler actions "
                        "(default 1000); with hysteresis, what keeps a "
                        "flash crowd from thrashing the fleet. Sets "
                        "TPU_DDP_SCALE_COOLDOWN_MS for every rank")
    p.add_argument("--tenant-classes", default=None,
                   help="SLO classes for multi-tenant serving: comma-"
                        "separated name=weight[:deadline_ms[:token_"
                        "budget]] (e.g. 'gold=3,bronze=1'); empty = "
                        "single-tenant FIFO. Sets "
                        "TPU_DDP_TENANT_CLASSES for every rank")
    p.add_argument("--publish-every", type=int, default=None,
                   help="publish a versioned weight update to "
                        "subscribed serving engines every this many "
                        "trainer steps (0 = off). Sets "
                        "TPU_DDP_PUBLISH_EVERY for every rank")
    p.add_argument("--publish-wire", default=None,
                   choices=("none", "bf16", "int8", "sparse"),
                   help="wire format for pushed weight deltas "
                        "(tpu_ddp/publish/): dense f32, bf16, "
                        "error-feedback int8, or lossless sparse "
                        "(zero-chunk elision — the MoE expert-delta "
                        "wire). Sets TPU_DDP_PUBLISH_WIRE for every "
                        "rank")
    p.add_argument("--publish-max-staleness", type=int, default=None,
                   help="steps the trainer may run ahead of the "
                        "slowest subscriber before publishing blocks "
                        "(0 = unbounded). Sets "
                        "TPU_DDP_PUBLISH_MAX_STALENESS for every rank")
    p.add_argument("--spec-k", type=int, default=None,
                   help="speculative decoding: proposals verified per "
                        "serving engine step (0 = off, the one-token "
                        "baseline; tpu_ddp/serve/speculative.py). Sets "
                        "TPU_DDP_SPEC_K for every rank")
    p.add_argument("--spec-draft", default=None,
                   help="draft family for speculation: 'chain' "
                        "(bitwise-exact same-program schedule), "
                        "'self-<j>' (early exit over the target's "
                        "first j blocks) or 'quant' (full-depth int8 "
                        "twin). Sets TPU_DDP_SPEC_DRAFT for every rank")
    p.add_argument("--decode-quant", default=None,
                   choices=("none", "int8"),
                   help="weight-only int8 decode compute "
                        "(tpu_ddp/ops/quant.py): per-channel "
                        "quantization of every decode-path projection "
                        "at engine construction. Sets "
                        "TPU_DDP_DECODE_QUANT for every rank")
    p.add_argument("--kv-tiers", type=int, default=None,
                   choices=(1, 2, 3),
                   help="tiered KV pool (tpu_ddp/serve/kv_pool.py): "
                        "1 = single-tier, 2 adds an in-HBM quantized "
                        "cold tier, 3 adds host-memory spill behind "
                        "it. Sets TPU_DDP_KV_TIERS for every rank")
    p.add_argument("--kv-cold-dtype", default=None,
                   choices=("int8", "bf16"),
                   help="cold-page codec for --kv-tiers >= 2: "
                        "per-token-row int8 or a bf16 downcast "
                        "(lossless under a bf16 hot cache dtype). "
                        "Sets TPU_DDP_KV_COLD_DTYPE for every rank")
    p.add_argument("--cp-prefill", default=None,
                   choices=("off", "ring", "ulysses"),
                   help="context-parallel chunked prefill "
                        "(tpu_ddp/serve/long_context.py): shard each "
                        "prefill chunk over the serving mesh's sp "
                        "axis. Sets TPU_DDP_CP_PREFILL for every rank")
    p.add_argument("--moe-experts", type=int, default=None,
                   help="experts per MoE MLP layer (0 = dense; "
                        "tpu_ddp/parallel/moe.py). Sets "
                        "TPU_DDP_MOE_EXPERTS for every rank")
    p.add_argument("--moe-top-k", type=int, default=None,
                   help="routed experts per token (1 = Switch, 2 = "
                        "GShard). Sets TPU_DDP_MOE_TOP_K for every "
                        "rank")
    p.add_argument("--moe-capacity", type=float, default=None,
                   help="expert capacity factor: slots per expert = "
                        "ceil(T * capacity * top_k / E); higher = "
                        "fewer dropped tokens, more padded compute. "
                        "Sets TPU_DDP_MOE_CAPACITY for every rank")
    p.add_argument("--diloco-h", type=int, default=None,
                   help="DiLoCo inner steps per outer round (0 = off; "
                        "tpu_ddp/train/outer.py): each group runs H "
                        "local steps, only the outer pseudo-gradient "
                        "exchange crosses groups. Sets "
                        "TPU_DDP_DILOCO_H for every rank")
    p.add_argument("--diloco-outer-lr", type=float, default=None,
                   help="outer Nesterov-momentum learning rate over "
                        "pseudo-gradients (1 with zero momentum = "
                        "plain parameter averaging). Sets "
                        "TPU_DDP_DILOCO_OUTER_LR for every rank")
    p.add_argument("--diloco-outer-momentum", type=float, default=None,
                   help="outer Nesterov momentum coefficient in "
                        "[0, 1). Sets TPU_DDP_DILOCO_OUTER_MOMENTUM "
                        "for every rank")
    p.add_argument("--diloco-outer-wire", default=None,
                   choices=("none", "bf16", "int8", "sparse"),
                   help="cross-group pseudo-gradient wire format (the "
                        "publish/ delta codec vocabulary; 'none' ships "
                        "bitwise full tensors). Sets "
                        "TPU_DDP_DILOCO_OUTER_WIRE for every rank")
    p.add_argument("--autotune", default=None,
                   choices=("off", "cached", "search"),
                   help="perf-knob autotuning (tpu_ddp/tune/): 'cached' "
                        "applies a previously searched tuning for this "
                        "workload fingerprint, 'search' runs measured "
                        "trials and persists the winner (single-process "
                        "only; multi-process ranks fall back to 'cached' "
                        "semantics). Sets TPU_DDP_AUTOTUNE for every "
                        "rank")
    p.add_argument("--audit", default=None,
                   choices=("off", "warn", "error"),
                   help="construction-time graph audit "
                        "(tpu_ddp/analysis/): statically check buffer "
                        "donation and collective precision of every "
                        "rank's compiled step programs before training "
                        "starts; 'error' fails construction on a "
                        "finding. Sets TPU_DDP_AUDIT for every rank")
    args, extra = p.parse_known_args(argv)
    env = {}
    if args.dispatch_depth is not None:
        if args.dispatch_depth < 0:
            p.error(f"--dispatch-depth must be >= 0, "
                    f"got {args.dispatch_depth}")
        env["TPU_DDP_DISPATCH_DEPTH"] = str(args.dispatch_depth)
    if args.grad_compress is not None:
        env["TPU_DDP_GRAD_COMPRESS"] = args.grad_compress
    if args.pp_schedule is not None:
        env["TPU_DDP_PP_SCHEDULE"] = args.pp_schedule
    if args.pp_microbatches is not None:
        if args.pp_microbatches < 0:
            p.error(f"--pp-microbatches must be >= 0, "
                    f"got {args.pp_microbatches}")
        env["TPU_DDP_PP_MICROBATCHES"] = str(args.pp_microbatches)
    if args.pp_virtual is not None:
        if args.pp_virtual < 1:
            p.error(f"--pp-virtual must be >= 1, got {args.pp_virtual}")
        env["TPU_DDP_PP_VIRTUAL"] = str(args.pp_virtual)
    if args.remat is not None:
        env["TPU_DDP_REMAT"] = args.remat
    if args.act_dtype is not None:
        env["TPU_DDP_ACT_DTYPE"] = args.act_dtype
    if args.fleet_health is not None:
        env["TPU_DDP_FLEET_HEALTH"] = args.fleet_health
    if args.fleet_probe_backoff_ms is not None:
        if args.fleet_probe_backoff_ms <= 0:
            p.error(f"--fleet-probe-backoff-ms must be > 0, "
                    f"got {args.fleet_probe_backoff_ms}")
        env["TPU_DDP_FLEET_HEALTH_BACKOFF_MS"] = \
            str(args.fleet_probe_backoff_ms)
    if args.fleet_step_deadline_ms is not None:
        if args.fleet_step_deadline_ms < 0:
            p.error(f"--fleet-step-deadline-ms must be >= 0, "
                    f"got {args.fleet_step_deadline_ms}")
        env["TPU_DDP_FLEET_HEALTH_DEADLINE_MS"] = \
            str(args.fleet_step_deadline_ms)
    if args.fleet_retry_budget is not None:
        if args.fleet_retry_budget < 0:
            p.error(f"--fleet-retry-budget must be >= 0, "
                    f"got {args.fleet_retry_budget}")
        env["TPU_DDP_FLEET_RETRY_BUDGET"] = str(args.fleet_retry_budget)
    if args.serve_queue_limit is not None:
        if args.serve_queue_limit < 0:
            p.error(f"--serve-queue-limit must be >= 0, "
                    f"got {args.serve_queue_limit}")
        env["TPU_DDP_SERVE_QUEUE_LIMIT"] = str(args.serve_queue_limit)
    if args.serve_shed_ms is not None:
        if args.serve_shed_ms < 0:
            p.error(f"--serve-shed-ms must be >= 0, "
                    f"got {args.serve_shed_ms}")
        env["TPU_DDP_SERVE_SHED_MS"] = str(args.serve_shed_ms)
    if args.fleet_autoscale is not None:
        env["TPU_DDP_FLEET_AUTOSCALE"] = args.fleet_autoscale
    if args.scale_cooldown_ms is not None:
        if args.scale_cooldown_ms <= 0:
            p.error(f"--scale-cooldown-ms must be > 0, "
                    f"got {args.scale_cooldown_ms}")
        env["TPU_DDP_SCALE_COOLDOWN_MS"] = str(args.scale_cooldown_ms)
    if args.tenant_classes is not None:
        for ent in args.tenant_classes.split(","):
            if ent.strip() and "=" not in ent:
                p.error(f"--tenant-classes entry {ent.strip()!r}: "
                        "expected name=weight[:deadline_ms[:token_"
                        "budget]]")
        env["TPU_DDP_TENANT_CLASSES"] = args.tenant_classes
    if args.publish_every is not None:
        if args.publish_every < 0:
            p.error(f"--publish-every must be >= 0, "
                    f"got {args.publish_every}")
        env["TPU_DDP_PUBLISH_EVERY"] = str(args.publish_every)
    if args.publish_wire is not None:
        env["TPU_DDP_PUBLISH_WIRE"] = args.publish_wire
    if args.publish_max_staleness is not None:
        if args.publish_max_staleness < 0:
            p.error(f"--publish-max-staleness must be >= 0, "
                    f"got {args.publish_max_staleness}")
        env["TPU_DDP_PUBLISH_MAX_STALENESS"] = \
            str(args.publish_max_staleness)
    if args.spec_k is not None:
        if args.spec_k < 0:
            p.error(f"--spec-k must be >= 0, got {args.spec_k}")
        env["TPU_DDP_SPEC_K"] = str(args.spec_k)
    if args.spec_draft is not None:
        sd = args.spec_draft.strip()
        if sd not in ("chain", "quant") and not (
                sd.startswith("self-")
                and sd[len("self-"):].isdigit()
                and int(sd[len("self-"):]) >= 1):
            p.error(f"--spec-draft {args.spec_draft!r}: expected "
                    "chain, self-<j> (j >= 1) or quant")
        env["TPU_DDP_SPEC_DRAFT"] = args.spec_draft
    if args.decode_quant is not None:
        env["TPU_DDP_DECODE_QUANT"] = args.decode_quant
    if args.kv_tiers is not None:
        env["TPU_DDP_KV_TIERS"] = str(args.kv_tiers)
    if args.kv_cold_dtype is not None:
        env["TPU_DDP_KV_COLD_DTYPE"] = args.kv_cold_dtype
    if args.cp_prefill is not None:
        env["TPU_DDP_CP_PREFILL"] = args.cp_prefill
    if args.moe_experts is not None:
        if args.moe_experts < 0:
            p.error(f"--moe-experts must be >= 0, got "
                    f"{args.moe_experts}")
        env["TPU_DDP_MOE_EXPERTS"] = str(args.moe_experts)
    if args.moe_top_k is not None:
        if args.moe_top_k < 1:
            p.error(f"--moe-top-k must be >= 1, got {args.moe_top_k}")
        env["TPU_DDP_MOE_TOP_K"] = str(args.moe_top_k)
    if args.moe_capacity is not None:
        if not args.moe_capacity > 0:
            p.error(f"--moe-capacity must be > 0, got "
                    f"{args.moe_capacity}")
        env["TPU_DDP_MOE_CAPACITY"] = str(args.moe_capacity)
    if args.diloco_h is not None:
        if args.diloco_h < 0:
            p.error(f"--diloco-h must be >= 0, got {args.diloco_h}")
        env["TPU_DDP_DILOCO_H"] = str(args.diloco_h)
    if args.diloco_outer_lr is not None:
        if not args.diloco_outer_lr > 0:
            p.error(f"--diloco-outer-lr must be > 0, got "
                    f"{args.diloco_outer_lr}")
        env["TPU_DDP_DILOCO_OUTER_LR"] = str(args.diloco_outer_lr)
    if args.diloco_outer_momentum is not None:
        if not 0.0 <= args.diloco_outer_momentum < 1.0:
            p.error(f"--diloco-outer-momentum must be in [0, 1), got "
                    f"{args.diloco_outer_momentum}")
        env["TPU_DDP_DILOCO_OUTER_MOMENTUM"] = str(
            args.diloco_outer_momentum)
    if args.diloco_outer_wire is not None:
        env["TPU_DDP_DILOCO_OUTER_WIRE"] = args.diloco_outer_wire
    if args.autotune is not None:
        env["TPU_DDP_AUTOTUNE"] = args.autotune
    if args.audit is not None:
        env["TPU_DDP_AUDIT"] = args.audit
    if args.overlap:
        env["TPU_DDP_OVERLAP"] = "1"
    if args.bucket_mb is not None:
        if args.bucket_mb <= 0:
            p.error(f"--bucket-mb must be > 0, got {args.bucket_mb}")
        env["TPU_DDP_BUCKET_MB"] = str(args.bucket_mb)
    if args.elastic_reshard:
        env["TPU_DDP_ELASTIC_RESHARD"] = "1"
    env = env or None
    try:
        res = launch_elastic(args.part, args.nproc,
                             max_restarts=args.max_restarts,
                             extra_args=extra, env=env,
                             min_restart_interval=args.min_restart_interval,
                             restart_window=args.restart_window,
                             heartbeat_timeout=args.heartbeat_timeout,
                             elastic_reshard=args.elastic_reshard,
                             platform=args.platform,
                             devices_per_proc=args.devices_per_proc,
                             port=args.port)
    except (ValueError, FileNotFoundError) as e:
        p.error(str(e))  # clean usage error, not a traceback
    for w in res.workers:
        print(f"[launch] rank {w.rank} exited {w.returncode}")
    if res.stalled:
        print("[launch] final attempt killed by the heartbeat watchdog")
    if res.reshards:
        print(f"[launch] absorbed {res.reshards} membership epoch(s) "
              "by live resharding")
    if res.restarts:
        print(f"[launch] recovered after {res.restarts} restart(s)")
    return res.returncode


if __name__ == "__main__":
    sys.exit(main())
