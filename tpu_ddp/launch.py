"""Local multi-process launcher — the cluster-in-a-box analogue of the
reference's launch recipe.

The reference is launched by hand on every node of a 4-node cluster with
the same command (reference README.md:8-19)::

    python main.py --num-nodes 4 --rank R --master-ip 10.10.1.1 --master-port 4000

This module automates that loop on ONE host: it spawns ``nproc`` worker
processes, each running a part's ``main.py`` with ``--rank i`` and a shared
``127.0.0.1`` coordinator, so the real multi-process rendezvous path
(``jax.distributed.initialize`` -> cross-process collectives) is exercised
without a cluster — the TPU-native analogue of gloo's multi-process
single-host mode (SURVEY.md §4). On an actual TPU pod each host still runs
its part ``main.py`` directly, exactly like the reference.

CLI::

    python -m tpu_ddp.launch part2b --nproc 4 [--platform cpu]
        [--devices-per-proc 1] [--port auto] [part args...]
"""

from __future__ import annotations

import argparse
import os
import random
import socket
import subprocess
import sys
import tempfile
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from pathlib import Path

from tpu_ddp.resilience.watchdog import (HEARTBEAT_ENV, STALL_EXIT_CODE,
                                         HeartbeatMonitor)

PARTS_DIR = Path(__file__).resolve().parent.parent / "parts"
PARTS = ("part1", "part2a", "part2b", "part3", "part4", "part5")


def find_free_port() -> int:
    """Ask the OS for a free TCP port for the coordinator."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class WorkerResult:
    rank: int
    returncode: int
    output: str = ""


@dataclass
class LaunchResult:
    workers: list = field(default_factory=list)
    # Exit code of the FIRST rank observed failing — the root cause, not
    # the -9 of bystander ranks reaped afterwards. 0 when all succeeded.
    first_failure: int = 0
    # Number of cluster restarts performed before this (final) attempt —
    # nonzero only for launch_elastic.
    restarts: int = 0
    # True when the heartbeat watchdog killed this attempt: every rank
    # was alive but none had completed a step within heartbeat_timeout
    # (the hung-collective failure mode — see resilience/watchdog.py).
    stalled: bool = False

    @property
    def returncode(self) -> int:
        if self.first_failure:
            return self.first_failure
        # Fallback (e.g. hand-built results): any nonzero rank fails the
        # launch, including negative signal-kill codes.
        return next((w.returncode for w in self.workers
                     if w.returncode != 0), 0)

    @property
    def ok(self) -> bool:
        return self.returncode == 0

    def output_of(self, rank: int) -> str:
        for w in self.workers:
            if w.rank == rank:
                return w.output
        raise KeyError(rank)


def _drain(proc, rank: int, sink: list, echo: bool) -> None:
    """Stream one worker's stdout, prefixing lines with its rank."""
    for raw in proc.stdout:
        line = raw.rstrip("\n")
        sink.append(line)
        if echo:
            print(f"[rank {rank}] {line}", flush=True)
    proc.stdout.close()


def launch(
    part: str,
    nproc: int,
    extra_args: list | None = None,
    platform: str = "cpu",
    devices_per_proc: int = 1,
    port: int | None = None,
    env: dict | None = None,
    echo: bool = True,
    timeout: float | None = None,
    heartbeat_timeout: float | None = None,
    heartbeat_dir: str | None = None,
) -> LaunchResult:
    """Run ``nproc`` rank processes of ``parts/<part>/main.py`` and wait.

    Each worker gets ``JAX_PLATFORMS=<platform>`` and (on cpu) a forced
    host-platform device count of ``devices_per_proc``, so a laptop/CI host
    emulates an ``nproc``-node cluster with ``nproc * devices_per_proc``
    total dp slots. Extra env wins over the computed defaults.

    ``heartbeat_timeout`` arms the watchdog: workers inherit
    ``TPU_DDP_HEARTBEAT_DIR`` (a fresh temp dir unless ``heartbeat_dir``
    pins it) and touch a per-rank file each step; once heartbeats exist,
    a cluster whose NEWEST beat is older than the deadline is killed and
    reported with ``stalled=True`` / exit :data:`STALL_EXIT_CODE` —
    catching hung collectives in seconds instead of waiting out
    ``timeout`` (which still bounds never-started clusters).
    """
    if nproc < 1:
        raise ValueError("nproc must be >= 1")
    if part in PARTS:
        script = PARTS_DIR / part / "main.py"
    elif part.endswith(".py"):
        # Any CLI honouring the reference launch contract
        # (--num-nodes/--rank/--master-ip/--master-port) can be
        # clustered, e.g. examples/lm_train.py. Relative paths resolve
        # against the repo root — the same cwd the workers get — so the
        # call works from any directory.
        p = Path(part)
        script = (p if p.is_absolute() else PARTS_DIR.parent / p).resolve()
    else:
        raise ValueError(f"unknown part {part!r}; available: {PARTS} "
                         "or a path to a *.py CLI")
    if not script.exists():
        raise FileNotFoundError(
            f"{script}: the launcher runs source-checkout CLIs "
            "(parts/ and examples/ are not part of the installed "
            "package)")
    port = port or find_free_port()
    monitor = None
    if heartbeat_timeout is not None:
        hb_dir = heartbeat_dir or tempfile.mkdtemp(prefix="tpu_ddp_hb_")
        monitor = HeartbeatMonitor(hb_dir, nproc, heartbeat_timeout)

    procs = []
    sinks = []
    threads = []
    for rank in range(nproc):
        child_env = dict(os.environ)
        child_env["JAX_PLATFORMS"] = platform
        if monitor is not None:
            child_env[HEARTBEAT_ENV] = monitor.directory
        if platform == "cpu":
            # Replace (not append) any inherited forced device count.
            flags = [f for f in child_env.get("XLA_FLAGS", "").split()
                     if "xla_force_host_platform_device_count" not in f]
            flags.append("--xla_force_host_platform_device_count="
                         f"{devices_per_proc}")
            child_env["XLA_FLAGS"] = " ".join(flags)
        if env:
            child_env.update(env)
        cmd = [sys.executable, str(script),
               "--num-nodes", str(nproc),
               "--rank", str(rank),
               "--master-ip", "127.0.0.1",
               "--master-port", str(port)] + list(extra_args or [])
        proc = subprocess.Popen(
            cmd, env=child_env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
            cwd=str(PARTS_DIR.parent))
        sink: list = []
        t = threading.Thread(target=_drain, args=(proc, rank, sink, echo),
                             daemon=True)
        t.start()
        procs.append(proc)
        sinks.append(sink)
        threads.append(t)

    # Poll all ranks concurrently against ONE shared deadline. Sequential
    # proc.wait() calls would hang forever (timeout=None) or for
    # nproc*timeout when one rank dies early and the survivors block in
    # the rendezvous/collective waiting for it.
    deadline = None if timeout is None else time.monotonic() + timeout
    rcs: dict = {}
    first_failure = 0
    while len(rcs) < len(procs):
        for rank, proc in enumerate(procs):
            if rank in rcs:
                continue
            rc = proc.poll()
            if rc is None:
                continue
            rcs[rank] = rc
            if rc != 0:
                first_failure = first_failure or rc
                # A dead rank leaves the others blocked in a collective;
                # reap them now instead of waiting out the timeout.
                for other in procs:
                    if other.poll() is None:
                        other.kill()
        if len(rcs) < len(procs):
            if monitor is not None and not first_failure \
                    and monitor.stalled():
                # Watchdog: every remaining rank is alive but the whole
                # cluster stopped completing steps — a hung collective.
                # Kill it now; launch_elastic will restart with backoff.
                print(f"[launch] heartbeat stall: no step completed in "
                      f"{monitor.timeout:.0f}s — killing the cluster",
                      flush=True)
                for rank, proc in enumerate(procs):
                    if rank not in rcs:
                        proc.kill()
                        rcs[rank] = proc.wait()
                first_failure = STALL_EXIT_CODE
                break
            if deadline is not None and time.monotonic() > deadline:
                # A rank may have exited with a real code (even 0, or a
                # real signal like SIGSEGV) between the last poll and
                # this sweep — record whatever wait() reports, and prefer
                # any such real code as the root cause over the -9 of
                # ranks we killed ourselves (checked only after the whole
                # sweep, so an early hung rank cannot mask a later rank's
                # real failure).
                sweep_real = 0
                for rank, proc in enumerate(procs):
                    if rank not in rcs:
                        proc.kill()
                        rc = proc.wait()
                        rcs[rank] = rc
                        if rc not in (0, -9):
                            sweep_real = sweep_real or rc
                first_failure = first_failure or sweep_real or -9
                break
            time.sleep(0.05)
    result = LaunchResult(first_failure=first_failure,
                          stalled=first_failure == STALL_EXIT_CODE)
    for rank in range(len(procs)):
        result.workers.append(WorkerResult(rank=rank, returncode=rcs[rank]))
    for t in threads:
        t.join(timeout=5)
    for w, sink in zip(result.workers, sinks):
        w.output = "\n".join(sink)
    return result


def backoff_delay(attempt: int, floor: float = 1.0, cap: float = 60.0,
                  rng: random.Random | None = None) -> float:
    """Seconds to wait before restart ``attempt`` (1-based).

    Exponential from ``floor`` (doubling per attempt, capped at ``cap``)
    plus 0–25% multiplicative jitter: a flaky shared dependency that
    fails N clusters at once must not have them all re-stampede it in
    lockstep. ``floor <= 0`` disables the wait entirely (tests).
    ``rng`` injects a seeded generator for deterministic schedules.
    """
    if attempt < 1:
        raise ValueError(f"attempt is 1-based, got {attempt}")
    if floor <= 0:
        return 0.0
    base = min(cap, floor * (2.0 ** (attempt - 1)))
    return base * (1.0 + (rng or random).uniform(0.0, 0.25))


def launch_elastic(
    part: str,
    nproc: int,
    max_restarts: int = 0,
    extra_args: list | None = None,
    min_restart_interval: float = 1.0,
    restart_window: float | None = None,
    backoff_cap: float = 60.0,
    **kwargs,
) -> LaunchResult:
    """:func:`launch` with elastic recovery — the failure-handling layer
    the reference lacks entirely (SURVEY.md §5: a dead gloo rank just
    hangs the cluster). On failure the whole cluster is respawned (fresh
    coordinator port) up to ``max_restarts`` times; when the part was
    given a ``--ckpt-dir`` and a checkpoint exists, retries append
    ``--resume`` so training continues from the last saved step instead
    of restarting from scratch.

    Restarts back off exponentially from ``min_restart_interval``
    (doubling per attempt up to ``backoff_cap``, with jitter —
    :func:`backoff_delay`), so a persistent failure burns budget slowly
    instead of crash-looping. ``restart_window`` makes the budget a
    SLIDING window: only restarts within the last ``restart_window``
    seconds count against ``max_restarts``, so a long healthy run that
    hits one preemption a day restarts indefinitely while a crash loop
    still stops after ``max_restarts`` attempts. ``None`` keeps the
    lifetime budget. Extra ``kwargs`` reach :func:`launch` — pass
    ``heartbeat_timeout`` to also arm the stall watchdog per attempt.
    """
    if max_restarts < 0:
        raise ValueError("max_restarts must be >= 0")
    extra = list(extra_args or [])
    ckpt_dir = None
    for idx, tok in enumerate(extra):
        if tok == "--ckpt-dir":
            if idx + 1 >= len(extra):
                raise ValueError("--ckpt-dir requires a value")
            ckpt_dir = extra[idx + 1]
        elif tok.startswith("--ckpt-dir="):
            ckpt_dir = tok.split("=", 1)[1]
    restart_times: deque = deque()  # monotonic stamps of restarts done
    attempt = 0
    while True:
        args = list(extra)
        if attempt > 0 and ckpt_dir and "--resume" not in args:
            from tpu_ddp.utils.checkpoint import latest_step
            if latest_step(ckpt_dir) is not None:
                args.append("--resume")
        res = launch(part, nproc, extra_args=args, **kwargs)
        res.restarts = attempt
        if res.ok:
            break
        # Budget for one more restart? Under a sliding window, stamps
        # older than the window no longer count.
        now = time.monotonic()
        if restart_window is not None:
            while restart_times and now - restart_times[0] \
                    > restart_window:
                restart_times.popleft()
            if len(restart_times) >= max_restarts:
                break
        elif attempt >= max_restarts:
            break
        attempt += 1
        delay = backoff_delay(attempt, floor=min_restart_interval,
                              cap=backoff_cap)
        why = "stalled" if res.stalled else f"rc={res.returncode}"
        print(f"[launch] attempt failed ({why}); restart {attempt} in "
              f"{delay:.2f}s", flush=True)
        if delay > 0:
            time.sleep(delay)
        restart_times.append(time.monotonic())
        kwargs.pop("port", None)  # fresh coordinator port per attempt
    return res


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tpu_ddp.launch",
        description="spawn an N-process local cluster running one part")
    p.add_argument("part", metavar="part|script.py",
                   help=f"one of {', '.join(PARTS)}, or a path to a "
                        "*.py CLI honouring the launch contract")
    p.add_argument("--nproc", type=int, required=True,
                   help="number of rank processes (the --num-nodes value)")
    p.add_argument("--platform", default="cpu",
                   help="JAX platform for workers (default cpu; use tpu "
                        "only with per-process device isolation)")
    p.add_argument("--devices-per-proc", type=int, default=1,
                   help="forced CPU device count per worker (cpu only)")
    p.add_argument("--port", type=int, default=None,
                   help="coordinator port (default: pick a free one)")
    p.add_argument("--max-restarts", type=int, default=0,
                   help="respawn the cluster up to N times on failure, "
                        "resuming from --ckpt-dir when possible")
    p.add_argument("--min-restart-interval", type=float, default=1.0,
                   help="backoff floor in seconds before the first "
                        "restart; doubles per attempt with jitter "
                        "(<= 0 restarts immediately)")
    p.add_argument("--restart-window", type=float, default=None,
                   help="count only restarts within the last N seconds "
                        "against --max-restarts (default: lifetime)")
    p.add_argument("--heartbeat-timeout", type=float, default=None,
                   help="kill + restart a cluster whose ranks all stop "
                        "completing steps for N seconds (stall watchdog)")
    p.add_argument("--dispatch-depth", type=int, default=None,
                   help="train steps kept in flight per worker before a "
                        "forced host sync (async dispatch pipeline, "
                        "tpu_ddp/train/pipeline.py); 0 = synchronous "
                        "loop. Sets TPU_DDP_DISPATCH_DEPTH for every "
                        "rank (default: the workers' config default)")
    p.add_argument("--grad-compress", default=None,
                   choices=("none", "bf16", "int8", "int8-noef"),
                   help="gradient wire format for the sync collectives "
                        "(tpu_ddp/parallel/compress.py): bf16 halves, "
                        "int8 ~quarters the bytes on the wire (int8 "
                        "carries an error-feedback residual; int8-noef "
                        "is the ablation without it). Sets "
                        "TPU_DDP_GRAD_COMPRESS for every rank")
    p.add_argument("--remat", default=None,
                   choices=("none", "blocks", "conv_stages", "dots"),
                   help="activation rematerialization policy "
                        "(tpu_ddp/memory/): which model stages "
                        "recompute in the backward pass instead of "
                        "saving activations — 'blocks' (per residual/"
                        "transformer block), 'conv_stages' (per "
                        "resolution stage, conv families), 'dots' "
                        "(save matmul outputs only). Sets "
                        "TPU_DDP_REMAT for every rank")
    p.add_argument("--act-dtype", default=None,
                   choices=("compute", "bf16", "f32"),
                   help="saved-residual dtype at remat-stage "
                        "boundaries (tpu_ddp/memory/): what autodiff "
                        "stores between forward and backward; stage "
                        "arithmetic stays in compute_dtype. Sets "
                        "TPU_DDP_ACT_DTYPE for every rank")
    p.add_argument("--autotune", default=None,
                   choices=("off", "cached", "search"),
                   help="perf-knob autotuning (tpu_ddp/tune/): 'cached' "
                        "applies a previously searched tuning for this "
                        "workload fingerprint, 'search' runs measured "
                        "trials and persists the winner (single-process "
                        "only; multi-process ranks fall back to 'cached' "
                        "semantics). Sets TPU_DDP_AUTOTUNE for every "
                        "rank")
    args, extra = p.parse_known_args(argv)
    env = {}
    if args.dispatch_depth is not None:
        if args.dispatch_depth < 0:
            p.error(f"--dispatch-depth must be >= 0, "
                    f"got {args.dispatch_depth}")
        env["TPU_DDP_DISPATCH_DEPTH"] = str(args.dispatch_depth)
    if args.grad_compress is not None:
        env["TPU_DDP_GRAD_COMPRESS"] = args.grad_compress
    if args.remat is not None:
        env["TPU_DDP_REMAT"] = args.remat
    if args.act_dtype is not None:
        env["TPU_DDP_ACT_DTYPE"] = args.act_dtype
    if args.autotune is not None:
        env["TPU_DDP_AUTOTUNE"] = args.autotune
    env = env or None
    try:
        res = launch_elastic(args.part, args.nproc,
                             max_restarts=args.max_restarts,
                             extra_args=extra, env=env,
                             min_restart_interval=args.min_restart_interval,
                             restart_window=args.restart_window,
                             heartbeat_timeout=args.heartbeat_timeout,
                             platform=args.platform,
                             devices_per_proc=args.devices_per_proc,
                             port=args.port)
    except (ValueError, FileNotFoundError) as e:
        p.error(str(e))  # clean usage error, not a traceback
    for w in res.workers:
        print(f"[launch] rank {w.rank} exited {w.returncode}")
    if res.stalled:
        print("[launch] final attempt killed by the heartbeat watchdog")
    if res.restarts:
        print(f"[launch] recovered after {res.restarts} restart(s)")
    return res.returncode


if __name__ == "__main__":
    sys.exit(main())
