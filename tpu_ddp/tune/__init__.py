"""tpu_ddp.tune — measured-trial autotuning over the perf-knob space.

The reference ladder is a *manual* search over sync strategies; this
repo's knob space has long outgrown hand-tuning (sync rung, wire
format, dispatch depth/grouping, prefetch, Pallas kernels, dtype — see
``space.KNOBS``). The tuner closes the loop from measurement:

- ``TPU_DDP_AUTOTUNE=search`` (or ``TrainConfig.autotune="search"`` /
  ``launch --autotune search``): run timed trials on the live workload
  (``runner.py``) under coordinate descent + successive halving
  (``search.py``), persist the winner to the fingerprint-keyed cache
  (``cache.py``), apply it;
- ``TPU_DDP_AUTOTUNE=cached``: apply a previously searched tuning when
  one exists for this exact workload fingerprint, warn-and-default
  otherwise — safe to leave on everywhere;
- ``off`` (default): the tuner does not exist.

:func:`resolve` is the single integration point — ``parts/common.py``
calls it before the model is built (all knobs applicable) and
``train/engine.py`` calls it as a fallback for direct ``Trainer``
construction (model-level knobs are dropped with a warning there).
Explicit ``TPU_DDP_*`` env pins always beat the tuner: a pinned knob is
neither searched nor overridden.
"""

from __future__ import annotations

import copy
import json
import os
import time

from tpu_ddp.tune import cache as tune_cache
from tpu_ddp.tune.runner import TrialRunner
from tpu_ddp.tune.search import run_search
from tpu_ddp.tune.space import (KNOBS, MODEL_LEVEL_FIELDS, Fingerprint,
                                fingerprint_for, knob_by_field,
                                searchable_knobs, workload_for)

__all__ = ["resolve", "apply_overrides", "tuned_vs_default",
           "fingerprint_for", "searchable_knobs", "KNOBS", "Fingerprint"]


def apply_overrides(cfg, overrides: dict, *, model_built: bool = False,
                    log=print):
    """A copy of ``cfg`` with tuned ``overrides`` applied and
    ``autotune`` disarmed. ``copy.copy`` + ``setattr``, never
    ``dataclasses.replace`` — replace() re-runs ``__post_init__``, which
    would re-read the env (re-arming ``TPU_DDP_AUTOTUNE`` into a
    recursion, and clobbering tuned values with env defaults).

    Skipped, with a log line naming why: fields pinned by their own
    ``TPU_DDP_*`` env var (the user's explicit pin wins), and — when
    ``model_built`` — model-level fields (``pallas_bn``,
    ``compute_dtype``) that can no longer take effect because
    ``get_model`` already ran.
    """
    out = copy.copy(cfg)
    out.autotune = "off"
    for field, value in overrides.items():
        knob = knob_by_field(field)
        if knob is None:
            log(f"[autotune] ignoring unknown override {field!r}")
            continue
        if os.environ.get(knob.env):
            log(f"[autotune] override {field}={value!r} skipped: "
                f"{knob.env} is explicitly set and pins the knob")
            continue
        if model_built and field in MODEL_LEVEL_FIELDS \
                and value != getattr(cfg, field):
            log(f"[autotune] override {field}={value!r} skipped: the "
                "model is already built (apply tunings via "
                "parts/common.py or launch --autotune to cover "
                "model-level knobs)")
            continue
        setattr(out, field, value)
    return out


def resolve(cfg, *, strategy: str = "none", mesh=None,
            model_built: bool = False, log=print):
    """Resolve ``cfg.autotune`` into a concrete config: search, load, or
    fall back to defaults — always returning a config with
    ``autotune="off"`` so downstream construction can't recurse."""
    mode = getattr(cfg, "autotune", "off")
    if mode == "off":
        return cfg

    import jax

    fp = fingerprint_for(cfg, strategy, mesh)
    hit = tune_cache.load(fp)
    if hit is not None:
        log(f"[autotune] cache hit: trials=0 "
            f"overrides={json.dumps(hit['overrides'], sort_keys=True)} "
            f"<- {hit['path']}")
        return apply_overrides(cfg, hit["overrides"],
                               model_built=model_built, log=log)

    if mode == "cached":
        log(f"[autotune] cached mode: no entry for {fp.key()}; using "
            "defaults (populate with TPU_DDP_AUTOTUNE=search)")
        return apply_overrides(cfg, {}, model_built=model_built, log=log)

    # mode == "search"
    if jax.process_count() > 1:
        # Per-process trial loops would run collectives on different
        # schedules across hosts (deadlock) and measure contended
        # devices (garbage). Search single-process, share via the cache.
        log("[autotune] search mode refused under multi-process "
            f"(process_count={jax.process_count()}); using defaults — "
            "run TPU_DDP_AUTOTUNE=search single-process to populate "
            "the cache, then use TPU_DDP_AUTOTUNE=cached")
        return apply_overrides(cfg, {}, model_built=model_built, log=log)

    ctx = workload_for(cfg, strategy, mesh)
    knobs = searchable_knobs(cfg, ctx)
    base = {knob.field: cands[0] for knob, cands in knobs}
    t0 = time.perf_counter()
    runner = TrialRunner(cfg, ctx, strategy=strategy, mesh=mesh, log=log)
    result = run_search(knobs, runner.evaluate, base, log=log)
    wall = time.perf_counter() - t0

    path = tune_cache.store(fp, result["overrides"], meta={
        "trials": result["trials"],
        "quarantined": result["quarantined"],
        "mode": result["mode"],
        "wall_s": round(wall, 2),
        "default_steps_per_sec": result["default_steps_per_sec"],
        "tuned_steps_per_sec": result["tuned_steps_per_sec"],
        "searched_knobs": [knob.name for knob, _ in knobs],
    })
    log(f"[autotune] search: trials={result['trials']} "
        f"quarantined={result['quarantined']} wall_s={wall:.1f} "
        f"overrides={json.dumps(result['overrides'], sort_keys=True)} "
        f"-> {path}")
    return apply_overrides(cfg, result["overrides"],
                           model_built=model_built, log=log)


def tuned_vs_default(config: str, *, strategy: str = "fused", mesh=None,
                     n_batches: int | None = None,
                     max_trials: int | None = None,
                     timeout_s: float | None = None,
                     log=None) -> dict:
    """Search one preset family WITHOUT touching the persistent cache
    and report tuned-vs-default steps/sec — bench.py's
    ``extra.autotune`` block and ``scripts/autotune_sweep.py`` both
    record this, so the headline shows the tuner paying rent."""
    import jax

    from tpu_ddp.parallel.mesh import make_mesh
    from tpu_ddp.utils.config import TrainConfig

    cfg = TrainConfig.preset(config)
    cfg.autotune = "off"
    if mesh is None:
        mesh = make_mesh(jax.devices()[:1])
    ctx = workload_for(cfg, strategy, mesh)
    knobs = searchable_knobs(cfg, ctx)
    base = {knob.field: cands[0] for knob, cands in knobs}
    t0 = time.perf_counter()
    runner = TrialRunner(cfg, ctx, strategy=strategy, mesh=mesh,
                         n_batches=n_batches, max_trials=max_trials,
                         timeout_s=timeout_s, log=log)
    result = run_search(knobs, runner.evaluate, base,
                        log=log or (lambda s: None))
    return {
        "config": config,
        "searched_knobs": [knob.name for knob, _ in knobs],
        "overrides": result["overrides"],
        "default_steps_per_sec": result["default_steps_per_sec"],
        "tuned_steps_per_sec": result["tuned_steps_per_sec"],
        "trials": result["trials"],
        "quarantined": result["quarantined"],
        "mode": result["mode"],
        "wall_s": round(time.perf_counter() - t0, 2),
        "fingerprint": fingerprint_for(cfg, strategy, mesh).asdict(),
    }
