"""The perf-knob search space: one declarative registry + constraints.

Every knob the autotuner may turn is ONE entry here, carrying the
``TrainConfig`` field it sets, the ``TPU_DDP_*`` env var that field
parses, the ``python -m tpu_ddp.launch`` flag (when one exists) and the
candidate values trials may measure. ``scripts/knob_audit.py``
cross-checks the four surfaces against each other (and against the
hand-rolled env block in ``utils/config.py``) so they cannot silently
drift — a new knob lands as one registry entry, not N files.
Non-perf control surfaces (TPU_DDP_AUDIT's graph-audit gate, the
elastic protocol plumbing) are deliberately NOT entries: they change
what is *checked* at construction, never what executes, so searching
them would be meaningless — ``knob_audit``'s ``NONPERF_ENV`` allowlist
names them and the reverse sweep keeps the split exact.

The constraint model (:func:`violations`) encodes the combinations the
engine itself refuses or degrades, so the search never spends a trial
on a cell whose measurement would be a lie:

- Pallas kernels compile for the TPU backend only (ops/pallas/);
- ``grad_compress != "none"`` needs a dp>1 mesh AND a syncing rung —
  the Trainer warns and degrades to fp32 otherwise (DESIGN.md §14);
- ``dispatch_depth > 0`` is forced to 0 by the streaming loop when a
  multi-process run carries a collective-bearing in-loop cadence
  (ckpt/replica-digest collectives must enqueue at the same loop
  position on every process — DESIGN.md §13 guard (e));
- ``steps_per_dispatch > 1`` falls back to the per-step path under
  in-loop cadences or ``device_prefetch > 0`` (engine.py), so those
  cells duplicate their per-step twins;
- ``remat`` cells that the memory policy resolves to another cell's
  program are skipped as duplicates: ``conv_stages`` on a transformer
  family degrades to ``blocks``, ``dots`` on a conv family compiles to
  the ``conv_stages`` program (no dot_general inside conv stages), and
  ``act_dtype`` equal to the compute dtype is a no-op cast
  (tpu_ddp/memory/policy.py).

``semantic=True`` marks knobs whose value changes the training
computation itself (dtype, batch size), not just its schedule; the
default space excludes them so tuned runs stay numerically identical
to default runs (opt in with ``TPU_DDP_TUNE_SEMANTIC=1``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Mapping

__all__ = ["Knob", "KNOBS", "Workload", "Fingerprint", "violations",
           "searchable_knobs", "space_version", "fingerprint_for",
           "workload_for", "knob_by_field", "parse_knob_filter"]


@dataclasses.dataclass(frozen=True)
class Knob:
    """One tunable knob and every surface it must agree across."""

    name: str              # registry name (== the TrainConfig field)
    field: str             # TrainConfig attribute the tuner sets
    env: str               # TPU_DDP_* env var utils/config.py parses
    values: tuple          # candidate values (must include the default)
    flag: str | None = None  # tpu_ddp.launch flag, when one exists
    semantic: bool = False   # changes numerics, not just schedule
    # What a trial measures to compare this knob's candidates:
    # "step_time" (the training objective — every schedule knob) or
    # "goodput" (tokens/sec under a latency SLO, the serving objective
    # measured by tpu_ddp/serve/loadgen.py). The default search space
    # is objective-scoped, so serving knobs never enter a training
    # search and vice versa.
    objective: str = "step_time"
    doc: str = ""

    def encode(self, value) -> str:
        """The env-var string that makes TrainConfig parse ``value`` —
        the round-trip knob_audit drives behaviourally."""
        if isinstance(value, bool):
            return "1" if value else "0"
        return str(value)


# The registry. Values are chosen so the default config is always a
# member (the search must be able to return "keep the defaults") and
# the non-default members are the settings the repo's own sweeps have
# shown to matter (scripts/host_gap.py, EXPERIMENTS.md §9/§10).
KNOBS: tuple[Knob, ...] = (
    Knob("dispatch_depth", "dispatch_depth", "TPU_DDP_DISPATCH_DEPTH",
         values=(0, 1, 2, 4), flag="--dispatch-depth",
         doc="async dispatch window (train/pipeline.py); 0 = sync loop"),
    Knob("steps_per_dispatch", "steps_per_dispatch",
         "TPU_DDP_STEPS_PER_DISPATCH", values=(1, 4, 8),
         doc="K uniform batches per jitted lax.scan dispatch"),
    Knob("device_prefetch", "device_prefetch", "TPU_DDP_PREFETCH",
         values=(0, 2),
         doc="host->device transfers kept in flight (data/prefetch.py)"),
    Knob("grad_compress", "grad_compress", "TPU_DDP_GRAD_COMPRESS",
         values=("none", "bf16", "int8"), flag="--grad-compress",
         doc="gradient wire format on the sync collectives "
             "(parallel/compress.py; int8-noef is an ablation, not a "
             "candidate)"),
    Knob("overlap", "overlap", "TPU_DDP_OVERLAP",
         values=(False, True), flag="--overlap",
         doc="bucketed in-backward gradient collectives + sharded "
             "weight update (parallel/overlap.py); numerics equivalent "
             "to the unbucketed rung up to reduction order, so "
             "searchable by default"),
    Knob("bucket_mb", "bucket_mb", "TPU_DDP_BUCKET_MB",
         values=(1, 4, 25), flag="--bucket-mb",
         doc="bucket payload target in MiB for overlap (torch DDP's "
             "bucket_cap_mb=25 default); smaller buckets start "
             "communicating earlier but amortize less per collective"),
    Knob("pp_schedule", "pp_schedule", "TPU_DDP_PP_SCHEDULE",
         values=("gpipe", "1f1b", "interleaved", "zerobubble"),
         flag="--pp-schedule",
         doc="pipeline tick schedule (parallel/pipeline.py): all four "
             "compute the same step (schedule-equivalence-tested "
             "against the dense model), so the choice is pure "
             "schedule — searchable by default on a pp>1 mesh"),
    Knob("pp_microbatches", "pp_microbatches", "TPU_DDP_PP_MICROBATCHES",
         values=(0, 4, 8, 16), flag="--pp-microbatches",
         doc="microbatches per pipeline step (0 = auto, one per "
             "stage); more microbatches shrink the bubble fraction "
             "but shrink each microbatch's arithmetic intensity"),
    Knob("pp_virtual", "pp_virtual", "TPU_DDP_PP_VIRTUAL",
         values=(1, 2, 4), flag="--pp-virtual",
         doc="virtual stage chunks per physical stage (interleaved "
             "schedule): bubble shrinks V x for V x more in-flight "
             "chunk activations and V x the edge traffic"),
    Knob("pallas_sgd", "pallas_sgd", "TPU_DDP_PALLAS_SGD",
         values=(False, True),
         doc="fused Pallas SGD momentum update kernel (TPU only)"),
    Knob("pallas_bn", "pallas_bn", "TPU_DDP_PALLAS_BN",
         values=(False, True),
         doc="fused Pallas BatchNorm+ReLU kernel (TPU only; model-"
             "level — must be applied before get_model)"),
    Knob("remat", "remat", "TPU_DDP_REMAT",
         values=("none", "blocks", "conv_stages", "dots"), flag="--remat",
         doc="activation rematerialization policy (tpu_ddp/memory/): "
             "recompute stages in the backward pass instead of saving "
             "activations — bytes-for-FLOPs on the HBM wall "
             "(EXPERIMENTS.md §14); numerics-preserving (same ops "
             "re-executed), so searchable by default"),
    Knob("act_dtype", "act_dtype", "TPU_DDP_ACT_DTYPE",
         values=("compute", "bf16", "f32"), flag="--act-dtype",
         semantic=True,
         doc="saved-residual dtype at stage boundaries "
             "(tpu_ddp/memory/); boundaries round-trip through this "
             "dtype, so it changes numerics — searched only with "
             "TPU_DDP_TUNE_SEMANTIC=1"),
    Knob("compute_dtype", "compute_dtype", "TPU_DDP_COMPUTE_DTYPE",
         values=("bfloat16", "float32"), semantic=True,
         doc="matmul/conv dtype; changes the training numerics, so "
             "searched only with TPU_DDP_TUNE_SEMANTIC=1"),
    Knob("global_batch_size", "global_batch_size",
         "TPU_DDP_GLOBAL_BATCH", values=(), semantic=True,
         doc="registered for the audit (field<->env agreement) but "
             "never searched: batch size is a training hyperparameter, "
             "not a schedule knob"),
    Knob("elastic_reshard", "elastic_reshard",
         "TPU_DDP_ELASTIC_RESHARD", values=(),
         flag="--elastic-reshard",
         doc="registered for the audit (field<->env<->flag agreement) "
             "but never searched: live membership resharding "
             "(resilience/elastic.py) is a robustness mode, not a "
             "schedule knob — turning it on cannot change steady-state "
             "step time"),
    # Serving knobs (tpu_ddp/serve/): objective="goodput" scopes them
    # out of the training (step_time) search space and into the
    # serve-sweep/loadgen measurement loop.
    Knob("serve_slots", "serve_slots", "TPU_DDP_SERVE_SLOTS",
         values=(4, 8, 16), objective="goodput",
         doc="continuous-batching decode slots — the live-batch width "
             "of the jitted whole-bank decode step; more slots "
             "amortize weight reads but grow per-step latency"),
    Knob("serve_block_size", "serve_block_size", "TPU_DDP_SERVE_BLOCK",
         values=(8, 16, 32), objective="goodput",
         doc="paged KV-cache block size in tokens (serve/kv_pool.py): "
             "small blocks waste less tail capacity per sequence, "
             "large blocks shrink the table/gather overhead"),
    Knob("serve_prefill_chunk", "serve_prefill_chunk",
         "TPU_DDP_SERVE_PREFILL_CHUNK", values=(16, 32, 64),
         objective="goodput",
         doc="prompt tokens run per engine step: the knob trading "
             "prefill throughput against how long one long prompt can "
             "stall the live decode batch (TTFT tail)"),
    Knob("serve_cache_dtype", "serve_cache_dtype",
         "TPU_DDP_SERVE_CACHE_DTYPE", values=("compute", "bf16", "f32"),
         semantic=True, objective="goodput",
         doc="KV-cache storage dtype (memory-policy vocabulary, "
             "tpu_ddp/memory/policy.py): 'bf16' under an f32 compute "
             "model halves cache reads but rounds the attended "
             "history — semantic, gated like act_dtype"),
    # Fleet knobs (tpu_ddp/fleet/): the serving-fleet layer on top of
    # the engine — same "goodput" objective, measured by the same
    # loadgen harness.
    Knob("fleet_roles", "fleet_roles", "TPU_DDP_FLEET_ROLES",
         values=("single", "disagg"), objective="goodput",
         doc="engine role split: 'disagg' runs a dedicated prefill "
             "role streaming finished KV blocks to a decode role over "
             "an explicit edge (fleet/disagg.py), so long prefills "
             "never steal decode-batch steps"),
    Knob("prefix_cache", "prefix_cache", "TPU_DDP_PREFIX_CACHE",
         values=(False, True), objective="goodput",
         doc="refcounted shared-prefix KV cache (fleet/prefix.py): "
             "requests sharing a system prompt pay one prefill; "
             "exactness-preserving via copy-on-write, so searchable "
             "without a semantic gate"),
    Knob("router_policy", "router_policy", "TPU_DDP_ROUTER_POLICY",
         values=("least-loaded", "prefix-affinity"),
         objective="goodput",
         doc="multi-replica routing (fleet/router.py): "
             "'prefix-affinity' sends a request to the replica whose "
             "prefix cache holds its longest match (cache hit-rate "
             "over pure load spreading); needs prefix_cache"),
    Knob("kv_wire", "kv_wire", "TPU_DDP_KV_WIRE",
         values=("none", "bf16", "int8"), semantic=True,
         objective="goodput",
         doc="disagg prefill->decode edge wire format "
             "(parallel/compress.py EdgeCodec): 'bf16'/'int8' shrink "
             "the shipped KV payload but round it — semantic, gated "
             "like serve_cache_dtype"),
    # Fleet-resilience knobs (fleet/resilience.py, DESIGN.md §23):
    # replica health + migration are Router concerns, shedding is an
    # engine admission concern — all measured by the same loadgen
    # goodput harness (a shed request's tokens are not good tokens).
    Knob("fleet_health", "fleet_health", "TPU_DDP_FLEET_HEALTH",
         values=(False, True), flag="--fleet-health",
         objective="goodput",
         doc="replica health tracking in the Router "
             "(fleet/router.py): step exceptions and deadline "
             "overruns mark a replica unhealthy, its in-flight "
             "requests migrate deterministically, and probe "
             "re-admission follows exponential backoff; off = "
             "fail-fast (a replica exception propagates)"),
    Knob("fleet_probe_backoff_ms", "fleet_probe_backoff_ms",
         "TPU_DDP_FLEET_HEALTH_BACKOFF_MS",
         values=(50.0, 200.0, 1000.0), flag="--fleet-probe-backoff-ms",
         objective="goodput",
         doc="initial probe-re-admission backoff for an unhealthy "
             "replica, doubling per consecutive failure (capped): "
             "short backoff re-admits flapping replicas faster but "
             "burns steps probing a dead one"),
    Knob("fleet_step_deadline_ms", "fleet_step_deadline_ms",
         "TPU_DDP_FLEET_HEALTH_DEADLINE_MS",
         values=(0.0, 250.0, 1000.0), flag="--fleet-step-deadline-ms",
         objective="goodput",
         doc="per-replica step deadline: a step exceeding this is "
             "treated as a failure (slow replica == dead replica, the "
             "serving mirror of the heartbeat stall detector); 0 "
             "disables the deadline"),
    Knob("fleet_retry_budget", "fleet_retry_budget",
         "TPU_DDP_FLEET_RETRY_BUDGET",
         values=(0, 1, 3), flag="--fleet-retry-budget",
         objective="goodput",
         doc="migrations allowed per request before the Router sheds "
             "it instead of re-queueing (a request that has killed N "
             "replicas is suspect — the serving analog of StepGuard's "
             "max-bad-steps budget)"),
    Knob("serve_queue_limit", "serve_queue_limit",
         "TPU_DDP_SERVE_QUEUE_LIMIT",
         values=(0, 64, 256), flag="--serve-queue-limit",
         objective="goodput",
         doc="bounded admission queue: submits beyond this many "
             "waiting requests are shed at the door (engine.py); 0 = "
             "unbounded. Under overload shedding keeps TTFT of "
             "admitted requests inside the SLO instead of letting the "
             "whole queue miss it"),
    Knob("serve_shed_ms", "serve_shed_ms", "TPU_DDP_SERVE_SHED_MS",
         values=(0.0, 100.0, 500.0), flag="--serve-shed-ms",
         objective="goodput",
         doc="queue-deadline shedding: a request still waiting (no "
             "prefill started) this many ms after submission is shed "
             "(its TTFT SLO is already lost); 0 disables"),
    Knob("publish_every", "publish_every", "TPU_DDP_PUBLISH_EVERY",
         values=(0, 1, 4, 16), flag="--publish-every",
         objective="goodput",
         doc="trainer-step cadence for pushing versioned weight "
             "updates to subscribed serving engines (tpu_ddp/publish/); "
             "0 = off. More frequent pushes keep served weights "
             "fresher but spend decode-step time staging buckets"),
    Knob("publish_wire", "publish_wire", "TPU_DDP_PUBLISH_WIRE",
         values=("none", "bf16", "int8", "sparse"),
         flag="--publish-wire",
         objective="goodput", semantic=True,
         doc="wire format for pushed weight deltas (EdgeCodec "
             "vocabulary). Lossy wires round the served weights, so "
             "the knob is semantic like kv_wire; 'sparse' is lossless "
             "zero-chunk elision (the MoE expert-delta wire)"),
    Knob("max_staleness_steps", "max_staleness_steps",
         "TPU_DDP_PUBLISH_MAX_STALENESS",
         values=(0, 2, 8), flag="--publish-max-staleness",
         objective="goodput",
         doc="steps the trainer may run ahead of the slowest "
             "subscriber before its publish gate blocks; 0 = "
             "unbounded (fully async)"),
    # Autoscaling + multi-tenancy knobs (fleet/autoscale.py,
    # serve/scheduler.py WFQ — DESIGN.md §25): same goodput objective,
    # measured by the day-in-the-life trace harness (loadgen.run_trace).
    Knob("fleet_autoscale", "fleet_autoscale", "TPU_DDP_FLEET_AUTOSCALE",
         values=(False, True), flag="--fleet-autoscale",
         objective="goodput",
         doc="autoscaling replica lifecycle control plane "
             "(fleet/autoscale.py): scale-up boots replicas from the "
             "publisher's full-push path, scale-down drains via "
             "bitwise continuation migration; off = static fleet"),
    Knob("scale_cooldown_ms", "scale_cooldown_ms",
         "TPU_DDP_SCALE_COOLDOWN_MS",
         values=(250.0, 1000.0, 5000.0), flag="--scale-cooldown-ms",
         objective="goodput",
         doc="minimum ms between autoscaler actions: short cooldowns "
             "react faster to a flash crowd but risk boot/drain "
             "thrash at the hysteresis band edge; must be > 0"),
    Knob("tenant_classes", "tenant_classes", "TPU_DDP_TENANT_CLASSES",
         values=("", "gold=3,silver=2,bronze=1"),
         flag="--tenant-classes", objective="goodput",
         doc="SLO classes for multi-tenant serving "
             "(serve/scheduler.py): comma-separated name=weight"
             "[:deadline_ms[:token_budget]]; non-empty switches "
             "admission from FIFO to weighted fair queueing with "
             "lowest-class-first shedding; empty = single-tenant"),
    # Speculative decoding + quantized decode (serve/speculative.py,
    # ops/quant.py — DESIGN.md §26): raw tokens/sec multipliers,
    # measured by scripts/spec_sweep.py.
    Knob("spec_k", "spec_k", "TPU_DDP_SPEC_K",
         values=(0, 4, 12), flag="--spec-k",
         objective="goodput",
         doc="speculative proposals verified per engine step "
             "(serve/speculative.py); 0 = the one-token baseline. "
             "Larger k amortizes more per-step host/dispatch overhead "
             "per emitted token but wastes compute past the draft's "
             "acceptance horizon (fused families) or stretches the "
             "emission burst (chain)"),
    Knob("spec_draft", "spec_draft", "TPU_DDP_SPEC_DRAFT",
         values=("chain", "self-1", "quant"), flag="--spec-draft",
         objective="goodput",
         doc="draft family for speculation: 'chain' re-dispatches the "
             "engine's own compiled decode program k+1 times "
             "(bitwise-exact stream — NOT semantic), 'self-<j>' "
             "early-exits over the target's first j blocks, 'quant' "
             "runs a full-depth int8 twin; the fused families trade "
             "exactness on CPU for one dispatch per step"),
    Knob("decode_quant", "decode_quant", "TPU_DDP_DECODE_QUANT",
         values=("none", "int8"), flag="--decode-quant",
         objective="goodput", semantic=True,
         doc="weight-only int8 decode compute (ops/quant.py): "
             "per-output-channel quantization of every decode-path "
             "projection, dequant fused into the matmul. Rounds the "
             "served logits (bounded by the sweep's 0.25% NLL drift "
             "bar), so the knob is semantic like publish_wire"),
    # Long-context serving (serve/long_context.py, serve/kv_pool.py —
    # DESIGN.md §27): tiered KV residency and context-parallel prefill,
    # measured by scripts/long_context_sweep.py.
    Knob("kv_tiers", "kv_tiers", "TPU_DDP_KV_TIERS",
         values=(1, 2, 3), flag="--kv-tiers",
         objective="goodput",
         doc="KV residency tiers (serve/kv_pool.py): 1 = the flat "
             "single-pool cache, 2 adds an in-HBM cold tier of "
             "quantized pages behind an LRU hot set, 3 adds host-memory "
             "spill with demand promotion so HBM bounds the HOT context "
             "per step, not the TOTAL resident context"),
    Knob("kv_cold_dtype", "kv_cold_dtype", "TPU_DDP_KV_COLD_DTYPE",
         values=("int8", "bf16"), flag="--kv-cold-dtype",
         objective="goodput", semantic=True,
         doc="storage dtype for cold-tier KV pages "
             "(parallel/compress.py page codec): 'int8' halves cold "
             "bytes with per-token-row scales and rounds re-read "
             "attention (semantic), 'bf16' is a lossless downcast when "
             "the hot pool is already bf16. Inert at kv_tiers=1 — "
             "there is no cold tier to store into"),
    Knob("cp_prefill", "cp_prefill", "TPU_DDP_CP_PREFILL",
         values=("off", "ring", "ulysses"), flag="--cp-prefill",
         objective="goodput",
         doc="context-parallel chunked prefill (serve/long_context.py): "
             "shard each prefill chunk's query rows over the sp mesh "
             "axis and run ring or Ulysses attention against the paged "
             "cache, cutting TTFT on long prompts. Requires an sp>=2 "
             "mesh and the single-tier pool (engine rejects tiers>1)"),
    # Mixture-of-experts knobs (parallel/moe.py, DESIGN.md §28): all
    # three change WHAT the model computes (a different architecture /
    # routing distribution, not a schedule), so all are semantic —
    # searched only under TPU_DDP_TUNE_SEMANTIC, like compute_dtype.
    Knob("moe_experts", "moe_experts", "TPU_DDP_MOE_EXPERTS",
         values=(0, 4, 8), flag="--moe-experts", semantic=True,
         doc="experts per MoE MLP layer (0 = dense): param count grows "
             "~linearly in E at per-token FLOPs tracking top_k — the "
             "capability-per-FLOP axis (experiments/moe_sweep.json); "
             "an ep>1 mesh must divide E"),
    Knob("moe_top_k", "moe_top_k", "TPU_DDP_MOE_TOP_K",
         values=(1, 2), flag="--moe-top-k", semantic=True,
         doc="routed experts per token: 1 = Switch routing (raw-prob "
             "gate), 2 = GShard (renormalized gates, shared capacity "
             "queues); topk_route rejects top_k > experts"),
    Knob("moe_capacity", "moe_capacity", "TPU_DDP_MOE_CAPACITY",
         values=(1.0, 1.25, 2.0), flag="--moe-capacity", semantic=True,
         doc="expert capacity factor: slots per expert = "
             "ceil(T * capacity * top_k / E). Higher drops fewer "
             "tokens (the dropped_frac train metric) at more padded "
             "expert compute; changes which tokens the experts see, "
             "so semantic"),
    # DiLoCo outer-loop knobs (train/outer.py, DESIGN.md §29): all
    # four change the training trajectory (H local steps between
    # syncs is a different algorithm, not a schedule), so all are
    # semantic — searched only under TPU_DDP_TUNE_SEMANTIC.
    Knob("diloco_h", "diloco_h", "TPU_DDP_DILOCO_H",
         values=(0, 8, 32), flag="--diloco-h", semantic=True,
         doc="DiLoCo inner steps per outer round (0 = off): each "
             "group runs H local steps, only the outer "
             "pseudo-gradient exchange crosses groups — cross-group "
             "bytes drop ~H x before compression "
             "(experiments/diloco_sweep.json)"),
    Knob("outer_lr", "outer_lr", "TPU_DDP_DILOCO_OUTER_LR",
         values=(0.4, 0.7, 1.0), flag="--diloco-outer-lr",
         semantic=True,
         doc="outer Nesterov learning rate over pseudo-gradients; "
             "1.0 with zero momentum is plain parameter averaging"),
    Knob("outer_momentum", "outer_momentum",
         "TPU_DDP_DILOCO_OUTER_MOMENTUM",
         values=(0.0, 0.9), flag="--diloco-outer-momentum",
         semantic=True,
         doc="outer Nesterov momentum in [0, 1); 0.9 is the DiLoCo "
             "setting that recovers most of the synced-baseline "
             "quality at H-fold fewer syncs"),
    Knob("outer_wire", "outer_wire", "TPU_DDP_DILOCO_OUTER_WIRE",
         values=("none", "bf16", "int8", "sparse"),
         flag="--diloco-outer-wire", semantic=True,
         doc="cross-group pseudo-gradient wire (publish/ delta codec "
             "vocabulary): 'none' ships bitwise full tensors, "
             "bf16/int8 quantize the rebased delta (int8 with "
             "per-bucket error feedback carried across rounds)"),
)

# Model-level knobs are baked into get_model() before the Trainer ever
# sees the config; tune.resolve(model_built=True) must drop them.
MODEL_LEVEL_FIELDS = ("pallas_bn", "compute_dtype")


def knob_by_field(field: str) -> Knob | None:
    for k in KNOBS:
        if k.field == field:
            return k
    return None


def space_version() -> str:
    """Hash of the registry structure: any change to the knob set or a
    knob's candidate values invalidates cached tunings via the
    fingerprint (stale overrides are a miss, never a surprise)."""
    payload = [(k.name, k.field, k.env, k.flag, list(map(str, k.values)),
                k.semantic, k.objective) for k in KNOBS]
    return hashlib.sha256(
        json.dumps(payload, sort_keys=True).encode()).hexdigest()[:12]


@dataclasses.dataclass(frozen=True)
class Workload:
    """The static context constraints are evaluated against."""

    platform: str = "cpu"          # jax.devices()[0].platform
    dp: int = 1                    # data-parallel slots on the mesh
    processes: int = 1             # jax.process_count()
    strategy: str = "none"         # canonical sync rung
    collective_cadence: bool = False  # in-loop ckpt/replica cadence
    # Model family ("conv" | "attn" | "" unknown): the remat policy's
    # degrade/duplicate rules are family-shaped (tpu_ddp/memory/).
    model_family: str = ""
    # Pipeline context (round 10): stages on the mesh and the model's
    # layer count (0 = unknown) — the interleaved divisibility rule
    # needs both; pp <= 1 scopes the pipeline knobs out entirely.
    pp: int = 1
    model_layers: int = 0
    # Expert-parallel extent on the mesh (round 19): the MoE knob
    # rules need it — ep>1 requires a divisible moe_experts.
    ep: int = 1


def workload_for(cfg, strategy: str = "none", mesh=None) -> Workload:
    """Build the constraint context from live runtime state (imports
    jax lazily so pure space/cache tests never touch the backend)."""
    import jax

    from tpu_ddp.parallel.sync import canonical_strategy

    dp, pp, ep = 1, 1, 1
    if mesh is not None:
        try:
            dp = int(mesh.shape.get("dp", 1))
            pp = int(mesh.shape.get("pp", 1))
            ep = int(mesh.shape.get("ep", 1))
        except Exception:  # noqa: BLE001 — a mesh without named axes
            dp, pp, ep = 1, 1, 1
    from tpu_ddp.memory import family_for_model

    layers = 0
    try:
        from tpu_ddp.models.transformer import make_transformer
        layers = int(make_transformer(cfg.model).num_layers)
    except (ValueError, TypeError):
        pass  # non-transformer family: layer-divisibility rule inert

    return Workload(
        platform=jax.devices()[0].platform,
        dp=dp,
        processes=jax.process_count(),
        strategy=canonical_strategy(strategy),
        collective_cadence=bool(cfg.ckpt_every_iters
                                or cfg.check_replicas_every),
        model_family=family_for_model(cfg.model),
        pp=pp,
        model_layers=layers,
        ep=ep,
    )


def violations(assignment: Mapping, ctx: Workload) -> list[str]:
    """Reasons ``assignment`` (field -> value) is a known-invalid cell
    for ``ctx``; empty list == feasible. Each rule mirrors a guard the
    engine enforces at runtime (cited in the module docstring) — the
    search skips these cells instead of measuring a degraded twin."""
    bad = []
    get = assignment.get
    if ctx.platform != "tpu":
        for field in ("pallas_sgd", "pallas_bn"):
            if get(field):
                bad.append(f"{field}=True requires the TPU backend "
                           f"(platform is {ctx.platform!r})")
    if get("grad_compress", "none") != "none":
        if ctx.dp <= 1 or ctx.strategy == "none":
            bad.append(
                f"grad_compress={get('grad_compress')!r} requires a "
                f"dp>1 mesh and a syncing rung (dp={ctx.dp}, "
                f"strategy={ctx.strategy!r}) — Trainer degrades it to "
                "'none' (DESIGN.md §14)")
    if get("overlap", False) and (ctx.dp <= 1 or ctx.strategy not in
                                  ("gather_scatter", "all_reduce",
                                   "fused")):
        bad.append(
            f"overlap=True requires a dp>1 mesh and a replicated "
            f"syncing rung (dp={ctx.dp}, strategy={ctx.strategy!r}) — "
            "Trainer degrades it to the unbucketed path "
            "(train/engine.py)")
    if get("bucket_mb", 25) != 25 and not get("overlap", False):
        bad.append(
            "bucket_mb is only read by the overlapped path — without "
            "overlap=True this cell duplicates the default")
    if get("dispatch_depth", 0) and ctx.processes > 1 \
            and ctx.collective_cadence:
        bad.append(
            "dispatch_depth>0 with a multi-process collective-bearing "
            "cadence — the streaming loop forces depth 0 "
            "(DESIGN.md §13 guard (e))")
    remat = get("remat", "none")
    if remat == "conv_stages" and ctx.model_family == "attn":
        bad.append(
            "remat='conv_stages' on a transformer family — the model "
            "degrades it to 'blocks' with a warning (tpu_ddp/memory/), "
            "so this cell duplicates the 'blocks' cell")
    if remat == "dots" and ctx.model_family == "conv":
        bad.append(
            "remat='dots' on a conv family — conv stages contain no "
            "dot_general (convs are conv_general_dilated), so the "
            "program is identical to 'conv_stages' (duplicate cell)")
    act = get("act_dtype", "compute")
    cdty = str(get("compute_dtype", "bfloat16"))
    if (act, cdty) in (("bf16", "bfloat16"), ("f32", "float32")):
        bad.append(
            f"act_dtype={act!r} with compute_dtype={cdty!r} — the "
            "boundary cast is a no-op, duplicate of 'compute'")
    scd = get("serve_cache_dtype", "compute")
    if (scd, cdty) in (("bf16", "bfloat16"), ("f32", "float32")):
        bad.append(
            f"serve_cache_dtype={scd!r} with compute_dtype={cdty!r} — "
            "the cache cast is a no-op, duplicate of 'compute' "
            "(tpu_ddp/memory/policy.py resolve_act_dtype)")
    # Fleet knobs (tpu_ddp/fleet/) — mirror the fleet layer's guards.
    kw = get("kv_wire", "none")
    if kw != "none" and get("fleet_roles", "single") != "disagg":
        bad.append(
            f"kv_wire={kw!r} without fleet_roles='disagg' — no edge "
            "exists for the wire format to compress, so the cell "
            "duplicates the default")
    if (get("router_policy", "least-loaded") == "prefix-affinity"
            and not get("prefix_cache", False)):
        bad.append(
            "router_policy='prefix-affinity' without prefix_cache — "
            "every replica reports a zero-length cached prefix, so "
            "routing degenerates to least-loaded (duplicate cell)")
    # Publish knobs (tpu_ddp/publish/) — mirror Publisher's guards.
    if get("publish_every", 0) == 0:
        if get("publish_wire", "none") != "none":
            bad.append(
                f"publish_wire={get('publish_wire')!r} with "
                "publish_every=0 — no push ever encodes, so the cell "
                "duplicates the default")
        if get("max_staleness_steps", 0) != 0:
            bad.append(
                f"max_staleness_steps={get('max_staleness_steps')} "
                "with publish_every=0 — the gate only arms on "
                "publish, so the cell duplicates the default")
    if get("scale_cooldown_ms", 1000.0) != 1000.0 \
            and not get("fleet_autoscale", False):
        bad.append(
            f"scale_cooldown_ms={get('scale_cooldown_ms')} without "
            "fleet_autoscale — the cooldown only gates autoscaler "
            "actions, so the cell duplicates the default")
    # Pipeline knobs (round 10) — mirror PipelineLMTrainer's guards.
    sched = get("pp_schedule", "gpipe")
    virt = get("pp_virtual", 1)
    micro = get("pp_microbatches", 0)
    if ctx.pp <= 1:
        if sched != "gpipe" or virt != 1 or micro != 0:
            bad.append(
                "pipeline knobs off-default on a pp<=1 mesh — no "
                "pipeline rung runs, so every cell duplicates the "
                "default")
    else:
        if virt > 1 and sched != "interleaved":
            bad.append(
                f"pp_virtual={virt} requires pp_schedule='interleaved' "
                f"(got {sched!r}) — PipelineLMTrainer rejects it "
                "(zero-bubble extends plain 1F1B; gpipe/1f1b run one "
                "chunk per stage)")
        if sched == "interleaved" and virt == 1:
            bad.append(
                "pp_schedule='interleaved' with pp_virtual=1 runs the "
                "plain 1F1B tick indices — duplicate of the '1f1b' "
                "cell")
        if (sched == "interleaved" and ctx.model_layers
                and ctx.model_layers % (ctx.pp * virt)):
            bad.append(
                f"interleaved needs num_layers % (pp*pp_virtual) == 0: "
                f"{ctx.model_layers} % {ctx.pp * virt} != 0 — "
                "PipelineLMTrainer rejects it")
        if micro and micro % ctx.pp:
            bad.append(
                f"pp_microbatches={micro} not divisible by "
                f"pp={ctx.pp} — the interleaved schedule rejects it "
                "and the others waste the ragged tail")
    if get("steps_per_dispatch", 1) > 1:
        if get("device_prefetch", 0):
            bad.append("steps_per_dispatch>1 with device_prefetch>0 — "
                       "the engine falls back to the per-step path "
                       "(duplicate of the prefetch-only cell)")
        if ctx.collective_cadence:
            bad.append("steps_per_dispatch>1 with an in-loop cadence — "
                       "the engine falls back to the per-step path")
    # Speculative-decoding knobs (serve/speculative.py §26).
    if get("spec_draft", "chain") != "chain" and get("spec_k", 0) == 0:
        bad.append(
            f"spec_draft={get('spec_draft')!r} with spec_k=0 — no "
            "speculative step ever runs, so the draft family is inert "
            "and the cell duplicates the default")
    if get("spec_k", 0) > 0 and get("fleet_roles", "single") == "disagg":
        bad.append(
            f"spec_k={get('spec_k')} with fleet_roles='disagg' — the "
            "disaggregated decode tier runs the fused adopt+decode "
            "program only (fleet/disagg.py); speculation is a "
            "single-engine/router feature")
    # Long-context serving knobs (serve/long_context.py §27).
    if get("kv_cold_dtype", "int8") != "int8" and get("kv_tiers", 1) == 1:
        bad.append(
            f"kv_cold_dtype={get('kv_cold_dtype')!r} with kv_tiers=1 — "
            "the flat pool has no cold tier, so the cold dtype is "
            "inert and the cell duplicates the default")
    if get("cp_prefill", "off") != "off" and get("kv_tiers", 1) > 1:
        bad.append(
            f"cp_prefill={get('cp_prefill')!r} with "
            f"kv_tiers={get('kv_tiers')} — the context-parallel "
            "prefill program gathers pages by flat slot id and the "
            "engine rejects the combination (serve/engine.py); tiered "
            "residency is a decode-side feature")
    # MoE knobs (parallel/moe.py §28) — mirror the model layer's guards.
    experts = get("moe_experts", 0)
    if experts == 0:
        if get("moe_top_k", 1) != 1:
            bad.append(
                f"moe_top_k={get('moe_top_k')} with moe_experts=0 — "
                "no routed layer exists, the knob is inert and the "
                "cell duplicates the dense default")
        if get("moe_capacity", 1.25) != 1.25:
            bad.append(
                f"moe_capacity={get('moe_capacity')} with "
                "moe_experts=0 — no routed layer exists, the knob is "
                "inert and the cell duplicates the dense default")
    else:
        if get("moe_top_k", 1) > experts:
            bad.append(
                f"moe_top_k={get('moe_top_k')} > moe_experts="
                f"{experts} — topk_route rejects it (beyond E the "
                "fully-masked argmax would silently re-route to "
                "expert 0)")
    if ctx.ep > 1:
        if experts == 0:
            bad.append(
                f"ep={ctx.ep} mesh with moe_experts=0 — expert "
                "parallelism requires a MoE model "
                "(with_expert_parallel rejects it)")
        elif experts % ctx.ep:
            bad.append(
                f"moe_experts={experts} not divisible by ep={ctx.ep} "
                "— with_expert_parallel rejects it (each device hosts "
                "E/ep stacked experts)")
    diloco_h = get("diloco_h", 0)
    if diloco_h == 0:
        if get("outer_lr", 0.7) != 0.7:
            bad.append(
                f"outer_lr={get('outer_lr')} with diloco_h=0 — the "
                "outer loop is inert, the knob does nothing and the "
                "cell duplicates the plain-sync default")
        if get("outer_momentum", 0.9) != 0.9:
            bad.append(
                f"outer_momentum={get('outer_momentum')} with "
                "diloco_h=0 — the outer loop is inert, the knob does "
                "nothing and the cell duplicates the plain-sync "
                "default")
        if get("outer_wire", "none") != "none":
            bad.append(
                f"outer_wire={get('outer_wire')!r} with diloco_h=0 — "
                "no outer exchange exists to put on a wire; the cell "
                "duplicates the plain-sync default")
    elif ctx.pp > 1:
        bad.append(
            f"diloco_h={diloco_h} on a pp={ctx.pp} mesh — a pipeline "
            "group's params live stage-sharded and the outer "
            "pseudo-gradient exchange assumes the canonical "
            "params_to_host layout per group; run DiLoCo groups over "
            "dp/fsdp rungs (pp inside a group is future work)")
    return bad


def parse_knob_filter(spec: str | None) -> dict | None:
    """Parse ``TPU_DDP_TUNE_KNOBS``: a comma-separated list of registry
    names, each optionally pinning its candidate values —
    ``"dispatch_depth=0|2,steps_per_dispatch"`` keeps two knobs and
    shrinks the first to {0, 2}. Returns {name: values-or-None}, or
    None when unset. Unknown names raise (a typo must not silently tune
    the full space)."""
    if not spec:
        return None
    out: dict = {}
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        name, _, raw = item.partition("=")
        name = name.strip()
        knob = knob_by_field(name)
        if knob is None:
            raise ValueError(
                f"TPU_DDP_TUNE_KNOBS: unknown knob {name!r}; known: "
                f"{[k.name for k in KNOBS]}")
        if not raw:
            out[name] = None
            continue
        vals = []
        for tok in raw.split("|"):
            tok = tok.strip()
            if knob.values and isinstance(knob.values[0], bool):
                vals.append(tok.lower() in ("1", "true", "yes", "on"))
            elif knob.values and isinstance(knob.values[0], int):
                vals.append(int(tok))
            else:
                vals.append(tok)
        out[name] = tuple(vals)
    return out


def searchable_knobs(cfg, ctx: Workload,
                     include_semantic: bool | None = None,
                     only: dict | None = None,
                     objective: str = "step_time") -> list[tuple]:
    """The live search space for ``cfg`` under ``ctx``: a list of
    ``(knob, candidate_values)`` with the config's CURRENT value always
    first (the search must be able to keep it). Knobs are dropped when
    the constraint model leaves fewer than two candidates (e.g. the
    Pallas knobs off-TPU) or when ``only`` (the parsed
    ``TPU_DDP_TUNE_KNOBS`` filter) excludes them. The space is
    ``objective``-scoped: the training search ("step_time", the
    default every existing caller gets) never sees the serving knobs,
    and a "goodput" search (scripts/serve_sweep.py's tuning section)
    never sees the training schedule. Per-value feasibility is checked
    with the other knobs at their config values; the search re-checks
    full assignments, so coupled constraints stay exact."""
    if include_semantic is None:
        include_semantic = os.environ.get(
            "TPU_DDP_TUNE_SEMANTIC", "") in ("1", "true", "yes", "on")
    if only is None:
        only = parse_knob_filter(os.environ.get("TPU_DDP_TUNE_KNOBS"))
    base = {k.field: getattr(cfg, k.field) for k in KNOBS}
    out = []
    for knob in KNOBS:
        if knob.objective != objective:
            continue
        if only is not None and knob.name not in only:
            continue
        if knob.semantic and not include_semantic:
            continue
        if os.environ.get(knob.env):
            # An explicit TPU_DDP_* pin is the user overriding this
            # knob by hand; the tuner must neither search nor override
            # it (resolve() enforces the same rule for cached entries).
            continue
        values = knob.values
        if only is not None and only[knob.name] is not None:
            values = only[knob.name]
        if not values:
            continue
        current = getattr(cfg, knob.field)
        candidates = [current]
        for v in values:
            if v == current or v in candidates:
                continue
            if not violations({**base, knob.field: v}, ctx):
                candidates.append(v)
        if len(candidates) >= 2:
            out.append((knob, tuple(candidates)))
    return out


@dataclasses.dataclass(frozen=True)
class Fingerprint:
    """The workload identity a tuning is valid for. Any field changing
    — model, data scale, mesh, backend, software version, or the knob
    space itself — keys a different cache entry, so a tuning can never
    be applied to a workload it was not measured on."""

    model: str
    dataset: str
    global_batch_size: int
    mesh_shape: str            # "dp=8,sp=1,..." or "none"
    strategy: str
    processes: int
    platform: str
    device_kind: str
    jax_version: str
    jaxlib_version: str
    space_version: str

    def asdict(self) -> dict:
        return dataclasses.asdict(self)

    def key(self) -> str:
        """Stable cache key: sha256 over the canonical JSON form."""
        return hashlib.sha256(
            json.dumps(self.asdict(), sort_keys=True).encode()
        ).hexdigest()[:16]


def fingerprint_for(cfg, strategy: str = "none", mesh=None) -> Fingerprint:
    import jax
    import jaxlib

    from tpu_ddp.parallel.sync import canonical_strategy

    if mesh is not None:
        mesh_shape = ",".join(f"{axis}={size}"
                              for axis, size in mesh.shape.items())
    else:
        mesh_shape = "none"
    dev = jax.devices()[0]
    return Fingerprint(
        model=cfg.model,
        dataset=cfg.dataset,
        global_batch_size=cfg.global_batch_size,
        mesh_shape=mesh_shape,
        strategy=canonical_strategy(strategy),
        processes=jax.process_count(),
        platform=dev.platform,
        device_kind=dev.device_kind,
        jax_version=jax.__version__,
        jaxlib_version=jaxlib.__version__,
        space_version=space_version(),
    )
