"""The persistent tuning cache: one JSON file per workload fingerprint.

Entries live under ``~/.cache/tpu_ddp/tune/`` (override with
``TPU_DDP_TUNE_CACHE_DIR``) as ``<fingerprint-key>.json``. Every load is
verified — the same paranoia as checkpoint restore
(``resilience/integrity.py``): a tuning that silently applied to the
wrong workload would be worse than no tuning, because it would look like
a measurement. The policy per failure class:

- unreadable / non-JSON / wrong shape → quarantine to ``*.corrupt``
  (``.corrupt-2``… if taken; never silently deleted);
- stored fingerprint != the caller's fingerprint (hash collision or a
  hand-edited file) → quarantine — the entry is actively wrong;
- override keys that are not registry fields → quarantine — the knob
  space the entry was tuned for no longer exists in this form;
- ``schema_version`` mismatch → plain miss, NO quarantine: an old
  schema is not corruption, and the next ``store`` overwrites it.

Writes are atomic (tmp file + ``os.replace``) so a killed search never
leaves a truncated entry for the next run to trip over.
"""

from __future__ import annotations

import json
import os
import tempfile
import warnings

from tpu_ddp.tune.space import Fingerprint, knob_by_field

__all__ = ["SCHEMA_VERSION", "cache_dir", "entry_path", "store", "load",
           "quarantine"]

SCHEMA_VERSION = 1


def cache_dir() -> str:
    env = os.environ.get("TPU_DDP_TUNE_CACHE_DIR")
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "tpu_ddp",
                        "tune")


def entry_path(fp: Fingerprint, directory: str | None = None) -> str:
    return os.path.join(directory or cache_dir(), f"{fp.key()}.json")


def quarantine(path: str) -> str | None:
    """Rename a bad entry to ``path.corrupt`` (``.corrupt-2``… if
    taken); returns the new path, or None when a concurrent process won
    the rename race. Mirrors ``integrity.quarantine_checkpoint``."""
    target = path + ".corrupt"
    n = 1
    while os.path.exists(target):
        n += 1
        target = f"{path}.corrupt-{n}"
    try:
        os.rename(path, target)
    except OSError:
        return None
    return target


def store(fp: Fingerprint, overrides: dict, *, directory: str | None = None,
          meta: dict | None = None) -> str:
    """Persist ``overrides`` (TrainConfig field -> tuned value) for
    ``fp``; returns the entry path. ``meta`` (trial counts, measured
    steps/sec, wall time) is carried verbatim for provenance."""
    directory = directory or cache_dir()
    os.makedirs(directory, exist_ok=True)
    path = entry_path(fp, directory)
    payload = {
        "schema_version": SCHEMA_VERSION,
        "fingerprint": fp.asdict(),
        "overrides": overrides,
        "meta": meta or {},
    }
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def load(fp: Fingerprint, *, directory: str | None = None) -> dict | None:
    """The verified entry for ``fp`` — ``{"overrides": ..., "meta": ...,
    "path": ...}`` — or None on any miss (absent, old schema, or a
    quarantined failure; a warning names which)."""
    path = entry_path(fp, directory)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            payload = json.load(f)
        if not isinstance(payload, dict):
            raise ValueError(f"entry is {type(payload).__name__}, "
                             "expected an object")
    except (OSError, ValueError) as e:
        moved = quarantine(path)
        warnings.warn(f"[autotune] corrupt cache entry {path}: {e}; "
                      f"quarantined to {moved}", stacklevel=2)
        return None

    if payload.get("schema_version") != SCHEMA_VERSION:
        # Old-schema entries are stale, not corrupt: miss without drama,
        # and the next search's store() overwrites in place.
        return None

    stored_fp = payload.get("fingerprint")
    if stored_fp != fp.asdict():
        moved = quarantine(path)
        warnings.warn(
            f"[autotune] cache entry {path} carries a different "
            f"fingerprint than its key (stored {stored_fp!r}); "
            f"quarantined to {moved}", stacklevel=2)
        return None

    overrides = payload.get("overrides")
    if not isinstance(overrides, dict) or any(
            knob_by_field(k) is None for k in overrides):
        moved = quarantine(path)
        unknown = [k for k in (overrides or {}) if knob_by_field(k) is None]
        warnings.warn(
            f"[autotune] cache entry {path} has override keys outside "
            f"the knob registry {unknown!r}; quarantined to {moved}",
            stacklevel=2)
        return None

    return {"overrides": overrides, "meta": payload.get("meta", {}),
            "path": path}
