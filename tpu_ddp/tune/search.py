"""The search strategy: coordinate descent + successive-halving.

The knob space is small (a handful of knobs, 2-4 candidates each) but a
trial costs real wall time, so the search spends cheap short-window
trials ruling cells out and expensive long-window (median-of-3) trials
only on finalists — successive halving with two rungs:

- **explore** (short fidelity): full grid when the live space has <= 2
  knobs (it is exhaustively affordable: at most ~12 cells); coordinate
  descent otherwise — sweep one knob at a time holding the incumbent
  fixed, adopt a move only when it beats the incumbent by ``epsilon``
  (measurement noise must not walk the search), repeat passes until a
  full pass makes no move;
- **confirm** (long fidelity): the top ``promote_top`` short-window
  assignments AND the pure-default assignment re-measure with
  median-of-3 windows; the confirmed winner takes it.

Every (assignment, fidelity) cell is memoized — a quarantined cell is
remembered as infeasible and never re-attempted. The final regression
guard compares winner-vs-default at the SAME (long) fidelity and
returns empty overrides unless the winner actually wins: the tuner may
be useless, but it must never ship a slowdown (acceptance: tuned >=
default, equal acceptable).
"""

from __future__ import annotations

__all__ = ["run_search"]

MAX_PASSES = 3


def run_search(knobs, evaluate, base: dict, *, epsilon: float = 0.02,
               promote_top: int = 2, log=None) -> dict:
    """Search ``knobs`` (``searchable_knobs`` output: list of
    ``(knob, candidates)`` with the config's current value first) using
    ``evaluate(assignment, fidelity) -> (steps_per_sec | None, reason)``
    (``TrialRunner.evaluate`` or a test double). ``base`` maps every
    searched field to its current/default value.

    Returns ``{"overrides", "default_steps_per_sec",
    "tuned_steps_per_sec", "trials", "quarantined", "mode", "history"}``
    — ``overrides`` holds only the fields whose winning value differs
    from ``base`` (empty == keep the defaults).
    """
    log = log or (lambda s: None)
    memo: dict = {}
    history: list = []
    counts = {"trials": 0, "quarantined": 0}

    def measure(assignment: dict, fidelity: str) -> float | None:
        key = (tuple(sorted(assignment.items())), fidelity)
        if key in memo:
            return memo[key]
        sps, reason = evaluate(assignment, fidelity)
        if sps is not None or (reason or "").startswith("quarantined"):
            counts["trials"] += 1
        if (reason or "").startswith("quarantined"):
            counts["quarantined"] += 1
        memo[key] = sps
        history.append({"assignment": dict(assignment),
                        "fidelity": fidelity,
                        "steps_per_sec": (round(sps, 3)
                                          if sps is not None else None),
                        "reason": reason})
        return sps

    default_assign = {knob.field: cands[0] for knob, cands in knobs}
    if not knobs:
        return {"overrides": {}, "default_steps_per_sec": None,
                "tuned_steps_per_sec": None, "trials": 0,
                "quarantined": 0, "mode": "empty", "history": []}

    # -- explore rung (short fidelity) --------------------------------
    if len(knobs) <= 2:
        mode = "grid"
        cells = [{}]
        for knob, cands in knobs:
            cells = [{**cell, knob.field: v}
                     for cell in cells for v in cands]
        for cell in cells:
            measure(cell, "short")
    else:
        mode = "coordinate_descent"
        incumbent = dict(default_assign)
        incumbent_sps = measure(incumbent, "short")
        for _ in range(MAX_PASSES):
            moved = False
            for knob, cands in knobs:
                for v in cands:
                    if v == incumbent[knob.field]:
                        continue
                    sps = measure({**incumbent, knob.field: v}, "short")
                    if sps is not None and (
                            incumbent_sps is None
                            or sps > incumbent_sps * (1 + epsilon)):
                        incumbent = {**incumbent, knob.field: v}
                        incumbent_sps = sps
                        moved = True
            if not moved:
                break

    # -- confirm rung (long fidelity, successive-halving promotion) ---
    shorts = [(h["steps_per_sec"], h["assignment"]) for h in history
              if h["fidelity"] == "short"
              and h["steps_per_sec"] is not None]
    shorts.sort(key=lambda t: -t[0])
    finalists: list[dict] = []
    for _, assignment in shorts:
        if assignment not in finalists:
            finalists.append(assignment)
        if len(finalists) >= promote_top:
            break
    if default_assign not in finalists:
        finalists.append(default_assign)

    default_sps = None
    best_assign, best_sps = default_assign, None
    for assignment in finalists:
        sps = measure(assignment, "long")
        if assignment == default_assign:
            default_sps = sps
        if sps is not None and (best_sps is None or sps > best_sps):
            best_assign, best_sps = assignment, sps

    # -- regression guard ---------------------------------------------
    overrides = {f: v for f, v in best_assign.items() if v != base.get(f)}
    if overrides and default_sps is not None and best_sps is not None \
            and best_sps <= default_sps:
        log("[autotune] winner did not beat defaults at confirm "
            f"fidelity ({best_sps:.2f} vs {default_sps:.2f} steps/s); "
            "keeping defaults")
        overrides, best_sps = {}, default_sps
    if not overrides and default_sps is not None:
        best_sps = default_sps

    return {"overrides": overrides,
            "default_steps_per_sec": (round(default_sps, 3)
                                      if default_sps else None),
            "tuned_steps_per_sec": (round(best_sps, 3)
                                    if best_sps else None),
            "trials": counts["trials"],
            "quarantined": counts["quarantined"],
            "mode": mode, "history": history}
