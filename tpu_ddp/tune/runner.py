"""Timed autotuning trials: one knob assignment -> one steps/sec number.

A trial runs ``Trainer.train_epoch`` over a fixed set of synthetic
host batches (bench.py's zero-egress protocol: raw uint8 over the wire,
normalization fused into the jitted step) with the candidate knobs
applied, using the shared warm-compile + median-of-windows loop from
``utils/timing.py`` — warm epoch first (compile + first execution,
the reference's discarded iteration 0), then back-to-back timed epochs
with one sync per window. Short windows (``fidelity="short"``) feed the
search's pruning passes; long windows confirm finalists
(``tune/search.py``).

Robustness is the point, not an afterthought: a compile failure, OOM,
divergence, or wall-clock blowout in ONE cell must mark that point
infeasible and keep searching, never kill the tuner. Every trial body is
wrapped; the failure reason is recorded in the trial history (and the
cell never re-measured — the search memoizes).

Trial mechanics that keep measurements honest:

- the trial config is a ``copy.copy`` of the workload config with
  ``autotune="off"`` (no recursion), the reference timing window
  disabled (``timing_first_iter=1, timing_last_iter=0`` — the window
  forces synchronous dispatch, which would mask ``dispatch_depth``;
  the depth_sweep idiom), and ``guard_max_bad_steps`` effectively
  infinite (random-label synthetic data at the preset lr can trip the
  divergence guard; a trial measures throughput, not convergence — the
  guard is host-side, so this changes no compiled program);
- trainers are cached per *jit-relevant* knob subset (model dtype,
  Pallas kernels, wire format) and loop-level knobs (dispatch depth,
  K-per-dispatch, prefetch) are mutated on the cached trainer's config
  — the same step executable serves every loop-knob cell, so trials
  price dispatch discipline, not recompilation.
"""

from __future__ import annotations

import copy
import os
import time

import numpy as np

from tpu_ddp.tune.space import Workload, violations
from tpu_ddp.utils.timing import warm_then_median_s

__all__ = ["TrialRunner"]

# Knobs whose value changes the compiled step or the model itself: a new
# value needs a new Trainer (and model). Everything else is a host-loop
# property mutated on the shared trainer (pipeline.depth_sweep idiom).
JIT_FIELDS = ("compute_dtype", "pallas_sgd", "pallas_bn", "grad_compress")
LOOP_FIELDS = ("dispatch_depth", "steps_per_dispatch", "device_prefetch")


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    return int(raw) if raw else default


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    return float(raw) if raw else default


class TrialRunner:
    """Measures knob assignments for one workload.

    ``evaluate(assignment, fidelity)`` returns ``(steps_per_sec, None)``
    for a successful trial or ``(None, reason)`` for an infeasible /
    quarantined cell. The runner owns the synthetic batches, the
    per-jit-key trainer cache, the trial counter, and the budget knobs
    (``TPU_DDP_TUNE_ITERS`` batches per epoch, ``TPU_DDP_TUNE_TIMEOUT_S``
    per-trial wall ceiling, ``TPU_DDP_TUNE_MAX_TRIALS``).
    """

    def __init__(self, cfg, ctx: Workload, *, strategy: str = "fused",
                 mesh=None, n_batches: int | None = None,
                 timeout_s: float | None = None,
                 max_trials: int | None = None, log=None):
        self.ctx = ctx
        self.strategy = strategy
        self.mesh = mesh
        self.log = log or (lambda s: None)
        # steps_per_dispatch=8 needs >= 8 uniform batches per epoch to
        # engage the grouped path at all; 16 gives it two dispatches.
        self.n_batches = n_batches or _env_int("TPU_DDP_TUNE_ITERS", 16)
        self.timeout_s = (timeout_s if timeout_s is not None
                          else _env_float("TPU_DDP_TUNE_TIMEOUT_S", 60.0))
        self.max_trials = (max_trials if max_trials is not None
                           else _env_int("TPU_DDP_TUNE_MAX_TRIALS", 64))
        self.long_windows = _env_int("TPU_DDP_TUNE_WINDOWS", 3)
        self.trials = 0
        self.quarantined: list[dict] = []
        # (jit_key, effective K) pairs whose executables are already
        # compiled — their trials skip the warm epoch. dispatch_depth
        # and device_prefetch are pure host-loop properties (no new
        # executable), so the compile surface is exactly (trainer, K).
        self._warmed: set = set()

        # The trial base config: workload config minus everything that
        # would make a trial lie (see module docstring). copy.copy, not
        # dataclasses.replace — replace() re-runs __post_init__, which
        # re-applies env overrides on top of trial values.
        base = copy.copy(cfg)
        base.autotune = "off"
        base.timing_first_iter, base.timing_last_iter = 1, 0
        base.guard_max_bad_steps = 10**9
        base.max_iters = None
        base.log_every = 10**9
        self.base_cfg = base

        import jax

        world = max(1, jax.process_count())
        batch = cfg.per_node_batch_size(world)
        rng = np.random.default_rng(0)
        side = cfg.image_size
        n_distinct = min(4, self.n_batches)
        distinct = [
            (rng.integers(0, 256, size=(batch, side, side,
                                        cfg.in_channels)).astype(np.uint8),
             rng.integers(0, cfg.num_classes,
                          size=batch).astype(np.int32))
            for _ in range(n_distinct)]
        reps = -(-self.n_batches // n_distinct)
        self.host_batches = (distinct * reps)[:self.n_batches]
        self._trainers: dict = {}

    # -- trainer cache ------------------------------------------------

    def _jit_key(self, assignment: dict) -> tuple:
        return tuple(assignment.get(f, getattr(self.base_cfg, f))
                     for f in JIT_FIELDS)

    def _trainer_for(self, assignment: dict):
        key = self._jit_key(assignment)
        hit = self._trainers.get(key)
        if hit is not None:
            return hit

        import jax.numpy as jnp

        from tpu_ddp.models import get_model
        from tpu_ddp.train.engine import Trainer

        cfg = copy.copy(self.base_cfg)
        for f, v in assignment.items():
            setattr(cfg, f, v)
        model = get_model(cfg.model, num_classes=cfg.num_classes,
                          use_pallas_bn=cfg.pallas_bn,
                          compute_dtype=jnp.dtype(cfg.compute_dtype))
        trainer = Trainer(model, cfg, strategy=self.strategy,
                          mesh=self.mesh)
        state = trainer.init_state()
        self._trainers[key] = (trainer, state)
        return self._trainers[key]

    # -- trials -------------------------------------------------------

    def evaluate(self, assignment: dict,
                 fidelity: str = "short") -> tuple[float | None, str | None]:
        """Measure ``assignment`` (field -> value, defaults implied for
        absent fields); ``fidelity`` picks the window count (short=1
        prunes, long=3 confirms with a median)."""
        bad = violations({**{f: getattr(self.base_cfg, f)
                             for f in JIT_FIELDS + LOOP_FIELDS},
                          **assignment}, self.ctx)
        if bad:
            return None, "constraint: " + "; ".join(bad)
        if self.trials >= self.max_trials:
            return None, f"budget: max_trials={self.max_trials} reached"

        self.trials += 1
        windows = self.long_windows if fidelity == "long" else 1
        t_start = time.perf_counter()
        try:
            trainer, state = self._trainer_for(assignment)
            cfg = trainer.config
            saved = {f: getattr(cfg, f) for f in LOOP_FIELDS}
            try:
                for f in LOOP_FIELDS:
                    setattr(cfg, f, assignment.get(f, saved[f]))

                def epoch():
                    nonlocal state
                    state, stats = trainer.train_epoch(
                        state, list(self.host_batches), epoch=0,
                        log=lambda s: None)
                    return None  # train_epoch already syncs its tail

                # Warm (compile + first execution) only when this cell
                # needs an executable no earlier trial built: the
                # grouped-K path engages exactly when K>1 with no
                # prefetch and no in-loop cadence (engine.train_epoch),
                # so the compile surface is (trainer, effective K).
                spd = assignment.get("steps_per_dispatch",
                                     saved["steps_per_dispatch"])
                grouped = (spd > 1
                           and not assignment.get(
                               "device_prefetch",
                               saved["device_prefetch"])
                           and not cfg.ckpt_every_iters
                           and not cfg.check_replicas_every)
                warm_key = (self._jit_key(assignment),
                            spd if grouped else 1)
                if warm_key not in self._warmed:
                    epoch()
                    self._warmed.add(warm_key)
                    if time.perf_counter() - t_start > self.timeout_s:
                        raise TimeoutError(
                            f"warm epoch blew the {self.timeout_s}s "
                            "trial budget")
                epoch_s, samples = warm_then_median_s(
                    epoch, iters=1, windows=windows, warmup=0,
                    sync=lambda _: None)
            finally:
                for f, v in saved.items():
                    setattr(cfg, f, v)
                # Trials share state across cells on purpose (random
                # labels; throughput only) — write the advanced state
                # back so the cache never rewinds to step 0.
                self._trainers[self._jit_key(assignment)] = (trainer,
                                                             state)
        except KeyboardInterrupt:
            raise
        except BaseException as e:  # noqa: BLE001 — quarantine, don't die
            # XlaRuntimeError (compile failure / RESOURCE_EXHAUSTED OOM),
            # TrainingDivergedError, TimeoutError... a bad cell is an
            # infeasible point, not a dead search.
            if isinstance(e, (SystemExit, GeneratorExit)):
                raise
            reason = f"quarantined: {type(e).__name__}: {e}"
            self.quarantined.append({"assignment": dict(assignment),
                                     "reason": reason})
            self.log(f"[autotune] trial quarantined "
                     f"({dict(assignment)}): {type(e).__name__}: {e}")
            return None, reason

        sps = self.n_batches / epoch_s
        self.log(f"[autotune] trial {self.trials}: {dict(assignment)} "
                 f"-> {sps:.2f} steps/s ({fidelity}, "
                 f"windows={[round(s, 4) for s in samples]})")
        return sps, None
