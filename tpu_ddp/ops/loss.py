"""Cross-entropy loss.

Replaces the reference's ``torch.nn.CrossEntropyLoss()`` (reference
part1/main.py:119, applied to logits + integer labels with mean reduction).
Implemented directly over ``logsumexp`` so XLA fuses it into the train step.
"""

import jax
import jax.numpy as jnp
from jax import lax
from jax.scipy.special import logsumexp


def softmax_cross_entropy(logits, labels):
    """Per-example CE of integer ``labels`` against ``logits`` (f32)."""
    logits = logits.astype(jnp.float32)
    lse = logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(
        logits, labels[:, None].astype(jnp.int32), axis=-1)[:, 0]
    return lse - picked


def cross_entropy_loss(logits, labels):
    """Mean-reduced CE — the exact semantics of torch's default
    ``CrossEntropyLoss`` used at reference part1/main.py:74-75."""
    return jnp.mean(softmax_cross_entropy(logits, labels))


def chunked_vocab_cross_entropy(hidden, head, labels, chunk: int):
    """Per-token CE of ``hidden @ head`` WITHOUT materializing the full
    (T, V) logits tensor.

    ``hidden``: (T, dm) final-LayerNorm activations; ``head``: (dm, V);
    ``labels``: (T,) int. A ``lax.scan`` over vocab chunks keeps an
    online logsumexp (running max / scaled sum) plus the label logit, so
    peak memory is O(T * chunk) instead of O(T * V) — at 32k+ vocab and
    long context the logits tensor is the train step's largest buffer
    (e.g. (8*4096, 32k) f32 = 4 GB). The head matmul itself fuses into
    the scan chunk by chunk.

    The scan body is wrapped in ``jax.checkpoint``: without it, scan's
    autodiff would SAVE each chunk's logits as residuals — O(T * V)
    again, precisely what this function exists to avoid — so the
    backward instead recomputes each chunk's matmul. Numerically
    identical to ``softmax_cross_entropy(hidden @ head, labels)``
    (tested).

    This is a MEMORY lever, not a speed one: the serialized chunk scan
    plus backward recompute measurably underruns the dense path when the
    dense path fits — enable it when the (T, V) logits buffer is what
    keeps a long-context configuration from fitting, and prefer the
    largest chunk that fits.
    """
    T, dm = hidden.shape
    V = head.shape[1]
    if V % chunk:
        raise ValueError(f"vocab {V} not divisible by chunk {chunk}")
    labels = labels.astype(jnp.int32)
    n_chunks = V // chunk
    # Same matmul precision as the dense path: operands in the model's
    # compute dtype (bf16 rides the MXU fast path), f32 accumulation.
    head_c = jnp.moveaxis(
        head.astype(hidden.dtype).reshape(dm, n_chunks, chunk), 1, 0)

    def body(carry, inputs):
        m, s, picked = carry
        idx, w = inputs                       # chunk index, (dm, chunk)
        logits = jnp.dot(hidden, w, preferred_element_type=jnp.float32)
        cm = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, cm)
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1)
        local = labels - idx * chunk
        in_chunk = (local >= 0) & (local < chunk)
        lab = jnp.take_along_axis(
            logits, jnp.clip(local, 0, chunk - 1)[:, None], axis=-1)[:, 0]
        picked = jnp.where(in_chunk, lab, picked)
        return (m_new, s, picked), None

    init = (jnp.full((T,), -jnp.inf, jnp.float32),
            jnp.zeros((T,), jnp.float32),
            jnp.zeros((T,), jnp.float32))
    (m, s, picked), _ = lax.scan(jax.checkpoint(body, prevent_cse=False),
                                 init, (jnp.arange(n_chunks), head_c))
    return m + jnp.log(s) - picked
