"""Cross-entropy loss.

Replaces the reference's ``torch.nn.CrossEntropyLoss()`` (reference
part1/main.py:119, applied to logits + integer labels with mean reduction).
Implemented directly over ``logsumexp`` so XLA fuses it into the train step.
"""

import jax.numpy as jnp
from jax.scipy.special import logsumexp


def softmax_cross_entropy(logits, labels):
    """Per-example CE of integer ``labels`` against ``logits`` (f32)."""
    logits = logits.astype(jnp.float32)
    lse = logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(
        logits, labels[:, None].astype(jnp.int32), axis=-1)[:, 0]
    return lse - picked


def cross_entropy_loss(logits, labels):
    """Mean-reduced CE — the exact semantics of torch's default
    ``CrossEntropyLoss`` used at reference part1/main.py:74-75."""
    return jnp.mean(softmax_cross_entropy(logits, labels))
