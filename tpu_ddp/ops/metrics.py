"""Eval metrics (reference part1/main.py:96-111: summed loss + top-1)."""

import jax.numpy as jnp


def top1_correct(logits, labels):
    """Number of argmax-correct predictions in the batch
    (reference part1/main.py:104-106)."""
    return jnp.sum(jnp.argmax(logits, axis=-1) == labels)
