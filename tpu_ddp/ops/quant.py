"""Weight-only int8 quantization for decode compute (DESIGN.md §26).

Decode is memory-bandwidth-bound: every engine step streams the full
parameter set from HBM to produce one (or, speculatively, k+1) tokens
per sequence. Weight-only quantization attacks exactly that wall —
the dense/attention projection weights are stored per-output-channel
int8 (4x fewer bytes than f32) and dequantized INSIDE the matmul:

    y @ W  ≈  (y @ Q) * s        Q int8 (in, out), s f32 (out,)

The scale factors commute with the contraction because they are
per-OUTPUT-column — the fp weights are never materialized, so the
compute path reads int8 bytes. Activations, embeddings and LayerNorms
stay in the compute dtype: the quality cliff of activation
quantization is not worth the bytes (embed is a gather, not a matmul).

Two execution paths, one contract:

- :func:`qdot` — the ONE dispatch point every decode-path matmul
  routes through (models/transformer.py ``qkv_proj``/``project``,
  models/decode.py ``mlp``/``block_finish``). For a plain array it
  traces byte-for-byte the pre-quantization program (same astype/
  reshape/dot sequence), so fp engines are bitwise unchanged. For a
  :class:`QuantizedWeight` it runs the fused int8 matmul.
- On TPU the fused matmul is the Pallas kernel
  (ops/pallas/quant_matmul.py): int8 tiles stream into VMEM, convert
  on the MXU's doorstep, and the per-column scale fuses into the
  epilogue. Off-TPU the reference XLA path computes the identical
  ``dot(x, q.astype(f32)) * s`` contraction.

:class:`QuantizedWeight` is a registered pytree node, so a quantized
parameter tree flows through ``jax.jit`` argument passing, donation
and ``tree.map`` exactly like a dense one — the serving engine keys
its memoized program caches on the treedef, which differs from the fp
tree's, giving quantized programs their own jit cache entries for
free. The quality bar is the compress-sweep convention: mean NLL of a
seeded eval stream within 0.25% of the fp32 model
(:func:`nll_drift`, enforced by scripts/spec_sweep.py and
tests/test_speculative.py).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

__all__ = ["QuantizedWeight", "quantize_weight", "dequantize",
           "quantize_params", "qdot", "decode_forward_logits",
           "stream_nll", "nll_drift", "DECODE_QUANTS"]

DECODE_QUANTS = ("none", "int8")


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class QuantizedWeight:
    """One int8-quantized weight in matmul layout: ``q`` (in, out)
    int8, ``s`` (out,) f32 per-output-channel scales. Symmetric
    (no zero point): ``W ≈ q * s``."""

    q: jax.Array
    s: jax.Array

    def tree_flatten(self):
        return (self.q, self.s), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        del aux
        return cls(*children)

    @property
    def shape(self):
        return self.q.shape

    @property
    def nbytes_dense_f32(self) -> int:
        return 4 * int(self.q.size)


def quantize_weight(w, reshape=None) -> QuantizedWeight:
    """Per-output-channel symmetric int8: ``s_c = max|w[:, c]| / 127``,
    ``q = round(w / s)``. ``reshape`` first brings a multi-axis weight
    into its 2-D (in, out) matmul layout (the same reshape the fp
    matmul call site applies), so quantization channels are exactly
    the matmul's output columns."""
    w = jnp.asarray(w, jnp.float32)
    if reshape is not None:
        w = w.reshape(reshape)
    if w.ndim != 2:
        raise ValueError(f"quantize_weight wants a 2-D matmul layout, "
                         f"got shape {w.shape}")
    amax = jnp.max(jnp.abs(w), axis=0)
    # An all-zero column quantizes to zeros under any scale; 1.0 keeps
    # the division finite without changing the result.
    s = jnp.where(amax > 0, amax, 1.0) / 127.0
    q = jnp.clip(jnp.round(w / s), -127, 127).astype(jnp.int8)
    return QuantizedWeight(q=q, s=s.astype(jnp.float32))


def dequantize(qw: QuantizedWeight):
    """fp32 reconstruction ``q * s`` — tests and error bounds only;
    the serving path never materializes this."""
    return qw.q.astype(jnp.float32) * qw.s[None, :]


def qdot(y, w, cd, reshape=None):
    """The one decode-path matmul dispatch: ``y @ w`` in f32 accum.

    Plain array ``w``: exactly the pre-quantization program —
    ``dot(y, w.astype(cd).reshape(reshape))`` with f32 accumulation,
    bitwise identical to the inlined call sites it replaced.
    :class:`QuantizedWeight`: the fused weight-only int8 matmul
    (``reshape`` is ignored — quantized weights are stored in matmul
    layout). Returns f32 (callers cast back to ``cd`` exactly where
    the fp code did)."""
    if isinstance(w, QuantizedWeight):
        if jax.default_backend() == "tpu":
            from tpu_ddp.ops.pallas.quant_matmul import int8_matmul
            return int8_matmul(y.astype(cd), w.q, w.s)
        # Reference XLA path: the scale is per-output-column, so it
        # commutes with the contraction — dequant AFTER the dot keeps
        # the weight reads int8.
        acc = jnp.dot(y.astype(cd), w.q.astype(cd),
                      preferred_element_type=jnp.float32)
        return acc * w.s
    w = w.astype(cd)
    if reshape is not None:
        w = w.reshape(reshape)
    return jnp.dot(y, w, preferred_element_type=jnp.float32)


def quantize_params(model, params):
    """Quantize every decode-path projection of a dense transformer
    parameter tree: per-block wqkv/wq/wkv, wo, w1/w2, plus the LM
    head. Embedding and LayerNorm leaves pass through untouched (they
    are gathers/normalizations, not matmuls). Returns a NEW tree with
    the same dict structure; matmul leaves become
    :class:`QuantizedWeight` in their 2-D matmul layout (the reshape
    their fp call sites applied)."""
    dm = model.d_model

    def one_block(blk):
        out = dict(blk)
        for name in ("wqkv", "wq", "wkv"):
            if name in blk:
                out[name] = quantize_weight(blk[name], reshape=(dm, -1))
        out["wo"] = quantize_weight(blk["wo"], reshape=(-1, dm))
        out["w1"] = quantize_weight(blk["w1"])
        out["w2"] = quantize_weight(blk["w2"])
        return out

    out = dict(params)
    out["blocks"] = tuple(one_block(blk) for blk in params["blocks"])
    out["head"] = quantize_weight(params["head"])
    return out


def decode_forward_logits(model, params, tokens):
    """Full-sequence logits (B, L, V) through the DECODE math path
    (project_qkv / attend_cached / block_finish / head_apply) — the
    path :func:`qdot` routes, so it accepts fp and quantized trees
    alike. This is the quality-bar forward: it evaluates exactly the
    program the serving engine runs, not the training ``apply``."""
    from tpu_ddp.models.decode import (attend_cached, block_finish,
                                       project_qkv)

    cd = model.compute_dtype
    b, L = tokens.shape
    pos = jnp.arange(L)
    x = params["embed"][tokens].astype(cd)
    for blk in params["blocks"]:
        q, k, v = project_qkv(model, blk, x, pos)
        o = attend_cached(model, q, k.astype(cd), v.astype(cd), pos)
        x = block_finish(model, blk, x, o)
    return model.head_apply(params, x)


def stream_nll(model, params, tokens) -> jax.Array:
    """Mean next-token NLL of ``tokens`` (B, L) under ``params``
    through the decode path — the scalar the 0.25%-of-fp32 quality
    bar compares."""
    logits = decode_forward_logits(model, params, tokens)
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), -1)
    tgt = tokens[:, 1:]
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)
    return jnp.mean(nll)


def nll_drift(model, params, qparams, tokens) -> dict:
    """The committed quality metric for ``decode_quant``: relative
    mean-NLL drift of the quantized tree vs the fp tree on a seeded
    eval stream, plus greedy next-token agreement (reported, not
    gated). The bar (≤ 0.25%, the compress-sweep convergence-drift
    convention) is enforced by the callers."""
    lf = decode_forward_logits(model, params, tokens)
    lq = decode_forward_logits(model, qparams, tokens)
    nll_f = float(stream_nll(model, params, tokens))
    nll_q = float(stream_nll(model, qparams, tokens))
    agree = float(jnp.mean(jnp.argmax(lf, -1) == jnp.argmax(lq, -1)))
    return {
        "nll_fp32": nll_f,
        "nll_int8": nll_q,
        "rel_drift": abs(nll_q - nll_f) / max(abs(nll_f), 1e-12),
        "greedy_agreement": agree,
        "max_abs_logit_err": float(jnp.max(jnp.abs(lq - lf))),
    }
