"""Exponential moving average of parameters (Polyak averaging).

No reference counterpart (the reference evaluates the raw SGD iterate,
part1/main.py:96-111); EMA is the standard eval-time smoothing for
vision training and half of many semi-supervised recipes. Pure pytree
transform in the zoo's optimizer style (tpu_ddp/ops/optim.py): state
lives wherever the params live, the update is elementwise and fuses
into the jitted train step.

The effective decay warms up as ``min(decay, (1 + t) / (10 + t))`` (the
classic schedule), so early EMA params track the fast-moving young
model instead of its random init.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class EMA:
    decay: float = 0.999
    warmup: bool = True

    def init(self, params) -> dict:
        return {"ema": jax.tree.map(jnp.asarray, params),
                "count": jnp.zeros((), jnp.int32)}

    def update(self, state: dict, params) -> dict:
        count = state["count"] + 1
        if self.warmup:
            c = count.astype(jnp.float32)
            d = jnp.minimum(self.decay, (1.0 + c) / (10.0 + c))
        else:
            d = self.decay
        # Blend in f32, store back in the state's own dtype — the warmup
        # `d` is a strong-typed f32 scalar and would otherwise promote
        # bf16 state to f32 (breaking scan carries and doubling memory).
        ema = jax.tree.map(
            lambda e, p: (e.astype(jnp.float32) * d
                          + p.astype(jnp.float32) * (1.0 - d)
                          ).astype(e.dtype),
            state["ema"], params)
        return {"ema": ema, "count": count}

    def params(self, state: dict):
        """The averaged parameters (plug into ``model.apply`` for eval)."""
        return state["ema"]
