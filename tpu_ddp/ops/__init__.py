"""Numerical ops owned by the framework: loss, optimizer, metrics.

These replace the reference's dependency surface (torch ``CrossEntropyLoss``,
``optim.SGD`` — ATen C++ kernels, SURVEY.md §2 row N3) with jax.numpy/XLA
implementations that fuse into the jitted train step.
"""

from tpu_ddp.ops.loss import cross_entropy_loss, softmax_cross_entropy  # noqa: F401
from tpu_ddp.ops.ema import EMA  # noqa: F401
from tpu_ddp.ops.optim import (  # noqa: F401
    SGD,
    SGDState,
    AdamW,
    Adafactor,
    warmup_cosine,
)
from tpu_ddp.ops.metrics import top1_correct  # noqa: F401
