"""SGD with momentum + weight decay, torch-semantics.

Replaces ``torch.optim.SGD(lr=0.1, momentum=0.9, weight_decay=1e-4)``
(reference part1/main.py:124-125). Torch's update rule (which differs from
some textbook variants) is:

    g   <- grad + weight_decay * param        # decoupled-from-loss L2
    buf <- momentum * buf + g                 # no dampening
    p   <- p - lr * buf

Hand-rolled as a pure pytree transform (no optax dependency needed for
parity) so the whole update fuses into the jitted train step; optimizer
state lives in the same sharding as the parameters.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec


SGDState = dict  # {"momentum": pytree like params}


@dataclasses.dataclass(frozen=True)
class SGD:
    learning_rate: float = 0.1
    momentum: float = 0.9
    weight_decay: float = 1e-4
    # Run the whole update as one single-pass Pallas kernel per leaf
    # (tpu_ddp/ops/pallas/sgd.py) instead of the tree.map chain below.
    use_pallas: bool = False

    def init(self, params) -> SGDState:
        return {"momentum": jax.tree.map(jnp.zeros_like, params)}

    def state_specs(self, param_specs):
        """Optimizer-state PartitionSpec tree mirroring ``param_specs`` —
        momentum lives in the same sharding as its parameter."""
        return {"momentum": param_specs}

    def decay_mask(self, params):
        """Torch SGD decays every parameter uniformly (reference
        part1/main.py:124-125) — no mask needed."""
        return None

    def map_param_like(self, state: SGDState, fn):
        """Apply ``fn`` to each params-shaped subtree of the state
        (ZeRO/FSDP re-layout hook); scalars would pass through unchanged
        (SGD has none)."""
        return {"momentum": fn(state["momentum"])}

    def _new_buf(self, p, g, buf):
        g = g.astype(p.dtype)
        if self.weight_decay:
            g = g + self.weight_decay * p
        return self.momentum * buf + g

    def apply(self, params, grads, state: SGDState, decay_mask=None):
        """One update; returns (new_params, new_state).

        ``decay_mask`` is accepted for optimizer-API uniformity (ZeRO
        passes one) and ignored: torch SGD decays every parameter
        uniformly (reference part1/main.py:124-125), so flattened slices
        update identically to the original leaves.
        """
        del decay_mask
        if self.use_pallas:
            from tpu_ddp.ops.pallas import fused_sgd_step
            new_params, new_buf = fused_sgd_step(
                params, grads, state["momentum"],
                lr=self.learning_rate, momentum=self.momentum,
                weight_decay=self.weight_decay)
            return new_params, {"momentum": new_buf}
        # Two tree.maps (buf recomputed in the second) — XLA CSEs the
        # duplicate, and it keeps the pytree structure trivially aligned.
        new_buf = jax.tree.map(self._new_buf, params, grads,
                               state["momentum"])
        new_params = jax.tree.map(
            lambda p, buf: p - self.learning_rate * buf, params, new_buf)
        return new_params, {"momentum": new_buf}


@dataclasses.dataclass(frozen=True)
class AdamW:
    """AdamW (decoupled weight decay) — the LM-family optimizer.

    No reference counterpart (the reference uses SGD only,
    part1/main.py:124-125); added for the transformer/long-context models,
    same pure-pytree-transform shape as :class:`SGD`.

    ``learning_rate`` may be a float or a SCHEDULE — any callable
    ``step (f32 scalar, 1-based) -> lr`` (e.g. :func:`warmup_cosine`);
    it is evaluated inside the jitted step from the state's own count,
    so resume continues the schedule exactly.
    """

    learning_rate: Any = 3e-4
    b1: float = 0.9
    b2: float = 0.95
    eps: float = 1e-8
    # Decoupled decay, applied ONLY to matrix-shaped leaves (ndim >= 2):
    # LayerNorm scales/biases and bias vectors are exempt, embeddings and
    # projection matrices decay — the standard transformer AdamW recipe.
    weight_decay: float = 0.1

    def init(self, params) -> dict:
        zeros = lambda: jax.tree.map(jnp.zeros_like, params)  # noqa: E731
        return {"mu": zeros(), "nu": zeros(),
                "count": jnp.zeros((), jnp.int32)}

    def state_specs(self, param_specs):
        """Optimizer-state PartitionSpec tree mirroring ``param_specs`` —
        moments live in the same sharding as their parameter."""
        return {"mu": param_specs, "nu": param_specs,
                "count": PartitionSpec()}

    def decay_mask(self, params):
        """The decay policy, queryable by wrappers (ZeRO) that re-lay-out
        leaves and must evaluate it on the ORIGINAL shapes."""
        return jax.tree.map(lambda p: p.ndim >= 2, params)

    def map_param_like(self, state, fn):
        """Apply ``fn`` to each params-shaped subtree of the state
        (ZeRO/FSDP re-layout hook); the step count passes through."""
        return {"mu": fn(state["mu"]), "nu": fn(state["nu"]),
                "count": state["count"]}

    def apply(self, params, grads, state, decay_mask=None):
        """``decay_mask``: optional bool pytree overriding the ndim>=2
        rule per leaf — ZeRO passes the ORIGINAL leaves' ranks since its
        flattened slices are all rank-1."""
        count = state["count"] + 1
        c = count.astype(jnp.float32)
        bc1 = 1.0 - self.b1 ** c
        bc2 = 1.0 - self.b2 ** c
        lr = (self.learning_rate(c) if callable(self.learning_rate)
              else self.learning_rate)
        if decay_mask is None:
            decay_mask = self.decay_mask(params)
        # Separate tree.maps per output (the SGD style above): structure-
        # safe for any params pytree, and XLA CSEs the shared subterms.
        new_mu = jax.tree.map(
            lambda p, g, mu: self.b1 * mu + (1 - self.b1) * g.astype(p.dtype),
            params, grads, state["mu"])
        new_nu = jax.tree.map(
            lambda p, g, nu: self.b2 * nu
            + (1 - self.b2) * jnp.square(g.astype(p.dtype)),
            params, grads, state["nu"])
        new_p = jax.tree.map(
            lambda p, mu, nu, dk: p - lr * (
                (mu / bc1) / (jnp.sqrt(nu / bc2) + self.eps)
                + (self.weight_decay * p if dk else 0.0)),
            params, new_mu, new_nu, decay_mask)
        return new_p, {"mu": new_mu, "nu": new_nu, "count": count}


def warmup_cosine(peak_lr: float, warmup_steps: int, total_steps: int,
                  floor: float = 0.0):
    """Linear warmup to ``peak_lr`` then cosine decay to ``floor`` — the
    standard transformer LM schedule. Returns a jit-safe callable
    ``step (1-based f32) -> lr`` for :class:`AdamW`'s ``learning_rate``.
    """
    if not 0 < warmup_steps < total_steps:
        raise ValueError(f"need 0 < warmup_steps={warmup_steps} < "
                         f"total_steps={total_steps}")

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = peak_lr * step / warmup_steps
        frac = jnp.clip((step - warmup_steps)
                        / (total_steps - warmup_steps), 0.0, 1.0)
        cos = floor + (peak_lr - floor) * 0.5 * (1 + jnp.cos(jnp.pi * frac))
        return jnp.where(step < warmup_steps, warm, cos)

    return schedule
