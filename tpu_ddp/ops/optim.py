"""SGD with momentum + weight decay, torch-semantics.

Replaces ``torch.optim.SGD(lr=0.1, momentum=0.9, weight_decay=1e-4)``
(reference part1/main.py:124-125). Torch's update rule (which differs from
some textbook variants) is:

    g   <- grad + weight_decay * param        # decoupled-from-loss L2
    buf <- momentum * buf + g                 # no dampening
    p   <- p - lr * buf

Hand-rolled as a pure pytree transform (no optax dependency needed for
parity) so the whole update fuses into the jitted train step; optimizer
state lives in the same sharding as the parameters.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec


SGDState = dict  # {"momentum": pytree like params}


@dataclasses.dataclass(frozen=True)
class SGD:
    # Float, or a schedule ``step (f32, 1-based) -> lr`` (e.g.
    # :func:`warmup_cosine`) evaluated inside the jitted step — the same
    # contract as :class:`AdamW`. Scheduled SGD carries a step count in
    # its state; plain SGD keeps the reference's stateless two-buffer
    # form (reference part1/main.py:124-125).
    learning_rate: Any = 0.1
    momentum: float = 0.9
    weight_decay: float = 1e-4
    # Run the whole update as one single-pass Pallas kernel per leaf
    # (tpu_ddp/ops/pallas/sgd.py) instead of the tree.map chain below.
    use_pallas: bool = False

    def __post_init__(self):
        if callable(self.learning_rate) and self.use_pallas:
            raise ValueError("use_pallas SGD takes a static lr; "
                             "scheduled learning rates use the jnp path")

    def init(self, params) -> SGDState:
        state = {"momentum": jax.tree.map(jnp.zeros_like, params)}
        if callable(self.learning_rate):
            state["count"] = jnp.zeros((), jnp.int32)
        return state

    def state_specs(self, param_specs):
        """Optimizer-state PartitionSpec tree mirroring ``param_specs`` —
        momentum lives in the same sharding as its parameter."""
        specs = {"momentum": param_specs}
        if callable(self.learning_rate):
            specs["count"] = PartitionSpec()
        return specs

    def decay_mask(self, params):
        """Torch SGD decays every parameter uniformly (reference
        part1/main.py:124-125) — no mask needed."""
        return None

    def map_param_like(self, state: SGDState, fn):
        """Apply ``fn`` to each params-shaped subtree of the state
        (ZeRO/FSDP re-layout hook); the schedule's step count (if any)
        passes through."""
        out = {"momentum": fn(state["momentum"])}
        if "count" in state:
            out["count"] = state["count"]
        return out

    def _new_buf(self, p, g, buf):
        g = g.astype(p.dtype)
        if self.weight_decay:
            g = g + self.weight_decay * p
        return self.momentum * buf + g

    def apply(self, params, grads, state: SGDState, decay_mask=None):
        """One update; returns (new_params, new_state).

        ``decay_mask`` is accepted for optimizer-API uniformity (ZeRO
        passes one) and ignored: torch SGD decays every parameter
        uniformly (reference part1/main.py:124-125), so flattened slices
        update identically to the original leaves.
        """
        del decay_mask
        if self.use_pallas:
            from tpu_ddp.ops.pallas import fused_sgd_step
            new_params, new_buf = fused_sgd_step(
                params, grads, state["momentum"],
                lr=self.learning_rate, momentum=self.momentum,
                weight_decay=self.weight_decay)
            return new_params, {"momentum": new_buf}
        # One update path for static and scheduled lr (AdamW's pattern):
        # resolve lr first, conditionally carry the schedule's count.
        scheduled = callable(self.learning_rate)
        if scheduled:
            count = state["count"] + 1
            lr = self.learning_rate(count.astype(jnp.float32))
        else:
            lr = self.learning_rate
        # Two tree.maps (buf recomputed in the second) — XLA CSEs the
        # duplicate, and it keeps the pytree structure trivially aligned.
        # astype: a traced f32 lr must not promote bf16 params.
        new_buf = jax.tree.map(self._new_buf, params, grads,
                               state["momentum"])
        new_params = jax.tree.map(
            lambda p, buf: (p - lr * buf).astype(p.dtype),
            params, new_buf)
        out = {"momentum": new_buf}
        if scheduled:
            out["count"] = count
        return new_params, out


@dataclasses.dataclass(frozen=True)
class AdamW:
    """AdamW (decoupled weight decay) — the LM-family optimizer.

    No reference counterpart (the reference uses SGD only,
    part1/main.py:124-125); added for the transformer/long-context models,
    same pure-pytree-transform shape as :class:`SGD`.

    ``learning_rate`` may be a float or a SCHEDULE — any callable
    ``step (f32 scalar, 1-based) -> lr`` (e.g. :func:`warmup_cosine`);
    it is evaluated inside the jitted step from the state's own count,
    so resume continues the schedule exactly.
    """

    learning_rate: Any = 3e-4
    b1: float = 0.9
    b2: float = 0.95
    eps: float = 1e-8
    # Decoupled decay, applied ONLY to matrix-shaped leaves (ndim >= 2):
    # LayerNorm scales/biases and bias vectors are exempt, embeddings and
    # projection matrices decay — the standard transformer AdamW recipe.
    weight_decay: float = 0.1

    def init(self, params) -> dict:
        zeros = lambda: jax.tree.map(jnp.zeros_like, params)  # noqa: E731
        return {"mu": zeros(), "nu": zeros(),
                "count": jnp.zeros((), jnp.int32)}

    def state_specs(self, param_specs):
        """Optimizer-state PartitionSpec tree mirroring ``param_specs`` —
        moments live in the same sharding as their parameter."""
        return {"mu": param_specs, "nu": param_specs,
                "count": PartitionSpec()}

    def decay_mask(self, params):
        """The decay policy, queryable by wrappers (ZeRO) that re-lay-out
        leaves and must evaluate it on the ORIGINAL shapes."""
        return jax.tree.map(lambda p: p.ndim >= 2, params)

    def map_param_like(self, state, fn):
        """Apply ``fn`` to each params-shaped subtree of the state
        (ZeRO/FSDP re-layout hook); the step count passes through."""
        return {"mu": fn(state["mu"]), "nu": fn(state["nu"]),
                "count": state["count"]}

    def apply(self, params, grads, state, decay_mask=None):
        """``decay_mask``: optional bool pytree overriding the ndim>=2
        rule per leaf — ZeRO passes the ORIGINAL leaves' ranks since its
        flattened slices are all rank-1."""
        count = state["count"] + 1
        c = count.astype(jnp.float32)
        bc1 = 1.0 - self.b1 ** c
        bc2 = 1.0 - self.b2 ** c
        lr = (self.learning_rate(c) if callable(self.learning_rate)
              else self.learning_rate)
        if decay_mask is None:
            decay_mask = self.decay_mask(params)
        # Separate tree.maps per output (the SGD style above): structure-
        # safe for any params pytree, and XLA CSEs the shared subterms.
        new_mu = jax.tree.map(
            lambda p, g, mu: self.b1 * mu + (1 - self.b1) * g.astype(p.dtype),
            params, grads, state["mu"])
        new_nu = jax.tree.map(
            lambda p, g, nu: self.b2 * nu
            + (1 - self.b2) * jnp.square(g.astype(p.dtype)),
            params, grads, state["nu"])
        new_p = jax.tree.map(
            lambda p, mu, nu, dk: p - lr * (
                (mu / bc1) / (jnp.sqrt(nu / bc2) + self.eps)
                + (self.weight_decay * p if dk else 0.0)),
            params, new_mu, new_nu, decay_mask)
        return new_p, {"mu": new_mu, "nu": new_nu, "count": count}


@dataclasses.dataclass(frozen=True)
class Adafactor:
    """Adafactor — sublinear-memory second moments (Shazeer & Stern,
    arXiv:1804.04235; reimplemented from the paper, not from any code).

    The TPU-frugal LM optimizer: for matrix leaves the second moment is
    stored FACTORED as a row vector + a column vector (O(n+m) instead of
    O(nm) — for an embedding table that is ~vocab_size x smaller), with
    the rank-1 reconstruction ``V[i,j] ≈ vr[i]·vc[j] / mean_i(vr)``
    (mean-form accumulators: vr/vc are per-row/per-column MEANS of the
    EMA'd g², so the normalizer is the row-moment mean — equivalent to
    the paper's sum-form ``R·C / 1ᵀR``). Vectors/scalars keep
    an exact full second moment. Per the paper: update clipping by RMS
    (``clip_threshold``), increasing decay ``beta2_t = 1 - t^-decay_rate``
    and, when ``learning_rate`` is None, the relative step size
    ``min(1e-2, 1/sqrt(t)) * max(eps2, RMS(param))``.

    Same pure-pytree-transform shape as :class:`SGD`/:class:`AdamW`.

    Composition: ZeRO-1 optimizer-state sharding uses the dedicated
    row-sharded wrapper ``tpu_ddp.parallel.zero.FactoredZeRO1``
    (``LMTrainer(opt_sharding="zero1")`` selects it automatically) — the
    generic flat re-layout (``map_param_like``) cannot host factored
    state and refuses loudly. Tensor-sharded (tp/ep/pp-stacked)
    parameter leaves compose via PER-CELL factoring (round-5): the
    trainers wrap this optimizer in
    ``tpu_ddp.parallel.zero.CellAdafactor`` (replicated opt) or the
    partition-aware ``FactoredZeRO1`` (``opt_sharding="zero1"``) — each
    model-parallel cell factors its own local slice, the T5X semantic.
    The BARE ``state_specs`` still refuses sharded leaves (its reduced
    state shapes have no global layout without the cell axes those
    wrappers add).
    """

    learning_rate: Any = None       # None -> relative step size schedule
    min_dim_size_to_factor: int = 128
    decay_rate: float = 0.8
    eps1: float = 1e-30             # regularizer inside sqrt
    eps2: float = 1e-3              # RMS(param) floor for relative steps
    clip_threshold: float = 1.0
    b1: float | None = None        # optional first moment (off = paper default)
    weight_decay: float = 0.0

    def _plan(self, shape):
        """How to factor a leaf of ``shape`` (None = full second moment).

        - ``("batch", None)``: factor the last two dims, batched over any
          leading dims (vr = shape[:-1], vc = shape[:-2]+shape[-1:]) —
          the right semantics for stacked per-layer/per-expert matrices,
          where each matrix gets its own factors.
        - ``("split", k)``: the last two dims are too small (e.g. the
          (dm, 3, heads, head_dim) attention leaves, where head_dim <
          min_dim_size_to_factor), so view the leaf as the 2-D matrix
          (prod(shape[:k]), prod(shape[k:])) picking the contiguous
          split k that qualifies with minimal vr+vc memory.

        State-layout note: leaves that the pre-split rule left unfactored
        (full ``v``) may now factor, changing their state shapes — a
        checkpoint from the old layout fails restore's shape check loudly
        (utils/checkpoint.py raises on any leaf mismatch); re-initialize
        the optimizer state for such checkpoints.
        """
        if (len(shape) >= 2
                and min(shape[-2:]) >= self.min_dim_size_to_factor):
            return ("batch", None)
        if len(shape) > 2:
            best = None
            for k in range(1, len(shape)):
                r = int(np.prod(shape[:k]))
                c = int(np.prod(shape[k:]))
                if min(r, c) >= self.min_dim_size_to_factor:
                    if best is None or r + c < best[0]:
                        best = (r + c, k)
            if best is not None:
                return ("split", best[1])
        return None

    def _factored(self, shape) -> bool:
        return self._plan(shape) is not None

    def _view_shape(self, shape) -> tuple:
        """The shape factoring math runs over: the leaf itself under the
        "batch" plan, the 2-D split view under "split"."""
        plan = self._plan(shape)
        if plan is None or plan[0] == "batch":
            return tuple(shape)
        k = plan[1]
        return (int(np.prod(shape[:k])), int(np.prod(shape[k:])))

    def init(self, params) -> dict:
        one = lambda: jnp.zeros((1,), jnp.float32)  # noqa: E731

        def vr(p):
            if not self._factored(p.shape):
                return one()
            return jnp.zeros(self._view_shape(p.shape)[:-1], jnp.float32)

        def vc(p):
            if not self._factored(p.shape):
                return one()
            view = self._view_shape(p.shape)
            return jnp.zeros(view[:-2] + view[-1:], jnp.float32)

        def v(p):
            return (one() if self._factored(p.shape)
                    else jnp.zeros_like(p, jnp.float32))

        def mu(p):
            return jnp.zeros_like(p) if self.b1 is not None else one()

        return {"vr": jax.tree.map(vr, params),
                "vc": jax.tree.map(vc, params),
                "v": jax.tree.map(v, params),
                "mu": jax.tree.map(mu, params),
                "count": jnp.zeros((), jnp.int32)}

    def state_specs(self, param_specs):
        """Factored moments have REDUCED shapes; only replicated
        parameters are supported (see class docstring)."""
        def check(spec):
            if tuple(x for x in spec if x is not None):
                raise NotImplementedError(
                    "bare Adafactor's factored state does not compose "
                    f"with sharded parameter leaves (got spec {spec}); "
                    "wrap it in tpu_ddp.parallel.zero.CellAdafactor "
                    "(per-cell factoring — the LM trainers do this "
                    "automatically) or use AdamW")
            return spec
        jax.tree.map(check, param_specs,
                     is_leaf=lambda x: isinstance(x, PartitionSpec))
        repl = jax.tree.map(lambda _: PartitionSpec(), param_specs,
                            is_leaf=lambda x: isinstance(x, PartitionSpec))
        return {"vr": repl, "vc": repl, "v": repl, "mu": repl,
                "count": PartitionSpec()}

    def decay_mask(self, params):
        return jax.tree.map(lambda p: p.ndim >= 2, params)

    def map_param_like(self, state, fn):
        raise NotImplementedError(
            "Adafactor's factored state is shape-coupled to its original "
            "leaves and cannot be re-laid-out by the flat ZeRO/FSDP "
            "wrappers; use tpu_ddp.parallel.zero.FactoredZeRO1 "
            "(LMTrainer(opt_sharding='zero1')) which shards the factored "
            "state natively, or AdamW under FSDP")

    def _schedule_terms(self, count):
        """(beta2t, rho, lr) for 1-based step ``count`` — the shared
        per-step scalars of :meth:`apply` and the per-cell wrapper
        (tpu_ddp/parallel/zero.py:CellAdafactor)."""
        c = count.astype(jnp.float32)
        beta2t = 1.0 - c ** (-self.decay_rate)
        if self.learning_rate is None:
            return beta2t, jnp.minimum(1e-2, 1.0 / jnp.sqrt(c)), None
        lr = (self.learning_rate(c) if callable(self.learning_rate)
              else self.learning_rate)
        return beta2t, None, lr

    def _leaf_update(self, p, g, vr, vc, v, mu, dk, beta2t, rho, lr):
        """One leaf's Adafactor update — factoring planned from
        ``p.shape``, update-RMS clip and relative step size over THIS
        leaf only. Inside a shard_map ``p`` is the local cell, so
        calling this per cell IS the T5X per-cell factoring semantic
        (each shard maintains row/col moments of its own slice)."""
        g32 = g.astype(jnp.float32)
        g2 = jnp.square(g32) + self.eps1
        if self._factored(p.shape):
            # Factoring runs over the plan's 2-D-per-matrix view
            # (identical to the leaf itself under the "batch" plan).
            view = self._view_shape(p.shape)
            g2v = g2.reshape(view)
            new_vr = beta2t * vr + (1 - beta2t) * jnp.mean(g2v, axis=-1)
            new_vc = beta2t * vc + (1 - beta2t) * jnp.mean(g2v, axis=-2)
            new_v = v
            # V[i,j] ≈ vr[i]·vc[j] / mean_i(vr) — exact for rank-1
            # g² (with mean-form accumulators the normalizer is the
            # row-moment MEAN, not its sum); rsqrt applied factored
            # so the (n, m) moment matrix is never materialized.
            r = new_vr / jnp.mean(new_vr, axis=-1, keepdims=True)
            u = (g32.reshape(view) * jax.lax.rsqrt(r[..., :, None])
                 * jax.lax.rsqrt(new_vc[..., None, :])).reshape(p.shape)
        else:
            new_vr, new_vc = vr, vc
            new_v = beta2t * v + (1 - beta2t) * g2
            u = g32 * jax.lax.rsqrt(new_v)
        rms_u = jnp.sqrt(jnp.mean(jnp.square(u)))
        u = u / jnp.maximum(1.0, rms_u / self.clip_threshold)
        if lr is None:
            rms_p = jnp.sqrt(jnp.mean(jnp.square(
                p.astype(jnp.float32))))
            alpha = rho * jnp.maximum(self.eps2, rms_p)
        else:
            alpha = lr
        if self.b1 is not None:
            new_mu = self.b1 * mu + (1 - self.b1) * u.astype(p.dtype)
            step = new_mu
        else:
            new_mu = mu
            step = u
        new_p = p - (alpha * step
                     + (alpha * self.weight_decay * p if dk else 0.0)
                     ).astype(p.dtype)
        return new_p, new_vr, new_vc, new_v, new_mu

    def apply(self, params, grads, state, decay_mask=None):
        count = state["count"] + 1
        beta2t, rho, lr = self._schedule_terms(count)
        if decay_mask is None:
            decay_mask = self.decay_mask(params)

        def upd(p, g, vr, vc, v, mu, dk):
            return self._leaf_update(p, g, vr, vc, v, mu, dk,
                                     beta2t, rho, lr)

        p_l, treedef = jax.tree.flatten(params)
        outs = [upd(*args) for args in zip(
            p_l, jax.tree.leaves(grads), jax.tree.leaves(state["vr"]),
            jax.tree.leaves(state["vc"]), jax.tree.leaves(state["v"]),
            jax.tree.leaves(state["mu"]), jax.tree.leaves(decay_mask))]
        unf = lambda i: treedef.unflatten([o[i] for o in outs])  # noqa: E731
        return unf(0), {"vr": unf(1), "vc": unf(2), "v": unf(3),
                        "mu": unf(4), "count": count}


def clip_scale_from_sq(sq, clip_norm: float):
    """Gradient scale for global-norm clipping, from the squared sum:
    ``min(1, clip / (||g|| + 1e-12))``. ONE definition shared by every
    layout's clipping path (replicated/fsdp in train/engine.py, the
    LM trainers' _clip_by_global_norm, ZeRO's apply_scattered) so the
    epsilon and semantics cannot drift between layouts — drift would
    silently break the cross-layout norm equality tests/test_clip_norm.py
    pins."""
    return jnp.minimum(1.0, clip_norm / (jnp.sqrt(sq) + 1e-12))


def clip_tree(grads, scale):
    """Scale every leaf, preserving its dtype (a traced f32 scale must
    not promote bf16 gradients)."""
    return jax.tree.map(lambda g: (g * scale).astype(g.dtype), grads)


def warmup_cosine(peak_lr: float, warmup_steps: int, total_steps: int,
                  floor: float = 0.0):
    """Linear warmup to ``peak_lr`` then cosine decay to ``floor`` — the
    standard transformer LM schedule. Returns a jit-safe callable
    ``step (1-based f32) -> lr`` for :class:`AdamW`'s ``learning_rate``.
    """
    if not 0 < warmup_steps < total_steps:
        raise ValueError(f"need 0 < warmup_steps={warmup_steps} < "
                         f"total_steps={total_steps}")

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = peak_lr * step / warmup_steps
        frac = jnp.clip((step - warmup_steps)
                        / (total_steps - warmup_steps), 0.0, 1.0)
        cos = floor + (peak_lr - floor) * 0.5 * (1 + jnp.cos(jnp.pi * frac))
        return jnp.where(step < warmup_steps, warm, cos)

    return schedule
