"""Flash attention — Pallas TPU kernel, forward and backward.

The LM family's hot op (tpu_ddp/models/transformer.py attention). The
jnp path (tpu_ddp/parallel/ring_attention.py:full_attention) materializes
the (L, L) score matrix in HBM; this kernel streams K/V blocks through
VMEM with the online-softmax recurrence (Dao et al., "FlashAttention",
arXiv:2205.14135 — reimplemented from the paper's algorithm, not from any
code), so HBM traffic is O(L·D) and peak memory per core is one
(block_q, block_k) tile. The backward pass recomputes probabilities from
the saved logsumexp in two sweeps (dk/dv with k-blocks resident, then dq
with q-blocks resident) — the standard flash backward.

TPU mapping:
- grid = (batch·heads, q-blocks, kv-blocks) with the kv axis innermost:
  TPU grid steps are sequential, so the online-softmax state (running
  max / sum / accumulator) lives in VMEM scratch that persists across
  the kv sweep, and outputs are written on the sweep's last step;
- blocks are 128x128 (MXU-shaped); sequence length is zero-padded to a
  multiple of 128 and head dim to 64 or a multiple of 128 (``_pad_d``),
  with validity masks from absolute positions so padding never
  contributes;
- all matmuls run on the MXU via ``preferred_element_type=float32``;
  the softmax state is float32 regardless of input dtype.

Runs compiled on TPU and in interpreter mode elsewhere (CI's virtual CPU
mesh). Exactness vs the jnp reference — values and gradients, causal and
not, padded and aligned shapes — is tested in tests/test_flash_attention.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_BLOCK = 128
_NEG_INF = -1e30


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def _pad_d(d: int) -> int:
    """Padded head dim. Head dims <= 64 stay at 64 — Mosaic handles a
    64-lane minor dim natively (same rule as jax's reference TPU flash
    kernel, which only requires a multiple of 128 when head_dim > 128),
    and every matmul touching d halves its FLOPs vs padding to 128.
    Round-2 verdict: the old blanket pad-to-128 doubled both attention
    matmuls for the presets' head_dim 64."""
    if d <= 64:
        return 64
    return _cdiv(d, _BLOCK) * _BLOCK


def _pick_block(lp: int, want: int) -> int:
    """Largest power-of-two block <= ``want`` dividing the padded length.
    Bigger tiles amortize the per-grid-step scratch read-modify-write and
    feed the MXU larger matmuls; lp is always a multiple of 128."""
    b = want
    while b > _BLOCK and lp % b:
        b //= 2
    return min(b, lp)


def _block_env(name: str, default: int) -> int:
    """Block-size tuning hook (TPU_DDP_FLASH_{BQ,BK,BWD_BQ,BWD_BK}):
    read at trace time, so a bench sweep can try tile shapes without a
    code edit. Trace-time means once a given shape has been traced in a
    process, jax's jit cache (keyed on avals, not env) silently reuses
    the previously-traced tiles — an in-process sweep would record
    identical timings for "different" tiles. Each tile configuration
    therefore needs a fresh process (the round-4 sweep ran one
    subprocess per tile config for exactly this reason).
    Defaults are the shipped, measured-best values (v5e
    sweep, round 4): fwd 512/1024 and bwd 512/512 beat the previous
    256/512 + 256/256 by 14% on the TransformerLM-large step (0.512 ->
    0.586 MFU at batch 4 seq 2048), +28% on the small LM, +46% at seq
    8192 — bigger tiles amortize the per-grid-step scratch
    read-modify-write and feed the MXU larger matmuls.

    Must be a power of two >= the 128 lane width: _pick_block halves the
    want until it divides the padded length, which only terminates on a
    divisor for powers of two (lp is always a multiple of 128) — a
    non-power-of-two value would leave tail rows silently unprocessed
    (the kernel grids floor-divide), so it is refused loudly here."""
    import os
    v = int(os.environ.get(name, default))
    if v < _BLOCK or (v & (v - 1)):
        raise ValueError(f"{name}={v}: must be a power of two "
                         f">= {_BLOCK}")
    return v


def _positions(i, j, bq, bk):
    q_pos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return q_pos, k_pos


def _masked_scores(q, k, i, j, *, scale, seq_len, causal):
    """(bq, bk) f32 scores with padding + causal masking applied.

    Inputs stay in their storage dtype (bf16 rides the MXU's fast path);
    accumulation is f32 via preferred_element_type."""
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
    q_pos, k_pos = _positions(i, j, q.shape[0], k.shape[0])
    ok = (q_pos < seq_len) & (k_pos < seq_len)
    if causal:
        ok &= k_pos <= q_pos
    return jnp.where(ok, s, _NEG_INF)


def _block_visible(i_q, j_k, bq, bk):
    """False iff the (q-block, k-block) pair is entirely above the causal
    diagonal (no q_pos >= k_pos) — its compute can be skipped outright."""
    return j_k * bk <= (i_q + 1) * bq - 1


# ---- forward ------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_sc, l_sc, acc_sc,
                *, scale, seq_len, causal):
    i, j = pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _():
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    bq, bk = q_ref.shape[1], k_ref.shape[1]

    def update():
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        s = _masked_scores(q, k, i, j, scale=scale, seq_len=seq_len,
                           causal=causal)
        m_prev = m_sc[:, :1]                               # (bq, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                             # (bq, bk)
        l_new = alpha * l_sc[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc_sc[:] = acc_sc[:] * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_sc[:] = jnp.broadcast_to(m_new, m_sc.shape)
        l_sc[:] = jnp.broadcast_to(l_new, l_sc.shape)

    if causal:
        # Skip blocks entirely above the diagonal — ~2x less compute.
        pl.when(_block_visible(i, j, bq, bk))(update)
    else:
        update()

    @pl.when(j == pl.num_programs(2) - 1)
    def _():
        l_safe = jnp.maximum(l_sc[:, :1], 1e-30)
        o_ref[0] = (acc_sc[:] / l_safe).astype(o_ref.dtype)
        # lse block is the FULL (1, 1, Lp) row (TPU block tiling forbids
        # a (1, bq) sub-row block); each q-block writes its slice.
        bq = q_ref.shape[1]
        lse_ref[0, :, pl.ds(i * bq, bq)] = \
            (m_sc[:, :1] + jnp.log(l_safe)).T


def _kv_index(b, *, n_heads, n_kv):
    """Grid dim-0 runs over B*H q-heads; the K/V array holds B*KV heads.
    Group-contiguous mapping (head h shares KV head h // (H/KV) — the
    ``jnp.repeat`` order): kv_row = (b // H) * KV + (b % H) // (H/KV).
    Identity when H == KV (MHA)."""
    if n_heads == n_kv:
        return b
    group = n_heads // n_kv
    return (b // n_heads) * n_kv + (b % n_heads) // group


@functools.partial(jax.jit,
                   static_argnames=("scale", "seq_len", "causal",
                                    "n_heads", "n_kv", "interpret"))
def _fwd_impl(q3, k3, v3, *, scale, seq_len, causal, n_heads, n_kv,
              interpret):
    bh, lp, dp = q3.shape
    bq = _pick_block(lp, _block_env("TPU_DDP_FLASH_BQ", 512))
    bk = _pick_block(lp, _block_env("TPU_DDP_FLASH_BK", 1024))
    kv_idx = functools.partial(_kv_index, n_heads=n_heads, n_kv=n_kv)
    qkv_spec = lambda which, blk: pl.BlockSpec(  # noqa: E731
        (1, blk, dp),
        {"q": lambda b, i, j: (b, i, 0),
         "kv": lambda b, i, j: (kv_idx(b), j, 0)}[which],
        memory_space=pltpu.VMEM)
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, seq_len=seq_len,
                          causal=causal),
        grid=(bh, lp // bq, lp // bk),
        in_specs=[qkv_spec("q", bq), qkv_spec("kv", bk),
                  qkv_spec("kv", bk)],
        out_specs=(qkv_spec("q", bq),
                   pl.BlockSpec((1, 1, lp), lambda b, i, j: (b, 0, 0),
                                memory_space=pltpu.VMEM)),
        out_shape=(jax.ShapeDtypeStruct(q3.shape, q3.dtype),
                   jax.ShapeDtypeStruct((bh, 1, lp), jnp.float32)),
        scratch_shapes=[pltpu.VMEM((bq, 128), jnp.float32),
                        pltpu.VMEM((bq, 128), jnp.float32),
                        pltpu.VMEM((bq, dp), jnp.float32)],
        interpret=interpret,
    )(q3, k3, v3)
    return o, lse


# ---- backward -----------------------------------------------------------

def _recompute_p_ds(q, k, v, do, lse_row, delta_row, i, j, *, scale,
                    seq_len, causal):
    """Shared backward algebra: p = exp(s - lse), ds = p*(dp - delta).

    ``lse_row``/``delta_row`` are (1, bq) blocks; transposed to column
    vectors here (2-D throughout for TPU layouts)."""
    s = _masked_scores(q, k, i, j, scale=scale, seq_len=seq_len,
                       causal=causal)
    p = jnp.exp(s - lse_row.T)                             # (bq, bk)
    dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
    ds = (p * (dp - delta_row.T) * scale).astype(q.dtype)
    return p.astype(q.dtype), ds


def _bwd_kv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dk_ref, dv_ref, dk_sc, dv_sc, *, scale, seq_len,
                   causal, n_q_blocks):
    """dk/dv sweep. Grid dim 0 runs over B*KV (the K/V rows); the inner
    dim enumerates (group member g, q-block iq) pairs as c = g *
    n_q_blocks + iq, so under grouped-query attention every q-head
    sharing this KV head accumulates into the SAME scratch before one
    flush (TPU grid steps are sequential). MHA is group == 1, where c is
    simply iq."""
    jk, c = pl.program_id(1), pl.program_id(2)
    iq = c % n_q_blocks

    @pl.when(c == 0)
    def _():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    bq, bk = q_ref.shape[1], k_ref.shape[1]

    def update():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        p, ds = _recompute_p_ds(q, k, v, do,
                                lse_ref[0, :, pl.ds(iq * bq, bq)],
                                delta_ref[0, :, pl.ds(iq * bq, bq)],
                                iq, jk, scale=scale, seq_len=seq_len,
                                causal=causal)
        dv_sc[:] += jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        dk_sc[:] += jnp.dot(ds.T, q, preferred_element_type=jnp.float32)

    if causal:
        pl.when(_block_visible(iq, jk, bq, bk))(update)
    else:
        update()

    @pl.when(c == pl.num_programs(2) - 1)
    def _():
        dk_ref[0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[:].astype(dv_ref.dtype)


def _bwd_q_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                  dq_ref, dq_sc, *, scale, seq_len, causal):
    iq, jk = pl.program_id(1), pl.program_id(2)  # q-block outer, k inner

    @pl.when(jk == 0)
    def _():
        dq_sc[:] = jnp.zeros_like(dq_sc)

    bq, bk = q_ref.shape[1], k_ref.shape[1]

    def update():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        _, ds = _recompute_p_ds(q, k, v, do,
                                lse_ref[0, :, pl.ds(iq * bq, bq)],
                                delta_ref[0, :, pl.ds(iq * bq, bq)],
                                iq, jk, scale=scale, seq_len=seq_len,
                                causal=causal)
        dq_sc[:] += jnp.dot(ds, k, preferred_element_type=jnp.float32)

    if causal:
        pl.when(_block_visible(iq, jk, bq, bk))(update)
    else:
        update()

    @pl.when(jk == pl.num_programs(2) - 1)
    def _():
        dq_ref[0] = dq_sc[:].astype(dq_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("scale", "seq_len", "causal",
                                    "n_heads", "n_kv", "interpret"))
def _bwd_impl(q3, k3, v3, o3, lse, do3, *, scale, seq_len, causal,
              n_heads, n_kv, interpret):
    bh, lp, dp = q3.shape
    bq = _pick_block(lp, _block_env("TPU_DDP_FLASH_BWD_BQ", 512))
    bk = _pick_block(lp, _block_env("TPU_DDP_FLASH_BWD_BK", 512))
    group = n_heads // n_kv
    nq = lp // bq
    kv_idx = functools.partial(_kv_index, n_heads=n_heads, n_kv=n_kv)
    # delta_i = rowsum(dO_i * O_i): one fused elementwise pass, f32.
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1)[:, None, :]                   # (bh, 1, lp)

    # ---- dk/dv sweep: grid dim 0 over the B*KV K/V rows; the inner dim
    # enumerates (group member, q-block) as c = g*nq + iq, so grouped
    # q-heads accumulate into one scratch (see _bwd_kv_kernel). For MHA
    # q_row(b, c) == b and the maps reduce to the plain layout.
    def q_row(b, c):
        if group == 1:
            return b
        return (b // n_kv) * n_heads + (b % n_kv) * group + c // nq

    def qspec_kv(blk):
        return pl.BlockSpec((1, blk, dp),
                            lambda b, a, c: (q_row(b, c), c % nq, 0),
                            memory_space=pltpu.VMEM)

    kvspec_kv = pl.BlockSpec((1, bk, dp), lambda b, a, c: (b, a, 0),
                             memory_space=pltpu.VMEM)
    # lse/delta ride as full (1, 1, Lp) rows; kernels slice their q-block
    # (TPU block tiling forbids a (1, bq) sub-row block).
    row_kv = pl.BlockSpec((1, 1, lp), lambda b, a, c: (q_row(b, c), 0, 0),
                          memory_space=pltpu.VMEM)

    kw = dict(scale=scale, seq_len=seq_len, causal=causal)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_kv_kernel, n_q_blocks=nq, **kw),
        grid=(k3.shape[0], lp // bk, group * nq),
        in_specs=[qspec_kv(bq), kvspec_kv, kvspec_kv, qspec_kv(bq),
                  row_kv, row_kv],
        out_specs=(kvspec_kv, kvspec_kv),
        # Cotangent dtypes must match the primals' (k and v may differ).
        out_shape=(jax.ShapeDtypeStruct(k3.shape, k3.dtype),
                   jax.ShapeDtypeStruct(v3.shape, v3.dtype)),
        scratch_shapes=[pltpu.VMEM((bk, dp), jnp.float32)] * 2,
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)

    # ---- dq sweep: per q-head grid; K/V blocks via the grouped map.
    def block3(which, blk):
        return pl.BlockSpec(
            (1, blk, dp),
            {"outer": lambda b, a, c: (b, a, 0),
             "inner": lambda b, a, c: (kv_idx(b), c, 0)}[which],
            memory_space=pltpu.VMEM)

    row_spec = pl.BlockSpec((1, 1, lp), lambda b, a, c: (b, 0, 0),
                            memory_space=pltpu.VMEM)
    dq = pl.pallas_call(
        functools.partial(_bwd_q_kernel, **kw),
        grid=(bh, lp // bq, lp // bk),  # q-blocks outer, k-blocks inner
        in_specs=[block3("outer", bq), block3("inner", bk),
                  block3("inner", bk), block3("outer", bq),
                  row_spec, row_spec],
        out_specs=block3("outer", bq),
        out_shape=jax.ShapeDtypeStruct(q3.shape, q3.dtype),
        scratch_shapes=[pltpu.VMEM((bq, dp), jnp.float32)],
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)
    return dq, dk, dv


# ---- public op ----------------------------------------------------------

def _interpret() -> bool:
    from tpu_ddp.ops.pallas import interpret_mode
    return interpret_mode()


def _to3(x, lp, dp):
    """(B, L, H, D) -> (B*H, Lp, Dp), zero-padded."""
    b, L, h, d = x.shape
    x = jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, L, d)
    return jnp.pad(x, ((0, 0), (0, lp - L), (0, dp - d)))


def _from3(x3, b, L, h, d):
    return jnp.transpose(
        x3[:, :L, :d].reshape(b, h, L, d), (0, 2, 1, 3))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention(q, k, v, causal: bool = False):
    """Exact multi-head attention, flash-style. (B, L, H, D) in and out.

    Drop-in replacement for
    tpu_ddp/parallel/ring_attention.py:full_attention — same math, O(L·D)
    HBM traffic instead of an O(L²) score matrix. Differentiable via the
    flash backward recomputation.

    Grouped-query attention: ``k``/``v`` may carry KV < H heads (H % KV
    == 0, group-contiguous ``jnp.repeat`` order). The kernels index K/V
    blocks by q-head group directly — the expansion is never
    materialized, and the backward accumulates each KV head's dk/dv
    across its group inside one scratch sweep.
    """
    o, _ = _flash_fwd_padded(q, k, v, causal)
    return o


def _check_heads(h: int, kvh: int) -> None:
    if h % kvh:
        raise ValueError(f"flash_attention: {h} query heads not "
                         f"divisible by {kvh} KV heads")


def _flash_fwd_padded(q, k, v, causal):
    b, L, h, d = q.shape
    kvh = k.shape[2]
    _check_heads(h, kvh)
    lp = _cdiv(L, _BLOCK) * _BLOCK
    dp = _pad_d(d)
    scale = 1.0 / (d ** 0.5)
    o3, lse = _fwd_impl(_to3(q, lp, dp), _to3(k, lp, dp), _to3(v, lp, dp),
                        scale=scale, seq_len=L, causal=causal,
                        n_heads=h, n_kv=kvh, interpret=_interpret())
    return _from3(o3, b, L, h, d), (o3, lse)


def _flash_fwd(q, k, v, causal):
    o, (o3, lse) = _flash_fwd_padded(q, k, v, causal)
    return o, (q, k, v, o3, lse)


def _flash_bwd(causal, residuals, g):
    q, k, v, o3, lse = residuals
    b, L, h, d = q.shape
    kvh = k.shape[2]
    lp = _cdiv(L, _BLOCK) * _BLOCK
    dp = _pad_d(d)
    scale = 1.0 / (d ** 0.5)
    dq3, dk3, dv3 = _bwd_impl(
        _to3(q, lp, dp), _to3(k, lp, dp), _to3(v, lp, dp), o3, lse,
        _to3(g, lp, dp), scale=scale, seq_len=L, causal=causal,
        n_heads=h, n_kv=kvh, interpret=_interpret())
    return (_from3(dq3, b, L, h, d), _from3(dk3, b, L, kvh, d),
            _from3(dv3, b, L, kvh, d))


flash_attention.defvjp(_flash_fwd, _flash_bwd)
