"""Pallas TPU kernels for the framework's hot ops.

The reference's compute kernels all live in ATen C++ (SURVEY.md §2 row N3);
the TPU-native replacement is mostly XLA-emitted HLO, but the ops where a
hand-written kernel pays — single-pass fused elementwise chains that XLA
would otherwise split across HBM round-trips — are implemented here with
Pallas:

- :mod:`sgd`      — fused SGD momentum+weight-decay parameter update
                    (one read + one write per buffer instead of the
                    multi-op elementwise chain).
- :mod:`bn_relu`  — fused BatchNorm(batch-stats)+ReLU forward/backward
                    with a custom VJP.
- :mod:`flash_attention` — flash attention forward/backward: O(L·D) HBM
                    traffic instead of the O(L²) score matrix.
- :mod:`quant_matmul` — weight-only int8 matmul with the dequant scale
                    fused into the epilogue (quantized decode compute,
                    ops/quant.py).

Every kernel runs compiled on TPU and falls back to interpreter mode on
CPU (tests force the host platform, conftest.py), selected automatically.
"""

from __future__ import annotations

import jax


def interpret_mode() -> bool:
    """True when Pallas must run interpreted (no TPU backend)."""
    return jax.default_backend() != "tpu"


from tpu_ddp.ops.pallas.sgd import fused_sgd_step  # noqa: E402
from tpu_ddp.ops.pallas.bn_relu import batch_norm_relu  # noqa: E402
from tpu_ddp.ops.pallas.flash_attention import flash_attention  # noqa: E402
from tpu_ddp.ops.pallas.quant_matmul import int8_matmul  # noqa: E402

__all__ = ["interpret_mode", "fused_sgd_step", "batch_norm_relu",
           "flash_attention", "int8_matmul"]
