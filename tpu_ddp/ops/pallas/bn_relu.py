"""Fused BatchNorm(batch-statistics) + ReLU — Pallas forward and backward.

The reference's conv block is conv -> BatchNorm2d(track_running_stats=False)
-> ReLU (reference part1/model.py:18-25); with batch-only statistics the
BN+ReLU pair is a pure function of the current activation, which makes it
an ideal fusion target: one reduction pass (per-channel sum / sum-of-
squares) and one normalize+ReLU pass, each streaming the activation
through VMEM exactly once. The backward pass is the classic BN gradient

    dx = (scale * inv / R) * (R*gy - sum(gy) - x_hat * sum(gy * x_hat))

with the ReLU mask folded into ``gy``, again as one reduction pass + one
elementwise pass, wired up through ``jax.custom_vjp`` (Pallas kernels are
not auto-differentiable).

Layout: the NHWC activation is viewed as (R, C) with R = N*H*W rows.
Lane alignment without copies: when C divides 128 (e.g. VGG's first
64-channel layer), k = 128/C consecutive rows are FOLDED side-by-side into
a (R/k, 128) view — a free row-major reshape, no padding materialization;
per-channel vectors are tiled k times for the kernels and the k row-group
partial sums are combined afterwards. Only when C neither divides nor is a
multiple of 128 does the code fall back to zero-padding the channel axis.
Rows are chunked over a 1-D grid (grid steps are sequential on TPU, so
per-channel accumulators live in a (1, 128·m) output block shared by all
steps).

Measured verdict (TPU v5e, VGG-11 train step): XLA's own conv+BN+ReLU
fusion BEATS this kernel — 25.3 ms vs 66.0 ms per step at batch 2048
(8.1 vs 11.1 ms at 256) — because XLA fuses the normalize+ReLU into the
surrounding convolution epilogues while a custom kernel forces the
activation through VMEM as a separate pass. The kernel stays as an
opt-in (``TPU_DDP_PALLAS_BN=1``) reference implementation and a Pallas
pattern exemplar; the default path is the right one.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_BLOCK_ROWS = 1024

BN_EPS = 1e-5  # torch BatchNorm2d default; callers pass the model's eps


# ---- layout: fold / pad to the 128-lane boundary ------------------------

def _layout(r, c):
    """Return (k, c_pad): fold factor and channel zero-pad width."""
    if c % _LANES == 0:
        return 1, 0
    if _LANES % c == 0 and r % (_LANES // c) == 0:
        return _LANES // c, 0
    return 1, -(-c // _LANES) * _LANES - c


def _fold_rows(x2d, k, c_pad):
    if k > 1:
        r, c = x2d.shape
        return x2d.reshape(r // k, c * k)  # free row-major view
    if c_pad:
        return jnp.pad(x2d, ((0, 0), (0, c_pad)))
    return x2d


def _fold_chan(v_1c, k, c_pad):
    """(1, C) channel vector -> (1, lane-width) for the kernels."""
    if k > 1:
        return jnp.tile(v_1c, (1, k))
    if c_pad:
        return jnp.pad(v_1c, ((0, 0), (0, c_pad)))
    return v_1c


def _combine_chan(s_folded, k, c):
    """(1, lane-width) kernel accumulator -> (1, C) per-channel totals."""
    if k > 1:
        return jnp.sum(s_folded.reshape(k, c), axis=0, keepdims=True)
    return s_folded[:, :c]


def _row_blocking(r):
    """Block rows (multiple of 8 sublanes) and the zero-pad to fill the
    last grid step. For the model's power-of-two activation shapes the pad
    is zero and ``jnp.pad`` is a no-op."""
    br = min(_BLOCK_ROWS, -(-r // 8) * 8)
    r_pad = -(-r // br) * br - r
    return br, r_pad


def _pad_rows(x, r_pad):
    return jnp.pad(x, ((0, r_pad), (0, 0))) if r_pad else x


def _row_spec(block_rows, lanes):
    return pl.BlockSpec((block_rows, lanes), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)


def _chan_spec(lanes):
    return pl.BlockSpec((1, lanes), lambda i: (0, 0),
                        memory_space=pltpu.VMEM)


# ---- forward ------------------------------------------------------------

def _stats_kernel(x_ref, sum_ref, sumsq_ref):
    @pl.when(pl.program_id(0) == 0)
    def _():
        sum_ref[:] = jnp.zeros_like(sum_ref)
        sumsq_ref[:] = jnp.zeros_like(sumsq_ref)

    xb = x_ref[:]
    sum_ref[:] += jnp.sum(xb, axis=0, keepdims=True)
    sumsq_ref[:] += jnp.sum(xb * xb, axis=0, keepdims=True)


def _norm_relu_kernel(x_ref, mean_ref, inv_ref, scale_ref, bias_ref, y_ref):
    y = (x_ref[:] - mean_ref[:]) * (inv_ref[:] * scale_ref[:]) + bias_ref[:]
    y_ref[:] = jnp.maximum(y, 0.0)


@functools.partial(jax.jit, static_argnames=("eps", "interpret"))
def _bn_relu_fwd_impl(x2d, scale, bias, *, eps, interpret):
    r, c = x2d.shape
    k, c_pad = _layout(r, c)
    xf = _fold_rows(x2d, k, c_pad)
    rf, lanes = xf.shape
    br, r_pad = _row_blocking(rf)
    xf = _pad_rows(xf, r_pad)
    grid = ((rf + r_pad) // br,)
    chan = jax.ShapeDtypeStruct((1, lanes), jnp.float32)

    s, ss = pl.pallas_call(
        _stats_kernel,
        grid=grid,
        in_specs=[_row_spec(br, lanes)],
        out_specs=(_chan_spec(lanes), _chan_spec(lanes)),
        out_shape=(chan, chan),
        interpret=interpret,
    )(xf)
    mean = _combine_chan(s, k, c) / r                      # (1, C)
    var = jnp.maximum(_combine_chan(ss, k, c) / r - mean * mean, 0.0)
    inv = jax.lax.rsqrt(var + eps)                         # (1, C)

    y = pl.pallas_call(
        _norm_relu_kernel,
        grid=grid,
        in_specs=[_row_spec(br, lanes)] + [_chan_spec(lanes)] * 4,
        out_specs=_row_spec(br, lanes),
        out_shape=jax.ShapeDtypeStruct(xf.shape, jnp.float32),
        interpret=interpret,
    )(xf, _fold_chan(mean, k, c_pad), _fold_chan(inv, k, c_pad),
      _fold_chan(scale.reshape(1, c), k, c_pad),
      _fold_chan(bias.reshape(1, c), k, c_pad))
    if r_pad:
        y = y[:rf]
    y = y.reshape(r, c) if k > 1 else y[:, :c]
    return y, mean, inv


# ---- backward -----------------------------------------------------------

def _bwd_stats_kernel(x_ref, g_ref, mean_ref, inv_ref, scale_ref, bias_ref,
                      dbias_ref, dscale_ref):
    @pl.when(pl.program_id(0) == 0)
    def _():
        dbias_ref[:] = jnp.zeros_like(dbias_ref)
        dscale_ref[:] = jnp.zeros_like(dscale_ref)

    x_hat = (x_ref[:] - mean_ref[:]) * inv_ref[:]
    y = x_hat * scale_ref[:] + bias_ref[:]
    gy = jnp.where(y > 0, g_ref[:], 0.0)
    dbias_ref[:] += jnp.sum(gy, axis=0, keepdims=True)
    dscale_ref[:] += jnp.sum(gy * x_hat, axis=0, keepdims=True)


def _bwd_dx_kernel(x_ref, g_ref, mean_ref, inv_ref, scale_ref, bias_ref,
                   dbias_ref, dscale_ref, dx_ref, *, count):
    x_hat = (x_ref[:] - mean_ref[:]) * inv_ref[:]
    y = x_hat * scale_ref[:] + bias_ref[:]
    gy = jnp.where(y > 0, g_ref[:], 0.0)
    dx_ref[:] = (scale_ref[:] * inv_ref[:] * (1.0 / count)) * (
        count * gy - dbias_ref[:] - x_hat * dscale_ref[:])


@functools.partial(jax.jit, static_argnames=("interpret",))
def _bn_relu_bwd_impl(x2d, g2d, mean, inv, scale, bias, *, interpret):
    r, c = x2d.shape
    k, c_pad = _layout(r, c)
    br, r_pad = _row_blocking(r // k)
    xf = _pad_rows(_fold_rows(x2d, k, c_pad), r_pad)
    gf = _pad_rows(_fold_rows(g2d, k, c_pad), r_pad)
    rf, lanes = xf.shape
    grid = (rf // br,)
    chan = jax.ShapeDtypeStruct((1, lanes), jnp.float32)
    mean_f = _fold_chan(mean, k, c_pad)
    inv_f = _fold_chan(inv, k, c_pad)
    scale_f = _fold_chan(scale.reshape(1, c), k, c_pad)
    bias_f = _fold_chan(bias.reshape(1, c), k, c_pad)

    db_f, ds_f = pl.pallas_call(
        _bwd_stats_kernel,
        grid=grid,
        in_specs=[_row_spec(br, lanes)] * 2 + [_chan_spec(lanes)] * 4,
        out_specs=(_chan_spec(lanes), _chan_spec(lanes)),
        out_shape=(chan, chan),
        interpret=interpret,
    )(xf, gf, mean_f, inv_f, scale_f, bias_f)
    dbias = _combine_chan(db_f, k, c)                      # (1, C)
    dscale = _combine_chan(ds_f, k, c)

    dx = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, count=float(r)),
        grid=grid,
        in_specs=[_row_spec(br, lanes)] * 2 + [_chan_spec(lanes)] * 6,
        out_specs=_row_spec(br, lanes),
        out_shape=jax.ShapeDtypeStruct(xf.shape, jnp.float32),
        interpret=interpret,
    )(xf, gf, mean_f, inv_f, scale_f, bias_f,
      _fold_chan(dbias, k, c_pad), _fold_chan(dscale, k, c_pad))
    if r_pad:
        dx = dx[:rf - r_pad]
    dx = dx.reshape(r, c) if k > 1 else dx[:, :c]
    return dx, dscale[0], dbias[0]


# ---- public op with custom VJP -----------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def batch_norm_relu(x, scale, bias, eps=BN_EPS):
    """``relu(batch_norm(x))`` over (..., C) using current-batch statistics.

    Drop-in fused replacement for ``batch_norm`` + ``maximum(·, 0)`` in
    tpu_ddp/models/vgg.py (the ``track_running_stats=False`` semantic of
    reference part1/model.py:24). Differentiable w.r.t. ``x``, ``scale``
    and ``bias``. Computes in float32 regardless of input dtype.
    """
    y, _, _ = _fwd(x, scale, bias, eps)
    return y


def _interpret():
    from tpu_ddp.ops.pallas import interpret_mode
    return interpret_mode()


def _fwd(x, scale, bias, eps):
    shape = x.shape
    x2d = x.astype(jnp.float32).reshape(-1, shape[-1])
    y2d, mean, inv = _bn_relu_fwd_impl(
        x2d, scale.astype(jnp.float32), bias.astype(jnp.float32),
        eps=float(eps), interpret=_interpret())
    return y2d.reshape(shape).astype(x.dtype), mean, inv


def _bn_relu_fwd(x, scale, bias, eps):
    y, mean, inv = _fwd(x, scale, bias, eps)
    return y, (x, mean, inv, scale, bias)


def _bn_relu_bwd(eps, residuals, g):
    x, mean, inv, scale, bias = residuals
    shape = x.shape
    x2d = x.astype(jnp.float32).reshape(-1, shape[-1])
    g2d = g.astype(jnp.float32).reshape(-1, shape[-1])
    dx2d, dscale, dbias = _bn_relu_bwd_impl(
        x2d, g2d, mean, inv, scale.astype(jnp.float32),
        bias.astype(jnp.float32), interpret=_interpret())
    return (dx2d.reshape(shape).astype(x.dtype),
            dscale.astype(scale.dtype), dbias.astype(bias.dtype))


batch_norm_relu.defvjp(_bn_relu_fwd, _bn_relu_bwd)
