"""Fused SGD(momentum, weight-decay) update as a single Pallas kernel.

Replaces the elementwise chain of the torch-semantics update (reference
part1/main.py:124-125; tpu_ddp/ops/optim.py)::

    g   <- grad + wd * p
    buf <- mom * buf + g
    p   <- p - lr * buf

For each parameter leaf the whole chain runs in ONE VMEM-resident pass:
params, grads and momentum stream HBM->VMEM once, the new params and new
momentum stream back once — the minimum possible HBM traffic (the update is
purely memory-bound). Inputs are aliased to outputs so the update is
in-place in HBM (donated buffers, no allocation churn).

Leaves are flattened, zero-padded to a (rows, 128) lane layout and chunked
over a 1-D grid; padding lanes compute ``0 - lr*(mom*0 + 0 + wd*0) = 0`` so
they are exact no-ops and are sliced away on reshape.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Lane width is fixed at 128 on TPU; 512 sublanes x 128 lanes x 4 B = 256 KB
# per buffer block, x5 live buffers ~= 1.3 MB of VMEM — comfortably small.
_LANES = 128
_BLOCK_ROWS = 512


def _sgd_kernel(p_ref, g_ref, b_ref, new_p_ref, new_b_ref, *,
                lr: float, momentum: float, weight_decay: float):
    g = g_ref[:]
    if weight_decay:
        g = g + weight_decay * p_ref[:]
    buf = momentum * b_ref[:] + g
    new_b_ref[:] = buf
    new_p_ref[:] = p_ref[:] - lr * buf


@functools.partial(jax.jit, static_argnames=("lr", "momentum", "weight_decay",
                                             "interpret"))
def _sgd_leaf(p2d, g2d, b2d, *, lr, momentum, weight_decay, interpret):
    rows = p2d.shape[0]
    block_rows = min(_BLOCK_ROWS, rows)
    grid = (pl.cdiv(rows, block_rows),)
    spec = pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    kernel = functools.partial(_sgd_kernel, lr=lr, momentum=momentum,
                               weight_decay=weight_decay)
    out_shape = jax.ShapeDtypeStruct(p2d.shape, p2d.dtype)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[spec, spec, spec],
        out_specs=(spec, spec),
        out_shape=(out_shape, out_shape),
        input_output_aliases={0: 0, 2: 1},
        interpret=interpret,
    )(p2d, g2d, b2d)


def _to_2d(x):
    """Flatten to (rows, 128) with zero padding; returns (x2d, orig_size)."""
    flat = x.reshape(-1)
    n = flat.shape[0]
    rows = -(-n // _LANES)
    pad = rows * _LANES - n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat.reshape(rows, _LANES), n


def fused_sgd_step(params, grads, momentum_buf, *, lr: float,
                   momentum: float, weight_decay: float,
                   interpret: bool | None = None):
    """Apply the fused update to every leaf of a parameter pytree.

    Returns ``(new_params, new_momentum_buf)`` with identical pytree
    structure. Numerics match :class:`tpu_ddp.ops.optim.SGD` exactly
    (tested leaf-wise in tests/test_pallas.py).
    """
    if interpret is None:
        from tpu_ddp.ops.pallas import interpret_mode
        interpret = interpret_mode()

    def leaf(p, g, b):
        shape = p.shape
        p2d, n = _to_2d(p)
        g2d, _ = _to_2d(g.astype(p.dtype))
        b2d, _ = _to_2d(b)
        np2d, nb2d = _sgd_leaf(p2d, g2d, b2d, lr=lr, momentum=momentum,
                               weight_decay=weight_decay,
                               interpret=interpret)
        return (np2d.reshape(-1)[:n].reshape(shape),
                nb2d.reshape(-1)[:n].reshape(shape))

    flat_p, treedef = jax.tree.flatten(params)
    flat_g = treedef.flatten_up_to(grads)
    flat_b = treedef.flatten_up_to(momentum_buf)
    out = [leaf(p, g, b) for p, g, b in zip(flat_p, flat_g, flat_b)]
    new_p = treedef.unflatten([o[0] for o in out])
    new_b = treedef.unflatten([o[1] for o in out])
    return new_p, new_b
