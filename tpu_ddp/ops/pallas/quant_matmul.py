"""Weight-only int8 matmul: ``x @ (q * s)`` without materializing fp
weights — the quantized-decode compute kernel (ops/quant.py).

Decode matmuls are weight-bandwidth-bound: activations are a few rows
(the live batch, or batch x (k+1) under speculation) while the weight
panel is the whole projection. The win is therefore byte traffic on
``q``: int8 tiles stream HBM->VMEM at 4x fewer bytes than f32, convert
to the MXU input dtype on the VMEM side of the wall, and the
per-output-column scale ``s`` fuses into the accumulator epilogue —
one kernel, zero fp-weight HBM traffic:

    acc(f32) = dot(x_tile, int8->f32(q_tile))   # MXU, f32 accumulate
    out      = acc * s_tile                     # epilogue, per column

Grid is (M tiles, N tiles) with the full K panel resident per program:
decode-shaped problems have small M and K = d_model, so a (bm, K)
activation block plus a (K, bn) weight block sit comfortably in VMEM
(K=8192 at bn=256 is 2 MB of int8). Inputs are zero-padded to lane
multiples by the wrapper and sliced back — zero K-padding contributes
exact zeros to the accumulator, zero N-padding is sliced away.

Runs compiled on TPU, interpreted elsewhere (tests force the host
platform); the XLA reference path in :func:`tpu_ddp.ops.quant.qdot`
computes the same contraction for CPU serving.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128      # TPU lane width: last-dim tile multiple
_BLOCK_M = 128    # activation rows per program
_BLOCK_N = 256    # output columns per program


def _qmm_kernel(x_ref, q_ref, s_ref, o_ref):
    x = x_ref[...].astype(jnp.float32)
    w = q_ref[...].astype(jnp.float32)
    acc = jnp.dot(x, w, preferred_element_type=jnp.float32)
    o_ref[...] = acc * s_ref[...]


@functools.partial(jax.jit, static_argnames=("interpret",))
def _qmm(x2d, q, s2d, *, interpret):
    m, k = x2d.shape
    n = q.shape[1]
    bm = min(_BLOCK_M, m)
    bn = min(_BLOCK_N, n)
    grid = (pl.cdiv(m, bm), pl.cdiv(n, bn))
    return pl.pallas_call(
        _qmm_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, k), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, bn), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bn), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        interpret=interpret,
    )(x2d, q, s2d)


def _pad_to(x, axis, mult):
    size = x.shape[axis]
    pad = (-size) % mult
    if not pad:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def int8_matmul(x, q, s, *, interpret: bool | None = None):
    """``x @ (q.astype(f32) * s)`` in f32, weights read as int8.

    ``x``: (..., K) activations; ``q``: (K, N) int8; ``s``: (N,) f32
    per-output-column scales. Returns (..., N) f32. Leading axes are
    flattened into rows for the kernel and restored after — the
    decode call sites pass (B, L, K).
    """
    if interpret is None:
        from tpu_ddp.ops.pallas import interpret_mode
        interpret = interpret_mode()
    k, n = q.shape
    lead = x.shape[:-1]
    x2d = x.reshape(-1, k)
    m = x2d.shape[0]
    # Lane-align every dim: zero K-padding adds exact zeros to the
    # accumulator, M/N padding is sliced away below. int8 sublane tile
    # is 32, so K pads to the f32 lane width (covers both operands).
    x2d = _pad_to(_pad_to(x2d, 1, _LANES), 0, 8)
    qp = _pad_to(_pad_to(q, 0, _LANES), 1, _LANES)
    sp = _pad_to(s.reshape(1, n), 1, _LANES)
    out = _qmm(x2d, qp, sp, interpret=bool(interpret))
    return out[:m, :n].reshape(*lead, n)
