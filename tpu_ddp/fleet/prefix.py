"""Hash-keyed prefix index: shared-prompt KV reuse over the paged pool.

The serving fleet's workloads are dominated by requests that share a
long system prompt. Without reuse, N such requests pay N identical
prefills and hold N identical copies of the prompt's KV blocks. The
index makes them pay ONE: after a request's prefill completes, its
full prompt blocks are registered under chain keys; a later request
whose prompt starts with the same token blocks adopts the cached
blocks into its own table (refcount +1 per block — see
``serve/kv_pool.py``) and starts prefilling at the first uncached
token. The saved work is exactly ``cached_len`` prompt tokens per hit.

Design points, in the order they bite:

- **Keys are exact, not hashes of hashes.** An entry's key is the
  recursive chain ``(parent_key, block_token_tuple)``. Two prompts
  share an entry iff they are token-identical up to and including that
  block — a hash collision can therefore never serve the wrong KV,
  which the bitwise-parity acceptance criterion (fleet output ==
  single-engine output) requires unconditionally.
- **Only FULL blocks are cacheable.** A partial tail block's KV would
  be extended in place by the next request, corrupting it for every
  other holder. Full blocks are immutable once registered.
- **Copy-on-write at the divergence point.** ``cached_len`` is capped
  at ``prompt_len - 1`` so the final prompt token always re-runs (the
  first output token is sampled from its logits). When a prompt's hit
  covers that final token's block (block-aligned full match), the
  request would write into a SHARED block — ``PrefixHit.cow`` marks
  it, and admission replaces the last hit block with a private
  ``pool.cow`` copy before any write happens.
- **The index is a holder.** Registered blocks carry an index
  refcount, so they survive their creator's retirement. Eviction is
  LRU over *leaf* entries nobody else holds (refcount 1, no child
  entry) — evicting a mid-chain entry would orphan its descendants.
  The index registers itself as the pool's ``reclaimer``: when the
  free list runs dry, cold cache entries are dropped on demand, so a
  full cache never blocks admission (``pool.allocatable`` counts
  evictable entries).

``plan`` is pure (the router probes it for prefix-affinity routing);
``share`` is the effectful twin the scheduler calls once per
admission, and is where hit statistics accrue.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict


@dataclasses.dataclass
class PrefixHit:
    """A pure lookup result: the longest indexed chain for a prompt."""

    blocks: list            # cached block ids, chain order
    keys: list              # their index keys (for LRU touch)
    cached_len: int         # prompt tokens the hit actually covers
    cow: bool               # last hit block needs a private copy

    def __bool__(self) -> bool:
        return bool(self.blocks)


@dataclasses.dataclass
class _Entry:
    block: int
    parent: object          # parent chain key, None at the root
    children: int = 0       # entries chaining from this one


class PrefixIndex:
    """Refcount-holding prefix cache over one :class:`PagedKVPool`."""

    def __init__(self, pool):
        self.pool = pool
        pool.reclaimer = self
        # key -> _Entry; OrderedDict doubles as the LRU order
        # (oldest-touched first).
        self._entries: OrderedDict = OrderedDict()
        self.lookups = 0            # admissions through the index
        self.hit_requests = 0       # admissions with >= 1 cached block
        self.cached_blocks_served = 0
        self.tokens_saved = 0       # prefill tokens skipped, total
        self.inserted = 0
        self.evicted = 0

    # No __len__: an empty index must stay truthy (``if index`` guards
    # would silently skip a cold cache); use ``stats()["entries"]``.

    # ---- lookup --------------------------------------------------------

    def _chain(self, prompt):
        """Yield ``(key, block_tokens)`` for each FULL block of the
        prompt, chaining keys exactly."""
        bs = self.pool.block_size
        key = None
        for i in range(len(prompt) // bs):
            tok = tuple(int(t) for t in prompt[i * bs:(i + 1) * bs])
            key = (key, tok)
            yield key

    def plan(self, prompt) -> PrefixHit:
        """Longest indexed chain for ``prompt``. Pure — no refcounts,
        no stats, no LRU touch — so the router can probe it per
        candidate replica without distorting anything."""
        blocks, keys = [], []
        for key in self._chain(prompt):
            e = self._entries.get(key)
            if e is None:
                break
            blocks.append(e.block)
            keys.append(key)
        if not blocks:
            return PrefixHit([], [], 0, False)
        bs = self.pool.block_size
        # The final prompt token must re-run (its logits seed the first
        # output token), so a full-prompt hit is capped one short —
        # and that capped token's block, being shared, needs CoW.
        cached_len = min(len(blocks) * bs, len(prompt) - 1)
        cow = len(blocks) * bs > cached_len
        return PrefixHit(list(blocks), keys, cached_len, cow)

    def cached_len(self, prompt) -> int:
        """Convenience for prefix-affinity routing."""
        return self.plan(prompt).cached_len

    # ---- admission-side effects ---------------------------------------

    def share(self, hit: PrefixHit) -> None:
        """Adopt a planned hit: one incref per cached block, LRU touch.
        Called exactly once per admission (with an empty hit on a
        miss), so ``lookups`` counts admissions through the index."""
        self.lookups += 1
        if not hit:
            return
        self.hit_requests += 1
        self.cached_blocks_served += len(hit.blocks)
        self.tokens_saved += hit.cached_len
        self.pool.incref(hit.blocks)
        for key in hit.keys:
            self._entries.move_to_end(key)

    def register(self, prompt, blocks) -> None:
        """Index a finished prefill's FULL prompt blocks. Blocks whose
        chain key is already present are skipped (the existing entry's
        block holds identical content by construction); new entries
        take an index refcount so they outlive the request."""
        key = None
        for i, k in enumerate(self._chain(prompt)):
            e = self._entries.get(k)
            if e is None:
                self.pool.incref([blocks[i]])
                self._entries[k] = _Entry(block=blocks[i], parent=key)
                if key is not None:
                    self._entries[key].children += 1
                self.inserted += 1
            self._entries.move_to_end(k)
            key = k

    # ---- pool reclaimer interface --------------------------------------

    @property
    def evictable_count(self) -> int:
        """Leaf entries nobody but the index holds — what ``reclaim``
        can free IMMEDIATELY. Cascading (a parent becoming a leaf
        after its child is evicted) can free more; counting only the
        first wave keeps the scheduler's reservation math conservative
        and therefore sound."""
        return sum(1 for e in self._entries.values()
                   if e.children == 0 and self.pool.refcount(e.block) == 1)

    def reclaim(self, n: int) -> int:
        """Evict up to ``n`` blocks' worth of cold entries, LRU-first,
        leaf-only, cascading into parents as they become leaves."""
        freed = 0
        progress = True
        while freed < n and progress:
            progress = False
            for key in list(self._entries.keys()):
                if freed >= n:
                    break
                e = self._entries[key]
                if e.children == 0 and self.pool.refcount(e.block) == 1:
                    self._evict(key)
                    freed += 1
                    progress = True
        return freed

    def _evict(self, key) -> None:
        e = self._entries.pop(key)
        if e.parent is not None:
            self._entries[e.parent].children -= 1
        self.pool.free([e.block])
        self.evicted += 1

    # ---- accounting ----------------------------------------------------

    def held_blocks(self) -> list:
        """The index's holder list, for ``pool.refcount_ok``."""
        return [e.block for e in self._entries.values()]

    @property
    def hit_rate(self) -> float:
        return self.hit_requests / self.lookups if self.lookups else 0.0

    def stats(self) -> dict:
        return {
            "entries": len(self._entries),
            "lookups": self.lookups,
            "hit_requests": self.hit_requests,
            "hit_rate": self.hit_rate,
            "cached_blocks_served": self.cached_blocks_served,
            "tokens_saved": self.tokens_saved,
            "inserted": self.inserted,
            "evicted": self.evicted,
        }
