"""Hash-keyed prefix index: shared-prompt KV reuse over the paged pool.

The serving fleet's workloads are dominated by requests that share a
long system prompt. Without reuse, N such requests pay N identical
prefills and hold N identical copies of the prompt's KV blocks. The
index makes them pay ONE: after a request's prefill completes, its
full prompt blocks are registered under chain keys; a later request
whose prompt starts with the same token blocks adopts the cached
blocks into its own table (refcount +1 per block — see
``serve/kv_pool.py``) and starts prefilling at the first uncached
token. The saved work is exactly ``cached_len`` prompt tokens per hit.

Design points, in the order they bite:

- **Keys are exact, not hashes of hashes.** An entry's key is the
  recursive chain ``(parent_key, block_token_tuple)``. Two prompts
  share an entry iff they are token-identical up to and including that
  block — a hash collision can therefore never serve the wrong KV,
  which the bitwise-parity acceptance criterion (fleet output ==
  single-engine output) requires unconditionally.
- **Only FULL blocks are cacheable.** A partial tail block's KV would
  be extended in place by the next request, corrupting it for every
  other holder. Full blocks are immutable once registered.
- **Copy-on-write at the divergence point.** ``cached_len`` is capped
  at ``prompt_len - 1`` so the final prompt token always re-runs (the
  first output token is sampled from its logits). When a prompt's hit
  covers that final token's block (block-aligned full match), the
  request would write into a SHARED block — ``PrefixHit.cow`` marks
  it, and admission replaces the last hit block with a private
  ``pool.cow`` copy before any write happens.
- **The index is a holder.** Registered blocks carry an index
  refcount, so they survive their creator's retirement. Eviction is
  LRU over *leaf* entries nobody else holds (refcount 1, no child
  entry) — evicting a mid-chain entry would orphan its descendants.
  The index registers itself as the pool's ``reclaimer``: when the
  free list runs dry, cold cache entries are dropped on demand, so a
  full cache never blocks admission (``pool.allocatable`` counts
  evictable entries).

``plan`` is pure (the router probes it for prefix-affinity routing);
``share`` is the effectful twin the scheduler calls once per
admission, and is where hit statistics accrue.

**Tenant namespaces (§25).** Chain keys are rooted at
``("ns", tenant)`` instead of ``None``, so two tenants submitting
token-identical prompts occupy DISJOINT key chains: tenant A's cache
can never serve tenant B — not as a policy check at lookup time, but
by construction of the key space (the isolation proof in
tests/test_fleet_autoscale.py shows 0 cross-tenant hits with
bitwise-identical output either way). The default namespace keeps
every pre-§25 call site byte-identical.

:class:`PrefixDirectory` is the fleet-level companion: a router-side
map from ``(tenant, first-block chain keys)`` to the replica indices
that have served them, so prefix-affinity routing probes only the
replicas that can possibly hit instead of every replica in the fleet.
Entries are optimistic (recorded at routing time, before prefill
registers) — the router re-verifies with the replica's own pure
``plan`` probe, so a stale or early entry costs one probe, never a
wrong route.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict

DEFAULT_NS = "default"


@dataclasses.dataclass
class PrefixHit:
    """A pure lookup result: the longest indexed chain for a prompt."""

    blocks: list            # cached block ids, chain order
    keys: list              # their index keys (for LRU touch)
    cached_len: int         # prompt tokens the hit actually covers
    cow: bool               # last hit block needs a private copy

    def __bool__(self) -> bool:
        return bool(self.blocks)


@dataclasses.dataclass
class _Entry:
    block: int
    parent: object          # parent chain key, None at the root
    children: int = 0       # entries chaining from this one


class PrefixIndex:
    """Refcount-holding prefix cache over one :class:`PagedKVPool`."""

    def __init__(self, pool):
        self.pool = pool
        pool.reclaimer = self
        # key -> _Entry; OrderedDict doubles as the LRU order
        # (oldest-touched first).
        self._entries: OrderedDict = OrderedDict()
        self.lookups = 0            # admissions through the index
        self.hit_requests = 0       # admissions with >= 1 cached block
        self.cached_blocks_served = 0
        self.tokens_saved = 0       # prefill tokens skipped, total
        self.inserted = 0
        self.evicted = 0

    # No __len__: an empty index must stay truthy (``if index`` guards
    # would silently skip a cold cache); use ``stats()["entries"]``.

    # ---- lookup --------------------------------------------------------

    def _chain(self, prompt, ns: str = DEFAULT_NS):
        """Yield the chain key for each FULL block of the prompt.
        Chains are rooted at the tenant namespace, so cross-tenant
        prompts can never share an entry no matter their tokens."""
        bs = self.pool.block_size
        key = ("ns", str(ns))
        for i in range(len(prompt) // bs):
            tok = tuple(int(t) for t in prompt[i * bs:(i + 1) * bs])
            key = (key, tok)
            yield key

    def plan(self, prompt, ns: str = DEFAULT_NS) -> PrefixHit:
        """Longest indexed chain for ``prompt`` within tenant
        namespace ``ns``. Pure — no refcounts, no stats, no LRU touch
        — so the router can probe it per candidate replica without
        distorting anything."""
        blocks, keys = [], []
        for key in self._chain(prompt, ns):
            e = self._entries.get(key)
            if e is None:
                break
            blocks.append(e.block)
            keys.append(key)
        if not blocks:
            return PrefixHit([], [], 0, False)
        bs = self.pool.block_size
        # The final prompt token must re-run (its logits seed the first
        # output token), so a full-prompt hit is capped one short —
        # and that capped token's block, being shared, needs CoW.
        cached_len = min(len(blocks) * bs, len(prompt) - 1)
        cow = len(blocks) * bs > cached_len
        return PrefixHit(list(blocks), keys, cached_len, cow)

    def cached_len(self, prompt, ns: str = DEFAULT_NS) -> int:
        """Convenience for prefix-affinity routing."""
        return self.plan(prompt, ns).cached_len

    # ---- admission-side effects ---------------------------------------

    def share(self, hit: PrefixHit) -> None:
        """Adopt a planned hit: one incref per cached block, LRU touch.
        Called exactly once per admission (with an empty hit on a
        miss), so ``lookups`` counts admissions through the index."""
        self.lookups += 1
        if not hit:
            return
        self.hit_requests += 1
        self.cached_blocks_served += len(hit.blocks)
        self.tokens_saved += hit.cached_len
        self.pool.incref(hit.blocks)
        for key in hit.keys:
            self._entries.move_to_end(key)

    def register(self, prompt, blocks, ns: str = DEFAULT_NS) -> None:
        """Index a finished prefill's FULL prompt blocks under tenant
        namespace ``ns``. Blocks whose chain key is already present
        are skipped (the existing entry's block holds identical
        content by construction); new entries take an index refcount
        so they outlive the request."""
        key = None
        for i, k in enumerate(self._chain(prompt, ns)):
            e = self._entries.get(k)
            if e is None:
                self.pool.incref([blocks[i]])
                self._entries[k] = _Entry(block=blocks[i], parent=key)
                if key is not None:
                    self._entries[key].children += 1
                self.inserted += 1
            self._entries.move_to_end(k)
            key = k

    # ---- pool reclaimer interface --------------------------------------

    @property
    def evictable_count(self) -> int:
        """Leaf entries nobody but the index holds — what ``reclaim``
        can free IMMEDIATELY. Cascading (a parent becoming a leaf
        after its child is evicted) can free more; counting only the
        first wave keeps the scheduler's reservation math conservative
        and therefore sound."""
        return sum(1 for e in self._entries.values()
                   if e.children == 0 and self.pool.refcount(e.block) == 1)

    def reclaim(self, n: int) -> int:
        """Evict up to ``n`` blocks' worth of cold entries, LRU-first,
        leaf-only, cascading into parents as they become leaves."""
        freed = 0
        progress = True
        while freed < n and progress:
            progress = False
            for key in list(self._entries.keys()):
                if freed >= n:
                    break
                e = self._entries[key]
                if e.children == 0 and self.pool.refcount(e.block) == 1:
                    self._evict(key)
                    freed += 1
                    progress = True
        return freed

    def _evict(self, key) -> None:
        e = self._entries.pop(key)
        if e.parent is not None:
            self._entries[e.parent].children -= 1
        self.pool.free([e.block])
        self.evicted += 1

    # ---- accounting ----------------------------------------------------

    def held_blocks(self) -> list:
        """The index's holder list, for ``pool.refcount_ok``."""
        return [e.block for e in self._entries.values()]

    @property
    def hit_rate(self) -> float:
        return self.hit_requests / self.lookups if self.lookups else 0.0

    def stats(self) -> dict:
        return {
            "entries": len(self._entries),
            "lookups": self.lookups,
            "hit_requests": self.hit_requests,
            "hit_rate": self.hit_rate,
            "cached_blocks_served": self.cached_blocks_served,
            "tokens_saved": self.tokens_saved,
            "inserted": self.inserted,
            "evicted": self.evicted,
        }


class PrefixDirectory:
    """Cross-replica prefix directory for affinity routing (§25).

    The router records ``(tenant, first full block of the prompt) ->
    replica index`` whenever it routes a request, and consults the
    directory BEFORE probing replicas: only the replicas recorded for
    that key can possibly have the prefix cached, so the per-request
    probe cost stays O(recorded replicas) instead of O(fleet). Entries
    are advisory — the router still verifies each candidate with the
    replica's pure ``plan`` probe, and a request whose key has no
    entries simply falls back to least-loaded routing (nothing could
    have hit anyway, since the directory has seen every routed
    submit). Replica removal (scale-down / breaker retirement) calls
    ``forget`` + ``reindex`` so stale indices never reach ``pick``."""

    def __init__(self, block_size: int):
        if block_size < 1:
            raise ValueError(
                f"block_size must be >= 1, got {block_size}")
        self.block_size = block_size
        # (tenant, first-block token tuple) -> set of replica indices
        self._where: dict = {}
        self.records = 0
        self.narrowed = 0   # picks the directory narrowed
        self.misses = 0     # picks with no recorded candidate

    def _key(self, tenant: str, prompt):
        if len(prompt) < self.block_size:
            return None  # no full block -> nothing cacheable to find
        return (str(tenant),
                tuple(int(t) for t in prompt[:self.block_size]))

    def record(self, tenant: str, prompt, replica: int) -> None:
        key = self._key(tenant, prompt)
        if key is None:
            return
        self._where.setdefault(key, set()).add(replica)
        self.records += 1

    def candidates(self, tenant: str, prompt) -> list[int]:
        """Replica indices that may hold this prompt's prefix (sorted
        for determinism). Empty = provably cold everywhere."""
        key = self._key(tenant, prompt)
        hits = self._where.get(key) if key is not None else None
        if hits:
            self.narrowed += 1
            return sorted(hits)
        self.misses += 1
        return []

    def forget(self, replica: int) -> None:
        """Drop every record pointing at ``replica`` (its pool — and
        therefore its cache — is gone)."""
        for key in list(self._where):
            s = self._where[key]
            s.discard(replica)
            if not s:
                del self._where[key]

    def reindex(self, removed: int) -> None:
        """Shift indices above a removed replica down by one, matching
        the router's compaction of its replica list."""
        self._where = {
            key: {i - 1 if i > removed else i for i in s}
            for key, s in self._where.items()}

    def stats(self) -> dict:
        return {"keys": len(self._where), "records": self.records,
                "narrowed": self.narrowed, "misses": self.misses}
