"""Multi-replica router: one front-end over N serving engines.

A fleet is N independent replicas (each a ``ServeEngine`` or
``DisaggEngine`` — the router is duck-typed over ``submit`` /
``cancel`` / ``step`` plus the router hooks ``outstanding()``,
``prefix_cached_len()``, and ``drain()``), and the router is the ONLY
stateful thing above them: it picks a replica per request, remembers
the assignment for ``cancel``, and fans ``step()`` across the fleet so
``run_load`` drives a whole fleet exactly like one engine.

Two policies (``TPU_DDP_ROUTER_POLICY``, tune/space.py "goodput"):

- ``least-loaded`` — send the request to the replica owing the fewest
  outstanding tokens (queued prompt+generation plus live remainders).
  The queueing-theory baseline: balances makespan, ignores state.
- ``prefix-affinity`` — ask every replica how many prompt tokens its
  prefix cache already holds (``prefix_cached_len``, a PURE probe) and
  send the request to the replica with the longest cached prefix,
  breaking ties by least-loaded. Shared-prompt traffic then piles onto
  the replica that already paid the prefill, instead of spraying N
  copies of the same system prompt across N caches — the hit-rate gap
  between the two policies on a shared-prefix workload is pinned by
  tests/test_fleet.py.

Affinity needs a tie-break CAP: a replica with the whole prompt cached
is still the wrong choice if it owes 10x the work of a cold one. The
router only honors affinity while the favored replica's backlog stays
within ``affinity_slack`` tokens of the least-loaded replica's;
past that it falls back to least-loaded (cache hits are cheap to
re-earn, head-of-line blocking is not).

Fleet resilience (docs/DESIGN.md §23, ``TPU_DDP_FLEET_HEALTH``): every
replica call is wrapped. A replica that raises out of ``step()`` (or
overruns ``TPU_DDP_FLEET_HEALTH_DEADLINE_MS``) is marked unhealthy,
its unfinished requests are harvested via ``drain()`` and replayed on
survivors from ``prompt + tokens_so_far`` — bitwise identical to the
undisturbed run, because sampling is stateless keyed on
``fold_in(seed, position)``. Re-admission is by exponential-backoff
probe (``TPU_DDP_FLEET_HEALTH_BACKOFF_MS``); a request that has
already been replayed ``TPU_DDP_FLEET_RETRY_BUDGET`` times is shed
rather than bounced forever. The accounting identity the chaos drills
pin: ``completed + cancelled + shed == submitted`` — no request is
ever lost, resurrected after cancel, or double-freed.
"""

from __future__ import annotations

import time
import warnings
from collections import deque

import numpy as np

from tpu_ddp.fleet.prefix import PrefixDirectory
from tpu_ddp.fleet.resilience import ReplicaHealth, continuation_of
from tpu_ddp.serve.engine import Request
from tpu_ddp.serve.scheduler import tenant_of

POLICIES = ("least-loaded", "prefix-affinity")


class Router:
    """Front-end over a list of replicas; same surface as one engine."""

    def __init__(self, replicas, policy: str | None = None,
                 affinity_slack: int = 256, health: bool | None = None,
                 retry_budget: int | None = None,
                 probe_backoff_ms: float | None = None,
                 step_deadline_ms: float | None = None,
                 clock=time.monotonic, config=None):
        if not replicas:
            raise ValueError("Router needs at least one replica")
        if config is None:
            from tpu_ddp.utils.config import TrainConfig
            config = TrainConfig()
        policy = policy if policy is not None else config.router_policy
        if policy not in POLICIES:
            raise ValueError(f"unknown router policy {policy!r}: "
                             f"expected one of {POLICIES}")
        self.replicas = list(replicas)
        self.policy = policy
        self.affinity_slack = int(affinity_slack)
        self.routed = [0] * len(self.replicas)
        self.affinity_hits = 0      # routed BY cached prefix (> 0 tokens)
        self._owner: dict[int, int] = {}   # id(request) -> replica index
        # ---- health + migration state ----
        self.health_enabled = bool(
            health if health is not None else config.fleet_health)
        self.retry_budget = int(
            retry_budget if retry_budget is not None
            else config.fleet_retry_budget)
        backoff_ms = float(
            probe_backoff_ms if probe_backoff_ms is not None
            else config.fleet_probe_backoff_ms)
        self._backoff_s = backoff_ms / 1e3  # add_replica needs it too
        self.step_deadline_ms = float(
            step_deadline_ms if step_deadline_ms is not None
            else config.fleet_step_deadline_ms)
        if self.retry_budget < 0:
            raise ValueError("retry_budget must be >= 0")
        if self.step_deadline_ms < 0:
            raise ValueError("step_deadline_ms must be >= 0")
        self._clock = clock
        self.health = [ReplicaHealth(backoff_s=backoff_ms / 1e3,
                                     clock=clock)
                       for _ in self.replicas]
        # Requests harvested off a failed replica, awaiting replay.
        self._pending: deque = deque()
        # id(original) -> [original, continuation, replica idx, synced]
        self._migrating: dict[int, list] = {}
        self._cont_to_orig: dict[int, Request] = {}
        self._rid = -1  # router-issued rids are negative (no clash)
        self.failovers = 0
        self.readmitted = 0
        self.migrated = 0   # replays that carried tokens already
        self.retried = 0    # replays that had not produced a token
        self.shed = 0       # retry budget exhausted
        # Cross-replica prefix directory (§25): under prefix-affinity
        # the router records which replica served each (tenant,
        # first-block) key, so ``pick`` probes only the replicas that
        # can possibly hit instead of the whole fleet. Advisory —
        # every hint is re-verified with the replica's pure probe.
        self.prefix_dir = None
        if self.policy == "prefix-affinity":
            bs = getattr(self.replicas[0], "block_size", None)
            if bs:
                self.prefix_dir = PrefixDirectory(int(bs))
        # Stamp each replica's chaos injector with its index so
        # ``:rank=R`` fault specs target one replica of the fleet.
        for i, r in enumerate(self.replicas):
            ch = getattr(r, "chaos", None)
            if ch is not None and hasattr(ch, "set_rank"):
                ch.set_rank(i)

    # ---- placement -----------------------------------------------------

    def _candidates(self) -> list[int]:
        idxs = [i for i in range(len(self.replicas))
                if self.health[i].healthy]
        return idxs or list(range(len(self.replicas)))

    def pick(self, prompt, tenant: str = "default") -> int:
        """The replica index ``submit`` would use for ``prompt`` —
        split out so tests can interrogate placement decisions.
        Unhealthy replicas are never picked while a healthy one
        exists. Affinity probes are tenant-namespaced and, when the
        prefix directory has hints for this (tenant, prompt) key,
        narrowed to the hinted replicas — with a full-fleet probe
        fallback whenever the hints all miss, so narrowing can only
        save probes, never change the decision."""
        cand = self._candidates()
        loads = {i: self.replicas[i].outstanding() for i in cand}
        least = min(cand, key=lambda i: (loads[i], i))
        if self.policy == "least-loaded":
            return least
        probe = cand
        if self.prefix_dir is not None:
            in_cand = set(cand)
            hinted = [i for i in self.prefix_dir.candidates(tenant,
                                                            prompt)
                      if i in in_cand]
            if hinted:
                probe = hinted
        cached = {i: self.replicas[i].prefix_cached_len(prompt, tenant)
                  for i in probe}
        if probe is not cand and max(cached.values()) == 0:
            for i in cand:  # stale hints: fall back to the full probe
                if i not in cached:
                    cached[i] = self.replicas[i].prefix_cached_len(
                        prompt, tenant)
        best = max(cached, key=lambda i: (cached[i], -loads[i], -i))
        if cached[best] > 0 and \
                loads[best] - loads[least] <= self.affinity_slack:
            return best
        return least

    def submit(self, prompt, max_new_tokens: int, **kw):
        tenant = str(kw.get("tenant", "default"))
        if self.health_enabled and \
                not any(h.healthy for h in self.health):
            # Whole fleet dark: hold the request at the router and
            # replay it the moment a probe re-admits a replica.
            req = Request(rid=self._rid,
                          prompt=np.asarray(prompt,
                                            np.int32).reshape(-1),
                          max_new_tokens=int(max_new_tokens),
                          temperature=float(kw.get("temperature", 0.0)),
                          seed=int(kw.get("seed", 0)),
                          eos_id=kw.get("eos_id"),
                          on_token=kw.get("on_token"),
                          tenant=tenant,
                          submitted_at=time.perf_counter())
            self._rid -= 1
            self._pending.append(req)
            return req
        i = self.pick(prompt, tenant)
        if self.policy == "prefix-affinity" and \
                self.replicas[i].prefix_cached_len(prompt, tenant) > 0:
            self.affinity_hits += 1
        req = self.replicas[i].submit(prompt, max_new_tokens, **kw)
        self.routed[i] += 1
        self._owner[id(req)] = i
        if self.prefix_dir is not None:
            self.prefix_dir.record(tenant, req.prompt, i)
        return req

    def cancel(self, req) -> bool:
        # A request parked in the retry/migration machinery owns no
        # replica state under its own identity — cancel must neither
        # resurrect it at the next resubmit nor double-free pages the
        # failover drain already released.
        if req.done:
            return False
        # Identity scan, NOT ``in``: Request is a dataclass whose
        # generated __eq__ would compare prompt arrays elementwise on
        # an rid collision (rids are per-replica counters).
        if any(p is req for p in self._pending):
            self._pending = deque(p for p in self._pending
                                  if p is not req)
            req.cancelled = True
            req.done = True
            req.finished_at = time.perf_counter()
            return True
        ent = self._migrating.pop(id(req), None)
        if ent is not None:
            orig, cont, i, _ = ent
            self._cont_to_orig.pop(id(cont), None)
            self.replicas[i].cancel(cont)
            orig.cancelled = True
            orig.done = True
            orig.finished_at = time.perf_counter()
            return True
        i = self._owner.get(id(req))
        if i is None:
            return False
        return self.replicas[i].cancel(req)

    # ---- replica lifecycle (the §25 autoscaler's surface) --------------

    def add_replica(self, replica) -> int:
        """Join a freshly booted replica to the fleet. It starts
        healthy with zero load, so the very next ``pick`` can route to
        it. Returns its index."""
        i = len(self.replicas)
        self.replicas.append(replica)
        self.routed.append(0)
        self.health.append(ReplicaHealth(backoff_s=self._backoff_s,
                                         clock=self._clock))
        ch = getattr(replica, "chaos", None)
        if ch is not None and hasattr(ch, "set_rank"):
            ch.set_rank(i)
        return i

    def drain_replica(self, i: int) -> int:
        """GRACEFUL drain for scale-down: harvest replica ``i``'s
        unfinished work and queue every request for replay elsewhere
        as a bitwise continuation. Unlike ``_fail_replica`` this is a
        planned retirement — no failure mark, no failover count, and
        NO retry-budget shed (zero dropped streams is the §25
        invariant; the budget guards crash loops, not lifecycle).
        Returns how many streams were queued for migration."""
        harvested = self.replicas[i].drain() \
            if hasattr(self.replicas[i], "drain") else []
        n = 0
        for req in harvested:
            orig = self._cont_to_orig.pop(id(req), None)
            if orig is not None:
                ent = self._migrating.pop(id(orig), None)
                if ent is not None:
                    self._sync_entry(ent)
                req = orig
            if req.done or req.cancelled:
                continue
            self._pending.append(req)
            n += 1
        if self.prefix_dir is not None:
            self.prefix_dir.forget(i)
        return n

    def remove_replica(self, i: int):
        """Retire replica ``i`` from the fleet (drain first — any
        residual work is harvested here the same graceful way) and
        compact every index-keyed structure. Returns the removed
        engine so the caller can detach its subscriber."""
        if len(self.replicas) <= 1:
            raise ValueError("cannot remove the last replica")
        self.drain_replica(i)  # idempotent: empty after a prior drain
        eng = self.replicas.pop(i)
        self.routed.pop(i)
        self.health.pop(i)
        # _owner entries for i point at requests that finished there
        # (unfinished ones were just harvested): drop them; shift the
        # rest. _migrating holds no continuation on i post-drain.
        self._owner = {k: (v - 1 if v > i else v)
                       for k, v in self._owner.items() if v != i}
        for ent in self._migrating.values():
            if ent[2] > i:
                ent[2] -= 1
        if self.prefix_dir is not None:
            self.prefix_dir.reindex(i)
        for j, r in enumerate(self.replicas):
            ch = getattr(r, "chaos", None)
            if ch is not None and hasattr(ch, "set_rank"):
                ch.set_rank(j)
        return eng

    def outstanding_by_tenant(self) -> dict[str, int]:
        """Fleet-wide backlog per tenant — the autoscaler's
        tenant-scoped load signal. Computed LIVE from replica queues/
        slots plus router-held pending work (never a cached counter),
        so ``cancel`` and shed-retire paths cannot leave a cancelled
        tenant's ghost load behind to trigger a spurious scale-up."""
        out: dict[str, int] = {}
        for r in self.replicas:
            by = getattr(r, "outstanding_by_tenant", None)
            if by is not None:
                for t, w in by().items():
                    out[t] = out.get(t, 0) + w
            else:
                w = r.outstanding()
                if w:
                    out["default"] = out.get("default", 0) + w
        for req in self._pending:
            t = tenant_of(req)
            out[t] = out.get(t, 0) \
                + len(req.prompt) + req.max_new_tokens - len(req.tokens)
        return out

    # ---- failure handling ----------------------------------------------

    def _fail_replica(self, i: int, exc: Exception) -> None:
        wait = self.health[i].mark_failure()
        self.failovers += 1
        warnings.warn(
            f"replica {i} failed ({type(exc).__name__}: {exc}); "
            f"marked unhealthy (probe in {wait:.2f}s), migrating its "
            "in-flight requests", stacklevel=3)
        harvested = self.replicas[i].drain() \
            if hasattr(self.replicas[i], "drain") else []
        for req in harvested:
            orig = self._cont_to_orig.pop(id(req), None)
            if orig is not None:
                # The dying replica was itself running a migrated
                # continuation: fold its progress into the original
                # and re-pend THAT (the caller only knows orig).
                ent = self._migrating.pop(id(orig), None)
                if ent is not None:
                    self._sync_entry(ent)
                req = orig
            if req.done or req.cancelled:
                continue
            if req.migrations >= self.retry_budget:
                req.shed = True
                req.done = True
                req.finished_at = time.perf_counter()
                self.shed += 1
                continue
            self._pending.append(req)

    def _resubmit_pending(self) -> bool:
        """Replay harvested requests on healthy replicas as
        continuations from ``prompt + tokens_so_far`` — bitwise
        identical to the undisturbed run (stateless sampling keyed on
        (seed, position))."""
        if not self._pending:
            return False
        healthy = [i for i in range(len(self.replicas))
                   if self.health[i].healthy]
        if not healthy:
            return False
        did = False
        while self._pending:
            orig = self._pending.popleft()
            if orig.done or orig.cancelled:
                continue  # cancelled while pending: never resurrect
            prompt, budget = continuation_of(orig)
            i = min(healthy,
                    key=lambda j: (self.replicas[j].outstanding(), j))
            try:
                cont = self.replicas[i].submit(
                    prompt, budget, temperature=orig.temperature,
                    seed=orig.seed, eos_id=orig.eos_id,
                    tenant=tenant_of(orig))
            except ValueError as e:
                # An invalid held request (fleet was dark at submit,
                # so validation never ran) surfaces here: shed it
                # loudly instead of killing the drive loop.
                warnings.warn(f"request {orig.rid}: replay rejected "
                              f"({e}); shedding", stacklevel=3)
                orig.shed = True
                orig.done = True
                orig.finished_at = time.perf_counter()
                self.shed += 1
                continue
            orig.migrations += 1
            if orig.tokens:
                self.migrated += 1
            else:
                self.retried += 1
            self.routed[i] += 1
            self._owner[id(orig)] = i
            self._migrating[id(orig)] = [orig, cont, i, 0]
            self._cont_to_orig[id(cont)] = orig
            if self.prefix_dir is not None:
                self.prefix_dir.record(tenant_of(orig), cont.prompt, i)
            did = True
        return did

    def _sync_entry(self, ent: list) -> None:
        """Copy a continuation's fresh tokens onto the original handle
        (streaming callbacks fire here — the caller never sees the
        continuation object)."""
        orig, cont, _, synced = ent
        if len(cont.tokens) > synced:
            now = time.perf_counter()
            vers = getattr(cont, "token_versions", None)
            for j in range(synced, len(cont.tokens)):
                orig.tokens.append(int(cont.tokens[j]))
                orig.logprobs.append(float(cont.logprobs[j]))
                if vers is not None and j < len(vers):
                    # Version stamps migrate with the tokens: the
                    # caller's atomic-cutover view survives failover.
                    orig.token_versions.append(int(vers[j]))
                if orig.first_token_at is None:
                    orig.first_token_at = now
                if orig.on_token is not None:
                    orig.on_token(int(cont.tokens[j]))
            ent[3] = len(cont.tokens)

    def _sync_migrations(self) -> None:
        for key in list(self._migrating):
            ent = self._migrating[key]
            orig, cont, _, _ = ent
            self._sync_entry(ent)
            if cont.done:
                del self._migrating[key]
                self._cont_to_orig.pop(id(cont), None)
                orig.shed = orig.shed or cont.shed
                orig.quarantined = orig.quarantined or cont.quarantined
                orig.done = True
                orig.finished_at = time.perf_counter()

    # ---- the iteration (run_load drives this like one engine) ----------

    def _step_replica(self, i: int) -> bool:
        """One guarded replica step: exceptions and deadline overruns
        become unhealthy state + migration instead of taking down the
        fleet."""
        r, h = self.replicas[i], self.health[i]
        if not h.healthy:
            if not h.probe_due():
                return False
            try:
                worked = bool(r.step())
            except Exception as e:  # noqa: BLE001 — probe failed
                h.mark_failure()
                return False
            h.mark_recovered()
            self.readmitted += 1
            return worked
        t0 = time.perf_counter()
        try:
            worked = bool(r.step())
        except Exception as e:  # noqa: BLE001 — crash becomes failover
            self._fail_replica(i, e)
            return False
        if self.step_deadline_ms and \
                (time.perf_counter() - t0) * 1e3 > self.step_deadline_ms:
            self._fail_replica(i, TimeoutError(
                f"step() overran the {self.step_deadline_ms:.0f}ms "
                "deadline"))
            return False
        return worked

    def step(self) -> bool:
        worked = False
        if not self.health_enabled:
            for r in self.replicas:
                worked |= bool(r.step())  # step EVERY replica
            return worked
        for i in range(len(self.replicas)):
            worked |= self._step_replica(i)
        worked |= self._resubmit_pending()
        self._sync_migrations()
        # Unfinished router-held work keeps the drive loop alive even
        # while every replica is backing off.
        return worked or bool(self._pending) or bool(self._migrating)

    def run(self, max_steps: int | None = None) -> int:
        n = 0
        while max_steps is None or n < max_steps:
            if not self.step():
                break
            n += 1
        return n

    # ---- weight streaming ----------------------------------------------

    def subscribe(self, publisher) -> list:
        """Fleet-wide version fan-out (tpu_ddp/publish/): give every
        replica its own subscriber on ``publisher``'s edge. One
        publish then reaches the whole fleet; replicas flip
        independently between their own steps (each stages one bucket
        per step), and ``stats()`` reports the per-replica versions —
        the publisher's staleness gate bounds how far they may trail
        the trainer."""
        from tpu_ddp.publish.subscriber import attach
        return attach(publisher, self, name="replica")

    # ---- introspection -------------------------------------------------

    def outstanding(self) -> int:
        w = sum(r.outstanding() for r in self.replicas)
        for req in self._pending:
            w += len(req.prompt) + req.max_new_tokens - len(req.tokens)
        return w

    def accounting_ok(self) -> bool:
        return all(r.accounting_ok() for r in self.replicas)

    def tenant_accounting_ok(self) -> bool:
        """Every replica's per-tenant ledger identity (§25) holds."""
        return all(r.tenant_accounting_ok() for r in self.replicas
                   if hasattr(r, "tenant_accounting_ok"))

    def stats(self) -> dict:
        per = []
        for i, r in enumerate(self.replicas):
            s = {"routed": self.routed[i],
                 "outstanding": r.outstanding(),
                 "health": self.health[i].state,
                 "failures": self.health[i].failures}
            prefix = getattr(r, "prefix", None)
            if prefix is not None:
                s["prefix"] = prefix.stats()
            if getattr(r, "subscriber", None) is not None:
                s["param_version"] = r.param_version
                s["publish_lag"] = r.subscriber.lag
            # Speculation ledger (§26): surfaced when the replica
            # speculates, so fleet dashboards can see proposal waste
            # (proposed - accepted) per replica.
            if getattr(r, "spec_k", 0) > 0 \
                    and hasattr(r, "spec_stats"):
                s["speculative"] = r.spec_stats()
            per.append(s)
        spec = [p["speculative"] for p in per if "speculative" in p]
        agg = None
        if spec:
            agg = {k: sum(s[k] for s in spec)
                   for k in ("proposed", "accepted", "rejected")}
            agg["acceptance"] = (agg["accepted"] / agg["proposed"]
                                 if agg["proposed"] else None)
        return {"policy": self.policy,
                "speculative": agg,
                "n_replicas": len(self.replicas),
                "routed": list(self.routed),
                "affinity_hits": self.affinity_hits,
                "tenant_backlog": self.outstanding_by_tenant(),
                "prefix_dir": (self.prefix_dir.stats()
                               if self.prefix_dir is not None else None),
                "health_enabled": self.health_enabled,
                "failovers": self.failovers,
                "readmitted": self.readmitted,
                "migrated": self.migrated,
                "retried": self.retried,
                "shed": self.shed,
                "pending": len(self._pending),
                "migrating": len(self._migrating),
                "replicas": per}


__all__ = ["Router", "POLICIES"]
