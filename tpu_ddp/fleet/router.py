"""Multi-replica router: one front-end over N serving engines.

A fleet is N independent replicas (each a ``ServeEngine`` or
``DisaggEngine`` — the router is duck-typed over ``submit`` /
``cancel`` / ``step`` plus the two router hooks ``outstanding()`` and
``prefix_cached_len()``), and the router is the ONLY stateful thing
above them: it picks a replica per request, remembers the assignment
for ``cancel``, and fans ``step()`` across the fleet so ``run_load``
drives a whole fleet exactly like one engine.

Two policies (``TPU_DDP_ROUTER_POLICY``, tune/space.py "goodput"):

- ``least-loaded`` — send the request to the replica owing the fewest
  outstanding tokens (queued prompt+generation plus live remainders).
  The queueing-theory baseline: balances makespan, ignores state.
- ``prefix-affinity`` — ask every replica how many prompt tokens its
  prefix cache already holds (``prefix_cached_len``, a PURE probe) and
  send the request to the replica with the longest cached prefix,
  breaking ties by least-loaded. Shared-prompt traffic then piles onto
  the replica that already paid the prefill, instead of spraying N
  copies of the same system prompt across N caches — the hit-rate gap
  between the two policies on a shared-prefix workload is pinned by
  tests/test_fleet.py.

Affinity needs a tie-break CAP: a replica with the whole prompt cached
is still the wrong choice if it owes 10x the work of a cold one. The
router only honors affinity while the favored replica's backlog stays
within ``affinity_slack`` tokens of the least-loaded replica's;
past that it falls back to least-loaded (cache hits are cheap to
re-earn, head-of-line blocking is not).
"""

from __future__ import annotations

POLICIES = ("least-loaded", "prefix-affinity")


class Router:
    """Front-end over a list of replicas; same surface as one engine."""

    def __init__(self, replicas, policy: str | None = None,
                 affinity_slack: int = 256, config=None):
        if not replicas:
            raise ValueError("Router needs at least one replica")
        if config is None:
            from tpu_ddp.utils.config import TrainConfig
            config = TrainConfig()
        policy = policy if policy is not None else config.router_policy
        if policy not in POLICIES:
            raise ValueError(f"unknown router policy {policy!r}: "
                             f"expected one of {POLICIES}")
        self.replicas = list(replicas)
        self.policy = policy
        self.affinity_slack = int(affinity_slack)
        self.routed = [0] * len(self.replicas)
        self.affinity_hits = 0      # routed BY cached prefix (> 0 tokens)
        self._owner: dict[int, int] = {}   # id(request) -> replica index

    # ---- placement -----------------------------------------------------

    def pick(self, prompt) -> int:
        """The replica index ``submit`` would use for ``prompt`` —
        split out so tests can interrogate placement decisions."""
        loads = [r.outstanding() for r in self.replicas]
        least = min(range(len(loads)), key=lambda i: (loads[i], i))
        if self.policy == "least-loaded":
            return least
        cached = [r.prefix_cached_len(prompt) for r in self.replicas]
        best = max(range(len(cached)),
                   key=lambda i: (cached[i], -loads[i], -i))
        if cached[best] > 0 and \
                loads[best] - loads[least] <= self.affinity_slack:
            return best
        return least

    def submit(self, prompt, max_new_tokens: int, **kw):
        i = self.pick(prompt)
        if self.policy == "prefix-affinity" and \
                self.replicas[i].prefix_cached_len(prompt) > 0:
            self.affinity_hits += 1
        req = self.replicas[i].submit(prompt, max_new_tokens, **kw)
        self.routed[i] += 1
        self._owner[id(req)] = i
        return req

    def cancel(self, req) -> bool:
        i = self._owner.get(id(req))
        if i is None:
            return False
        return self.replicas[i].cancel(req)

    # ---- the iteration (run_load drives this like one engine) ----------

    def step(self) -> bool:
        worked = False
        for r in self.replicas:
            worked |= bool(r.step())   # no short-circuit: step EVERY replica
        return worked

    def run(self, max_steps: int | None = None) -> int:
        n = 0
        while max_steps is None or n < max_steps:
            if not self.step():
                break
            n += 1
        return n

    # ---- introspection -------------------------------------------------

    def outstanding(self) -> int:
        return sum(r.outstanding() for r in self.replicas)

    def accounting_ok(self) -> bool:
        return all(r.accounting_ok() for r in self.replicas)

    def stats(self) -> dict:
        per = []
        for i, r in enumerate(self.replicas):
            s = {"routed": self.routed[i],
                 "outstanding": r.outstanding()}
            prefix = getattr(r, "prefix", None)
            if prefix is not None:
                s["prefix"] = prefix.stats()
            per.append(s)
        return {"policy": self.policy,
                "n_replicas": len(self.replicas),
                "routed": list(self.routed),
                "affinity_hits": self.affinity_hits,
                "replicas": per}


__all__ = ["Router", "POLICIES"]
