"""Serving fleet: prefill/decode disaggregation over an explicit KV
edge (disagg.py), refcounted prefix caching over the paged pool
(prefix.py), and a multi-replica router (router.py). docs/DESIGN.md
§21."""

from tpu_ddp.fleet.disagg import DisaggEngine, KVEdge, KVTransfer
from tpu_ddp.fleet.prefix import PrefixHit, PrefixIndex
from tpu_ddp.fleet.router import POLICIES, Router

__all__ = [
    "DisaggEngine",
    "KVEdge",
    "KVTransfer",
    "PrefixHit",
    "PrefixIndex",
    "POLICIES",
    "Router",
]
