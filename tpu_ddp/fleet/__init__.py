"""Serving fleet: prefill/decode disaggregation over an explicit KV
edge (disagg.py), refcounted prefix caching over the paged pool
(prefix.py), a multi-replica router (router.py), and the fleet
resilience layer — replica health, deterministic request migration,
and serve-side chaos (resilience.py). docs/DESIGN.md §21, §23."""

from tpu_ddp.fleet.disagg import DisaggEngine, KVEdge, KVTransfer
from tpu_ddp.fleet.prefix import PrefixHit, PrefixIndex
from tpu_ddp.fleet.resilience import (
    ReplicaCrashError,
    ReplicaHealth,
    ServeFaultInjector,
    continuation_of,
)
from tpu_ddp.fleet.router import POLICIES, Router

__all__ = [
    "DisaggEngine",
    "KVEdge",
    "KVTransfer",
    "PrefixHit",
    "PrefixIndex",
    "POLICIES",
    "ReplicaCrashError",
    "ReplicaHealth",
    "Router",
    "ServeFaultInjector",
    "continuation_of",
]
