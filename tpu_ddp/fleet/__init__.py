"""Serving fleet: prefill/decode disaggregation over an explicit KV
edge (disagg.py), refcounted prefix caching with per-tenant namespaces
over the paged pool (prefix.py), a multi-replica router (router.py),
the fleet resilience layer — replica health, deterministic request
migration, and serve-side chaos (resilience.py) — and the autoscaling
replica lifecycle control plane (autoscale.py). docs/DESIGN.md §21,
§23, §25."""

from tpu_ddp.fleet.autoscale import Autoscaler
from tpu_ddp.fleet.disagg import DisaggEngine, KVEdge, KVTransfer
from tpu_ddp.fleet.prefix import PrefixDirectory, PrefixHit, PrefixIndex
from tpu_ddp.fleet.resilience import (
    ReplicaCrashError,
    ReplicaHealth,
    ServeFaultInjector,
    continuation_of,
)
from tpu_ddp.fleet.router import POLICIES, Router

__all__ = [
    "Autoscaler",
    "DisaggEngine",
    "KVEdge",
    "KVTransfer",
    "PrefixDirectory",
    "PrefixHit",
    "PrefixIndex",
    "POLICIES",
    "ReplicaCrashError",
    "ReplicaHealth",
    "Router",
    "ServeFaultInjector",
    "continuation_of",
]
