"""Fleet resilience: the serving mirror of the round-5 training fault
model.

The training half earned its failure handling over three rounds
(StepGuard, verified checkpoints, elastic live-resharding); this module
gives the serving tier the same discipline. Three pieces:

- :class:`ReplicaHealth` — per-replica health state the Router keeps.
  A replica that raises out of ``step()`` (or overruns the optional
  step deadline) goes ``unhealthy``; re-admission is by probe with
  exponential backoff (``backoff * 2**(failures-1)``, capped), so a
  flapping replica gets exponentially rarer chances while a recovered
  one rejoins after a single successful probe. The clock is
  injectable so the backoff schedule is unit-testable without sleeps.

- :class:`ServeFaultInjector` — the serve-side chaos hooks, riding the
  training :class:`~tpu_ddp.resilience.chaos.FaultInjector` spec
  grammar, seed, and sentinel machinery unchanged
  (``TPU_DDP_CHAOS_FAULTS``; kinds in
  ``tpu_ddp.resilience.chaos.SERVE_FAULT_KINDS``). ``rank`` in a spec
  is the REPLICA index — the Router stamps each replica's injector
  with its position — and ``step`` is that replica's engine-step
  counter (``edge-drop`` counts edge deliveries instead). Every kind
  is one-shot by step match, so a crashed-then-probed replica does not
  re-crash and re-admission is actually reachable.

- :func:`continuation_of` — the deterministic-migration primitive.
  Because sampling is stateless keyed on ``fold_in(seed, position)``
  (serve/engine.py, round 12), a request replayed elsewhere from
  ``prompt + tokens_so_far`` samples its next token at exactly the
  position key the undisturbed run would have used: the continuation
  prompt has length ``P + g``, so its first sampled token is keyed at
  position ``P + g`` — the original's token ``g``. Migration is
  therefore BITWISE invisible in the token stream, which is the
  testable contract (tests/test_fleet_resilience.py).

What is lost on a replica crash: the replica's KV pages and any decode
step in flight. What is replayed: every undone request, from its
prompt plus tokens already streamed (prefill is recomputed — KV pages
are not migrated between replica pools). What is never lost: tokens
already handed to the caller, and the accounting identity
``completed + cancelled + shed == submitted``.
"""

from __future__ import annotations

import os
import time

import numpy as np

from tpu_ddp.resilience.chaos import FaultInjector

HEALTHY = "healthy"
UNHEALTHY = "unhealthy"


class ReplicaCrashError(RuntimeError):
    """Raised by chaos (or a genuinely broken replica) out of
    ``step()`` — the signal the Router converts into unhealthy state
    plus request migration."""


class ReplicaHealth:
    """Health state machine for one replica: healthy <-> unhealthy
    with exponential-backoff probing."""

    def __init__(self, backoff_s: float = 0.2, backoff_cap_s: float = 30.0,
                 clock=time.monotonic):
        if backoff_s <= 0:
            raise ValueError(f"backoff_s must be > 0, got {backoff_s}")
        self.backoff_s = float(backoff_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.clock = clock
        self.state = HEALTHY
        self.failures = 0          # consecutive, reset on recovery
        self.next_probe_at = 0.0

    @property
    def healthy(self) -> bool:
        return self.state == HEALTHY

    def mark_failure(self) -> float:
        """Record one failure; returns the backoff until the next
        probe (doubling per consecutive failure, capped)."""
        self.failures += 1
        self.state = UNHEALTHY
        wait = min(self.backoff_s * 2 ** (self.failures - 1),
                   self.backoff_cap_s)
        self.next_probe_at = self.clock() + wait
        return wait

    def mark_recovered(self) -> None:
        self.state = HEALTHY
        self.failures = 0
        self.next_probe_at = 0.0

    def probe_due(self) -> bool:
        """True when an unhealthy replica has served its backoff and
        may be probed for re-admission."""
        return self.state == UNHEALTHY \
            and self.clock() >= self.next_probe_at


def continuation_of(request):
    """The (prompt, max_new_tokens) a migrated replay submits: the
    original prompt extended by every token already streamed, with the
    generation budget shrunk by the same amount. Stateless sampling
    keyed on (seed, position) makes the replayed stream bitwise equal
    to the undisturbed one."""
    if request.tokens:
        prompt = np.concatenate(
            [np.asarray(request.prompt, np.int32),
             np.asarray(request.tokens, np.int32)])
    else:
        prompt = np.asarray(request.prompt, np.int32)
    return prompt, request.max_new_tokens - len(request.tokens)


class ServeFaultInjector(FaultInjector):
    """Serve-side fault hooks over the shared chaos spec machinery.

    Engines construct one (when ``TPU_DDP_CHAOS_FAULTS`` is set) and
    call :meth:`replica_step` at the top of every ``step()``;
    DisaggEngine additionally consults :meth:`edge_drop_fires` per
    edge delivery, and both decode paths consult :meth:`poison_fires`
    before building a decode bank. Training kinds in the same env are
    ignored here (and vice versa), so one spec string can drill a
    whole train+serve stack.
    """

    @classmethod
    def from_env(cls, rank: int | None = 0) -> "ServeFaultInjector":
        inj = super().from_env(rank=rank)
        # Serve processes are single-host: default the rank (replica
        # index) to 0 instead of jax.process_index(); the Router
        # overwrites it with the replica's actual position.
        if inj._rank is None:
            inj._rank = 0
        return inj

    def set_rank(self, rank: int) -> None:
        """The Router stamps each replica's injector with its index so
        ``:rank=R`` specs target one replica of a fleet."""
        self._rank = int(rank)

    def replica_step(self, step: int) -> None:
        """Top-of-``step()`` faults: ``slow-replica`` sleeps once
        (``TPU_DDP_CHAOS_SLOW_S``) so a deadline-armed router sees the
        overrun; ``replica-crash`` raises. Both are one-shot (exact
        step match + sentinel), so the post-backoff probe of the same
        replica succeeds and re-admission is reachable."""
        for spec in self.specs:
            if spec.kind == "slow-replica" and self._fires(spec, step):
                self._announce(spec, step)
                self._mark_sentinel(spec, step)
                time.sleep(self.slow_s)
        for spec in self.specs:
            if spec.kind == "replica-crash" and self._fires(spec, step):
                self._announce(spec, step)
                self._mark_sentinel(spec, step)
                raise ReplicaCrashError(
                    f"chaos: replica {spec.rank} crashed at engine "
                    f"step {step}")

    def edge_drop_fires(self, delivery: int) -> bool:
        """True when the ``delivery``-th KV-edge transfer must be
        lost in flight (the decode worker then falls back to local
        chunked prefill)."""
        for spec in self.specs:
            if spec.kind == "edge-drop" and self._fires(spec, delivery):
                self._announce(spec, delivery)
                self._mark_sentinel(spec, delivery)
                return True
        return False

    def publisher_death_fires(self, push_n: int) -> bool:
        """True when the ``push_n``-th weight publish must find the
        publisher dead (subscribers then keep serving last-good and
        count the loss; nothing crashes)."""
        for spec in self.specs:
            if spec.kind == "publisher-death" \
                    and self._fires(spec, push_n):
                self._announce(spec, push_n)
                self._mark_sentinel(spec, push_n)
                return True
        return False

    def push_stall_fires(self, push_n: int) -> bool:
        """True when the ``push_n``-th weight push must stall in
        flight (delivery delayed until the trainer's staleness gate
        flushes it — a delay drill, not a loss drill)."""
        for spec in self.specs:
            if spec.kind == "push-stall" \
                    and self._fires(spec, push_n):
                self._announce(spec, push_n)
                self._mark_sentinel(spec, push_n)
                return True
        return False

    def poison_fires(self, step: int) -> bool:
        """True when this engine step must corrupt one live request's
        KV pages with NaN (the ``nonfinite-logits`` drill: the decode
        bank's in-graph finiteness check must quarantine exactly the
        poisoned request)."""
        for spec in self.specs:
            if spec.kind == "nonfinite-logits" \
                    and self._fires(spec, step):
                self._announce(spec, step)
                self._mark_sentinel(spec, step)
                return True
        return False

    # ---- load-surge kinds (consumed by the DRIVE loop, not a replica;
    # chaos decides WHEN the surge lands, the drill decides what burst
    # to submit — see scripts/serve_chaos_sweep.py) -----------------------

    def flash_crowd_fires(self, step: int) -> bool:
        """True when a fleet-wide load surge must land at this drive
        step (the autoscaler's hysteresis/cooldown drill)."""
        for spec in self.specs:
            if spec.kind == "flash-crowd" and self._fires(spec, step):
                self._announce(spec, step)
                self._mark_sentinel(spec, step)
                return True
        return False

    def tenant_storm_fires(self, step: int) -> str | None:
        """The storming tenant's name when a single-tenant flood must
        land at this drive step (the WFQ-isolation drill), else
        None."""
        for spec in self.specs:
            if spec.kind == "tenant-storm" and self._fires(spec, step):
                self._announce(spec, step)
                self._mark_sentinel(spec, step)
                return spec.tenant
        return None


def serve_chaos_active() -> bool:
    """True when the chaos env is set at all — engines then construct
    a :class:`ServeFaultInjector` (specs with only training kinds are
    harmless: no serve hook matches them)."""
    from tpu_ddp.resilience.chaos import CHAOS_ENV
    return bool(os.environ.get(CHAOS_ENV))


__all__ = [
    "HEALTHY",
    "UNHEALTHY",
    "ReplicaCrashError",
    "ReplicaHealth",
    "ServeFaultInjector",
    "continuation_of",
    "serve_chaos_active",
]
