"""Autoscaling replica lifecycle: the control plane over the Router.

The fleet so far is a static-N replica set; production load is diurnal
with flash crowds. This module closes the loop: an :class:`Autoscaler`
watches the router's backlog and walks replicas through the §25 state
machine —

    scale decision -> (up) boot-from-push -> join
                   -> (down) drain -> migrate -> retire

**Scale-up is boot-from-push, never checkpoint restart.** A new
replica is constructed from the engine factory (its jitted programs
come out of the same-geometry ``lru_cache`` — ZERO new compiles, the
graph-audit pin), wired onto the Publisher's edge, and seeded by
``Publisher.bootstrap``: the CURRENT reconstruction ships as one full
``none``-wire update at the current version, so the replica joins the
fleet serving bitwise the same weights as everyone else. The measured
boot time (factory + bootstrap + staged catch-up) is the reaction-time
number ``bench.py`` compares against ``ServeEngine.from_checkpoint``.

**Scale-down is drain -> migrate -> retire.** The victim (the least
loaded healthy replica) is drained via the router's GRACEFUL path:
every unfinished stream re-pends as a ``continuation_of`` replay —
bitwise identical tokens, zero dropped streams, no retry-budget shed
(the budget guards crash loops, not planned lifecycle) — and only then
is the replica removed and its subscriber detached.

**Hysteresis + cooldown so flash crowds don't thrash.** Scaling needs
``hold`` CONSECUTIVE over/under-threshold observations (separate up/
down thresholds form the hysteresis band) and at least ``cooldown_ms``
since the last action (``TPU_DDP_SCALE_COOLDOWN_MS``) — a one-step
spike buys nothing, and boot/drain churn would burn the very capacity
scaling is meant to add.

**Breaker-tripped replicas are excluded from capacity math.** The load
signal is backlog per HEALTHY replica: a fleet of 3 with 2 breakers
open is a fleet of 1 for scaling purposes, so the controller adds
capacity instead of waiting for probes that may never succeed.

The Autoscaler mirrors the engine drive surface (``submit`` /
``cancel`` / ``step`` / ``run`` / ``outstanding`` /
``accounting_ok``), so ``loadgen.run_load`` / ``run_trace`` drive an
autoscaling fleet exactly like one engine.
"""

from __future__ import annotations

import time
import warnings


class Autoscaler:
    """Replica-count controller over one :class:`Router`.

    ``engine_factory`` returns a fresh, empty replica (same model and
    cache geometry as the fleet — geometry is what makes the compile
    cache shared). ``publisher`` (optional) seeds booted replicas via
    :meth:`Publisher.bootstrap`; without one, booted replicas serve
    the factory's params (version 0).
    """

    def __init__(self, router, engine_factory, publisher=None, *,
                 min_replicas: int = 1, max_replicas: int = 4,
                 up_tokens_per_replica: float = 256.0,
                 down_tokens_per_replica: float = 32.0,
                 hold_steps: int = 3, cooldown_ms: float | None = None,
                 enabled: bool | None = None, clock=time.monotonic,
                 config=None):
        if config is None:
            from tpu_ddp.utils.config import TrainConfig
            config = TrainConfig()
        self.router = router
        self.factory = engine_factory
        self.publisher = publisher
        self.enabled = bool(enabled if enabled is not None
                            else config.fleet_autoscale)
        self.cooldown_ms = float(cooldown_ms if cooldown_ms is not None
                                 else config.scale_cooldown_ms)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.up_tokens = float(up_tokens_per_replica)
        self.down_tokens = float(down_tokens_per_replica)
        self.hold_steps = int(hold_steps)
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if not self.down_tokens < self.up_tokens:
            raise ValueError(
                "down_tokens_per_replica must be < up_tokens_per_replica "
                "(the gap IS the hysteresis band)")
        if self.hold_steps < 1:
            raise ValueError("hold_steps must be >= 1")
        if self.cooldown_ms <= 0:
            raise ValueError("cooldown_ms must be > 0")
        self._clock = clock
        self._last_action_at = None   # no cooldown before the first act
        self._up_streak = 0
        self._down_streak = 0
        # Lifecycle counters + the replica-second integral the sweep's
        # goodput-per-replica acceptance check divides by.
        self.scale_ups = 0
        self.scale_downs = 0
        self.migrated_on_drain = 0
        self.boot_s: list[float] = []
        self.events: list[dict] = []
        self._rs_integral = 0.0
        self._rs_last = self._clock()

    # ---- load signal ---------------------------------------------------

    def _healthy(self) -> list[int]:
        return [i for i in range(len(self.router.replicas))
                if self.router.health[i].healthy]

    def capacity(self) -> int:
        """Replicas that count: healthy (breaker closed) only."""
        return len(self._healthy())

    def load_per_replica(self) -> float:
        """Fleet backlog divided by HEALTHY capacity — tripped
        breakers concentrate load on the survivors, and the signal
        must say so."""
        return self.router.outstanding() / max(1, self.capacity())

    # ---- the control loop ----------------------------------------------

    def step(self) -> bool:
        """One fleet step + one controller tick."""
        worked = bool(self.router.step())
        self._tick()
        return worked

    def run(self, max_steps: int | None = None) -> int:
        n = 0
        while max_steps is None or n < max_steps:
            if not self.step():
                break
            n += 1
        return n

    def _tick(self) -> None:
        now = self._clock()
        self._rs_integral += self.capacity() * (now - self._rs_last)
        self._rs_last = now
        if not self.enabled:
            return
        load = self.load_per_replica()
        if load > self.up_tokens:
            self._up_streak += 1
            self._down_streak = 0
        elif load < self.down_tokens:
            self._down_streak += 1
            self._up_streak = 0
        else:
            self._up_streak = self._down_streak = 0
        if self._last_action_at is not None and \
                (now - self._last_action_at) * 1e3 < self.cooldown_ms:
            return
        if self._up_streak >= self.hold_steps \
                and len(self.router.replicas) < self.max_replicas:
            self.scale_up()
        elif self._down_streak >= self.hold_steps \
                and self.capacity() > self.min_replicas \
                and len(self.router.replicas) > self.min_replicas:
            self.scale_down()

    def _acted(self, action: str, **detail) -> None:
        self._last_action_at = self._clock()
        self._up_streak = self._down_streak = 0
        self.events.append(dict(action=action,
                                n_replicas=len(self.router.replicas),
                                **detail))

    # ---- scale-up: boot from the publisher's full-push path ------------

    def scale_up(self):
        """Boot a replica and join it to the fleet. Returns it."""
        t0 = time.perf_counter()
        eng = self.factory()
        if self.publisher is not None:
            from tpu_ddp.publish.subscriber import Subscriber
            sub = Subscriber(eng, name=f"boot{self.scale_ups}")
            eng.subscriber = sub
            self.publisher.connect(sub)
            if self.publisher.bootstrap(sub) is not None:
                # Stage the boot push to completion BEFORE taking
                # traffic: the replica joins already serving the
                # fleet's current version, so routing to it can never
                # regress a stream's param_version.
                while sub.lag:
                    eng.step()
        boot_s = time.perf_counter() - t0
        self.boot_s.append(boot_s)
        self.scale_ups += 1
        i = self.router.add_replica(eng)
        self._acted("scale-up", replica=i, boot_s=boot_s,
                    version=getattr(eng, "param_version", 0))
        return eng

    # ---- scale-down: drain -> migrate -> retire ------------------------

    def scale_down(self):
        """Retire the least-loaded healthy replica. Its unfinished
        streams migrate as bitwise continuations (zero dropped).
        Returns the removed engine, or None if nothing was eligible."""
        healthy = self._healthy()
        if len(self.router.replicas) <= self.min_replicas \
                or not healthy:
            return None
        victim = min(healthy,
                     key=lambda i: (self.router.replicas[i].outstanding(),
                                    i))
        migrated = self.router.drain_replica(victim)
        eng = self.router.remove_replica(victim)
        self.migrated_on_drain += migrated
        sub = getattr(eng, "subscriber", None)
        if self.publisher is not None and sub is not None:
            try:
                self.publisher.subscribers.remove(sub)
            except ValueError:
                warnings.warn("autoscale: retired replica's subscriber "
                              "was not on the publisher's edge",
                              stacklevel=2)
        self.scale_downs += 1
        self._acted("scale-down", replica=victim, migrated=migrated)
        return eng

    # ---- engine drive surface (run_load / run_trace) -------------------

    def submit(self, prompt, max_new_tokens: int, **kw):
        return self.router.submit(prompt, max_new_tokens, **kw)

    def cancel(self, req) -> bool:
        return self.router.cancel(req)

    def outstanding(self) -> int:
        return self.router.outstanding()

    def outstanding_by_tenant(self) -> dict:
        return self.router.outstanding_by_tenant()

    def accounting_ok(self) -> bool:
        return self.router.accounting_ok()

    def tenant_accounting_ok(self) -> bool:
        return self.router.tenant_accounting_ok()

    def set_clock(self, clock) -> None:
        """Swap the control-plane clock mid-life — ``run_trace`` hands
        the controller its fleet-parallel VIRTUAL clock so cooldown
        windows and the replica-second integral tick in trace time,
        not wall time. Resets the integral's last sample and the
        cooldown anchor to the new clock's epoch (already-accumulated
        replica-seconds are kept)."""
        self._clock = clock
        self._rs_last = clock()
        self._last_action_at = None

    # ---- introspection -------------------------------------------------

    def replica_seconds(self) -> float:
        """∫ capacity dt over the drive so far — the denominator of
        goodput-per-replica-second."""
        now = self._clock()
        return self._rs_integral + self.capacity() * (now - self._rs_last)

    def stats(self) -> dict:
        return {"enabled": self.enabled,
                "n_replicas": len(self.router.replicas),
                "capacity": self.capacity(),
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "migrated_on_drain": self.migrated_on_drain,
                "boot_s": list(self.boot_s),
                "replica_seconds": self.replica_seconds(),
                "cooldown_ms": self.cooldown_ms,
                "events": list(self.events),
                "router": self.router.stats()}


__all__ = ["Autoscaler"]
