"""Prefill/decode disaggregation: two engine roles, one explicit edge.

Why split the roles at all: in the round-12 single engine, a prefill
chunk and the whole-bank decode step share one host loop, so a burst
of long prompts steals engine steps from live decodes (TTFT for the
burst trades directly against TPOT for everyone else). The fleet
answer — the DistServe/Splitwise argument — is to pin prefill to its
own worker whose pool only ever holds prompts, and stream each
finished prompt's KV blocks to the decode worker over an explicit
edge. Decode steps then never wait on prefill compute; prefill
capacity scales independently of decode capacity.

The edge is the MPMD round's machinery pointed at serving: the payload
rides :class:`tpu_ddp.parallel.compress.EdgeCodec` wire formats
("none" / "bf16" / "int8" — the ``kv_wire`` knob), so a DCN-crossing
role split pays 2–4x fewer bytes per prompt. int8 rides the
error-feedback-free variant: each transfer is an independent one-shot
payload (a different request's KV), so there is no trajectory along
which a residual could telescope. Garbage tail positions of the last
prompt block are zero-masked before encoding — stale values would
pollute the per-block int8 scales.

Adoption is free-list surgery, not a copy: the decode pool allocates
block ids, the payload lands in them with ONE scatter fused into the
front of the decode step (``_build_adopt_decode_step``), and the
request's slot starts directly in the decode phase. The fused program
applies the adoption scatter BEFORE the bank's own writes/gathers —
the adopted ids are in no live table this step, so the decode math is
untouched, and the scatter's dependence cones leave every layer's
QKV/MLP projections free: ``utils/hlo_comm.update_overlap_report``
checks exactly that, i.e. a latency-hiding scheduler is ALLOWED to
run the transfer landing behind decode compute.

Sampling stays stateless-keyed by (seed, position) on both sides, so
any role split reproduces the single engine's tokens bitwise with
``kv_wire="none"`` — the parity acceptance criterion. Lossy wires
round the shipped KV and are gated as semantic, like cache dtype.

Degraded mode (docs/DESIGN.md §23): a transfer lost on the edge, or
the prefill worker dying outright, must not wedge the pipeline. The
decode worker owns a one-slot fallback scheduler (``dsched``) over its
OWN pool and re-runs the lost request's prefill locally, chunked, with
the same jitted prefill program at the decode pool's shapes — single-
engine semantics, already bitwise-pinned, so degraded output equals
healthy output token for token (recomputed KV is recomputed, not
migrated). Prefill-worker death flips ``prefill_degraded``: every
pending prompt (mid-prefill, queued, and in-flight edge transfers) is
reaped and replayed locally, and later submits skip the dead role
entirely. A warning marks each degradation; nothing is silently lost.
"""

from __future__ import annotations

import dataclasses
import functools
import itertools
import math
import time
import warnings
from collections import deque
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from tpu_ddp.models.decode import check_decodable
from tpu_ddp.parallel.compress import EdgeCodec
from tpu_ddp.serve.engine import (
    Request,
    _build_prefill_step,
    decode_bank,
)
from tpu_ddp.serve.kv_pool import PagedKVPool, pin_committed
from tpu_ddp.serve.scheduler import (
    Scheduler,
    parse_tenant_classes,
    tenant_of,
)
from tpu_ddp.utils.metrics import MetricsLogger


@functools.lru_cache(maxsize=32)
def _build_adopt_decode_step(model, block_size: int,
                             blocks_per_seq: int):
    """The fused transfer-landing + whole-bank decode program.
    ``adopt_ids`` (nb,) are freshly allocated (table-less) block ids;
    ``adopt_k``/``adopt_v`` (L, nb, bs, KV, hd) is the decoded wire
    payload. The scatter runs FIRST so it depends on nothing the
    decode computes and nothing heavy depends on it — the dataflow
    freedom ``update_overlap_report`` verifies."""

    def step(params, pool_k, pool_v, adopt_ids, adopt_k, adopt_v,
             tables, lengths, last_tokens, temps, seeds):
        pool_k = pool_k.at[:, adopt_ids].set(
            adopt_k.astype(pool_k.dtype))
        pool_v = pool_v.at[:, adopt_ids].set(
            adopt_v.astype(pool_v.dtype))
        return decode_bank(model, block_size, blocks_per_seq, params,
                           pool_k, pool_v, tables, lengths,
                           last_tokens, temps, seeds)

    return jax.jit(step, donate_argnums=(1, 2))


@dataclasses.dataclass
class KVTransfer:
    """One finished prefill in flight on the edge: encoded KV blocks
    plus the last-token state the decode role resumes from."""

    request: Request
    wire_k: dict
    wire_v: dict
    n_blocks: int
    length: int          # prompt tokens (valid cache positions)
    pending_token: int   # first sampled token, already emitted
    nbytes: int          # wire payload bytes (both tensors)


class KVEdge:
    """The explicit prefill→decode edge: a FIFO of encoded transfers
    with one :class:`EdgeCodec` providing the wire format and the
    honest byte accounting (``bytes_sent`` / ``ratio``)."""

    def __init__(self, wire: str = "none"):
        if wire not in ("none", "bf16", "int8"):
            raise ValueError(f"kv_wire={wire!r}: expected "
                             "none|bf16|int8")
        self.wire = wire
        # int8 rides the EF-free variant: transfers are independent
        # one-shot payloads, not a trajectory a residual could follow.
        self.codec = EdgeCodec("int8-noef" if wire == "int8" else wire)
        self.queue: deque = deque()
        self.sent = 0
        self.delivered = 0
        self.dropped = 0

    def send(self, transfer: KVTransfer) -> None:
        self.queue.append(transfer)
        self.sent += 1

    def pop(self) -> KVTransfer:
        self.delivered += 1
        return self.queue.popleft()

    def drop(self, request: Request) -> bool:
        """Cancel support: remove a pending transfer for ``request``.
        Its blocks live only in the payload (the prefill side already
        freed its pool copies), so dropping the transfer IS the
        cleanup."""
        for t in self.queue:
            if t.request is request:
                self.queue.remove(t)
                self.dropped += 1
                return True
        return False

    def stats(self) -> dict:
        return {"wire": self.wire, "sent": self.sent,
                "delivered": self.delivered, "dropped": self.dropped,
                "pending": len(self.queue),
                "bytes_sent": self.codec.bytes_sent,
                "bytes_dense": self.codec.bytes_dense,
                "ratio": self.codec.ratio}


class DisaggEngine:
    """Prefill-role + decode-role pair behind the single-engine
    surface (``submit`` / ``cancel`` / ``step`` / ``run``), so
    loadgen, the router, and the sweep drive it interchangeably with
    :class:`ServeEngine`.

    One ``step()`` advances both roles once: admit + one prefill
    chunk on the prefill worker (shipping on completion), land at
    most one edge transfer on the decode worker (fused into the
    decode step when a live batch exists), one whole-bank decode
    step. Equal-simulated-hardware comparisons give the two pools a
    combined budget matching the single engine's.
    """

    def __init__(self, model, params, *,
                 num_slots: int | None = None,
                 block_size: int | None = None,
                 prefill_chunk: int | None = None,
                 num_blocks: int | None = None,
                 prefill_blocks: int | None = None,
                 cache_dtype: str | None = None,
                 kv_wire: str | None = None,
                 prefix_cache: bool | None = None,
                 queue_limit: int | None = None,
                 shed_ms: float | None = None,
                 tenant_classes: str | None = None,
                 decode_quant: str | None = None,
                 metrics: MetricsLogger | None = None,
                 config=None):
        check_decodable(model)
        if config is None:
            from tpu_ddp.utils.config import TrainConfig
            config = TrainConfig()
        self.model = model
        self.params = pin_committed(jax.tree.map(jnp.asarray, params))
        self.num_slots = int(num_slots if num_slots is not None
                             else config.serve_slots)
        self.block_size = int(block_size if block_size is not None
                              else config.serve_block_size)
        self.prefill_chunk = int(
            prefill_chunk if prefill_chunk is not None
            else config.serve_prefill_chunk)
        if self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        self.blocks_per_seq = math.ceil(model.max_seq_len
                                        / self.block_size)
        cache_dtype = (cache_dtype if cache_dtype is not None
                       else config.serve_cache_dtype)
        if num_blocks is None:
            num_blocks = self.num_slots * self.blocks_per_seq + 1
        if prefill_blocks is None:
            # Room for two worst-case prompts (one prefilling, one
            # admitted behind it) plus prefix-cache residency.
            prefill_blocks = 2 * self.blocks_per_seq + 1
        # Decode role: the round-12 pool + scheduler, decode-only in
        # practice (every slot is placed post-prefill).
        self.pool = PagedKVPool(model, num_blocks, self.block_size,
                                cache_dtype)
        self.sched = Scheduler(self.pool, self.num_slots, "continuous")
        # Prefill role: prompt-only reservations; finished KV ships
        # over the edge, so the prefix index (when on) lives HERE —
        # cached blocks must be in the pool the prefill step gathers.
        self.prefill_pool = PagedKVPool(model, prefill_blocks,
                                        self.block_size, cache_dtype)
        self.prefix = None
        prefix_cache = (bool(prefix_cache) if prefix_cache is not None
                        else config.prefix_cache)
        if prefix_cache:
            from tpu_ddp.fleet.prefix import PrefixIndex
            self.prefix = PrefixIndex(self.prefill_pool)
        # Tenant classes (§25) apply at the ADMISSION scheduler — the
        # prefill role's queue is where disagg requests wait. Degraded
        # mode trades WFQ for liveness (the fallback queue is FIFO):
        # with the prefill worker dead, draining anything beats
        # draining fairly.
        tc = (tenant_classes if tenant_classes is not None
              else config.tenant_classes)
        self.tenants = parse_tenant_classes(tc) or None
        self.psched = Scheduler(self.prefill_pool, 1, "continuous",
                                prefix=self.prefix, role="prefill",
                                tenants=self.tenants)
        # Degraded-mode fallback: a one-slot scheduler over the DECODE
        # pool that re-prefills requests whose edge transfer was lost
        # or whose prefill worker died. It shares the decode pool, so
        # the two schedulers are reservation peers — admitted-always-
        # finish holds across both.
        self.dsched = Scheduler(self.pool, 1, "continuous")
        self.sched.peers = [self.dsched]
        self.dsched.peers = [self.sched]
        self.prefill_degraded = False
        self.edge = KVEdge(kv_wire if kv_wire is not None
                           else config.kv_wire)
        # Weight-only int8 decode compute (§26, TPU_DDP_DECODE_QUANT)
        # for BOTH roles: one quantized tree feeds prefill, degraded
        # prefill, decode and adopt+decode, so the shipped KV and the
        # decode queries come from the same arithmetic. (Speculation
        # is NOT supported here — the decode tier runs the fused
        # adopt+decode program only; tune/space.py marks spec_k>0
        # with fleet_roles='disagg' infeasible.)
        self.decode_quant = str(
            decode_quant if decode_quant is not None
            else getattr(config, "decode_quant", "none"))
        if self.decode_quant not in ("none", "int8"):
            raise ValueError(
                f"decode_quant={self.decode_quant!r}: expected 'none'"
                " or 'int8' (TPU_DDP_DECODE_QUANT)")
        self._refresh_quant()
        self.metrics = metrics if metrics is not None \
            else MetricsLogger(None)
        self._prefill = _build_prefill_step(model, self.block_size,
                                            self.blocks_per_seq)
        self._adopt_decode = _build_adopt_decode_step(
            model, self.block_size, self.blocks_per_seq)
        self._rid = itertools.count()
        self.queue_limit = int(queue_limit if queue_limit is not None
                               else config.serve_queue_limit)
        self.shed_ms = float(shed_ms if shed_ms is not None
                             else config.serve_shed_ms)
        if self.queue_limit < 0:
            raise ValueError("queue_limit must be >= 0")
        if self.shed_ms < 0:
            raise ValueError("shed_ms must be >= 0")
        self._step_n = 0
        # Weight streaming (tpu_ddp/publish/): both roles serve ONE
        # ``self.params`` tree, passed per call to every jitted
        # program (prefill, degraded prefill, decode, adopt+decode) —
        # a subscriber flip swaps all of them at once, between steps.
        self.param_version = 0
        self.subscriber = None
        self.chaos = None
        from tpu_ddp.fleet.resilience import (
            ServeFaultInjector, serve_chaos_active)
        if serve_chaos_active():
            self.chaos = ServeFaultInjector.from_env()

    # ---- request lifecycle ---------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               temperature: float = 0.0, seed: int = 0,
               eos_id: int | None = None,
               on_token: Callable[[int], None] | None = None,
               tenant: str = "default") -> Request:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must hold >= 1 token")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = prompt.size + max_new_tokens
        if total > self.model.max_seq_len:
            raise ValueError(f"prompt + generation = {total} exceeds "
                             f"max_seq_len={self.model.max_seq_len}")
        if temperature < 0:
            raise ValueError("temperature must be >= 0")
        if not tenant:
            raise ValueError("tenant must be a non-empty string")
        req = Request(rid=next(self._rid), prompt=prompt,
                      max_new_tokens=int(max_new_tokens),
                      temperature=float(temperature), seed=int(seed),
                      eos_id=eos_id, on_token=on_token,
                      tenant=str(tenant),
                      submitted_at=time.perf_counter())
        # Decode-side feasibility must hold too, or the transfer could
        # never be adopted and would head-block the edge forever.
        dneed = self.sched.worst_case_blocks(req)
        if dneed > self.pool.total_usable:
            raise ValueError(
                f"request needs up to {dneed} decode KV blocks but "
                f"the decode pool holds only {self.pool.total_usable}")
        self.metrics.inc("serve_submitted")
        qlen = len(self.dsched.queue) if self.prefill_degraded \
            else len(self.psched.queue)
        if self.queue_limit and qlen >= self.queue_limit:
            self._shed(req)
            return req
        if self.prefill_degraded:
            self.dsched.enqueue(req)  # the prefill role is gone
        else:
            self.psched.enqueue(req)
        return req

    def _shed(self, req: Request) -> None:
        req.shed = True
        req.done = True
        req.finished_at = time.perf_counter()
        self.metrics.inc("serve_shed")

    def _shed_expired(self) -> None:
        """Deadline shedding over both admission queues. Only
        requests that have not produced a token are sheddable — a
        degraded-queue request replaying a lost transfer already
        streamed its first token and must finish."""
        if not self.shed_ms:
            return
        now = time.perf_counter()
        for q in (self.psched.queue, self.dsched.queue):
            expired = [r for r in q if not r.tokens
                       and (now - r.submitted_at) * 1e3 > self.shed_ms]
            for r in expired:
                q.remove(r)
                self._shed(r)

    def cancel(self, req: Request) -> bool:
        """Drop a request anywhere in the pipeline: queued, mid-
        prefill (frees the prefill pool's reserved blocks), pending on
        the edge (drops the transfer), or decoding."""
        if req.done:
            return False
        if self.edge.drop(req):
            pass
        elif req in self.psched.queue:
            self.psched.queue.remove(req)
        elif req in self.dsched.queue:
            self.dsched.queue.remove(req)
        else:
            for sched in (self.psched, self.dsched, self.sched):
                hit = False
                for i, s in enumerate(sched.slots):
                    if s is not None and s.request is req:
                        sched.retire(i)
                        hit = True
                        break
                if hit:
                    break
            else:
                return False
        req.cancelled = True
        req.done = True
        req.finished_at = time.perf_counter()
        self.metrics.inc("serve_cancelled")
        return True

    # ---- the iteration -------------------------------------------------

    def step(self) -> bool:
        """One fleet iteration: each role advances once. Degraded
        requests (lost transfer / dead prefill worker) re-prefill on
        the decode worker, one chunk per step, yielding to healthy
        prefill traffic when both exist."""
        self._step_n += 1
        if self.chaos is not None:
            # May raise ReplicaCrashError — before any state mutation.
            self.chaos.replica_step(self._step_n)
        if self.subscriber is not None:
            # Weight streaming: stage/flip between steps (see
            # ServeEngine.step) — prefill and decode roles flip
            # together, so a request never prefills on one version
            # and starts decoding on another within one step.
            self.subscriber.on_engine_step()
        self._shed_expired()
        admitted = list(self.psched.admit())
        self._promote_degraded()
        admitted += self.dsched.admit()
        did = False

        pi = self.psched.prefill_slot()
        di = self.dsched.prefill_slot()
        if pi is not None:
            did = True
            try:
                self._run_prefill_chunk(pi)
            except Exception as e:  # noqa: BLE001 — degrade, don't wedge
                self._fail_prefill(e)
        elif di is not None:
            did = True
            self._run_degraded_chunk(di)

        transfer = self._pop_adoptable()
        dslots = self.sched.decode_slots()
        if transfer is not None:
            did = True
            self._land(transfer, dslots)
        elif dslots:
            did = True
            self._run_decode_step(dslots)

        self.metrics.observe("serve_queue_depth",
                             len(self.psched.queue)
                             + len(self.dsched.queue))
        self.metrics.observe("serve_slot_occupancy",
                             self.sched.live / self.num_slots)
        return did or bool(admitted) or self.dsched.live > 0

    def run(self, max_steps: int | None = None) -> int:
        n = 0
        while max_steps is None or n < max_steps:
            if not self.step():
                break
            n += 1
        return n

    def swap_params(self, params, version: int) -> None:
        """Atomic weight flip for BOTH roles (see
        ServeEngine.swap_params): one tree feeds prefill, degraded
        prefill, decode and adopt+decode, so a single swap keeps every
        program on the same version from the next step on."""
        self.params = params
        self.param_version = int(version)
        self._refresh_quant()

    def _refresh_quant(self) -> None:
        """(Re)derive the serving parameter tree from the fp master
        ``self.params`` — at construction and after every
        :meth:`swap_params` flip (the subscriber re-quantizes on
        hot-swap without knowing the knob exists; see
        ServeEngine._refresh_quant)."""
        if self.decode_quant == "int8":
            from tpu_ddp.ops.quant import quantize_params
            self._decode_params = pin_committed(
                quantize_params(self.model, self.params))
        else:
            self._decode_params = self.params

    def stats(self) -> dict:
        """Pipeline introspection for dashboards and the sweep:
        the edge ledger, the quantization knob, and the degraded
        flag. ``speculative`` is always None — the decode tier runs
        the fused adopt+decode program only (speculation is a
        single-engine/router feature; tune/space.py marks the combo
        infeasible)."""
        return {"edge": self.edge.stats(),
                "decode_quant": self.decode_quant,
                "prefill_degraded": self.prefill_degraded,
                "speculative": None}

    # ---- router hooks --------------------------------------------------

    def outstanding(self) -> int:
        w = 0
        for q in (self.psched.queue, self.dsched.queue):
            for r in q:
                w += len(r.prompt) + r.max_new_tokens - len(r.tokens)
        for t in self.edge.queue:
            w += t.request.max_new_tokens - len(t.request.tokens)
        for sched in (self.psched, self.dsched, self.sched):
            for s in sched.slots:
                if s is not None:
                    w += (len(s.request.prompt) - s.prefill_done) \
                        + (s.request.max_new_tokens - s.generated)
        return w

    def prefix_cached_len(self, prompt, tenant: str = "default") -> int:
        if self.prefix is None:
            return 0
        return self.prefix.cached_len(
            np.asarray(prompt, np.int32).reshape(-1), ns=tenant)

    def outstanding_by_tenant(self) -> dict[str, int]:
        """``outstanding()`` by tenant (see ServeEngine) — computed
        live over queues, edge and slots, so cancels leave no ghost
        load in the autoscaler's backlog signal."""
        out: dict[str, int] = {}

        def add(t, w):
            out[t] = out.get(t, 0) + w

        for q in (self.psched.queue, self.dsched.queue):
            for r in q:
                add(tenant_of(r),
                    len(r.prompt) + r.max_new_tokens - len(r.tokens))
        for t in self.edge.queue:
            add(tenant_of(t.request),
                t.request.max_new_tokens - len(t.request.tokens))
        for sched in (self.psched, self.dsched, self.sched):
            for s in sched.slots:
                if s is not None:
                    add(tenant_of(s.request),
                        (len(s.request.prompt) - s.prefill_done)
                        + (s.request.max_new_tokens - s.generated))
        return out

    # ---- prefill role --------------------------------------------------

    def _table_for(self, slot) -> np.ndarray:
        t = np.zeros(self.blocks_per_seq, np.int32)
        t[:len(slot.blocks)] = slot.blocks
        return t

    def _run_prefill_chunk(self, pi: int) -> None:
        s = self.psched.slots[pi]
        req = s.request
        start, C = s.prefill_done, self.prefill_chunk
        chunk = np.zeros((1, C), np.int32)
        piece = req.prompt[start:start + C]
        chunk[0, :piece.size] = piece
        k, v, tok, lp = self._prefill(
            self._decode_params, self.prefill_pool.k,
            self.prefill_pool.v,
            jnp.asarray(self._table_for(s)), jnp.asarray(chunk),
            jnp.int32(start), jnp.int32(req.prompt.size),
            jnp.float32(req.temperature), jnp.int32(req.seed))
        self.prefill_pool.commit(k, v)
        s.prefill_done = min(start + C, int(req.prompt.size))
        s.length = s.prefill_done
        if s.prefill_done >= req.prompt.size:
            self._ship(pi, int(tok), float(lp))

    def _ship(self, pi: int, tok: int, lp: float) -> None:
        """Prefill finished: emit the first token (TTFT is prefill
        completion), encode the prompt's KV blocks onto the edge, hand
        the blocks back to the prefill pool (the payload is the copy
        in flight; the prefix index keeps its own refs)."""
        s = self.psched.slots[pi]
        req = s.request
        self._emit_first(req, tok, lp)
        if not req.done:
            nb = len(s.blocks)
            # page_arrays is the tier-aware whole-page read: at
            # tiers == 1 it is the direct gather this always was; a
            # tiered prefill pool promotes the blocks hot first so the
            # wire carries exact-dtype bytes, never double-quantized
            # cold pages.
            kb, vb = self.prefill_pool.page_arrays(s.blocks)
            # kb/vb: (L, nb, bs, KV, hd)
            # Zero the garbage tail of the last block: stale positions
            # would pollute the int8 per-block quantization scales.
            valid = (np.arange(nb * self.block_size)
                     < req.prompt.size).reshape(nb, self.block_size)
            mask = jnp.asarray(valid)[None, :, :, None, None]
            kb = jnp.where(mask, kb, 0)
            vb = jnp.where(mask, vb, 0)
            wire_k, n_k = self.edge.codec.encode(kb)
            wire_v, n_v = self.edge.codec.encode(vb)
            self.edge.send(KVTransfer(
                request=req, wire_k=wire_k, wire_v=wire_v, n_blocks=nb,
                length=int(req.prompt.size), pending_token=tok,
                nbytes=n_k + n_v))
            self.metrics.inc("fleet_shipped")
            self.metrics.observe("fleet_wire_bytes", n_k + n_v)
        if self.prefix is not None:
            self.prefix.register(req.prompt, s.blocks,
                                 ns=tenant_of(req))
        self.psched.retire(pi)

    def _emit_first(self, req: Request, tok: int, lp: float) -> None:
        req.tokens.append(tok)
        req.logprobs.append(lp)
        req.token_versions.append(self.param_version)
        now = time.perf_counter()
        req.token_times.append(now)
        req.first_token_at = now
        self.metrics.observe("serve_ttft_ms",
                             (now - req.submitted_at) * 1e3)
        if req.on_token is not None:
            req.on_token(tok)
        if req.max_new_tokens == 1 \
                or (req.eos_id is not None and tok == req.eos_id):
            req.done = True
            req.finished_at = now
            self.metrics.inc("serve_retired")

    # ---- degraded mode -------------------------------------------------

    def _degrade(self, req: Request) -> None:
        """Queue ``req`` for local re-prefill on the decode worker."""
        self.dsched.enqueue(req)
        self.metrics.inc("fleet_degraded")

    def _fail_prefill(self, exc: Exception) -> None:
        """The prefill worker died mid-chunk: reap EVERYTHING it owned
        — its slot, its queue, and every transfer still on the edge —
        and replay all of it through local chunked prefill. The
        prefill pool (and the prefix index rooted in it) dies with the
        worker; later submits route straight to the fallback."""
        warnings.warn(
            f"prefill worker failed ({type(exc).__name__}: {exc}); "
            "falling back to local chunked prefill on the decode "
            "worker", stacklevel=3)
        self.prefill_degraded = True
        self.metrics.inc("fleet_prefill_failures")
        harvested = []
        for i, s in enumerate(self.psched.slots):
            if s is not None:
                harvested.append(s.request)
                self.psched.retire(i)  # host bookkeeping; pool is dead
        harvested.extend(self.psched.queue)
        self.psched.queue.clear()
        while self.edge.queue:  # reap pending-edge state
            t = self.edge.queue.popleft()
            self.edge.dropped += 1
            harvested.append(t.request)
        self.prefix = None  # rooted in the dead prefill pool
        for req in sorted(harvested, key=lambda r: r.rid):
            if not req.done:
                self._degrade(req)

    def _run_degraded_chunk(self, di: int) -> None:
        """One local prefill chunk against the DECODE pool — the same
        jitted prefill program at the decode pool's shapes, so the
        recomputed KV (and the stateless-sampled first token) is
        bitwise what the healthy path would have produced."""
        s = self.dsched.slots[di]
        req = s.request
        start, C = s.prefill_done, self.prefill_chunk
        chunk = np.zeros((1, C), np.int32)
        piece = req.prompt[start:start + C]
        chunk[0, :piece.size] = piece
        k, v, tok, lp = self._prefill(
            self._decode_params, self.pool.k, self.pool.v,
            jnp.asarray(self._table_for(s)), jnp.asarray(chunk),
            jnp.int32(start), jnp.int32(req.prompt.size),
            jnp.float32(req.temperature), jnp.int32(req.seed))
        self.pool.commit(k, v)
        s.prefill_done = min(start + C, int(req.prompt.size))
        s.length = s.prefill_done
        if s.prefill_done >= req.prompt.size:
            if not req.tokens:
                # Prefill-death replay: the first token was never
                # emitted — emit it now (TTFT is prefill completion).
                self._emit_first(req, int(tok), float(lp))
            # else: edge-drop replay — the first token already
            # streamed at _ship time; the recomputed sample is
            # bitwise identical (stateless (seed, position) keying)
            # and is dropped, never double-emitted.
            s.phase = "decode"
            s.generated = len(req.tokens)
            s.pending_token = req.tokens[-1] if req.tokens else int(tok)
            if req.done:  # max_new_tokens == 1 or instant EOS
                self.dsched.retire(di)

    def _promote_degraded(self) -> None:
        """Hand a locally re-prefilled sequence to the decode
        scheduler as soon as it has a free slot: ownership of the
        blocks transfers (both schedulers draw on the decode pool),
        and the slot starts in the decode phase exactly like an
        adopted transfer."""
        for i, s in enumerate(self.dsched.slots):
            if s is not None and s.phase == "decode" \
                    and self.sched.live < self.num_slots:
                st = self.dsched.release(i)
                self.sched.place(st.request, st.blocks, st.length,
                                 st.pending_token)
                self.metrics.inc("fleet_degraded_promoted")

    # ---- decode role ---------------------------------------------------

    def _pop_adoptable(self) -> KVTransfer | None:
        """FIFO edge delivery, gated by the decode scheduler's
        reservation rule (a free slot AND the full worst case fits).
        A transfer lost in flight (the ``edge-drop`` chaos drill)
        degrades to local re-prefill instead of vanishing."""
        if not self.edge.queue:
            return None
        if self.sched.live >= self.num_slots:
            return None
        t = self.edge.queue[0]
        need = self.sched.worst_case_blocks(t.request)
        if need > self.sched.pool_budget:
            return None
        t = self.edge.pop()
        if self.chaos is not None \
                and self.chaos.edge_drop_fires(self.edge.delivered):
            warnings.warn(
                f"KV transfer for request {t.request.rid} lost on the "
                "edge; re-prefilling locally on the decode worker",
                stacklevel=3)
            self.edge.dropped += 1
            self.metrics.inc("fleet_edge_failures")
            self._degrade(t.request)
            return None
        return t

    def _land(self, t: KVTransfer, dslots: list) -> None:
        """Adopt a transfer's blocks into the decode pool — fused into
        the decode step when a live batch exists, a standalone scatter
        otherwise — then place the slot."""
        ids = [self.pool.alloc() for _ in range(t.n_blocks)]
        adopt_ids = jnp.asarray(np.asarray(ids, np.int32))
        ak = EdgeCodec.decode(t.wire_k)
        av = EdgeCodec.decode(t.wire_v)
        if dslots:
            tables, lengths, last, temps, seeds = \
                self._bank_inputs(dslots)
            self._maybe_poison(dslots)
            k, v, toks, lps, bad = self._adopt_decode(
                self._decode_params, self.pool.k, self.pool.v,
                adopt_ids,
                ak, av, tables, lengths, last, temps, seeds)
            self.pool.commit(k, v)
            self._emit_bank(dslots, toks, lps, bad)
        else:
            self.pool.commit(
                self.pool.k.at[:, adopt_ids].set(
                    ak.astype(self.pool.k.dtype)),
                self.pool.v.at[:, adopt_ids].set(
                    av.astype(self.pool.v.dtype)))
        self.sched.place(t.request, ids, t.length, t.pending_token)
        self.metrics.inc("fleet_adopted")

    def _bank_inputs(self, dslots: list):
        S, BPS = self.num_slots, self.blocks_per_seq
        tables = np.zeros((S, BPS), np.int32)
        lengths = np.zeros(S, np.int32)
        last = np.zeros(S, np.int32)
        temps = np.zeros(S, np.float32)
        seeds = np.zeros(S, np.int32)
        for i in dslots:
            self.sched.ensure_block(i)
            s = self.sched.slots[i]
            tables[i] = self._table_for(s)
            lengths[i] = s.length
            last[i] = s.pending_token
            temps[i] = s.request.temperature
            seeds[i] = s.request.seed
        return (jnp.asarray(tables), jnp.asarray(lengths),
                jnp.asarray(last), jnp.asarray(temps),
                jnp.asarray(seeds))

    def _maybe_poison(self, dslots: list) -> None:
        """The ``nonfinite-logits`` drill on the disagg decode worker
        (see ServeEngine._maybe_poison): NaN one live request's
        private last KV block host-side."""
        if self.chaos is None or not dslots \
                or not self.chaos.poison_fires(self._step_n):
            return
        s = self.sched.slots[dslots[0]]
        blk = s.blocks[-1]
        self.pool.v = self.pool.v.at[:, blk].set(jnp.nan)

    def _run_decode_step(self, dslots: list) -> None:
        from tpu_ddp.serve.engine import _build_decode_step
        tables, lengths, last, temps, seeds = self._bank_inputs(dslots)
        self._maybe_poison(dslots)
        step = _build_decode_step(self.model, self.block_size,
                                  self.blocks_per_seq)
        k, v, toks, lps, bad = step(
            self._decode_params, self.pool.k, self.pool.v,
            tables, lengths, last, temps, seeds)
        self.pool.commit(k, v)
        self._emit_bank(dslots, toks, lps, bad)

    def _emit_bank(self, dslots: list, toks, lps, bad) -> None:
        toks, lps = np.asarray(toks), np.asarray(lps)
        bad = np.asarray(bad)
        for i in dslots:
            s = self.sched.slots[i]
            req = s.request
            if bad[i]:
                # Quarantine the poisoned request, not the bank:
                # scrub its private pages (a NaN'd page re-issued to
                # another request would leak through zero-weight
                # attention) and finish it flagged.
                self.pool.scrub([b for b in s.blocks
                                 if self.pool.refcount(b) == 1])
                self.sched.retire(i)
                req.quarantined = True
                req.done = True
                req.finished_at = time.perf_counter()
                self.metrics.inc("serve_quarantined")
                warnings.warn(
                    f"request {req.rid}: non-finite logits at engine "
                    f"step {self._step_n}; request quarantined",
                    stacklevel=3)
                continue
            s.length += 1
            tok = int(toks[i])
            s.generated += 1
            s.pending_token = tok
            req.tokens.append(tok)
            req.logprobs.append(float(lps[i]))
            req.token_versions.append(self.param_version)
            req.token_times.append(time.perf_counter())
            if req.on_token is not None:
                req.on_token(tok)
            if s.generated >= req.max_new_tokens \
                    or (req.eos_id is not None and tok == req.eos_id):
                req.done = True
                req.finished_at = time.perf_counter()
                self.sched.retire(i)
                self.metrics.inc("serve_retired")

    # ---- introspection -------------------------------------------------

    def accounting_ok(self) -> bool:
        # The decode pool has TWO schedulers drawing on it (sched +
        # the degraded-prefill fallback), so its identity is checked
        # over their joint holders. The prefill pool's check is
        # skipped once its worker died — that hardware (and its
        # accounting) is gone from the system.
        holders = [s.blocks for s in self.sched.slots if s is not None]
        holders += [s.blocks for s in self.dsched.slots
                    if s is not None]
        if not self.pool.refcount_ok(holders):
            return False
        return self.prefill_degraded or self.psched.accounting_ok()

    def drain(self) -> list[Request]:
        """Harvest every unfinished request from the whole pipeline
        (queues, prefill slot, edge, fallback, decode slots) and
        release all engine state — the router's failure-migration
        hook. Submit order."""
        reqs = list(self.psched.queue)
        self.psched.queue.clear()
        reqs.extend(self.dsched.queue)
        self.dsched.queue.clear()
        for sched in (self.psched, self.dsched, self.sched):
            for i, s in enumerate(sched.slots):
                if s is not None:
                    reqs.append(s.request)
                    sched.retire(i)
        while self.edge.queue:
            t = self.edge.queue.popleft()
            self.edge.dropped += 1
            reqs.append(t.request)
        return sorted((r for r in reqs if not r.done),
                      key=lambda r: r.rid)

    def lower_adopt_decode(self, n_blocks: int = 2):
        """``jit.lower`` the fused adopt+decode program for a
        representative transfer size — the audit surface
        ``tpu_ddp/analysis`` fingerprints and donation-checks."""
        sds = lambda x: jax.ShapeDtypeStruct(  # noqa: E731
            jnp.shape(x), jnp.result_type(x))
        params = jax.tree.map(sds, self._decode_params)
        S, BPS = self.num_slots, self.blocks_per_seq
        pk = sds(self.pool.k)
        payload = jax.ShapeDtypeStruct(
            (self.model.num_layers, n_blocks, self.block_size,
             self.model.kv_heads, self.model.head_dim), jnp.float32)
        i32 = lambda shape: jax.ShapeDtypeStruct(shape, jnp.int32)  # noqa: E731
        return self._adopt_decode.lower(
            params, pk, pk, i32((n_blocks,)), payload, payload,
            i32((S, BPS)), i32((S,)), i32((S,)),
            jax.ShapeDtypeStruct((S,), jnp.float32),
            i32((S,)))

    def adopt_decode_hlo(self, n_blocks: int = 2) -> str:
        """Compiled HLO of the fused adopt+decode program — what
        ``tpu_ddp/analysis`` (assert_transfer_overlap) scans."""
        return self.lower_adopt_decode(n_blocks).compile().as_text()

    def lower_degraded_prefill(self):
        """``jit.lower`` the degraded-mode local prefill: the SAME
        prefill program traced at the DECODE pool's shapes (more
        blocks than the prefill pool), i.e. a distinct compiled
        program — the graph-audit cell for the fallback path."""
        sds = jax.ShapeDtypeStruct
        return self._prefill.lower(
            self._decode_params, self.pool.k, self.pool.v,
            sds((self.blocks_per_seq,), jnp.int32),
            sds((1, self.prefill_chunk), jnp.int32),
            sds((), jnp.int32), sds((), jnp.int32),
            sds((), jnp.float32), sds((), jnp.int32))


__all__ = ["DisaggEngine", "KVEdge", "KVTransfer"]
