"""Prefill/decode disaggregation: two engine roles, one explicit edge.

Why split the roles at all: in the round-12 single engine, a prefill
chunk and the whole-bank decode step share one host loop, so a burst
of long prompts steals engine steps from live decodes (TTFT for the
burst trades directly against TPOT for everyone else). The fleet
answer — the DistServe/Splitwise argument — is to pin prefill to its
own worker whose pool only ever holds prompts, and stream each
finished prompt's KV blocks to the decode worker over an explicit
edge. Decode steps then never wait on prefill compute; prefill
capacity scales independently of decode capacity.

The edge is the MPMD round's machinery pointed at serving: the payload
rides :class:`tpu_ddp.parallel.compress.EdgeCodec` wire formats
("none" / "bf16" / "int8" — the ``kv_wire`` knob), so a DCN-crossing
role split pays 2–4x fewer bytes per prompt. int8 rides the
error-feedback-free variant: each transfer is an independent one-shot
payload (a different request's KV), so there is no trajectory along
which a residual could telescope. Garbage tail positions of the last
prompt block are zero-masked before encoding — stale values would
pollute the per-block int8 scales.

Adoption is free-list surgery, not a copy: the decode pool allocates
block ids, the payload lands in them with ONE scatter fused into the
front of the decode step (``_build_adopt_decode_step``), and the
request's slot starts directly in the decode phase. The fused program
applies the adoption scatter BEFORE the bank's own writes/gathers —
the adopted ids are in no live table this step, so the decode math is
untouched, and the scatter's dependence cones leave every layer's
QKV/MLP projections free: ``utils/hlo_comm.update_overlap_report``
checks exactly that, i.e. a latency-hiding scheduler is ALLOWED to
run the transfer landing behind decode compute.

Sampling stays stateless-keyed by (seed, position) on both sides, so
any role split reproduces the single engine's tokens bitwise with
``kv_wire="none"`` — the parity acceptance criterion. Lossy wires
round the shipped KV and are gated as semantic, like cache dtype.
"""

from __future__ import annotations

import dataclasses
import functools
import itertools
import math
import time
from collections import deque
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from tpu_ddp.models.decode import check_decodable
from tpu_ddp.parallel.compress import EdgeCodec
from tpu_ddp.serve.engine import (
    Request,
    _build_prefill_step,
    decode_bank,
)
from tpu_ddp.serve.kv_pool import PagedKVPool
from tpu_ddp.serve.scheduler import Scheduler
from tpu_ddp.utils.metrics import MetricsLogger


@functools.lru_cache(maxsize=32)
def _build_adopt_decode_step(model, block_size: int,
                             blocks_per_seq: int):
    """The fused transfer-landing + whole-bank decode program.
    ``adopt_ids`` (nb,) are freshly allocated (table-less) block ids;
    ``adopt_k``/``adopt_v`` (L, nb, bs, KV, hd) is the decoded wire
    payload. The scatter runs FIRST so it depends on nothing the
    decode computes and nothing heavy depends on it — the dataflow
    freedom ``update_overlap_report`` verifies."""

    def step(params, pool_k, pool_v, adopt_ids, adopt_k, adopt_v,
             tables, lengths, last_tokens, temps, seeds):
        pool_k = pool_k.at[:, adopt_ids].set(
            adopt_k.astype(pool_k.dtype))
        pool_v = pool_v.at[:, adopt_ids].set(
            adopt_v.astype(pool_v.dtype))
        return decode_bank(model, block_size, blocks_per_seq, params,
                           pool_k, pool_v, tables, lengths,
                           last_tokens, temps, seeds)

    return jax.jit(step, donate_argnums=(1, 2))


@dataclasses.dataclass
class KVTransfer:
    """One finished prefill in flight on the edge: encoded KV blocks
    plus the last-token state the decode role resumes from."""

    request: Request
    wire_k: dict
    wire_v: dict
    n_blocks: int
    length: int          # prompt tokens (valid cache positions)
    pending_token: int   # first sampled token, already emitted
    nbytes: int          # wire payload bytes (both tensors)


class KVEdge:
    """The explicit prefill→decode edge: a FIFO of encoded transfers
    with one :class:`EdgeCodec` providing the wire format and the
    honest byte accounting (``bytes_sent`` / ``ratio``)."""

    def __init__(self, wire: str = "none"):
        if wire not in ("none", "bf16", "int8"):
            raise ValueError(f"kv_wire={wire!r}: expected "
                             "none|bf16|int8")
        self.wire = wire
        # int8 rides the EF-free variant: transfers are independent
        # one-shot payloads, not a trajectory a residual could follow.
        self.codec = EdgeCodec("int8-noef" if wire == "int8" else wire)
        self.queue: deque = deque()
        self.sent = 0
        self.delivered = 0
        self.dropped = 0

    def send(self, transfer: KVTransfer) -> None:
        self.queue.append(transfer)
        self.sent += 1

    def pop(self) -> KVTransfer:
        self.delivered += 1
        return self.queue.popleft()

    def drop(self, request: Request) -> bool:
        """Cancel support: remove a pending transfer for ``request``.
        Its blocks live only in the payload (the prefill side already
        freed its pool copies), so dropping the transfer IS the
        cleanup."""
        for t in self.queue:
            if t.request is request:
                self.queue.remove(t)
                self.dropped += 1
                return True
        return False

    def stats(self) -> dict:
        return {"wire": self.wire, "sent": self.sent,
                "delivered": self.delivered, "dropped": self.dropped,
                "pending": len(self.queue),
                "bytes_sent": self.codec.bytes_sent,
                "bytes_dense": self.codec.bytes_dense,
                "ratio": self.codec.ratio}


class DisaggEngine:
    """Prefill-role + decode-role pair behind the single-engine
    surface (``submit`` / ``cancel`` / ``step`` / ``run``), so
    loadgen, the router, and the sweep drive it interchangeably with
    :class:`ServeEngine`.

    One ``step()`` advances both roles once: admit + one prefill
    chunk on the prefill worker (shipping on completion), land at
    most one edge transfer on the decode worker (fused into the
    decode step when a live batch exists), one whole-bank decode
    step. Equal-simulated-hardware comparisons give the two pools a
    combined budget matching the single engine's.
    """

    def __init__(self, model, params, *,
                 num_slots: int | None = None,
                 block_size: int | None = None,
                 prefill_chunk: int | None = None,
                 num_blocks: int | None = None,
                 prefill_blocks: int | None = None,
                 cache_dtype: str | None = None,
                 kv_wire: str | None = None,
                 prefix_cache: bool | None = None,
                 metrics: MetricsLogger | None = None,
                 config=None):
        check_decodable(model)
        if config is None:
            from tpu_ddp.utils.config import TrainConfig
            config = TrainConfig()
        self.model = model
        self.params = jax.tree.map(jnp.asarray, params)
        self.num_slots = int(num_slots if num_slots is not None
                             else config.serve_slots)
        self.block_size = int(block_size if block_size is not None
                              else config.serve_block_size)
        self.prefill_chunk = int(
            prefill_chunk if prefill_chunk is not None
            else config.serve_prefill_chunk)
        if self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        self.blocks_per_seq = math.ceil(model.max_seq_len
                                        / self.block_size)
        cache_dtype = (cache_dtype if cache_dtype is not None
                       else config.serve_cache_dtype)
        if num_blocks is None:
            num_blocks = self.num_slots * self.blocks_per_seq + 1
        if prefill_blocks is None:
            # Room for two worst-case prompts (one prefilling, one
            # admitted behind it) plus prefix-cache residency.
            prefill_blocks = 2 * self.blocks_per_seq + 1
        # Decode role: the round-12 pool + scheduler, decode-only in
        # practice (every slot is placed post-prefill).
        self.pool = PagedKVPool(model, num_blocks, self.block_size,
                                cache_dtype)
        self.sched = Scheduler(self.pool, self.num_slots, "continuous")
        # Prefill role: prompt-only reservations; finished KV ships
        # over the edge, so the prefix index (when on) lives HERE —
        # cached blocks must be in the pool the prefill step gathers.
        self.prefill_pool = PagedKVPool(model, prefill_blocks,
                                        self.block_size, cache_dtype)
        self.prefix = None
        prefix_cache = (bool(prefix_cache) if prefix_cache is not None
                        else config.prefix_cache)
        if prefix_cache:
            from tpu_ddp.fleet.prefix import PrefixIndex
            self.prefix = PrefixIndex(self.prefill_pool)
        self.psched = Scheduler(self.prefill_pool, 1, "continuous",
                                prefix=self.prefix, role="prefill")
        self.edge = KVEdge(kv_wire if kv_wire is not None
                           else config.kv_wire)
        self.metrics = metrics if metrics is not None \
            else MetricsLogger(None)
        self._prefill = _build_prefill_step(model, self.block_size,
                                            self.blocks_per_seq)
        self._adopt_decode = _build_adopt_decode_step(
            model, self.block_size, self.blocks_per_seq)
        self._rid = itertools.count()

    # ---- request lifecycle ---------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               temperature: float = 0.0, seed: int = 0,
               eos_id: int | None = None,
               on_token: Callable[[int], None] | None = None) -> Request:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must hold >= 1 token")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = prompt.size + max_new_tokens
        if total > self.model.max_seq_len:
            raise ValueError(f"prompt + generation = {total} exceeds "
                             f"max_seq_len={self.model.max_seq_len}")
        if temperature < 0:
            raise ValueError("temperature must be >= 0")
        req = Request(rid=next(self._rid), prompt=prompt,
                      max_new_tokens=int(max_new_tokens),
                      temperature=float(temperature), seed=int(seed),
                      eos_id=eos_id, on_token=on_token,
                      submitted_at=time.perf_counter())
        # Decode-side feasibility must hold too, or the transfer could
        # never be adopted and would head-block the edge forever.
        dneed = self.sched.worst_case_blocks(req)
        if dneed > self.pool.total_usable:
            raise ValueError(
                f"request needs up to {dneed} decode KV blocks but "
                f"the decode pool holds only {self.pool.total_usable}")
        self.psched.enqueue(req)
        self.metrics.inc("serve_submitted")
        return req

    def cancel(self, req: Request) -> bool:
        """Drop a request anywhere in the pipeline: queued, mid-
        prefill (frees the prefill pool's reserved blocks), pending on
        the edge (drops the transfer), or decoding."""
        if req.done:
            return False
        if self.edge.drop(req):
            pass
        elif req in self.psched.queue:
            self.psched.queue.remove(req)
        else:
            for sched in (self.psched, self.sched):
                hit = False
                for i, s in enumerate(sched.slots):
                    if s is not None and s.request is req:
                        sched.retire(i)
                        hit = True
                        break
                if hit:
                    break
            else:
                return False
        req.cancelled = True
        req.done = True
        req.finished_at = time.perf_counter()
        self.metrics.inc("serve_cancelled")
        return True

    # ---- the iteration -------------------------------------------------

    def step(self) -> bool:
        """One fleet iteration: each role advances once."""
        admitted = self.psched.admit()
        did = False

        pi = self.psched.prefill_slot()
        if pi is not None:
            did = True
            self._run_prefill_chunk(pi)

        transfer = self._pop_adoptable()
        dslots = self.sched.decode_slots()
        if transfer is not None:
            did = True
            self._land(transfer, dslots)
        elif dslots:
            did = True
            self._run_decode_step(dslots)

        self.metrics.observe("serve_queue_depth",
                             len(self.psched.queue))
        self.metrics.observe("serve_slot_occupancy",
                             self.sched.live / self.num_slots)
        return did or bool(admitted)

    def run(self, max_steps: int | None = None) -> int:
        n = 0
        while max_steps is None or n < max_steps:
            if not self.step():
                break
            n += 1
        return n

    # ---- router hooks --------------------------------------------------

    def outstanding(self) -> int:
        w = 0
        for r in self.psched.queue:
            w += len(r.prompt) + r.max_new_tokens
        for t in self.edge.queue:
            w += t.request.max_new_tokens - len(t.request.tokens)
        for sched in (self.psched, self.sched):
            for s in sched.slots:
                if s is not None:
                    w += (len(s.request.prompt) - s.prefill_done) \
                        + (s.request.max_new_tokens - s.generated)
        return w

    def prefix_cached_len(self, prompt) -> int:
        if self.prefix is None:
            return 0
        return self.prefix.cached_len(
            np.asarray(prompt, np.int32).reshape(-1))

    # ---- prefill role --------------------------------------------------

    def _table_for(self, slot) -> np.ndarray:
        t = np.zeros(self.blocks_per_seq, np.int32)
        t[:len(slot.blocks)] = slot.blocks
        return t

    def _run_prefill_chunk(self, pi: int) -> None:
        s = self.psched.slots[pi]
        req = s.request
        start, C = s.prefill_done, self.prefill_chunk
        chunk = np.zeros((1, C), np.int32)
        piece = req.prompt[start:start + C]
        chunk[0, :piece.size] = piece
        k, v, tok, lp = self._prefill(
            self.params, self.prefill_pool.k, self.prefill_pool.v,
            jnp.asarray(self._table_for(s)), jnp.asarray(chunk),
            jnp.int32(start), jnp.int32(req.prompt.size),
            jnp.float32(req.temperature), jnp.int32(req.seed))
        self.prefill_pool.commit(k, v)
        s.prefill_done = min(start + C, int(req.prompt.size))
        s.length = s.prefill_done
        if s.prefill_done >= req.prompt.size:
            self._ship(pi, int(tok), float(lp))

    def _ship(self, pi: int, tok: int, lp: float) -> None:
        """Prefill finished: emit the first token (TTFT is prefill
        completion), encode the prompt's KV blocks onto the edge, hand
        the blocks back to the prefill pool (the payload is the copy
        in flight; the prefix index keeps its own refs)."""
        s = self.psched.slots[pi]
        req = s.request
        self._emit_first(req, tok, lp)
        if not req.done:
            nb = len(s.blocks)
            ids = jnp.asarray(np.asarray(s.blocks, np.int32))
            kb = self.prefill_pool.k[:, ids]   # (L, nb, bs, KV, hd)
            vb = self.prefill_pool.v[:, ids]
            # Zero the garbage tail of the last block: stale positions
            # would pollute the int8 per-block quantization scales.
            valid = (np.arange(nb * self.block_size)
                     < req.prompt.size).reshape(nb, self.block_size)
            mask = jnp.asarray(valid)[None, :, :, None, None]
            kb = jnp.where(mask, kb, 0)
            vb = jnp.where(mask, vb, 0)
            wire_k, n_k = self.edge.codec.encode(kb)
            wire_v, n_v = self.edge.codec.encode(vb)
            self.edge.send(KVTransfer(
                request=req, wire_k=wire_k, wire_v=wire_v, n_blocks=nb,
                length=int(req.prompt.size), pending_token=tok,
                nbytes=n_k + n_v))
            self.metrics.inc("fleet_shipped")
            self.metrics.observe("fleet_wire_bytes", n_k + n_v)
        if self.prefix is not None:
            self.prefix.register(req.prompt, s.blocks)
        self.psched.retire(pi)

    def _emit_first(self, req: Request, tok: int, lp: float) -> None:
        req.tokens.append(tok)
        req.logprobs.append(lp)
        now = time.perf_counter()
        req.first_token_at = now
        self.metrics.observe("serve_ttft_ms",
                             (now - req.submitted_at) * 1e3)
        if req.on_token is not None:
            req.on_token(tok)
        if req.max_new_tokens == 1 \
                or (req.eos_id is not None and tok == req.eos_id):
            req.done = True
            req.finished_at = now
            self.metrics.inc("serve_retired")

    # ---- decode role ---------------------------------------------------

    def _pop_adoptable(self) -> KVTransfer | None:
        """FIFO edge delivery, gated by the decode scheduler's
        reservation rule (a free slot AND the full worst case fits)."""
        if not self.edge.queue:
            return None
        if self.sched.live >= self.num_slots:
            return None
        t = self.edge.queue[0]
        need = self.sched.worst_case_blocks(t.request)
        if need > self.pool.allocatable - self.sched.reserved_unallocated:
            return None
        return self.edge.pop()

    def _land(self, t: KVTransfer, dslots: list) -> None:
        """Adopt a transfer's blocks into the decode pool — fused into
        the decode step when a live batch exists, a standalone scatter
        otherwise — then place the slot."""
        ids = [self.pool.alloc() for _ in range(t.n_blocks)]
        adopt_ids = jnp.asarray(np.asarray(ids, np.int32))
        ak = EdgeCodec.decode(t.wire_k)
        av = EdgeCodec.decode(t.wire_v)
        if dslots:
            tables, lengths, last, temps, seeds = \
                self._bank_inputs(dslots)
            k, v, toks, lps = self._adopt_decode(
                self.params, self.pool.k, self.pool.v, adopt_ids,
                ak, av, tables, lengths, last, temps, seeds)
            self.pool.commit(k, v)
            self._emit_bank(dslots, toks, lps)
        else:
            self.pool.commit(
                self.pool.k.at[:, adopt_ids].set(
                    ak.astype(self.pool.k.dtype)),
                self.pool.v.at[:, adopt_ids].set(
                    av.astype(self.pool.v.dtype)))
        self.sched.place(t.request, ids, t.length, t.pending_token)
        self.metrics.inc("fleet_adopted")

    def _bank_inputs(self, dslots: list):
        S, BPS = self.num_slots, self.blocks_per_seq
        tables = np.zeros((S, BPS), np.int32)
        lengths = np.zeros(S, np.int32)
        last = np.zeros(S, np.int32)
        temps = np.zeros(S, np.float32)
        seeds = np.zeros(S, np.int32)
        for i in dslots:
            self.sched.ensure_block(i)
            s = self.sched.slots[i]
            tables[i] = self._table_for(s)
            lengths[i] = s.length
            last[i] = s.pending_token
            temps[i] = s.request.temperature
            seeds[i] = s.request.seed
        return (jnp.asarray(tables), jnp.asarray(lengths),
                jnp.asarray(last), jnp.asarray(temps),
                jnp.asarray(seeds))

    def _run_decode_step(self, dslots: list) -> None:
        from tpu_ddp.serve.engine import _build_decode_step
        tables, lengths, last, temps, seeds = self._bank_inputs(dslots)
        step = _build_decode_step(self.model, self.block_size,
                                  self.blocks_per_seq)
        k, v, toks, lps = step(self.params, self.pool.k, self.pool.v,
                               tables, lengths, last, temps, seeds)
        self.pool.commit(k, v)
        self._emit_bank(dslots, toks, lps)

    def _emit_bank(self, dslots: list, toks, lps) -> None:
        toks, lps = np.asarray(toks), np.asarray(lps)
        for i in dslots:
            s = self.sched.slots[i]
            s.length += 1
            req = s.request
            tok = int(toks[i])
            s.generated += 1
            s.pending_token = tok
            req.tokens.append(tok)
            req.logprobs.append(float(lps[i]))
            if req.on_token is not None:
                req.on_token(tok)
            if s.generated >= req.max_new_tokens \
                    or (req.eos_id is not None and tok == req.eos_id):
                req.done = True
                req.finished_at = time.perf_counter()
                self.sched.retire(i)
                self.metrics.inc("serve_retired")

    # ---- introspection -------------------------------------------------

    def accounting_ok(self) -> bool:
        return (self.sched.accounting_ok()
                and self.psched.accounting_ok())

    def lower_adopt_decode(self, n_blocks: int = 2):
        """``jit.lower`` the fused adopt+decode program for a
        representative transfer size — the audit surface
        ``tpu_ddp/analysis`` fingerprints and donation-checks."""
        sds = lambda x: jax.ShapeDtypeStruct(  # noqa: E731
            jnp.shape(x), jnp.result_type(x))
        params = jax.tree.map(sds, self.params)
        S, BPS = self.num_slots, self.blocks_per_seq
        pk = sds(self.pool.k)
        payload = jax.ShapeDtypeStruct(
            (self.model.num_layers, n_blocks, self.block_size,
             self.model.kv_heads, self.model.head_dim), jnp.float32)
        i32 = lambda shape: jax.ShapeDtypeStruct(shape, jnp.int32)  # noqa: E731
        return self._adopt_decode.lower(
            params, pk, pk, i32((n_blocks,)), payload, payload,
            i32((S, BPS)), i32((S,)), i32((S,)),
            jax.ShapeDtypeStruct((S,), jnp.float32),
            i32((S,)))

    def adopt_decode_hlo(self, n_blocks: int = 2) -> str:
        """Compiled HLO of the fused adopt+decode program — what
        ``tpu_ddp/analysis`` (assert_transfer_overlap) scans."""
        return self.lower_adopt_decode(n_blocks).compile().as_text()


__all__ = ["DisaggEngine", "KVEdge", "KVTransfer"]
