"""ImageNet-1k data path — the BASELINE.json stretch config
("ResNet-50 / ImageNet-1k scale-up", configs[4]; no reference counterpart,
the reference is CIFAR-10-only — reference part1/main.py:19-50).

Real ImageNet is found via ``IMAGENET_DIR`` pointing at a directory of
pre-converted numpy shards (``{split}_images.npy`` uint8 NHWC +
``{split}_labels.npy``); anything heavier (TFDS/JPEG decode) is out of
scope in a zero-egress environment. Otherwise a deterministic
class-conditional synthetic stand-in with ImageNet shapes is used, flagged
in the returned metadata, so the ResNet-50 config trains end to end
anywhere.
"""

from __future__ import annotations

import os

import numpy as np

from tpu_ddp.utils.config import SEED

# torchvision's canonical ImageNet normalization constants.
IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], dtype=np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], dtype=np.float32)

_DEFAULT_SYNTH = {"train": 2048, "val": 512}


def _synthetic(split: str, n: int | None, image_size: int,
               num_classes: int):
    if n is None:
        env = os.environ.get("TPU_DDP_SYNTH_SIZE")
        if env is not None:
            n = int(env) if split == "train" else max(int(env) // 4, 8)
        else:
            n = _DEFAULT_SYNTH["train" if split == "train" else "val"]
    # Class signatures from a split-INDEPENDENT seed (shared by train and
    # val, else eval on the synthetic stand-in is anti-correlated noise).
    base = np.random.default_rng(0x1A46E7).normal(
        0, 30, size=(num_classes, 1, 1, 3))
    rng = np.random.default_rng(0x1A46E7 + (1 if split == "train" else 2))
    labels = rng.integers(0, num_classes, size=n).astype(np.int32)
    images = rng.normal(118, 55, size=(n, image_size, image_size, 3))
    images = np.clip(images + base[labels], 0, 255).astype(np.uint8)
    return images, labels


def load_imagenet(root: str | None = None, split: str = "train",
                  synthetic_size: int | None = None, image_size: int = 224,
                  num_classes: int = 1000):
    """Returns ``(images_u8_nhwc, labels_i32, meta)``."""
    root = root or os.environ.get("IMAGENET_DIR")
    if root:
        xp = os.path.join(root, f"{split}_images.npy")
        yp = os.path.join(root, f"{split}_labels.npy")
        if os.path.exists(xp) and os.path.exists(yp):
            return (np.load(xp, mmap_mode="r"),
                    np.load(yp).astype(np.int32),
                    {"synthetic": False, "dir": root})
    images, labels = _synthetic(split, synthetic_size, image_size,
                                num_classes)
    return images, labels, {"synthetic": True, "dir": None}


def create_imagenet_loaders(
    rank: int = 0,
    world_size: int = 1,
    batch_size: int = 256,
    root: str | None = None,
    seed: int = SEED,
    synthetic_size: int | None = None,
    image_size: int = 224,
    num_classes: int = 1000,
    native: bool | None = None,
):
    """(train_loader, test_loader) with the same contract as the CIFAR
    facade (tpu_ddp/data/loader.py): per-node batch in, train sharded by
    rank, val unsharded."""
    from tpu_ddp.data.loader import DataLoader, _pick_loader_cls
    from tpu_ddp.data.sampler import DistributedShardSampler

    train_x, train_y, meta_tr = load_imagenet(
        root, "train", synthetic_size, image_size, num_classes)
    test_x, test_y, meta_va = load_imagenet(
        root, "val",
        None if synthetic_size is None else max(synthetic_size // 4, 8),
        image_size, num_classes)
    for split, meta in (("train", meta_tr), ("val", meta_va)):
        if meta["synthetic"]:
            print(f"[tpu_ddp.data] ImageNet {split} split not found -> "
                  "deterministic synthetic stand-in (set IMAGENET_DIR to "
                  "use real shards)")
    sampler = None
    if world_size > 1:
        sampler = DistributedShardSampler(
            len(train_y), num_replicas=world_size, rank=rank,
            shuffle=False, drop_last=False)
    cls = _pick_loader_cls(native)
    if isinstance(train_x, np.memmap) and cls is not DataLoader:
        # NativeDataLoader's ascontiguousarray would materialize the whole
        # mmap'd train split (~190 GB at full ImageNet) into RAM; the numpy
        # loader slices per batch and keeps the memmap lazy.
        print("[tpu_ddp.data] real ImageNet shards are memory-mapped -> "
              "numpy loader (native loader would copy the full split)")
        cls = DataLoader
    train = cls(train_x, train_y, batch_size, sampler=sampler,
                augment=True, seed=seed, mean=IMAGENET_MEAN,
                std=IMAGENET_STD)
    test = cls(test_x, test_y, batch_size, augment=False,
               mean=IMAGENET_MEAN, std=IMAGENET_STD)
    return train, test
