"""Text pipeline for the LM family: byte tokenizer, document packing,
sharded epoch batches.

No reference counterpart (the reference's only dataset is CIFAR-10,
part1/main.py:19-50) — this module gives the transformer family the
same complete data story the vision side has: tokenize -> pack ->
shard-per-rank -> per-epoch reshuffle, with the packing hot loop in
C++ (native/tpu_ddp_text.cpp, ctypes-bound like the image pipeline in
tpu_ddp/data/native.py) and a numpy fallback producing IDENTICAL rows
(tested in tests/test_text.py).

Design:
- **ByteTokenizer** — vocabulary = 256 bytes + PAD/BOS/EOS (259 ids).
  Zero-egress and language-agnostic; a learned subword vocabulary can
  replace it behind the same encode/decode surface.
- **pack_documents** — one token stream ``[BOS] doc EOS [BOS] doc EOS
  ...`` chunked into (N, seq_len + 1) rows (GPT-2-style grouping; the
  +1 lets ``make_lm_batch`` split shifted inputs/targets). Tail
  remainder is dropped.
- **epoch_batches** — rank-sharded, optionally epoch-shuffled batch
  iterator over packed rows, built on the same
  :class:`DistributedShardSampler` contract as the vision loaders
  (stride sharding, wrap padding, ``set_epoch``).
"""

from __future__ import annotations

import ctypes

import numpy as np

from tpu_ddp.data.native import NativeLib, _i32p, _i64p, _u8p

PAD_ID = 0
BOS_ID = 1
EOS_ID = 2
_BYTE_OFFSET = 3
VOCAB_SIZE = 256 + _BYTE_OFFSET


def _bind(lib):
    lib.tpu_ddp_text_stream_len.argtypes = [_i64p, ctypes.c_int64,
                                            ctypes.c_int]
    lib.tpu_ddp_text_stream_len.restype = ctypes.c_int64
    lib.tpu_ddp_text_pack.argtypes = [
        _u8p, _i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int,
        _i32p, ctypes.c_int64]
    lib.tpu_ddp_text_pack.restype = ctypes.c_int64
    return lib


_text_lib = NativeLib("libtpu_ddp_text.so", "tpu_ddp_text.cpp", _bind)
_get_lib = _text_lib.get


def native_available() -> bool:
    return _get_lib() is not None


class ByteTokenizer:
    """Byte-level tokenizer: PAD=0, BOS=1, EOS=2, byte b -> b + 3."""

    vocab_size = VOCAB_SIZE
    pad_id, bos_id, eos_id = PAD_ID, BOS_ID, EOS_ID

    def encode(self, text) -> np.ndarray:
        data = text.encode("utf-8") if isinstance(text, str) else bytes(text)
        return np.frombuffer(data, np.uint8).astype(np.int32) + _BYTE_OFFSET

    def decode(self, ids) -> str:
        ids = np.asarray(ids)
        ids = ids[ids >= _BYTE_OFFSET] - _BYTE_OFFSET
        return ids.astype(np.uint8).tobytes().decode("utf-8",
                                                     errors="replace")


def _doc_buffers(docs):
    blobs = [d.encode("utf-8") if isinstance(d, str) else bytes(d)
             for d in docs]
    offsets = np.zeros(len(blobs) + 1, np.int64)
    np.cumsum([len(b) for b in blobs], out=offsets[1:])
    return np.frombuffer(b"".join(blobs), np.uint8), offsets


def pack_documents(docs, seq_len: int, add_bos: bool = True,
                   use_native: bool | None = None) -> np.ndarray:
    """Pack ``docs`` (str/bytes list) into (N, seq_len + 1) int32 rows.

    ``use_native=None`` picks the C++ packer when the library builds,
    numpy otherwise; both produce identical rows. Raises on empty input
    or when the stream is shorter than one row.
    """
    if not docs:
        raise ValueError("pack_documents: no documents")
    row_len = seq_len + 1
    data, offsets = _doc_buffers(docs)
    if use_native is None:
        use_native = native_available()
    if use_native:
        lib = _get_lib()
        if lib is None:
            raise RuntimeError(f"native text library unavailable: "
                               f"{_text_lib.build_error}")
        stream = lib.tpu_ddp_text_stream_len(offsets, len(docs),
                                             int(add_bos))
        n_rows = stream // row_len
        if n_rows == 0:
            raise ValueError(f"documents too short: {stream} tokens < "
                             f"one row of {row_len}")
        out = np.empty((n_rows, row_len), np.int32)
        got = lib.tpu_ddp_text_pack(
            np.ascontiguousarray(data), offsets, len(docs), row_len,
            int(add_bos), out, n_rows)
        if got < 0:
            raise RuntimeError(f"tpu_ddp_text_pack error {got}")
        return out[:got]
    # numpy fallback — must match the C++ layout exactly.
    pieces = []
    for d in range(len(docs)):
        if add_bos:
            pieces.append(np.array([BOS_ID], np.int32))
        pieces.append(data[offsets[d]:offsets[d + 1]].astype(np.int32)
                      + _BYTE_OFFSET)
        pieces.append(np.array([EOS_ID], np.int32))
    stream = np.concatenate(pieces)
    n_rows = len(stream) // row_len
    if n_rows == 0:
        raise ValueError(f"documents too short: {len(stream)} tokens < "
                         f"one row of {row_len}")
    return stream[:n_rows * row_len].reshape(n_rows, row_len)


def epoch_batches(rows: np.ndarray, batch_size: int, *, rank: int = 0,
                  world_size: int = 1, shuffle: bool = True,
                  seed: int = 0, epoch: int = 0, drop_last: bool = True):
    """Yield this rank's (inputs, targets) LM batches for one epoch.

    Sharding follows the vision sampler's contract
    (tpu_ddp/data/sampler.py): wrap-pad to a common per-rank length,
    stride-shard by rank. ``shuffle`` permutes ROWS per epoch with a
    seed shared by all ranks (rows are independent contexts, so row
    order — unlike the reference's intentionally unshuffled CIFAR
    epochs — is free to mix). ``drop_last`` drops a ragged final batch
    (LM steps want static shapes under jit).
    """
    from tpu_ddp.data.sampler import DistributedShardSampler
    from tpu_ddp.train.lm import make_lm_batch
    # One sharding implementation in the codebase: the sampler owns the
    # deadlock-sensitive wrap-pad + stride-shard rule (and its torch
    # parity tests); this iterator just feeds its order to the LM batcher.
    sampler = DistributedShardSampler(len(rows), world_size, rank,
                                      shuffle=shuffle, seed=seed,
                                      drop_last=False)
    sampler.set_epoch(epoch)
    mine = sampler.indices()
    for i in range(0, len(mine), batch_size):
        take = mine[i:i + batch_size]
        if drop_last and len(take) < batch_size:
            break
        yield make_lm_batch(rows[take])
