"""Train-time augmentation: RandomCrop(32, padding=4) + RandomHorizontalFlip.

Reference part1/main.py:23-28 composes exactly these two (then ToTensor +
Normalize). Implemented as vectorized numpy over the whole batch — the
host-side analogue of torchvision's per-image C transforms (SURVEY.md §2
row N4). Exact bit parity with torch RNG order is a non-goal; seed-fixed
self-consistency is (SURVEY.md §7).
"""

from __future__ import annotations

import numpy as np


def random_crop_flip(
    images_u8: np.ndarray,
    rng: np.random.Generator,
    padding: int = 4,
) -> np.ndarray:
    """Batched random 32x32 crop from zero-padded 40x40 + per-image hflip.

    ``images_u8``: (N, H, W, C) uint8. Returns same shape/dtype.
    """
    n, h, w, c = images_u8.shape
    padded = np.zeros((n, h + 2 * padding, w + 2 * padding, c),
                      dtype=images_u8.dtype)
    padded[:, padding:padding + h, padding:padding + w] = images_u8
    ys = rng.integers(0, 2 * padding + 1, size=n)
    xs = rng.integers(0, 2 * padding + 1, size=n)
    flips = rng.random(n) < 0.5
    # Gather crops via advanced indexing: build per-image row/col indices.
    rows = ys[:, None] + np.arange(h)[None, :]            # (N, H)
    cols = xs[:, None] + np.arange(w)[None, :]            # (N, W)
    out = padded[np.arange(n)[:, None, None], rows[:, :, None],
                 cols[:, None, :]]                        # (N, H, W, C)
    out[flips] = out[flips, :, ::-1]
    return out
