"""ctypes bindings for the C++ native data pipeline (native/tpu_ddp_data.cpp).

The reference's data path is native too — torchvision's C transforms plus
the DataLoader worker pool (reference part1/main.py:19-50,36-41; SURVEY.md
§2 row N4). This module exposes that C++ replacement to Python:

- :func:`transform_batch` — one-shot augment+normalize of a batch (the
  transforms alone, used by tests and small jobs);
- :class:`NativeDataLoader` — drop-in for
  :class:`tpu_ddp.data.loader.DataLoader`: same ``set_epoch`` /
  ``__len__`` / ``__iter__`` contract, but batches are produced by C++
  worker threads into a bounded prefetch queue, so augmentation and
  normalization overlap with the device step (the reference gets this from
  ``num_workers=2``).

The shared library builds lazily on first use (``make -C native``); when no
toolchain is available, callers fall back to the numpy pipeline
(:func:`available` tells them).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from tpu_ddp.data.cifar10 import CIFAR10_MEAN, CIFAR10_STD
from tpu_ddp.utils.config import SEED

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")


_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")


class NativeLib:
    """Shared lazy build-and-load machinery for the ctypes-bound C++
    libraries under ``native/`` (the image pipeline here, text packing
    in tpu_ddp/data/text.py): mtime-checked `make` on first use,
    negative-cached build errors, thread-safe single load."""

    def __init__(self, lib_name: str, src_name: str, bind):
        self._lib_path = os.path.join(_NATIVE_DIR, lib_name)
        self._src_path = os.path.join(_NATIVE_DIR, src_name)
        self._bind = bind
        self._lib = None
        self._lock = threading.Lock()
        self.build_error: str | None = None

    def _build(self) -> bool:
        if os.path.exists(self._lib_path):
            if not os.path.exists(self._src_path):
                return True  # prebuilt .so shipped without source: use it
            if os.path.getmtime(self._lib_path) >= \
                    os.path.getmtime(self._src_path):
                return True
        try:
            # Build ONLY this library's target: a compile failure in a
            # sibling library must not poison this one, and per-target
            # builds can't race each other onto the same .so.
            subprocess.run(["make", "-C", _NATIVE_DIR,
                            os.path.basename(self._lib_path)],
                           check=True, capture_output=True, text=True,
                           timeout=300)
            return True
        except (subprocess.SubprocessError, OSError) as e:
            out = getattr(e, "stderr", "") or str(e)
            self.build_error = f"native build failed: {out[-500:]}"
            return False

    def get(self):
        """The loaded library, building if needed; None on failure."""
        with self._lock:
            if self._lib is not None:
                return self._lib
            if self.build_error is not None:
                return None  # negative-cached: don't re-spawn make
            if not self._build():
                return None
            try:
                self._lib = self._bind(ctypes.CDLL(self._lib_path))
            except OSError as e:  # pragma: no cover - exotic
                self.build_error = str(e)
                return None
            return self._lib


def _bind(lib):
    lib.tpu_ddp_transform_batch.argtypes = [
        _u8p, _i32p, ctypes.c_int64, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_void_p, ctypes.c_int64, _f32p, _f32p,
        ctypes.c_int, ctypes.c_uint64, ctypes.c_uint64, _f32p, _i32p]
    lib.tpu_ddp_transform_batch.restype = None
    lib.tpu_ddp_loader_create.argtypes = [
        _u8p, _i32p, ctypes.c_int64, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, _i64p, ctypes.c_int64, ctypes.c_int, _f32p, _f32p,
        ctypes.c_int, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int,
        ctypes.c_int]
    lib.tpu_ddp_loader_create.restype = ctypes.c_void_p
    lib.tpu_ddp_loader_next.argtypes = [ctypes.c_void_p, _f32p, _i32p]
    lib.tpu_ddp_loader_next.restype = ctypes.c_int
    lib.tpu_ddp_loader_destroy.argtypes = [ctypes.c_void_p]
    lib.tpu_ddp_loader_destroy.restype = None
    lib.tpu_ddp_version.restype = ctypes.c_int
    return lib


_data_lib = NativeLib("libtpu_ddp_data.so", "tpu_ddp_data.cpp", _bind)


def get_lib():
    """The loaded shared library, building it if needed; None on failure."""
    return _data_lib.get()


def available() -> bool:
    return get_lib() is not None


def build_error() -> str | None:
    return _data_lib.build_error


def transform_batch(images_u8, labels, indices=None, *, augment=False,
                    seed: int = SEED, epoch: int = 0,
                    mean=CIFAR10_MEAN, std=CIFAR10_STD):
    """Augment+normalize ``images_u8[indices]`` in C++; returns (f32, i32).

    With ``augment=False`` this is numerically identical to
    :func:`tpu_ddp.data.cifar10.normalize` (tested); with ``augment=True``
    it applies RandomCrop(pad 4)+RandomHorizontalFlip with counter-based,
    schedule-independent randomness.
    """
    lib = get_lib()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_data_lib.build_error}")
    images_u8 = np.ascontiguousarray(images_u8, dtype=np.uint8)
    labels = np.ascontiguousarray(labels, dtype=np.int32)
    n, h, w, c = images_u8.shape
    if indices is None:
        idx_ptr, n_out = None, n
    else:
        indices = np.ascontiguousarray(indices, dtype=np.int64)
        idx_ptr = indices.ctypes.data_as(ctypes.c_void_p)
        n_out = len(indices)
    out_x = np.empty((n_out, h, w, c), np.float32)
    out_y = np.empty((n_out,), np.int32)
    lib.tpu_ddp_transform_batch(
        images_u8, labels, n, h, w, c, idx_ptr, n_out,
        np.ascontiguousarray(mean, np.float32),
        np.ascontiguousarray(std, np.float32),
        int(augment), seed, epoch, out_x, out_y)
    return out_x, out_y


class NativeDataLoader:
    """C++-prefetched drop-in for :class:`tpu_ddp.data.loader.DataLoader`.

    Same constructor surface and iteration contract (normalized f32 NHWC
    images, i32 labels; ``drop_last=False`` keeps the short final batch).
    ``num_threads``/``prefetch_depth`` mirror the reference DataLoader's
    ``num_workers=2`` + its 2-batch-per-worker prefetch.
    """

    def __init__(self, images_u8, labels, batch_size, sampler=None,
                 augment=False, seed: int = SEED, num_threads: int = 2,
                 prefetch_depth: int = 4,
                 mean=CIFAR10_MEAN, std=CIFAR10_STD):
        self.images_u8 = np.ascontiguousarray(images_u8, dtype=np.uint8)
        self.labels = np.ascontiguousarray(labels, dtype=np.int32)
        self.batch_size = batch_size
        self.sampler = sampler
        self.augment = augment
        self.seed = seed
        self.epoch = 0
        self.num_threads = num_threads
        self.prefetch_depth = prefetch_depth
        self.mean = np.ascontiguousarray(mean, np.float32)
        self.std = np.ascontiguousarray(std, np.float32)
        if get_lib() is None:
            raise RuntimeError(f"native library unavailable: {_data_lib.build_error}")

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        if self.sampler is not None:
            self.sampler.set_epoch(epoch)

    def _order(self):
        if self.sampler is not None:
            return np.ascontiguousarray(self.sampler.indices(), np.int64)
        return np.arange(len(self.labels), dtype=np.int64)

    def __len__(self) -> int:
        n = len(self.sampler) if self.sampler is not None \
            else len(self.labels)
        return (n + self.batch_size - 1) // self.batch_size

    def __iter__(self):
        lib = get_lib()
        order = self._order()
        n, h, w, c = self.images_u8.shape
        handle = lib.tpu_ddp_loader_create(
            self.images_u8, self.labels, n, h, w, c, order, len(order),
            self.batch_size, self.mean, self.std, int(self.augment),
            self.seed, self.epoch, self.num_threads, self.prefetch_depth)
        if not handle:
            raise RuntimeError("tpu_ddp_loader_create failed")
        out_x = np.empty((self.batch_size, h, w, c), np.float32)
        out_y = np.empty((self.batch_size,), np.int32)
        try:
            while True:
                got = lib.tpu_ddp_loader_next(handle, out_x, out_y)
                if got < 0:
                    break
                # Copy out: the queue buffer is reused next iteration.
                yield out_x[:got].copy(), out_y[:got].copy()
        finally:
            lib.tpu_ddp_loader_destroy(handle)
