"""Host-side data pipeline (reference L4, ``create_data_loaders``).

Replaces torchvision's CIFAR-10 dataset + transforms + torch DataLoader +
DistributedSampler (reference part1/main.py:19-50, part2/part2b/main.py:61-94)
with a numpy pipeline feeding the device mesh: local CIFAR-10 batches (or a
deterministic synthetic stand-in when the dataset isn't on disk — this
environment has no network egress), vectorized crop/flip augmentation, and a
sampler reproducing ``torch.utils.data.DistributedSampler`` semantics
exactly (verified against torch in tests/test_sampler.py).
"""

from tpu_ddp.data.cifar10 import (  # noqa: F401
    CIFAR10_MEAN,
    CIFAR10_STD,
    load_cifar10,
    normalize,
)
from tpu_ddp.data.sampler import DistributedShardSampler  # noqa: F401
from tpu_ddp.data.text import (  # noqa: F401
    ByteTokenizer,
    epoch_batches,
    pack_documents,
)
from tpu_ddp.data.loader import DataLoader, create_data_loaders  # noqa: F401


def normalization_constants(dataset: str):
    """(mean, std) on the x/255 scale for a dataset name."""
    if dataset == "cifar10":
        return CIFAR10_MEAN, CIFAR10_STD
    if dataset == "imagenet":
        from tpu_ddp.data.imagenet import IMAGENET_MEAN, IMAGENET_STD
        return IMAGENET_MEAN, IMAGENET_STD
    raise ValueError(f"unknown dataset {dataset!r}")
