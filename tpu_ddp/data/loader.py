"""Batched data loader over numpy arrays + the create_data_loaders facade.

The reference's ``create_data_loaders`` (part1/main.py:19-50 single;
part2/part2b/main.py:61-94 sharded) returns ``(train_loader, test_loader)``
with: global batch 256 (per-node ``int(256/ws)``), train sharded by a
DistributedSampler (``shuffle=False, drop_last=False``), test NOT sharded,
augmentation on train only. Same contract here, numpy end to end.
"""

from __future__ import annotations

import numpy as np

from tpu_ddp.data.augment import random_crop_flip
from tpu_ddp.data.cifar10 import (CIFAR10_MEAN, CIFAR10_STD, load_cifar10,
                                  normalize)
from tpu_ddp.data.sampler import DistributedShardSampler
from tpu_ddp.utils.config import SEED


class DataLoader:
    """Iterates (normalized f32 NHWC images, i32 labels) batches.

    Augmentation RNG is seeded per (seed, epoch) so every run — and every
    replica, which matters because each replica loads only its own shard —
    is deterministic; call :meth:`set_epoch` like the reference does with
    ``train_loader.sampler.set_epoch(epoch)`` (part2/part2b/main.py:189).
    """

    def __init__(
        self,
        images_u8: np.ndarray,
        labels: np.ndarray,
        batch_size: int,
        sampler: DistributedShardSampler | None = None,
        augment: bool = False,
        seed: int = SEED,
        mean: np.ndarray = CIFAR10_MEAN,
        std: np.ndarray = CIFAR10_STD,
        with_weights: bool = False,
    ):
        self.images_u8 = images_u8
        self.labels = np.asarray(labels, dtype=np.int32)
        self.batch_size = batch_size
        self.sampler = sampler
        self.augment = augment
        self.seed = seed
        self.epoch = 0
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        # True -> yield (images, labels, weights) triples, weight 0 on
        # sampler wrap-padding rows (the process-sharded eval contract).
        self.with_weights = with_weights

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        if self.sampler is not None:
            self.sampler.set_epoch(epoch)

    def __len__(self) -> int:
        n = len(self.sampler) if self.sampler is not None \
            else len(self.labels)
        # drop_last=False everywhere in the reference (part1/main.py:36-41):
        # final short batch is kept.
        return (n + self.batch_size - 1) // self.batch_size

    def __iter__(self):
        if self.sampler is not None:
            idx, valid = self.sampler.indices_and_valid()
        else:
            idx = np.arange(len(self.labels))
            valid = np.ones(len(idx), bool)
        rng = np.random.default_rng((self.seed, self.epoch))
        for start in range(0, len(idx), self.batch_size):
            sel = idx[start:start + self.batch_size]
            imgs = self.images_u8[sel]
            if self.augment:
                imgs = random_crop_flip(imgs, rng)
            batch = (normalize(imgs, self.mean, self.std),
                     self.labels[sel])
            if self.with_weights:
                # Sampler wrap-padding duplicates carry weight 0 — the
                # process-sharded eval contract (each example counted
                # once globally; tpu_ddp/train/engine.py:evaluate).
                batch += (valid[start:start + self.batch_size]
                          .astype(np.float32),)
            yield batch


def _pick_loader_cls(native: bool | None):
    """DataLoader or NativeDataLoader per the ``native`` arg /
    ``TPU_DDP_NATIVE_LOADER`` env, with fallback when no toolchain."""
    if native is None:
        from tpu_ddp.utils.config import _env_bool
        native = _env_bool("TPU_DDP_NATIVE_LOADER", False)
    if native:
        from tpu_ddp.data import native as native_mod
        if native_mod.available():
            return native_mod.NativeDataLoader
        print("[tpu_ddp.data] native loader requested but unavailable "
              f"({native_mod.build_error()}) -> numpy pipeline")
    return DataLoader


def create_data_loaders(
    rank: int = 0,
    world_size: int = 1,
    batch_size: int = 256,
    root: str | None = None,
    seed: int = SEED,
    synthetic_size: int | None = None,
    native: bool | None = None,
    shard_eval: bool = False,
):
    """(train_loader, test_loader), the reference's L4 facade.

    ``batch_size`` here is the PER-NODE batch, exactly as the reference
    passes ``int(256/world_size)`` in (part2/part2b/main.py:177). Train is
    sharded by rank with DistributedSampler semantics (``shuffle=False,
    drop_last=False``, part2/part2b/main.py:78-79); test is unsharded so
    every node evaluates the full set (part2/part2b/main.py:89-93) —
    unless ``shard_eval=True``, which shards the test set by rank too and
    yields (images, labels, weights) triples (wrap-padding rows weight 0)
    for ``Trainer.evaluate(sharded=True)`` in multi-process runs.
    """
    train_x, train_y, meta = load_cifar10(root, "train", synthetic_size)
    test_x, test_y, _ = load_cifar10(
        root, "test",
        None if synthetic_size is None else max(synthetic_size // 5, 10))
    if meta["synthetic"]:
        print("[tpu_ddp.data] CIFAR-10 not found on disk -> deterministic "
              "synthetic stand-in (set CIFAR10_DIR to use the real data)")
    sampler = None
    if world_size > 1:
        sampler = DistributedShardSampler(
            len(train_y), num_replicas=world_size, rank=rank,
            shuffle=False, drop_last=False)
    loader_cls = _pick_loader_cls(native)
    train_loader = loader_cls(train_x, train_y, batch_size,
                              sampler=sampler, augment=True, seed=seed)
    if shard_eval and world_size > 1:
        # Weights ride only the numpy DataLoader (eval is unaugmented;
        # the native pipeline's decode threads buy nothing here).
        test_loader = DataLoader(
            test_x, test_y, batch_size,
            sampler=DistributedShardSampler(
                len(test_y), num_replicas=world_size, rank=rank,
                shuffle=False, drop_last=False),
            augment=False, with_weights=True)
    else:
        test_loader = loader_cls(test_x, test_y, batch_size,
                                 augment=False)
    return train_loader, test_loader
