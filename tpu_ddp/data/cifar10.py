"""CIFAR-10 dataset: local load + normalization constants.

The reference downloads via ``torchvision.datasets.CIFAR10(download=True)``
(reference part1/main.py:34). This environment has no network egress, so we
(1) load the standard ``cifar-10-batches-py`` pickle format from any of the
usual on-disk locations, and (2) fall back to a deterministic synthetic
stand-in with identical shapes/dtypes so every part, test and benchmark runs
without the real data (clearly flagged in the returned metadata).

Normalization constants are the reference's exactly
(part1/main.py:20-21): mean [125.3, 123.0, 113.9]/255,
std [63.0, 62.1, 66.7]/255.
"""

from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

CIFAR10_MEAN = np.array([125.3, 123.0, 113.9], dtype=np.float32) / 255.0
CIFAR10_STD = np.array([63.0, 62.1, 66.7], dtype=np.float32) / 255.0

_TRAIN_FILES = [f"data_batch_{i}" for i in range(1, 6)]
_TEST_FILES = ["test_batch"]

# Candidate roots, including the reference's relative roots
# (part1/main.py:34 "./../data", part2b/main.py:76 "./../../data").
_SEARCH_ROOTS = [
    os.environ.get("CIFAR10_DIR", ""),
    "./data", "../data", "../../data",
    os.path.expanduser("~/data"),
    "/root/data", "/data", "/tmp/data",
]


def _find_batches_dir(root: str | None = None):
    roots = [root] if root else [r for r in _SEARCH_ROOTS if r]
    for r in roots:
        cand = os.path.join(r, "cifar-10-batches-py")
        if os.path.isdir(cand):
            return cand
        if os.path.isdir(r) and os.path.exists(
                os.path.join(r, "data_batch_1")):
            return r
        tgz = os.path.join(r, "cifar-10-python.tar.gz")
        if os.path.isfile(tgz):
            with tarfile.open(tgz) as tf:
                tf.extractall(r)
            return os.path.join(r, "cifar-10-batches-py")
    return None


def _load_pickled(batches_dir: str, files):
    images, labels = [], []
    for name in files:
        with open(os.path.join(batches_dir, name), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        # CHW-flat uint8 -> NHWC uint8 (we are NHWC-native for the TPU).
        arr = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        images.append(arr)
        labels.append(np.asarray(d[b"labels"], dtype=np.int32))
    return np.concatenate(images), np.concatenate(labels)


def _synthetic(split: str, n: int | None):
    """Deterministic stand-in: same shapes/dtypes/class balance as CIFAR-10.

    Images are class-conditional noise (mean shifted per class) so training
    CAN reduce loss, making convergence smoke-tests meaningful.
    """
    if n is None:
        n = 50_000 if split == "train" else 10_000
        n = int(os.environ.get("TPU_DDP_SYNTH_SIZE", n))
    # Class signatures come from a split-INDEPENDENT seed: train and test
    # must share them, or a model that learns the train classes scores
    # chance (or worse) on test and convergence artifacts are garbage.
    base = np.random.default_rng(0xC1FA8).normal(0, 40, size=(10, 1, 1, 3))
    rng = np.random.default_rng(0xC1FA8 + (1 if split == "train" else 2))
    labels = rng.integers(0, 10, size=n).astype(np.int32)
    images = rng.normal(128, 50, size=(n, 32, 32, 3))
    images = np.clip(images + base[labels], 0, 255).astype(np.uint8)
    return images, labels


def load_cifar10(root: str | None = None, split: str = "train",
                 synthetic_size: int | None = None):
    """Returns ``(images_u8_nhwc, labels_i32, meta)``.

    ``meta["synthetic"]`` tells callers whether the real dataset was found.
    """
    batches_dir = _find_batches_dir(root)
    if batches_dir is not None:
        files = _TRAIN_FILES if split == "train" else _TEST_FILES
        images, labels = _load_pickled(batches_dir, files)
        return images, labels, {"synthetic": False, "dir": batches_dir}
    images, labels = _synthetic(split, synthetic_size)
    return images, labels, {"synthetic": True, "dir": None}


def normalize(images_u8: np.ndarray, mean: np.ndarray = CIFAR10_MEAN,
              std: np.ndarray = CIFAR10_STD) -> np.ndarray:
    """uint8 NHWC -> normalized float32 (ToTensor + Normalize,
    reference part1/main.py:20-31). ``mean``/``std`` are on the x/255
    scale; defaults are CIFAR-10's."""
    x = images_u8.astype(np.float32) / 255.0
    return (x - mean) / std
