"""Deterministic shard sampler with torch ``DistributedSampler`` parity.

The reference shards CIFAR-10 with ``DistributedSampler(num_replicas=ws,
rank=rank, shuffle=False, drop_last=False)`` (reference
part2/part2b/main.py:78-79) plus a per-epoch ``sampler.set_epoch(epoch)``
hook (part2/part2b/main.py:189). Torch's exact semantics, reproduced here:

- base order: ``range(n)`` when ``shuffle=False``; a permutation from a
  generator seeded with ``seed + epoch`` when ``shuffle=True``;
- ``drop_last=False`` pads to ``ceil(n/ws)*ws`` by wrapping from the start
  of the index list (SURVEY.md §7 "hard parts");
- rank r takes the strided slice ``indices[r::ws]``.

Parity is asserted against ``torch.utils.data.DistributedSampler`` in
tests/test_sampler.py (shuffle=False case is bit-exact; shuffled order uses
numpy's RNG, so only the partition property is asserted there).
"""

from __future__ import annotations

import math

import numpy as np


class DistributedShardSampler:
    def __init__(
        self,
        dataset_len: int,
        num_replicas: int = 1,
        rank: int = 0,
        shuffle: bool = False,
        seed: int = 0,
        drop_last: bool = False,
    ):
        if not (0 <= rank < num_replicas):
            raise ValueError(f"rank {rank} out of range [0, {num_replicas})")
        self.dataset_len = dataset_len
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        if drop_last and dataset_len % num_replicas:
            self.num_samples = dataset_len // num_replicas
        else:
            self.num_samples = math.ceil(dataset_len / num_replicas)
        self.total_size = self.num_samples * num_replicas

    def set_epoch(self, epoch: int) -> None:
        """Per-epoch reshuffle hook (reference part2/part2b/main.py:189)."""
        self.epoch = epoch

    def indices(self) -> np.ndarray:
        return self.indices_and_valid()[0]

    def indices_and_valid(self) -> tuple[np.ndarray, np.ndarray]:
        """(this rank's indices, bool validity mask).

        ``valid[i]`` is False exactly for the wrap-padding duplicates
        (positions past ``dataset_len`` in the padded global list) —
        the rows a process-sharded EVAL must weight 0 so each test
        example counts once globally, while every rank still yields
        equal-shaped shards (the multi-process global-array assembly
        contract, tpu_ddp/parallel/mesh.py:put_sharded)."""
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            idx = rng.permutation(self.dataset_len)
        else:
            idx = np.arange(self.dataset_len)
        if not self.drop_last and len(idx) < self.total_size:
            # Pad by wrapping from the start (torch DistributedSampler
            # drop_last=False behavior).
            pad = self.total_size - len(idx)
            reps = math.ceil(pad / len(idx))
            idx = np.concatenate([idx, np.tile(idx, reps)[:pad]])
        else:
            idx = idx[: self.total_size]
        valid = np.arange(self.total_size) < self.dataset_len
        return (idx[self.rank :: self.num_replicas],
                valid[self.rank :: self.num_replicas])

    def __iter__(self):
        return iter(self.indices())

    def __len__(self) -> int:
        return self.num_samples
