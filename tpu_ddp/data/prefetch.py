"""Device prefetch: keep the next batches' host->device transfers in flight.

The reference overlaps host work with compute through DataLoader workers +
``pin_memory=True`` (reference part1/main.py:36-41). The TPU-native
equivalent is to issue ``device_put`` for upcoming batches before the
current step completes — JAX transfers are asynchronous, so a small
lookahead hides the PCIe/tunnel latency behind the device step.
"""

from __future__ import annotations

import collections
from typing import Callable, Iterable, Iterator


def prefetch_to_device(batches: Iterable, put_fn: Callable, depth: int = 2
                       ) -> Iterator:
    """Yield ``put_fn(batch)`` results with ``depth`` transfers in flight.

    ``put_fn`` is typically ``Trainer.put_batch`` applied to the loader's
    ``(images, labels)`` tuples; ``depth=0`` degenerates to plain mapping
    (no lookahead). A negative depth raises — it would silently become
    the no-lookahead mapping, masking a config typo.

    Composition with the engine's pipelines (round 6):

    - **dispatch pipeline** (``cfg.dispatch_depth``, train/pipeline.py):
      orthogonal and complementary. Prefetch overlaps host->device
      TRANSFERS with compute; the dispatch window overlaps host-side
      RESULT HARVESTING with compute. The epoch loop runs both —
      transfers of batch i+depth are in flight while step i executes
      and step i-dispatch_depth's loss is being accounted.
    - **fault injection** (resilience/chaos.py): only faults that
      poison a batch host-side on an exact step (``nan-grad``) disable
      prefetch — the poisoning must happen before the transfer.
      Passive injectors (slow-rank, hard-exit, corrupt-ckpt,
      stalled-step) compose with it (``FaultInjector.poisons_batches``).
    - **grouped dispatch** (``cfg.steps_per_dispatch > 1``): not
      composed; the grouped loop stages K batches per call via
      ``put_batches`` instead.
    """
    if depth < 0:
        raise ValueError(f"prefetch depth must be >= 0, got {depth} "
                         "(0 = no lookahead)")
    if depth == 0:
        for b in batches:
            yield put_fn(*b) if isinstance(b, tuple) else put_fn(b)
        return
    it = iter(batches)
    queue = collections.deque()
    try:
        while len(queue) < depth:
            b = next(it)
            queue.append(put_fn(*b) if isinstance(b, tuple) else put_fn(b))
    except StopIteration:
        pass
    while queue:
        yield queue.popleft()
        try:
            b = next(it)
            queue.append(put_fn(*b) if isinstance(b, tuple) else put_fn(b))
        except StopIteration:
            continue
