"""Device prefetch: keep the next batches' host->device transfers in flight.

The reference overlaps host work with compute through DataLoader workers +
``pin_memory=True`` (reference part1/main.py:36-41). The TPU-native
equivalent is to issue ``device_put`` for upcoming batches before the
current step completes — JAX transfers are asynchronous, so a small
lookahead hides the PCIe/tunnel latency behind the device step.
"""

from __future__ import annotations

import collections
from typing import Callable, Iterable, Iterator


def prefetch_to_device(batches: Iterable, put_fn: Callable, depth: int = 2
                       ) -> Iterator:
    """Yield ``put_fn(batch)`` results with ``depth`` transfers in flight.

    ``put_fn`` is typically ``Trainer.put_batch`` applied to the loader's
    ``(images, labels)`` tuples; with ``depth=0`` this degenerates to plain
    mapping (no lookahead).
    """
    if depth <= 0:
        for b in batches:
            yield put_fn(*b) if isinstance(b, tuple) else put_fn(b)
        return
    it = iter(batches)
    queue = collections.deque()
    try:
        while len(queue) < depth:
            b = next(it)
            queue.append(put_fn(*b) if isinstance(b, tuple) else put_fn(b))
    except StopIteration:
        pass
    while queue:
        yield queue.popleft()
        try:
            b = next(it)
            queue.append(put_fn(*b) if isinstance(b, tuple) else put_fn(b))
        except StopIteration:
            continue
