"""Decoder-only transformer LM — the long-context model family.

No reference counterpart (the reference ships only VGG,
part1/model.py:49-50); this family exists because long-context training is
first-class in this framework. Same conventions as the rest of the zoo:
functional (init/apply over a pytree), bf16 compute with f32 params and
f32 softmax/LN statistics, static config on a frozen dataclass.

Sequence parallelism: ``apply`` takes the LOCAL sequence chunk. When
``sp_axis``/``sp_size`` are configured (and apply runs inside a
``shard_map`` over that axis), attention runs as ring attention over the
``sp`` mesh axis (tpu_ddp/parallel/ring_attention.py) and RoPE positions
are offset by the chunk's global start — so the model computes EXACTLY the
same function as the single-device configuration (tested in
tests/test_ring_attention.py).

Tensor parallelism: when ``tp_axis``/``tp_size`` are configured, each
block's parameters arrive as mp-shards (attention heads and the MLP hidden
axis split over ``tp_size`` — :meth:`TransformerLM.param_specs` is the
authoritative layout) and the block computes with the Megatron column/row
sandwich (tpu_ddp/parallel/tensor_parallel.py): two ``psum``s per block,
everything else replicated. Composes with sequence parallelism — ring
attention rotates K/V over ``sp`` within each head shard.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from jax.sharding import PartitionSpec as P

from tpu_ddp.parallel.ring_attention import attend
from tpu_ddp.parallel.tensor_parallel import tp_input, tp_output


def _normal(key, shape, std, dtype):
    return std * jax.random.normal(key, shape, dtype)


def rope(x, positions, base: float = 10000.0):
    """Rotary position embedding. x: (B, L, H, D); positions: (L,)
    shared across the batch (training / offline decode), or (B, L)
    per-row (continuous-batching decode, where every live sequence
    sits at its own offset — tpu_ddp/serve/). The (L,) path is
    bit-identical to the original shared-position formulation."""
    d = x.shape[-1]
    half = d // 2
    freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[..., None].astype(jnp.float32) * freqs
    cos = jnp.cos(angles)[..., None, :]  # (..., L, 1, half)
    sin = jnp.sin(angles)[..., None, :]
    if angles.ndim == 2:  # shared (L,) positions: add the batch dim
        cos, sin = cos[None], sin[None]
    x1, x2 = x[..., :half], x[..., half:]
    x32_1, x32_2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    return jnp.concatenate(
        [x32_1 * cos - x32_2 * sin, x32_2 * cos + x32_1 * sin],
        axis=-1).astype(x.dtype)


def layer_norm(x, scale, bias, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    return ((x32 - mu) * lax.rsqrt(var + eps) * scale + bias).astype(x.dtype)


@dataclasses.dataclass(frozen=True)
class TransformerLM:
    """GPT-style pre-LN decoder. Causal by construction."""

    name: str = "TransformerLM"
    vocab_size: int = 32000
    num_layers: int = 4
    num_heads: int = 8
    # Grouped-query attention (Ainslie et al., arXiv:2305.13245): K/V get
    # ``num_kv_heads`` heads shared by groups of Q heads. None -> MHA
    # (= num_heads; the "wqkv" param layout is kept bit-compatible).
    # num_kv_heads=1 is multi-query attention. The KV cache shrinks by
    # num_heads/num_kv_heads (models/generate.py init_cache).
    num_kv_heads: int | None = None
    d_model: int = 512
    d_ff: int = 2048
    max_seq_len: int = 2048
    compute_dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    # Sequence parallelism: mesh axis name/extent the LOCAL chunk lives on.
    # ``sp_mode`` picks the scheme: "ring" (K/V rotation via ppermute,
    # tpu_ddp/parallel/ring_attention.py) or "ulysses" (all-to-all head
    # re-sharding, tpu_ddp/parallel/ulysses.py). Both are exact.
    sp_axis: str | None = None
    sp_size: int = 1
    sp_mode: str = "ring"
    # Tensor parallelism: mesh axis name/extent block params are sharded on.
    tp_axis: str | None = None
    tp_size: int = 1
    # Mixture of experts: when > 0 every block's MLP is a routed MoE
    # with this many experts (tpu_ddp/parallel/moe.py); top_k=1 is
    # Switch routing, top_k=2 the GShard scheme.
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    moe_top_k: int = 1
    # Expert parallelism: mesh axis name/extent the expert axis shards on.
    ep_axis: str | None = None
    ep_size: int = 1
    # Use the Pallas flash-attention kernel
    # (tpu_ddp/ops/pallas/flash_attention.py). Honored when attention is
    # local: sp==1, or sp>1 with sp_mode="ulysses" (the kernel runs on
    # the all-to-all-gathered sequence). The ring path (sp>1, "ring")
    # has its own blockwise online softmax and ignores this flag.
    use_flash: bool = False
    # Memory policy (tpu_ddp/memory/policy.py): "blocks" remats each
    # transformer block in the backward pass — trades ~num_layers x
    # activation memory for one extra forward, the standard
    # long-context memory lever on HBM-bound chips; "dots" saves the
    # matmul outputs and recomputes LN/softmax/GELU ("conv_stages"
    # degrades to "blocks" here — no conv stages). act_dtype is the
    # saved dtype of the inter-block residual stream.
    remat: str = "none"
    act_dtype: str = "compute"
    # DEPRECATED alias for remat="blocks" (the pre-policy field); kept
    # functional for back-compat, ignored when ``remat`` is set.
    remat_blocks: bool = False
    # Dropout on the embedding and each block's two residual branches.
    # Active only when the caller passes an ``rng`` to apply/trunk (the
    # trainer does, per step); eval/generate never pass one, so they
    # are deterministic with no mode flag.
    dropout_rate: float = 0.0

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads

    @property
    def kv_heads(self) -> int:
        return (self.num_kv_heads if self.num_kv_heads is not None
                else self.num_heads)

    @property
    def is_gqa(self) -> bool:
        return self.kv_heads != self.num_heads

    @property
    def remat_policy(self) -> str:
        """Effective remat mode, honoring the deprecated
        ``remat_blocks`` alias (``remat`` wins when set)."""
        if self.remat != "none":
            return self.remat
        return "blocks" if self.remat_blocks else "none"

    def __post_init__(self):
        from tpu_ddp.memory import validate_act_dtype, validate_remat
        validate_remat(self.remat)
        validate_act_dtype(self.act_dtype)
        if not 0.0 <= self.dropout_rate < 1.0:
            raise ValueError(f"dropout_rate must be in [0, 1), got "
                             f"{self.dropout_rate}")
        if self.kv_heads < 1:
            raise ValueError(f"num_kv_heads must be >= 1, got "
                             f"{self.kv_heads}")
        if self.num_heads % self.kv_heads:
            raise ValueError(
                f"num_heads={self.num_heads} not divisible by "
                f"num_kv_heads={self.kv_heads}")

    @property
    def _tp(self) -> int:
        return self.tp_size if self.tp_axis is not None else 1

    @property
    def _ep(self) -> int:
        return self.ep_size if self.ep_axis is not None else 1

    # ---- parameters ----------------------------------------------------

    def init(self, key) -> dict:
        """GLOBAL parameter pytree (sharding is the trainer's job).

        Layouts are chosen so tensor-parallel sharding is a clean axis
        split (:meth:`param_specs`): ``wqkv`` is (dm, 3, heads, head_dim)
        and ``wo`` is (heads, head_dim, dm) — the head axis shards over
        ``tp``; ``w1``/``w2`` shard on the ``d_ff`` axis.
        """
        dm, dff, v = self.d_model, self.d_ff, self.vocab_size
        h, hd = self.num_heads, self.head_dim
        std = 0.02
        keys = iter(jax.random.split(key, 4 + 8 * self.num_layers))
        params = {
            "embed": _normal(next(keys), (v, dm), std, self.param_dtype),
            "ln_f": {"scale": jnp.ones((dm,), self.param_dtype),
                     "bias": jnp.zeros((dm,), self.param_dtype)},
            "head": _normal(next(keys), (dm, v), std, self.param_dtype),
        }
        blocks = []
        E = self.moe_experts
        for _ in range(self.num_layers):
            blk = {
                "ln1": {"scale": jnp.ones((dm,), self.param_dtype),
                        "bias": jnp.zeros((dm,), self.param_dtype)},
                "wo": _normal(next(keys), (h, hd, dm), std,
                              self.param_dtype),
                "ln2": {"scale": jnp.ones((dm,), self.param_dtype),
                        "bias": jnp.zeros((dm,), self.param_dtype)},
            }
            if self.is_gqa:
                # Separate Q and (smaller) KV projections; the fused
                # "wqkv" layout stays reserved for MHA back-compat.
                blk["wq"] = _normal(next(keys), (dm, h, hd), std,
                                    self.param_dtype)
                blk["wkv"] = _normal(next(keys), (dm, 2, self.kv_heads,
                                                  hd), std,
                                     self.param_dtype)
            else:
                blk["wqkv"] = _normal(next(keys), (dm, 3, h, hd), std,
                                      self.param_dtype)
            if E:
                # MoE MLP: stacked expert weights + a router.
                blk["router"] = _normal(next(keys), (dm, E), std,
                                        self.param_dtype)
                blk["w1"] = _normal(next(keys), (E, dm, dff), std,
                                    self.param_dtype)
                blk["w2"] = _normal(next(keys), (E, dff, dm), std,
                                    self.param_dtype)
            else:
                blk["w1"] = _normal(next(keys), (dm, dff), std,
                                    self.param_dtype)
                blk["w2"] = _normal(next(keys), (dff, dm), std,
                                    self.param_dtype)
            blocks.append(blk)
        params["blocks"] = tuple(blocks)
        return params

    def param_specs(self) -> dict:
        """Pytree of ``PartitionSpec``s mirroring :meth:`init`'s tree.

        The authoritative tensor-parallel layout: attention head axis and
        MLP hidden axis shard over ``tp_axis``; everything else (LayerNorm,
        embeddings, LM head) is replicated. With ``tp_size == 1`` every
        leaf is fully replicated.
        """
        tp = self.tp_axis if self._tp > 1 else None
        ep = self.ep_axis if self._ep > 1 else None
        ln = {"scale": P(), "bias": P()}
        blk = {
            "ln1": dict(ln),
            "wo": P(tp, None, None),
            "ln2": dict(ln),
        }
        if self.is_gqa:
            blk["wq"] = P(None, tp, None)
            blk["wkv"] = P(None, None, tp, None)
        else:
            blk["wqkv"] = P(None, None, tp, None)
        if self.moe_experts:
            blk["router"] = P()
            blk["w1"] = P(ep, None, tp)
            blk["w2"] = P(ep, tp, None)
        else:
            blk["w1"] = P(None, tp)
            blk["w2"] = P(tp, None)
        return {
            "embed": P(),
            "ln_f": dict(ln),
            "head": P(),
            "blocks": tuple(dict(blk) for _ in range(self.num_layers)),
        }

    # ---- forward -------------------------------------------------------

    def check_seq_len(self, local_len: int) -> None:
        """Validate the GLOBAL sequence length (local x sp under
        sequence parallelism) against ``max_seq_len``. The ONE home of
        this invariant — the dense trunk and the pipeline entry points
        (tpu_ddp/parallel/pipeline.py) both call it, so the sp-aware
        length accounting cannot drift between the two paths."""
        sp = self.sp_size if self.sp_axis is not None else 1
        if local_len * sp > self.max_seq_len:
            raise ValueError(
                f"global sequence length {local_len * sp} (local "
                f"{local_len} x sp {sp}) exceeds "
                f"max_seq_len={self.max_seq_len}")

    def _positions(self, lc: int):
        """Global positions of the local chunk (chunk offset under sp)."""
        if self.sp_axis is not None and self.sp_size > 1:
            start = lax.axis_index(self.sp_axis) * lc
        else:
            start = 0
        return start + jnp.arange(lc)

    def _tp_in(self, x):
        """Megatron ``f`` before a column-parallel matmul (no-op sans tp).

        Sits AFTER LayerNorm so the psum'd backward makes LN/embedding/
        residual gradients exact and replicated on every tp shard."""
        if self._tp > 1:
            return tp_input(x, self.tp_axis)
        return x

    def _tp_out(self, x):
        """Megatron ``g`` after a row-parallel matmul (no-op sans tp)."""
        if self._tp > 1:
            return tp_output(x, self.tp_axis)
        return x

    def _dropout(self, x, rng):
        """Inverted dropout; identity when inactive (rate 0 or no rng).
        The branch is static, so inactive configurations compile to the
        bare graph."""
        if rng is None or self.dropout_rate <= 0.0:
            return x
        keep = 1.0 - self.dropout_rate
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)

    def apply(self, params, tokens, rng=None):
        """tokens: (B, L_local) int32 -> logits (B, L_local, V) float32.

        Under tensor parallelism ``params`` holds this shard's slices
        (heads and d_ff split ``tp_size``-ways, :meth:`param_specs`); the
        residual stream stays replicated, with one ``psum`` after each of
        the two row-parallel projections. ``rng`` activates dropout
        (training); omit it for deterministic eval.
        """
        return self.apply_with_aux(params, tokens, rng=rng)[0]

    def apply_with_aux(self, params, tokens, rng=None):
        """Like :meth:`apply`, additionally returning the mean Switch
        load-balance auxiliary loss over MoE blocks (0.0 when dense)."""
        x, aux = self.trunk_with_aux(params, tokens, rng=rng)
        return self.project(params, x), aux

    def project(self, params, x):
        """Vocabulary projection of post-LN activations — the ONE place
        the head matmul's precision is decided. Routed through
        :func:`tpu_ddp.ops.quant.qdot` so an int8-quantized serving
        tree (decode_quant, ops/quant.py) runs the fused weight-only
        matmul; a plain fp tree traces the identical dot."""
        from tpu_ddp.ops.quant import qdot
        logits = qdot(x, params["head"], self.compute_dtype)
        return logits.astype(jnp.float32)

    def trunk_with_aux(self, params, tokens, rng=None, stats=None):
        """Everything but the vocabulary projection: embed -> blocks ->
        final LayerNorm, returning ((B, L, dm) activations, aux). The
        split exists so the LM loss can fuse the head matmul into a
        chunked-vocab cross-entropy without materializing (T, V) logits
        (tpu_ddp/ops/loss.py chunked_vocab_cross_entropy). This is the
        single full-forward implementation — :meth:`apply` /
        :meth:`apply_with_aux` wrap it, so validation lives here once.

        ``rng``: dropout key (pre-decorrelated across data shards by the
        trainer); None disables dropout. ``stats``: optional mutable
        list collecting each MoE block's routing-health dict
        (tpu_ddp/parallel/moe.py routing_stats) — forces the direct
        block path (no remat), so pass it only on diagnostic runs."""
        cd = self.compute_dtype
        lc = tokens.shape[1]
        self.check_seq_len(lc)
        pos = self._positions(lc)
        x = params["embed"][tokens].astype(cd)
        if rng is not None:
            x = self._dropout(x, jax.random.fold_in(rng, self.num_layers))
        aux = jnp.float32(0.0)
        from tpu_ddp.memory import cast_saved, effective_remat, wrap_stage
        remat = effective_remat(self.remat_policy, "attn")
        if stats is not None or (remat == "none"
                                 and self.act_dtype == "compute"):
            def blk_fn(blk, x, pos, r):
                return self.block_apply_aux(blk, x, pos, r, stats=stats)
        else:
            # _block_entry re-enters compute_dtype, so the boundary
            # cast below only changes what autodiff SAVES.
            blk_fn = wrap_stage(self._block_entry, remat)
        for i, blk in enumerate(params["blocks"]):
            r = jax.random.fold_in(rng, i) if rng is not None else None
            x, a = blk_fn(blk, cast_saved(x, self.act_dtype, cd), pos, r)
            aux = aux + a
        x = layer_norm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
        return x, aux / max(self.num_layers, 1)

    def block_apply(self, blk, x, pos):
        """One transformer block: (B, L, dm) -> (B, L, dm).

        Factored out so the pipeline engine can ``lax.scan`` it over a
        stage's stacked layer slice (tpu_ddp/parallel/pipeline.py) while
        the dense path loops over the blocks tuple. For MoE blocks the
        router's auxiliary loss is discarded here; use
        :meth:`block_apply_aux` / :meth:`apply_with_aux` to train with
        the load-balance regularizer.
        """
        return self.block_apply_aux(blk, x, pos)[0]

    def qkv_proj(self, blk, y, pos):
        """Projected + RoPE'd q (B, L, H/tp, hd) and k/v (B, L, KV/tp,
        hd) from normalized input ``y`` (``_tp_in`` already applied by
        the caller under tensor parallelism). Column-parallel: local
        heads only, zero communication. One fused "wqkv" matmul for MHA;
        separate "wq"/"wkv" for GQA (KV/tp heads, the smaller
        projection). Shared by training (block_apply_aux) and KV-cache
        decode (models/generate.py). The projections route through
        :func:`tpu_ddp.ops.quant.qdot` (identical trace for fp trees;
        fused int8 matmul for a quantized serving tree)."""
        from tpu_ddp.ops.quant import qdot
        cd = self.compute_dtype
        b, lc, hd = y.shape[0], y.shape[1], self.head_dim
        h_loc = self.num_heads // self._tp
        # Dispatch on the STATIC config, not the params keys: a config/
        # checkpoint layout mismatch then fails immediately with a
        # KeyError instead of silently training the other scheme.
        if not self.is_gqa:
            qkv = qdot(y, blk["wqkv"], cd, reshape=(self.d_model, -1))
            qkv = qkv.astype(cd).reshape(b, lc, 3, h_loc, hd)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        else:
            kv_loc = self.kv_heads // self._tp
            q = qdot(y, blk["wq"], cd, reshape=(self.d_model, -1))
            q = q.astype(cd).reshape(b, lc, h_loc, hd)
            kvp = qdot(y, blk["wkv"], cd, reshape=(self.d_model, -1))
            kvp = kvp.astype(cd).reshape(b, lc, 2, kv_loc, hd)
            k, v = kvp[:, :, 0], kvp[:, :, 1]
        return rope(q, pos), rope(k, pos), v

    def _block_entry(self, blk, x, pos, rng=None):
        """:meth:`block_apply_aux` with the residual stream re-entering
        ``compute_dtype`` — the checkpoint-region entry point under a
        memory policy (the saved boundary input is in ``act_dtype``,
        the block arithmetic is not)."""
        return self.block_apply_aux(blk, x.astype(self.compute_dtype),
                                    pos, rng)

    def block_apply_aux(self, blk, x, pos, rng=None, stats=None):
        cd = self.compute_dtype
        b, lc = x.shape[0], x.shape[1]
        h_loc, hd = self.num_heads // self._tp, self.head_dim
        r1 = r2 = None
        if rng is not None:
            # Branch keys derive from this block's key; the trainer
            # already decorrelated ``rng`` across data shards (and left
            # it IDENTICAL across mp shards — the residual stream is
            # replicated over tp, so its mask must be too).
            r1, r2 = jax.random.split(rng)
        y = layer_norm(x, blk["ln1"]["scale"], blk["ln1"]["bias"])
        # Under GQA k/v stay at KV-head width end to end: every attend()
        # path contracts grouped — ring/blockwise/full in jnp, and the
        # flash kernel indexes K/V blocks by q-head group natively — so
        # collectives, memory and score math all carry KV-width bytes.
        q, k, v = self.qkv_proj(blk, self._tp_in(y), pos)
        o = attend(q, k, v, causal=True, axis_name=self.sp_axis,
                   axis_size=self.sp_size, flash=self.use_flash,
                   mode=self.sp_mode)
        # Row-parallel output projection: partial sums psum'd over tp.
        wo = blk["wo"].astype(cd).reshape(h_loc * hd, self.d_model)
        o = self._tp_out(jnp.dot(
            o.reshape(b, lc, h_loc * hd), wo,
            preferred_element_type=jnp.float32)).astype(cd)
        x = x + self._dropout(o, r1)
        y = layer_norm(x, blk["ln2"]["scale"], blk["ln2"]["bias"])
        if self.moe_experts:
            from tpu_ddp.parallel.moe import moe_mlp
            y, aux = moe_mlp(
                y, blk["router"], blk["w1"], blk["w2"],
                num_experts=self.moe_experts,
                capacity_factor=self.moe_capacity_factor,
                top_k=self.moe_top_k,
                ep_axis=self.ep_axis or "ep", ep_size=self._ep,
                tp_in=self._tp_in, tp_out=self._tp_out, stats=stats)
            return x + self._dropout(y, r2), aux
        # Column-parallel up-projection (local d_ff slice) ...
        y = jnp.dot(self._tp_in(y), blk["w1"].astype(cd),
                    preferred_element_type=jnp.float32)
        y = jax.nn.gelu(y.astype(jnp.float32)).astype(cd)
        # ... row-parallel down-projection, psum'd.
        y = self._tp_out(jnp.dot(
            y, blk["w2"].astype(cd),
            preferred_element_type=jnp.float32)).astype(cd)
        return x + self._dropout(y, r2), jnp.float32(0.0)

    def route_stats(self, params, tokens):
        """Diagnostic routing-health probe: one deterministic trunk
        pass (no dropout) collecting each MoE block's routing counters
        — list of dicts with ``dropped_frac``, ``expert_load`` (E,),
        and ``imbalance`` (tpu_ddp/parallel/moe.py routing_stats), one
        per layer, [] for a dense model. Routing is per-token and
        partition-independent, so callers holding sharded training
        params strip the partition axes and run this on the canonical
        tree (tpu_ddp/train/lm.py LMTrainer.route_stats does exactly
        that)."""
        if not self.moe_experts:
            return []
        stats: list = []
        self.trunk_with_aux(params, tokens, rng=None, stats=stats)
        return stats

    def head_apply(self, params, x):
        """Final LayerNorm + LM head: (B, L, dm) -> (B, L, V) float32."""
        x = layer_norm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
        return self.project(params, x)

    def num_params(self, params=None, key=None) -> int:
        if params is None:
            params = self.init(key if key is not None else jax.random.key(0))
        return sum(int(p.size) for p in jax.tree.leaves(params))

    def with_sequence_parallel(self, axis_name: str, axis_size: int,
                               mode: str = "ring") -> "TransformerLM":
        if mode not in ("ring", "ulysses"):
            raise ValueError(f"unknown sequence-parallel mode {mode!r}; "
                             "expected 'ring' or 'ulysses'")
        if mode == "ulysses" and (self.num_heads // self._tp) % axis_size:
            raise ValueError(
                f"ulysses needs (num_heads/tp) % sp == 0 (got heads="
                f"{self.num_heads}/{self._tp} per tp shard, sp={axis_size})")
        return dataclasses.replace(self, sp_axis=axis_name,
                                   sp_size=axis_size, sp_mode=mode)

    def with_tensor_parallel(self, axis_name: str,
                             axis_size: int) -> "TransformerLM":
        if self.num_heads % axis_size:
            raise ValueError(f"num_heads={self.num_heads} not divisible by "
                             f"tp={axis_size}")
        if self.kv_heads % axis_size:
            raise ValueError(f"num_kv_heads={self.kv_heads} not divisible "
                             f"by tp={axis_size}")
        if self.d_ff % axis_size:
            raise ValueError(f"d_ff={self.d_ff} not divisible by "
                             f"tp={axis_size}")
        # Re-validate an already-configured Ulysses sp against the PER-TP
        # head count (trainers apply sp before tp, so the sp-time check
        # ran with tp=1) — fail at construction, not inside the jit trace.
        if (self.sp_mode == "ulysses" and self.sp_size > 1
                and (self.num_heads // axis_size) % self.sp_size):
            raise ValueError(
                f"ulysses needs (num_heads/tp) % sp == 0 (got heads="
                f"{self.num_heads}/{axis_size} per tp shard, "
                f"sp={self.sp_size})")
        return dataclasses.replace(self, tp_axis=axis_name,
                                   tp_size=axis_size)

    def with_expert_parallel(self, axis_name: str,
                             axis_size: int) -> "TransformerLM":
        if not self.moe_experts:
            raise ValueError("expert parallelism requires a MoE model "
                             "(moe_experts > 0)")
        if self.moe_experts % axis_size:
            raise ValueError(f"moe_experts={self.moe_experts} not "
                             f"divisible by ep={axis_size}")
        return dataclasses.replace(self, ep_axis=axis_name,
                                   ep_size=axis_size)


def make_transformer(name: str = "TransformerLM-small",
                     **kwargs) -> TransformerLM:
    presets = {
        "TransformerLM-tiny": dict(num_layers=2, num_heads=4, d_model=128,
                                   d_ff=512, vocab_size=1024),
        "TransformerLM-small": dict(num_layers=4, num_heads=8, d_model=512,
                                    d_ff=2048, vocab_size=32000),
        "TransformerLM-base": dict(num_layers=12, num_heads=12, d_model=768,
                                   d_ff=3072, vocab_size=32000),
        # MXU-saturating single-chip bench config (~740M params): every
        # matmul has K,N >= 2048 and head_dim 128 fills the MXU tile
        # exactly; fits a 16 GB v5e with f32 AdamW states + remat.
        "TransformerLM-large": dict(num_layers=12, num_heads=16,
                                    d_model=2048, d_ff=8192,
                                    vocab_size=32000, remat="blocks"),
        # Long-context zoo entries (DESIGN.md §27): tiny compute dims
        # so CPU tests and the long-context sweep trace fast, with a
        # max_seq_len far past what one hot KV tier holds — prompt
        # length, not model size, is what these exist to stress.
        "TransformerLM-tiny-8k": dict(num_layers=2, num_heads=4,
                                      d_model=128, d_ff=512,
                                      vocab_size=1024,
                                      max_seq_len=8192),
        "TransformerLM-small-32k": dict(num_layers=4, num_heads=8,
                                        d_model=512, d_ff=2048,
                                        vocab_size=32000,
                                        max_seq_len=32768),
        # MoE zoo family (DESIGN.md §28): Switch (top-1) at the small
        # end, GShard (top-2) at scale. d_ff is the PER-EXPERT hidden
        # width, so param count grows ~linearly in moe_experts while
        # per-token FLOPs track top_k — the capability-per-FLOP trade
        # the family exists to buy (experiments/moe_sweep.json).
        "TransformerLM-moe-tiny": dict(num_layers=2, num_heads=4,
                                       d_model=128, d_ff=256,
                                       vocab_size=1024, moe_experts=4,
                                       moe_top_k=1,
                                       moe_capacity_factor=1.25),
        "TransformerLM-moe-small": dict(num_layers=4, num_heads=8,
                                        d_model=512, d_ff=1024,
                                        vocab_size=32000, moe_experts=8,
                                        moe_top_k=2,
                                        moe_capacity_factor=1.25),
        # LM-large's sparse sibling: same trunk geometry, 16 experts of
        # half the dense d_ff — ~4.3x the dense family's MLP params at
        # top-2 per-token compute close to dense (cap algebra in
        # DESIGN.md §28); remat="blocks" like its dense twin.
        "TransformerLM-moe-large": dict(num_layers=12, num_heads=16,
                                        d_model=2048, d_ff=4096,
                                        vocab_size=32000,
                                        moe_experts=16, moe_top_k=2,
                                        moe_capacity_factor=1.25,
                                        remat="blocks"),
    }
    if name not in presets:
        raise ValueError(f"unknown transformer preset {name!r}; "
                         f"available: {sorted(presets)}")
    cfg = dict(presets[name])
    cfg.update(kwargs)
    return TransformerLM(name=name, **cfg)
