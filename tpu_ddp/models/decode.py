"""Shared KV-cache decode core — ONE home for the incremental-attention
math, used by both :func:`tpu_ddp.models.generate.generate` (batch
offline sampling) and the continuous-batching serving engine
(tpu_ddp/serve/). The two callers differ only in cache LAYOUT (one
contiguous ``(B, max_len, KV, hd)`` buffer per block vs the serve
engine's block-paged pool, tpu_ddp/serve/kv_pool.py); the projection,
attention, and MLP math is these functions, so "the engine decodes the
same distribution the trainer optimized" is a property of one module,
tested once (tests/test_generate.py exactness vs ``apply``,
tests/test_serve.py engine-vs-generate parity).

Position handling is the one generalization over the original
``generate.py`` internals: :func:`attend_cached` accepts per-batch-row
query positions ``(B, Lq)`` in addition to the shared ``(Lq,)`` form,
because under continuous batching every live sequence sits at its own
offset (models/transformer.py ``rope`` accepts the same two forms).
The ``(Lq,)`` path traces the exact pre-refactor program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from tpu_ddp.models.transformer import layer_norm

_NEG_INF = -1e30


def check_decodable(model) -> None:
    """Refuse model configs the decode path cannot serve. Sharded
    (sp/tp/ep) configs hold parameters in training layouts — the
    checkpoint is canonical, so materialize dense serving params first
    (:func:`dense_params_from_checkpoint` is the one-call path)."""
    if model.sp_axis is not None or model.tp_axis is not None \
            or model.ep_axis is not None:
        raise ValueError(
            "decode runs dense single-device models; drop the sp/tp/ep "
            "configuration and load the training checkpoint into a "
            "dense model — dense_params_from_checkpoint(model, ckpt_dir)"
            " (tpu_ddp/models/decode.py) does exactly that via the "
            "canonical checkpoint path")


def mlp(model, blk, y):
    """Block MLP on a decode/prefill activation bank ``y`` (B, L, dm).

    Dense models run the two qdot matmuls (fp or fused int8). MoE
    models run the routed layer (tpu_ddp/parallel/moe.py) with the
    expert axis UNSHARDED — serving params are dense — and capacity
    computed by ``moe_mlp`` from the LIVE bank size T = B*L (the slot
    bank for a decode step, the chunk for prefill), not the training
    batch. Routing is per-token, so with capacity admitting every
    token (the serve engine sizes ``moe_capacity_factor`` so the E
    queues cover the bank; tests pin greedy-stream parity vs ``apply``)
    each token's output is independent of its batch neighbors — the
    property that makes incremental decode match the whole-sequence
    forward despite capacity competition happening per step here and
    per sequence there. At tight capacity the two CAN diverge (tokens
    drop in one composition and not the other); that trade is the
    operator's, surfaced as the dropped-token counter, never silent.
    """
    from tpu_ddp.ops.quant import qdot
    cd = model.compute_dtype
    if model.moe_experts:
        from tpu_ddp.parallel.moe import moe_mlp
        out, _ = moe_mlp(
            y, blk["router"], blk["w1"], blk["w2"],
            num_experts=model.moe_experts,
            capacity_factor=model.moe_capacity_factor,
            top_k=model.moe_top_k, ep_size=1)
        return out.astype(cd)
    y = qdot(y, blk["w1"], cd)
    y = jax.nn.gelu(y.astype(jnp.float32)).astype(cd)
    return qdot(y, blk["w2"], cd).astype(cd)


def attend_cached(model, q, ck, cv, q_pos):
    """q: (B, Lq, H, hd) at absolute positions ``q_pos`` — (Lq,) shared
    across the batch, or (B, Lq) per row (continuous batching); ck/cv:
    full (B, S, KV, hd) cache views. Attends each query over cache
    positions <= its own — the causal mask also covers not-yet-written
    (or stale, for the paged pool) slots: their positions exceed every
    live query's, and the masked ``exp(-1e30 - max)`` underflows to an
    exact 0 weight, so garbage beyond the live length can never leak
    into the output. Under GQA the grouped einsum contracts Q heads
    (B, Lq, KV, G, hd) directly against the KV-width cache — the
    expansion is never materialized, preserving the smaller cache's
    bandwidth win (decode is KV-read-bound)."""
    scale = 1.0 / (model.head_dim ** 0.5)
    b, lq, h, hd = q.shape
    kv = ck.shape[2]
    qg = q.reshape(b, lq, kv, h // kv, hd)
    scores = jnp.einsum("bqkgd,bskd->bkgqs", qg, ck,
                        preferred_element_type=jnp.float32) * scale
    k_pos = jnp.arange(ck.shape[1])
    q_pos = jnp.asarray(q_pos)
    qp = q_pos if q_pos.ndim == 2 else q_pos[None]
    mask = k_pos[None, None, None, None, :] \
        > qp[:, None, None, :, None]
    scores = jnp.where(mask, _NEG_INF, scores)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgqs,bskd->bqkgd", p, cv.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return out.reshape(b, lq, h, hd).astype(q.dtype)


def project_qkv(model, blk, x, pos):
    """Pre-attention half of a block: LN1 + the training-path QKV
    projection with RoPE at ``pos`` ((L,) or (B, L)). The caller owns
    writing k/v into ITS cache layout before attending."""
    y = layer_norm(x, blk["ln1"]["scale"], blk["ln1"]["bias"])
    return model.qkv_proj(blk, y, pos)


def block_finish(model, blk, x, o):
    """Post-attention half of a block: output projection + residual,
    LN2 + MLP + residual. (B, L, dm) -> (B, L, dm)."""
    from tpu_ddp.ops.quant import qdot
    cd = model.compute_dtype
    b, L = x.shape[0], x.shape[1]
    o = qdot(o.reshape(b, L, -1), blk["wo"], cd,
             reshape=(-1, model.d_model)).astype(cd)
    x = x + o
    y = layer_norm(x, blk["ln2"]["scale"], blk["ln2"]["bias"])
    return x + mlp(model, blk, y)


def forward_cached(model, params, tokens, caches, start: int):
    """Run ``tokens`` (B, L) occupying absolute positions
    ``start..start+L-1`` against (and updating) contiguous
    (B, max_len, KV, hd) caches. Returns (last-position logits (B, V),
    new caches). The ``generate()`` path; the serve engine's paged
    twin (tpu_ddp/serve/engine.py) is the same project/attend/finish
    sequence over pool-gathered cache views."""
    cd = model.compute_dtype
    b, L = tokens.shape
    pos = start + jnp.arange(L)
    x = params["embed"][tokens].astype(cd)
    new_caches = []
    for blk, (ck, cv) in zip(params["blocks"], caches):
        q, k, v = project_qkv(model, blk, x, pos)
        ck = lax.dynamic_update_slice(ck, k.astype(ck.dtype),
                                      (0, start, 0, 0))
        cv = lax.dynamic_update_slice(cv, v.astype(cv.dtype),
                                      (0, start, 0, 0))
        o = attend_cached(model, q, ck, cv, pos)
        x = block_finish(model, blk, x, o)
        new_caches.append((ck, cv))
    logits = model.head_apply(params, x[:, -1:])[:, 0]
    return logits, tuple(new_caches)


def init_cache(model, batch: int, max_len: int):
    """Per-block (K, V) buffers: (B, max_len, KV, hd) each — under GQA
    the cache is num_heads/num_kv_heads times smaller than MHA's, the
    scheme's reason to exist (decode is KV-cache-bandwidth-bound)."""
    shape = (batch, max_len, model.kv_heads, model.head_dim)
    zeros = jnp.zeros(shape, model.compute_dtype)
    return tuple((zeros, zeros) for _ in range(model.num_layers))


def sample_token(model, logits, temperature, seed, position):
    """The ONE sampling rule for serving: greedy argmax at
    ``temperature == 0``, else categorical at the given temperature,
    keyed deterministically by (per-request ``seed``, the sequence
    ``position`` the sampled token will occupy) — stateless, so a
    retried or resumed request re-samples identically. Returns
    (token, logprob-of-token), both scalars; vmap over the live batch
    for the continuous-batching step."""
    key = jax.random.fold_in(jax.random.key(seed), position)
    scaled = logits / jnp.maximum(temperature, 1e-6)
    sampled = jax.random.categorical(key, scaled).astype(jnp.int32)
    greedy = jnp.argmax(logits).astype(jnp.int32)
    tok = jnp.where(temperature > 0, sampled, greedy)
    logprob = jax.nn.log_softmax(logits.astype(jnp.float32))[tok]
    return tok, logprob


def verify_sample(model, logits, temperature, seed, positions):
    """Batched multi-position sampling for speculative verification:
    ``logits`` (W, V) at ``positions`` (W,) under ONE request's
    (temperature, seed) -> (tokens (W,), logprobs (W,)). Each column
    is exactly :func:`sample_token` with the same stateless
    ``fold_in(seed, position)`` key the one-token decode step would
    use at that position — the property that makes the speculative
    accept path bitwise identical to the non-speculative stream
    (tpu_ddp/serve/speculative.py, DESIGN.md §26). vmap over the live
    batch for the verify program."""
    return jax.vmap(
        lambda lg, p: sample_token(model, lg, temperature, seed, p)
    )(logits, positions)


def dense_params_from_checkpoint(model, directory: str,
                                 step: int | None = None):
    """Sharded-training-checkpoint -> dense serving params, one call.

    Checkpoints are written in CANONICAL (dense, global) shapes by
    every trainer — the vision engine routes through
    ``Trainer.state_to_host`` and the LM trainers through their
    gather + canonicalize path — precisely so any strategy's artifact
    restores anywhere. This helper reads ONLY the ``params`` subtree
    against the dense model's template (optimizer state, step counter
    and any compression carry are dropped), digest-verifying each leaf
    (utils/checkpoint.py), and returns a pytree :func:`generate`'s /
    the serve engine's dense math accepts directly. ``model`` must be
    the dense config (no sp/tp/ep axes; drop them with
    ``dataclasses.replace`` if you hold the training-time config —
    the parameter TREE is identical, only the runtime layout differs).
    """
    check_decodable(model)
    from tpu_ddp.utils.checkpoint import restore_checkpoint
    template = {"params": jax.eval_shape(
        lambda: model.init(jax.random.key(0)))}
    restored, _ = restore_checkpoint(
        directory, template, step,
        drop_extra=("opt_state", "step", "comp_state"))
    return jax.tree.map(jnp.asarray, restored["params"])
