"""Model zoo.

Parity target: the reference's config-driven VGG builder
(reference part1/model.py:1-50, byte-identical across all four parts) —
VGG11/13/16/19 channel plans, only VGG11 exported by default, BatchNorm with
``track_running_stats=False`` (eval uses batch statistics), 512 -> 10 head.
"""

from tpu_ddp.models.vgg import (  # noqa: F401
    VGG_CFG,
    VGGModel,
    vgg11,
    vgg13,
    vgg16,
    vgg19,
    make_vgg,
)
from tpu_ddp.models.resnet import ResNetModel, resnet50, make_resnet  # noqa: F401
from tpu_ddp.models.vit import ViTModel, make_vit  # noqa: F401
from tpu_ddp.models.generate import generate  # noqa: F401
from tpu_ddp.models.transformer import (  # noqa: F401
    TransformerLM,
    make_transformer,
)
import functools as _functools

_REGISTRY = {
    "VGG11": vgg11,
    "VGG13": vgg13,
    "VGG16": vgg16,
    "VGG19": vgg19,
    "ResNet50": resnet50,
    "ViT-tiny": _functools.partial(make_vit, "ViT-tiny"),
    "ViT-S16": _functools.partial(make_vit, "ViT-S16"),
    "TransformerLM-tiny": _functools.partial(make_transformer,
                                             "TransformerLM-tiny"),
    "TransformerLM-small": _functools.partial(make_transformer,
                                              "TransformerLM-small"),
    "TransformerLM-base": _functools.partial(make_transformer,
                                             "TransformerLM-base"),
}


def get_model(name: str, **kwargs):
    """Look up a model factory by name (e.g. ``get_model("VGG11")``)."""
    try:
        return _REGISTRY[name](**kwargs)
    except KeyError:
        raise ValueError(
            f"unknown model {name!r}; available: {sorted(_REGISTRY)}"
        ) from None
