"""Functional ResNet (bottleneck) family — the BASELINE.json stretch config
("ResNet-50 / ImageNet-1k scale-up", configs[4]). No reference counterpart
exists (the reference ships only VGG, part1/model.py); this follows the same
functional/NHWC/bf16 conventions as ``tpu_ddp.models.vgg``.

BatchNorm uses current-batch statistics only, matching the framework-wide BN
semantic chosen for parity with the reference (part1/model.py:24,
``track_running_stats=False``).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from tpu_ddp.models.vgg import BN_EPS, batch_norm

RESNET_CFG = {
    # (blocks per stage); bottleneck width multiplier is 4.
    "ResNet50": (3, 4, 6, 3),
    "ResNet101": (3, 4, 23, 3),
    "ResNet152": (3, 8, 36, 3),
}

_STAGE_WIDTHS = (64, 128, 256, 512)


def _he_normal(key, shape, dtype):
    fan_in = 1
    for d in shape[:-1]:
        fan_in *= d
    std = (2.0 / fan_in) ** 0.5
    return std * jax.random.normal(key, shape, dtype)


def _conv(x, kernel, stride, cd):
    # bf16 in / bf16 out; MXU accumulates f32 internally, BN restores f32.
    return lax.conv_general_dilated(
        x.astype(cd), kernel.astype(cd),
        window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


@dataclasses.dataclass(frozen=True)
class ResNetModel:
    name: str
    stage_blocks: tuple
    num_classes: int = 1000
    in_channels: int = 3
    small_inputs: bool = False   # True: 3x3/1 stem, no stem pool (CIFAR)
    compute_dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    # Fused Pallas BatchNorm+ReLU kernel for the relu=True blocks
    # (tpu_ddp/ops/pallas/bn_relu.py); BN-without-relu stays on the jnp path.
    use_pallas_bn: bool = False
    # Memory policy (tpu_ddp/memory/policy.py): "blocks" remats each
    # bottleneck residual block, "conv_stages" each of the 4 resolution
    # stages ("dots" has nothing to save inside a conv stage, so it
    # compiles to the conv_stages program); act_dtype is the saved
    # dtype of the inter-block residual stream.
    remat: str = "none"
    act_dtype: str = "compute"

    def __post_init__(self):
        from tpu_ddp.memory import validate_act_dtype, validate_remat
        validate_remat(self.remat)
        validate_act_dtype(self.act_dtype)

    def _conv_bn(self, key, h, w, c_in, c_out):
        k_w, = jax.random.split(key, 1)
        return {
            "kernel": _he_normal(k_w, (h, w, c_in, c_out), self.param_dtype),
            "bn_scale": jnp.ones((c_out,), self.param_dtype),
            "bn_bias": jnp.zeros((c_out,), self.param_dtype),
        }

    def init(self, key) -> dict:
        keys = iter(jax.random.split(key, 4096))
        stem_hw = 3 if self.small_inputs else 7
        params = {"stem": self._conv_bn(next(keys), stem_hw, stem_hw,
                                        self.in_channels, 64)}
        c_in = 64
        stages = []
        for si, n_blocks in enumerate(self.stage_blocks):
            width = _STAGE_WIDTHS[si]
            blocks = []
            for bi in range(n_blocks):
                block = {
                    "conv1": self._conv_bn(next(keys), 1, 1, c_in, width),
                    "conv2": self._conv_bn(next(keys), 3, 3, width, width),
                    "conv3": self._conv_bn(next(keys), 1, 1, width, width * 4),
                }
                if bi == 0 and c_in != width * 4:
                    block["proj"] = self._conv_bn(next(keys), 1, 1, c_in,
                                                  width * 4)
                blocks.append(block)
                c_in = width * 4
            stages.append(tuple(blocks))
        head_key = next(keys)
        params["stages"] = tuple(stages)
        params["head"] = {
            "kernel": _he_normal(head_key, (c_in, self.num_classes),
                                 self.param_dtype),
            "bias": jnp.zeros((self.num_classes,), self.param_dtype),
        }
        return params

    def _bn_relu(self, x, p, relu=True):
        scale = p["bn_scale"].astype(jnp.float32)
        bias = p["bn_bias"].astype(jnp.float32)
        if relu and self.use_pallas_bn:
            from tpu_ddp.ops.pallas import batch_norm_relu
            # x stays in compute dtype: the kernel casts to f32 internally
            # and the VJP residual then holds the small bf16 activation.
            y = batch_norm_relu(x, scale, bias, BN_EPS)
            return y.astype(self.compute_dtype)
        y = batch_norm(x, scale, bias)
        if relu:
            y = jnp.maximum(y, 0)
        return y.astype(self.compute_dtype)

    def _block_apply(self, block, x, stride):
        """One bottleneck residual block (the remat unit under
        ``remat='blocks'``). Enters in the saved-residual dtype,
        computes in ``compute_dtype``. ``stride`` is static (closed
        over, not traced)."""
        cd = self.compute_dtype
        x = x.astype(cd)
        shortcut = x
        y = _conv(x, block["conv1"]["kernel"], 1, cd)
        y = self._bn_relu(y, block["conv1"])
        y = _conv(y, block["conv2"]["kernel"], stride, cd)
        y = self._bn_relu(y, block["conv2"])
        y = _conv(y, block["conv3"]["kernel"], 1, cd)
        y = self._bn_relu(y, block["conv3"], relu=False)
        if "proj" in block:
            shortcut = _conv(shortcut, block["proj"]["kernel"],
                             stride, cd)
            shortcut = self._bn_relu(shortcut, block["proj"],
                                     relu=False)
        elif stride != 1:
            shortcut = lax.reduce_window(
                shortcut, -jnp.inf, lax.max,
                (1, 1, 1, 1), (1, stride, stride, 1), "SAME")
        return jnp.maximum(y.astype(jnp.float32)
                           + shortcut.astype(jnp.float32), 0).astype(cd)

    def _stage_apply(self, stage, x, si):
        for bi, block in enumerate(stage):
            stride = 2 if (si > 0 and bi == 0) else 1
            x = self._block_apply(block, x, stride)
        return x

    def apply(self, params, x):
        from tpu_ddp.memory import cast_saved, effective_remat, wrap_stage
        cd = self.compute_dtype
        stem_stride = 1 if self.small_inputs else 2
        x = _conv(x, params["stem"]["kernel"], stem_stride, cd)
        x = self._bn_relu(x, params["stem"])
        if not self.small_inputs:
            x = lax.reduce_window(
                x, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME")
        remat = effective_remat(self.remat, "conv")
        if remat in ("conv_stages", "dots"):
            for si, stage in enumerate(params["stages"]):
                fn = wrap_stage(
                    functools.partial(self._stage_apply, si=si), remat)
                x = fn(stage, cast_saved(x, self.act_dtype, cd))
        else:
            for si, stage in enumerate(params["stages"]):
                for bi, block in enumerate(stage):
                    stride = 2 if (si > 0 and bi == 0) else 1
                    x = cast_saved(x, self.act_dtype, cd)
                    if remat == "none":
                        x = self._block_apply(block, x, stride)
                    else:
                        fn = wrap_stage(functools.partial(
                            self._block_apply, stride=stride), remat)
                        x = fn(block, x)
        x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        logits = jnp.dot(x.astype(cd), params["head"]["kernel"].astype(cd))
        logits = logits.astype(jnp.float32) \
            + params["head"]["bias"].astype(jnp.float32)
        return logits

    def num_params(self, params=None, key=None) -> int:
        if params is None:
            params = self.init(key if key is not None else jax.random.key(0))
        return sum(int(p.size) for p in jax.tree.leaves(params))


def make_resnet(name: str = "ResNet50", **kwargs) -> ResNetModel:
    if name not in RESNET_CFG:
        raise ValueError(f"unknown ResNet variant {name!r}")
    return ResNetModel(name=name, stage_blocks=RESNET_CFG[name], **kwargs)


def resnet50(**kw):
    return make_resnet("ResNet50", **kw)
