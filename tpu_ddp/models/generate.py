"""Autoregressive generation for TransformerLM — KV-cache decode.

No reference counterpart (the reference is an image classifier); this
completes the LM family's API surface: train with tpu_ddp/train/lm.py,
sample with :func:`generate`.

Design, TPU-first:
- the whole decode loop is ONE jitted ``lax.scan`` over positions —
  no per-token Python dispatch, static shapes throughout;
- the KV cache is a preallocated (B, max_len, KV, hd) buffer per block
  (KV = num_kv_heads: under GQA it is num_heads/num_kv_heads smaller),
  written with ``lax.dynamic_update_slice`` and attended over with a
  position mask (the standard static-shape decode pattern);
- prefill runs the prompt through the same math as
  ``TransformerLM.apply`` while capturing K/V (exactness vs ``apply``
  is tested in tests/test_generate.py), so generation continues exactly
  the distribution the trainer optimized.

Single-device dense models only: generation is a serving concern and the
sharded-training configs (sp/tp/ep) hold their parameters in training
layouts; materialize full params first (the trainers' checkpoints are
canonical, tpu_ddp/train/engine.py save_checkpoint).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from tpu_ddp.models.transformer import layer_norm, rope

_NEG_INF = -1e30


def _check_dense(model):
    if model.sp_axis is not None or model.tp_axis is not None \
            or model.ep_axis is not None:
        raise ValueError(
            "generate() runs dense single-device models; drop the "
            "sp/tp/ep configuration (training checkpoints are canonical "
            "and load into a dense model)")
    if model.moe_experts:
        # Incremental decode cannot reproduce training-time MoE routing:
        # capacity competition is over ALL positions in apply() but only
        # over the new tokens per decode step, so the distributions
        # diverge. Refusing keeps the exactness guarantee honest.
        raise ValueError("generate() does not support MoE models: "
                         "per-step expert capacity cannot match "
                         "apply()'s whole-sequence slot competition")


def _mlp(model, blk, y):
    cd = model.compute_dtype
    y = jnp.dot(y, blk["w1"].astype(cd),
                preferred_element_type=jnp.float32)
    y = jax.nn.gelu(y.astype(jnp.float32)).astype(cd)
    return jnp.dot(y, blk["w2"].astype(cd),
                   preferred_element_type=jnp.float32).astype(cd)


def _attend_cached(model, q, ck, cv, q_pos):
    """q: (B, Lq, H, hd) at absolute positions ``q_pos``; ck/cv: full
    (B, max_len, KV, hd) caches. Attends each query over cache positions
    <= its own — the causal mask also covers not-yet-written slots
    (their positions exceed every live query's). Under GQA the grouped
    einsum contracts Q heads (B, Lq, KV, G, hd) directly against the
    KV-width cache — the expansion is never materialized, preserving the
    smaller cache's bandwidth win (decode is KV-read-bound)."""
    scale = 1.0 / (model.head_dim ** 0.5)
    b, lq, h, hd = q.shape
    kv = ck.shape[2]
    qg = q.reshape(b, lq, kv, h // kv, hd)
    scores = jnp.einsum("bqkgd,bskd->bkgqs", qg, ck,
                        preferred_element_type=jnp.float32) * scale
    k_pos = jnp.arange(ck.shape[1])
    mask = k_pos[None, None, None, None, :] \
        > q_pos[None, None, None, :, None]
    scores = jnp.where(mask, _NEG_INF, scores)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgqs,bskd->bqkgd", p, cv.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return out.reshape(b, lq, h, hd).astype(q.dtype)


def _forward_cached(model, params, tokens, caches, start: int):
    """Run ``tokens`` (B, L) occupying absolute positions
    ``start..start+L-1`` against (and updating) the caches. Returns
    (last-position logits (B, V), new caches)."""
    cd = model.compute_dtype
    b, L = tokens.shape
    pos = start + jnp.arange(L)
    x = params["embed"][tokens].astype(cd)
    new_caches = []
    for blk, (ck, cv) in zip(params["blocks"], caches):
        y = layer_norm(x, blk["ln1"]["scale"], blk["ln1"]["bias"])
        # Same projection as training: q at H heads, k/v at KV-head
        # width, so the cache stores only the KV heads.
        q, k, v = model.qkv_proj(blk, y, pos)
        ck = lax.dynamic_update_slice(ck, k.astype(ck.dtype),
                                      (0, start, 0, 0))
        cv = lax.dynamic_update_slice(cv, v.astype(cv.dtype),
                                      (0, start, 0, 0))
        o = _attend_cached(model, q, ck, cv, pos)
        wo = blk["wo"].astype(cd).reshape(-1, model.d_model)
        o = jnp.dot(o.reshape(b, L, -1), wo,
                    preferred_element_type=jnp.float32).astype(cd)
        x = x + o
        y = layer_norm(x, blk["ln2"]["scale"], blk["ln2"]["bias"])
        x = x + _mlp(model, blk, y)
        new_caches.append((ck, cv))
    logits = model.head_apply(params, x[:, -1:])[:, 0]
    return logits, tuple(new_caches)


def init_cache(model, batch: int, max_len: int):
    """Per-block (K, V) buffers: (B, max_len, KV, hd) each — under GQA
    the cache is num_heads/num_kv_heads times smaller than MHA's, the
    scheme's reason to exist (decode is KV-cache-bandwidth-bound)."""
    shape = (batch, max_len, model.kv_heads, model.head_dim)
    zeros = jnp.zeros(shape, model.compute_dtype)
    return tuple((zeros, zeros) for _ in range(model.num_layers))


@functools.partial(jax.jit,
                   static_argnames=("model", "max_new_tokens"))
def _generate_impl(model, params, prompt, max_new_tokens, temperature,
                   key):
    b, p_len = prompt.shape
    total = p_len + max_new_tokens
    caches = init_cache(model, b, total)
    logits, caches = _forward_cached(model, params, prompt, caches, 0)

    def pick(logits, key):
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        key, sub = jax.random.split(key)
        sampled = jax.random.categorical(
            sub, logits / jnp.maximum(temperature, 1e-6), axis=-1
        ).astype(jnp.int32)
        return jnp.where(temperature > 0, sampled, greedy), key

    tok0, key = pick(logits, key)

    def step(carry, i):
        caches, tok, key = carry
        logits, caches = _forward_cached(model, params, tok[:, None],
                                         caches, p_len + i)
        nxt, key = pick(logits, key)
        return (caches, nxt, key), tok

    (_, last, _), toks = lax.scan(
        step, (caches, tok0, key), jnp.arange(max_new_tokens - 1))
    # toks: (max_new-1, B) emitted BEFORE each step; append the final one.
    return jnp.concatenate(
        [jnp.moveaxis(toks, 0, 1), last[:, None]], axis=1)


def generate(model, params, prompt, max_new_tokens: int,
             temperature: float = 0.0, key=None):
    """Sample ``max_new_tokens`` continuations of ``prompt`` (B, P).

    ``temperature == 0`` is greedy argmax decoding; otherwise softmax
    sampling at the given temperature (``key`` required). Returns the
    (B, max_new_tokens) generated tokens. The prompt plus generation
    must fit ``model.max_seq_len``.
    """
    _check_dense(model)
    prompt = jnp.asarray(prompt, jnp.int32)
    if prompt.ndim != 2 or prompt.shape[1] < 1:
        raise ValueError("prompt must be (batch, prompt_len >= 1)")
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    total = prompt.shape[1] + max_new_tokens
    if total > model.max_seq_len:
        raise ValueError(f"prompt + generation = {total} exceeds "
                         f"max_seq_len={model.max_seq_len}")
    if temperature > 0 and key is None:
        raise ValueError("temperature sampling needs a PRNG key")
    if key is None:
        key = jax.random.key(0)
    return _generate_impl(model, params, prompt, max_new_tokens,
                          jnp.float32(temperature), key)
