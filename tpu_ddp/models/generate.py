"""Autoregressive generation for TransformerLM — KV-cache decode.

No reference counterpart (the reference is an image classifier); this
completes the LM family's API surface: train with tpu_ddp/train/lm.py,
sample with :func:`generate`.

Design, TPU-first:
- the whole decode loop is ONE jitted ``lax.scan`` over positions —
  no per-token Python dispatch, static shapes throughout;
- the KV cache is a preallocated (B, max_len, KV, hd) buffer per block
  (KV = num_kv_heads: under GQA it is num_heads/num_kv_heads smaller),
  written with ``lax.dynamic_update_slice`` and attended over with a
  position mask (the standard static-shape decode pattern);
- prefill runs the prompt through the same math as
  ``TransformerLM.apply`` while capturing K/V (exactness vs ``apply``
  is tested in tests/test_generate.py), so generation continues exactly
  the distribution the trainer optimized.

The cache math itself lives in tpu_ddp/models/decode.py — ONE shared
decode core, so this offline batch sampler and the continuous-batching
serving engine (tpu_ddp/serve/) provably run the same projection/
attention/MLP program; this module owns only the scan-shaped loop.

Single-device dense models only: generation is a serving concern and the
sharded-training configs (sp/tp/ep) hold their parameters in training
layouts; materialize full params first with
:func:`dense_params_from_checkpoint` (re-exported here from the decode
core — the trainers' checkpoints are canonical).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from tpu_ddp.models.decode import (  # noqa: F401 — public re-exports
    _NEG_INF,
    attend_cached,
    check_decodable,
    dense_params_from_checkpoint,
    forward_cached,
    init_cache,
    mlp,
)

# Back-compat aliases: the underscored names were this module's
# internals before the decode core was extracted; tests and downstream
# callers may still import them from here.
_check_dense = check_decodable
_mlp = mlp
_attend_cached = attend_cached
_forward_cached = forward_cached


@functools.partial(jax.jit,
                   static_argnames=("model", "max_new_tokens"))
def _generate_impl(model, params, prompt, max_new_tokens, temperature,
                   key):
    b, p_len = prompt.shape
    total = p_len + max_new_tokens
    caches = init_cache(model, b, total)
    logits, caches = forward_cached(model, params, prompt, caches, 0)

    def pick(logits, key):
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        key, sub = jax.random.split(key)
        sampled = jax.random.categorical(
            sub, logits / jnp.maximum(temperature, 1e-6), axis=-1
        ).astype(jnp.int32)
        return jnp.where(temperature > 0, sampled, greedy), key

    tok0, key = pick(logits, key)

    def step(carry, i):
        caches, tok, key = carry
        logits, caches = forward_cached(model, params, tok[:, None],
                                        caches, p_len + i)
        nxt, key = pick(logits, key)
        return (caches, nxt, key), tok

    (_, last, _), toks = lax.scan(
        step, (caches, tok0, key), jnp.arange(max_new_tokens - 1))
    # toks: (max_new-1, B) emitted BEFORE each step; append the final one.
    return jnp.concatenate(
        [jnp.moveaxis(toks, 0, 1), last[:, None]], axis=1)


def generate(model, params, prompt, max_new_tokens: int,
             temperature: float = 0.0, key=None):
    """Sample ``max_new_tokens`` continuations of ``prompt`` (B, P).

    ``temperature == 0`` is greedy argmax decoding; otherwise softmax
    sampling at the given temperature (``key`` required). Returns the
    (B, max_new_tokens) generated tokens. The prompt plus generation
    must fit ``model.max_seq_len``.
    """
    check_decodable(model)
    prompt = jnp.asarray(prompt, jnp.int32)
    if prompt.ndim != 2 or prompt.shape[1] < 1:
        raise ValueError("prompt must be (batch, prompt_len >= 1)")
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    total = prompt.shape[1] + max_new_tokens
    if total > model.max_seq_len:
        raise ValueError(f"prompt + generation = {total} exceeds "
                         f"max_seq_len={model.max_seq_len}")
    if temperature > 0 and key is None:
        raise ValueError("temperature sampling needs a PRNG key")
    if key is None:
        key = jax.random.key(0)
    return _generate_impl(model, params, prompt, max_new_tokens,
                          jnp.float32(temperature), key)
