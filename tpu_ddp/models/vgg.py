"""Functional VGG family for 32x32 inputs — TPU-native.

Parity target: reference part1/model.py:1-50 (byte-identical across parts).
Design differences (deliberate, TPU-first):

- **Functional, not stateful**: ``init`` returns a parameter pytree;
  ``apply(params, x)`` is a pure function, so the whole train step jits into
  a single XLA program.
- **NHWC layout** with ``HWIO`` kernels — the layout XLA:TPU tiles onto the
  MXU without transposes (torch uses NCHW; reference part1/model.py:18-25).
- **bf16 compute / f32 params**: convolutions and the final matmul run in
  ``compute_dtype`` (bfloat16 by default) with float32 accumulation
  (``preferred_element_type``); batch-norm statistics are always float32.
- **BatchNorm semantics**: the reference constructs every BN with
  ``track_running_stats=False`` (reference part1/model.py:24) so *both train
  and eval use the current batch's statistics* — a deliberate fix for
  cross-replica running-stat divergence (report §3.2). We reproduce exactly
  that: BN here has only ``scale``/``bias`` parameters and no running state.

Channel plans match reference part1/model.py:3-8: 3x3 conv (pad 1, bias) ->
BN -> ReLU per entry, MaxPool 2x2/2 at ``'M'``, then flatten 512 -> Linear
to ``num_classes``.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

# Channel plans (reference part1/model.py:3-8). 'M' = 2x2/2 max-pool.
VGG_CFG = {
    "VGG11": (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    "VGG13": (64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
              512, 512, "M"),
    "VGG16": (64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
              "M", 512, 512, 512, "M"),
    "VGG19": (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
              512, 512, 512, 512, "M", 512, 512, 512, 512, "M"),
}

BN_EPS = 1e-5  # torch BatchNorm2d default, matched for loss-curve parity


def _uniform_fan_in(key, shape, fan_in, dtype):
    """U(-1/sqrt(fan_in), 1/sqrt(fan_in)).

    Same distribution as torch's default Conv2d/Linear init
    (kaiming_uniform with a=sqrt(5) reduces to exactly this bound), so the
    rebuilt model starts from a statistically equivalent point. Bit parity
    with torch RNG is a non-goal (SURVEY.md §7 "hard parts").
    """
    bound = 1.0 / math.sqrt(fan_in)
    return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)


def batch_norm(x, scale, bias, eps=BN_EPS):
    """Batch normalisation over (N, H, W) using *current batch* statistics.

    No running stats, in train and eval alike — the
    ``track_running_stats=False`` semantic of reference part1/model.py:24.
    Statistics are computed in float32 regardless of compute dtype.
    """
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=(0, 1, 2))
    var = jnp.var(x32, axis=(0, 1, 2))
    inv = lax.rsqrt(var + eps) * scale
    return ((x32 - mean) * inv + bias).astype(x.dtype)


def max_pool_2x2(x):
    """2x2 stride-2 max pool, NHWC (reference part1/model.py:16)."""
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, 2, 2, 1),
        window_strides=(1, 2, 2, 1),
        padding="VALID",
    )


@dataclasses.dataclass(frozen=True)
class VGGModel:
    """A VGG variant as a (init, apply) pair over a parameter pytree.

    ``cfg`` is a static tuple, so instances hash and the apply function can
    be closed over by ``jax.jit`` without retracing per call.
    """

    name: str
    cfg: tuple
    num_classes: int = 10
    in_channels: int = 3
    compute_dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    # Use the fused Pallas BatchNorm+ReLU kernel (tpu_ddp/ops/pallas/
    # bn_relu.py) instead of the XLA-fused jnp pair below.
    use_pallas_bn: bool = False
    # Memory policy (tpu_ddp/memory/policy.py): "blocks" remats each
    # conv->BN->ReLU unit, "conv_stages" each between-pool group
    # ("dots" has nothing to save inside a conv stage, so it compiles
    # to the conv_stages program); act_dtype is the saved dtype of the
    # between-stage activations.
    remat: str = "none"
    act_dtype: str = "compute"

    def __post_init__(self):
        from tpu_ddp.memory import validate_act_dtype, validate_remat
        validate_remat(self.remat)
        validate_act_dtype(self.act_dtype)

    # ---- parameters ----------------------------------------------------

    def init(self, key) -> dict:
        """Build the parameter pytree.

        Layout::

            {"features": ({"kernel","bias","bn_scale","bn_bias"}, ...),
             "head": {"kernel", "bias"}}

        with one features entry per conv block ('M' entries carry no
        parameters), kernels HWIO.
        """
        feats = []
        c_in = self.in_channels
        for width in self.cfg:
            if width == "M":
                continue
            key, k_w, k_b = jax.random.split(key, 3)
            fan_in = c_in * 3 * 3
            feats.append({
                "kernel": _uniform_fan_in(
                    k_w, (3, 3, c_in, width), fan_in, self.param_dtype),
                "bias": _uniform_fan_in(k_b, (width,), fan_in, self.param_dtype),
                "bn_scale": jnp.ones((width,), self.param_dtype),
                "bn_bias": jnp.zeros((width,), self.param_dtype),
            })
            c_in = width
        key, k_w, k_b = jax.random.split(key, 3)
        head = {
            "kernel": _uniform_fan_in(
                k_w, (c_in, self.num_classes), c_in, self.param_dtype),
            "bias": _uniform_fan_in(k_b, (self.num_classes,), c_in,
                                    self.param_dtype),
        }
        return {"features": tuple(feats), "head": head}

    # ---- forward -------------------------------------------------------

    @property
    def _stage_plan(self) -> tuple:
        """Conv-stage grouping of ``cfg``: ``((n_convs, pool_after),
        ...)`` — one entry per between-pool group (the remat unit under
        ``remat='conv_stages'``)."""
        plan = []
        n = 0
        for width in self.cfg:
            if width == "M":
                plan.append((n, True))
                n = 0
            else:
                n += 1
        if n:
            plan.append((n, False))
        return tuple(plan)

    def _conv_unit(self, p, x):
        """One conv->bias->BN->ReLU entry (the remat unit under
        ``remat='blocks'``). Enters in the saved-residual dtype,
        computes in ``compute_dtype``."""
        cd = self.compute_dtype
        x = x.astype(cd)
        # bf16 in / bf16 out: XLA:TPU still accumulates the MXU matmul
        # in f32 internally; BN below recomputes stats in f32.
        y = lax.conv_general_dilated(
            x, p["kernel"].astype(cd),
            window_strides=(1, 1), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        y = y.astype(jnp.float32) + p["bias"].astype(jnp.float32)
        if self.use_pallas_bn:
            from tpu_ddp.ops.pallas import batch_norm_relu
            return batch_norm_relu(
                y, p["bn_scale"].astype(jnp.float32),
                p["bn_bias"].astype(jnp.float32), BN_EPS).astype(cd)
        y = batch_norm(y, p["bn_scale"].astype(jnp.float32),
                       p["bn_bias"].astype(jnp.float32))
        return jnp.maximum(y, 0).astype(cd)

    def _stage_apply(self, stage_params, x, pool):
        for p in stage_params:
            x = self._conv_unit(p, x)
        return max_pool_2x2(x) if pool else x

    def apply(self, params, x):
        """Forward pass: NHWC image batch -> logits (float32).

        Mirrors reference part1/model.py:41-45: features -> flatten -> fc.
        Convs and the head matmul run in ``compute_dtype`` with float32
        accumulation so the MXU sees bf16 operands. Under a remat policy
        each unit/stage is a ``jax.checkpoint`` region with its input
        saved in the ``act_dtype`` boundary dtype (tpu_ddp/memory/).
        """
        from tpu_ddp.memory import cast_saved, effective_remat, wrap_stage
        cd = self.compute_dtype
        x = x.astype(cd)
        remat = effective_remat(self.remat, "conv")
        feats = params["features"]
        if remat in ("conv_stages", "dots"):
            i = 0
            for n, pool in self._stage_plan:
                fn = wrap_stage(
                    functools.partial(self._stage_apply, pool=pool), remat)
                x = fn(feats[i:i + n], cast_saved(x, self.act_dtype, cd))
                i += n
        else:
            unit = (self._conv_unit if remat == "none"
                    else wrap_stage(self._conv_unit, remat))
            conv_i = 0
            for width in self.cfg:
                if width == "M":
                    x = max_pool_2x2(x)
                    continue
                x = unit(feats[conv_i], cast_saved(x, self.act_dtype, cd))
                conv_i += 1
        # After 5 pools a 32x32 input is 1x1x512 -> flatten to 512
        # (reference part1/model.py:42-44).
        x = x.astype(cd).reshape(x.shape[0], -1)
        logits = jnp.dot(x, params["head"]["kernel"].astype(cd))
        logits = logits.astype(jnp.float32) \
            + params["head"]["bias"].astype(jnp.float32)
        return logits

    def num_params(self, params=None, key=None) -> int:
        if params is None:
            params = self.init(key if key is not None else jax.random.key(0))
        return sum(int(p.size) for p in jax.tree.leaves(params))


def make_vgg(name: str = "VGG11", **kwargs) -> VGGModel:
    """Factory over the config table (reference part1/model.py:49-50 exports
    only VGG11; we expose the full table like its ``_cfg``)."""
    if name not in VGG_CFG:
        raise ValueError(f"unknown VGG variant {name!r}")
    return VGGModel(name=name, cfg=VGG_CFG[name], **kwargs)


def vgg11(**kw):
    return make_vgg("VGG11", **kw)


def vgg13(**kw):
    return make_vgg("VGG13", **kw)


def vgg16(**kw):
    return make_vgg("VGG16", **kw)


def vgg19(**kw):
    return make_vgg("VGG19", **kw)
