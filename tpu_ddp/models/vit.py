"""Vision Transformer — patch-embedding image classifier.

No reference counterpart (the reference ships only VGG,
part1/model.py:49-50); this family bridges the zoo's CNN side (VGG/
ResNet, trained by tpu_ddp/train/engine.py) and its transformer side —
one model that exercises the engine's image pipeline AND the attention
stack (Dosovitskiy et al., "An Image is Worth 16x16 Words",
arXiv:2010.11929 — reimplemented from the paper, not from any code).

TPU-first choices:
- patch embedding is ONE matmul over flattened patches (a stride-p conv
  is the same linear map, but the reshape+dot form feeds the MXU a
  single large GEMM);
- bidirectional attention through the shared ``attend`` dispatch
  (tpu_ddp/parallel/ring_attention.py) — the Pallas flash kernel is one
  flag away (``use_flash``), as is blockwise streaming;
- bf16 compute / f32 params and LayerNorm statistics, like the rest of
  the zoo; global-average-pool head (no CLS token: GAP is the simpler
  exact-equivalent classifier head and one less special token to shard).

Same functional contract as VGG/ResNet (init/apply over a pytree), so
the Trainer engine, the DP ladder parts, checkpointing, and bench.py all
work unchanged via ``get_model("ViT-tiny")``.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from tpu_ddp.models.transformer import _normal, layer_norm
from tpu_ddp.parallel.ring_attention import attend


@dataclasses.dataclass(frozen=True)
class ViTModel:
    name: str = "ViT"
    image_size: int = 32
    patch_size: int = 4
    num_classes: int = 10
    in_channels: int = 3
    num_layers: int = 6
    num_heads: int = 4
    d_model: int = 256
    d_ff: int = 1024
    compute_dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    use_flash: bool = False
    # Memory policy (tpu_ddp/memory/policy.py): "blocks" remats each
    # transformer block, "dots" saves matmul outputs only
    # ("conv_stages" degrades to "blocks" — no conv stages); act_dtype
    # is the saved dtype of the inter-block residual stream.
    remat: str = "none"
    act_dtype: str = "compute"
    # DEPRECATED alias for remat="blocks" (the pre-policy field); kept
    # functional for back-compat, ignored when ``remat`` is set.
    remat_blocks: bool = False

    @property
    def remat_policy(self) -> str:
        """Effective remat mode, honoring the deprecated
        ``remat_blocks`` alias (``remat`` wins when set)."""
        if self.remat != "none":
            return self.remat
        return "blocks" if self.remat_blocks else "none"

    def __post_init__(self):
        from tpu_ddp.memory import validate_act_dtype, validate_remat
        validate_remat(self.remat)
        validate_act_dtype(self.act_dtype)
        if self.image_size % self.patch_size:
            raise ValueError(
                f"image_size={self.image_size} not divisible by "
                f"patch_size={self.patch_size}")
        if self.d_model % self.num_heads:
            raise ValueError(f"d_model={self.d_model} not divisible by "
                             f"num_heads={self.num_heads}")

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads

    # ---- parameters ----------------------------------------------------

    def init(self, key) -> dict:
        dm, dff = self.d_model, self.d_ff
        h, hd = self.num_heads, self.head_dim
        p = self.patch_size
        std = 0.02
        keys = iter(jax.random.split(key, 3 + 4 * self.num_layers))
        params = {
            "patch": {
                "kernel": _normal(next(keys),
                                  (p * p * self.in_channels, dm), std,
                                  self.param_dtype),
                "bias": jnp.zeros((dm,), self.param_dtype),
            },
            "pos": _normal(next(keys), (self.num_patches, dm), std,
                           self.param_dtype),
            "ln_f": {"scale": jnp.ones((dm,), self.param_dtype),
                     "bias": jnp.zeros((dm,), self.param_dtype)},
            "head": {
                "kernel": _normal(next(keys), (dm, self.num_classes),
                                  std, self.param_dtype),
                "bias": jnp.zeros((self.num_classes,), self.param_dtype),
            },
        }
        blocks = []
        for _ in range(self.num_layers):
            blocks.append({
                "ln1": {"scale": jnp.ones((dm,), self.param_dtype),
                        "bias": jnp.zeros((dm,), self.param_dtype)},
                "wqkv": _normal(next(keys), (dm, 3, h, hd), std,
                                self.param_dtype),
                "wo": _normal(next(keys), (h, hd, dm), std,
                              self.param_dtype),
                "ln2": {"scale": jnp.ones((dm,), self.param_dtype),
                        "bias": jnp.zeros((dm,), self.param_dtype)},
                "w1": _normal(next(keys), (dm, dff), std,
                              self.param_dtype),
                "w2": _normal(next(keys), (dff, dm), std,
                              self.param_dtype),
            })
        params["blocks"] = tuple(blocks)
        return params

    # ---- forward -------------------------------------------------------

    def _patchify(self, x):
        """(B, H, W, C) -> (B, N, p·p·C) flattened patch rows."""
        b = x.shape[0]
        p = self.patch_size
        g = self.image_size // p
        x = x.reshape(b, g, p, g, p, self.in_channels)
        x = x.transpose(0, 1, 3, 2, 4, 5)  # (B, gh, gw, p, p, C)
        return x.reshape(b, g * g, p * p * self.in_channels)

    def _block_entry(self, blk, x):
        """:meth:`_block` with the residual stream re-entering
        ``compute_dtype`` — the checkpoint-region entry point under a
        memory policy."""
        return self._block(blk, x.astype(self.compute_dtype))

    def _block(self, blk, x):
        cd = self.compute_dtype
        b, n = x.shape[0], x.shape[1]
        h, hd = self.num_heads, self.head_dim
        y = layer_norm(x, blk["ln1"]["scale"], blk["ln1"]["bias"])
        wqkv = blk["wqkv"].astype(cd).reshape(self.d_model, -1)
        qkv = jnp.dot(y, wqkv, preferred_element_type=jnp.float32)
        qkv = qkv.astype(cd).reshape(b, n, 3, h, hd)
        o = attend(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2],
                   causal=False, flash=self.use_flash)
        wo = blk["wo"].astype(cd).reshape(h * hd, self.d_model)
        o = jnp.dot(o.reshape(b, n, h * hd), wo,
                    preferred_element_type=jnp.float32).astype(cd)
        x = x + o
        y = layer_norm(x, blk["ln2"]["scale"], blk["ln2"]["bias"])
        y = jnp.dot(y, blk["w1"].astype(cd),
                    preferred_element_type=jnp.float32)
        y = jax.nn.gelu(y.astype(jnp.float32)).astype(cd)
        y = jnp.dot(y, blk["w2"].astype(cd),
                    preferred_element_type=jnp.float32).astype(cd)
        return x + y

    def apply(self, params, x):
        """(B, H, W, C) images -> (B, num_classes) float32 logits."""
        cd = self.compute_dtype
        if x.shape[1] != self.image_size or x.shape[2] != self.image_size:
            raise ValueError(f"expected {self.image_size}x"
                             f"{self.image_size} inputs, got "
                             f"{x.shape[1]}x{x.shape[2]}")
        tok = self._patchify(x.astype(cd))
        tok = jnp.dot(tok, params["patch"]["kernel"].astype(cd),
                      preferred_element_type=jnp.float32)
        tok = (tok + params["patch"]["bias"]).astype(cd)
        tok = tok + params["pos"].astype(cd)
        from tpu_ddp.memory import cast_saved, effective_remat, wrap_stage
        remat = effective_remat(self.remat_policy, "attn")
        if remat == "none" and self.act_dtype == "compute":
            blk_fn = self._block
        else:
            # _block_entry re-enters compute_dtype, so the boundary
            # cast below only changes what autodiff SAVES.
            blk_fn = wrap_stage(self._block_entry, remat)
        for blk in params["blocks"]:
            tok = blk_fn(blk, cast_saved(tok, self.act_dtype, cd))
        tok = layer_norm(tok, params["ln_f"]["scale"],
                         params["ln_f"]["bias"])
        pooled = jnp.mean(tok.astype(jnp.float32), axis=1)  # GAP
        logits = jnp.dot(pooled, params["head"]["kernel"].astype(
            jnp.float32)) + params["head"]["bias"]
        return logits.astype(jnp.float32)

    def num_params(self, params=None, key=None) -> int:
        if params is None:
            params = self.init(key if key is not None else jax.random.key(0))
        return sum(int(p.size) for p in jax.tree.leaves(params))


_PRESETS = {
    # CIFAR-scale: 4x4 patches over 32x32 -> 64 tokens.
    "ViT-tiny": dict(image_size=32, patch_size=4, num_layers=6,
                     num_heads=4, d_model=256, d_ff=1024, num_classes=10),
    # ImageNet-scale ViT-S/16: 196 tokens at 224x224.
    "ViT-S16": dict(image_size=224, patch_size=16, num_layers=12,
                    num_heads=6, d_model=384, d_ff=1536,
                    num_classes=1000),
}


def make_vit(name: str = "ViT-tiny", *, use_pallas_bn: bool = False,
             **kwargs) -> ViTModel:
    """Factory matching the zoo's ``get_model`` calling convention.
    ``use_pallas_bn`` is accepted (the Trainer passes it uniformly to
    vision models) and ignored — ViT has no BatchNorm."""
    del use_pallas_bn
    if name not in _PRESETS:
        raise ValueError(f"unknown ViT preset {name!r}; available: "
                         f"{sorted(_PRESETS)}")
    cfg = dict(_PRESETS[name])
    cfg.update(kwargs)
    return ViTModel(name=name, **cfg)
