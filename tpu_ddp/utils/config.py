"""Hyperparameter / run configuration.

The reference keeps every hyperparameter as a module-level constant
(reference part2/part2b/main.py:16-18,177,184-188); we centralise them in one
dataclass so all four parts and the tests share a single source of truth.
"""

from __future__ import annotations

import dataclasses
import os

# Shared seed applied on every node so parameter init is identical across
# replicas — correctness invariant (i) of the reference
# (reference part1/main.py:14,115-117; report §2.2).
SEED = 89395

# Global batch is fixed; per-node batch = global // world_size
# (reference part2/part2b/main.py:177).
GLOBAL_BATCH_SIZE = 256


def _env_bool(name: str, default: bool) -> bool:
    """Parse a boolean env var; unset -> default, junk -> ValueError."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    low = raw.strip().lower()
    if low in ("1", "true", "yes", "on"):
        return True
    if low in ("0", "false", "no", "off"):
        return False
    raise ValueError(f"{name}={raw!r}: expected a boolean "
                     f"(1/0/true/false/yes/no/on/off)")


def _env_num(name: str, conv, default):
    """Parse a numeric env var; unset -> default, junk -> ValueError
    naming the variable (a typo'd knob silently running the default
    would be the worst kind of drift)."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return conv(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r}: expected "
                         f"{conv.__name__}") from None


@dataclasses.dataclass
class TrainConfig:
    """One training run's configuration (defaults = the reference's)."""

    # Model / data
    model: str = "VGG11"
    num_classes: int = 10
    image_size: int = 32
    in_channels: int = 3
    dataset: str = "cifar10"          # "cifar10" | "imagenet"

    # Optimizer: SGD(lr=0.1, momentum=0.9, weight_decay=1e-4)
    # (reference part1/main.py:124-125).
    learning_rate: float = 0.1
    momentum: float = 0.9
    weight_decay: float = 1e-4

    # Loop shape (reference part1/main.py:17,128).
    global_batch_size: int = GLOBAL_BATCH_SIZE
    epochs: int = 1
    seed: int = SEED

    # Instrumentation cadence: loss print every 20 iters, timing over
    # iterations 1..39 with iteration 0 discarded as warm-up
    # (reference part1/main.py:82-91).
    log_every: int = 20
    timing_first_iter: int = 1
    timing_last_iter: int = 39

    # TPU-first knobs (no reference equivalent — native to this framework).
    compute_dtype: str = "bfloat16"   # matmul/conv dtype on the MXU
    param_dtype: str = "float32"      # master params & optimizer state
    pallas_sgd: bool = False          # fused Pallas optimizer update kernel
    pallas_bn: bool = False           # fused Pallas BatchNorm+ReLU kernel
    device_prefetch: int = 0          # host->device transfers kept in flight
    # > 1: the epoch loop groups K uniform batches per dispatch via
    # Trainer.build_multi_step (one lax.scan over K optimizer steps —
    # amortizes per-dispatch overhead; bit-equal to K single steps).
    # Ragged/tail batches and in-loop checkpoint/invariant cadences fall
    # back to the per-step path. Env: TPU_DDP_STEPS_PER_DISPATCH.
    steps_per_dispatch: int = 1
    # Async dispatch window (tpu_ddp/train/pipeline.py): the epoch loop
    # keeps up to this many train steps in flight and harvests results
    # lazily — losses, guard flags, heartbeats and checkpoint cadences
    # are driven from HARVESTED steps, so divergence can surface up to
    # dispatch_depth steps late (docs/DESIGN.md §13). 0 = the reference's
    # fully synchronous loop (forced automatically while chaos injection
    # is active and inside the timing window). Env: TPU_DDP_DISPATCH_DEPTH.
    dispatch_depth: int = 2

    # Gradient wire compression (tpu_ddp/parallel/compress.py): the
    # dtype gradients travel the sync collectives at. "none" (fp32
    # baseline), "bf16" (cast before, fp32-accumulate after — 2x fewer
    # wire bytes), "int8" (blockwise quantization with error-feedback
    # residual — ~4x) or "int8-noef" (ablation without the residual).
    # Env: TPU_DDP_GRAD_COMPRESS. Requires a dp>1 mesh and a syncing
    # strategy; degrades to "none" with a warning otherwise.
    grad_compress: str = "none"

    # Pipeline schedule knobs (round 10; tpu_ddp/parallel/pipeline.py,
    # consumed by examples/lm_train.py's pipeline rung). pp_schedule
    # picks the tick schedule: "gpipe" (AD of the forward scan),
    # "1f1b" (hand-scheduled, O(pp) activation residency),
    # "interleaved" (1F1B with pp_virtual chunks per stage — bubble
    # shrinks V x) or "zerobubble" (backward split B-input/B-weight,
    # weight grads fill the cooldown). pp_microbatches 0 = auto (= pp).
    # pp_virtual > 1 requires pp_schedule="interleaved" and
    # num_layers % (pp * pp_virtual) == 0 — the engine re-validates;
    # tune/space.py mirrors the same constraints as knob violations.
    # Env: TPU_DDP_PP_SCHEDULE / TPU_DDP_PP_MICROBATCHES /
    # TPU_DDP_PP_VIRTUAL.
    pp_schedule: str = "gpipe"
    pp_microbatches: int = 0
    pp_virtual: int = 1

    # Overlapped bucketized gradient collectives
    # (tpu_ddp/parallel/overlap.py): partition the gradient pytree into
    # ~bucket_mb-MiB buckets in reverse-autodiff order and issue each
    # bucket's collective from INSIDE the backward pass (torch DDP's
    # reducer, reference part3/main.py:174), with the 2004.13336-style
    # sharded weight update on the all_reduce/fused rungs. Requires a
    # dp>1 mesh and a replicated syncing rung; degrades to the
    # unbucketed path with a warning otherwise. Env: TPU_DDP_OVERLAP;
    # launch flag --overlap.
    overlap: bool = False
    # Bucket payload target in MiB (torch DDP's bucket_cap_mb; default
    # matches its 25). Only meaningful with overlap on. Env:
    # TPU_DDP_BUCKET_MB; launch flag --bucket-mb.
    bucket_mb: int = 25

    # Memory policy (tpu_ddp/memory/): activation rematerialization.
    # Which model stages recompute in the backward pass instead of
    # saving their interior activations to HBM — "none" (save
    # everything), "blocks" (per residual/transformer block),
    # "conv_stages" (coarser: per resolution stage; conv families
    # only, transformers degrade to "blocks" with a warning) or "dots"
    # (jax.checkpoint_policies.dots_saveable: matmul outputs saved,
    # elementwise recomputed). Env: TPU_DDP_REMAT; launch flag --remat.
    remat: str = "none"
    # Saved-residual dtype at stage boundaries: "compute" (no cast),
    # "bf16" or "f32". Changes what autodiff SAVES, not the arithmetic
    # inside stages (regions cast back to compute_dtype on entry) —
    # semantic when it differs from compute_dtype, so the autotuner
    # treats it like compute_dtype (TPU_DDP_TUNE_SEMANTIC gate).
    # Env: TPU_DDP_ACT_DTYPE; launch flag --act-dtype.
    act_dtype: str = "compute"

    # Autotuning (tpu_ddp/tune/): "off" (default), "cached" (apply a
    # previously searched tuning for this workload fingerprint when the
    # cache has one; defaults-with-warning otherwise — safe to leave on
    # everywhere), or "search" (run measured trials over the knob space,
    # persist the winner, apply it). Env: TPU_DDP_AUTOTUNE; launch flag
    # --autotune. Explicit TPU_DDP_* pins on individual knobs always
    # beat the tuner.
    autotune: str = "off"

    # Graph audit (tpu_ddp/analysis/): "off" (default), "warn"
    # (construction-time donation + precision audit of the jitted step
    # programs, findings surfaced as warnings), or "error" (findings
    # raise GraphAuditError before the engine burns a step). Non-perf
    # — it changes what is checked, never what is executed — so it has
    # no tune/space.py entry (NONPERF_ENV in scripts/knob_audit.py).
    # Env: TPU_DDP_AUDIT; launch flag --audit.
    audit: str = "off"

    # Serving (tpu_ddp/serve/): continuous-batching decode slots — the
    # live-batch width of the jitted whole-bank decode step. Env:
    # TPU_DDP_SERVE_SLOTS.
    serve_slots: int = 8
    # Paged KV-cache block size in tokens (tpu_ddp/serve/kv_pool.py).
    # Env: TPU_DDP_SERVE_BLOCK.
    serve_block_size: int = 16
    # Prefill chunk in tokens: how much of a prompt runs per engine
    # step, bounding how long one long prompt can stall the decode
    # batch. Env: TPU_DDP_SERVE_PREFILL_CHUNK.
    serve_prefill_chunk: int = 32
    # KV-cache storage dtype — the memory-policy vocabulary
    # (tpu_ddp/memory/policy.py ACT_DTYPES): "compute" (no cast),
    # "bf16" or "f32". Semantic when it differs from compute_dtype
    # (rounds the attended history), so the autotuner gates it like
    # act_dtype. Env: TPU_DDP_SERVE_CACHE_DTYPE.
    serve_cache_dtype: str = "compute"

    # Serving fleet (tpu_ddp/fleet/): engine role split — "single"
    # (round-12 engine: prefill + decode in one program pair) or
    # "disagg" (dedicated prefill role streaming finished KV blocks to
    # a decode role over an explicit edge). Env: TPU_DDP_FLEET_ROLES.
    fleet_roles: str = "single"
    # Refcounted shared-prefix KV cache (tpu_ddp/fleet/prefix.py): N
    # requests sharing a system prompt pay ONE prefill. Exactness-
    # preserving (copy-on-write at the first divergent token). Env:
    # TPU_DDP_PREFIX_CACHE.
    prefix_cache: bool = False
    # Multi-replica router policy (tpu_ddp/fleet/router.py):
    # "least-loaded" or "prefix-affinity" (route to the replica whose
    # prefix cache holds the longest match; needs prefix_cache). Env:
    # TPU_DDP_ROUTER_POLICY.
    router_policy: str = "least-loaded"
    # Wire format for the disagg prefill->decode KV-block edge, riding
    # parallel/compress.py's EdgeCodec vocabulary: "none" (dense),
    # "bf16", "int8". Lossy formats round the shipped KV, so the knob
    # is semantic (gated like cache dtype). Env: TPU_DDP_KV_WIRE.
    kv_wire: str = "none"

    # Fleet resilience (tpu_ddp/fleet/resilience.py, docs/DESIGN.md
    # §23). Replica health tracking in the router: a replica raising
    # out of step() goes unhealthy and its in-flight requests migrate
    # to survivors. Env: TPU_DDP_FLEET_HEALTH.
    fleet_health: bool = True
    # Exponential-backoff base for probing an unhealthy replica
    # (doubles per consecutive failure, capped at 30s). Env:
    # TPU_DDP_FLEET_HEALTH_BACKOFF_MS.
    fleet_probe_backoff_ms: float = 200.0
    # Per-replica step() wall-clock deadline; an overrun marks the
    # replica unhealthy like a crash (0 = off — CPU test hosts jitter
    # far past any useful default). Env:
    # TPU_DDP_FLEET_HEALTH_DEADLINE_MS.
    fleet_step_deadline_ms: float = 0.0
    # Times one request may be replayed after replica failures before
    # the router sheds it instead of bouncing it forever. Env:
    # TPU_DDP_FLEET_RETRY_BUDGET.
    fleet_retry_budget: int = 3
    # Bounded admission queue per engine: submits past this depth are
    # shed at the door (0 = unbounded). Env:
    # TPU_DDP_SERVE_QUEUE_LIMIT.
    serve_queue_limit: int = 0
    # Deadline-based shedding: a request still queued (no token, no
    # block) past this many ms is dropped — serving it would only burn
    # capacity on an already-missed SLO (0 = off). Env:
    # TPU_DDP_SERVE_SHED_MS.
    serve_shed_ms: float = 0.0
    # Autoscaling fleet control plane (tpu_ddp/fleet/autoscale.py,
    # docs/DESIGN.md §25): an Autoscaler over the Router boots
    # replicas from the weight-publisher's full-push path under load
    # and drains them via deterministic migration when idle. Env:
    # TPU_DDP_FLEET_AUTOSCALE.
    fleet_autoscale: bool = False
    # Minimum ms between autoscale actions — the cooldown half of the
    # thrash guard (hysteresis streaks are Autoscaler constructor
    # args). Must be > 0: a zero cooldown lets one flash crowd churn
    # boot/drain cycles that burn the capacity scaling should add.
    # Env: TPU_DDP_SCALE_COOLDOWN_MS.
    scale_cooldown_ms: float = 1000.0
    # Tenant SLO classes for weighted fair queueing
    # (tpu_ddp/serve/scheduler.py): comma-separated
    # "name=weight[:deadline_ms[:token_budget]]" entries; empty = one
    # anonymous class, plain FIFO admission. Mirrors
    # scheduler.parse_tenant_classes (the source of truth, which
    # re-validates at engine construction). Env:
    # TPU_DDP_TENANT_CLASSES.
    tenant_classes: str = ""
    # Speculative decoding (tpu_ddp/serve/speculative.py,
    # docs/DESIGN.md §26): proposals verified per engine step
    # (0 = off, the one-token baseline). Env: TPU_DDP_SPEC_K.
    spec_k: int = 0
    # Draft family for speculation: "chain" (same-program schedule,
    # bitwise-exact stream), "self-<j>" (early exit over the target's
    # first j blocks) or "quant" (full-depth int8 twin). Mirrors
    # serve/speculative.py parse_spec_draft (the source of truth,
    # which re-validates at engine construction). Env:
    # TPU_DDP_SPEC_DRAFT.
    spec_draft: str = "chain"
    # Weight-only int8 decode compute (tpu_ddp/ops/quant.py): "none"
    # serves fp, "int8" quantizes every decode-path projection
    # per-output-channel at engine construction (re-derived on each
    # weight hot-swap). Env: TPU_DDP_DECODE_QUANT.
    decode_quant: str = "none"
    # Tiered KV pool (tpu_ddp/serve/kv_pool.py, docs/DESIGN.md §27):
    # 1 = the single-tier pool unchanged; 2 adds an in-HBM quantized
    # cold tier; 3 adds the host-memory spill tier behind it. Mirrors
    # PagedKVPool (the source of truth, which re-validates at pool
    # construction). Env: TPU_DDP_KV_TIERS.
    kv_tiers: int = 1
    # Cold-page codec for tiers >= 2: "int8" (per-token-row symmetric
    # quantization, parallel/compress.py page_quantize) or "bf16"
    # (plain downcast — lossless when the hot cache dtype is bf16).
    # Inert at kv_tiers == 1. Env: TPU_DDP_KV_COLD_DTYPE.
    kv_cold_dtype: str = "int8"
    # Context-parallel chunked prefill (tpu_ddp/serve/long_context.py):
    # "off", or shard each prefill chunk over the mesh's sp axis with
    # "ring" (K/V rotation, cache-seeded online softmax) or "ulysses"
    # (all-to-all head re-sharding). Needs a serving mesh with sp >= 2.
    # Env: TPU_DDP_CP_PREFILL.
    cp_prefill: str = "off"

    # Live train->serve weight streaming (tpu_ddp/publish/,
    # docs/DESIGN.md §24). Publish a versioned weight update to
    # subscribed serving engines every this many trainer steps
    # (0 = off). Env: TPU_DDP_PUBLISH_EVERY.
    publish_every: int = 0
    # Wire format for the pushed param deltas, riding the same
    # EdgeCodec vocabulary as kv_wire: "none" (dense f32), "bf16",
    # "int8" (error-feedback quantization). Env: TPU_DDP_PUBLISH_WIRE.
    publish_wire: str = "none"
    # How many steps the trainer may run ahead of the slowest
    # subscriber's applied version before its publish gate blocks
    # (0 = unbounded; fully async). Env: TPU_DDP_PUBLISH_MAX_STALENESS.
    max_staleness_steps: int = 0

    # Mixture of experts (tpu_ddp/parallel/moe.py, docs/DESIGN.md §28).
    # Experts per MoE MLP layer (0 = dense models; >0 selects/overrides
    # the routed family — the moe presets in models/transformer.py set
    # it per entry). Env: TPU_DDP_MOE_EXPERTS.
    moe_experts: int = 0
    # Routed experts per token: 1 = Switch, 2 = GShard. The model layer
    # re-validates top_k <= experts where the expert count is known.
    # Env: TPU_DDP_MOE_TOP_K.
    moe_top_k: int = 1
    # Expert capacity factor: slots per expert =
    # ceil(T * capacity * top_k / E). Higher = fewer dropped tokens,
    # more padded compute. Env: TPU_DDP_MOE_CAPACITY.
    moe_capacity: float = 1.25

    # DiLoCo low-communication outer loop (tpu_ddp/train/outer.py,
    # docs/DESIGN.md §29). Inner steps per outer round (0 = off: the
    # outer loop is inert and training traces the plain sync path
    # byte-for-byte). Env: TPU_DDP_DILOCO_H.
    diloco_h: int = 0
    # Outer Nesterov-momentum optimizer over pseudo-gradients
    # (params_start - params_end). lr=1 + momentum=0 is the identity
    # outer optimizer (plain parameter averaging).
    # Envs: TPU_DDP_DILOCO_OUTER_LR / TPU_DDP_DILOCO_OUTER_MOMENTUM.
    outer_lr: float = 0.7
    outer_momentum: float = 0.9
    # Wire format of the cross-group pseudo-gradient exchange — the
    # round-17 publish/ delta codec vocabulary ("none" ships bitwise
    # full tensors; bf16/int8/sparse ship rebased deltas, int8 with
    # per-bucket error feedback). Env: TPU_DDP_DILOCO_OUTER_WIRE.
    outer_wire: str = "none"

    # Test/CI hook: cap iterations per epoch (None = full epoch). Settable
    # via env TPU_DDP_MAX_ITERS so part CLIs can be smoke-tested quickly.
    max_iters: int | None = None
    # Mid-epoch checkpoint cadence in steps (0 = epoch ends only); env
    # TPU_DDP_CKPT_EVERY. Enables resume after mid-epoch failures
    # (tpu_ddp/launch.py:launch_elastic).
    ckpt_every_iters: int = 0
    # Replica-consistency check cadence in steps (0 = off); env
    # TPU_DDP_CHECK_REPLICAS_EVERY (tpu_ddp/utils/invariants.py).
    check_replicas_every: int = 0
    # Step guard (tpu_ddp/resilience/guard.py): skip updates whose loss
    # or global grad-norm is non-finite — the state passes through a
    # bad batch unchanged. On by default (a healthy step is bit-identical
    # to an unguarded one); env TPU_DDP_GUARD=0 disables.
    guard_nonfinite: bool = True
    # Consecutive skipped steps before train_epoch raises
    # TrainingDivergedError (the elastic layer then rolls back to the
    # last checkpoint); env TPU_DDP_GUARD_MAX_BAD.
    guard_max_bad_steps: int = 3
    # Elastic membership (tpu_ddp/resilience/elastic.py): on a rank
    # loss/stall/rejoin, survivors reshard their LIVE TrainState onto a
    # rebuilt mesh (parallel/redistribute.py) instead of the cluster
    # dying into restart-from-checkpoint. Workers only act on it when
    # the launcher also provides the protocol directory
    # (TPU_DDP_ELASTIC_DIR). Env: TPU_DDP_ELASTIC_RESHARD; launch flag
    # --elastic-reshard.
    elastic_reshard: bool = False

    def __post_init__(self):
        if self.max_iters is None:
            env = os.environ.get("TPU_DDP_MAX_ITERS")
            if env:
                self.max_iters = int(env)
        # Smoke-test hook: shrink the global batch (e.g. on the 1-core CPU
        # CI host, where a 256-image VGG step is minutes of compute).
        env_bs = os.environ.get("TPU_DDP_GLOBAL_BATCH")
        if env_bs:
            self.global_batch_size = int(env_bs)
        self.pallas_sgd = _env_bool("TPU_DDP_PALLAS_SGD", self.pallas_sgd)
        self.pallas_bn = _env_bool("TPU_DDP_PALLAS_BN", self.pallas_bn)
        env_pf = os.environ.get("TPU_DDP_PREFETCH")
        if env_pf:
            self.device_prefetch = int(env_pf)
        env_spd = os.environ.get("TPU_DDP_STEPS_PER_DISPATCH")
        if env_spd:
            self.steps_per_dispatch = int(env_spd)
        env_dd = os.environ.get("TPU_DDP_DISPATCH_DEPTH")
        if env_dd:
            self.dispatch_depth = int(env_dd)
        if self.dispatch_depth < 0:
            raise ValueError(
                f"dispatch_depth must be >= 0, got {self.dispatch_depth} "
                "(0 = synchronous loop)")
        env_gc = os.environ.get("TPU_DDP_GRAD_COMPRESS")
        if env_gc:
            self.grad_compress = env_gc
        # Mirrors parallel/compress.py SPECS (the source of truth, which
        # re-validates); duplicated so a bad env/config fails HERE with
        # the flag name, not deep inside Trainer construction.
        if self.grad_compress not in ("none", "bf16", "int8",
                                      "int8-noef"):
            raise ValueError(
                f"grad_compress={self.grad_compress!r}: expected "
                "none|bf16|int8|int8-noef (TPU_DDP_GRAD_COMPRESS)")
        env_ps = os.environ.get("TPU_DDP_PP_SCHEDULE")
        if env_ps:
            self.pp_schedule = env_ps
        if self.pp_schedule not in ("gpipe", "1f1b", "interleaved",
                                    "zerobubble"):
            raise ValueError(
                f"pp_schedule={self.pp_schedule!r}: expected "
                "gpipe|1f1b|interleaved|zerobubble (TPU_DDP_PP_SCHEDULE)")
        env_pm = os.environ.get("TPU_DDP_PP_MICROBATCHES")
        if env_pm:
            self.pp_microbatches = int(env_pm)
        if self.pp_microbatches < 0:
            raise ValueError(
                f"pp_microbatches must be >= 0 (0 = auto), got "
                f"{self.pp_microbatches} (TPU_DDP_PP_MICROBATCHES)")
        env_pv = os.environ.get("TPU_DDP_PP_VIRTUAL")
        if env_pv:
            self.pp_virtual = int(env_pv)
        if self.pp_virtual < 1:
            raise ValueError(
                f"pp_virtual must be >= 1, got {self.pp_virtual} "
                "(TPU_DDP_PP_VIRTUAL)")
        # Cross-knob coupling (pp_virtual>1 needs the interleaved
        # schedule, layer divisibility) is enforced where the mesh and
        # model are known: PipelineLMTrainer rejects bad combinations
        # at construction and tune/space.py mirrors them as violations.
        # Validating it here would make each env knob's parse depend on
        # the others', which the single-var audit probes forbid.
        # f32 end-to-end runs turn the bf16-rounding drift story into a
        # measurement (run_experiments --dtype float32): bit-equivalent
        # programs must then agree to f32 reduction-order tolerance.
        env_cd = os.environ.get("TPU_DDP_COMPUTE_DTYPE")
        if env_cd:
            if env_cd not in ("bfloat16", "float32", "float16"):
                raise ValueError(f"TPU_DDP_COMPUTE_DTYPE={env_cd!r}: "
                                 "expected bfloat16|float32|float16")
            self.compute_dtype = env_cd
        # Learning-rate override: the tamed ladder-agreement run
        # (run_experiments --tame) drops lr to 1e-3 so reduction-order
        # noise is not amplified by the lr-0.1 batch-stats-BN dynamics
        # (EXPERIMENTS.md §6 measured ~4x/iter amplification at 0.1).
        env_lr = os.environ.get("TPU_DDP_LR")
        if env_lr:
            lr = float(env_lr)
            if not lr > 0:  # also rejects NaN
                raise ValueError(f"TPU_DDP_LR={env_lr!r}: expected a "
                                 "positive learning rate")
            self.learning_rate = lr
        env_ck = os.environ.get("TPU_DDP_CKPT_EVERY")
        if env_ck:
            self.ckpt_every_iters = int(env_ck)
        env_rc = os.environ.get("TPU_DDP_CHECK_REPLICAS_EVERY")
        if env_rc:
            self.check_replicas_every = int(env_rc)
        self.guard_nonfinite = _env_bool("TPU_DDP_GUARD",
                                         self.guard_nonfinite)
        env_gb = os.environ.get("TPU_DDP_GUARD_MAX_BAD")
        if env_gb:
            self.guard_max_bad_steps = int(env_gb)
        self.elastic_reshard = _env_bool("TPU_DDP_ELASTIC_RESHARD",
                                         self.elastic_reshard)
        self.overlap = _env_bool("TPU_DDP_OVERLAP", self.overlap)
        env_bm = os.environ.get("TPU_DDP_BUCKET_MB")
        if env_bm:
            self.bucket_mb = int(env_bm)
        if self.bucket_mb <= 0:
            raise ValueError(
                f"bucket_mb must be > 0, got {self.bucket_mb} "
                "(TPU_DDP_BUCKET_MB)")
        env_rm = os.environ.get("TPU_DDP_REMAT")
        if env_rm:
            self.remat = env_rm
        env_ad = os.environ.get("TPU_DDP_ACT_DTYPE")
        if env_ad:
            self.act_dtype = env_ad
        # Mirrors tpu_ddp/memory/policy.py (the source of truth, which
        # re-validates at model construction); duplicated so a bad
        # env/config fails HERE with the env-var name.
        if self.remat not in ("none", "blocks", "conv_stages", "dots"):
            raise ValueError(
                f"remat={self.remat!r}: expected "
                "none|blocks|conv_stages|dots (TPU_DDP_REMAT)")
        if self.act_dtype not in ("compute", "bf16", "f32"):
            raise ValueError(
                f"act_dtype={self.act_dtype!r}: expected "
                "compute|bf16|f32 (TPU_DDP_ACT_DTYPE)")
        env_at = os.environ.get("TPU_DDP_AUTOTUNE")
        if env_at:
            self.autotune = env_at
        if self.autotune not in ("off", "cached", "search"):
            raise ValueError(
                f"autotune={self.autotune!r}: expected off|cached|search "
                "(TPU_DDP_AUTOTUNE)")
        env_audit = os.environ.get("TPU_DDP_AUDIT")
        if env_audit:
            self.audit = env_audit
        if self.audit not in ("off", "warn", "error"):
            raise ValueError(
                f"audit={self.audit!r}: expected off|warn|error "
                "(TPU_DDP_AUDIT)")
        env_ss = os.environ.get("TPU_DDP_SERVE_SLOTS")
        if env_ss:
            self.serve_slots = int(env_ss)
        if self.serve_slots < 1:
            raise ValueError(f"serve_slots must be >= 1, got "
                             f"{self.serve_slots} (TPU_DDP_SERVE_SLOTS)")
        env_sb = os.environ.get("TPU_DDP_SERVE_BLOCK")
        if env_sb:
            self.serve_block_size = int(env_sb)
        if self.serve_block_size < 1:
            raise ValueError(
                f"serve_block_size must be >= 1, got "
                f"{self.serve_block_size} (TPU_DDP_SERVE_BLOCK)")
        env_sp = os.environ.get("TPU_DDP_SERVE_PREFILL_CHUNK")
        if env_sp:
            self.serve_prefill_chunk = int(env_sp)
        if self.serve_prefill_chunk < 1:
            raise ValueError(
                f"serve_prefill_chunk must be >= 1, got "
                f"{self.serve_prefill_chunk} "
                "(TPU_DDP_SERVE_PREFILL_CHUNK)")
        env_sc = os.environ.get("TPU_DDP_SERVE_CACHE_DTYPE")
        if env_sc:
            self.serve_cache_dtype = env_sc
        # Mirrors tpu_ddp/memory/policy.py ACT_DTYPES (the source of
        # truth, which re-validates at pool construction).
        if self.serve_cache_dtype not in ("compute", "bf16", "f32"):
            raise ValueError(
                f"serve_cache_dtype={self.serve_cache_dtype!r}: expected "
                "compute|bf16|f32 (TPU_DDP_SERVE_CACHE_DTYPE)")
        env_fr = os.environ.get("TPU_DDP_FLEET_ROLES")
        if env_fr:
            self.fleet_roles = env_fr
        if self.fleet_roles not in ("single", "disagg"):
            raise ValueError(
                f"fleet_roles={self.fleet_roles!r}: expected "
                "single|disagg (TPU_DDP_FLEET_ROLES)")
        self.prefix_cache = _env_bool("TPU_DDP_PREFIX_CACHE",
                                      self.prefix_cache)
        env_rp = os.environ.get("TPU_DDP_ROUTER_POLICY")
        if env_rp:
            self.router_policy = env_rp
        if self.router_policy not in ("least-loaded", "prefix-affinity"):
            raise ValueError(
                f"router_policy={self.router_policy!r}: expected "
                "least-loaded|prefix-affinity (TPU_DDP_ROUTER_POLICY)")
        env_kw = os.environ.get("TPU_DDP_KV_WIRE")
        if env_kw:
            self.kv_wire = env_kw
        # Mirrors parallel/compress.py EdgeCodec wire kinds (the
        # source of truth, which re-validates at edge construction).
        if self.kv_wire not in ("none", "bf16", "int8"):
            raise ValueError(
                f"kv_wire={self.kv_wire!r}: expected none|bf16|int8 "
                "(TPU_DDP_KV_WIRE)")
        self.fleet_health = _env_bool("TPU_DDP_FLEET_HEALTH",
                                      self.fleet_health)
        self.fleet_probe_backoff_ms = _env_num(
            "TPU_DDP_FLEET_HEALTH_BACKOFF_MS", float,
            self.fleet_probe_backoff_ms)
        if self.fleet_probe_backoff_ms <= 0:
            raise ValueError(
                f"fleet_probe_backoff_ms must be > 0, got "
                f"{self.fleet_probe_backoff_ms} "
                "(TPU_DDP_FLEET_HEALTH_BACKOFF_MS)")
        self.fleet_step_deadline_ms = _env_num(
            "TPU_DDP_FLEET_HEALTH_DEADLINE_MS", float,
            self.fleet_step_deadline_ms)
        if self.fleet_step_deadline_ms < 0:
            raise ValueError(
                f"fleet_step_deadline_ms must be >= 0, got "
                f"{self.fleet_step_deadline_ms} "
                "(TPU_DDP_FLEET_HEALTH_DEADLINE_MS)")
        self.fleet_retry_budget = _env_num(
            "TPU_DDP_FLEET_RETRY_BUDGET", int, self.fleet_retry_budget)
        if self.fleet_retry_budget < 0:
            raise ValueError(
                f"fleet_retry_budget must be >= 0, got "
                f"{self.fleet_retry_budget} (TPU_DDP_FLEET_RETRY_BUDGET)")
        self.serve_queue_limit = _env_num(
            "TPU_DDP_SERVE_QUEUE_LIMIT", int, self.serve_queue_limit)
        if self.serve_queue_limit < 0:
            raise ValueError(
                f"serve_queue_limit must be >= 0, got "
                f"{self.serve_queue_limit} (TPU_DDP_SERVE_QUEUE_LIMIT)")
        self.serve_shed_ms = _env_num(
            "TPU_DDP_SERVE_SHED_MS", float, self.serve_shed_ms)
        if self.serve_shed_ms < 0:
            raise ValueError(
                f"serve_shed_ms must be >= 0, got "
                f"{self.serve_shed_ms} (TPU_DDP_SERVE_SHED_MS)")
        self.publish_every = _env_num(
            "TPU_DDP_PUBLISH_EVERY", int, self.publish_every)
        if self.publish_every < 0:
            raise ValueError(
                f"publish_every must be >= 0, got "
                f"{self.publish_every} (TPU_DDP_PUBLISH_EVERY)")
        self.fleet_autoscale = _env_bool("TPU_DDP_FLEET_AUTOSCALE",
                                         self.fleet_autoscale)
        self.scale_cooldown_ms = _env_num(
            "TPU_DDP_SCALE_COOLDOWN_MS", float, self.scale_cooldown_ms)
        if self.scale_cooldown_ms <= 0:
            raise ValueError(
                f"scale_cooldown_ms must be > 0, got "
                f"{self.scale_cooldown_ms} (TPU_DDP_SCALE_COOLDOWN_MS)")
        env_tc = os.environ.get("TPU_DDP_TENANT_CLASSES")
        if env_tc is not None:
            self.tenant_classes = env_tc
        # Mirrors serve/scheduler.py parse_tenant_classes (the source
        # of truth, which re-validates at engine construction): comma-
        # separated name=weight[:deadline_ms[:token_budget]] entries.
        for entry in str(self.tenant_classes).split(","):
            entry = entry.strip()
            if not entry:
                continue
            name, _, rest = entry.partition("=")
            parts = rest.split(":")
            ok = bool(name.strip()) and "=" in entry and \
                1 <= len(parts) <= 3
            if ok:
                try:
                    ok = float(parts[0]) >= 1 and all(
                        float(p) >= 0 for p in parts[1:])
                except ValueError:
                    ok = False
            if not ok:
                raise ValueError(
                    f"tenant_classes entry {entry!r}: expected "
                    "name=weight[:deadline_ms[:token_budget]] "
                    "(TPU_DDP_TENANT_CLASSES)")
        env_pw = os.environ.get("TPU_DDP_PUBLISH_WIRE")
        if env_pw:
            self.publish_wire = env_pw
        # Mirrors publish/publisher.py PUBLISH_WIRES (the publisher
        # re-validates at construction).
        if self.publish_wire not in ("none", "bf16", "int8", "sparse"):
            raise ValueError(
                f"publish_wire={self.publish_wire!r}: expected "
                "none|bf16|int8|sparse (TPU_DDP_PUBLISH_WIRE)")
        self.max_staleness_steps = _env_num(
            "TPU_DDP_PUBLISH_MAX_STALENESS", int,
            self.max_staleness_steps)
        if self.max_staleness_steps < 0:
            raise ValueError(
                f"max_staleness_steps must be >= 0, got "
                f"{self.max_staleness_steps} "
                "(TPU_DDP_PUBLISH_MAX_STALENESS)")
        self.spec_k = _env_num("TPU_DDP_SPEC_K", int, self.spec_k)
        if self.spec_k < 0:
            raise ValueError(
                f"spec_k must be >= 0, got {self.spec_k} "
                "(TPU_DDP_SPEC_K)")
        env_sd = os.environ.get("TPU_DDP_SPEC_DRAFT")
        if env_sd:
            self.spec_draft = env_sd
        # Mirrors serve/speculative.py parse_spec_draft (the source of
        # truth, which re-validates at engine construction): "chain",
        # "self-<j>" (j >= 1) or "quant".
        sd = str(self.spec_draft).strip()
        ok = sd in ("chain", "quant")
        if not ok and sd.startswith("self-"):
            ok = sd[len("self-"):].isdigit() and int(sd[5:]) >= 1
        if not ok:
            raise ValueError(
                f"spec_draft={self.spec_draft!r}: expected "
                "chain|self-<j>|quant (TPU_DDP_SPEC_DRAFT)")
        env_dq = os.environ.get("TPU_DDP_DECODE_QUANT")
        if env_dq:
            self.decode_quant = env_dq
        if self.decode_quant not in ("none", "int8"):
            raise ValueError(
                f"decode_quant={self.decode_quant!r}: expected "
                "none|int8 (TPU_DDP_DECODE_QUANT)")
        self.kv_tiers = _env_num("TPU_DDP_KV_TIERS", int, self.kv_tiers)
        if self.kv_tiers not in (1, 2, 3):
            raise ValueError(
                f"kv_tiers must be 1, 2 or 3, got {self.kv_tiers} "
                "(TPU_DDP_KV_TIERS)")
        env_cd = os.environ.get("TPU_DDP_KV_COLD_DTYPE")
        if env_cd:
            self.kv_cold_dtype = env_cd
        if self.kv_cold_dtype not in ("int8", "bf16"):
            raise ValueError(
                f"kv_cold_dtype={self.kv_cold_dtype!r}: expected "
                "int8|bf16 (TPU_DDP_KV_COLD_DTYPE)")
        env_cp = os.environ.get("TPU_DDP_CP_PREFILL")
        if env_cp:
            self.cp_prefill = env_cp
        if self.cp_prefill not in ("off", "ring", "ulysses"):
            raise ValueError(
                f"cp_prefill={self.cp_prefill!r}: expected "
                "off|ring|ulysses (TPU_DDP_CP_PREFILL)")
        self.moe_experts = _env_num(
            "TPU_DDP_MOE_EXPERTS", int, self.moe_experts)
        if self.moe_experts < 0:
            raise ValueError(
                f"moe_experts must be >= 0 (0 = dense), got "
                f"{self.moe_experts} (TPU_DDP_MOE_EXPERTS)")
        self.moe_top_k = _env_num(
            "TPU_DDP_MOE_TOP_K", int, self.moe_top_k)
        if self.moe_top_k < 1:
            raise ValueError(
                f"moe_top_k must be >= 1, got {self.moe_top_k} "
                "(TPU_DDP_MOE_TOP_K)")
        # top_k <= experts needs both knobs; like the pp coupling above,
        # cross-knob checks live in the model layer (topk_route) and in
        # tune/space.py violations, never in the single-var parses.
        self.moe_capacity = _env_num(
            "TPU_DDP_MOE_CAPACITY", float, self.moe_capacity)
        if not self.moe_capacity > 0:  # also rejects NaN
            raise ValueError(
                f"moe_capacity must be > 0, got {self.moe_capacity} "
                "(TPU_DDP_MOE_CAPACITY)")
        self.diloco_h = _env_num(
            "TPU_DDP_DILOCO_H", int, self.diloco_h)
        if self.diloco_h < 0:
            raise ValueError(
                f"diloco_h must be >= 0 (0 = off), got "
                f"{self.diloco_h} (TPU_DDP_DILOCO_H)")
        self.outer_lr = _env_num(
            "TPU_DDP_DILOCO_OUTER_LR", float, self.outer_lr)
        if not self.outer_lr > 0:  # also rejects NaN
            raise ValueError(
                f"outer_lr must be > 0, got {self.outer_lr} "
                "(TPU_DDP_DILOCO_OUTER_LR)")
        self.outer_momentum = _env_num(
            "TPU_DDP_DILOCO_OUTER_MOMENTUM", float, self.outer_momentum)
        if not 0.0 <= self.outer_momentum < 1.0:  # also rejects NaN
            raise ValueError(
                f"outer_momentum must be in [0, 1), got "
                f"{self.outer_momentum} (TPU_DDP_DILOCO_OUTER_MOMENTUM)")
        env_ow = os.environ.get("TPU_DDP_DILOCO_OUTER_WIRE")
        if env_ow:
            self.outer_wire = env_ow
        # Mirrors publish/publisher.py PUBLISH_WIRES (train/outer.py
        # re-validates at OuterLoop construction). diloco_h x pp
        # coupling is a cross-knob rule and lives in tune/space.py
        # violations, like the other couplings above.
        if self.outer_wire not in ("none", "bf16", "int8", "sparse"):
            raise ValueError(
                f"outer_wire={self.outer_wire!r}: expected "
                "none|bf16|int8|sparse (TPU_DDP_DILOCO_OUTER_WIRE)")

    def per_node_batch_size(self, world_size: int) -> int:
        # int(256 / world_size), as in reference part2/part2b/main.py:177.
        return int(self.global_batch_size / world_size)

    @classmethod
    def preset(cls, name: str, **overrides) -> "TrainConfig":
        """Named run configurations (BASELINE.json configs)."""
        try:
            base = dict(PRESETS[name])
        except KeyError:
            raise ValueError(
                f"unknown preset {name!r}; available: {sorted(PRESETS)}"
            ) from None
        base.update(overrides)
        return cls(**base)


# The reference ladder's configuration (configs[0..3]) plus the stretch
# scale-up (configs[4], "ResNet-50 / ImageNet-1k").
PRESETS = {
    "vgg11_cifar10": {},
    "resnet50_imagenet": dict(model="ResNet50", num_classes=1000,
                              image_size=224, dataset="imagenet"),
    "vit_cifar10": dict(model="ViT-tiny"),
}
