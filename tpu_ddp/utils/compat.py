"""JAX version compatibility shims.

The framework targets the modern JAX surface (``jax.shard_map`` with the
``check_vma`` flag, promoted out of ``jax.experimental`` in 0.6); the
pinned environment may carry an older release where the function still
lives at ``jax.experimental.shard_map.shard_map`` and the flag is named
``check_rep``. Rather than sprinkling try/except around every call site,
:func:`install` backfills ``jax.shard_map`` once, at package import
(tpu_ddp/__init__.py) — call sites are written against the modern API
only.
"""

from __future__ import annotations

import jax


def install() -> None:
    """Backfill modern API names onto older jax modules. Idempotent."""
    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map

        def shard_map(f, mesh, in_specs, out_specs, check_vma=True,
                      **kwargs):
            # check_vma is the modern name of check_rep (the value-moved-
            # across check); semantics are unchanged for our uses.
            return _shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_vma,
                              **kwargs)

        jax.shard_map = shard_map

    if not hasattr(jax.distributed, "is_initialized"):
        # Added to the public API after this release; the underlying
        # client handle has always carried the answer.
        from jax._src import distributed as _distributed_impl

        def is_initialized() -> bool:
            return _distributed_impl.global_state.client is not None

        jax.distributed.is_initialized = is_initialized

    try:
        jax.tree_util.keystr((), simple=True, separator=".")
    except TypeError:
        # Older keystr() predates simple/separator (added in 0.4.36+ API
        # churn); emulate: simple mode renders each key entry bare
        # (dict key / sequence index / attribute name, no brackets or
        # quotes) joined by the separator.
        _orig_keystr = jax.tree_util.keystr

        def keystr(keys, simple=False, separator=""):
            if not simple:
                return _orig_keystr(keys)

            def one(k):
                for attr in ("key", "idx", "name"):
                    if hasattr(k, attr):
                        return str(getattr(k, attr))
                return str(k)

            return separator.join(one(k) for k in keys)

        jax.tree_util.keystr = keystr
