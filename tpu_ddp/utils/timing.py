"""Per-iteration timing harness — the reference's primary metric.

Reference part1/main.py:66,86-91 (and clones in 2a/2b/3): wall time of each
iteration via ``time.perf_counter_ns()``; iterations 1..39 accumulated
(iteration 0 discarded as compile/warm-up); total and average printed at
iteration 39. The JAX-correct analogue must call ``block_until_ready`` on
the step outputs before stopping the clock — otherwise async dispatch makes
every iteration look free (SURVEY.md §7 "hard parts").

:func:`timed_window_s` / :func:`warm_then_median_s` are the shared
warm-compile + timed-window loop that used to be hand-rolled in every
sweep script (``scripts/compress_sweep.py``,
``scripts/bench_pipeline_schedules.py``) and now also drives the
autotuner's trials (``tpu_ddp/tune/runner.py``): warm calls first (the
reference's discarded iteration 0), then back-to-back calls with ONE
sync at the window edge, so the number prices the work, not per-call
host round-trips.
"""

from __future__ import annotations

import dataclasses
import time


def _default_sync(value) -> None:
    """Block on a step's outputs (ignores None so ``run`` callbacks that
    return nothing still get a correct, if trusting, clock stop)."""
    if value is not None:
        import jax

        jax.block_until_ready(value)


def timed_window_s(run, iters: int, sync=None) -> float:
    """Average wall seconds per call over ONE window of ``iters``
    back-to-back ``run()`` calls, with ``sync`` (default
    ``jax.block_until_ready``) applied to the LAST call's return value
    before the clock stops — the async-dispatch-correct window shape
    (one sync per window, not per call). The caller is responsible for
    warming/compiling first; see :func:`warm_then_median_s`.
    """
    if iters < 1:
        raise ValueError(f"iters must be >= 1, got {iters}")
    sync = sync or _default_sync
    t0 = time.perf_counter()
    out = None
    for _ in range(iters):
        out = run()
    sync(out)
    return (time.perf_counter() - t0) / iters


def warm_then_median_s(run, iters: int, windows: int = 1,
                       warmup: int = 1, sync=None) -> tuple[float, list]:
    """``warmup`` discarded calls (compile + first execution), then
    ``windows`` timed windows of ``iters`` calls each; returns
    ``(median avg-s/call, all window samples)``.

    The shared warm/median loop (round-7 consolidation): the median over
    >= 3 windows is how every committed number in this repo defends
    itself against host noise (bench.py's protocol); ``windows=1``
    reproduces the old single-window sweep scripts exactly.
    """
    sync = sync or _default_sync
    out = None
    for _ in range(max(0, warmup)):
        out = run()
    sync(out)
    samples = [timed_window_s(run, iters, sync=sync)
               for _ in range(max(1, windows))]
    ordered = sorted(samples)
    mid = len(ordered) // 2
    median = (ordered[mid] if len(ordered) % 2
              else 0.5 * (ordered[mid - 1] + ordered[mid]))
    return median, samples


@dataclasses.dataclass
class IterationTimer:
    """Accumulates ns over iterations [first_iter, last_iter]."""

    first_iter: int = 1
    last_iter: int = 39
    total_ns: int = 0
    count: int = 0
    _t0: int = 0

    def start(self):
        self._t0 = time.perf_counter_ns()

    def stop(self, iteration: int) -> int:
        """Record iteration's elapsed ns; returns the elapsed ns."""
        elapsed = time.perf_counter_ns() - self._t0
        if self.first_iter <= iteration <= self.last_iter:
            self.total_ns += elapsed
            self.count += 1
        return elapsed

    def stop_many(self, first_iteration: int, k: int) -> int:
        """Attribute the elapsed time since :meth:`start` evenly to
        iterations [first_iteration, first_iteration + k) — the
        K-steps-per-dispatch case (Trainer.build_multi_step), where
        per-iteration boundaries don't exist on the host."""
        elapsed = time.perf_counter_ns() - self._t0
        share = elapsed // max(k, 1)
        for it in range(first_iteration, first_iteration + k):
            if self.first_iter <= it <= self.last_iter:
                self.total_ns += share
                self.count += 1
        return elapsed

    @property
    def average_ns(self) -> float:
        return self.total_ns / self.count if self.count else 0.0

    @property
    def average_s(self) -> float:
        return self.average_ns / 1e9

    def report(self, prefix: str = "") -> str:
        """The reference prints total + average ns after iteration 39
        (part1/main.py:86-91); same payload here."""
        return (f"{prefix}timing over iterations "
                f"{self.first_iter}-{self.last_iter}: total {self.total_ns} ns, "
                f"average {self.average_ns:.0f} ns "
                f"({self.average_s:.4f} s/iter)")
