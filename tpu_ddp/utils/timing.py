"""Per-iteration timing harness — the reference's primary metric.

Reference part1/main.py:66,86-91 (and clones in 2a/2b/3): wall time of each
iteration via ``time.perf_counter_ns()``; iterations 1..39 accumulated
(iteration 0 discarded as compile/warm-up); total and average printed at
iteration 39. The JAX-correct analogue must call ``block_until_ready`` on
the step outputs before stopping the clock — otherwise async dispatch makes
every iteration look free (SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

import dataclasses
import time


@dataclasses.dataclass
class IterationTimer:
    """Accumulates ns over iterations [first_iter, last_iter]."""

    first_iter: int = 1
    last_iter: int = 39
    total_ns: int = 0
    count: int = 0
    _t0: int = 0

    def start(self):
        self._t0 = time.perf_counter_ns()

    def stop(self, iteration: int) -> int:
        """Record iteration's elapsed ns; returns the elapsed ns."""
        elapsed = time.perf_counter_ns() - self._t0
        if self.first_iter <= iteration <= self.last_iter:
            self.total_ns += elapsed
            self.count += 1
        return elapsed

    def stop_many(self, first_iteration: int, k: int) -> int:
        """Attribute the elapsed time since :meth:`start` evenly to
        iterations [first_iteration, first_iteration + k) — the
        K-steps-per-dispatch case (Trainer.build_multi_step), where
        per-iteration boundaries don't exist on the host."""
        elapsed = time.perf_counter_ns() - self._t0
        share = elapsed // max(k, 1)
        for it in range(first_iteration, first_iteration + k):
            if self.first_iter <= it <= self.last_iter:
                self.total_ns += share
                self.count += 1
        return elapsed

    @property
    def average_ns(self) -> float:
        return self.total_ns / self.count if self.count else 0.0

    @property
    def average_s(self) -> float:
        return self.average_ns / 1e9

    def report(self, prefix: str = "") -> str:
        """The reference prints total + average ns after iteration 39
        (part1/main.py:86-91); same payload here."""
        return (f"{prefix}timing over iterations "
                f"{self.first_iter}-{self.last_iter}: total {self.total_ns} ns, "
                f"average {self.average_ns:.0f} ns "
                f"({self.average_s:.4f} s/iter)")
