"""Checkpoint / resume.

The reference has NO checkpointing (SURVEY.md §5: no ``state_dict``
save/load anywhere; training is 1 epoch from scratch) — this subsystem is
native to the TPU framework so long runs on preemptible TPU slices can
resume. Design:

- A checkpoint is a directory ``step_{N:08d}/`` holding one ``arrays.npz``
  (every leaf of the state pytree, keyed by its tree path) plus
  ``manifest.json`` (step, leaf order, framework version). No pickle.
- Writes are atomic: a ``.tmp-*`` staging dir is renamed into place only
  when complete, so a preempted write can never be mistaken for a valid
  checkpoint.
- Restore maps leaves back into a caller-provided template pytree (the
  standard JAX pattern — ``Trainer.init_state()`` provides it), so device
  placement/sharding of the restored state matches the template's.
- Multi-host: state under pure DP is replicated, so only process 0 writes
  (callers gate on ``jax.process_index() == 0``); every process restores.
- ``keep_last`` prunes old step dirs after a successful write.
"""

from __future__ import annotations

import atexit
import functools
import json
import os
import re
import shutil
import tempfile
import threading
import weakref

import jax
import numpy as np

_STEP_RE = re.compile(r"^step_(\d{8,})$")
_FORMAT_VERSION = 1


def _leaf_key(i: int, path) -> str:
    # Human-readable but unambiguous: "0003:features.2.kernel"
    return f"{i:05d}:" + jax.tree_util.keystr(path, simple=True,
                                              separator=".")


def save_checkpoint(directory: str, state, step: int,
                    keep_last: int | None = None) -> str:
    """Write ``state`` (any pytree of arrays) as step ``step``.

    Returns the final checkpoint path. Atomic: partial writes never
    become visible.
    """
    from tpu_ddp.resilience.integrity import leaf_digest
    os.makedirs(directory, exist_ok=True)
    final = os.path.join(directory, f"step_{step:08d}")
    leaves = jax.tree_util.tree_flatten_with_path(state)[0]
    arrays = {}
    for i, (path, leaf) in enumerate(leaves):
        arrays[_leaf_key(i, path)] = np.asarray(leaf)
    tmp = tempfile.mkdtemp(prefix=".tmp-", dir=directory)
    try:
        with open(os.path.join(tmp, "arrays.npz"), "wb") as f:
            np.savez(f, **arrays)
        manifest = {
            "format_version": _FORMAT_VERSION,
            "step": step,
            "leaves": list(arrays.keys()),
            # Per-leaf sha256 over raw bytes: restore (and the offline
            # verifier, resilience/integrity.py) re-hash and compare, so
            # a truncated npz or flipped bit is caught BEFORE training
            # resumes from garbage.
            "digests": {k: leaf_digest(v) for k, v in arrays.items()},
        }
        with open(os.path.join(tmp, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=1)
        if os.path.isdir(final):
            shutil.rmtree(final)  # re-saving the same step overwrites
        os.rename(tmp, final)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    if keep_last is not None:
        for step_i in all_steps(directory)[:-keep_last]:
            shutil.rmtree(os.path.join(directory, f"step_{step_i:08d}"),
                          ignore_errors=True)
    return final


class AsyncCheckpointWriter:
    """Background-thread checkpoint writes so the train loop never stalls
    on serialization + disk I/O (typically the dominant cost — the
    device->host copy is cheap by comparison and stays synchronous so the
    snapshot is consistent).

    Contract:
    - ``submit`` snapshots the tree to host numpy SYNCHRONOUSLY (the
      caller may donate/mutate device state immediately after), then
      hands the npz write + atomic rename to the writer thread and
      returns the path the checkpoint WILL occupy.
    - At most one write is in flight: a new ``submit`` first joins the
      previous write, preserving checkpoint ordering (and bounding host
      memory at one extra state copy).
    - A failed background write re-raises from the NEXT ``submit``/
      ``wait`` call — a crashed writer can't be silently ignored.
    - ``wait()`` blocks until the in-flight write is durable; call it
      before reading the checkpoint back or exiting the process.
    """

    # Live writers, drained by ONE atexit hook (registered lazily below):
    # the writer thread is a daemon, so without the drain a clean exit
    # would silently abandon the last submitted checkpoint (and swallow
    # any stored write error — wait() re-raises, atexit prints it).
    _live: "weakref.WeakSet[AsyncCheckpointWriter]" = weakref.WeakSet()
    _atexit_registered = False

    @classmethod
    def _drain_all(cls):
        # Drain EVERY writer before surfacing any failure — one failed
        # write must not abandon the other writers' in-flight checkpoints.
        first_error = None
        for writer in list(cls._live):
            try:
                writer.wait()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                if first_error is None:
                    first_error = e
        if first_error is not None:
            raise first_error

    def __init__(self):
        self._thread: threading.Thread | None = None
        self._error: BaseException | None = None
        AsyncCheckpointWriter._live.add(self)
        if not AsyncCheckpointWriter._atexit_registered:
            AsyncCheckpointWriter._atexit_registered = True
            atexit.register(AsyncCheckpointWriter._drain_all)

    def submit(self, directory: str, state, step: int,
               keep_last: int | None = None) -> str:
        # Join the previous write BEFORE snapshotting, so peak host
        # memory stays at one extra state copy (the in-flight write's),
        # per the class contract.
        self.wait()
        # np.array(copy=True), not bare device_get: on the CPU backend
        # device_get can return views aliasing the source buffer (donated
        # or mutated by the very next train step) — the snapshot must own
        # its memory.
        host_state = jax.tree_util.tree_map(
            lambda x: np.array(x, copy=True), jax.device_get(state))

        def write():
            try:
                save_checkpoint(directory, host_state, step,
                                keep_last=keep_last)
            except BaseException as e:  # noqa: BLE001 — re-raised at join
                self._error = e

        self._thread = threading.Thread(target=write, daemon=True,
                                        name=f"ckpt-write-{step}")
        self._thread.start()
        return os.path.join(directory, f"step_{step:08d}")

    def wait(self) -> None:
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError("background checkpoint write failed") \
                from err


@functools.lru_cache(maxsize=8)
def _replicating_identity(sharding):
    return jax.jit(lambda x: x, out_shardings=sharding)


def gather_tree_to_host(tree, repl_sharding):
    """Gather a (possibly sharded) tree to host memory LEAF BY LEAF.

    Each leaf's gather is a collective all processes must enter; doing it
    per leaf keeps the transient device-memory peak at ONE replicated
    leaf rather than the whole tree — the difference between a checkpoint
    and an OOM for ZeRO/FSDP-sharded state. Returns host numpy arrays on
    process 0 and a None-leaved tree elsewhere.
    """
    fn = _replicating_identity(repl_sharding)
    writer = jax.process_index() == 0

    def leaf(x):
        g = fn(x)
        host = np.asarray(g) if writer else None
        g.delete()  # free the replicated copy before the next leaf
        return host

    return jax.tree_util.tree_map(leaf, tree)


def all_steps(directory: str) -> list[int]:
    """Completed checkpoint steps in ``directory``, ascending."""
    if not os.path.isdir(directory):
        return []
    steps = []
    for name in os.listdir(directory):
        m = _STEP_RE.match(name)
        if m and os.path.exists(os.path.join(directory, name,
                                             "manifest.json")):
            steps.append(int(m.group(1)))
    return sorted(steps)


def latest_step(directory: str) -> int | None:
    steps = all_steps(directory)
    return steps[-1] if steps else None


def restore_checkpoint(directory: str, template, step: int | None = None,
                       verify: bool = True,
                       drop_extra: tuple = ()):
    """Restore into the structure of ``template``; returns ``(state, step)``.

    ``template`` supplies the pytree structure (and is typically a freshly
    built state, e.g. ``Trainer.init_state()``); restored leaves are
    returned as numpy arrays in that structure — callers re-place them on
    device (``Trainer.restore`` does). ``step=None`` picks the latest.

    Every leaf is digest-verified against the manifest as it is read
    (``verify=False`` skips — e.g. after an explicit
    ``verify_checkpoint``); unreadable/truncated archives and digest
    mismatches raise :class:`tpu_ddp.resilience.CheckpointCorruptError`
    naming the checkpoint path, so callers can tell "this checkpoint is
    damaged" apart from "this checkpoint is for a different model"
    (which stays ``ValueError``/``KeyError``).

    ``drop_extra`` names top-level path prefixes whose saved leaves are
    IGNORED (e.g. ``("comp_state",)`` lets a compression-less trainer
    read a checkpoint that carries an error-feedback residual). The
    remaining saved leaves must then match the template leaf-for-leaf —
    each surviving key's path is checked against the template's, which
    is stricter than the default count-only structural check.
    """
    from tpu_ddp.resilience.integrity import (CheckpointCorruptError,
                                              leaf_digest)
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(
                f"no checkpoints under {directory!r}")
    path = os.path.join(directory, f"step_{step:08d}")
    try:
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(
            f"unreadable manifest in checkpoint {path!r}: {e}",
            path=path) from e
    if manifest["format_version"] != _FORMAT_VERSION:
        raise ValueError(
            f"checkpoint format {manifest['format_version']} != "
            f"{_FORMAT_VERSION}")
    digests = manifest.get("digests") if verify else None
    npz_path = os.path.join(path, "arrays.npz")
    try:
        npz_cm = np.load(npz_path)
    except Exception as e:  # zipfile.BadZipFile, OSError, …
        raise CheckpointCorruptError(
            f"unreadable checkpoint arrays {npz_path!r}: "
            f"{type(e).__name__}: {e}", path=path) from e
    with npz_cm as npz:
        paths_and_leaves, treedef = \
            jax.tree_util.tree_flatten_with_path(template)
        saved_keys = None
        if drop_extra:
            def _dropped(key: str) -> bool:
                leaf_path = key.split(":", 1)[1]
                return any(leaf_path == p or leaf_path.startswith(p + ".")
                           for p in drop_extra)
            saved_keys = [k for k in manifest["leaves"]
                          if not _dropped(k)]
            if len(paths_and_leaves) != len(saved_keys):
                raise ValueError(
                    f"checkpoint has {len(saved_keys)} leaves after "
                    f"dropping {drop_extra}, template has "
                    f"{len(paths_and_leaves)} — structures differ")
        elif len(paths_and_leaves) != len(manifest["leaves"]):
            raise ValueError(
                f"checkpoint has {len(manifest['leaves'])} leaves, "
                f"template has {len(paths_and_leaves)} — structures differ")
        restored = []
        for i, (tree_path, leaf) in enumerate(paths_and_leaves):
            if saved_keys is not None:
                key = saved_keys[i]
                want_path = jax.tree_util.keystr(tree_path, simple=True,
                                                 separator=".")
                if key.split(":", 1)[1] != want_path:
                    raise KeyError(
                        f"leaf {want_path!r} of the template aligns to "
                        f"saved leaf {key!r} — structure mismatch")
            else:
                key = _leaf_key(i, tree_path)
            if key not in npz:
                raise KeyError(
                    f"leaf {key!r} missing from checkpoint {path!r} "
                    f"(saved: {manifest['leaves'][i]!r}) — structure "
                    f"mismatch")
            try:
                arr = npz[key]
            except Exception as e:  # truncated member: zlib.error, …
                raise CheckpointCorruptError(
                    f"leaf {key!r} of {npz_path!r} failed to read: "
                    f"{type(e).__name__}: {e} — checkpoint is "
                    f"truncated or corrupt", path=path) from e
            if digests is not None and key in digests \
                    and leaf_digest(arr) != digests[key]:
                raise CheckpointCorruptError(
                    f"digest mismatch on leaf {key!r} of {npz_path!r} "
                    f"— checkpoint is corrupt", path=path)
            want = np.shape(leaf)
            if tuple(arr.shape) != tuple(want):
                raise ValueError(
                    f"leaf {key!r}: checkpoint shape {arr.shape} != "
                    f"template shape {want}")
            restored.append(arr)
    return treedef.unflatten(restored), manifest["step"]
