"""Back-compat shim: the HLO scanner moved to ``tpu_ddp.analysis``.

The collective scanner lives in :mod:`tpu_ddp.analysis.hlo` and the
dependence-cone overlap predicates in :mod:`tpu_ddp.analysis.cones`;
this module re-exports every public (and pinned-by-tests private) name
so existing consumers — scripts/comm_volume.py, scripts/overlap_sweep.py,
scripts/compress_sweep.py, bench.py, tests/test_overlap.py,
tests/test_compress.py, tests/test_mpmd.py, tests/test_fleet.py —
keep importing from here unchanged. New code should import from
``tpu_ddp.analysis``.
"""

from __future__ import annotations

from tpu_ddp.analysis.cones import (  # noqa: F401
    HEAVY_OPS,
    UPDATE_OPS,
    _called_comps,
    _COMP_HEADER,
    _element_bytes,
    _HEAVY_CUSTOM,
    _INSTR_LINE,
    _NAME_TOKEN,
    _operand_span,
    _parse_computation,
    _split_computations,
    _update_payload_bytes,
    assert_overlap,
    assert_transfer_overlap,
    overlap_report,
    update_overlap_report,
)
from tpu_ddp.analysis.hlo import (  # noqa: F401
    _INSTR,
    _SHAPE,
    COLLECTIVES,
    DTYPE_BYTES,
    collective_dtype_bytes,
    collective_ops,
    collective_volume,
    dtype_bytes,
    shape_bytes,
    train_step_hlo,
)

__all__ = [
    "COLLECTIVES",
    "DTYPE_BYTES",
    "HEAVY_OPS",
    "UPDATE_OPS",
    "assert_overlap",
    "assert_transfer_overlap",
    "collective_dtype_bytes",
    "collective_ops",
    "collective_volume",
    "dtype_bytes",
    "overlap_report",
    "shape_bytes",
    "train_step_hlo",
    "update_overlap_report",
]
