"""Compiled-HLO collective scanner: ops, dtypes, bytes on the wire.

Factored out of ``scripts/comm_volume.py`` (which re-exports it for its
pinned tests) so jit-level communication claims are checkable anywhere —
the script's ladder table, tests/test_compress.py's reduced-dtype
invariant, and scripts/compress_sweep.py's bytes/step column all scan
with the same parser instead of three regex forks.

The scan is textual over ``compiled.as_text()``: each collective
instruction's RESULT shape gives its payload (for all-reduce and
collective-permute result == operand; reduce-scatter's input is
result * N; all-gather's result already is the gathered size — the ring
cost model accounts for each). Tuple-shaped results (all-to-all renders
as ``(s8[1,256], s8[1,256], ...)`` per peer) sum their elements.

Why per-dtype accounting exists: gradient compression
(parallel/compress.py) promises the collective EXECUTES at the reduced
dtype. That is a claim about compiled HLO — XLA float-normalization can
legalize a bf16 collective back to f32, silently widening the wire while
keeping the numerics — so the invariant is "scan the compiled text and
check the payload bytes per dtype", not "trust the jaxpr".
"""

from __future__ import annotations

import re

DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
               "f16": 2, "bf16": 2, "s32": 4, "u32": 4, "f32": 4,
               "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16}

COLLECTIVES = ("all-reduce", "reduce-scatter", "all-gather",
               "all-to-all", "collective-permute")

# One HLO instruction: "%name = <shape> op-name(...)" where <shape> is
# "f32[a,b]{layout}" or a tuple "(f32[a]{0}, f32[b]{0})".
_INSTR = re.compile(
    r"=\s*(\([^)]*\)|[a-z0-9]+\[[0-9,]*\][^ ]*)\s+"
    r"(" + "|".join(COLLECTIVES) + r")(?:-start)?\(")

_SHAPE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")


def shape_bytes(shape_str: str) -> int:
    """Total bytes of an HLO shape string (tuples sum their elements)."""
    return sum(dtype_bytes(shape_str).values())


def dtype_bytes(shape_str: str) -> dict:
    """Per-dtype byte totals of an HLO shape string."""
    out: dict = {}
    for dtype, dims in _SHAPE.findall(shape_str):
        if dtype not in DTYPE_BYTES:
            continue  # e.g. token[] / opaque
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        out[dtype] = out.get(dtype, 0) + n * DTYPE_BYTES[dtype]
    return out


def collective_ops(hlo_text: str) -> list:
    """Every collective instruction as ``{"op", "shape", "payload_bytes",
    "dtype_bytes"}`` in program order — the raw per-op view
    ``collective_volume`` aggregates."""
    found = []
    for m in _INSTR.finditer(hlo_text):
        shape_str, op = m.group(1), m.group(2)
        per_dtype = dtype_bytes(shape_str)
        found.append({"op": op, "shape": shape_str,
                      "payload_bytes": sum(per_dtype.values()),
                      "dtype_bytes": per_dtype})
    return found


def collective_dtype_bytes(hlo_text: str) -> dict:
    """Payload bytes per dtype summed over ALL collectives — the
    reduced-dtype invariant's input: a compressed step must put its
    gradient payload under s8/u16, with f32 collective traffic bounded
    by the per-block scales + scalar psums (loss terms, guard flag)."""
    totals: dict = {}
    for rec in collective_ops(hlo_text):
        for dt, b in rec["dtype_bytes"].items():
            totals[dt] = totals.get(dt, 0) + b
    return totals


def collective_volume(hlo_text: str, n_devices: int) -> dict:
    """Scan compiled HLO for collective ops; payload + ring wire bytes.

    Ring cost model per device (reference CS744 §2.2.2 and the
    docstring of scripts/comm_volume.py):

    - all-reduce:          2 * (N-1)/N * payload
    - reduce-scatter:          (N-1)/N * input payload (= result * N)
    - all-gather:              (N-1)/N * output payload
    - all-to-all:              (N-1)/N * payload
    - collective-permute:                payload      (one neighbor hop)
    """
    ops: dict = {k: {"count": 0, "payload_bytes": 0, "dtype_bytes": {}}
                 for k in COLLECTIVES}
    for rec in collective_ops(hlo_text):
        agg = ops[rec["op"]]
        agg["count"] += 1
        agg["payload_bytes"] += rec["payload_bytes"]
        for dt, b in rec["dtype_bytes"].items():
            agg["dtype_bytes"][dt] = agg["dtype_bytes"].get(dt, 0) + b
    frac = (n_devices - 1) / n_devices
    wire = 0.0
    for op, rec in ops.items():
        if op == "all-reduce":
            rec["wire_bytes_per_device"] = 2 * frac * rec["payload_bytes"]
        elif op == "reduce-scatter":
            # result is the 1/N shard; input payload = result * N.
            rec["wire_bytes_per_device"] = (frac * rec["payload_bytes"]
                                            * n_devices)
        elif op == "all-gather":
            rec["wire_bytes_per_device"] = frac * rec["payload_bytes"]
        elif op == "all-to-all":
            rec["wire_bytes_per_device"] = frac * rec["payload_bytes"]
        else:  # collective-permute: one neighbor hop
            rec["wire_bytes_per_device"] = float(rec["payload_bytes"])
        wire += rec["wire_bytes_per_device"]
    ops = {k: v for k, v in ops.items() if v["count"]}
    return {"ops": ops, "total_wire_bytes_per_device": wire,
            "total_collectives": sum(v["count"] for v in ops.values()),
            "dtype_payload_bytes": collective_dtype_bytes(hlo_text)}


def train_step_hlo(trainer, state, images, labels, weights) -> str:
    """Compiled HLO text of a Trainer's jitted train step (handles the
    stateful-compression signature via ``Trainer.lower_train_step``)."""
    return trainer.lower_train_step(
        state, images, labels, weights).compile().as_text()


# ---------------------------------------------------------------------------
# Overlap verdict: is the gradient traffic bucketized such that the
# scheduler COULD hide it behind backward compute?
#
# This is deliberately a DATAFLOW predicate, not a schedule one.  The CPU
# backend (where tests run) strips ``optimization_barrier`` and its linear
# scheduler is free to sink every collective to the end of the step, so
# "collective appears between two convolutions in program order" proves
# nothing either way.  What bucketization actually changes is the
# dependence structure: with one fused collective, every heavy backward op
# (convolution/dot) is an ANCESTOR of the collective, so no compute can
# ever run concurrently with it; with k buckets issued reverse-autodiff
# order, bucket 0's collective is independent of the (still pending)
# backward compute of buckets 1..k-1 — a latency-hiding scheduler (the
# TPU one) is then ALLOWED to overlap them.  We check exactly that: a
# gradient collective is *overlappable* iff some heavy op is neither in
# its ancestor cone nor in its descendant cone.
#
# Verdict rule: >= 2 gradient-sized collectives, and at least
# ``max(1, n // 2)`` of them overlappable.  The last bucket (input-side
# leaves, fires after all backward compute) and the reassembly gathers of
# the final bucket are structurally never overlappable, hence majority
# rather than all.  The negative control is a SINGLE-bucket overlap step
# (``bucket_mb`` larger than the model): one concatenated collective
# whose ancestor cone contains every heavy op — the "flatten, concat,
# sync once" anti-pattern torch DDP's bucketing exists to avoid.  Note
# the per-leaf baseline rungs (sync.py) genuinely ARE dataflow-
# overlappable and report as such; what bucketing changes vs per-leaf is
# launch count and payload sizing (per-tensor latency), not dependence
# structure, so the verdict for them being True is correct, not a false
# positive.
# ---------------------------------------------------------------------------

HEAVY_OPS = ("convolution", "dot")

# CPU/GPU backends frequently legalize conv/gemm into custom-calls
# (oneDNN / Eigen / cuDNN); match those targets as heavy too.
_HEAVY_CUSTOM = re.compile(r"conv|gemm|matmul|dot|onednn|dnn|eigen", re.I)

# Param lists may nest parens (while/region bodies take TUPLE params:
# ``%while_body (p: (s32[], f32[...])) -> (...) {``) — ``\(.*\)`` spans
# them; ``[^)]*`` would drop exactly the computations that hold a
# pipelined step's edge collectives.
_COMP_HEADER = re.compile(
    r"^(?P<entry>ENTRY\s+)?%?(?P<name>[\w.\-]+)\s*\(.*\)\s*->\s*.*\{")

_INSTR_LINE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*"
    r"(?P<shape>\([^)]*\)|[\w\[\],]+(?:\{[^}]*\})?)\s+"
    r"(?P<op>[\w\-]+)\(")

_NAME_TOKEN = re.compile(r"%?([\w.\-]+)")


def _split_computations(hlo_text: str) -> dict:
    """Map computation name -> list of raw instruction lines."""
    comps: dict = {}
    current = None
    for line in hlo_text.splitlines():
        stripped = line.strip()
        if current is None:
            m = _COMP_HEADER.match(stripped)
            if m and "=" not in stripped.split("(", 1)[0]:
                current = m.group("name")
                comps[current] = []
        elif stripped == "}":
            current = None
        elif stripped:
            comps[current].append(line)
    return comps


def _operand_span(line: str, start: int) -> str:
    """Text of the balanced operand parens opening at ``line[start]``."""
    depth = 0
    for i in range(start, len(line)):
        if line[i] == "(":
            depth += 1
        elif line[i] == ")":
            depth -= 1
            if depth == 0:
                return line[start + 1:i]
    return line[start + 1:]


def _parse_computation(lines: list) -> dict:
    """name -> {"op", "shape", "operands": [names], "attrs": str}."""
    instrs: dict = {}
    order = []
    for line in lines:
        m = _INSTR_LINE.match(line)
        if not m:
            continue
        open_at = line.index("(", m.end("op"))
        operands_txt = _operand_span(line, open_at)
        attrs = line[open_at + len(operands_txt) + 2:]
        instrs[m.group("name")] = {
            "op": m.group("op"), "shape": m.group("shape"),
            "operands_txt": operands_txt, "attrs": attrs,
        }
        order.append(m.group("name"))
    for name in order:
        rec = instrs[name]
        rec["operands"] = [
            t for t in _NAME_TOKEN.findall(rec.pop("operands_txt"))
            if t in instrs and t != name]
    return instrs


def _called_comps(attrs: str) -> list:
    """Computation names referenced by an instruction's attributes
    (calls= / to_apply= / body= / condition= / branch_computations=)."""
    return re.findall(r"=\s*\{?%?([\w.\-]+)", attrs)


def _comp_has_heavy(comp_name, comps_instrs, memo) -> bool:
    if comp_name in memo:
        return memo[comp_name]
    memo[comp_name] = False  # cycle guard
    heavy = False
    for rec in comps_instrs.get(comp_name, {}).values():
        if _instr_is_heavy(rec, comps_instrs, memo):
            heavy = True
            break
    memo[comp_name] = heavy
    return heavy


def _instr_is_heavy(rec, comps_instrs, memo) -> bool:
    if rec["op"] in HEAVY_OPS:
        return True
    if rec["op"] == "custom-call" and _HEAVY_CUSTOM.search(rec["attrs"]):
        return True
    if rec["op"] in ("fusion", "call", "while", "conditional", "map"):
        return any(_comp_has_heavy(c, comps_instrs, memo)
                   for c in _called_comps(rec["attrs"]))
    return False


def overlap_report(hlo_text: str, min_payload_bytes: int = 1024) -> dict:
    """Dataflow overlap verdict for a compiled train step.

    Scans the computation with the most gradient-sized collectives
    (ENTRY for a plain step, the while-body for a K-step scan), builds
    the dependence graph, and classifies each collective as overlappable
    iff some heavy op (convolution/dot, incl. fused/custom-call forms)
    lies outside both its ancestor and descendant cones.

    ``min_payload_bytes`` filters out the scalar bookkeeping collectives
    (loss psum, StepGuard flag) that exist on every rung regardless of
    bucketing.  Never raises — ``assert_overlap`` wraps this for tests;
    bench.py records the raw report.
    """
    comps_lines = _split_computations(hlo_text)
    comps_instrs = {name: _parse_computation(lines)
                    for name, lines in comps_lines.items()}
    heavy_memo: dict = {}

    def grad_collectives(instrs):
        out = []
        for name, rec in instrs.items():
            op = rec["op"]
            base = op[:-6] if op.endswith("-start") else op
            if base not in COLLECTIVES:
                continue
            payload = shape_bytes(rec["shape"])
            if base == "reduce-scatter":
                # result is the 1/N shard; grad payload is the input.
                ops = rec["operands"]
                if ops:
                    payload = shape_bytes(instrs[ops[0]]["shape"])
            if payload >= min_payload_bytes:
                out.append((name, base, payload))
        return out

    target, target_colls = None, []
    for name, instrs in comps_instrs.items():
        colls = grad_collectives(instrs)
        if len(colls) > len(target_colls):
            target, target_colls = name, colls
    if target is None:
        return {"overlapped": False, "n_grad_collectives": 0,
                "n_overlappable": 0, "n_heavy_ops": 0,
                "computation": None, "collectives": [],
                "min_payload_bytes": min_payload_bytes,
                "schedule_interleaved": None}

    instrs = comps_instrs[target]
    names = list(instrs)
    idx = {n: i for i, n in enumerate(names)}

    # Ancestor cones as bitmasks; HLO text is def-before-use so a single
    # forward pass suffices (operands of x always precede x).
    anc = [0] * len(names)
    for i, n in enumerate(names):
        m = 0
        for o in instrs[n]["operands"]:
            j = idx[o]
            m |= anc[j] | (1 << j)
        anc[i] = m

    heavy_idx = [i for i, n in enumerate(names)
                 if _instr_is_heavy(instrs[n], comps_instrs, heavy_memo)]
    heavy_mask = 0
    for i in heavy_idx:
        heavy_mask |= 1 << i

    coll_idx = {n: idx[n] for n, _, _ in target_colls}
    # Descendant cone of each collective: every instr whose ancestor
    # mask contains the collective's bit.
    desc = {n: 0 for n in coll_idx}
    for i in range(len(names)):
        for n, ci in coll_idx.items():
            if anc[i] >> ci & 1:
                desc[n] |= 1 << i

    collectives = []
    n_overlappable = 0
    for n, base, payload in target_colls:
        ci = coll_idx[n]
        free = heavy_mask & ~anc[ci] & ~desc[n] & ~(1 << ci)
        ok = bool(free)
        n_overlappable += ok
        collectives.append({"name": n, "op": base,
                            "payload_bytes": payload,
                            "overlappable": ok})

    # Informational only: does program order already interleave heavy
    # compute between the grad collectives?  (The CPU scheduler often
    # doesn't even when the dataflow allows it; TPU's does.)
    positions = sorted(coll_idx.values())
    interleaved = None
    if len(positions) >= 2 and heavy_idx:
        interleaved = any(positions[0] < h < positions[-1]
                          for h in heavy_idx)

    n = len(target_colls)
    return {
        "overlapped": bool(n >= 2 and n_overlappable >= max(1, n // 2)),
        "n_grad_collectives": n,
        "n_overlappable": n_overlappable,
        "n_heavy_ops": len(heavy_idx),
        "computation": target,
        "collectives": collectives,
        "min_payload_bytes": min_payload_bytes,
        "schedule_interleaved": interleaved,
    }


# ---------------------------------------------------------------------------
# The same dataflow predicate, generalized from collectives to LARGE
# in-place updates — the disagg fleet's KV-block adoption scatter
# (tpu_ddp/fleet/disagg.py). The claim to check is identical in shape:
# the fused adopt+decode program applies the transfer's payload with a
# scatter that depends on nothing the decode computes (it runs against
# freshly allocated, table-less block ids), so a latency-hiding
# scheduler is ALLOWED to land the transfer behind decode compute. A
# wrong fusion order — adopting AFTER the bank's writes — would put
# every heavy op in the scatter's ancestor cone and serialize the edge
# behind the step; that is the regression this analysis exists to
# catch.
#
# Backend reality: XLA rarely leaves ``scatter`` standing at the entry
# computation. The CPU expander lowers a multi-row scatter into a
# ``while`` loop whose carried state holds the updates payload, and
# single-row updates fuse into loop fusions with a
# ``dynamic-update-slice`` root. The target picker therefore matches
# any entry instruction that IS or CONTAINS (via called computations)
# a scatter/dynamic-update-slice, and sizes its payload from the
# shapes riding along: the largest tuple element / operand that is
# NOT the in-place buffer itself (the buffer is always the biggest —
# it's the whole pool). ``min_update_bytes`` then separates the
# block-payload adoption (KBs per transfer) from the bank's own
# per-token writes (one row per slot).
# ---------------------------------------------------------------------------

UPDATE_OPS = ("scatter", "dynamic-update-slice")

_ENTRY_NAME = re.compile(r"^ENTRY\s+%?([\w.\-]+)", re.M)


def _comp_has_update(comp_name, comps_instrs, memo) -> bool:
    if comp_name in memo:
        return memo[comp_name]
    memo[comp_name] = False  # cycle guard
    found = False
    for rec in comps_instrs.get(comp_name, {}).values():
        if _instr_has_update(rec, comps_instrs, memo):
            found = True
            break
    memo[comp_name] = found
    return found


def _instr_has_update(rec, comps_instrs, memo) -> bool:
    if rec["op"] in UPDATE_OPS:
        return True
    if rec["op"] in ("fusion", "call", "while", "conditional", "map"):
        return any(_comp_has_update(c, comps_instrs, memo)
                   for c in _called_comps(rec["attrs"]))
    return False


def _element_bytes(shape_str: str) -> list:
    """Byte size of each array element of an HLO shape string (one
    entry for a plain array, one per element for a tuple)."""
    sizes = []
    for dtype, dims in _SHAPE.findall(shape_str):
        if dtype not in DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        sizes.append(n * DTYPE_BYTES[dtype])
    return sizes


def _update_payload_bytes(rec, instrs) -> int:
    """Updates-operand size for an update-carrying instruction: the
    largest shape riding along that is NOT the in-place buffer. For a
    tuple result (scatter lowered to a while loop) the candidates are
    the tuple elements; otherwise the resolvable operand shapes."""
    if rec["shape"].startswith("("):
        sizes = _element_bytes(rec["shape"])
    else:
        sizes = []
        for o in rec.get("operands", []):
            if o in instrs:
                sizes.extend(_element_bytes(instrs[o]["shape"]))
        sizes.extend([max(_element_bytes(rec["shape"]) or [0])])
    if len(sizes) < 2:
        return 0
    sizes.sort()
    buffer_bytes = sizes[-1]
    rest = [s for s in sizes[:-1] if s < buffer_bytes]
    return max(rest) if rest else 0


def update_overlap_report(hlo_text: str,
                          min_update_bytes: int = 4096) -> dict:
    """Dataflow overlap verdict for large in-place updates in the
    ENTRY computation — the disagg KV-adoption check.

    The predicate is STRICTER than the collective one, because "some
    heavy op outside both cones" is true even of a landing serialized
    at the very end of the step (it could still overlap the sampling
    tail). What "the transfer lands behind decode compute" actually
    requires is that the landing can START at step begin: a target is
    overlappable iff it has NO heavy ancestor (it waits on no compute)
    AND at least one heavy op sits outside both its cones (there is
    compute to hide behind). The verdict requires the LARGEST update
    (the transfer landing) to pass. Never raises —
    ``assert_transfer_overlap`` wraps it.
    """
    entry = _ENTRY_NAME.search(hlo_text)
    empty = {"overlapped": False, "n_updates": 0, "n_overlappable": 0,
             "n_heavy_ops": 0, "computation": None, "updates": [],
             "min_update_bytes": min_update_bytes}
    if entry is None:
        return empty
    comps_lines = _split_computations(hlo_text)
    comps_instrs = {name: _parse_computation(lines)
                    for name, lines in comps_lines.items()}
    target = entry.group(1)
    if target not in comps_instrs:
        return empty
    instrs = comps_instrs[target]
    update_memo: dict = {}
    heavy_memo: dict = {}

    targets = []
    for name, rec in instrs.items():
        if not _instr_has_update(rec, comps_instrs, update_memo):
            continue
        payload = _update_payload_bytes(rec, instrs)
        if payload >= min_update_bytes:
            targets.append((name, payload))
    if not targets:
        return dict(empty, computation=target)

    names = list(instrs)
    idx = {n: i for i, n in enumerate(names)}
    anc = [0] * len(names)
    for i, n in enumerate(names):
        m = 0
        for o in instrs[n]["operands"]:
            j = idx[o]
            m |= anc[j] | (1 << j)
        anc[i] = m
    heavy_mask = 0
    n_heavy = 0
    for i, n in enumerate(names):
        if _instr_is_heavy(instrs[n], comps_instrs, heavy_memo):
            heavy_mask |= 1 << i
            n_heavy += 1

    tgt_idx = {n: idx[n] for n, _ in targets}
    desc = {n: 0 for n in tgt_idx}
    for i in range(len(names)):
        for n, ti in tgt_idx.items():
            if anc[i] >> ti & 1:
                desc[n] |= 1 << i

    updates = []
    n_overlappable = 0
    for n, payload in targets:
        ti = tgt_idx[n]
        # Heavy ops the landing must WAIT for (its ancestor cone): any
        # here means the transfer cannot start until compute finishes —
        # the serialized bad ordering, regardless of how much free
        # compute the tail still has.
        blocked_by = heavy_mask & anc[ti]
        free = heavy_mask & ~anc[ti] & ~desc[n] & ~(1 << ti)
        ok = not blocked_by and bool(free)
        n_overlappable += ok
        updates.append({"name": n, "payload_bytes": payload,
                        "n_heavy_ancestors": bin(blocked_by).count("1"),
                        "overlappable": ok})
    updates.sort(key=lambda u: -u["payload_bytes"])
    return {
        "overlapped": bool(updates and updates[0]["overlappable"]),
        "n_updates": len(updates),
        "n_overlappable": n_overlappable,
        "n_heavy_ops": n_heavy,
        "computation": target,
        "updates": updates,
        "min_update_bytes": min_update_bytes,
    }


def assert_transfer_overlap(hlo_text: str,
                            min_update_bytes: int = 4096) -> dict:
    """Raise ``AssertionError`` unless the program's largest in-place
    update (the disagg transfer landing) is dataflow-overlappable with
    heavy compute; returns the report on success."""
    report = update_overlap_report(hlo_text,
                                   min_update_bytes=min_update_bytes)
    if not report["overlapped"]:
        raise AssertionError(
            "the transfer-landing update is not overlappable with "
            f"compute: {report['n_overlappable']}/{report['n_updates']} "
            f"updates (>= {min_update_bytes}B payload) start free of "
            "heavy ancestors with heavy ops outside their cones "
            f"(computation={report['computation']!r}, "
            f"heavy_ops={report['n_heavy_ops']}, "
            f"updates={[(u['name'], u['n_heavy_ancestors']) for u in report['updates']]})")
    return report


def assert_overlap(hlo_text: str, min_payload_bytes: int = 1024) -> dict:
    """Raise ``AssertionError`` unless ``overlap_report`` says the step's
    gradient collectives are bucketized-and-overlappable; returns the
    report on success so callers can log it."""
    report = overlap_report(hlo_text, min_payload_bytes=min_payload_bytes)
    if not report["overlapped"]:
        raise AssertionError(
            "gradient collectives are not overlappable with compute: "
            f"{report['n_overlappable']}/{report['n_grad_collectives']} "
            f"grad-sized collectives (>= {min_payload_bytes}B) have "
            "heavy ops outside their dependence cones "
            f"(computation={report['computation']!r}, "
            f"heavy_ops={report['n_heavy_ops']})")
    return report
