"""Profiler integration.

The reference's only tracing is a hand-rolled ``perf_counter_ns`` harness
(SURVEY.md §5) — preserved in :mod:`tpu_ddp.utils.timing`. This module adds
the TPU-native deep profiler: XLA device traces via ``jax.profiler``,
viewable in TensorBoard/Perfetto, enabled by flag or the
``TPU_DDP_PROFILE_DIR`` env var.
"""

from __future__ import annotations

import contextlib
import os

import jax


@contextlib.contextmanager
def profile_trace(logdir: str | None = None):
    """Capture a device trace into ``logdir`` for the duration of the
    ``with`` block; no-op when ``logdir`` is falsy."""
    if not logdir:
        yield
        return
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region that shows up on the trace timeline (host + device)."""
    return jax.profiler.TraceAnnotation(name)


def profile_dir_from_env() -> str | None:
    return os.environ.get("TPU_DDP_PROFILE_DIR") or None
