"""Runtime correctness invariants + fault injection.

The reference's correctness rests on two invariants stated in its report
(SURVEY.md §1 L1): (i) identical parameter init on every node, (ii)
identical parameter updates via gradient sync. It has no machinery to
CHECK them — a silent sync bug (the data-parallel analogue of a data
race) shows up only as a mysteriously bad loss curve. This module makes
the invariant checkable at runtime, plus a deterministic fault-injection
hook for exercising failure/restart paths (the reference has neither —
SURVEY.md §5 "Race detection: Absent", "Failure detection: Absent").

- :func:`replica_divergence` — per-leaf maximum absolute difference
  between device copies of replicated arrays: local shards are compared
  directly; across processes a per-leaf digest is all-gathered and
  compared. Zero everywhere iff every replica holds identical values.
- :func:`check_replica_consistency` — raises ``ReplicaDivergenceError``
  naming the worst leaf when divergence exceeds ``atol``. The engine
  calls it every ``check_replicas_every`` steps when configured.
- :func:`maybe_inject_failure` — BACK-COMPAT SHIM. Fault injection
  graduated into the resilience subsystem
  (:mod:`tpu_ddp.resilience.chaos`), which generalizes the single
  hard-exit knob into five fault kinds behind ``TPU_DDP_CHAOS_*`` env
  config; the name (and :data:`FAULT_EXIT_CODE`) stay importable from
  here with identical semantics.
"""

from __future__ import annotations

import jax
import numpy as np

from tpu_ddp.resilience.chaos import (  # noqa: F401  (back-compat)
    FAULT_EXIT_CODE, maybe_inject_failure)


class ReplicaDivergenceError(RuntimeError):
    pass


def _leaf_paths(tree):
    import jax.tree_util as jtu
    return [(jtu.keystr(path), leaf)
            for path, leaf in jtu.tree_flatten_with_path(tree)[0]]


def _bitwise_digest(arr: np.ndarray) -> np.uint64:
    """First 8 bytes of sha256 over the raw array bytes: equal iff (with
    overwhelming probability) the arrays are bitwise equal — a sum-style
    digest would miss divergences that preserve the sum (e.g. two
    swapped elements)."""
    import hashlib
    h = hashlib.sha256(np.ascontiguousarray(arr).tobytes()).digest()
    return np.frombuffer(h[:8], dtype=np.uint64)[0]


def replica_divergence(tree) -> dict:
    """{leaf path: max abs divergence} over replicated leaves.

    Local device copies are compared element-wise (the values feed the
    ``atol`` tolerance); ACROSS processes the comparison is a bitwise
    digest — any cross-process difference reports ``inf`` (a tolerance
    cannot be evaluated without shipping whole arrays between hosts).
    Non-replicated (sharded) leaves are skipped — each device
    legitimately holds different values there.
    """
    out = {}
    digests = []
    names = []
    for name, leaf in _leaf_paths(tree):
        if not hasattr(leaf, "addressable_shards"):
            continue
        if not getattr(leaf, "is_fully_replicated", False):
            continue
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        worst = 0.0
        for s in shards[1:]:
            worst = max(worst,
                        float(np.max(np.abs(s - shards[0]))) if s.size
                        else 0.0)
        out[name] = worst
        digests.append(_bitwise_digest(shards[0]))
        names.append(name)
    if jax.process_count() > 1 and digests:
        from jax.experimental import multihost_utils
        all_digests = np.asarray(multihost_utils.process_allgather(
            np.asarray(digests, np.uint64)))
        for col, name in enumerate(names):
            if len(np.unique(all_digests[:, col])) > 1:
                out[name] = float("inf")
    return out


def check_replica_consistency(tree, atol: float = 0.0) -> dict:
    """Raise :class:`ReplicaDivergenceError` if any replicated leaf's
    copies differ by more than ``atol``; returns the divergence map."""
    div = replica_divergence(tree)
    bad = {k: v for k, v in div.items() if v > atol}
    if bad:
        worst = max(bad, key=bad.get)
        raise ReplicaDivergenceError(
            f"replica divergence on {len(bad)} leaves; worst "
            f"{worst}: {bad[worst]:.3e} (invariant (ii) of the reference "
            f"report: replicas must hold identical parameters)")
    return div
