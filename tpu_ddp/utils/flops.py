"""Analytic FLOP accounting + chip peak detection — the MFU story.

The reference's report judges performance as time/iteration on known
hardware (CS744__Assignment_2.pdf §3, Table 1); on TPU the honest analogue
is MFU: achieved model FLOP/s divided by the chip's peak. This module
provides the three ingredients the bench needs:

- ``*_fwd_flops``: analytic forward FLOPs per step for each model family
  (matmul/conv terms only, multiply+add = 2 FLOPs; BN/LN/softmax/elementwise
  are bandwidth- not FLOP-bound and are excluded, the standard MFU
  convention). Training FLOPs = ``TRAIN_FLOPS_MULT`` x forward (backward
  does the two grad matmuls per forward matmul). Attention is counted at
  the full L^2 term (PaLM appendix-B convention — causal masking halves the
  work the chip does but not the "model FLOPs" denominator).
- ``xla_flops``: the compiled program's own FLOP count from XLA's cost
  analysis — includes everything (backward, optimizer, remat recompute), so
  it is the *hardware* FLOP count; recorded alongside as a cross-check.
- ``peak_tflops``: bf16 dense per-chip peak by ``device_kind``, overridable
  with ``TPU_DDP_PEAK_TFLOPS`` for kinds not in the table.
"""

from __future__ import annotations

import os

import numpy as np

# Backward pass ~= 2x forward (one matmul each for dL/dx and dL/dW per
# forward matmul); optimizer FLOPs are negligible against the matmuls.
TRAIN_FLOPS_MULT = 3

# bf16 dense peak TFLOP/s PER CHIP, keyed by substrings of
# jax.Device.device_kind (checked in order; first match wins). Public
# numbers: v2 180/board(4 chips), v3 123/chip, v4 275, v4i 138,
# v5e 197, v5p 459, v6e (Trillium) 918.
_PEAKS = (
    ("v6", 918.0),
    ("v5p", 459.0),
    ("v5 lite", 197.0),
    ("v5e", 197.0),
    ("v5litepod", 197.0),
    ("v5", 459.0),
    ("v4 lite", 138.0),
    ("v4i", 138.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 46.0),
)


def peak_tflops(device) -> tuple[float | None, str]:
    """(bf16 peak TFLOP/s for ``device``, source string).

    ``TPU_DDP_PEAK_TFLOPS`` overrides (for chips not in the table); a
    non-TPU platform or unknown kind returns (None, reason) — the bench
    then reports achieved FLOP/s but a null MFU rather than a wrong one.
    """
    env = os.environ.get("TPU_DDP_PEAK_TFLOPS")
    if env:
        try:
            return float(env), "env:TPU_DDP_PEAK_TFLOPS"
        except ValueError:
            return None, f"unparseable TPU_DDP_PEAK_TFLOPS={env!r}"
    if device.platform != "tpu":
        return None, f"non-TPU platform {device.platform!r}: no peak table"
    kind = device.device_kind.lower()
    for sub, peak in _PEAKS:
        if sub in kind:
            return peak, f"device_kind {device.device_kind!r}"
    return None, f"unknown device_kind {device.device_kind!r}"


def vgg_fwd_flops(cfg, image_size: int, batch: int,
                  num_classes: int = 10, in_channels: int = 3) -> int:
    """Forward FLOPs for one VGG step (models/vgg.py channel plans)."""
    h = w = image_size
    c_in = in_channels
    per_image = 0
    for width in cfg:
        if width == "M":
            h //= 2
            w //= 2
            continue
        per_image += 2 * 9 * c_in * width * h * w  # 3x3 SAME conv
        c_in = width
    per_image += 2 * c_in * num_classes  # 512 -> classes head
    return per_image * batch


def resnet_fwd_flops(stage_blocks, image_size: int, batch: int,
                     num_classes: int = 1000, in_channels: int = 3,
                     small_inputs: bool = False) -> int:
    """Forward FLOPs for one bottleneck-ResNet step, mirroring the shape
    walk of models/resnet.py:apply (stem, 4 stages, head)."""
    stage_widths = (64, 128, 256, 512)
    h = image_size // (1 if small_inputs else 2)
    stem_hw = 3 if small_inputs else 7
    per_image = 2 * stem_hw * stem_hw * in_channels * 64 * h * h
    if not small_inputs:
        h //= 2  # stem max-pool
    c_in = 64
    for si, n_blocks in enumerate(stage_blocks):
        width = stage_widths[si]
        for bi in range(n_blocks):
            stride = 2 if (si > 0 and bi == 0) else 1
            h_out = h // stride
            per_image += 2 * c_in * width * h * h            # conv1 1x1
            per_image += 2 * 9 * width * width * h_out * h_out  # conv2 3x3
            per_image += 2 * width * width * 4 * h_out * h_out  # conv3 1x1
            if bi == 0 and c_in != width * 4:
                per_image += 2 * c_in * width * 4 * h_out * h_out  # proj
            c_in = width * 4
            h = h_out
    per_image += 2 * c_in * num_classes
    return per_image * batch


def transformer_fwd_flops(model, batch: int, seq_len: int) -> int:
    """Forward FLOPs for one decoder-LM step (models/transformer.py).

    2 x (matmul params) per token + the attention score/value matmuls at
    4*d_model*L per token per layer (full-L convention; GQA changes K/V
    projection size, not the score matmuls). MoE models count ACTIVE
    expert params (top_k experts per token).
    """
    dm, dff = model.d_model, model.d_ff
    h, kvh, hd = model.num_heads, model.kv_heads, model.head_dim
    per_layer = dm * (h * hd + 2 * kvh * hd)   # wqkv (fused or split)
    per_layer += h * hd * dm                   # wo
    mlp = 2 * dm * dff                         # w1 + w2
    if model.moe_experts:
        mlp *= max(model.moe_top_k, 1)         # active experts per token
        per_layer += dm * model.moe_experts    # router
    per_layer += mlp
    matmul_params = model.num_layers * per_layer + dm * model.vocab_size
    tokens = batch * seq_len
    attn = 4 * dm * seq_len * model.num_layers  # QK^T + AV per token
    return tokens * (2 * matmul_params + attn)


def vit_fwd_flops(model, batch: int) -> int:
    """Forward FLOPs for one ViT classifier step (models/vit.py): patch
    embed + encoder blocks (full-L² attention convention) + GAP head."""
    n = model.num_patches
    dm, dff = model.d_model, model.d_ff
    patch_in = model.patch_size ** 2 * model.in_channels
    per_image = 2 * n * patch_in * dm                    # patch embed GEMM
    per_layer = 2 * n * (4 * dm * dm + 2 * dm * dff)     # qkv+o, mlp
    per_layer += 4 * n * n * dm                          # QK^T + AV
    per_image += model.num_layers * per_layer
    per_image += 2 * dm * model.num_classes
    return per_image * batch


def train_flops(fwd_flops: int) -> int:
    return TRAIN_FLOPS_MULT * fwd_flops


def xla_flops(jitted_fn, *args) -> float | None:
    """FLOPs of the compiled program per XLA's cost analysis, or None if
    the backend doesn't report them. This counts what the hardware
    executes (incl. remat recompute), not the analytic model FLOPs."""
    try:
        analysis = jitted_fn.lower(*args).compile().cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        flops = analysis.get("flops")
        if flops is None or not np.isfinite(flops) or flops <= 0:
            return None
        return float(flops)
    except Exception:
        return None


# Public HBM bandwidth GB/s per chip, keyed like _PEAKS: v2 700/board,
# v3 900, v4 1228, v5e 819, v5p 2765, v6e (Trillium) 1640.
_HBM_GBPS = (
    ("v6", 1640.0),
    ("v5p", 2765.0),
    ("v5 lite", 819.0),
    ("v5e", 819.0),
    ("v5litepod", 819.0),
    ("v5", 2765.0),
    ("v4", 1228.0),
    ("v3", 900.0),
    ("v2", 700.0),
)


def device_hbm_gbps(device,
                    default: float = 819.0) -> tuple[float, str]:
    """(HBM bandwidth GB/s for ``device``, source label).

    ``TPU_DDP_HBM_GBPS`` overrides; unknown kinds fall back to
    ``default`` (the v5e bench chip) with the source saying so — so
    bandwidth-utilization accounting degrades to a LABELED estimate,
    never a number indistinguishable from a real measurement (the
    peak_tflops contract, with a fallback instead of None)."""
    env = os.environ.get("TPU_DDP_HBM_GBPS")
    if env:
        try:
            return float(env), "env:TPU_DDP_HBM_GBPS"
        except ValueError:
            pass
    kind = getattr(device, "device_kind", "")
    for sub, bw in _HBM_GBPS:
        if sub in kind.lower():
            return bw, f"device_kind {kind!r}"
    return default, (f"FALLBACK default (platform "
                     f"{getattr(device, 'platform', '?')!r}, kind "
                     f"{kind!r} not in table) — estimate, not the "
                     "real chip's bandwidth")


def mfu_fields(flops_per_step: float | None, step_seconds: float,
               device, xla_flops_per_step: float | None = None) -> dict:
    """The bench JSON's MFU block: achieved TFLOP/s, peak, MFU."""
    peak, peak_src = peak_tflops(device)
    out = {
        "flops_per_step": flops_per_step,
        "flops_source": "analytic" if flops_per_step is not None else None,
        "xla_flops_per_step": xla_flops_per_step,
        "peak_tflops_bf16": peak,
        "peak_source": peak_src,
        "achieved_tflops": None,
        "mfu": None,
    }
    if flops_per_step is None and xla_flops_per_step is not None:
        flops_per_step = xla_flops_per_step
        out["flops_per_step"] = flops_per_step
        out["flops_source"] = "xla_cost_analysis"
    if flops_per_step and step_seconds > 0:
        achieved = flops_per_step / step_seconds / 1e12
        out["achieved_tflops"] = round(achieved, 3)
        if peak:
            out["mfu"] = round(achieved / peak, 4)
    return out
