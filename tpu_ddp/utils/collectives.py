"""Collective-communication microbenchmarks over the device mesh.

The reference's performance story hinges on its gradient-sync collectives
(gather/scatter vs ring all-reduce vs bucketed DDP — SURVEY.md §6 shows
the ladder's speedups are entirely comm-bound), but it ships no way to
measure the primitives themselves. This module does: it times each XLA
collective the framework's strategies are built from (``psum``,
``psum_scatter``, ``all_gather``, ``ppermute`` ring hop, ``all_to_all``)
over an actual mesh axis, so regressions in the comm layer show up as
numbers rather than as mysterious step-time drift.

Usage::

    from tpu_ddp.parallel.mesh import make_mesh
    from tpu_ddp.utils.collectives import bench_collectives
    print(bench_collectives(make_mesh(), mb=8))

Each op runs inside one jitted ``shard_map`` over the ``dp`` axis, is
compiled + warmed once, then timed over ``iters`` runs with
``block_until_ready`` (the same discipline as the train-step timing
harness, tpu_ddp/utils/timing.py). Reported bandwidth is the algorithmic
per-device payload divided by wall time — comparable across ops, not a
hardware line rate.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_ddp.parallel.mesh import DATA_AXIS


def _ops(axis: str, n: int):
    ring = [(i, (i + 1) % n) for i in range(n)]
    return {
        "psum": lambda x: lax.psum(x, axis),
        "psum_scatter": lambda x: lax.psum_scatter(
            x.reshape(n, -1), axis, scatter_dimension=0),
        "all_gather": lambda x: lax.all_gather(x, axis, tiled=True),
        "ppermute": lambda x: lax.ppermute(x, axis, ring),
        "all_to_all": lambda x: lax.all_to_all(
            x.reshape(n, -1), axis, split_axis=0, concat_axis=0,
            tiled=False),
    }


def bench_collectives(mesh: Mesh, mb: float = 4.0, iters: int = 10,
                      axis: str = DATA_AXIS,
                      dtype: str = "float32") -> dict:
    """Time each collective on ``mesh``'s ``axis``; returns a dict
    ``{op: {"ms": avg_ms, "gbps": payload_gb_per_s}}``.

    ``mb`` is the per-device payload in MiB of ``dtype`` — the element
    count scales with the itemsize, so ``dtype="int8"`` times the same
    BYTES through 4x the elements, which is exactly the compressed-wire
    question (parallel/compress.py ships gradients as s8/u16): does the
    fabric move reduced-dtype payloads at the same line rate? Bandwidth
    is computed from the actual itemsize. Integer dtypes skip nothing:
    psum/psum_scatter reduce integers exactly. Runs anywhere a mesh
    exists — on the virtual CPU mesh the numbers are only useful
    relative to each other; on real chips they expose the ICI.
    """
    n = mesh.shape[axis]
    if n < 2:
        raise ValueError(f"axis {axis!r} has size {n}; need >= 2 devices "
                         "to move bytes")
    # jnp resolves names numpy alone does not know (e.g. "bfloat16").
    dt = jnp.dtype(dtype)
    itemsize = dt.itemsize
    n_elems = int(mb * (1 << 20) / itemsize)
    n_elems -= n_elems % n  # divisible for the reshaping ops
    bytes_payload = n_elems * itemsize

    rng = np.random.default_rng(0)
    if jnp.issubdtype(dt, jnp.integer):
        info = jnp.iinfo(dt)
        host = rng.integers(info.min, info.max + 1, size=(n * n_elems,)) \
            .astype(dt)
    else:
        host = rng.normal(size=(n * n_elems,)).astype(dt)
    # Shard the payload over the SAME axis the collectives run on
    # (other mesh axes replicate), or the measurement is meaningless.
    x = jax.device_put(host, NamedSharding(mesh, P(axis)))

    results = {}
    for name, op in _ops(axis, n).items():
        fn = jax.jit(jax.shard_map(
            op, mesh=mesh, in_specs=P(axis),
            out_specs=P(axis), check_vma=False))
        jax.block_until_ready(fn(x))  # compile + warm
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(x)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / iters
        results[name] = {
            "ms": round(dt * 1e3, 4),
            "gbps": round(bytes_payload / dt / 1e9, 3),
        }
    return results


def main(argv=None) -> int:
    import argparse
    import json
    import os

    # Some environments pre-import jax via a site hook that overrides
    # the platform list; re-assert the user's JAX_PLATFORMS so
    # `JAX_PLATFORMS=cpu python -m tpu_ddp.utils.collectives` behaves as
    # documented (same pattern as parts/common.py).
    env_platforms = os.environ.get("JAX_PLATFORMS")
    if env_platforms and jax.config.jax_platforms != env_platforms:
        jax.config.update("jax_platforms", env_platforms)

    from tpu_ddp.parallel.mesh import make_mesh

    ap = argparse.ArgumentParser(
        description="microbenchmark XLA collectives over the dp axis")
    ap.add_argument("--mb", type=float, default=4.0,
                    help="per-device payload in MiB")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--dtype", default="float32",
                    help="payload dtype (float32, bfloat16, int8, ... — "
                         "compressed-wire microbenchmarks)")
    args = ap.parse_args(argv)
    mesh = make_mesh()
    out = {"devices": int(np.prod(list(mesh.shape.values()))),
           "platform": jax.devices()[0].platform,
           "payload_mib": args.mb,
           "dtype": args.dtype,
           "collectives": bench_collectives(mesh, args.mb, args.iters,
                                            dtype=args.dtype)}
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
