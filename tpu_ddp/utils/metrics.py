"""Structured metrics logging.

The reference's observability is ``print`` only (SURVEY.md §5: loss every
20 mini-batches, timing at iter 39, eval summary). Those prints survive in
the engine for parity; this module adds the framework-native structured
sink: one JSON object per line, suitable for tailing, plotting or joining
across ranks (each line carries rank + timestamp).
"""

from __future__ import annotations

import json
import os
import time


class MetricsLogger:
    """Append-only JSONL metrics writer.

    ``path=None`` makes every call a no-op, so engine code can log
    unconditionally. Lines look like::

        {"ts": 1722..., "rank": 0, "event": "train_iter", "step": 40,
         "loss": 1.93, "iter_s": 0.0021}
    """

    def __init__(self, path: str | None = None, rank: int = 0):
        self.path = path
        self.rank = rank
        self._fh = None
        # In-memory event counters (resilience accounting: skipped
        # steps, injected faults, quarantined checkpoints). Tracked even
        # with no sink file, so code can ask "how many?" after a run
        # without parsing JSONL.
        self.counters: dict[str, int] = {}
        # In-memory gauge accumulators (observe()): dispatch-pipeline
        # stall time (host_gap_ms) and friends — count/total/max per
        # name, queryable after a run without parsing JSONL.
        self.gauges: dict[str, dict] = {}
        if path:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            self._fh = open(path, "a", buffering=1)  # line-buffered

    @property
    def enabled(self) -> bool:
        return self._fh is not None

    def inc(self, name: str, n: int = 1) -> int:
        """Bump (and return) an in-memory counter; no line is written —
        pair with :meth:`log` when the event itself matters."""
        self.counters[name] = self.counters.get(name, 0) + n
        return self.counters[name]

    def observe(self, name: str, value: float) -> None:
        """Accumulate one gauge sample in memory (no line written —
        pair with :meth:`log` when the sample itself matters). Used by
        the train engine for per-epoch ``host_gap_ms`` (time the host
        spent stalled in forced device syncs, train/pipeline.py)."""
        g = self.gauges.setdefault(
            name, {"count": 0, "total": 0.0, "max": 0.0, "last": 0.0})
        v = float(value)
        g["count"] += 1
        g["total"] += v
        g["max"] = max(g["max"], v)
        g["last"] = v

    def gauge_summary(self, name: str) -> dict | None:
        """count/total/max/last/mean for one observed gauge, or None."""
        g = self.gauges.get(name)
        if g is None:
            return None
        return {**g, "mean": g["total"] / max(g["count"], 1)}

    def log(self, event: str, **fields) -> None:
        if self._fh is None:
            return
        rec = {"ts": round(time.time(), 3), "rank": self.rank,
               "event": event}
        rec.update(fields)
        self._fh.write(json.dumps(rec) + "\n")

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def from_env(rank: int = 0) -> MetricsLogger:
    """Logger configured by ``TPU_DDP_METRICS_FILE`` (``{rank}`` expands)."""
    path = os.environ.get("TPU_DDP_METRICS_FILE")
    if path:
        path = path.replace("{rank}", str(rank))
    return MetricsLogger(path, rank=rank)
