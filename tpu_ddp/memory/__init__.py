"""Memory-policy subsystem: activation remat + residual precision.

See :mod:`tpu_ddp.memory.policy` for the model, the policy table and
the knob surfaces (``TrainConfig.remat`` / ``act_dtype``).
"""

from tpu_ddp.memory.policy import (  # noqa: F401
    ACT_DTYPES,
    REMAT_POLICIES,
    apply_policy,
    cast_saved,
    checkpoint_policy,
    effective_remat,
    family_for_model,
    resolve_act_dtype,
    validate_act_dtype,
    validate_remat,
    wrap_stage,
)

__all__ = [
    "ACT_DTYPES", "REMAT_POLICIES", "apply_policy", "cast_saved",
    "checkpoint_policy", "effective_remat", "family_for_model",
    "resolve_act_dtype", "validate_act_dtype", "validate_remat",
    "wrap_stage",
]
