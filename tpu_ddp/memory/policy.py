"""Activation-rematerialization & residual-precision policies.

The round-5 roofline validation (EXPERIMENTS.md §9) pinned the conv
families to the memory wall: ResNet-50 sustains 95.7% of the v5e's
819 GB/s HBM peak while its flops-bound floor is ~4x lower. The only
lever that moves a bandwidth-bound step is moving fewer bytes, and the
classic bytes-for-FLOPs trade is activation rematerialization: wrap a
model stage in ``jax.checkpoint`` so autodiff saves ONLY the stage's
boundary input and recomputes the interior in the backward pass,
instead of materializing every interior activation (and, for BN/LN,
their f32 statistics residuals) to HBM between the forward and the
backward. On the LM side the same lever is what lets plain
(non-grad-accum) large batches compile at all — the saved-activation
working set is the thing that outgrows HBM (EXPERIMENTS.md §10).

This module is the ONE home of that policy for the whole model zoo.
Every model family carries two static dataclass fields and resolves
them through the helpers here:

``remat`` — which regions recompute:

- ``"none"``: save everything (the default; bit-identical to the
  pre-policy programs).
- ``"blocks"``: one checkpoint region per natural block — residual
  bottleneck for ResNet, conv->BN->ReLU unit for VGG, transformer
  block for the LM/ViT. Only block-boundary residuals are saved.
- ``"conv_stages"``: coarser regions for the conv families — one per
  resolution stage (ResNet's 4 stages; VGG's between-pool groups).
  Fewer saved boundaries than ``blocks``, more recompute. Transformer
  families have no conv stages: the policy degrades to ``blocks`` with
  a warning (mirrored by the autotuner's constraint model so the
  search never measures the duplicate cell).
- ``"dots"``: checkpoint with ``jax.checkpoint_policies.dots_saveable``
  — matmul outputs are saved, everything elementwise (LN, softmax,
  GELU, BN statistics) recomputes. The standard transformer middle
  ground. Conv stages contain no ``dot_general`` (convs are
  ``conv_general_dilated``), so for conv families this compiles to the
  same program as ``conv_stages`` (also encoded in the constraint
  model as a duplicate cell).

``act_dtype`` — the dtype of the SAVED stage-boundary residual stream:

- ``"compute"``: no cast (default).
- ``"bf16"`` / ``"f32"``: each stage boundary is cast to this dtype
  before entering the next region, and every region casts back to
  ``compute_dtype`` on entry — so the cast changes what autodiff
  SAVES (the boundary tensors), not the arithmetic inside the stages.
  ``bf16`` under f32 compute halves the residual-stream bytes
  (semantic: boundaries round-trip through bf16); ``f32`` under bf16
  compute is the precision-up direction.

Models apply the policy themselves (their ``apply`` calls
:func:`wrap_stage` / :func:`cast_saved` on static fields, so the
policied program traces through every engine jit surface — plain jit,
``shard_map``, the K-step scan, FSDP — with zero engine changes), and
``train/engine.py`` imprints the config-level knobs onto the model via
:func:`apply_policy` at Trainer construction. The 4-surface knob
contract (``TrainConfig.remat`` / ``TPU_DDP_REMAT`` / ``launch
--remat`` / ``tune/space.py``) is audited by ``scripts/knob_audit.py``.
"""

from __future__ import annotations

import dataclasses
import warnings

import jax
import jax.numpy as jnp

__all__ = [
    "REMAT_POLICIES", "ACT_DTYPES", "validate_remat",
    "validate_act_dtype", "resolve_act_dtype", "cast_saved",
    "checkpoint_policy", "wrap_stage", "effective_remat",
    "family_for_model", "apply_policy",
]

REMAT_POLICIES = ("none", "blocks", "conv_stages", "dots")
ACT_DTYPES = ("compute", "bf16", "f32")


def validate_remat(value: str, where: str = "remat") -> str:
    if value not in REMAT_POLICIES:
        raise ValueError(
            f"{where}={value!r}: expected one of {'|'.join(REMAT_POLICIES)}"
            " (TPU_DDP_REMAT)")
    return value


def validate_act_dtype(value: str, where: str = "act_dtype") -> str:
    if value not in ACT_DTYPES:
        raise ValueError(
            f"{where}={value!r}: expected one of {'|'.join(ACT_DTYPES)}"
            " (TPU_DDP_ACT_DTYPE)")
    return value


def resolve_act_dtype(act_dtype: str, compute_dtype) -> jnp.dtype:
    """The concrete dtype the saved boundary residuals carry."""
    validate_act_dtype(act_dtype)
    if act_dtype == "compute":
        return jnp.dtype(compute_dtype)
    return jnp.dtype(jnp.bfloat16 if act_dtype == "bf16" else jnp.float32)


def cast_saved(x, act_dtype: str, compute_dtype):
    """Cast a stage-boundary residual to the saved-activation dtype.

    A no-op (the operand itself, no inserted convert) when the dtypes
    already match — the default policy traces the exact pre-policy
    program."""
    return x.astype(resolve_act_dtype(act_dtype, compute_dtype))


def checkpoint_policy(remat: str):
    """The ``jax.checkpoint`` ``policy=`` argument for a remat mode
    (None = save nothing inside the region, i.e. full remat)."""
    validate_remat(remat)
    if remat == "dots":
        return jax.checkpoint_policies.dots_saveable
    return None


def wrap_stage(fn, remat: str, *, prevent_cse: bool = True,
               static_argnums=()):
    """Wrap one model stage under the remat policy; identity for
    ``"none"``. ``prevent_cse=False`` is for stages already inside a
    ``lax.scan`` body (the scan's loop structure prevents the
    problematic CSE — parallel/pipeline.py)."""
    if remat == "none":
        return fn
    kwargs = {}
    policy = checkpoint_policy(remat)
    if policy is not None:
        kwargs["policy"] = policy
    return jax.checkpoint(fn, prevent_cse=prevent_cse,
                          static_argnums=static_argnums, **kwargs)


def effective_remat(remat: str, family: str) -> str:
    """Resolve a remat mode against a model family ("conv" | "attn").

    Transformer families have no conv stages — ``conv_stages`` degrades
    to ``blocks`` with a warning (the grad_compress degrade precedent:
    warn, never silently change semantics the user asked for). The
    autotuner's constraint model (tune/space.py violations) mirrors
    this so the search skips the duplicate cell."""
    validate_remat(remat)
    if family == "attn" and remat == "conv_stages":
        warnings.warn(
            "remat='conv_stages' on a transformer family (no conv "
            "stages): degrading to per-block remat ('blocks')",
            stacklevel=2)
        return "blocks"
    return remat


def family_for_model(name: str) -> str:
    """Model-family classification for the constraint model:
    "conv" | "attn" | "" (unknown)."""
    if name.startswith(("VGG", "ResNet")):
        return "conv"
    if name.startswith(("ViT", "TransformerLM")):
        return "attn"
    return ""


def apply_policy(model, remat: str = "none", act_dtype: str = "compute"):
    """Imprint config-level memory policy onto a built model.

    Models carry the policy as static frozen-dataclass fields, so this
    is a ``dataclasses.replace`` — cheap, and every jit surface that
    closes over the model retraces the policied apply automatically.
    Config defaults never DOWNGRADE a model that was constructed with
    an explicit policy (e.g. the TransformerLM-large preset's block
    remat); a non-default config value always wins, since the config
    is the tuner/env/flag surface."""
    validate_remat(remat)
    validate_act_dtype(act_dtype)
    if remat == "none" and act_dtype == "compute":
        return model
    if not (dataclasses.is_dataclass(model) and hasattr(model, "remat")
            and hasattr(model, "act_dtype")):
        warnings.warn(
            f"model {type(model).__name__} does not carry memory-policy "
            f"fields; remat={remat!r} / act_dtype={act_dtype!r} ignored",
            stacklevel=2)
        return model
    updates = {}
    if remat != "none" and model.remat != remat:
        updates["remat"] = remat
    if act_dtype != "compute" and model.act_dtype != act_dtype:
        updates["act_dtype"] = act_dtype
    return dataclasses.replace(model, **updates) if updates else model
