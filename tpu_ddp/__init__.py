"""tpu_ddp — a TPU-native data-parallel training framework (JAX/XLA/pjit).

Built from scratch with the capabilities of the reference
(ruc98/Distributed-Data-Parallel-ML-Training): a four-part ladder of
gradient-synchronization strategies behind one training loop,

  part1  : single-device jit-compiled train step            (no sync)
  part2a : root-centric gather -> mean -> scatter            (manual sync)
  part2b : per-parameter all-reduce(SUM) / world_size        (manual sync)
  part3  : fused DP step — grads pmean'd inside one jitted
           step so XLA overlaps the ICI collective with the
           remaining backward pass                           (framework sync)

plus the surrounding framework: model zoo, host data pipeline with
DistributedSampler-parity sharding, distributed bootstrap over
``jax.distributed``, benchmark/timing harness, and a test suite.

The compute path is JAX/XLA (NHWC convs on the MXU, bf16-friendly); the
sync strategies are XLA collectives (`psum`, `all_gather`) over the device
mesh instead of the reference's gloo/TCP process group.
"""

__version__ = "0.1.0"

from tpu_ddp.utils import compat as _compat

_compat.install()  # backfill jax.shard_map on older jax releases

from tpu_ddp.utils.config import TrainConfig, SEED  # noqa: F401
