"""Each graph-audit detector must catch its seeded defect AND pass a
clean control — a detector that never fires is indistinguishable from
one that checks nothing (the test_knob_audit.py doctrine, applied to
the static program auditor in tpu_ddp/analysis/).

Four drill classes, one per detector, each seeding the historical bug
class the detector exists for:

- donation: a donated-but-unaliasable buffer (static) and a held
  ``np.asarray`` view defeating donation at runtime (round-10);
- retrace: a shape-varying call recompiling a "compiled" path
  (round-8);
- lockstep: two programs issuing the same collectives in different
  orders (the SPMD deadlock class);
- precision: a naive bf16 psum that XLA widens back to f32 (round-7).

Plus parser unit tests over synthetic HLO (async pair counting, alias
headers, replica groups, f64 creep) and the construction-time gate's
dispatch semantics.
"""

import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from tpu_ddp import analysis
from tpu_ddp.analysis import (
    GraphAuditError,
    RetraceError,
    collective_fingerprint,
    collective_ops,
    dispatch_findings,
    donation_report,
    fingerprint_digest,
    lockstep_check,
    no_retrace,
    precision_report,
    runtime_donation_check,
)
from tpu_ddp.analysis.donation import parse_input_output_alias
from tpu_ddp.analysis.hlo import async_payload_shape, tuple_elements
from tpu_ddp.analysis.lockstep import _replica_groups


# ---------------------------------------------------------------------------
# Parser units: synthetic HLO, no compiles.


ASYNC_HLO = """\
HloModule m

ENTRY main (p0: f32[128]) -> f32[128] {
  p0 = f32[128] parameter(0)
  ars = (f32[128], f32[128], u32[]) all-reduce-start(p0), replica_groups={{0,1},{2,3}}
  ROOT ard = f32[128] all-reduce-done(ars)
}
"""


class TestHloParsers:
    def test_async_pair_counts_once(self):
        # Satellite (2): a -start/-done pair is ONE logical collective
        # whose payload is the result tuple's element 1, not the sum
        # of the start tuple plus a double-count from the done.
        ops = collective_ops(ASYNC_HLO)
        assert len(ops) == 1
        (rec,) = ops
        assert rec["op"] == "all-reduce" and rec["async"]
        assert rec["dtype_bytes"] == {"f32": 128 * 4}

    def test_async_payload_shape(self):
        assert async_payload_shape(
            "(f32[32], f32[32], u32[], u32[])") == "f32[32]"
        assert tuple_elements("(f32[4], s8[8])") == ["f32[4]", "s8[8]"]
        # Non-tuple shapes pass through (sync collectives).
        assert async_payload_shape("f32[64]") == "f32[64]"

    def test_alias_header_parsing(self):
        text = ("HloModule m, input_output_alias={ {0}: (0, {}, "
                "may-alias), {1}: (3, {}, must-alias) }\n")
        assert parse_input_output_alias(text) == {0, 3}
        assert parse_input_output_alias("HloModule m\n") == set()

    def test_replica_groups_forms(self):
        assert _replica_groups(
            "replica_groups={{0,1},{2,3}}, to_apply=add") \
            == "{{0,1},{2,3}}"
        assert _replica_groups(
            "channel_id=1, replica_groups=[2,2]<=[4], dims={0}") \
            == "[2,2]<=[4]"
        assert _replica_groups("to_apply=add") == ""

    def test_fingerprint_over_async_program(self):
        fp = collective_fingerprint(ASYNC_HLO)
        assert fingerprint_digest(fp) == \
            ["all-reduce:f32:512:{{0,1},{2,3}}"]


F64_HLO = """\
HloModule m

ENTRY main (p0: f32[8]) -> f64[8] {
  p0 = f32[8] parameter(0)
  ROOT c = f64[8] convert(p0)
}
"""

CLEAN_WIRE_HLO = """\
HloModule m

ENTRY main (p0: u16[4096]) -> u16[4096] {
  p0 = u16[4096] parameter(0)
  ar = u16[4096] all-reduce(p0), replica_groups={{0,1,2,3}}
  s = f32[1] all-reduce(l), replica_groups={{0,1,2,3}}
  ROOT r = u16[4096] copy(ar)
}
"""


class TestPrecisionLint:
    def test_f64_creep_flagged(self):
        rep = precision_report(F64_HLO)
        assert any("f64" in f for f in rep["findings"])
        assert precision_report(ASYNC_HLO)["findings"] == []

    def test_reduced_wire_clean_control(self):
        # u16 movement payload + a scalar f32 psum (loss term): the
        # legitimate compiled shape under wire=bf16 — no findings.
        rep = precision_report(CLEAN_WIRE_HLO, "bf16")
        assert rep["findings"] == []
        assert rep["dtype_bytes"]["u16"] == 4096 * 2

    def test_unknown_wire_rejected(self):
        with pytest.raises(ValueError, match="unknown wire"):
            precision_report(CLEAN_WIRE_HLO, "fp8")


# ---------------------------------------------------------------------------
# Drill: donation (round-10).


class TestDonationDrill:
    def test_static_defeated_donation_caught(self):
        # The donated buffer can alias NO output (dtype change): the
        # executable drops the donation and copies every call.
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            f = jax.jit(lambda x: x.astype(jnp.int8), donate_argnums=0)
            rep = donation_report(
                f.lower(jax.ShapeDtypeStruct((512,), jnp.float32)),
                min_bytes=1024)
        assert rep["donated"] == [0] and rep["aliased"] == []
        assert any("copied every call" in f for f in rep["findings"])

    def test_static_clean_control(self):
        g = jax.jit(lambda x: x + 1.0, donate_argnums=0)
        rep = donation_report(
            g.lower(jax.ShapeDtypeStruct((512,), jnp.float32)),
            min_bytes=1024)
        assert rep["aliased"] == [0] and rep["findings"] == []

    def test_runtime_held_view_defeats_donation(self):
        # The alias exists statically, but a live np.asarray view of
        # the input forces PJRT to copy — only the runtime check sees
        # this.
        g = jax.jit(lambda x: x + 1.0, donate_argnums=0)
        x = jnp.arange(512, dtype=jnp.float32)
        view = np.asarray(x)  # zero-copy external reference
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            findings = runtime_donation_check(g, x)
        assert any("COPIED at runtime" in f for f in findings)
        del view

    def test_runtime_clean_control(self):
        g = jax.jit(lambda x: x + 1.0, donate_argnums=0)
        assert runtime_donation_check(
            g, jnp.arange(512, dtype=jnp.float32)) == []


# ---------------------------------------------------------------------------
# Drill: retrace (round-8).


def _drill_step(x):
    return x * 2.0 + 1.0


class TestRetraceDrill:
    def test_shape_varying_recompile_caught(self):
        jf = jax.jit(_drill_step)
        with pytest.raises(RetraceError, match="_drill_step"):
            with no_retrace(watch=("_drill_step",)):
                jf(jnp.ones((4,)))
                jf(jnp.ones((8,)))  # new aval -> second compile

    def test_stable_shapes_clean(self):
        jf = jax.jit(_drill_step)
        with no_retrace(watch=("_drill_step",)) as counter:
            jf(jnp.ones((16,)))
            jf(jnp.ones((16,)))  # cache hit, not a compile
        assert counter.counts.get("_drill_step", 0) <= 1

    def test_watch_scopes_the_sentinel(self):
        # Unwatched names never trip, however often they compile.
        jf = jax.jit(_drill_step)
        with no_retrace(watch=("some_other_fn",)):
            jf(jnp.ones((3,)))
            jf(jnp.ones((5,)))

    def test_fixture_is_the_context_manager(self, no_retrace):
        jf = jax.jit(_drill_step)
        with pytest.raises(RetraceError):
            with no_retrace(watch=("_drill_step",)):
                jf(jnp.ones((7,)))
                jf(jnp.ones((9,)))


# ---------------------------------------------------------------------------
# Drill: collective lockstep (the SPMD deadlock class).


def _two_collective_program(flipped, mesh):
    """A dependency-chained pair of psums (16 then 8 elements per
    shard, or flipped) — the chain pins program order so the compiled
    schedule IS the source order."""

    def straight(g):
        a = lax.psum(g, "dp")
        return lax.psum(a[:8], "dp")

    def reordered(g):
        a = lax.psum(g[:8], "dp")
        return lax.psum(jnp.pad(a, (0, 8)) + g, "dp")[:8]

    body = reordered if flipped else straight
    return jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P("dp"),
                                 out_specs=P()))


class TestLockstepDrill:
    def test_order_mismatch_caught(self, devices):
        mesh = Mesh(np.array(devices[:4]), ("dp",))
        arg = jax.ShapeDtypeStruct((64,), jnp.float32)
        fps = {}
        for name, flipped in (("straight", False), ("reordered", True)):
            text = _two_collective_program(flipped, mesh) \
                .lower(arg).compile().as_text()
            fps[name] = collective_fingerprint(text)
        assert all(len(fp) == 2 for fp in fps.values())
        findings = lockstep_check(fps)
        assert any("order mismatch" in f and "deadlock" in f
                   for f in findings)

    def test_same_config_lowered_twice_is_deterministic(self, devices):
        mesh = Mesh(np.array(devices[:4]), ("dp",))
        arg = jax.ShapeDtypeStruct((64,), jnp.float32)
        fn = _two_collective_program(False, mesh)
        a = collective_fingerprint(fn.lower(arg).compile().as_text())
        b = collective_fingerprint(fn.lower(arg).compile().as_text())
        assert lockstep_check({"lower-1": a, "lower-2": b}) == []

    def test_count_mismatch_caught(self):
        fp = [{"computation": "main", "op": "all-reduce", "dtype": "f32",
               "payload_bytes": 64, "replica_groups": "{{0,1}}"}]
        findings = lockstep_check({"a": fp + fp, "b": fp})
        assert any("count mismatch" in f for f in findings)

    def test_single_program_vacuously_clean(self):
        assert lockstep_check({"only": []}) == []


# ---------------------------------------------------------------------------
# Drill: precision widening (round-7).


class TestPrecisionDrill:
    def test_naive_bf16_psum_widened_and_caught(self, devices):
        # The seeded defect: an ARITHMETIC bf16 psum. XLA's
        # FloatNormalization legalizes it back to f32 — the compiled
        # wire is 2x what the config promised. The lint must see both
        # the f32 traffic and the missing reduced-dtype payload.
        mesh = Mesh(np.array(devices[:4]), ("dp",))

        def naive(g):
            return lax.psum(g.astype(jnp.bfloat16), "dp") \
                .astype(jnp.float32)

        text = jax.jit(jax.shard_map(
            naive, mesh=mesh, in_specs=P("dp"), out_specs=P())) \
            .lower(jax.ShapeDtypeStruct((16384,), jnp.float32)) \
            .compile().as_text()
        rep = precision_report(text, "bf16")
        assert any("widened" in f for f in rep["findings"]) \
            or any("no reduced-dtype" in f for f in rep["findings"])

    def test_real_compressed_wire_is_clean(self):
        # The committed artifact pins the positive control at repo
        # scale: the REAL bf16/int8 rungs audited clean.
        import json
        from pathlib import Path
        art = json.loads(
            (Path(__file__).parent.parent / "experiments"
             / "graph_audit.json").read_text())
        cells = {c["program"]: c for c in art["cells"]}
        for prog in ("train/fused+bf16", "train/fused+int8"):
            assert cells[prog]["findings"] == []
            assert cells[prog]["wire"] in ("bf16", "int8")


# ---------------------------------------------------------------------------
# The TPU_DDP_AUDIT gate.


class TestAuditGate:
    def test_dispatch_modes(self):
        assert dispatch_findings([], "error", "x") == []
        assert dispatch_findings(["f"], "off", "x") == ["f"]
        with pytest.warns(UserWarning, match="graph audit"):
            dispatch_findings(["f"], "warn", "x")
        with pytest.raises(GraphAuditError, match="graph audit of x"):
            dispatch_findings(["f"], "error", "x")
        with pytest.raises(ValueError, match="off|warn|error"):
            dispatch_findings(["f"], "loud", "x")

    def test_env_surface_parses_and_rejects_junk(self):
        from tpu_ddp.utils.config import TrainConfig
        old = os.environ.pop("TPU_DDP_AUDIT", None)
        try:
            os.environ["TPU_DDP_AUDIT"] = "warn"
            assert TrainConfig().audit == "warn"
            os.environ["TPU_DDP_AUDIT"] = "audit-junk"
            with pytest.raises(ValueError, match="audit"):
                TrainConfig()
        finally:
            os.environ.pop("TPU_DDP_AUDIT", None)
            if old is not None:
                os.environ["TPU_DDP_AUDIT"] = old

    def test_gate_runs_at_trainer_construction(self, devices,
                                               monkeypatch):
        # The dispatch path end-to-end through Trainer.__init__,
        # with the (expensive) probe stubbed: findings must block
        # construction under error and warn under warn.
        from tpu_ddp.analysis import gate
        from tpu_ddp.models.vgg import VGGModel
        from tpu_ddp.parallel.mesh import make_mesh
        from tpu_ddp.train.engine import Trainer
        from tpu_ddp.utils.config import TrainConfig

        monkeypatch.setattr(gate, "audit_trainer",
                            lambda tr: ["seeded defect"])
        mesh = make_mesh(devices[:4])
        model = VGGModel(name="tiny", cfg=(8, "M"),
                         compute_dtype=jnp.float32)
        with pytest.raises(GraphAuditError, match="seeded defect"):
            Trainer(model, TrainConfig(audit="error"),
                    strategy="fused", mesh=mesh)
        with pytest.warns(UserWarning, match="seeded defect"):
            Trainer(model, TrainConfig(audit="warn"),
                    strategy="fused", mesh=mesh)
        monkeypatch.setattr(gate, "audit_trainer", lambda tr: [])
        Trainer(model, TrainConfig(audit="error"), strategy="fused",
                mesh=mesh)  # clean engine constructs under error
