"""Compressed gradient collectives (tpu_ddp/parallel/compress.py).

What the ladder's compression layer must guarantee, each pinned here:

- the int8 quantizer's per-element error is bounded by one per-block
  step and stochastic rounding is unbiased;
- error feedback makes the lossy wire's bias telescope away (toy
  quadratic: int8+EF lands on the fp32 optimum, int8-noef hovers at a
  noise floor above it);
- every rung of the ladder stays on the fp32 trajectory within the
  documented tolerance when compressed (strategy-equivalence sweep);
- the stateful carry behaves: checkpointed + restored bit-exact,
  reset (with a warning) on any layout mismatch, rolled back by a
  StepGuard skip, and the K-step scan is bit-equal to K single steps;
- the compiled step really moves gradients at the reduced dtype —
  scanned out of the HLO (utils/hlo_comm.py), because XLA float
  normalization can silently widen a bf16 collective back to f32.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from tpu_ddp.parallel.compress import (SPECS, GradCompressor,
                                       get_compressor)
from tpu_ddp.parallel.mesh import DATA_AXIS, make_mesh
from tpu_ddp.train.engine import Trainer
from tpu_ddp.utils.config import TrainConfig


@dataclasses.dataclass(frozen=True)
class TinyNoBN:
    """Per-example-decoupled conv model (same rationale as
    test_sync.TinyNoBN: BN's batch statistics would make distributed
    forwards differ from the single-device pass for reasons unrelated
    to the gradient wire)."""

    def init(self, key):
        k1, k2, k3 = jax.random.split(key, 3)
        return {
            "conv": 0.3 * jax.random.normal(k1, (3, 3, 3, 8)),
            "bias": jnp.zeros((8,)),
            "head": 0.3 * jax.random.normal(k2, (2 * 2 * 8, 10)),
            "head_b": 0.01 * jax.random.normal(k3, (10,)),
        }

    def apply(self, params, x):
        y = lax.conv_general_dilated(
            x, params["conv"], (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        y = jnp.maximum(y + params["bias"], 0)
        y = lax.reduce_window(y, -jnp.inf, lax.max, (1, 2, 2, 1),
                              (1, 2, 2, 1), "VALID")
        return y.reshape(y.shape[0], -1) @ params["head"] + params["head_b"]


def _batch(n=16, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4, 4, 3)).astype(np.float32)
    y = rng.integers(0, 10, size=n).astype(np.int32)
    return x, y


def _trainer(devices, strategy, spec, dp=4):
    mesh = make_mesh(devices[:dp])
    return Trainer(TinyNoBN(), TrainConfig(grad_compress=spec),
                   strategy=strategy, mesh=mesh)


def _flat(tree):
    return np.concatenate([np.ravel(np.asarray(jax.device_get(l)))
                           for l in jax.tree.leaves(tree)])


def _pflat(tr, state):
    """Flat param vector comparable ACROSS strategies: FSDP keeps
    1/dp-padded flat leaves at rest, so unshard before flattening."""
    params = jax.device_get(state.params)
    zero3 = getattr(tr, "zero3", None)
    if zero3 is not None:
        params = zero3.unshard_host(params)
    return _flat(params)


def _run_steps(tr, n_steps=3):
    state = tr.init_state()
    losses = []
    for i in range(n_steps):
        xb, yb, wb = tr.put_batch(*_batch(seed=i))
        state, loss = tr.train_step(state, xb, yb, wb)
        losses.append(float(np.ravel(np.asarray(loss))[0]))
    return state, losses


# ---------------------------------------------------------------------
# quantizer
# ---------------------------------------------------------------------

class TestQuantizer:
    def test_roundtrip_error_bounded_by_block_scale(self):
        """|deq(q(x)) - x| <= one quantization step (= the block's
        scale), element-wise — stochastic rounding moves at most one
        level, and amax/127 scaling means no value clips."""
        comp = get_compressor("int8", block_size=64)
        rng = np.random.default_rng(0)
        # Mixed-magnitude blocks: per-BLOCK scales must keep small
        # blocks' errors small even next to a huge one.
        x = jnp.asarray(
            rng.normal(size=(4, 256)) * np.array([1e-3, 1.0, 50.0, 1e4]
                                                 )[:, None],
            jnp.float32)
        q, scale = comp._quant(x, jax.random.key(1))
        assert q.dtype == jnp.int8 and q.shape == x.shape
        assert scale.shape == (4, 4)  # 256 / 64 blocks per row
        err = np.abs(np.asarray(comp._dequant(q, scale) - x))
        step = np.repeat(np.asarray(scale), 64, axis=-1)
        assert np.all(err <= step * (1 + 1e-6))

    def test_stochastic_rounding_is_unbiased(self):
        """Averaged over keys, deq(q(x)) -> x (floor(x/s + u) with
        u ~ U[0,1) is unbiased per element)."""
        comp = get_compressor("int8", block_size=128)
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.normal(size=(128,)), jnp.float32)

        def deq_once(k):
            q, s = comp._quant(x, k)
            return comp._dequant(q, s)

        keys = jax.random.split(jax.random.key(7), 512)
        mean = np.asarray(jnp.mean(jax.vmap(deq_once)(keys), axis=0))
        _, s = comp._quant(x, keys[0])
        # Bias per element ~ step / sqrt(512) ≈ 0.044 steps; allow 4x.
        assert np.max(np.abs(mean - np.asarray(x))) < float(s[0]) * 0.2

    def test_zeros_quantize_exactly(self):
        """A zero block round-trips to exactly zero for ANY key — the
        property that makes chunk padding invisible to means and to the
        error-feedback residual."""
        comp = get_compressor("int8")
        x = jnp.zeros((512,), jnp.float32)
        q, s = comp._quant(x, jax.random.key(123))
        assert not np.any(np.asarray(q))
        assert not np.any(np.asarray(comp._dequant(q, s)))

    def test_bf16_wire_bitcast_roundtrip(self):
        x = jnp.asarray([1.0, -2.5, 3.0e-8, 65504.0], jnp.float32)
        w = GradCompressor._to_wire_bf16(x)
        assert w.dtype == jnp.uint16
        back = GradCompressor._from_wire_bf16(w)
        np.testing.assert_array_equal(
            np.asarray(back), np.asarray(x.astype(jnp.bfloat16)
                                         .astype(jnp.float32)))

    def test_spec_validation(self):
        with pytest.raises(ValueError, match="unknown grad_compress"):
            get_compressor("fp8")
        with pytest.raises(ValueError, match="block_size"):
            get_compressor("int8", block_size=0)
        assert get_compressor(None).spec == "none"
        for spec in SPECS:
            c = get_compressor(spec)
            assert c.describe()["spec"] == spec

    def test_config_validation(self, monkeypatch):
        with pytest.raises(ValueError, match="TPU_DDP_GRAD_COMPRESS"):
            TrainConfig(grad_compress="fp8")
        monkeypatch.setenv("TPU_DDP_GRAD_COMPRESS", "bf16")
        assert TrainConfig().grad_compress == "bf16"


# ---------------------------------------------------------------------
# error feedback on a toy quadratic
# ---------------------------------------------------------------------

class TestErrorFeedback:
    """min_w mean_i 0.5||w - t_i||^2 over dp devices, grad_i = w - t_i.
    Plain GD with the exact mean gradient converges to mean(t);
    int8+EF must track it, int8-noef hovers at the quantization noise
    floor above it — the drift the residual exists to remove."""

    # Small LR + many steps: stochastic rounding is unbiased, so noef's
    # handicap is VARIANCE, not bias — the noef noise floor scales
    # ~sqrt(lr) while EF's noise-shaping floor scales ~lr, and the gap
    # between them only opens as lr shrinks (measured: 1.2x at lr=0.4,
    # 5.1x at lr=0.02).
    D, LR, STEPS = 512, 0.02, 800

    def _targets(self, n):
        rng = np.random.default_rng(11)
        # Heavy-tailed per-device offsets keep per-block amax (and so
        # the quantization step) large relative to the shrinking
        # gradient near the optimum — the regime where EF matters.
        return jnp.asarray(rng.normal(size=(n, self.D)) *
                           rng.choice([0.05, 1.0, 30.0],
                                      size=(n, self.D)), jnp.float32)

    def _descend(self, devices, spec, n=8):
        mesh = make_mesh(devices[:n])
        comp = get_compressor(spec, block_size=64)
        t = self._targets(n)
        template = {"w": jax.ShapeDtypeStruct((self.D,), jnp.float32)}
        cstate = comp.init_state(template, n, seed=0)
        cspecs = comp.state_specs(cstate)
        if cstate is not None:
            from jax.sharding import NamedSharding
            cstate = jax.device_put(cstate, jax.tree.map(
                lambda s: NamedSharding(mesh, s), cspecs,
                is_leaf=lambda x: isinstance(x, P)))

        def step(w, c, ti):
            g = {"w": w["w"] - ti.reshape(-1)}
            if spec == "none":
                g = lax.pmean(g, DATA_AXIS)
                new_c = c
            else:
                g, new_c = comp.sync_replicated("fused", g, c,
                                                DATA_AXIS, n)
            return {"w": w["w"] - self.LR * g["w"]}, new_c

        def descend(w, c, ti):
            # All STEPS inside ONE dispatch. Besides being fast, this
            # is load-bearing on the 1-core CPU backend: a Python loop
            # of un-harvested dispatches piles up concurrent
            # executions whose in-process all_to_all rendezvous can
            # starve each other and deadlock (8 device threads per
            # execution, one core). One execution cannot race itself.
            return lax.fori_loop(
                0, self.STEPS, lambda _, wc: step(*wc, ti), (w, c))

        in_specs = (P(), cspecs if cstate is not None else P(),
                    P(DATA_AXIS))
        out_specs = (P(), cspecs if cstate is not None else P())
        fn = jax.jit(jax.shard_map(descend, mesh=mesh,
                                   in_specs=in_specs,
                                   out_specs=out_specs, check_vma=False))
        from jax.sharding import NamedSharding
        w = jax.device_put({"w": jnp.zeros((self.D,), jnp.float32)},
                           NamedSharding(mesh, P()))
        td = jax.device_put(t, NamedSharding(mesh, P(DATA_AXIS)))
        c = cstate if cstate is not None else jnp.zeros((), jnp.float32)
        w, c = fn(w, c, td)
        return np.asarray(jax.device_get(w["w"])), np.asarray(
            jnp.mean(t, axis=0))

    def test_ef_converges_noef_drifts(self, devices):
        w_fp32, opt = self._descend(devices, "none")
        w_ef, _ = self._descend(devices, "int8")
        w_noef, _ = self._descend(devices, "int8-noef")
        err_fp32 = np.linalg.norm(w_fp32 - opt)
        err_ef = np.linalg.norm(w_ef - opt)
        err_noef = np.linalg.norm(w_noef - opt)
        # fp32 GD contracts (1-lr)^steps -> essentially exact.
        assert err_fp32 < 1e-3
        # EF must land within a whisker of the fp32 trajectory...
        assert np.linalg.norm(w_ef - w_fp32) < 0.05 * np.linalg.norm(opt)
        # ...while the ablation stalls at a visibly higher noise floor
        # (measured 5.1x at this lr; deterministic seeds).
        assert err_noef > 3 * max(err_ef, 1e-6)


# ---------------------------------------------------------------------
# strategy equivalence under compression
# ---------------------------------------------------------------------

ALL_RUNGS = ("gather_scatter", "all_reduce", "fused", "zero", "fsdp")


class TestStrategyEquivalence:
    """Every compressed rung must stay on the fp32 fused trajectory
    within the documented tolerance (compress.py module docstring):
    bf16 keeps ~8 mantissa bits, int8 adds blockwise quantization noise
    that error feedback re-injects rather than compounds."""

    _base = {}

    def _baseline(self, devices):
        if "p" not in self._base:
            state, losses = _run_steps(
                _trainer(devices, "fused", "none"))
            self._base["p"] = _flat(state.params)
            self._base["l"] = losses
        return self._base["p"], self._base["l"]

    @pytest.mark.parametrize("strategy", ["fused", "zero"])
    def test_bf16_core_rungs(self, devices, strategy):
        # fused/zero cover the two bf16 code paths (sync_replicated /
        # scatter_mean); the remaining rungs ride the slow tier below.
        p0, _ = self._baseline(devices)
        tr = _trainer(devices, strategy, "bf16")
        state, losses = _run_steps(tr)
        assert np.all(np.isfinite(losses))
        assert np.max(np.abs(_pflat(tr, state) - p0)) < 5e-3

    @pytest.mark.slow  # 3 more trainer compiles
    @pytest.mark.parametrize("strategy", ["gather_scatter", "all_reduce",
                                          "fsdp"])
    def test_bf16_remaining_rungs(self, devices, strategy):
        p0, _ = self._baseline(devices)
        tr = _trainer(devices, strategy, "bf16")
        state, losses = _run_steps(tr)
        assert np.all(np.isfinite(losses))
        assert np.max(np.abs(_pflat(tr, state) - p0)) < 5e-3

    @pytest.mark.parametrize("strategy", ["fused", "zero"])
    def test_int8_stays_on_trajectory(self, devices, strategy):
        p0, _ = self._baseline(devices)
        tr = _trainer(devices, strategy, "int8")
        state, losses = _run_steps(tr)
        assert np.all(np.isfinite(losses))
        assert np.max(np.abs(_pflat(tr, state) - p0)) < 2e-2

    @pytest.mark.slow  # 6 more trainer compiles; fused/zero cover the
    # two code paths (sync_replicated / scatter_mean) in the default tier
    @pytest.mark.parametrize("strategy", ["gather_scatter", "all_reduce",
                                          "fsdp"])
    @pytest.mark.parametrize("spec", ["int8", "int8-noef"])
    def test_int8_remaining_rungs(self, devices, strategy, spec):
        p0, _ = self._baseline(devices)
        tr = _trainer(devices, strategy, spec)
        state, losses = _run_steps(tr)
        assert np.all(np.isfinite(losses))
        assert np.max(np.abs(_pflat(tr, state) - p0)) < 5e-2

    def test_degrades_to_none_without_sync(self, devices):
        """Under strategy 'none' there is no collective to compress:
        the trainer must warn and run uncompressed, not silently change
        the rung's semantics."""
        mesh = make_mesh(devices[:4])
        with pytest.warns(UserWarning, match="compression disabled"):
            tr = Trainer(TinyNoBN(), TrainConfig(grad_compress="int8"),
                         strategy="none", mesh=mesh)
        assert tr.compressor.spec == "none"
        state = tr.init_state()
        assert state.comp_state is None
        state, loss = tr.train_step(state, *tr.put_batch(*_batch()))
        assert np.all(np.isfinite(np.asarray(loss)))


# ---------------------------------------------------------------------
# the stateful carry: scan, checkpoint, guard
# ---------------------------------------------------------------------

class TestCarry:
    def test_multi_step_bit_equals_single_steps(self, devices):
        """build_multi_step's scanned K steps must be bit-equal to K
        train_step calls — including the residual and seed carry."""
        tr = _trainer(devices, "fused", "int8")
        state = tr.init_state()
        for i in range(2):
            state, _ = tr.train_step(state,
                                     *tr.put_batch(*_batch(seed=i)))
            # Serialize: concurrent in-flight all_to_all executions can
            # deadlock the 1-core CPU backend's rendezvous.
            jax.block_until_ready(state.params)

        tr2 = _trainer(devices, "fused", "int8")
        s2 = tr2.init_state()
        xs, ys = zip(*[_batch(seed=i) for i in range(2)])
        fn = tr2.build_multi_step(2)
        s2, _ = fn(s2, *tr2.put_batches(np.stack(xs), np.stack(ys)))

        np.testing.assert_array_equal(_flat(state.params),
                                      _flat(s2.params))
        np.testing.assert_array_equal(
            _flat(state.comp_state["residual"]),
            _flat(s2.comp_state["residual"]))
        assert (int(jax.device_get(state.comp_state["seed"]))
                == int(jax.device_get(s2.comp_state["seed"])))

    def test_checkpoint_roundtrip_restores_residual(self, devices,
                                                    tmp_path):
        tr = _trainer(devices, "fused", "int8")
        state, _ = _run_steps(tr, n_steps=3)
        assert np.any(_flat(state.comp_state["residual"]))  # non-trivial
        tr.save_checkpoint(str(tmp_path), state)
        restored = tr.restore_checkpoint(str(tmp_path))
        assert restored.step == state.step
        np.testing.assert_array_equal(_flat(state.params),
                                      _flat(restored.params))
        np.testing.assert_array_equal(
            _flat(state.comp_state["residual"]),
            _flat(restored.comp_state["residual"]))
        assert (int(jax.device_get(restored.comp_state["seed"]))
                == int(jax.device_get(state.comp_state["seed"])))
        # and the run continues.
        restored, loss = tr.train_step(restored,
                                       *tr.put_batch(*_batch(seed=9)))
        assert np.all(np.isfinite(np.asarray(loss)))

    def test_compressed_checkpoint_into_plain_trainer(self, devices,
                                                      tmp_path):
        """A compression-less trainer DROPS a checkpoint's comp_state
        leaves instead of refusing the file."""
        tr = _trainer(devices, "fused", "int8")
        state, _ = _run_steps(tr, n_steps=2)
        tr.save_checkpoint(str(tmp_path), state)
        plain = _trainer(devices, "fused", "none")
        restored = plain.restore_checkpoint(str(tmp_path))
        assert restored.comp_state is None
        np.testing.assert_array_equal(_flat(state.params),
                                      _flat(restored.params))

    def test_plain_checkpoint_resets_residual(self, devices, tmp_path):
        """Restoring a pre-compression checkpoint into an int8 trainer
        warns and resets the carry — the residual is an optimization
        accelerator, never a correctness requirement."""
        plain = _trainer(devices, "fused", "none")
        state, _ = _run_steps(plain, n_steps=1)
        plain.save_checkpoint(str(tmp_path), state)
        tr = _trainer(devices, "fused", "int8")
        with pytest.warns(UserWarning, match="comp_state"):
            restored = tr.restore_checkpoint(str(tmp_path))
        assert not np.any(_flat(restored.comp_state["residual"]))
        np.testing.assert_array_equal(_flat(state.params),
                                      _flat(restored.params))

    @pytest.mark.slow  # two extra trainer compiles (dp=4 and dp=8)
    def test_dp_mismatch_resets_residual(self, devices, tmp_path):
        """The residual is (dp, *shape): a checkpoint from another dp
        size cannot be reinterpreted and must reset, not crash."""
        tr4 = _trainer(devices, "fused", "int8", dp=4)
        state, _ = _run_steps(tr4, n_steps=2)
        tr4.save_checkpoint(str(tmp_path), state)
        tr8 = _trainer(devices, "fused", "int8", dp=8)
        with pytest.warns(UserWarning, match="comp_state"):
            restored = tr8.restore_checkpoint(str(tmp_path))
        assert not np.any(_flat(restored.comp_state["residual"]))
        np.testing.assert_array_equal(_flat(state.params),
                                      _flat(restored.params))

    def test_guard_skip_rolls_back_carry(self, devices):
        """A StepGuard-skipped step must not consume the carry: the
        residual would absorb a gradient that was never applied and the
        stochastic-rounding seed would advance."""
        tr = _trainer(devices, "fused", "int8")
        state = tr.init_state()
        state, _ = tr.train_step(state, *tr.put_batch(*_batch()))
        p0 = _flat(state.params)
        r0 = _flat(state.comp_state["residual"])
        s0 = int(jax.device_get(state.comp_state["seed"]))
        x, y = _batch(seed=5)
        x[0, 0, 0, 0] = np.nan
        state, _ = tr.train_step(state, *tr.put_batch(x, y))
        assert tr.last_step_skipped()
        np.testing.assert_array_equal(p0, _flat(state.params))
        np.testing.assert_array_equal(r0,
                                      _flat(state.comp_state["residual"]))
        assert int(jax.device_get(state.comp_state["seed"])) == s0


# ---------------------------------------------------------------------
# the HLO invariant: the wire really is s8/u16
# ---------------------------------------------------------------------

class TestReducedDtypeHLO:
    """Compiled-HLO proof (utils/hlo_comm.py scanner) on the 8-device
    mesh: a compressed step's collective payload lives at the wire
    dtype, with f32 collective traffic bounded by per-block scales and
    the step's scalar psums. This is what the bitcast-to-integer wire
    exists for — XLA float normalization would otherwise legalize a
    bf16 all-reduce back to f32 and silently undo the compression."""

    GRAD_BYTES = 554 * 4  # TinyNoBN param count x fp32

    def _dtypes(self, devices, strategy, spec):
        from tpu_ddp.utils.hlo_comm import (collective_dtype_bytes,
                                            train_step_hlo)
        tr = _trainer(devices, strategy, spec, dp=8)
        state = tr.init_state()
        xb, yb, wb = tr.put_batch(*_batch())
        return collective_dtype_bytes(train_step_hlo(tr, state, xb, yb,
                                                     wb))

    def test_fp32_baseline_has_no_reduced_wire(self, devices):
        d = self._dtypes(devices, "fused", "none")
        assert "u16" not in d and "s8" not in d
        assert d["f32"] >= self.GRAD_BYTES

    def test_bf16_fused_wire(self, devices):
        d = self._dtypes(devices, "fused", "bf16")
        # Two movement phases at 2 bytes/elem >= one grad at half width.
        assert d.get("u16", 0) >= self.GRAD_BYTES // 2
        # f32 collectives: only the loss/guard scalar psums remain.
        assert d.get("f32", 0) <= 64

    def test_int8_fused_wire(self, devices):
        d = self._dtypes(devices, "fused", "int8")
        assert d.get("s8", 0) >= self.GRAD_BYTES // 4
        assert "u16" not in d
        # f32: scalar psums + the per-block scales (554 params / 256
        # block ~ a few dozen scale floats across both phases).
        assert d.get("f32", 0) <= 512

    @pytest.mark.slow  # one extra dp=8 trainer compile
    def test_int8_scattered_rung_wire(self, devices):
        """ZeRO's compressed reduce_scatter moves s8; the f32 that
        remains is the rung's own fp32 PARAMETER all_gather (documented
        out of compression's scope) plus scales and scalars."""
        d = self._dtypes(devices, "zero", "int8")
        assert d.get("s8", 0) > 0
        assert d.get("f32", 0) <= self.GRAD_BYTES + 512
