"""Metrics JSONL sink + profiler hooks (the reference's observability is
print-only, SURVEY.md §5 — these are framework-native extensions)."""

import json

import jax.numpy as jnp
import numpy as np

from tpu_ddp.models import get_model
from tpu_ddp.train.engine import Trainer
from tpu_ddp.utils.config import TrainConfig
from tpu_ddp.utils.metrics import MetricsLogger, from_env
from tpu_ddp.utils.profiling import annotate, profile_trace


class TestMetricsLogger:
    def test_writes_jsonl(self, tmp_path):
        p = tmp_path / "m.jsonl"
        with MetricsLogger(str(p), rank=3) as m:
            assert m.enabled
            m.log("train_iter", step=1, loss=2.5)
            m.log("eval", test_loss=2.1)
        lines = [json.loads(l) for l in p.read_text().splitlines()]
        assert len(lines) == 2
        assert lines[0]["event"] == "train_iter"
        assert lines[0]["rank"] == 3
        assert lines[0]["loss"] == 2.5
        assert "ts" in lines[0]

    def test_disabled_is_noop(self):
        m = MetricsLogger(None)
        assert not m.enabled
        m.log("anything", x=1)  # must not raise
        m.close()

    def test_from_env_rank_expansion(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPU_DDP_METRICS_FILE",
                           str(tmp_path / "r{rank}.jsonl"))
        m = from_env(rank=2)
        m.log("e")
        m.close()
        assert (tmp_path / "r2.jsonl").exists()

    def test_trainer_emits_metrics(self, tmp_path):
        p = tmp_path / "train.jsonl"
        cfg = TrainConfig(global_batch_size=8, log_every=1, max_iters=2)
        model = get_model("VGG11", compute_dtype=jnp.float32)
        tr = Trainer(model, cfg, strategy="none",
                     metrics=MetricsLogger(str(p)))
        rng = np.random.default_rng(0)
        batches = [(rng.normal(size=(8, 32, 32, 3)).astype(np.float32),
                    (np.arange(8) % 10).astype(np.int32))
                   for _ in range(2)]
        state = tr.init_state()
        state, _ = tr.train_epoch(state, batches, epoch=0)
        tr.evaluate(state, batches)
        events = [json.loads(l)["event"] for l in p.read_text().splitlines()]
        assert events.count("train_iter") == 2
        assert "epoch" in events
        assert "eval" in events


class TestProfiling:
    def test_noop_without_logdir(self):
        with profile_trace(None):
            pass  # must not raise

    def test_trace_writes_files(self, tmp_path):
        d = str(tmp_path / "prof")
        with profile_trace(d):
            with annotate("toy"):
                _ = jnp.sum(jnp.arange(16.0))
        import os
        found = [os.path.join(r, f) for r, _, fs in os.walk(d) for f in fs]
        assert found, "profiler produced no trace files"
