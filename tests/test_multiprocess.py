"""True multi-process integration tests.

The reference was only ever verified on a real 4-node cluster (SURVEY.md
§4); these tests stand up the same topology as OS processes on localhost:
each rank is a separate Python process, rendezvous goes through
``jax.distributed.initialize`` at a 127.0.0.1 coordinator, and gradient
sync crosses a real process boundary (XLA's cross-process CPU collectives)
— not just the in-process virtual-device mesh the rest of the suite uses.

Kept deliberately small (2 ranks, tiny synthetic data, 3 iterations): the
point is the rendezvous + cross-process collective path, not throughput.
"""

from __future__ import annotations

import subprocess
import sys

import pytest

from tpu_ddp.launch import PARTS, find_free_port, launch

SMOKE_ENV = {
    "TPU_DDP_SYNTH_SIZE": "64",
    "TPU_DDP_MAX_ITERS": "3",
    "TPU_DDP_GLOBAL_BATCH": "16",
    "CIFAR10_DIR": "/nonexistent-so-synthetic",
}


@pytest.mark.slow
def test_two_process_part2b_all_reduce():
    res = launch("part2b", nproc=2, env=SMOKE_ENV, echo=False, timeout=600)
    for w in res.workers:
        assert w.returncode == 0, (
            f"rank {w.rank} failed ({w.returncode}):\n{w.output}")
    for rank in (0, 1):
        out = res.output_of(rank)
        # The sanity probe (reference part2/part2a/main.py:42-49).
        assert "World size: 2" in out
        assert f"Rank: {rank}" in out
        # Per-node batch = int(16/2) = 8 (reference part2/part2b/main.py:177).
        assert "per-node batch=8" in out
        # Both ranks trained and evaluated the full (unsharded) test set.
        assert "Test set: average loss" in out
    # Eval is replicated, params are synchronized -> identical accuracy
    # lines on both ranks (invariant (ii), report §2.2).
    line0 = [l for l in res.output_of(0).splitlines() if "Test set" in l]
    line1 = [l for l in res.output_of(1).splitlines() if "Test set" in l]
    assert line0 == line1


@pytest.mark.slow
def test_two_process_part3_fused():
    res = launch("part3", nproc=2, env=SMOKE_ENV, echo=False, timeout=600)
    assert res.ok, "\n".join(w.output for w in res.workers)
    for rank in (0, 1):
        assert "strategy=fused" in res.output_of(rank)


@pytest.mark.slow
def test_two_process_part5_fsdp():
    """FSDP rung across REAL process boundaries: parameters live as
    per-process shards; the in-step all_gather and its reduce_scatter
    transpose span two jax.distributed processes."""
    res = launch("part5", nproc=2, env=SMOKE_ENV, echo=False, timeout=600)
    assert res.ok, "\n".join(w.output for w in res.workers)
    for rank in (0, 1):
        assert "strategy=fsdp" in res.output_of(rank)
        assert "Test set: average loss" in res.output_of(rank)
    line0 = [l for l in res.output_of(0).splitlines() if "Test set" in l]
    line1 = [l for l in res.output_of(1).splitlines() if "Test set" in l]
    assert line0 == line1


@pytest.mark.slow
def test_two_process_part4_zero():
    """ZeRO rung across REAL process boundaries: the reduce_scatter +
    all_gather pair and the dp-sharded optimizer state span two
    jax.distributed processes; synchronized params -> identical eval."""
    res = launch("part4", nproc=2, env=SMOKE_ENV, echo=False, timeout=600)
    assert res.ok, "\n".join(w.output for w in res.workers)
    for rank in (0, 1):
        assert "strategy=zero" in res.output_of(rank)
    line0 = [l for l in res.output_of(0).splitlines() if "Test set" in l]
    line1 = [l for l in res.output_of(1).splitlines() if "Test set" in l]
    assert line0 == line1


def test_failed_rank_fails_launch_fast():
    # Out-of-range rank -> bootstrap ValueError before rendezvous. The
    # launch must report failure (not mask it behind a clean rank) and
    # must not wait out the full timeout.
    import time

    t0 = time.monotonic()
    res = launch("part2b", nproc=2, extra_args=["--rank", "5"], echo=False,
                 timeout=300, env={"TPU_DDP_SYNTH_SIZE": "64"})
    assert not res.ok
    assert res.returncode != 0
    assert time.monotonic() - t0 < 120


def test_returncode_reports_any_nonzero_rank():
    from tpu_ddp.launch import LaunchResult, WorkerResult

    res = LaunchResult(workers=[WorkerResult(0, 0), WorkerResult(1, -9)])
    assert res.returncode == -9 and not res.ok
    res = LaunchResult(workers=[WorkerResult(0, 0), WorkerResult(1, 0)])
    assert res.ok


def test_launcher_rejects_unknown_part():
    with pytest.raises(ValueError):
        launch("part9", nproc=2)
    with pytest.raises(ValueError):
        launch("part1", nproc=0)


def test_find_free_port_is_bindable():
    import socket

    port = find_free_port()
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", port))


def test_cli_surface():
    # --help must not import jax or touch any backend: it has to be instant.
    out = subprocess.run(
        [sys.executable, "-m", "tpu_ddp.launch", "--help"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0
    for part in PARTS:
        assert part in out.stdout


@pytest.mark.slow
def test_elastic_restart_resumes_from_checkpoint(tmp_path):
    """Fault injection kills every rank at step 2; the elastic launcher
    respawns the cluster, which resumes from the step-2 mid-epoch
    checkpoint and finishes (SURVEY.md §5: the reference has no failure
    handling at all — a dead rank hangs its cluster)."""
    from tpu_ddp.launch import launch_elastic

    env = dict(SMOKE_ENV)
    env.update({
        "TPU_DDP_CKPT_EVERY": "1",       # checkpoint every step
        "TPU_DDP_FAIL_AT_STEP": "2",     # crash (exit 13) at step 2
    })
    res = launch_elastic(
        "part3", nproc=2, max_restarts=1, echo=False, timeout=900,
        extra_args=["--ckpt-dir", str(tmp_path)], env=env)
    assert res.ok, "\n".join(w.output for w in res.workers)
    assert res.restarts == 1
    out0 = res.output_of(0)
    assert "resumed from" in out0
    assert "Test set: average loss" in out0


@pytest.mark.slow
def test_elastic_gives_up_after_max_restarts(tmp_path):
    """A fault that fires before any checkpoint exists cannot be resumed
    past; the launcher must stop after max_restarts and surface the
    injected exit code, not loop forever."""
    from tpu_ddp.launch import launch_elastic
    from tpu_ddp.utils.invariants import FAULT_EXIT_CODE

    env = dict(SMOKE_ENV)
    env.update({"TPU_DDP_FAIL_AT_STEP": "1"})  # no --ckpt-dir -> no resume
    res = launch_elastic("part2b", nproc=2, max_restarts=1, echo=False,
                         timeout=900, env=env)
    assert not res.ok
    assert res.restarts == 1
    assert res.returncode == FAULT_EXIT_CODE


@pytest.mark.slow
def test_two_process_lm_train():
    """The LM engine across REAL process boundaries: rendezvous, global
    batch assembly from per-process shards, cross-process gradient
    pmean — and the same again with FSDP parameter sharding."""
    for fsdp in ("0", "1"):
        env = {"TPU_DDP_LM_STEPS": "3", "TPU_DDP_GLOBAL_BATCH": "4",
               "TPU_DDP_LM_FSDP": fsdp}
        res = launch("examples/lm_train.py", nproc=2, env=env,
                     echo=False, timeout=600)
        assert res.ok, "\n".join(w.output for w in res.workers)
        for rank in (0, 1):
            out = res.output_of(rank)
            assert f"rank={rank} world=2 dp=2" in out
            assert f"fsdp={fsdp == '1'}" in out  # mode actually engaged
            assert "step 3/3 loss" in out
        # Params are synchronized; both ranks' shard losses track the
        # same model, and the run must have made progress.
        first = [float(l.rsplit(" ", 1)[1])
                 for l in res.output_of(0).splitlines() if "loss" in l]
        assert first[-1] < first[0], (fsdp, first)


@pytest.mark.slow
def test_two_process_lm_zero1_adafactor():
    """ZeRO-1 Adafactor across REAL process boundaries: the row-block
    psum_scatter / vc psums / all_gather of FactoredZeRO1 span two
    jax.distributed processes, and training makes progress (each rank
    prints the mean over ITS data shard, so values differ per rank but
    each must be finite and falling)."""
    res = launch("examples/lm_train.py", nproc=2,
                 env={"TPU_DDP_LM_STEPS": "5",
                      "TPU_DDP_LM_OPT": "adafactor",
                      "TPU_DDP_LM_ZERO1": "1"},
                 echo=False, timeout=600)
    assert res.ok, "\n".join(w.output for w in res.workers)
    import math
    import re
    for rank in (0, 1):
        out = res.output_of(rank)
        assert "opt_shard=zero1 opt=adafactor" in out
        losses = [float(m.group(1)) for m in
                  re.finditer(r"step \d+/\d+ loss ([0-9.naninf-]+)", out)]
        assert len(losses) == 5, out
        assert all(math.isfinite(x) for x in losses), losses
        assert losses[-1] < losses[0], losses


@pytest.mark.slow
def test_two_process_lm_zero2_clip():
    """ZeRO-2 + global-norm clipping across REAL process boundaries
    (round-4): the per-microbatch psum_scatter of the accumulation and
    the clip's cross-slice norm psum span two jax.distributed
    processes; training makes progress on both ranks."""
    res = launch("examples/lm_train.py", nproc=2,
                 env={"TPU_DDP_LM_STEPS": "5",
                      "TPU_DDP_LM_OPT_SHARD": "zero2",
                      "TPU_DDP_LM_ACCUM": "2",
                      "TPU_DDP_LM_CLIP": "1.0"},
                 echo=False, timeout=600)
    assert res.ok, "\n".join(w.output for w in res.workers)
    import math
    import re
    for rank in (0, 1):
        out = res.output_of(rank)
        assert "opt_shard=zero2" in out and "clip=1.0" in out
        losses = [float(m.group(1)) for m in
                  re.finditer(r"step \d+/\d+ loss ([0-9.naninf-]+)",
                              out)]
        assert len(losses) == 5, out
        assert all(math.isfinite(x) for x in losses), losses
        assert losses[-1] < losses[0], losses


@pytest.mark.slow
def test_two_process_sharded_eval():
    """Process-sharded evaluation (round-3 verdict item 8): the test set
    shards by process in the loader (wrap-pad rows weight 0), per-shard
    sums psum over dp, and the metrics equal the replicated eval's.
    synth 320 -> 320-example test set divisible by batch*world, so the
    example asserts tight loss equality too (its internal asserts fail
    the ranks if violated)."""
    res = launch("examples/sharded_eval.py", nproc=2,
                 env={"TPU_DDP_SYNTH_SIZE": "320",
                      "TPU_DDP_GLOBAL_BATCH": "16"},
                 echo=False, timeout=600)
    assert res.ok, "\n".join(w.output for w in res.workers)
    for rank in (0, 1):
        out = res.output_of(rank)
        assert "agreement ok" in out, out
        # Both evals ran and printed the reference-format line.
        assert "[replicated] Test set: average loss" in out
        assert "[sharded] Test set: average loss" in out


@pytest.mark.slow
def test_four_process_lm_zero1_tensor_parallel():
    """ZeRO-1 x tp across REAL process boundaries (round-3): a 4-process
    dp2 x tp2 cluster where Megatron collectives AND the P((mp, dp))
    optimizer-state psum_scatter/all_gather span processes. Ranks in the
    same tp group hold the same dp shard -> identical loss streams."""
    res = launch("examples/lm_train.py", nproc=4,
                 env={"TPU_DDP_LM_STEPS": "3", "TPU_DDP_LM_TP": "2",
                      "TPU_DDP_LM_ZERO1": "1",
                      "TPU_DDP_GLOBAL_BATCH": "4"},
                 echo=False, timeout=600)
    assert res.ok, "\n".join(w.output for w in res.workers)
    import re

    def losses(rank):
        return [m.group(1) for m in re.finditer(
            r"step \d+/\d+ loss ([0-9.]+)", res.output_of(rank))]
    for rank in range(4):
        assert "dp=2 sp=1 tp=2" in res.output_of(rank)
        assert "opt_shard=zero1" in res.output_of(rank)
        assert len(losses(rank)) == 3
    # tp groups (0,1) and (2,3) see the same tokens: identical losses.
    assert losses(0) == losses(1)
    assert losses(2) == losses(3)
    assert losses(0) != losses(2)  # different dp shards
